#!/bin/bash
# Background TPU liveness watcher: probes the axon backend every 4 min.
# Exits 0 (notifying the driver) the moment the chip answers; writes
# /root/repo/.tpu_alive with a timestamp. Caps out after ~11h.
for i in $(seq 1 160); do
  if timeout 90 env JAX_PLATFORMS=axon python -c "import jax; d=jax.devices(); assert d" >/dev/null 2>&1; then
    date -u +"%Y-%m-%dT%H:%M:%SZ alive (iter $i)" > /root/repo/.tpu_alive
    exit 0
  fi
  echo "$(date -u +%H:%M:%S) iter $i: dead" >> /root/repo/.tpu_watch.log
  sleep 240
done
exit 1
