#!/bin/bash
# Background TPU liveness watcher: probes the axon backend every 10 min
# at lowest CPU priority (the box has ONE core — an unniced jax import
# starves the foreground test/build work).  Exits 0 the moment the chip
# answers; writes /root/repo/.tpu_alive.  Caps out after ~11h.
for i in $(seq 1 66); do
  if timeout 120 nice -n 19 env JAX_PLATFORMS=axon python -c "import jax; d=jax.devices(); assert d" >/dev/null 2>&1; then
    date -u +"%Y-%m-%dT%H:%M:%SZ alive (iter $i)" > /root/repo/.tpu_alive
    exit 0
  fi
  # reap any orphaned axon warm-up children the probe left behind —
  # match the plugin's exact no-space helper text so bench.py's own
  # live probe ('jnp.ones((8, 8)).sum()...', with spaces) is never hit
  pkill -f 'np\.asarray\(\(jnp\.ones\(\(8,8\)\)' 2>/dev/null
  echo "$(date -u +%H:%M:%S) iter $i: dead" >> /root/repo/.tpu_watch.log
  sleep 600
done
exit 1
