"""Wide-row gather/scatter microbenchmarks on the default backend.

Hypothesis: XLA TPU element-gather runs ~8.7ns/elem (serial), but
gathering W-wide ROWS lowers to per-row DMA near bandwidth.  If true,
SpMV = row-gather + in-row one-hot select + one-hot spread +
row-segment-sum beats the element path ~50x.

    python scripts/prim_bench2.py [--scale 20] [--ef 16] [--iters 5]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


from _benchutil import sync, timeit  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=int, default=20)
    ap.add_argument("--ef", type=int, default=16)
    ap.add_argument("--iters", type=int, default=5)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    import bench

    n, src, dst = bench.rmat_edges(args.scale, args.ef)
    s2 = np.concatenate([src, dst])
    d2 = np.concatenate([dst, src])
    order = np.argsort(s2, kind="stable")
    row_np = s2[order].astype(np.int32)
    col_np = d2[order].astype(np.int32)
    row = jnp.asarray(row_np)
    col = jnp.asarray(col_np)
    e = len(row_np)
    x = jnp.asarray(np.random.default_rng(0).random(n).astype(np.float32))
    print(f"platform={jax.devices()[0].platform} E={e} N={n}", file=sys.stderr)

    res = {}

    for w in (8, 16, 32):
        lg = int(np.log2(w))
        x2 = x.reshape(n >> lg, w)
        ridx = col >> lg

        # row gather alone
        rg = jax.jit(lambda x2, r: x2[r])
        res[f"rowgather_w{w}_ms"] = timeit(rg, x2, ridx, iters=args.iters) * 1e3

        # row gather + in-row one-hot select = full gather x[col]
        def gsel(x2, c, lg=lg, w=w):
            rows = x2[c >> lg]  # [E, w]
            lane = (c & (w - 1))[:, None]
            oh = (lane == jnp.arange(w, dtype=c.dtype)[None, :]).astype(
                rows.dtype
            )
            return (rows * oh).sum(axis=1)

        gselj = jax.jit(gsel)
        res[f"gather_via_rows_w{w}_ms"] = (
            timeit(gselj, x2, col, iters=args.iters) * 1e3
        )

        # scatter side: one-hot spread + segment_sum of [E, w] rows
        def ssum(v, r, lg=lg, w=w):
            lane = (r & (w - 1))[:, None]
            oh = (lane == jnp.arange(w, dtype=r.dtype)[None, :]).astype(v.dtype)
            out2 = jax.ops.segment_sum(
                v[:, None] * oh, r >> lg, num_segments=n >> lg,
                indices_are_sorted=True,
            )
            return out2.reshape(-1)

        vals = jnp.ones((e,), jnp.float32)
        ssumj = jax.jit(ssum)
        res[f"segsum_via_rows_w{w}_ms"] = (
            timeit(ssumj, vals, row, iters=args.iters) * 1e3
        )

        # full SpMV via rows
        def spmv(x2, c, r, lg=lg, w=w):
            v = gsel(x2, c, lg, w)
            return ssum(v, r, lg, w)

        spmvj = jax.jit(spmv)
        res[f"spmv_via_rows_w{w}_ms"] = (
            timeit(spmvj, x2, col, row, iters=args.iters) * 1e3
        )

    # reference point: element segment_sum on [E] (the r1 path)
    vals = jnp.ones((e,), jnp.float32)
    seg1 = jax.jit(
        lambda v, r: jax.ops.segment_sum(
            v, r, num_segments=n, indices_are_sorted=True
        )
    )
    res["segsum_elem_ms"] = timeit(seg1, vals, row, iters=args.iters) * 1e3

    # dense-row segment_sum WITHOUT one-hot spread (pure row reduce):
    # bounds how much of segsum_via_rows is the spread vs the reduce
    v8 = jnp.ones((e, 8), jnp.float32)
    segr = jax.jit(
        lambda v, r: jax.ops.segment_sum(
            v, r >> 3, num_segments=n >> 3, indices_are_sorted=True
        )
    )
    res["segsum_rows8_pre_ms"] = timeit(segr, v8, row, iters=args.iters) * 1e3

    for k, v in res.items():
        res[k] = round(v, 3)
    print(json.dumps(res))


if __name__ == "__main__":
    main()
