#!/usr/bin/env python
"""TPU profiling harness (round-2 perf loop, ROADMAP item 1).

Runs each algorithm on an RMAT graph on the default backend, reports
cold/warm timings and per-round costs, and (with --trace) captures an
XLA profiler trace for tensorboard.

  python scripts/tpu_profile.py [--scale 20] [--ef 16] [--fnum 1]
      [--algorithms pagerank,sssp,bfs,wcc,cdlp] [--trace /tmp/trace]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--scale", type=int, default=20)
    p.add_argument("--ef", type=int, default=16)
    p.add_argument("--fnum", type=int, default=None)
    p.add_argument("--algorithms", default="pagerank,sssp,bfs,wcc,cdlp")
    p.add_argument("--trace", default="")
    p.add_argument("--platform", default="")
    p.add_argument("--cpu_devices", type=int, default=0)
    args = p.parse_args()

    if args.cpu_devices:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.cpu_devices}"
        ).strip()

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    import numpy as np

    import bench as benchmod
    from libgrape_lite_tpu.fragment.edgecut import ShardedEdgecutFragment
    from libgrape_lite_tpu.models import APP_REGISTRY
    from libgrape_lite_tpu.parallel.comm_spec import CommSpec
    from libgrape_lite_tpu.utils.memory import get_memory_stats
    from libgrape_lite_tpu.vertex_map.partitioner import MapPartitioner
    from libgrape_lite_tpu.vertex_map.vertex_map import VertexMap
    from libgrape_lite_tpu.worker.worker import Worker

    n, src, dst = benchmod.rmat_edges(args.scale, args.ef)
    w = (np.abs(np.sin(src * 0.37 + dst * 0.71)) * 99 + 1).astype(np.float64)
    comm = CommSpec(fnum=args.fnum)
    oids = np.arange(n, dtype=np.int64)
    vm = VertexMap.build(oids, MapPartitioner(comm.fnum, oids))
    t0 = time.perf_counter()
    frag = ShardedEdgecutFragment.build(comm, vm, src, dst, w, directed=False)
    print(f"build: {time.perf_counter() - t0:.2f}s  "
          f"V=2^{args.scale} E={len(src)} fnum={comm.fnum} "
          f"platform={jax.devices()[0].platform}")
    print(f"memory: {get_memory_stats()}")

    from libgrape_lite_tpu.runner import QueryArgs, build_query_kwargs

    qargs = QueryArgs(sssp_source=0, bfs_source=0, bc_source=0,
                      pr_d=0.85, pr_mr=10, cdlp_mr=10, kcore_k=4)

    def kwargs_for(name):
        return build_query_kwargs(name, qargs)

    report = {}
    for name in args.algorithms.split(","):
        app = APP_REGISTRY[name]()
        worker = Worker(app, frag)
        kw = kwargs_for(name)
        t0 = time.perf_counter()
        worker.query(**kw)
        cold = time.perf_counter() - t0
        if args.trace:
            with jax.profiler.trace(os.path.join(args.trace, name)):
                worker.query(**kw)
        t0 = time.perf_counter()
        worker.query(**kw)
        warm = time.perf_counter() - t0
        per_round = warm / max(worker.rounds, 1)
        report[name] = {
            "cold_s": round(cold, 4),
            "warm_s": round(warm, 4),
            "rounds": worker.rounds,
            "per_round_ms": round(per_round * 1e3, 3),
        }
        print(f"{name}: {report[name]}")

    print(json.dumps(report))


if __name__ == "__main__":
    main()
