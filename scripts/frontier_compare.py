"""Round-structure comparison: dense pull vs message-path vs the
optimized frontier apps (VERDICT r2 item 3 'done' artifact).

Usage:
    python scripts/frontier_compare.py [--scale N] [--platform cpu|default]

Prints one JSON line per (graph, app) with rounds + wall-clock; run on
TPU for the real numbers, CPU gives the round-structure story.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=int, default=18)
    ap.add_argument("--edge_factor", type=int, default=16)
    ap.add_argument("--platform", default="default")
    ap.add_argument("--fnum", type=int, default=1)
    ap.add_argument("--apps", default="",
                    help="comma-filter by app name (default: all six)")
    ap.add_argument("--graphs", default="",
                    help="comma-filter by graph name (default: both)")
    args = ap.parse_args()

    if args.platform != "default":
        import jax

        jax.config.update("jax_platforms", args.platform)

    import numpy as np

    import bench
    from libgrape_lite_tpu.fragment.edgecut import ShardedEdgecutFragment
    from libgrape_lite_tpu.fragment.loader import LoadGraph, LoadGraphSpec
    from libgrape_lite_tpu.models import (
        BFS,
        BFSMsg,
        BFSOpt,
        SSSP,
        SSSPDelta,
        SSSPMsg,
    )
    from libgrape_lite_tpu.parallel.comm_spec import CommSpec
    from libgrape_lite_tpu.utils.types import LoadStrategy
    from libgrape_lite_tpu.vertex_map.partitioner import SegmentedPartitioner
    from libgrape_lite_tpu.vertex_map.vertex_map import VertexMap
    from libgrape_lite_tpu.worker.worker import Worker

    graphs = {}

    # p2p-31 (weighted, the golden graph)
    root = os.path.join(os.path.dirname(__file__), "..")
    spec = LoadGraphSpec(directed=False, weighted=True,
                         edata_dtype=np.float64)
    graphs["p2p-31"] = LoadGraph(
        os.path.join(root, "dataset", "p2p-31.e"),
        os.path.join(root, "dataset", "p2p-31.v"),
        CommSpec(fnum=args.fnum), spec,
    )

    # RMAT (unit weights for BFS; weighted uniform for SSSP)
    n, src, dst = bench.rmat_edges(args.scale, args.edge_factor)
    rng = np.random.default_rng(3)
    w = rng.uniform(1.0, 100.0, size=len(src))
    oids = np.arange(n, dtype=np.int64)
    vm = VertexMap.build(
        oids, SegmentedPartitioner(args.fnum, oids),
        idxer_type="sorted_array",
    )
    graphs[f"rmat{args.scale}"] = ShardedEdgecutFragment.build(
        CommSpec(fnum=args.fnum), vm, src, dst, w,
        directed=False, load_strategy=LoadStrategy.kBothOutIn,
    )

    apps = [
        ("bfs_dense", lambda: BFS(), {"source": 6}),
        ("bfs_msg", lambda: BFSMsg(), {"source": 6}),
        ("bfs_opt", lambda: BFSOpt(), {"source": 6}),
        ("sssp_dense", lambda: SSSP(), {"source": 6}),
        ("sssp_msg", lambda: SSSPMsg(), {"source": 6}),
        ("sssp_delta", lambda: SSSPDelta(), {"source": 6}),
    ]

    app_filter = set(filter(None, args.apps.split(",")))
    graph_filter = set(filter(None, args.graphs.split(",")))
    for gname, frag in graphs.items():
        if graph_filter and gname not in graph_filter:
            continue
        for aname, mk, kw in apps:
            if app_filter and aname not in app_filter:
                continue
            app = mk()
            w0 = Worker(app, frag)
            t0 = time.perf_counter()
            w0.query(**kw)
            cold = time.perf_counter() - t0
            app2 = mk()
            w1 = Worker(app2, frag)
            w1.query(**kw)  # compile cache warm inside app instance? no:
            # fresh app -> fresh cache; warm = re-query the same worker
            t0 = time.perf_counter()
            w1.query(**kw)
            warm = time.perf_counter() - t0
            rec = {
                "graph": gname,
                "app": aname,
                "rounds": w1.rounds,
                "cold_s": round(cold, 4),
                "warm_s": round(warm, 4),
            }
            for extra in ("push_rounds", "pull_rounds", "buckets",
                          "retries", "final_capacity"):
                if hasattr(app2, extra):
                    rec[extra] = getattr(app2, extra)
            print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    main()
