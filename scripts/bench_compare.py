#!/usr/bin/env python
"""Diff two BENCH json records against the declared schema and gate
on perf regressions.

`check_bench_schema.py` pins the SHAPE of a bench record; this tool
pins its TRAJECTORY: given a baseline record and a candidate record
(driver wrappers, bare records, or bench stdout — the same loader),
it walks the schema's own block declarations (`_BLOCKS` /
`_TOP_SCALARS` — nothing is compared that is not declared) and

* compares every numeric field whose DIRECTION is known (wall
  seconds, latency ms, overhead pct and recount mismatches are
  lower-better; qps, MTEPS value, updates/s and speedups are
  higher-better — config ints like scale/fnum/cadence are identity
  guards, not metrics);
* refuses to compare what is not comparable: a block whose config
  fields (scale, app, fnum, metric, ...) differ between the two
  records is skipped and REPORTED — a scale-10 CI record diffed
  against the full-scale BENCH_r*.json gates nothing silently;
* exits 2 when any gated field regresses by more than
  --threshold-pct (default 10%), 0 otherwise — self-compare is
  exactly 0 regressions by construction;
* applies ONE absolute gate on top of the relative ones: a candidate
  `calibration` block reporting >5% modeled-vs-measured drift (or
  drift_ok false) exits 2 regardless of the baseline — rate drift is
  judged against device truth (ops/calibration.py), and a baseline
  that drifted just as far is no excuse.

Usage: python scripts/bench_compare.py BASELINE CANDIDATE
           [--threshold-pct 10] [--json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import check_bench_schema as cbs  # noqa: E402

#: fields that pin what a block MEASURED — any mismatch makes the
#: block incomparable (skipped + reported), never a regression
_CONFIG_KEYS = {
    "metric", "unit", "variant", "app", "mode", "policy",
    "scan_mode", "planner_choice", "measured_winner", "auto_backend",
    "scale", "bench_scale", "fnum", "k", "cadence", "probes",
    "replicas", "tenants", "queries", "queries_per_app", "drain_at",
    "drained_replica", "updates_per_chunk", "n",
}

#: leaf-name direction tables: the ONLY numeric fields the gate
#: judges; anything else numeric is informational
_LOWER_BETTER_SUFFIXES = ("_s", "_ms", "_us", "_pct", "_mismatch")
_LOWER_BETTER = {
    "p50", "p99", "dropped", "evictions", "overlay_recompiles",
    "readmit_compiles",
}
_HIGHER_BETTER = {
    "value", "vs_baseline", "qps", "updates_per_s", "qps_win_b8",
    "inc_speedup",
}


#: the r17 ABSOLUTE gate (ops/calibration.py, docs/CALIBRATION.md): a
#: candidate whose `calibration` block reports more than this
#: modeled-vs-measured drift fails the compare outright — drift is
#: against device truth, so a baseline that drifted just as far is no
#: excuse (unlike every relative gate below)
_DRIFT_LIMIT_PCT = 5.0


def calibration_drift_failure(cand: dict):
    """The reason string when the candidate's calibration block fails
    the absolute drift gate, else None (no block = nothing gated)."""
    blk = cand.get("calibration")
    if not isinstance(blk, dict):
        return None
    drift = blk.get("drift_pct")
    if blk.get("drift_ok") is False or (
            _is_num(drift) and drift > _DRIFT_LIMIT_PCT):
        return (f"calibration drift {drift}% exceeds the absolute "
                f"{_DRIFT_LIMIT_PCT:g}% gate under profile "
                f"{blk.get('profile')!r}")
    return None


def _direction(leaf: str) -> int:
    """-1 = lower is better, +1 = higher is better, 0 = ungated."""
    if leaf in _CONFIG_KEYS:
        return 0
    if leaf in _HIGHER_BETTER:
        return +1
    if leaf in _LOWER_BETTER or leaf.endswith(_LOWER_BETTER_SUFFIXES):
        return -1
    return 0


def _is_num(v) -> bool:
    return isinstance(v, cbs._NUM) and not isinstance(v, bool)


def _walk(base, cand, prefix, rows, skipped):
    """Recurse matched dict paths.  A config mismatch ANYWHERE in a
    subtree skips that whole subtree (its numbers measured a
    different experiment); missing-on-either-side numeric leaves are
    reported but never gated."""
    for k in base:
        if k not in cand:
            continue
        b, c = base[k], cand[k]
        path = f"{prefix}{k}"
        if k in _CONFIG_KEYS and not isinstance(b, dict):
            if b != c:
                skipped.append(
                    (prefix.rstrip(".") or "record",
                     f"{k}: {b!r} != {c!r}")
                )
                return False
    for k in base:
        if k not in cand:
            continue
        b, c = base[k], cand[k]
        path = f"{prefix}{k}"
        if isinstance(b, dict) and isinstance(c, dict):
            _walk(b, c, path + ".", rows, skipped)
        elif _is_num(b) and _is_num(c):
            d = _direction(k)
            if d == 0:
                continue
            delta_pct = (
                (c - b) / abs(b) * 100.0 if b != 0
                else (0.0 if c == 0 else float("inf"))
            )
            rows.append({
                "field": path,
                "baseline": b,
                "candidate": c,
                "delta_pct": delta_pct,
                # regression magnitude: positive = worse, in percent
                "regress_pct": delta_pct * -d,
            })
    return True


def compare(base: dict, cand: dict):
    """(rows, skipped): gated-field comparisons + incomparable
    subtrees.  Blocks come from the schema declaration, so a record
    key outside `_BLOCKS`/`_TOP_SCALARS` is never compared — the same
    single-declaration-point discipline the validator enforces."""
    rows: list = []
    skipped: list = []
    top_base = {k: base[k] for k in cbs._TOP_SCALARS if k in base}
    top_cand = {k: cand[k] for k in cbs._TOP_SCALARS if k in cand}
    _walk(top_base, top_cand, "", rows, skipped)
    for name in cbs._BLOCKS:
        b, c = base.get(name), cand.get(name)
        if isinstance(b, dict) and isinstance(c, dict):
            _walk(b, c, name + ".", rows, skipped)
        elif isinstance(b, dict) != isinstance(c, dict):
            skipped.append((name, "present in only one record"))
    return rows, skipped


def _load(path: str) -> dict:
    text = sys.stdin.read() if path == "-" else open(path).read()
    pairs = cbs._records_from_text(text, path)
    return pairs[0][0]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.strip().splitlines()[0])
    ap.add_argument("baseline", help="baseline BENCH json (wrapper, "
                                     "record, or bench stdout)")
    ap.add_argument("candidate", help="candidate BENCH json")
    ap.add_argument("--threshold-pct", type=float, default=10.0,
                    help="gated regression threshold in percent "
                         "(default 10)")
    ap.add_argument("--json", action="store_true",
                    help="print the structured comparison instead of "
                         "the table")
    ns = ap.parse_args(argv)
    try:
        base = _load(ns.baseline)
        cand = _load(ns.candidate)
    except (OSError, ValueError) as e:
        print(f"bench_compare: {e}", file=sys.stderr)
        return 1
    for label, rec in (("baseline", base), ("candidate", cand)):
        errors = cbs.validate_record(rec)
        if errors:
            # a malformed record must fail loudly, not diff garbage
            print(f"bench_compare: {label} fails the bench schema "
                  f"({len(errors)} error(s)):", file=sys.stderr)
            for e in errors[:8]:
                print(f"  - {e}", file=sys.stderr)
            return 1
    rows, skipped = compare(base, cand)
    regressions = [
        r for r in rows if r["regress_pct"] > ns.threshold_pct
    ]
    drift_fail = calibration_drift_failure(cand)
    if ns.json:
        print(json.dumps({
            "threshold_pct": ns.threshold_pct,
            "compared": rows,
            "skipped": skipped,
            "regressions": [r["field"] for r in regressions],
            "calibration_drift": drift_fail,
        }))
        return 2 if (regressions or drift_fail) else 0
    print(f"bench_compare: {len(rows)} gated field(s), threshold "
          f"{ns.threshold_pct:g}%")
    for r in rows:
        worse = r["regress_pct"] > ns.threshold_pct
        mark = " REGRESSION" if worse else ""
        print(f"  {r['field']:<44} {r['baseline']:>12g} -> "
              f"{r['candidate']:>12g} ({r['delta_pct']:+.1f}%){mark}")
    for where, why in skipped:
        print(f"  [skip] {where}: not comparable ({why})")
    if drift_fail:
        print(f"FAIL: {drift_fail}")
        return 2
    if regressions:
        print(f"FAIL: {len(regressions)} field(s) regressed "
              f">{ns.threshold_pct:g}%")
        return 2
    print("OK: no gated regression")
    return 0


if __name__ == "__main__":
    sys.exit(main())
