#!/usr/bin/env python
"""Two-process jax.distributed dryrun — the multi-host (DCN) analogue
of the reference's `mpirun -n 2` CI lane (`misc/app_tests.sh:231-238`).

Exercises `CommSpec.init_distributed` (parallel/comm_spec.py): each
process brings up the distributed runtime, contributes its local CPU
devices to the global frag mesh, and the two run a psum + ring
ppermute over a globally-sharded array — the collective patterns every
app uses, now crossing a process boundary (the reference's
PROCESS BOUNDARY marks in SURVEY.md §3.1).

Usage:
  python scripts/multihost_dryrun.py                  # parent: spawns 2 workers
  python scripts/multihost_dryrun.py --worker I ADDR  # child process I
"""

from __future__ import annotations

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

NPROC = 2
LOCAL_DEVICES = 2  # per process -> 4 global


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def worker(pid: int, coord: str) -> None:
    import jax

    # pin CPU before any backend init (the sandbox's sitecustomize
    # registers the axon plugin; env vars alone do not stop it)
    jax.config.update("jax_platforms", "cpu")

    from libgrape_lite_tpu import compat
    from libgrape_lite_tpu.parallel.comm_spec import FRAG_AXIS, CommSpec

    comm_spec = CommSpec.init_distributed(
        coordinator_address=coord, num_processes=NPROC, process_id=pid
    )
    assert comm_spec.fnum == NPROC * LOCAL_DEVICES, (
        f"expected {NPROC * LOCAL_DEVICES} global devices, got "
        f"{comm_spec.fnum}"
    )
    assert comm_spec.worker_id == pid

    import numpy as np
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    fnum = comm_spec.fnum
    vp = 8
    sharding = NamedSharding(comm_spec.mesh, P(FRAG_AXIS))

    # each process materialises only its addressable shards
    def make(cb):
        return jax.make_array_from_callback((fnum, vp), sharding, cb)

    x = make(lambda idx: np.full(
        (1, vp), float(idx[0].start if idx[0].start else 0), np.float32
    ))

    def step(xs):
        local = xs[0]
        total = lax.psum(local.sum(), FRAG_AXIS)  # termination-vote shape
        fid = lax.axis_index(FRAG_AXIS)
        ring = [(i, (i + 1) % fnum) for i in range(fnum)]
        passed = lax.ppermute(local, FRAG_AXIS, ring)  # mirror exchange
        return (passed + total)[None], total

    fn = jax.jit(
        compat.shard_map(
            step, mesh=comm_spec.mesh, in_specs=(P(FRAG_AXIS),),
            out_specs=(P(FRAG_AXIS), P()), check_vma=False,
        )
    )
    out, total = fn(x)
    got = float(np.asarray(total))
    want = float(sum(f * vp for f in range(fnum)))
    assert got == want, f"psum across processes: got {got}, want {want}"
    # every shard received its ring predecessor's block: shard j was
    # filled with the constant j, so after the ring ppermute + psum it
    # must hold ((j-1) mod fnum) + want exactly
    for s in out.addressable_shards:
        j = s.index[0].start or 0
        expect = ((j - 1) % fnum) + want
        block = np.asarray(s.data)
        assert (block == expect).all(), (
            f"shard {j}: expected predecessor value {expect}, got {block}"
        )
    # ---- full app query across the process boundary (VERDICT r3 next
    # #10): PageRank on p2p-31 through the real loader + Worker, each
    # process verifying its addressable shards against the golden ----
    from libgrape_lite_tpu.fragment.loader import LoadGraph, LoadGraphSpec
    from libgrape_lite_tpu.models import PageRank
    from libgrape_lite_tpu.worker.worker import Worker

    jax.config.update("jax_enable_x64", True)  # f64 golden comparison

    spec = LoadGraphSpec(
        directed=False, weighted=True, edata_dtype=np.float64
    )
    frag = LoadGraph(
        os.path.join(REPO, "dataset", "p2p-31.e"),
        os.path.join(REPO, "dataset", "p2p-31.v"),
        comm_spec, spec,
    )
    app = PageRank()
    wk = Worker(app, frag)
    rank = wk.query(delta=0.85, max_round=10)["rank"]

    golden = {}
    with open(os.path.join(REPO, "dataset", "p2p-31-PR")) as f:
        for line in f:
            k, v = line.split()
            golden[int(k)] = float(v)

    checked = 0
    for shard in rank.addressable_shards:
        f = shard.index[0].start or 0
        vals = np.asarray(shard.data)[0]
        oids = frag.vertex_map.inner_oids(f)
        for i, o in enumerate(np.asarray(oids).tolist()):
            g = golden[int(o)]
            r = float(vals[i])
            assert abs(r - g) <= 1e-4 * max(abs(g), 1e-12), (
                f"shard {f} oid {o}: {r} vs golden {g}"
            )
            checked += 1
    assert checked > 0

    # ---- checkpointed query lane (docs/FAULT_TOLERANCE.md,
    # "Distributed resilience"): each process writes only its own
    # rank_<r>.npz shards under the two-phase commit barrier, then
    # both verify the committed snapshot's manifest ----
    ckpt_dir = os.environ.get("GRAPE_DRYRUN_CKPT_DIR", "")
    ckpt_note = ""
    if ckpt_dir:
        from libgrape_lite_tpu.ft.checkpoint import (
            list_checkpoints, read_meta,
        )
        from libgrape_lite_tpu.models import SSSP

        swk = Worker(SSSP(), frag)
        swk.query_stepwise(
            checkpoint_every=2, checkpoint_dir=ckpt_dir, source=6
        )
        steps = list_checkpoints(ckpt_dir)
        assert steps, f"no committed checkpoint in {ckpt_dir}"
        newest = steps[-1][1]
        meta = read_meta(newest)
        assert meta.get("layout") == "sharded", meta.get("layout")
        assert meta.get("ranks") == NPROC, meta
        for r in range(NPROC):
            shard = os.path.join(newest, f"rank_{r}.npz")
            assert os.path.exists(shard), f"missing {shard}"
        # output() on EVERY rank: the result gather inside it is a
        # process_allgather all processes must join (a rank-0-only
        # call deadlocks the gang); rank 0 alone then writes the files
        out_dir = os.path.join(os.path.dirname(ckpt_dir), "out")
        swk.output(out_dir)
        if pid == 0:
            for f in range(frag.fnum):
                rf = os.path.join(out_dir, f"result_frag_{f}")
                assert os.path.getsize(rf) > 0, f"empty {rf}"
        ckpt_note = (
            f", sharded ckpt rounds={meta['rounds']} ranks={meta['ranks']}"
            f", output files={frag.fnum}"
        )

    print(
        f"[worker {pid}] ok: fnum={fnum}, psum={got}, "
        f"pagerank golden rows checked={checked} rounds={wk.rounds}"
        f"{ckpt_note}",
        flush=True,
    )


def main() -> int:
    if "--worker" in sys.argv:
        i = sys.argv.index("--worker")
        worker(int(sys.argv[i + 1]), sys.argv[i + 2])
        return 0

    import tempfile
    import time

    coord = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ)
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={LOCAL_DEVICES}"
    ).strip()
    # shared dir for the sharded-checkpoint lane; both workers write
    # their rank shards here and verify the committed manifest
    ckpt_tmp = tempfile.TemporaryDirectory(prefix="dryrun_ckpt_")
    env["GRAPE_DRYRUN_CKPT_DIR"] = os.path.join(ckpt_tmp.name, "ck")
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.abspath(__file__),
             "--worker", str(i), coord],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        )
        for i in range(NPROC)
    ]
    # one shared deadline for ALL workers (not 180s each): callers wrap
    # this script in their own timeout, and sequential per-worker waits
    # would overshoot it while orphaning the rest of the gang
    deadline = time.monotonic() + 180
    ok = True
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=max(1.0, deadline - time.monotonic()))
        except subprocess.TimeoutExpired:
            for q in procs:  # a hung gang must die together
                if q.poll() is None:
                    q.kill()
            out, _ = p.communicate()
            ok = False
        outs.append(out)
    for i, (p, out) in enumerate(zip(procs, outs)):
        text = out.decode(errors="replace")
        print(f"--- worker {i} (rc={p.returncode}) ---\n{text}")
        ok = ok and p.returncode == 0 and "ok:" in text
    print("multihost_dryrun:", "PASS" if ok else "FAIL")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
