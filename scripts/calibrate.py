#!/usr/bin/env python
"""Calibration CI entry point (ops/calibration.py, docs/CALIBRATION.md).

Thin wrapper over `python -m libgrape_lite_tpu.cli calibrate` so CI
and shell hooks have a stable script path next to the other gates:

    python scripts/calibrate.py --platform cpu \
        --out scratch/rates.json --samples-out scratch/samples.json
    python scripts/calibrate.py --check --samples scratch/samples.json

Exit codes: 0 fit ok / drift gate passed, 2 infeasible fit or the
active profile drifts >5% from the measured walls.

scripts/app_tests.sh runs a CPU calibrate + drift check on every CI
pass; scripts/tpu_first_light.sh fits the first real-TPU profile and
re-gates the bench under it.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from libgrape_lite_tpu.cli import calibrate_main  # noqa: E402

if __name__ == "__main__":
    sys.exit(calibrate_main(sys.argv[1:]))
