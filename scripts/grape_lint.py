#!/usr/bin/env python
"""grape-lint CI entry point (analysis/, docs/STATIC_ANALYSIS.md).

Thin wrapper over `python -m libgrape_lite_tpu.cli lint` so CI and
shell hooks have a stable script path next to the other gates:

    python scripts/grape_lint.py                 # AST rules, text report
    python scripts/grape_lint.py --json          # structured report
    python scripts/grape_lint.py --artifact      # + compiled-artifact
                                                 #   audits (A1/A2/A3)

Exit codes: 0 clean (baseline suppressions allowed), 1 unsuppressed
finding(s), 3 the --json report itself failed its declared schema
(analysis/report.py validate_lint_report — the same pinned-artifact
contract scripts/check_bench_schema.py applies to BENCH records).

scripts/app_tests.sh runs the AST gate on every CI pass;
scripts/tpu_first_light.sh adds --artifact so the first real-TPU
session also proves no baked constants / surprise compiles on device.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from libgrape_lite_tpu.cli import lint_main  # noqa: E402

if __name__ == "__main__":
    sys.exit(lint_main(sys.argv[1:]))
