#!/bin/bash
# First-live-window playbook (VERDICT r3 next #1): run the complete
# hardware measurement sequence the moment the TPU tunnel answers.
# Usage:  bash scripts/tpu_first_light.sh [outdir]
# The background watcher (scripts/tpu_watch.sh) writes .tpu_alive and
# exits when the chip responds; this script is the follow-up — it can
# also be run directly (it re-probes first and aborts fast if dead).
set -eo pipefail
cd "$(dirname "$0")/.."
OUT=${1:-scratch/first_light}
mkdir -p "$OUT"
# plans persist across every step below AND later bench re-runs
export GRAPE_PACK_PLAN_CACHE="$PWD/scratch/pack_plans"

echo "== probe =="
# must see a REAL accelerator: a failed axon init can fall back to CPU,
# where the pack A/B runs interpret-mode ('not a measurement') and
# burns the live window
if ! timeout 120 python -c "
import jax
d = jax.devices()
print(d)
assert d and d[0].platform != 'cpu', f'CPU fallback: {d}'
"; then
  echo "tunnel dead (or CPU fallback); aborting" >&2
  exit 1
fi

echo "== grape-lint artifact audit (no baked constants / surprise
compiles ON DEVICE — the A1/A3 contracts proven against real TPU
lowering, not the CPU fallback; docs/STATIC_ANALYSIS.md) =="
if ! timeout 900 python scripts/grape_lint.py --artifact --json \
    > "$OUT/lint_artifact.json" 2> "$OUT/lint_artifact.err"; then
  echo "GRAPE-LINT ARTIFACT AUDIT FAILED (see $OUT/lint_artifact.json" \
       "— a baked constant or surprise compile on device)" >&2
  tail -5 "$OUT/lint_artifact.err" >&2
  exit 1
fi

echo "== primitive rates (prices the sublane dynamic_gather — the
cost-model unknown; see docs/PERF_NOTES.md r4 section) =="
timeout 900 python scripts/pallas_probe.py 2> "$OUT/probe.err" | tee "$OUT/probe.json" || true

echo "== bench A/B (xla vs pack, PageRank + SSSP) =="
GRAPE_BENCH_ASSUME_ALIVE=1 timeout 3600 python bench.py \
  2> "$OUT/bench.err" | tee "$OUT/bench.json" \
  || { tail -20 "$OUT/bench.err" >&2; exit 1; }
# pack-ineligibility / fallback warnings matter even on success — a
# silent xla-only A/B must not read as a pack measurement
grep -iE "pack|warn" "$OUT/bench.err" | tail -10 || true

echo "== scan A/B (mxu triangular-matmul scan vs shift ladder; both
plans pre-seeded by scripts/seed_pack_plans.py) =="
GRAPE_BENCH_ASSUME_ALIVE=1 GRAPE_SPMV=pack GRAPE_PACK_SCAN=shift \
  timeout 3600 python bench.py \
  2> "$OUT/bench_shift.err" | tee "$OUT/bench_shift.json" || true

echo "== pipeline A/B (GRAPE_PIPELINE=0 vs 1 — superstep software
pipelining, parallel/pipeline.py; the bench's own pipeline lane runs
the serial-vs-pipelined pair at fnum>=2 and gates on byte identity +
the overlap-term recount; docs/PIPELINE.md) =="
GRAPE_BENCH_ASSUME_ALIVE=1 GRAPE_PIPELINE=0 timeout 3600 python bench.py \
  2> "$OUT/bench_pipe0.err" | tee "$OUT/bench_pipe0.json" || true
GRAPE_BENCH_ASSUME_ALIVE=1 GRAPE_PIPELINE=1 timeout 3600 python bench.py \
  2> "$OUT/bench_pipe1.err" | tee "$OUT/bench_pipe1.json" || true
grep -h "\[bench\] pipeline" "$OUT/bench_pipe0.err" \
  "$OUT/bench_pipe1.err" | tail -4 || true

echo "== lcc backend A/B (GRAPE_LCC_BACKEND=intersect vs spgemm —
tiled masked SpGEMM on the MXU, ops/spgemm_pack.py; the bench's own
spgemm lane runs the pair at lane geometry and gates on bit-identity
+ the ledger recount; docs/SPGEMM.md) =="
GRAPE_BENCH_ASSUME_ALIVE=1 GRAPE_LCC_BACKEND=intersect \
  timeout 3600 python bench.py \
  2> "$OUT/bench_lcc_int.err" | tee "$OUT/bench_lcc_int.json" || true
GRAPE_BENCH_ASSUME_ALIVE=1 GRAPE_LCC_BACKEND=spgemm \
  timeout 3600 python bench.py \
  2> "$OUT/bench_lcc_sp.err" | tee "$OUT/bench_lcc_sp.json" || true
grep -h "\[bench\] spgemm" "$OUT/bench_lcc_int.err" \
  "$OUT/bench_lcc_sp.err" | tail -4 || true

echo "== serve async-pump A/B (dispatch window, serve/pipeline.py —
the bench's own serve_async lane interleaves W=1 vs W=4 at b in
{1,8,32} with concurrent barrier ingest and gates on per-query byte
identity + zero overlay recompiles; on TPU the launch cap defaults to
the full window because the device queue serialises programs without
stealing host cores — the overlap the CPU fallback could not show;
docs/SERVING.md \"The async pump\") =="
GRAPE_BENCH_ASSUME_ALIVE=1 timeout 3600 python bench.py \
  2> "$OUT/bench_serve_async.err" | tee "$OUT/bench_serve_async.json" \
  || true
grep -h "\[bench\] serve_async" "$OUT/bench_serve_async.err" \
  | tail -8 || true

echo "== per-stage profile (stepwise mode, per-round wall clock) =="
GRAPE_SPMV=pack GRAPE_TPU_VLOG=1 timeout 1200 python - <<'EOF' 2>&1 | tee "$OUT/profile.log" || true
import sys
sys.path.insert(0, ".")
import numpy as np
from bench import rmat_edges
from libgrape_lite_tpu.fragment.edgecut import ShardedEdgecutFragment
from libgrape_lite_tpu.parallel.comm_spec import CommSpec
from libgrape_lite_tpu.utils.id_parser import IdParser
from libgrape_lite_tpu.utils.types import LoadStrategy
from libgrape_lite_tpu.vertex_map.idxer import HashMapIdxer
from libgrape_lite_tpu.vertex_map.partitioner import SegmentedPartitioner
from libgrape_lite_tpu.vertex_map.vertex_map import VertexMap
from libgrape_lite_tpu.models import PageRank
from libgrape_lite_tpu.worker.worker import Worker

n, src, dst = rmat_edges(20, 16)
oids = np.arange(n, dtype=np.int64)
part = SegmentedPartitioner(1, oids)
vm = VertexMap(part, [HashMapIdxer(oids)], IdParser(1, n))
frag = ShardedEdgecutFragment.build(
    CommSpec(fnum=1), vm, src, dst, None, directed=False,
    load_strategy=LoadStrategy.kBothOutIn)
app = PageRank(delta=0.85, max_round=10)
w = Worker(app, frag)
w.query_stepwise(max_rounds=10)   # logs per-round wall clock
EOF

echo "== op-budget ledger vs measurement (offline-safe; the stepwise
profile above logs the same per-stage attribution via the worker's
pack op-budget vlog line) =="
timeout 1800 python scripts/pack_cost_model.py \
  2> "$OUT/cost_model.err" | tee "$OUT/cost_model.json" || {
  echo "LEDGER/COST-MODEL MISMATCH (see $OUT/cost_model.err)" >&2
}

echo "== calibrate-then-recheck (r17, ops/calibration.py,
docs/CALIBRATION.md): fit the FIRST real-TPU rate profile from
measured device walls, persist profile + sweep, then re-run the
bench drift lane UNDER the fitted profile — exit 2 there means the
fit does not model the hardware it just measured =="
timeout 1800 python scripts/calibrate.py \
  --out "$OUT/rates.json" --samples-out "$OUT/rate_samples.json" \
  2> "$OUT/calibrate.err" | tee "$OUT/calibrate.txt" || {
  echo "CALIBRATION FIT/GATE FAILED (see $OUT/calibrate.err)" >&2
}
if [ -f "$OUT/rates.json" ]; then
  GRAPE_RATE_PROFILE="$OUT/rates.json" \
  GRAPE_CALIBRATION_SAMPLES="$OUT/rate_samples.json" \
  GRAPE_BENCH_ASSUME_ALIVE=1 GRAPE_BENCH_SCALE=16 \
  GRAPE_BENCH_NO_GUARD=1 GRAPE_BENCH_NO_SERVE=1 \
  GRAPE_BENCH_NO_SERVE_ASYNC=1 GRAPE_BENCH_NO_DYN=1 \
  GRAPE_BENCH_NO_PIPELINE=1 GRAPE_BENCH_NO_P2D=1 \
  GRAPE_BENCH_NO_SPGEMM=1 GRAPE_BENCH_NO_FLEET=1 \
  GRAPE_BENCH_NO_AUTOPILOT=1 GRAPE_BENCH_NO_TELEMETRY=1 \
  GRAPE_BENCH_NO_LEDGER=1 \
  timeout 1800 python bench.py \
    > "$OUT/bench_calibrated.json" 2> "$OUT/bench_calibrated.err" || {
    echo "CALIBRATED DRIFT GATE FAILED — the fitted profile drifts" \
         ">5% from its own measurement (see $OUT/bench_calibrated.err)" >&2
  }
fi

echo "== done; results in $OUT =="
