"""Shared measurement core for the TPU probe scripts.

`block_until_ready` does NOT drain the remote queue under the axon
tunnel — timings without a data-dependent device->host readback are
fiction (prim_bench once reported 6,674 "TFLOPS" that way).  Every
timing here therefore ends in a real device_get of one element.
"""

from __future__ import annotations

import time


def sync(x):
    """Force a real device->host readback of one element."""
    import jax
    import numpy as np

    leaf = jax.tree.leaves(x)[0]
    return np.asarray(leaf.ravel()[:1])


def timeit(fn, *args, iters=5):
    """Average seconds per call over `iters` dispatches, amortizing one
    readback at the end (the queue is FIFO, so the final sync waits for
    all dispatched iterations)."""
    out = fn(*args)
    sync(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    sync(out)
    return (time.perf_counter() - t0) / iters
