#!/usr/bin/env python
"""LDBC Graphalytics benchmark driver.

Re-design of the reference's Java harness (`ldbc_driver/`, driven by
`run_ldbc.sh`): runs the six Graphalytics algorithms (BFS, PR, WCC,
CDLP, LCC, SSSP) on a dataset, times load/compile/run phases separately
(Graphalytics scores processing time only), optionally validates
against expected-output files, and writes a JSON report.

Usage:
  python scripts/run_ldbc.py --efile dataset/p2p-31.e \
      --vfile dataset/p2p-31.v --validation_dir dataset \
      --dataset_name p2p-31 --fnum 4 [--platform cpu --cpu_devices 8]
  python scripts/run_ldbc.py ci     # the run_ldbc.sh ci equivalent
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

ALGOS = ["bfs", "pagerank", "wcc", "cdlp", "lcc", "sssp"]


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "ci":
        argv = [
            "--efile", os.path.join(REPO, "dataset", "p2p-31.e"),
            "--vfile", os.path.join(REPO, "dataset", "p2p-31.v"),
            "--validation_dir", os.path.join(REPO, "dataset"),
            "--dataset_name", "p2p-31",
            "--platform", "cpu", "--cpu_devices", "4", "--fnum", "4",
        ] + argv[1:]

    p = argparse.ArgumentParser()
    p.add_argument("--efile", required=True)
    p.add_argument("--vfile", required=True)
    p.add_argument("--dataset_name", default="dataset")
    p.add_argument("--validation_dir", default="")
    p.add_argument("--fnum", type=int, default=None)
    p.add_argument("--platform", default="")
    p.add_argument("--cpu_devices", type=int, default=0)
    p.add_argument("--algorithms", default=",".join(ALGOS))
    p.add_argument("--source", type=int, default=6)
    p.add_argument("--report", default="ldbc_report.json")
    p.add_argument("--runs", type=int, default=3)
    args = p.parse_args(argv)

    if args.cpu_devices:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.cpu_devices}"
        ).strip()
    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    import numpy as np

    from libgrape_lite_tpu.fragment.loader import LoadGraph, LoadGraphSpec
    from libgrape_lite_tpu.models import APP_REGISTRY
    from libgrape_lite_tpu.parallel.comm_spec import CommSpec
    from libgrape_lite_tpu.worker.worker import Worker, format_result_lines

    comm = CommSpec(fnum=args.fnum)
    report = {
        "dataset": args.dataset_name,
        "fnum": comm.fnum,
        "platform": jax.devices()[0].platform,
        "device": str(jax.devices()[0]),
        "results": {},
    }

    t0 = time.perf_counter()
    frag_w = LoadGraph(
        args.efile, args.vfile, comm,
        LoadGraphSpec(weighted=True, edata_dtype=np.float64),
    )
    report["load_seconds"] = round(time.perf_counter() - t0, 4)

    def query_kwargs(name):
        if name in ("sssp", "bfs"):
            return {"source": args.source}
        if name == "pagerank":
            return {"delta": 0.85, "max_round": 10}
        if name == "cdlp":
            return {"max_round": 10}
        return {}

    for name in args.algorithms.split(","):
        app = APP_REGISTRY[name]()
        worker = Worker(app, frag_w)
        kw = query_kwargs(name)
        t0 = time.perf_counter()
        worker.query(**kw)  # includes compile
        cold = time.perf_counter() - t0
        # processing_s = best of `runs` warm runs (cold run excluded,
        # like Graphalytics' makespan vs processing split).  Every query
        # blocks on the result (Worker.query -> block_until_ready), so a
        # warm run exceeding the cold makespan can only be host-load
        # noise — the full warm list is recorded so a single noisy
        # sample is visible instead of silently reported as the metric.
        warm = []
        for _ in range(max(1, args.runs)):
            t0 = time.perf_counter()
            worker.query(**kw)
            warm.append(time.perf_counter() - t0)
        entry = {
            "makespan_cold_s": round(cold, 4),
            "processing_s": round(min(warm), 4),
            "warm_runs_s": [round(w, 4) for w in warm],
            "rounds": worker.rounds,
        }
        if min(warm) > cold:
            entry["timer_note"] = (
                "warm > cold despite blocked timing: host-load noise"
            )

        suffix_map = {
            "bfs": "BFS", "pagerank": "PR", "wcc": "WCC",
            "cdlp": "CDLP", "lcc": "LCC", "sssp": "SSSP",
        }
        base = name.split("_")[0]  # same-result variants share the golden
        # pagerank_local* are a genuinely different algorithm
        # (competitor-compatible convergence, Performance.md:61-67) and
        # can never match the standard PR golden
        if name.startswith("pagerank_local"):
            base = None
        if args.validation_dir and base in suffix_map:
            suffix = suffix_map[base]
            golden_path = os.path.join(
                args.validation_dir, f"{args.dataset_name}-{suffix}"
            )
            if os.path.exists(golden_path):
                entry["validated"] = _validate(
                    worker, frag_w, base, golden_path, format_result_lines
                )
        report["results"][name] = entry
        print(f"{name}: {entry}")

    with open(args.report, "w") as f:
        json.dump(report, f, indent=2)
    print(f"report -> {args.report}")
    failed = [
        k for k, v in report["results"].items() if v.get("validated") is False
    ]
    if failed:
        print(f"VALIDATION FAILED: {failed}")
        return 1
    return 0


def _validate(worker, frag, name, golden_path, fmt_lines) -> bool:
    from tests.verifiers import (
        eps_verify, exact_verify, load_golden, load_result_lines, wcc_verify,
    )

    values = worker.result_values()
    chunks = []
    for f in range(frag.fnum):
        n = frag.inner_vertices_num(f)
        if n:
            chunks.append(
                fmt_lines(frag.inner_oids(f), values[f, :n],
                          worker.app.result_format)
            )
    res = load_result_lines("".join(chunks))
    gold = load_golden(golden_path)
    try:
        if name == "wcc":
            wcc_verify(res, gold)
        elif name in ("pagerank", "lcc"):
            eps_verify(res, gold)
        elif name == "sssp":
            inf_r = {k for k, v in res.items() if v == "infinity"}
            inf_g = {k for k, v in gold.items() if v == "infinity"}
            if inf_r != inf_g:
                return False
            eps_verify(
                {k: v for k, v in res.items() if k not in inf_r},
                {k: v for k, v in gold.items() if k not in inf_g},
            )
        else:
            exact_verify(res, gold)
        return True
    except AssertionError:
        return False


if __name__ == "__main__":
    sys.exit(main())
