#!/usr/bin/env python
"""Kill/resume fault drill — the end-to-end proof that checkpoint
recovery works, runnable as a CI smoke check.

Two modes:

**kill/resume** (default) — per app (default: sssp, pagerank, cdlp on
dataset/p2p-31):

  1. **reference** — an uninterrupted checkpointed run writes its
     per-fragment result files.
  2. **kill** — the same run re-executes in a child process armed with
     `GRAPE_FT_FAULTS=kill@K`: the process is killed (os._exit) right
     after superstep K's checkpoint is durable.  The drill asserts the
     child died with the injected exit code and produced no output.
  3. **corrupt** (`--corrupt`) — the newest checkpoint shard is
     byte-flipped, so the resume must fall back to the previous
     complete superstep.
  4. **resume** — `--resume` continues from the last usable checkpoint
     and writes its result files.
  5. **verify** — the resumed output must be byte-identical to the
     reference output.

**self-heal** (`--self-heal`, default apps: sssp, pagerank, wcc) — the
guard/ closed loop, end-to-end through the real CLI:

  1. **reference** — an uninterrupted checkpointed run writes its
     per-fragment result files.
  2. **heal** — the same run re-executes armed with
     `GRAPE_FT_FAULTS=corrupt_carry@K` and `GRAPE_GUARD=rollback`: the
     injected device-state corruption must be detected by the app's
     invariants within one cadence, rolled back to the last good
     snapshot, replayed in paranoid mode, and the process must exit 0.
  3. **verify** — the healed output must be byte-identical to the
     reference, and the log must show the breach + rollback markers.

**postmortem** (`--postmortem`) — the flight-recorder loop (obs/
recorder.py), end-to-end through the real `serve` CLI under the fleet:

  1. **breach** — a 2-replica, 2-tenant serve run executes a mixed
     24-query stream armed with `GRAPE_FT_FAULTS=corrupt_carry@K` and
     `--guard halt`: every poisoned lane fails ALONE (breach
     isolation), and each guard breach trips `RECORDER.trigger`,
     dumping a postmortem bundle into the `GRAPE_POSTMORTEM` sink.
  2. **verify** — the newest bundle must carry the guard forensics
     plus buffered `serve_query` span rows, and
     `cli postmortem <bundle> --trace <trace.json>` must prove every
     bundle span row byte-matches the Chrome trace's row for the same
     query id (bundles copy tracer history verbatim — any drift in
     the export form is a correlation bug).

**kill_rank** (`--kill_rank`, default app: sssp) — the distributed
resilience drill (docs/FAULT_TOLERANCE.md, "Distributed resilience"):

  1. **reference** — a fault-free single-process run on the REDUCED
     fnum-2 mesh the survivors will restore onto.
  2. **gang** — a 2-process `jax.distributed` gang runs the query at
     fnum 4 with sharded two-phase checkpoints
     (`ckpt_<K>/rank_<r>.npz`); `GRAPE_FT_FAULTS=kill_rank@K:1` kills
     rank 1 right after superstep K's commit is durable, stranding
     rank 0 in the next collective (genuine process loss).
  3. **gang telemetry** (PR 20) — the same gang re-runs with
     GRAPE_TRACE + GRAPE_POSTMORTEM armed and a RAISE-mode kill: the
     injected fault travels the breach vote, both ranks halt, the
     per-rank sidecars merge into one Perfetto timeline (both ranks'
     superstep spans, a vote flow crossing the rank tracks, monotonic
     aligned timestamps), and every rank's postmortem shard lands
     under one `incident_<id>/` with a byte-verified `gang.json`.
  4. **reshard restore** — a single survivor process resumes the
     4-shard snapshot onto fnum 2 (`restore_resharded`).
  5. **verify** — the resumed output must be byte-identical to the
     fault-free run; a schema'd `ft_drill` JSON record is emitted
     (scripts/check_bench_schema.py) carrying the gang-telemetry
     fields.  Exit 2 iff results diverge.

Exit code 0 iff every app passes.  Usage:

    python scripts/fault_drill.py                 # kill/resume, 3 apps
    python scripts/fault_drill.py --apps sssp --corrupt
    python scripts/fault_drill.py --self-heal     # guard rollback drill
    python scripts/fault_drill.py --postmortem    # flight-recorder drill
    python scripts/fault_drill.py --kill_rank     # distributed reshard drill
"""

from __future__ import annotations

import argparse
import filecmp
import os
import shutil
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

APP_FLAGS = {
    "sssp": ["--sssp_source", "6"],
    "pagerank": ["--pr_mr", "10"],
    "cdlp": ["--cdlp_mr", "10"],
}


def run_cli(extra, env_overrides=None, timeout=600):
    env = dict(os.environ)
    env.pop("GRAPE_FT_FAULTS", None)
    env.pop("GRAPE_GUARD", None)  # ambient guards must not leak in
    env.pop("GRAPE_POSTMORTEM", None)  # nor an ambient bundle sink
    env.update(env_overrides or {})
    cmd = [sys.executable, "-m", "libgrape_lite_tpu.cli"] + extra
    proc = subprocess.run(
        cmd, cwd=REPO, env=env, timeout=timeout,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
    )
    return proc.returncode, proc.stdout.decode(errors="replace")


def compare_outputs(ref_dir: str, res_dir: str) -> list[str]:
    problems = []
    ref_files = sorted(os.listdir(ref_dir))
    res_files = sorted(os.listdir(res_dir))
    if ref_files != res_files:
        return [f"file sets differ: {ref_files} vs {res_files}"]
    for name in ref_files:
        if not filecmp.cmp(
            os.path.join(ref_dir, name), os.path.join(res_dir, name),
            shallow=False,
        ):
            problems.append(f"{name} differs byte-for-byte")
    if not ref_files:
        problems.append("reference run produced no output files")
    return problems


def drill(app: str, args, workdir: str) -> bool:
    from libgrape_lite_tpu.ft.checkpoint import list_checkpoints
    from libgrape_lite_tpu.ft.faults import (
        DEFAULT_KILL_EXIT_CODE, corrupt_file,
    )

    wd = os.path.join(workdir, app)
    os.makedirs(wd, exist_ok=True)
    base = [
        "--application", app,
        "--efile", args.efile, "--vfile", args.vfile,
        "--platform", "cpu", "--cpu_devices", str(args.cpu_devices),
        "--checkpoint_every", str(args.checkpoint_every),
    ] + APP_FLAGS.get(app, [])

    out_ref = os.path.join(wd, "out_ref")
    rc, log = run_cli(base + [
        "--checkpoint_dir", os.path.join(wd, "ck_ref"),
        "--out_prefix", out_ref,
    ])
    if rc != 0:
        print(f"[{app}] FAIL: reference run rc={rc}\n{log}")
        return False

    ck = os.path.join(wd, "ck")
    out_kill = os.path.join(wd, "out_kill")
    rc, log = run_cli(
        base + ["--checkpoint_dir", ck, "--out_prefix", out_kill],
        env_overrides={"GRAPE_FT_FAULTS": f"kill@{args.kill_at}"},
    )
    if rc != DEFAULT_KILL_EXIT_CODE:
        print(
            f"[{app}] FAIL: killed run rc={rc} "
            f"(expected {DEFAULT_KILL_EXIT_CODE})\n{log}"
        )
        return False
    if os.path.exists(out_kill) and os.listdir(out_kill):
        print(f"[{app}] FAIL: killed run wrote output")
        return False
    steps = list_checkpoints(ck)
    if not steps:
        print(f"[{app}] FAIL: killed run left no complete checkpoint")
        return False

    if args.corrupt:
        shard = os.path.join(steps[-1][1], "state.npz")
        corrupt_file(shard)
        print(f"[{app}] corrupted newest shard {shard}")

    out_res = os.path.join(wd, "out_res")
    rc, log = run_cli(base + [
        "--resume", "--checkpoint_dir", ck, "--out_prefix", out_res,
    ])
    if rc != 0:
        print(f"[{app}] FAIL: resume rc={rc}\n{log}")
        return False

    problems = compare_outputs(out_ref, out_res)
    if problems:
        print(f"[{app}] FAIL: " + "; ".join(problems))
        return False
    killed_at = steps[-1][0]
    print(
        f"[{app}] PASS: killed at superstep {args.kill_at} "
        f"(last checkpoint {killed_at}"
        f"{', corrupted' if args.corrupt else ''}), resumed run is "
        f"byte-identical to the uninterrupted one"
    )
    return True


def self_heal_drill(app: str, args, workdir: str) -> bool:
    """corrupt_carry@K + GRAPE_GUARD=rollback must self-heal to
    byte-identical results through the real CLI."""
    import re

    wd = os.path.join(workdir, f"heal_{app}")
    os.makedirs(wd, exist_ok=True)
    base = [
        "--application", app,
        "--efile", args.efile, "--vfile", args.vfile,
        "--platform", "cpu", "--cpu_devices", str(args.cpu_devices),
        "--checkpoint_every", str(args.checkpoint_every),
    ] + APP_FLAGS.get(app, [])

    out_ref = os.path.join(wd, "out_ref")
    rc, log = run_cli(base + [
        "--checkpoint_dir", os.path.join(wd, "ck_ref"),
        "--out_prefix", out_ref,
    ])
    if rc != 0:
        print(f"[{app}] FAIL: reference run rc={rc}\n{log}")
        return False

    out_heal = os.path.join(wd, "out_heal")
    rc, log = run_cli(
        base + [
            "--checkpoint_dir", os.path.join(wd, "ck_heal"),
            "--out_prefix", out_heal, "--guard", "rollback",
        ],
        env_overrides={
            "GRAPE_FT_FAULTS": f"corrupt_carry@{args.corrupt_carry_at}",
        },
    )
    if rc != 0:
        print(f"[{app}] FAIL: self-heal run rc={rc}\n{log}")
        return False

    m = re.search(r"invariant breach at superstep (\d+)", log)
    if not m:
        print(f"[{app}] FAIL: injected corruption was never detected\n{log}")
        return False
    breach_at = int(m.group(1))
    if breach_at - args.corrupt_carry_at > args.checkpoint_every:
        print(
            f"[{app}] FAIL: breach detected at superstep {breach_at}, "
            f"more than one cadence after the injection at "
            f"{args.corrupt_carry_at}"
        )
        return False
    if "rolled back to superstep" not in log:
        print(f"[{app}] FAIL: breach detected but no rollback ran\n{log}")
        return False

    problems = compare_outputs(out_ref, out_heal)
    if problems:
        print(f"[{app}] FAIL: " + "; ".join(problems))
        return False
    print(
        f"[{app}] PASS: corrupt_carry@{args.corrupt_carry_at} detected at "
        f"superstep {breach_at}, rolled back, replayed; healed run is "
        f"byte-identical to the fault-free one"
    )
    return True


def postmortem_drill(args, workdir: str) -> bool:
    """Guard breaches under the fleet must dump flight-recorder
    bundles whose serve_query span rows byte-match the Chrome trace."""
    import glob
    import json

    wd = os.path.join(workdir, "postmortem")
    os.makedirs(wd, exist_ok=True)
    stream = os.path.join(wd, "stream.txt")
    with open(stream, "w") as fh:
        for i in range(16):
            fh.write(f"sssp {6 + i}\n")
        for i in range(8):
            fh.write(f"bfs {6 + i}\n")
    pm = os.path.join(wd, "pm")
    trace = os.path.join(wd, "trace.json")

    # --max_batch 1 pins the stepwise guarded lane (the corrupt_carry
    # hook's path); halt policy = breach isolation, so every poisoned
    # query fails alone and the stream still completes
    rc, log = run_cli(
        [
            "serve",
            "--efile", args.efile, "--vfile", args.vfile,
            "--platform", "cpu", "--cpu_devices", str(args.cpu_devices),
            "--fnum", "2", "--stream", stream, "--max_batch", "1",
            "--guard", "halt", "--replicas", "2", "--tenants", "by_app",
            "--trace", trace,
        ],
        env_overrides={
            "GRAPE_FT_FAULTS": f"corrupt_carry@{args.corrupt_carry_at}",
            "GRAPE_POSTMORTEM": pm,
        },
    )
    if rc != 1:
        print(f"[postmortem] FAIL: poisoned serve rc={rc} (expected 1: "
              f"every lane breaches, the stream completes)\n{log}")
        return False
    if "invariant breach at superstep" not in log:
        print(f"[postmortem] FAIL: no breach was ever detected\n{log}")
        return False
    try:
        rec = json.loads(
            [l for l in log.splitlines() if l.startswith("{")][-1])
    except (IndexError, ValueError):
        print(f"[postmortem] FAIL: serve wrote no summary record\n{log}")
        return False
    if rec["queries"] != 24 or rec["failed"] != 24:
        print(f"[postmortem] FAIL: expected all 24 poisoned lanes to "
              f"fail alone, got {rec['failed']}/{rec['queries']}")
        return False

    bundles = sorted(glob.glob(os.path.join(pm, "postmortem_*.json")))
    if len(bundles) < 2:
        print(f"[postmortem] FAIL: {len(bundles)} bundle(s) dumped, "
              f"expected one per breach")
        return False
    newest = bundles[-1]
    bundle = json.load(open(newest))
    sq = [s for s in bundle.get("spans", [])
          if s.get("name") == "serve_query"]
    if not sq or not bundle.get("guard") or not bundle.get("federation"):
        print(f"[postmortem] FAIL: newest bundle lacks serve_query "
              f"spans / guard forensics / federation snapshot "
              f"({len(sq)} spans)")
        return False

    rc, log = run_cli(["postmortem", newest, "--trace", trace])
    if rc != 0:
        print(f"[postmortem] FAIL: postmortem --trace rc={rc}\n{log}")
        return False
    if "0 mismatched, 0 absent" not in log:
        print(f"[postmortem] FAIL: bundle span rows drifted from the "
              f"Chrome trace\n{log}")
        return False
    print(
        f"[postmortem] PASS: {len(bundles)} breach bundle(s) dumped "
        f"under the 2-replica fleet; newest carries {len(sq)} "
        f"serve_query row(s), every one byte-identical to the Chrome "
        f"trace's row for the same query id"
    )
    return True


def _gang_telemetry_leg(app: str, args, wd: str, common) -> dict | None:
    """Gang-wide telemetry leg of the kill_rank drill (PR 20,
    docs/OBSERVABILITY.md "Gang-wide telemetry"): re-run the
    2-process gang with the tracer armed and a RAISE-mode rank kill,
    so the injected fault travels the breach vote instead of
    os._exit — rank 1 re-raises InjectedFault, rank 0 halts on
    RemoteBreachError, and BOTH ranks land their telemetry:

      * per-rank trace sidecars under `<trace>.gang/`, merged here via
        obs.gang.assemble — the drill pins both ranks' superstep
        spans, at least one breach-vote flow crossing the rank tracks,
        and monotonic post-alignment timestamps;
      * the distributed flight recorder: one `incident_<id>/` dir in
        the GRAPE_POSTMORTEM sink holding every rank's shard plus the
        rank-0 `gang.json` manifest with byte-verified shard hashes.

    Returns the gang fields for the ft_drill record, or None on any
    failed check."""
    import glob
    import json
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        coord = f"127.0.0.1:{s.getsockname()[1]}"
    trace = os.path.join(wd, "gang_trace.json")
    pm = os.path.join(wd, "gang_pm")
    env = dict(os.environ)
    env.pop("GRAPE_GUARD", None)
    # the whole point of mode=raise: the kill is an exception, so the
    # breach vote (not a stranded collective) halts the gang and the
    # telemetry plane gets to run on every rank
    env["GRAPE_FT_FAULTS"] = f"kill_rank@{args.kill_at}:1,mode=raise"
    env["GRAPE_TRACE"] = trace
    env["GRAPE_POSTMORTEM"] = pm
    flags = common + [
        "--fnum", "4",
        "--checkpoint_dir", os.path.join(wd, "ck_gangtrace"),
        "--out_prefix", os.path.join(wd, "out_gangtrace"),
        "--coordinator", coord, "--num_processes", "2",
    ]
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "libgrape_lite_tpu.cli"]
            + flags + ["--process_id", str(r)],
            cwd=REPO, env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        )
        for r in range(2)
    ]
    outs = []
    timed_out = False
    for q in procs:
        try:
            out, _ = q.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            timed_out = True
            q.kill()
            out, _ = q.communicate()
        outs.append(out.decode(errors="replace"))
    if timed_out or any(q.returncode == 0 for q in procs):
        print(
            f"[{app}] FAIL: raise-mode gang must halt BOTH ranks "
            f"through the vote (rcs="
            f"{[q.returncode for q in procs]}, timeout={timed_out})\n"
            f"--- rank 0 ---\n{outs[0]}\n--- rank 1 ---\n{outs[1]}"
        )
        return None

    from libgrape_lite_tpu.obs import gang

    summary = gang.assemble(
        os.path.splitext(trace)[0] + ".gang",
        out_path=os.path.join(wd, "gang_merged.json"),
    )
    problems = []
    if not summary["complete"]:
        problems.append(
            f"merged gang trace incomplete: ranks={summary['ranks']} "
            f"missing={summary['missing']} aligned={summary['aligned']}"
        )
    if any(int(summary["supersteps_by_rank"].get(str(r), 0)) < 1
           for r in range(2)):
        problems.append(
            "a rank contributed no superstep spans: "
            f"{summary['supersteps_by_rank']}"
        )
    if summary["cross_rank_flows"] < 1:
        problems.append(
            f"no breach-vote flow crosses the rank tracks "
            f"({summary['flow_events']} flow leg(s), "
            f"{summary['flow_ids']} id(s))"
        )
    if not summary["monotonic"]:
        problems.append("post-alignment timestamps are not monotonic")

    incident_dirs = sorted(glob.glob(os.path.join(pm, "incident_*")))
    manifest = {}
    if len(incident_dirs) != 1:
        problems.append(
            f"expected ONE shared incident dir, found "
            f"{[os.path.basename(d) for d in incident_dirs]}"
        )
    else:
        inc = incident_dirs[0]
        for r in range(2):
            if not os.path.exists(os.path.join(inc, f"rank_{r}.json")):
                problems.append(f"incident lacks rank_{r}.json")
        mpath = os.path.join(inc, "gang.json")
        if not os.path.exists(mpath):
            problems.append("rank 0 wrote no gang.json manifest")
        else:
            manifest = json.load(open(mpath))
            if not manifest.get("complete"):
                problems.append(
                    f"gang manifest not byte-verified: "
                    f"{manifest.get('shards')}"
                )
    if problems:
        print(f"[{app}] FAIL (gang telemetry): " + "; ".join(problems)
              + f"\n--- rank 0 ---\n{outs[0]}\n--- rank 1 ---\n{outs[1]}")
        return None
    print(
        f"[{app}] gang telemetry: merged trace complete "
        f"({summary['events']} events, supersteps "
        f"{summary['supersteps_by_rank']}, "
        f"{summary['cross_rank_flows']} cross-rank flow(s)); "
        f"incident {manifest.get('incident')} byte-verified across "
        f"{manifest.get('nprocs')} rank(s)"
    )
    return {
        "gang_trace_events": int(summary["events"]),
        "gang_trace_complete": bool(summary["complete"]),
        "gang_cross_rank_flows": int(summary["cross_rank_flows"]),
        "gang_incident": str(manifest.get("incident", "")),
        "gang_bundle_verified": bool(manifest.get("complete", False)),
    }


def kill_rank_drill(app: str, args, workdir: str) -> int:
    """Distributed resilience drill (docs/FAULT_TOLERANCE.md): a
    2-process gang runs the query at fnum 4 with sharded two-phase
    checkpoints, rank 1 is killed at superstep K, and the survivors'
    snapshot is restored onto a *smaller* single-process fnum-2 mesh
    (reshard-on-loss).  The resumed output must be byte-identical to a
    fault-free run on that reduced mesh.  Returns 0 on pass, 2 on
    result divergence, 1 on any other failure."""
    import json
    import socket
    import time

    from libgrape_lite_tpu.ft.checkpoint import list_checkpoints, read_meta
    from libgrape_lite_tpu.ft.faults import DEFAULT_KILL_EXIT_CODE

    wd = os.path.join(workdir, f"killrank_{app}")
    os.makedirs(wd, exist_ok=True)
    common = [
        "--application", app,
        "--efile", args.efile, "--vfile", args.vfile,
        "--platform", "cpu", "--cpu_devices", "2",
        "--checkpoint_every", str(args.checkpoint_every),
    ] + APP_FLAGS.get(app, [])

    # 1. fault-free reference on the REDUCED mesh the survivors will
    # restore onto (fnum 2, single process)
    out_ref = os.path.join(wd, "out_ref")
    rc, log = run_cli(common + [
        "--fnum", "2",
        "--checkpoint_dir", os.path.join(wd, "ck_ref"),
        "--out_prefix", out_ref,
    ])
    if rc != 0:
        print(f"[{app}] FAIL: fnum-2 reference run rc={rc}\n{log}")
        return 1

    # 2. 2-process gang at fnum 4 (2 local CPU devices each), sharded
    # checkpoints, rank 1 killed at superstep K right after the
    # two-phase commit is durable
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        coord = f"127.0.0.1:{s.getsockname()[1]}"
    ck = os.path.join(wd, "ck")
    env = dict(os.environ)
    env.pop("GRAPE_GUARD", None)
    env.pop("GRAPE_POSTMORTEM", None)
    env["GRAPE_FT_FAULTS"] = f"kill_rank@{args.kill_at}:1"
    gang_flags = common + [
        "--fnum", "4", "--checkpoint_dir", ck,
        "--out_prefix", os.path.join(wd, "out_gang"),
        "--coordinator", coord, "--num_processes", "2",
    ]
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "libgrape_lite_tpu.cli"]
            + gang_flags + ["--process_id", str(r)],
            cwd=REPO, env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        )
        for r in range(2)
    ]
    try:
        out1, _ = procs[1].communicate(timeout=300)
    except subprocess.TimeoutExpired:
        for q in procs:
            if q.poll() is None:
                q.kill()
        out1, _ = procs[1].communicate()
        print(f"[{app}] FAIL: killed rank never exited\n"
              f"{out1.decode(errors='replace')}")
        procs[0].communicate()
        return 1
    # rank 0 is stranded in the next collective once its sibling is
    # gone — that IS the loss scenario; the gang dies together
    time.sleep(1.0)
    if procs[0].poll() is None:
        procs[0].kill()
    out0, _ = procs[0].communicate()
    if procs[1].returncode != DEFAULT_KILL_EXIT_CODE:
        print(
            f"[{app}] FAIL: killed rank rc={procs[1].returncode} "
            f"(expected {DEFAULT_KILL_EXIT_CODE})\n"
            f"--- rank 0 ---\n{out0.decode(errors='replace')}\n"
            f"--- rank 1 ---\n{out1.decode(errors='replace')}"
        )
        return 1
    steps = list_checkpoints(ck)
    if not steps:
        print(f"[{app}] FAIL: gang left no complete sharded checkpoint\n"
              f"--- rank 0 ---\n{out0.decode(errors='replace')}\n"
              f"--- rank 1 ---\n{out1.decode(errors='replace')}")
        return 1
    meta = read_meta(steps[-1][1])
    if meta.get("layout") != "sharded" or meta.get("ranks") != 2:
        print(f"[{app}] FAIL: newest checkpoint is not a 2-rank "
              f"sharded snapshot: layout={meta.get('layout')!r} "
              f"ranks={meta.get('ranks')!r}")
        return 1
    if int(meta["rounds"]) != args.kill_at:
        print(f"[{app}] FAIL: newest durable snapshot is superstep "
              f"{meta['rounds']}, expected the kill round "
              f"{args.kill_at} (kill fires after commit)")
        return 1

    # 2b. gang-wide telemetry leg (PR 20): the same gang, raise-mode
    # kill — the halt travels the breach vote, so both ranks land
    # their trace sidecars and postmortem shards under one incident
    gang_fields = _gang_telemetry_leg(app, args, wd, common)
    if gang_fields is None:
        return 1

    # 3. reshard restore: single survivor process resumes the 4-shard
    # snapshot onto fnum 2
    out_res = os.path.join(wd, "out_res")
    t0 = time.monotonic()
    rc, log = run_cli(common + [
        "--fnum", "2", "--resume", "--checkpoint_dir", ck,
        "--out_prefix", out_res,
    ])
    wall = time.monotonic() - t0
    if rc != 0:
        print(f"[{app}] FAIL: reshard resume rc={rc}\n{log}")
        return 1
    if "resharded checkpoint" not in log:
        print(f"[{app}] FAIL: resume did not go through the reshard "
              f"path\n{log}")
        return 1

    # 4. verify byte-identity + emit the schema'd ft_drill record
    problems = compare_outputs(out_ref, out_res)
    rec = {
        "metric": "ft_drill_restore_wall",
        "value": round(wall, 3), "unit": "s", "vs_baseline": 1.0,
        "ft_drill": {
            "ranks": 2, "kill_round": args.kill_at, "kill_rank": 1,
            "old_fnum": 4, "new_fnum": 2,
            "checkpoint_rounds": int(meta["rounds"]),
            "restore_wall_s": round(wall, 3),
            "byte_identical": not problems,
            **gang_fields,
        },
    }
    print(json.dumps(rec))
    if problems:
        print(f"[{app}] FAIL: " + "; ".join(problems))
        return 2
    print(
        f"[{app}] PASS: rank 1 of 2 killed at superstep "
        f"{args.kill_at}; survivors' {meta['fnum']}-shard snapshot "
        f"resharded onto fnum 2 and resumed byte-identical to the "
        f"fault-free run ({wall:.1f}s restore wall)"
    )
    return 0


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--apps", default="",
                   help="comma-separated app list (default: "
                        "sssp,pagerank,cdlp — or sssp,pagerank,wcc "
                        "with --self-heal)")
    p.add_argument("--efile", default=os.path.join(REPO, "dataset", "p2p-31.e"))
    p.add_argument("--vfile", default=os.path.join(REPO, "dataset", "p2p-31.v"))
    p.add_argument("--kill_at", type=int, default=4,
                   help="superstep to kill the child at")
    p.add_argument("--checkpoint_every", type=int, default=2)
    p.add_argument("--cpu_devices", type=int, default=2)
    p.add_argument("--corrupt", action="store_true",
                   help="also corrupt the newest shard before resuming "
                        "(exercises the fallback to the previous "
                        "complete superstep)")
    p.add_argument("--self-heal", dest="self_heal", action="store_true",
                   help="guard/ drill: inject corrupt_carry@K with "
                        "GRAPE_GUARD=rollback and verify detection, "
                        "rollback-replay, and byte-identical results")
    p.add_argument("--corrupt_carry_at", type=int, default=4,
                   help="superstep for the corrupt_carry injection "
                        "(--self-heal / --postmortem)")
    p.add_argument("--postmortem", action="store_true",
                   help="flight-recorder drill: breach a 2-replica "
                        "fleet serve stream under --guard halt with a "
                        "GRAPE_POSTMORTEM sink and verify the dumped "
                        "bundle's serve_query rows byte-match the "
                        "Chrome trace")
    p.add_argument("--kill_rank", action="store_true",
                   help="distributed resilience drill: 2-process gang "
                        "at fnum 4 with sharded two-phase checkpoints, "
                        "rank 1 killed at --kill_at, survivors' "
                        "snapshot reshard-restored onto a "
                        "single-process fnum-2 mesh (default app: "
                        "sssp; exit 2 iff the resumed result diverges)")
    p.add_argument("--workdir", default="",
                   help="working directory (default: a fresh temp dir, "
                        "removed on success)")
    args = p.parse_args()

    if not args.apps:
        if args.kill_rank:
            args.apps = "sssp"
        elif args.self_heal:
            args.apps = "sssp,pagerank,wcc"
        else:
            args.apps = "sssp,pagerank,cdlp"
    workdir = args.workdir or tempfile.mkdtemp(prefix="grape-fault-drill-")
    rc = 0
    if args.postmortem:
        rc = 0 if postmortem_drill(args, workdir) else 1
    elif args.kill_rank:
        for app in filter(None, args.apps.split(",")):
            rc = max(rc, kill_rank_drill(app.strip(), args, workdir))
    else:
        run_one = self_heal_drill if args.self_heal else drill
        for app in filter(None, args.apps.split(",")):
            if not run_one(app.strip(), args, workdir):
                rc = 1
    if rc == 0 and not args.workdir:
        shutil.rmtree(workdir, ignore_errors=True)
    else:
        print(f"artifacts kept under {workdir}")
    print("fault_drill:", "PASS" if rc == 0 else "FAIL")
    return rc


if __name__ == "__main__":
    sys.exit(main())
