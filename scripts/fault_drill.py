#!/usr/bin/env python
"""Kill/resume fault drill — the end-to-end proof that checkpoint
recovery works, runnable as a CI smoke check.

Per app (default: sssp, pagerank, cdlp on dataset/p2p-31):

  1. **reference** — an uninterrupted checkpointed run writes its
     per-fragment result files.
  2. **kill** — the same run re-executes in a child process armed with
     `GRAPE_FT_FAULTS=kill@K`: the process is killed (os._exit) right
     after superstep K's checkpoint is durable.  The drill asserts the
     child died with the injected exit code and produced no output.
  3. **corrupt** (`--corrupt`) — the newest checkpoint shard is
     byte-flipped, so the resume must fall back to the previous
     complete superstep.
  4. **resume** — `--resume` continues from the last usable checkpoint
     and writes its result files.
  5. **verify** — the resumed output must be byte-identical to the
     reference output.

Exit code 0 iff every app passes.  Usage:

    python scripts/fault_drill.py                 # all three apps
    python scripts/fault_drill.py --apps sssp --corrupt
"""

from __future__ import annotations

import argparse
import filecmp
import os
import shutil
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

APP_FLAGS = {
    "sssp": ["--sssp_source", "6"],
    "pagerank": ["--pr_mr", "10"],
    "cdlp": ["--cdlp_mr", "10"],
}


def run_cli(extra, env_overrides=None, timeout=600):
    env = dict(os.environ)
    env.pop("GRAPE_FT_FAULTS", None)
    env.update(env_overrides or {})
    cmd = [sys.executable, "-m", "libgrape_lite_tpu.cli"] + extra
    proc = subprocess.run(
        cmd, cwd=REPO, env=env, timeout=timeout,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
    )
    return proc.returncode, proc.stdout.decode(errors="replace")


def compare_outputs(ref_dir: str, res_dir: str) -> list[str]:
    problems = []
    ref_files = sorted(os.listdir(ref_dir))
    res_files = sorted(os.listdir(res_dir))
    if ref_files != res_files:
        return [f"file sets differ: {ref_files} vs {res_files}"]
    for name in ref_files:
        if not filecmp.cmp(
            os.path.join(ref_dir, name), os.path.join(res_dir, name),
            shallow=False,
        ):
            problems.append(f"{name} differs byte-for-byte")
    if not ref_files:
        problems.append("reference run produced no output files")
    return problems


def drill(app: str, args, workdir: str) -> bool:
    from libgrape_lite_tpu.ft.checkpoint import list_checkpoints
    from libgrape_lite_tpu.ft.faults import (
        DEFAULT_KILL_EXIT_CODE, corrupt_file,
    )

    wd = os.path.join(workdir, app)
    os.makedirs(wd, exist_ok=True)
    base = [
        "--application", app,
        "--efile", args.efile, "--vfile", args.vfile,
        "--platform", "cpu", "--cpu_devices", str(args.cpu_devices),
        "--checkpoint_every", str(args.checkpoint_every),
    ] + APP_FLAGS.get(app, [])

    out_ref = os.path.join(wd, "out_ref")
    rc, log = run_cli(base + [
        "--checkpoint_dir", os.path.join(wd, "ck_ref"),
        "--out_prefix", out_ref,
    ])
    if rc != 0:
        print(f"[{app}] FAIL: reference run rc={rc}\n{log}")
        return False

    ck = os.path.join(wd, "ck")
    out_kill = os.path.join(wd, "out_kill")
    rc, log = run_cli(
        base + ["--checkpoint_dir", ck, "--out_prefix", out_kill],
        env_overrides={"GRAPE_FT_FAULTS": f"kill@{args.kill_at}"},
    )
    if rc != DEFAULT_KILL_EXIT_CODE:
        print(
            f"[{app}] FAIL: killed run rc={rc} "
            f"(expected {DEFAULT_KILL_EXIT_CODE})\n{log}"
        )
        return False
    if os.path.exists(out_kill) and os.listdir(out_kill):
        print(f"[{app}] FAIL: killed run wrote output")
        return False
    steps = list_checkpoints(ck)
    if not steps:
        print(f"[{app}] FAIL: killed run left no complete checkpoint")
        return False

    if args.corrupt:
        shard = os.path.join(steps[-1][1], "state.npz")
        corrupt_file(shard)
        print(f"[{app}] corrupted newest shard {shard}")

    out_res = os.path.join(wd, "out_res")
    rc, log = run_cli(base + [
        "--resume", "--checkpoint_dir", ck, "--out_prefix", out_res,
    ])
    if rc != 0:
        print(f"[{app}] FAIL: resume rc={rc}\n{log}")
        return False

    problems = compare_outputs(out_ref, out_res)
    if problems:
        print(f"[{app}] FAIL: " + "; ".join(problems))
        return False
    killed_at = steps[-1][0]
    print(
        f"[{app}] PASS: killed at superstep {args.kill_at} "
        f"(last checkpoint {killed_at}"
        f"{', corrupted' if args.corrupt else ''}), resumed run is "
        f"byte-identical to the uninterrupted one"
    )
    return True


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--apps", default="sssp,pagerank,cdlp",
                   help="comma-separated app list")
    p.add_argument("--efile", default=os.path.join(REPO, "dataset", "p2p-31.e"))
    p.add_argument("--vfile", default=os.path.join(REPO, "dataset", "p2p-31.v"))
    p.add_argument("--kill_at", type=int, default=4,
                   help="superstep to kill the child at")
    p.add_argument("--checkpoint_every", type=int, default=2)
    p.add_argument("--cpu_devices", type=int, default=2)
    p.add_argument("--corrupt", action="store_true",
                   help="also corrupt the newest shard before resuming "
                        "(exercises the fallback to the previous "
                        "complete superstep)")
    p.add_argument("--workdir", default="",
                   help="working directory (default: a fresh temp dir, "
                        "removed on success)")
    args = p.parse_args()

    workdir = args.workdir or tempfile.mkdtemp(prefix="grape-fault-drill-")
    ok = True
    for app in filter(None, args.apps.split(",")):
        ok = drill(app.strip(), args, workdir) and ok
    if ok and not args.workdir:
        shutil.rmtree(workdir, ignore_errors=True)
    else:
        print(f"artifacts kept under {workdir}")
    print("fault_drill:", "PASS" if ok else "FAIL")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
