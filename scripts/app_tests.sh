#!/bin/bash -e
# End-to-end CLI test harness — the analogue of the reference's
# misc/app_tests.sh: every app via the real CLI at several fragment
# counts, outputs verified against dataset/p2p-31-* goldens.
# (pytest tests/ covers the same matrix in-process; this script drives
# the user-facing surface.)

REPO="$( cd "$(dirname "$0")/.." >/dev/null 2>&1 ; pwd -P )"
cd "$REPO"
OUT=$(mktemp -d)
trap 'rm -rf "$OUT"' EXIT

PLATFORM_ARGS="--platform cpu --cpu_devices 8"
DS="$REPO/dataset"

run() {
  local np=$1; shift
  local app=$1; shift
  rm -rf "$OUT/res"
  python -m libgrape_lite_tpu.cli --application "$app" \
    --efile "$DS/p2p-31.e" --vfile "$DS/p2p-31.v" \
    --out_prefix "$OUT/res" $PLATFORM_ARGS --fnum "$np" "$@" >/dev/null
  cat "$OUT/res"/* | sort -k1n > "$OUT/merged.res"
}

verify() {  # verify <kind:exact|eps|wcc> <golden>
  python - "$1" "$DS/$2" "$OUT/merged.res" <<'EOF'
import sys
sys.path.insert(0, ".")
from tests.verifiers import (load_golden, load_result_lines,
                             exact_verify, eps_verify, wcc_verify)
kind, golden_path, res_path = sys.argv[1:4]
res = load_result_lines(open(res_path).read())
gold = load_golden(golden_path)
{"exact": exact_verify, "eps": eps_verify, "wcc": wcc_verify}[kind](res, gold)
print(f"  OK ({kind}, {len(res)} vertices)")
EOF
}

for np in 1 2 4 8; do
  echo "== fnum=$np =="
  echo "sssp";          run $np sssp --sssp_source=6;        verify exact p2p-31-SSSP
  echo "sssp_auto";     run $np sssp_auto --sssp_source=6;   verify exact p2p-31-SSSP
  echo "bfs";           run $np bfs --bfs_source=6;          verify exact p2p-31-BFS
  echo "pagerank";      run $np pagerank --pr_mr=10;         verify eps p2p-31-PR
  echo "cdlp";          run $np cdlp --cdlp_mr=10;           verify exact p2p-31-CDLP
  echo "wcc";           run $np wcc;                         verify wcc p2p-31-WCC
done

echo "== strategy variants (fnum=4) =="
echo "sssp_msg";  run 4 sssp_msg --sssp_source=6;  verify exact p2p-31-SSSP
echo "wcc_opt";   run 4 wcc_opt;                   verify wcc p2p-31-WCC
echo "pagerank_push"; run 4 pagerank_push --pr_mr=10; verify eps p2p-31-PR

echo "== extra apps smoke (fnum=2, no goldens ship) =="
for app in bc kcore core_decomposition kclique; do
  echo "$app"
  run 2 $app --bc_source=6 --kcore_k=4 --kclique_k=3
done
echo "lcc_directed"
run 2 lcc_directed --directed

echo "== directed (fnum=4) =="
echo "sssp --directed"; run 4 sssp --sssp_source=6 --directed; verify exact p2p-31-SSSP-directed
echo "bfs --directed";  run 4 bfs --bfs_source=6 --directed;   verify exact p2p-31-BFS-directed
echo "pagerank --directed"; run 4 pagerank --pr_mr=10 --directed; verify eps p2p-31-PR-directed

echo "== lcc (fnum=4) =="
run 4 lcc; verify eps p2p-31-LCC

echo "== lcc backend A/B: spgemm cmp-identical to intersect (fnum=4) =="
# GRAPE_LCC_BACKEND=spgemm routes the bitmap LCC's triangle credits
# through the tiled masked SpGEMM (ops/spgemm_pack.py); the credit
# algebra is integer-identical, so the merged result files must be
# bit-identical to the intersect run's (docs/SPGEMM.md)
( export GRAPE_LCC_BACKEND=intersect; run 4 lcc_opt )
cp "$OUT/merged.res" "$OUT/lcc_intersect.res"
( export GRAPE_LCC_BACKEND=spgemm; run 4 lcc_opt )
cmp "$OUT/lcc_intersect.res" "$OUT/merged.res" \
  || { echo "SPGEMM LCC DIVERGED FROM INTERSECT" >&2; exit 1; }
verify eps p2p-31-LCC
echo "  OK (byte-identical across backends)"

echo "== vertex-cut pagerank (fnum=4) =="
run 4 pagerank --vc --pr_mr=10; verify eps p2p-31-PR

echo "== mutation (fnum=4) =="
rm -rf "$OUT/res"
python -m libgrape_lite_tpu.cli --application sssp \
  --efile "$DS/p2p-31.e.mutable_base" --vfile "$DS/p2p-31.v" \
  --delta_efile "$DS/p2p-31.e.mutable_delta" --sssp_source=6 \
  --out_prefix "$OUT/res" $PLATFORM_ARGS --fnum 4 >/dev/null
cat "$OUT/res"/* | sort -k1n > "$OUT/merged.res"
verify exact p2p-31-SSSP

echo "== serialization roundtrip (fnum=2) =="
SER="$OUT/serial"
run 2 pagerank --pr_mr=10 --serialize --serialization_prefix "$SER"; verify eps p2p-31-PR
run 2 pagerank --pr_mr=10 --deserialize --serialization_prefix "$SER"; verify eps p2p-31-PR

echo "== load validation gate (fnum=2) =="
# subshell: `VAR=x fn` would leak past the bash function call
( export GRAPE_VALIDATE_LOAD=1; run 2 wcc ); verify wcc p2p-31-WCC

echo "== guarded run, goldens unchanged (fnum=2) =="
run 2 sssp --sssp_source=6 --guard=halt; verify exact p2p-31-SSSP

echo "== superstep pipelining: byte-identity vs serial (fnum=2) =="
# GRAPE_PIPELINE=1 (auto) through the real CLI with the byte threshold
# floored so the small p2p graph engages; the merged result files must
# be bit-identical to the serial run's (parallel/pipeline.py,
# docs/PIPELINE.md — min folds split exactly, and the exchange double
# buffer never aliases the live carry)
for app_spec in "sssp --sssp_source=6" "bfs --bfs_source=6"; do
  set -- $app_spec
  echo "$1 pipelined"
  run 2 "$@"
  cp "$OUT/merged.res" "$OUT/serial.res"
  ( export GRAPE_PIPELINE=1 GRAPE_PIPELINE_MIN_BYTES=1; run 2 "$@" )
  cmp "$OUT/serial.res" "$OUT/merged.res" \
    || { echo "PIPELINED RESULT DIVERGED FROM SERIAL ($1)" >&2; exit 1; }
  echo "  OK (byte-identical to serial)"
done

echo "== 2-D vertex-cut partition: cmp-identical to 1-D (sssp, fnum=4) =="
# GRAPE_PARTITION=2d routes sssp through the k x k vertex-cut mesh
# (fragment/partition.py + models/vc2d.py); min folds regroup exactly
# across tiles, so the merged result files must be bit-identical to
# the serial 1-D run's (docs/PARTITION2D.md)
run 4 sssp --sssp_source=6
cp "$OUT/merged.res" "$OUT/serial_1d.res"
( export GRAPE_PARTITION=2d; run 4 sssp --sssp_source=6 )
cmp "$OUT/serial_1d.res" "$OUT/merged.res" \
  || { echo "2-D VERTEX-CUT RESULT DIVERGED FROM 1-D" >&2; exit 1; }
echo "  OK (byte-identical to the 1-D edge-cut)"
# declined geometry (fnum=2 is not a square) must fall back to 1-D
# with the reason recorded, never error out
( export GRAPE_PARTITION=2d; run 2 sssp --sssp_source=6 ); verify exact p2p-31-SSSP

echo "== guard self-heal drill (corrupt_carry + rollback-replay) =="
python scripts/fault_drill.py --self-heal --apps sssp,pagerank,wcc

echo "== flight-recorder drill (fleet breach -> bundle byte-matches trace) =="
# obs/recorder.py end-to-end: guard breaches under a 2-replica fleet
# dump postmortem bundles; the newest bundle's serve_query span rows
# must byte-match the Chrome trace's rows for the same query ids
python scripts/fault_drill.py --postmortem

echo "== distributed resilience (2-proc gang: sharded 2PC + kill_rank + reshard) =="
# docs/FAULT_TOLERANCE.md "Distributed resilience", through the real
# CLI: (1) the 2-process jax.distributed dryrun, now growing a
# checkpointed query lane that commits per-rank shard files under the
# two-phase barrier; (2) the kill_rank drill — rank 1 of 2 dies at
# superstep 4, and the survivors' fnum-4 sharded snapshot is
# reshard-restored onto a single-process fnum-2 mesh, byte-identical
# to a fault-free run (the drill exits 2 on divergence); the emitted
# ft_drill record must pass the bench schema gate
timeout 600 python scripts/multihost_dryrun.py > "$OUT/dryrun.txt" \
  || { cat "$OUT/dryrun.txt"; exit 1; }
grep -q "sharded ckpt" "$OUT/dryrun.txt" \
  || { echo "DRYRUN CHECKPOINT LANE MISSING" >&2; cat "$OUT/dryrun.txt"; exit 1; }
python scripts/fault_drill.py --kill_rank --workdir "$OUT/killrank" \
  > "$OUT/killrank.txt" \
  || { DRILL_RC=$?; cat "$OUT/killrank.txt";
       echo "KILL_RANK DRILL FAILED (rc=$DRILL_RC)" >&2; exit $DRILL_RC; }
cat "$OUT/killrank.txt"
grep '"ft_drill"' "$OUT/killrank.txt" | tail -1 > "$OUT/ft_drill.json"
python scripts/check_bench_schema.py "$OUT/ft_drill.json"
rm -rf "$OUT/killrank"
echo "  OK (dryrun ckpt lane, kill_rank reshard byte-identical, schema'd record)"

echo "== obs trace + per-superstep report (stepwise SSSP, fnum=2) =="
run 2 sssp --sssp_source=6 --profile \
  --trace "$OUT/trace.json" --metrics "$OUT/metrics"
verify exact p2p-31-SSSP
python scripts/trace_report.py "$OUT/trace.json" >/dev/null
test -s "$OUT/trace.jsonl" && test -s "$OUT/metrics.prom"
echo "  OK (trace + jsonl + metrics written, report rendered)"

echo "== serve: scripted 32-query stream through the CLI (fnum=2) =="
# mixed stream: 24 sssp + 8 bfs queries coalesce per-app under
# max_batch=8 — exercises admission, coalescing, and the vmapped
# batched dispatch through the real user-facing surface
python - > "$OUT/serve_stream.txt" <<'EOF'
for i in range(24):
    print("sssp", 6 + i)
for i in range(8):
    print("bfs", 6 + i)
EOF
python -m libgrape_lite_tpu.cli serve \
  --efile "$DS/p2p-31.e" --vfile "$DS/p2p-31.v" $PLATFORM_ARGS --fnum 2 \
  --stream "$OUT/serve_stream.txt" --max_batch 8 > "$OUT/serve.json"
python - "$OUT/serve.json" <<'EOF'
import json, sys
rec = json.loads(
    [l for l in open(sys.argv[1]) if l.startswith("{")][-1])
assert rec["queries"] == 32 and rec["failed"] == 0, rec
assert rec["apps"] == {"sssp": 24, "bfs": 8}, rec["apps"]
assert sum(rec["batch_hist"].values()) >= 4, rec["batch_hist"]
print(f"  OK (32 queries, {rec['qps']} q/s, hist {rec['batch_hist']})")
EOF

echo "== telemetry: live OpenMetrics scrape mid-serve + stages + SLO (fnum=2) =="
# the obs/ plane through the real CLI: --metrics_port 0 binds an
# ephemeral exporter (URL on stderr); the scrape runs WHILE the stream
# is live and must name every federated namespace in OpenMetrics text
# (docs/OBSERVABILITY.md); the summary must carry the per-stage
# p50/p99 decomposition and the SLO error-budget block
python -m libgrape_lite_tpu.cli serve \
  --efile "$DS/p2p-31.e" --vfile "$DS/p2p-31.v" $PLATFORM_ARGS --fnum 2 \
  --stream "$OUT/serve_stream.txt" --max_batch 8 --inflight 2 \
  --metrics_port 0 --slo 'sssp=5000,*=5000' \
  > "$OUT/tele_serve.json" 2> "$OUT/tele_serve.err" &
TELE_PID=$!
URL=""
for _ in $(seq 1 200); do
  URL=$(sed -n 's/.*metrics exporter: \(http[^ ]*\).*/\1/p' "$OUT/tele_serve.err" | head -1)
  [ -n "$URL" ] && break
  sleep 0.05
done
[ -n "$URL" ] || { echo "EXPORTER URL NEVER PRINTED" >&2; kill "$TELE_PID"; exit 1; }
python - "$URL" "$TELE_PID" <<'EOF'
import json, os, sys, time, urllib.request
url, pid = sys.argv[1], int(sys.argv[2])
# poll until the SLO ledger shows deliveries, so the scrape is a
# genuine mid-serve one (the all-8-namespace scrape is bench.py's
# telemetry lane; HERE the contract is consistency: everything the
# process has federated so far must be named in the OpenMetrics text)
fed, observed = {}, 0
for _ in range(600):
    try:
        fed = json.load(
            urllib.request.urlopen(url + "/federation", timeout=10))
    except OSError:
        break
    observed = (fed.get("slo") or {}).get("observed", 0)
    if observed >= 1 or not os.path.exists(f"/proc/{pid}"):
        break
    time.sleep(0.05)
assert observed >= 1, \
    f"serve ended before a delivery was ever scraped: {sorted(fed)}"
assert {"pump", "recorder", "slo"} <= set(fed), sorted(fed)
text = urllib.request.urlopen(url + "/metrics", timeout=10).read().decode()
live = os.path.exists(f"/proc/{pid}")
missing = [ns for ns in fed
           if f'grape_stats_registry{{namespace="{ns}"}}' not in text]
assert not missing, f"scrape missing namespaces: {missing}"
assert "grape_stats_slo_observed" in text, text[-400:]
assert text.endswith("# EOF\n"), "scrape is not OpenMetrics-terminated"
print(f"  OK ({'mid' if live else 'post'}-serve scrape at "
      f"{observed} deliveries named all {len(fed)} live namespace(s): "
      f"{sorted(fed)})")
EOF
wait "$TELE_PID"
python - "$OUT/tele_serve.json" <<'EOF'
import json, sys
rec = json.loads(
    [l for l in open(sys.argv[1]) if l.startswith("{")][-1])
assert rec["queries"] == 32 and rec["failed"] == 0, rec
st = rec["stages"]
assert {"queue_wait_us", "dispatch_us", "device_us",
        "harvest_us"} <= set(st), st
assert all(set(v) == {"p50", "p99"} for v in st.values()), st
slo = rec["slo"]
assert slo["observed"] == 32 and slo["breaches"] == 0, slo
print(f"  OK (stages {sorted(st)}; slo {slo['observed']} observed, "
      f"{slo['breaches']} breach(es))")
EOF

echo "== dyn: ingest a delta stream while a mixed query stream runs (fnum=2) =="
# streaming smoke (dyn/): 10 additive delta ops ingested in chunks
# between query batches — they ride the overlay side-path (no repack
# below the threshold) while 16 sssp + 8 bfs queries stay live
python - > "$OUT/dyn_delta.txt" <<'EOF'
for i in range(10):
    print("a 6", 200 + 17 * i, "0.5")
EOF
python - > "$OUT/dyn_stream.txt" <<'EOF'
for i in range(16):
    print("sssp", 6 + i)
for i in range(8):
    print("bfs", 6 + i)
EOF
python -m libgrape_lite_tpu.cli serve \
  --efile "$DS/p2p-31.e" --vfile "$DS/p2p-31.v" $PLATFORM_ARGS --fnum 2 \
  --stream "$OUT/dyn_stream.txt" --max_batch 8 \
  --delta_stream "$OUT/dyn_delta.txt" --ingest_every 8 \
  --dyn_repack_ratio 0.5 > "$OUT/dyn_serve.json"
python - "$OUT/dyn_serve.json" <<'EOF'
import json, sys
rec = json.loads(
    [l for l in open(sys.argv[1]) if l.startswith("{")][-1])
assert rec["queries"] == 24 and rec["failed"] == 0, rec
d = rec["dyn"]
assert d["ingested"] == 10 and d["repack_count"] == 0, d
assert d["overlay_applies"] >= 1 and d["updates_per_s"] > 0, d
assert d["queries_ok"] == 24, d
print(f"  OK (24 queries live, {d['ingested']} ops ingested at "
      f"{d['updates_per_s']} upd/s, {d['overlay_applies']} overlay "
      "applies, 0 repacks)")
EOF

echo "== async serve pump: --inflight 4 cmp-identical to --inflight 1 (fnum=2) =="
# the dispatch-window smoke (serve/pipeline.py): the SAME mixed query
# stream + 10-op delta stream through the CLI at window depth 1 and 4
# — per-query value digests (--dump_results, submit order) must be
# byte-identical, the ingest stays overlay-only (zero repacks), and
# the W=4 run must actually engage the window (pump block present,
# batches overlapped).  max_batch 4 with ingest_every 16 keeps TWO
# batches per ingest group, so the window genuinely overlaps.
for w in 1 4; do
  python -m libgrape_lite_tpu.cli serve \
    --efile "$DS/p2p-31.e" --vfile "$DS/p2p-31.v" $PLATFORM_ARGS --fnum 2 \
    --stream "$OUT/dyn_stream.txt" --max_batch 4 \
    --delta_stream "$OUT/dyn_delta.txt" --ingest_every 16 \
    --dyn_repack_ratio 0.5 --inflight $w \
    --dump_results "$OUT/async_w$w.res" > "$OUT/async_w$w.json"
done
cmp "$OUT/async_w1.res" "$OUT/async_w4.res" \
  || { echo "ASYNC PUMP (W=4) DIVERGED FROM THE SYNC LOOP (W=1)" >&2; exit 1; }
python - "$OUT/async_w4.json" <<'EOF'
import json, sys
rec = json.loads(
    [l for l in open(sys.argv[1]) if l.startswith("{")][-1])
assert rec["queries"] == 24 and rec["failed"] == 0, rec
assert rec["dyn"]["ingested"] == 10 and rec["dyn"]["repack_count"] == 0, rec["dyn"]
p = rec["pump"]
assert p["window"] == 4 and p["engaged"] >= 1, p
assert p["max_inflight"] >= 2, p  # the window genuinely held >1 batch
print(f"  OK (cmp-identical across windows; engaged={p['engaged']}, "
      f"max_inflight={p['max_inflight']}, "
      f"overlapped={p['overlapped_harvests']})")
EOF

echo "== fleet: 2 tenants x 2 replicas + drain, cmp-identical to single-replica (fnum=2) =="
# the serving-fleet smoke (fleet/, docs/FLEET.md): the SAME mixed
# stream + 10-op delta stream through the CLI, once plain and once as
# a 2-replica router with a by_app tenant split and replica 0 drained
# mid-stream (it rejoins through its catch-up log after the next
# ingest barrier) — per-query value digests must be byte-identical
# (zero-downtime drain, version-fenced ingest), zero queries dropped,
# and both replicas must have genuinely served traffic
python -m libgrape_lite_tpu.cli serve \
  --efile "$DS/p2p-31.e" --vfile "$DS/p2p-31.v" $PLATFORM_ARGS --fnum 2 \
  --stream "$OUT/dyn_stream.txt" --max_batch 4 \
  --delta_stream "$OUT/dyn_delta.txt" --ingest_every 8 \
  --dyn_repack_ratio 0.5 \
  --dump_results "$OUT/fleet_r1.res" > "$OUT/fleet_r1.json"
python -m libgrape_lite_tpu.cli serve \
  --efile "$DS/p2p-31.e" --vfile "$DS/p2p-31.v" $PLATFORM_ARGS --fnum 2 \
  --stream "$OUT/dyn_stream.txt" --max_batch 4 \
  --delta_stream "$OUT/dyn_delta.txt" --ingest_every 8 \
  --dyn_repack_ratio 0.5 --replicas 2 --tenants by_app --drain_at 12 \
  --dump_results "$OUT/fleet_r2.res" > "$OUT/fleet_r2.json"
cmp "$OUT/fleet_r1.res" "$OUT/fleet_r2.res" \
  || { echo "FLEET (R=2, drained) DIVERGED FROM THE SINGLE-REPLICA RUN" >&2; exit 1; }
python - "$OUT/fleet_r2.json" <<'EOF'
import json, sys
rec = json.loads(
    [l for l in open(sys.argv[1]) if l.startswith("{")][-1])
assert rec["queries"] == 24 and rec["failed"] == 0, rec
fl = rec["fleet"]
assert fl["replicas"] == 2 and fl["tenants"] == 2, fl
assert fl["dropped"] == 0 and fl["drains"] == 1, fl
reps = fl["router"]["replicas"]
assert all(r["served"] > 0 for r in reps.values()), reps
assert len({r["version"] for r in reps.values()}) == 1, reps
print(f"  OK (cmp-identical; fence={fl['router']['fence']}, "
      + ", ".join(f"{k} served {v['served']}" for k, v in reps.items())
      + ")")
EOF

echo "== autopilot: closed-loop serve with a repeated-source stream (fnum=2) =="
# the control-plane smoke (autopilot/, docs/AUTOPILOT.md): a
# repeated-source stream (4 sources x 6 cycles) through
# `serve --autopilot` — repeats of an already-answered (app, source)
# pair must come out of the fence-epoch result cache instead of the
# device (cache_hits asserted), every query must still succeed, and
# the summary must carry the autopilot block (ticks, scale counters,
# cache snapshot)
python - > "$OUT/ap_stream.txt" <<'EOF'
for cycle in range(6):
    for s in (6, 7, 8, 9):
        print("sssp", s)
EOF
python -m libgrape_lite_tpu.cli serve \
  --efile "$DS/p2p-31.e" --vfile "$DS/p2p-31.v" $PLATFORM_ARGS --fnum 2 \
  --stream "$OUT/ap_stream.txt" --max_batch 4 \
  --autopilot --min_replicas 1 --max_replicas 2 \
  > "$OUT/ap_serve.json"
python - "$OUT/ap_serve.json" <<'EOF'
import json, sys
rec = json.loads(
    [l for l in open(sys.argv[1]) if l.startswith("{")][-1])
assert rec["queries"] == 24 and rec["failed"] == 0, rec
ap = rec["autopilot"]
assert ap["ticks"] >= 24, ap
assert ap["cache_hits"] >= 8, ap  # repeats answered off-device
assert ap["cache"]["entries"] >= 4, ap["cache"]
assert ap["replicas_final"] >= ap["min_replicas"], ap
assert rec["fleet"]["dropped"] == 0, rec["fleet"]
print(f"  OK (24 queries, {ap['cache_hits']} cache hit(s) of "
      f"{ap['cache_hits'] + ap['cache_misses']} probes, "
      f"{ap['ticks']} control ticks, "
      f"{ap['replicas_final']} replica(s))")
EOF

echo "== grape-lint: static contract rules, zero unsuppressed findings =="
# the AST gate (R1-R9, analysis/): exits 1 on any finding the
# baseline does not name, 3 if the --json record drifts from its own
# declared schema — both fail this harness (set -e)
python scripts/grape_lint.py --json > "$OUT/lint.json"
python - "$OUT/lint.json" <<'EOF'
import json, sys
rec = json.load(open(sys.argv[1]))
assert rec["ok"], rec["findings"]
live = [f for f in rec["findings"] if not f["suppressed"]]
assert live == [], live
print(f"  OK (clean; {rec['suppressed']} named suppression(s))")
EOF

echo "== BENCH record schema (fresh small-scale bench incl. serve block + archived r05) =="
GRAPE_BENCH_SCALE=10 GRAPE_BENCH_NO_PROBE=1 GRAPE_BENCH_NO_LEDGER=1 \
  GRAPE_BENCH_NO_GUARD=1 python bench.py > "$OUT/bench.json" 2>/dev/null
python scripts/check_bench_schema.py "$OUT/bench.json" BENCH_r05.json
python - "$OUT/bench.json" <<'EOF'
import json, sys
rec = json.loads(
    [l for l in open(sys.argv[1]) if l.startswith("{")][-1])
sv = rec["serve"]
for app in ("sssp", "bfs"):
    qps = {k: v["qps"] for k, v in sv[app].items()}
    assert all(v["ok"] == v["n"] for v in sv[app].values()), sv[app]
    print(f"  serve {app}: qps {qps}")
tel = rec["telemetry"]
assert tel["federation_ok"] and tel["scrape_ok"], tel
assert tel["namespaces"] >= 6, tel
assert {"queue_wait_us", "dispatch_us", "device_us",
        "harvest_us"} <= set(tel["stages"]), tel
print(f"  telemetry: {tel['namespaces']} namespaces federated, "
      f"live scrape ok, {len(tel['stages'])} stages")
EOF

echo "== bench_compare: declaration-driven regression gate =="
# satellite of the schema gate (scripts/bench_compare.py): identical
# records gate zero regressions, the archived full-scale r05 record
# SKIPS (config guards) instead of false-failing against a scale-10
# run, and a seeded 2x regression must exit 2
python scripts/bench_compare.py "$OUT/bench.json" "$OUT/bench.json" > /dev/null
python scripts/bench_compare.py "$OUT/bench.json" BENCH_r05.json > /dev/null
python - "$OUT/bench.json" > "$OUT/bench_regressed.json" <<'EOF'
import json, sys
rec = json.loads(
    [l for l in open(sys.argv[1]) if l.startswith("{")][-1])
rec["value"] *= 0.5                            # halve the headline MTEPS
rec["telemetry"]["stages"]["device_us"]["p99"] *= 10.0
json.dump(rec, sys.stdout)
EOF
set +e
python scripts/bench_compare.py "$OUT/bench.json" "$OUT/bench_regressed.json" \
  > "$OUT/bench_cmp.txt" 2>&1
BC_RC=$?
set -e
test "$BC_RC" -eq 2 \
  || { echo "SEEDED REGRESSION NOT GATED (rc=$BC_RC)" >&2; cat "$OUT/bench_cmp.txt"; exit 1; }
grep -q "REGRESSION" "$OUT/bench_cmp.txt"
grep -q "telemetry.stages.device_us.p99" "$OUT/bench_cmp.txt"
echo "  OK (self-compare clean, archived r05 skipped-not-failed, seeded 2x regression exits 2)"

echo "== calibration: CPU rate fit + drift gate (ops/calibration.py) =="
# the r17 self-calibrating cost-ledger loop end to end on the CPU
# backend: fit a profile from a measured sweep (persisting sweep +
# profile), re-gate the RECORDED samples under the fitted profile
# (deterministic — no scheduler re-race), then prove a deliberately
# corrupted profile trips the 5% drift gate with exit 2, standalone
# AND through the bench calibration lane
timeout 900 python scripts/calibrate.py --scales 11,12 --repeats 3 \
  --out "$OUT/rates.json" --samples-out "$OUT/rate_samples.json" \
  > "$OUT/calibrate.txt"
timeout 300 python scripts/calibrate.py --check \
  --samples "$OUT/rate_samples.json" --profile "$OUT/rates.json" > /dev/null
python - "$OUT/rates.json" "$OUT/rates_bad.json" <<'EOF'
import json, sys
d = json.load(open(sys.argv[1]))
d["vpu_lanes_per_cycle"] *= 20            # a deliberately wrong rate
json.dump(d, open(sys.argv[2], "w"))
EOF
set +e
timeout 300 python scripts/calibrate.py --check \
  --samples "$OUT/rate_samples.json" --profile "$OUT/rates_bad.json" \
  > "$OUT/calibrate_bad.txt" 2>&1
CAL_RC=$?
set -e
test "$CAL_RC" -eq 2 \
  || { echo "CORRUPTED PROFILE NOT GATED (rc=$CAL_RC)" >&2; cat "$OUT/calibrate_bad.txt"; exit 1; }
# the bench lane under the same profile/samples: fitted passes, the
# corrupted profile exits 2 (every other lane skipped — this tests
# the gate, not the measurements)
BENCH_CAL="GRAPE_BENCH_SCALE=10 GRAPE_BENCH_NO_PROBE=1 \
  GRAPE_BENCH_NO_LEDGER=1 GRAPE_BENCH_NO_GUARD=1 GRAPE_BENCH_NO_SERVE=1 \
  GRAPE_BENCH_NO_SERVE_ASYNC=1 GRAPE_BENCH_NO_DYN=1 \
  GRAPE_BENCH_NO_PIPELINE=1 GRAPE_BENCH_NO_P2D=1 GRAPE_BENCH_NO_SPGEMM=1 \
  GRAPE_BENCH_NO_FLEET=1 GRAPE_BENCH_NO_AUTOPILOT=1 \
  GRAPE_BENCH_NO_TELEMETRY=1 GRAPE_CALIBRATION_SAMPLES=$OUT/rate_samples.json"
env $BENCH_CAL GRAPE_RATE_PROFILE="$OUT/rates.json" \
  python bench.py > "$OUT/bench_calibrated.json" 2>/dev/null
set +e
env $BENCH_CAL GRAPE_RATE_PROFILE="$OUT/rates_bad.json" \
  python bench.py > /dev/null 2> "$OUT/bench_calibrated_bad.err"
BCAL_RC=$?
set -e
test "$BCAL_RC" -eq 2 \
  || { echo "BENCH DRIFT GATE NOT TRIPPED (rc=$BCAL_RC)" >&2; cat "$OUT/bench_calibrated_bad.err"; exit 1; }
echo "  OK (fit within gate, corrupted profile exits 2 standalone + via bench)"

echo "ALL APP TESTS PASSED"
