#!/usr/bin/env python
"""Streaming GNN sampler driver — parity with
`examples/gnn_sampler/run_sampler.cc` + `misc/sampler_test.sh`.

Static mode (the sampler_test.sh shape):

  python scripts/run_sampler.py --efile dataset/p2p-31.e \
      --vfile dataset/p2p-31.v --sampling_strategy random \
      --hop_and_num 4-5 --out_prefix /tmp/output_sampling

samples every vertex once and writes `result_frag_0` lines
`vid: n1 n2 ...` (hops flattened, like the reference's Output).

Streaming mode (the reference's kafka loop, run_sampler.cc:93-135):

  python scripts/run_sampler.py ... --input_stream updates.txt \
      --output_stream samples.txt

consumes the interleaved line protocol (`e src dst [w]` graph updates,
`q vid` sample queries), extends the append-only fragment
(`sampler/append_only_fragment.py`, the ExtendFragment analogue), and
emits sampled neighborhoods to the sink as they are produced.  With
--enable_kafka (and confluent_kafka importable) the same loop binds to
Kafka topics instead of files.
"""

from __future__ import annotations

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--efile", required=True)
    p.add_argument("--vfile", default="")
    p.add_argument("--out_prefix", default="")
    p.add_argument("--sampling_strategy", default="random",
                   choices=("random", "edge_weight", "top_k"))
    p.add_argument("--hop_and_num", default="4-5",
                   help="'-'-separated per-hop fanouts (reference "
                        "flags.h:27, e.g. 4-5)")
    p.add_argument("--weighted", action="store_true",
                   help="efile has a weight column")
    p.add_argument("--directed", action="store_true",
                   help="stream updates are directed edges (pass this "
                        "when the stream already carries both "
                        "orientations — there is no dedup downstream)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--batch", type=int, default=512,
                   help="streaming query batch size (reference "
                        "batch_size flag)")
    # streaming transports
    p.add_argument("--input_stream", default="",
                   help="update/query line file (`e src dst [w]` / "
                        "`q vid`)")
    p.add_argument("--output_stream", default="",
                   help="sample sink file (default: stdout)")
    p.add_argument("--enable_kafka", action="store_true")
    p.add_argument("--broker_list", default="localhost:9092")
    p.add_argument("--input_topic", default="")
    p.add_argument("--output_topic", default="")
    p.add_argument("--platform", default="",
                   help="pin a jax platform (e.g. cpu) before init")
    return p.parse_args(argv)


def main(argv=None) -> int:
    args = parse_args(argv)
    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)

    import numpy as np

    from libgrape_lite_tpu.io.line_parser import (
        read_edge_file, read_vertex_file,
    )
    from libgrape_lite_tpu.sampler.append_only_fragment import (
        AppendOnlyEdgecutFragment,
    )
    from libgrape_lite_tpu.sampler.sampler import GraphSampler
    from libgrape_lite_tpu.sampler.stream import (
        AsyncSink, FileSink, FileSource, kafka_available, run_pipeline,
    )
    from libgrape_lite_tpu.utils.timer import phase

    fanouts = tuple(int(x) for x in args.hop_and_num.split("-") if x)
    if not fanouts:
        raise SystemExit("--hop_and_num must name at least one fanout")

    with phase("load graph"):
        src, dst, w = read_edge_file(args.efile, weighted=args.weighted)
        if args.vfile:
            oids = read_vertex_file(args.vfile)
            n = int(np.max(oids)) + 1 if len(oids) else 0
        else:
            oids = np.unique(np.concatenate([src, dst]))
            n = int(oids.max()) + 1 if len(oids) else 0
        # undirected like the reference loader (graph_spec directed=false)
        frag = AppendOnlyEdgecutFragment(
            n, np.concatenate([src, dst]), np.concatenate([dst, src]),
            None if w is None else np.concatenate([w, w]),
        )
    sampler = GraphSampler(frag, args.sampling_strategy)

    if args.input_stream or args.enable_kafka:
        if args.enable_kafka:
            if not kafka_available():
                raise SystemExit(
                    "--enable_kafka needs confluent_kafka, which is not "
                    "in this image; use --input_stream/--output_stream"
                )
            from libgrape_lite_tpu.sampler.stream import (
                KafkaSink, KafkaSource,
            )

            source = KafkaSource(args.broker_list, args.input_topic)
            sink = KafkaSink(args.broker_list, args.output_topic)
        else:
            source = FileSource(args.input_stream)
            # async writer thread, like the reference's output job
            sink = AsyncSink(
                FileSink(args.output_stream) if args.output_stream
                else _StdoutSink()
            )
        with phase("stream pipeline"):
            emitted = run_pipeline(
                frag, sampler, source, sink, fanouts=fanouts,
                batch=args.batch, seed=args.seed,
                directed=args.directed,
            )
        sink.close()
        print(f"[run_sampler] emitted {emitted} samples; "
              f"graph now {frag.num_edges} edges over {frag.n} vertices",
              file=sys.stderr)
        return 0

    # static mode (sampler_test.sh): sample every vertex once — the
    # same pipeline, fed a synthetic all-vertices query stream, so both
    # modes share one emit/format/batching path
    os.makedirs(args.out_prefix or ".", exist_ok=True)
    out_path = os.path.join(args.out_prefix or ".", "result_frag_0")
    sink = FileSink(out_path)
    with phase("sample"):
        emitted = run_pipeline(
            frag, sampler,
            (f"q {o}" for o in oids.tolist()),
            sink, fanouts=fanouts, batch=args.batch, seed=args.seed,
        )
    sink.close()
    print(f"[run_sampler] wrote {emitted} lines to {out_path}",
          file=sys.stderr)
    return 0


class _StdoutSink:
    def emit(self, line: str) -> None:
        print(line)

    def close(self) -> None:
        pass


if __name__ == "__main__":
    sys.exit(main())
