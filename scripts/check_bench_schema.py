#!/usr/bin/env python
"""Validate BENCH json records against the declared schema.

The bench record is a cross-session contract: the driver reads the
LAST json line, PERF_NOTES tables are built from the fields, and the
r7 ledger gate compares split engine columns — a silently renamed or
mistyped field corrupts every downstream comparison without failing
anything.  This script makes the record shape a pinned artifact:

* `SCHEMA` declares every block bench.py may emit (top-level metric,
  `sssp`, `guard`, `pack_ledger` with the r7 vpu/mxu split fields,
  the r8 `obs` rollup block);
* `validate_record(record)` returns a list of human-readable errors
  (empty = valid) — bench.py self-checks each record with it BEFORE
  printing, and scripts/app_tests.sh validates a fresh small-scale
  bench line end-to-end;
* unknown top-level / block keys are errors: a new field must be
  declared here (one line) or it is a typo.

CLI: `python scripts/check_bench_schema.py FILE...` where FILE is a
json record, a BENCH_r*.json driver wrapper (validated via its
`parsed` field), or `-` for the last json line on stdin.
"""

from __future__ import annotations

import json
import sys

_NUM = (int, float)

# field -> (type tuple, required).  Top-level SCALAR fields only —
# every nested block is declared once in _BLOCKS below and wired into
# _TOP / SCHEMA / validate_record / the CLI listing BY CONSTRUCTION
# (the PR 9/11/12 wiring-gap class: a block declared here but
# forgotten in one of the four consumers silently validated nothing).
_TOP_SCALARS = {
    "metric": (str, True),
    "value": (_NUM, True),
    "unit": (str, True),
    "vs_baseline": (_NUM, True),
    "load_avg_1m": (_NUM, False),
}

_SSSP = {
    "metric": (str, True),
    "value": (_NUM, True),
    "unit": (str, True),
    "variant": (str, True),
    "vs_baseline": (_NUM, True),
    "fused_pull": (bool, False),
}

_GUARD = {
    "fused_off_s": (_NUM, True),
    "guarded_s": (_NUM, True),
    "guarded_overhead_pct": (_NUM, True),
    "policy": (str, True),
    "cadence": (int, True),
    "probes": (int, True),
}

# the r7 split-engine columns are REQUIRED whenever the block appears:
# a ledger without the vpu/mxu split is the pre-split format the cost
# model can no longer recount
_PACK_LEDGER = {
    "vpu_ops_per_edge": (_NUM, True),
    "mxu_elems_per_edge": (_NUM, True),
    "gather_slots_per_edge": (_NUM, True),
    "bytes_per_edge": (_NUM, True),
    "per_stage_ops_per_edge": (dict, True),
    "scan_mode": (str, True),
    "modeled": (dict, True),
    "ledger_recount_mismatch": (_NUM, True),
}

_OBS = {
    "trace_id": ((str, type(None)), False),
    "spans": (dict, True),
}

# the r9 serving-throughput lane: per app, per batch size (keys b<k>),
# qps at fixed p99 over the scripted stream; batch_hist is the
# admission queue's batch-size histogram (digit-string keys)
_SERVE = {
    "scale": (int, True),
    "queries_per_app": (int, True),
    "sssp": (dict, False),
    "bfs": (dict, False),
    "batch_hist": (dict, True),
}

_SERVE_POINT = {
    "qps": (_NUM, True),
    "p50_ms": (_NUM, True),
    "p99_ms": (_NUM, True),
    "n": (int, True),
    "ok": (int, True),
}

# the r12 async-pump lane (serve/pipeline.py, docs/SERVING.md): the
# dispatch-window A/B — W in {1, 4} at batch sizes {1, 8, 32} over the
# serve-scale twin WITH a concurrent delta-ingest stream.  `window_ab`
# holds w<k> -> b<k> -> point maps; each point is a _SERVE_POINT plus
# the sustained updates/s of its run.  `identical` is the per-query
# byte-identity verdict W=4 vs W=1 (bench exits 2 when it breaks),
# `overlay_recompiles` counts XLA compiles during the measured
# overlay-only ingests (must be 0 — compile_events), and qps_win_b8
# is the headline: measured W=4 / W=1 qps at b=8.  Verdict fields are
# DECLARED bool, like the pipeline lane's.
_SERVE_ASYNC = {
    "scale": (int, True),
    "app": (str, True),
    "queries": (int, True),
    "window_ab": (dict, True),
    "identical": (bool, True),
    "qps_win_b8": (_NUM, True),
    "updates_per_chunk": (int, True),
    "overlay_recompiles": (int, True),
    "admission_wait_ms": (dict, True),
    "declines": (dict, False),
}

_SERVE_ASYNC_POINT = dict(_SERVE_POINT)
_SERVE_ASYNC_POINT["updates_per_s"] = (_NUM, True)

# the r10 dynamic-graph lane (dyn/, docs/DYNAMIC_GRAPHS.md): updates
# ingested per second while a query stream stays live, repack vs
# overlay counts, and the incremental-vs-cold round/wall comparison
_DYN = {
    "updates_per_s": (_NUM, True),
    "ingested": (int, True),
    "repack_count": (int, True),
    "overlay_applies": (int, True),
    "queries": (int, True),
    "queries_ok": (int, True),
    "inc_cold_rounds": (int, False),
    "inc_seeded_rounds": (int, False),
    "inc_speedup": (_NUM, False),
}

# the r9 superstep-pipelining lane (parallel/pipeline.py,
# docs/PIPELINE.md): serial vs pipelined wall at fnum>=2 with the
# byte-identity verdict, the modeled hidden-exchange fraction from the
# overlap term (t = max(compute_interior, exchange) + compute_boundary)
# and the boundary-set sizes, plus the cost model's recount drift
# (>5% fails the bench like the pack-ledger gate).  `byte_identical`
# and `engaged` are DECLARED bool — everywhere else bool-in-numeric
# stays rejected.
_PIPELINE = {
    "scale": (int, True),
    "fnum": (int, True),
    "app": (str, True),
    "engaged": (bool, True),
    "mode": (str, True),
    # the truth meter's join key (grape-lint R12): every modeled
    # claim in this block is auditable only through this uid
    "plan_uid": (str, True),
    "serial_s": (_NUM, True),
    "pipelined_s": (_NUM, True),
    "byte_identical": (bool, True),
    "modeled_hidden_frac": (_NUM, True),
    "exchange_bytes": (int, True),
    "boundary_vertices": (int, True),
    "interior_vertices": (int, True),
    "boundary_edges": (int, True),
    "interior_edges": (int, True),
    "overlap_recount_mismatch": (_NUM, True),
    "overlap_truth": (dict, True),
}

# the PR 20 modeled-vs-measured reconciliation (obs/truth.py
# block_brief): the pipeline lane's modeled hidden_us_per_round joined
# against the tracer's measured device waits per plan uid; rides the
# `pipeline` block (the lane's own run) and the `calibration` block
# (the main bench's history).  `claim_frac` above the claim limit
# fails the bench under an explicit GRAPE_RATE_PROFILE.
_OVERLAP_TRUTH = {
    "queries": (int, True),
    "joined": (int, True),
    "plan_uid": (str, True),
    "modeled_hidden_us_per_round": (_NUM, True),
    "measured_round_us": (_NUM, True),
    "claim_frac": (_NUM, True),
    "compile_rounds_excluded": (int, True),
    "ok": (bool, True),
}

# the r10 2-D vertex-cut partition lane (fragment/partition.py,
# models/vc2d.py, docs/PARTITION2D.md): hub-heavy RMAT A/B at fnum 4
# (k=2) — max-tile vs the raw 1-D hub fragment, modeled exchange
# bytes under the shared ledgers, serial-vs-2D wall, byte/eps
# identity verdicts, the planner's recorded auto decision vs the
# measured winner, and the per-tile pack-plan recount drift (the 5%
# gate).  Verdict fields are DECLARED bool, like the pipeline lane's.
_PARTITION2D = {
    "scale": (int, True),
    "fnum": (int, True),
    "k": (int, True),
    "app": (str, True),
    "hub_1d_edges": (int, True),
    "max_1d_edges": (int, True),
    "max_tile_edges": (int, True),
    "tile_skew": (_NUM, True),
    "tile_ratio_vs_hub": (_NUM, True),
    "tile_bound_ok": (bool, True),
    "exchange_bytes_1d": (int, True),
    "exchange_bytes_2d": (int, True),
    "exchange_reduced": (bool, True),
    "serial_1d_s": (_NUM, True),
    "vc2d_s": (_NUM, True),
    "sssp_byte_identical": (bool, True),
    "pagerank_max_rel_err": (_NUM, True),
    "pagerank_eps_identical": (bool, True),
    "planner_choice": (str, True),
    "planner_t1d_s": (_NUM, True),
    "planner_t2d_s": (_NUM, True),
    "measured_winner": (str, True),
    "decision_matches": (bool, True),
    "tile_plan_ok": (bool, True),
    "tile_recount_mismatch": (_NUM, True),
}

# the PR 19 pipelined-SUMMA lane (parallel/pipeline.py
# VC2DPipelinePlan, models/vc2d.py, docs/PARTITION2D.md "Overlapped
# round"): 2-D SSSP pipelined vs unpipelined vs the 1-D baseline,
# byte-compared per oid; the decision record's rate-profile label and
# modeled hidden-µs per round are REQUIRED (the lane gates on both),
# and the wall's backend is declared so a CPU correctness proxy can
# never read as overlap evidence.  Verdict fields are DECLARED bool.
_VC2D_PIPELINE = {
    "scale": (int, True),
    "fnum": (int, True),
    "k": (int, True),
    "app": (str, True),
    "engaged": (bool, True),
    "phase_split": (int, True),
    "edge_slots": (int, True),
    "exchange_bytes": (int, True),
    "serial_1d_s": (_NUM, True),
    "serial_2d_s": (_NUM, True),
    "pipelined_2d_s": (_NUM, True),
    "pipelined_eq_serial_2d": (bool, True),
    "pipelined_eq_1d": (bool, True),
    "profile": (str, True),
    "plan_uid": (str, True),
    "modeled_hidden_us": (_NUM, True),
    "modeled_hidden_frac": (_NUM, True),
    "measured_speedup": (_NUM, True),
    "wall_backend": (str, True),
    "wall_is_overlap_evidence": (bool, True),
}

# the r11 masked-SpGEMM lane (ops/spgemm_pack.py, docs/SPGEMM.md):
# LCC intersect-vs-spgemm wall A/B at the lane geometry with the
# bit-exactness verdict and the shipped-plan ledger recount (the 5%
# gate), plus the modeled ops/edge A/B at full bench geometry —
# spgemm MXU elems + VPU lanes per oriented mask edge against the
# popcount sweep's word-ops, priced into modeled seconds with the
# win verdict and the ledger-auto decision.  Verdict fields are
# DECLARED bool, like the pipeline lane's.
_SPGEMM = {
    "scale": (int, True),
    "bench_scale": (int, True),
    "intersect_s": (_NUM, True),
    "spgemm_s": (_NUM, True),
    "byte_identical": (bool, True),
    "items": (int, True),
    "items_per_edge": (_NUM, True),
    "mask_edges": (int, True),
    "ledger_recount_mismatch": (_NUM, True),
    "bench_mask_edges": (int, True),
    "bench_items_per_edge": (_NUM, True),
    "mxu_elems_per_edge": (_NUM, True),
    "vpu_ops_per_edge": (_NUM, True),
    "intersect_word_ops_per_edge": (_NUM, True),
    "modeled_spgemm_s": (_NUM, True),
    "modeled_intersect_s": (_NUM, True),
    "modeled_win": (bool, True),
    "auto_backend": (str, True),
}

_SPAN_ROLLUP = {
    "count": (int, True),
    "total_s": (_NUM, True),
    "mean_s": (_NUM, True),
    "max_s": (_NUM, True),
}

# the r13 serving-fleet lane (fleet/, docs/FLEET.md): the drain drill
# — R=2 replicas serving the query stream with concurrent barrier
# ingest, one replica drained mid-run — with per-replica qps@p99 (the
# ROADMAP's stated target bench), the byte-identity verdict vs the
# undrained R=1 run (bench exits 2 when it breaks), the
# dropped-query count (must be 0), and the budget/eviction counters.
# Verdict fields are DECLARED bool, like the pipeline lane's.
_FLEET = {
    "scale": (int, True),
    "replicas": (int, True),
    "tenants": (int, True),
    "queries": (int, True),
    "ok": (int, True),
    "dropped": (int, True),
    "drain_at": (int, True),
    "drained_replica": (int, True),
    "drain_wall_s": (_NUM, True),
    "catchup_ops": (int, True),
    "updates": (int, True),
    "updates_per_s": (_NUM, True),
    "fence": (int, True),
    "byte_identical": (bool, True),
    "per_replica": (dict, True),
    "evictions": (int, True),
    "readmit_compiles": (int, False),
}

_FLEET_REPLICA = {
    "qps": (_NUM, True),
    "p50_ms": (_NUM, True),
    "p99_ms": (_NUM, True),
    "served": (int, True),
    "ok": (int, True),
}

# the r15 telemetry lane (obs/, docs/OBSERVABILITY.md): the serve
# stream's per-stage latency decomposition (stage -> {p50_ms, p99_ms}
# from ServeResult.stages), the stats-federation census (registered
# namespace count + the self_check verdict), the SLO burn and the
# flight-recorder counters.  `scrape_ok` is the live-exporter smoke:
# an in-process scrape of /metrics named every federated namespace.
_TELEMETRY = {
    "namespaces": (int, True),
    "federation_ok": (bool, True),
    "scrape_ok": (bool, False),
    "stages": (dict, True),
    "slo_observed": (int, True),
    "slo_breaches": (int, True),
    "slo_max_burn": (_NUM, True),
    "recorder_recorded": (int, True),
    "recorder_dropped": (int, True),
    "recorder_triggers": (int, True),
}

_STAGE_POINT = {
    "p50": (_NUM, True),
    "p99": (_NUM, True),
}

# the r16 autopilot lane (autopilot/, docs/AUTOPILOT.md): the
# closed-loop drill — the feeder's arrival rate steps up mid-stream
# (rate_spec, serve/feeder.py) and the scaler must answer with at
# least one zero-drop scale-up through the drain/rejoin/replicate
# machinery while every answer stays byte-identical to a static-R
# scripted run; plus the result-cache sub-drill: repeated sources
# answered from the cache with ZERO XLA compiles, then one
# fence-bumping ingest invalidates the epoch and the post-ingest
# answers are byte-identical to a cold run on the mutated graph.
# Verdict fields are DECLARED bool, like the pipeline lane's.
_AUTOPILOT = {
    "scale": (int, True),
    "queries": (int, True),
    "ok": (int, True),
    "dropped": (int, True),
    "rate_spec": (str, True),
    "min_replicas": (int, True),
    "max_replicas": (int, True),
    "replicas_final": (int, True),
    "scale_ups": (int, True),
    "scale_downs": (int, True),
    "ticks": (int, True),
    "p99_ms": (_NUM, True),
    "p99_bound_ms": (_NUM, True),
    "p99_ok": (bool, True),
    "byte_identical": (bool, True),
    "cache_hits": (int, True),
    "cache_misses": (int, True),
    "cache_hit_compiles": (int, True),
    "cache_invalidations": (int, True),
    "post_ingest_identical": (bool, True),
}

# the r17 calibration lane (ops/calibration.py, docs/CALIBRATION.md):
# the fitted-rate record — the ACTIVE profile's label/fingerprint, the
# fit's sample count and RMS residual, the per-surface aggregate
# modeled-vs-measured drift (the 5% gate bench exits 2 on when an
# explicit GRAPE_RATE_PROFILE drifts), and the fitted rate values
# themselves so PERF_NOTES can table pinned-vs-fitted.  Verdict
# fields are DECLARED bool; every rate is numeric with bool rejected
# (the R5 class) via the extra rates-dict walk in validate_record.
_CALIBRATION = {
    "profile": (str, True),
    "fingerprint": (str, True),
    "source": (str, True),
    "fitted": (bool, True),
    "samples": (int, True),
    "residual_pct": (_NUM, True),
    "drift_pct": (_NUM, True),
    "max_sample_drift_pct": (_NUM, True),
    "drift_ok": (bool, True),
    "rates": (dict, True),
    "unfitted": (list, False),
    "fallback_notes": (list, False),
    "surfaces": (dict, False),
    "overlap_truth": (dict, True),
}

_CALIB_SURFACE = {
    "modeled_s": (_NUM, True),
    "measured_s": (_NUM, True),
    "samples": (int, True),
    "drift_pct": (_NUM, True),
}

# the distributed resilience drill (scripts/fault_drill.py
# --kill_rank, docs/FAULT_TOLERANCE.md "Distributed resilience"): a
# 2-process gang loses a rank at kill_round, and the survivors'
# sharded two-phase snapshot is reshard-restored onto a smaller mesh;
# byte_identical is the drill's verdict (the drill itself exits 2 on
# divergence — this block makes the record auditable after the fact)
_FT_DRILL = {
    "ranks": (int, True),
    "kill_round": (int, True),
    "kill_rank": (int, True),
    "old_fnum": (int, True),
    "new_fnum": (int, True),
    "checkpoint_rounds": (int, True),
    "restore_wall_s": (_NUM, True),
    "byte_identical": (bool, True),
    # the PR 20 gang-telemetry leg (tracer armed across the kill):
    # merged-trace completeness, the vote's cross-rank flow count,
    # and the byte-verified gang postmortem under one incident id
    "gang_trace_events": (int, False),
    "gang_trace_complete": (bool, False),
    "gang_cross_rank_flows": (int, False),
    "gang_incident": (str, False),
    "gang_bundle_verified": (bool, False),
}

# the PR 20 bench gang-telemetry self-drill (bench.py obs_gang_lane):
# two in-process fake-rank tracers federate sidecars through the real
# assembler (completeness / alignment / monotonicity / cross-rank
# flow verdicts), plus the armed-vs-disarmed fused-HLO byte-identity
# re-proof.  Verdict fields are DECLARED bool.
_OBS_GANG = {
    "ranks": (int, True),
    "events": (int, True),
    "flow_events": (int, True),
    "cross_rank_flows": (int, True),
    "aligned": (bool, True),
    "monotonic": (bool, True),
    "complete": (bool, True),
    "hlo_identical": (bool, True),
}

#: every nested block bench.py may emit — THE single declaration
#: point; _TOP, SCHEMA, validate_record and the CLI listing all
#: derive from it (self_check() pins the derivation)
_BLOCKS = {
    "sssp": _SSSP,
    "guard": _GUARD,
    "pack_ledger": _PACK_LEDGER,
    "obs": _OBS,
    "serve": _SERVE,
    "serve_async": _SERVE_ASYNC,
    "dyn": _DYN,
    "pipeline": _PIPELINE,
    "partition2d": _PARTITION2D,
    "vc2d_pipeline": _VC2D_PIPELINE,
    "spgemm": _SPGEMM,
    "fleet": _FLEET,
    "telemetry": _TELEMETRY,
    "autopilot": _AUTOPILOT,
    "calibration": _CALIBRATION,
    "ft_drill": _FT_DRILL,
    "obs_gang": _OBS_GANG,
}

_TOP = {**_TOP_SCALARS, **{k: (dict, False) for k in _BLOCKS}}

SCHEMA = {"": _TOP, **_BLOCKS}


def self_check() -> list:
    """The wiring-gap gate: every DECLARED block must be wired into
    _TOP, SCHEMA and validate_record — which all derive from _BLOCKS,
    so the only way to regress is to bypass the derivation; this
    check fails the CLI (exit 2) and tests/test_fleet.py if anyone
    does.  Returns a list of inconsistencies (empty = wired)."""
    errors = []
    top_blocks = {
        k for k, (types, _) in _TOP.items()
        if (types if isinstance(types, tuple) else (types,)) == (dict,)
    }
    if top_blocks != set(_BLOCKS):
        errors.append(
            f"_TOP dict-typed fields {sorted(top_blocks)} != declared "
            f"blocks {sorted(_BLOCKS)}"
        )
    if set(SCHEMA) != {""} | set(_BLOCKS):
        errors.append(
            f"SCHEMA keys {sorted(SCHEMA)} != '' + declared blocks"
        )
    for name, spec in _BLOCKS.items():
        if SCHEMA.get(name) is not spec:
            errors.append(f"SCHEMA[{name!r}] is not the declared spec")
    # validate_record must actually CHECK every declared block: feed
    # it a record where every block violates its spec and demand one
    # error per block
    probe = {k: {"__not_a_field__": 1} for k in _BLOCKS}
    probe.update({"metric": "x", "value": 1, "unit": "u",
                  "vs_baseline": 1.0})
    found = validate_record(probe)
    for name in _BLOCKS:
        if not any(e.startswith(f"{name}.") or e.startswith(f"{name}:")
                   for e in found):
            errors.append(
                f"validate_record never checked block {name!r}"
            )
    return errors


def _check_block(block: dict, spec: dict, where: str, errors: list,
                 allow_unknown: bool = False) -> None:
    for field, (types, required) in spec.items():
        if field not in block:
            if required:
                errors.append(f"{where}: missing required field {field!r}")
            continue
        v = block[field]
        accepted = types if isinstance(types, tuple) else (types,)
        # bool is an int subclass: every numeric field (int OR the
        # (int, float) number tuple) must reject it explicitly
        if isinstance(v, bool) and bool not in accepted:
            errors.append(
                f"{where}.{field}: expected "
                f"{getattr(types, '__name__', types)}, got bool"
            )
        elif not isinstance(v, types):
            errors.append(
                f"{where}.{field}: expected "
                f"{getattr(types, '__name__', types)}, got "
                f"{type(v).__name__} ({v!r})"
            )
    if not allow_unknown:
        for k in block:
            if k not in spec:
                errors.append(
                    f"{where}: unknown field {k!r} — declare it in "
                    "scripts/check_bench_schema.py or fix the typo"
                )


def validate_record(record) -> list:
    """Every schema violation in one BENCH record (empty = valid)."""
    errors: list = []
    if not isinstance(record, dict):
        return [f"record is {type(record).__name__}, expected object"]
    _check_block(record, _TOP, "record", errors)
    for key, spec in _BLOCKS.items():
        block = record.get(key)
        if isinstance(block, dict):
            _check_block(block, spec, key, errors)
    led = record.get("pack_ledger")
    if isinstance(led, dict):
        stages = led.get("per_stage_ops_per_edge")
        if isinstance(stages, dict):
            for k, v in stages.items():
                if not isinstance(v, _NUM) or isinstance(v, bool):
                    errors.append(
                        f"pack_ledger.per_stage_ops_per_edge[{k!r}]: "
                        f"expected number, got {type(v).__name__}"
                    )
        if led.get("scan_mode") not in (None, "mxu", "shift"):
            errors.append(
                f"pack_ledger.scan_mode: {led.get('scan_mode')!r} not in "
                "('mxu', 'shift')"
            )
    p2 = record.get("partition2d")
    if isinstance(p2, dict):
        for f in ("planner_choice", "measured_winner"):
            if p2.get(f) not in (None, "1d", "2d"):
                errors.append(
                    f"partition2d.{f}: {p2.get(f)!r} not in "
                    "('1d', '2d')"
                )
    sg = record.get("spgemm")
    if isinstance(sg, dict):
        if sg.get("auto_backend") not in (None, "intersect", "spgemm"):
            errors.append(
                f"spgemm.auto_backend: {sg.get('auto_backend')!r} not "
                "in ('intersect', 'spgemm')"
            )
    ob = record.get("obs")
    if isinstance(ob, dict) and isinstance(ob.get("spans"), dict):
        for name, r in ob["spans"].items():
            if not isinstance(r, dict):
                errors.append(f"obs.spans[{name!r}]: expected object")
                continue
            _check_block(r, _SPAN_ROLLUP, f"obs.spans[{name!r}]", errors)
    sv = record.get("serve")
    if isinstance(sv, dict):
        for app in ("sssp", "bfs"):
            blk = sv.get(app)
            if not isinstance(blk, dict):
                continue
            for bkey, point in blk.items():
                where = f"serve.{app}[{bkey!r}]"
                if not (bkey.startswith("b") and bkey[1:].isdigit()):
                    errors.append(
                        f"{where}: batch keys must look like b<k>"
                    )
                    continue
                if not isinstance(point, dict):
                    errors.append(f"{where}: expected object")
                    continue
                _check_block(point, _SERVE_POINT, where, errors)
        bh = sv.get("batch_hist")
        if isinstance(bh, dict):
            for k, v in bh.items():
                if not (isinstance(k, str) and k.isdigit()):
                    errors.append(
                        f"serve.batch_hist[{k!r}]: keys are decimal "
                        "batch sizes"
                    )
                if not isinstance(v, int) or isinstance(v, bool):
                    errors.append(
                        f"serve.batch_hist[{k!r}]: expected int count, "
                        f"got {type(v).__name__}"
                    )
    sa = record.get("serve_async")
    if isinstance(sa, dict):
        wab = sa.get("window_ab")
        if isinstance(wab, dict):
            for wkey, points in wab.items():
                where = f"serve_async.window_ab[{wkey!r}]"
                if not (wkey.startswith("w") and wkey[1:].isdigit()):
                    errors.append(f"{where}: window keys look like w<k>")
                    continue
                if not isinstance(points, dict):
                    errors.append(f"{where}: expected object")
                    continue
                for bkey, point in points.items():
                    pwhere = f"{where}[{bkey!r}]"
                    if not (bkey.startswith("b") and bkey[1:].isdigit()):
                        errors.append(
                            f"{pwhere}: batch keys look like b<k>"
                        )
                        continue
                    if not isinstance(point, dict):
                        errors.append(f"{pwhere}: expected object")
                        continue
                    _check_block(point, _SERVE_ASYNC_POINT, pwhere,
                                 errors)
        aw = sa.get("admission_wait_ms")
        if isinstance(aw, dict):
            for q in ("p50", "p99"):
                v = aw.get(q)
                if not isinstance(v, _NUM) or isinstance(v, bool):
                    errors.append(
                        f"serve_async.admission_wait_ms.{q}: expected "
                        f"number, got {type(v).__name__}"
                    )
    tl = record.get("telemetry")
    if isinstance(tl, dict) and isinstance(tl.get("stages"), dict):
        for sname, point in tl["stages"].items():
            where = f"telemetry.stages[{sname!r}]"
            if not isinstance(point, dict):
                errors.append(f"{where}: expected object")
                continue
            _check_block(point, _STAGE_POINT, where, errors)
    for holder in ("pipeline", "calibration"):
        blk = record.get(holder)
        if isinstance(blk, dict) and isinstance(
                blk.get("overlap_truth"), dict):
            _check_block(blk["overlap_truth"], _OVERLAP_TRUTH,
                         f"{holder}.overlap_truth", errors)
    cb = record.get("calibration")
    if isinstance(cb, dict):
        rates = cb.get("rates")
        if isinstance(rates, dict):
            for k, v in rates.items():
                if not isinstance(v, _NUM) or isinstance(v, bool):
                    errors.append(
                        f"calibration.rates[{k!r}]: expected number, "
                        f"got {type(v).__name__}"
                    )
        for lf in ("unfitted", "fallback_notes"):
            seq = cb.get(lf)
            if isinstance(seq, list):
                for i, v in enumerate(seq):
                    if not isinstance(v, str):
                        errors.append(
                            f"calibration.{lf}[{i}]: expected str, "
                            f"got {type(v).__name__}"
                        )
        surfs = cb.get("surfaces")
        if isinstance(surfs, dict):
            for sname, point in surfs.items():
                where = f"calibration.surfaces[{sname!r}]"
                if not isinstance(point, dict):
                    errors.append(f"{where}: expected object")
                    continue
                _check_block(point, _CALIB_SURFACE, where, errors)
    fl = record.get("fleet")
    if isinstance(fl, dict):
        pr = fl.get("per_replica")
        if isinstance(pr, dict):
            for rkey, point in pr.items():
                where = f"fleet.per_replica[{rkey!r}]"
                if not (rkey.startswith("r") and rkey[1:].isdigit()):
                    errors.append(
                        f"{where}: replica keys look like r<k>"
                    )
                    continue
                if not isinstance(point, dict):
                    errors.append(f"{where}: expected object")
                    continue
                _check_block(point, _FLEET_REPLICA, where, errors)
    return errors


def _records_from_text(text: str, where: str):
    """(record, label) pairs from a file's content: a driver wrapper
    (validated via `parsed`), a bare record, or line-delimited output
    where the LAST json object line wins (the driver's convention)."""
    text = text.strip()
    try:
        doc = json.loads(text)
    except json.JSONDecodeError:
        doc = None
    if isinstance(doc, dict):
        if "parsed" in doc and isinstance(doc["parsed"], dict):
            return [(doc["parsed"], f"{where}:parsed")]
        return [(doc, where)]
    # stream mode: last parseable json-object line (bench stdout)
    last = None
    for line in text.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                last = json.loads(line)
            except json.JSONDecodeError:
                continue
    if last is None:
        raise ValueError(f"{where}: no json record found")
    return [(last, f"{where}:last-line")]


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    # the self-consistency gate runs FIRST: a declared-but-unwired
    # block must fail the tool itself, not quietly validate nothing
    wiring = self_check()
    if wiring:
        print("FAIL schema self-check:", file=sys.stderr)
        for e in wiring:
            print(f"  - {e}", file=sys.stderr)
        return 2
    if not argv:
        print(__doc__.strip().splitlines()[0], file=sys.stderr)
        print("usage: check_bench_schema.py FILE... (or - for stdin)",
              file=sys.stderr)
        return 64
    failed = False
    for path in argv:
        text = sys.stdin.read() if path == "-" else open(path).read()
        try:
            pairs = _records_from_text(text, path)
        except ValueError as e:
            print(f"FAIL {e}")
            failed = True
            continue
        for record, label in pairs:
            errors = validate_record(record)
            if errors:
                failed = True
                print(f"FAIL {label}: {len(errors)} schema error(s)")
                for e in errors:
                    print(f"  - {e}")
            else:
                blocks = [k for k in _BLOCKS if k in record]
                print(f"OK {label} ({record.get('metric')}"
                      + (f"; blocks: {', '.join(blocks)}" if blocks
                         else "") + ")")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
