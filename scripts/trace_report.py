#!/usr/bin/env python
"""Render a per-superstep table from an obs/ trace.

Reads a Chrome trace_event JSON (or its JSONL twin) produced by
GRAPE_TRACE / --trace / obs.configure and prints:

* one row per superstep (PEval = round 0): wall ms, device-wait ms
  (the device-execution estimate under the sync-before-close
  convention — tracer.Span), dispatch ms, active vertices, guard
  verdicts whose instant events landed inside the round's interval;
* the modeled pack-ledger cost attached to the enclosing query span
  (ops/bytes per superstep — the planner's static budget, constant
  across rounds), laid against each round's measured wall time;
* a drift flag on any superstep whose measured/modeled ratio is
  more than DRIFT_X (2x) away from the run's median ratio.  Modeled
  cost is per-round constant, so the ratio is wall-time-per-modeled-
  unit: a flagged round ran slower (or faster) than the same modeled
  work did in the median round — the supersteps worth profiling.
* the superstep-pipeline split when the query span carries one (r9,
  parallel/pipeline.py): boundary/interior vertex+edge counts, the
  exchange mode and bytes, the modeled hidden-exchange fraction, an
  `ovl_ms` overlap column (hidden-exchange time per superstep), and a
  PIPELINE DRIFT flag when pipelining is armed but hides <10% of the
  exchange;
* the 2-D vertex-cut tile table when the query span carries one
  (r10, docs/PARTITION2D.md): one labeled row per (row, col) tile
  with its edge count and share of the max tile, plus the
  max-tile-skew summary;
* the async serve-pump table when the trace carries serve_dispatch/
  serve_harvest spans (r12, serve/pipeline.py): one row per batch
  with dispatch and harvest lag and the window occupancy at harvest,
  plus the hidden-harvest fraction — harvest wall spent while other
  batches were still in flight — and a PUMP DRIFT flag when a W>1
  window is armed but hides <10% of the harvest wall (the window is
  paying its bookkeeping and buying no overlap);
* the per-query serve table when the trace carries serve_query lane
  spans (r15): one row per query with its queue-wait column (the
  submit->pop admission wait the session stamps on every span), plus
  per-tenant and per-replica rollup rows (fleet_replica spans) so a
  mixed-tenant fleet trace reads as one table;
* a phase rollup (obs.rollup) for the non-superstep spans.

With ``--gang`` (PR 20, obs/gang.py) TRACE names a gang sidecar
directory — or the per-rank trace base whose ``<base>.gang`` dir
holds the ``rank_<r>.json`` sidecars — and the report first merges
every rank into ONE Perfetto timeline (one process track per rank,
timestamps aligned onto rank 0's clock by the recorded handshake
offsets, vote/2PC flow arrows preserved), prints the federation
summary (per-rank span counts, flow coverage, completeness verdict),
writes the merged trace next to the sidecars (or ``--out``), and then
renders the usual tables over the merged stream.

Usage: python scripts/trace_report.py TRACE [--drift-x 2.0]
       python scripts/trace_report.py --gang TRACEDIR [--out merged.json]
"""

from __future__ import annotations

import argparse
import os
import sys

DRIFT_X = 2.0


def _fmt_ms(us):
    return f"{us / 1000.0:10.3f}" if us is not None else f"{'-':>10}"


def superstep_rows(events):
    """One row per host-track peval/superstep span, in timestamp
    order.  Rounds deliberately may REPEAT: a guard rollback-replay
    re-executes rounds and a file can hold several queries (bench
    warm + measured) — every execution is a real measurement, so rows
    are never keyed/overwritten by round number."""
    from libgrape_lite_tpu.obs.events import FRAG_TID_BASE

    rows = []
    for ev in events:
        if ev.get("ph") != "X" or ev.get("name") not in (
            "peval", "superstep"
        ):
            continue
        if ev.get("tid", 0) >= FRAG_TID_BASE:
            continue  # per-fragment mirrors restate the host interval
        args = ev.get("args") or {}
        rnd = args.get("round")
        if rnd is None:
            rnd = 0 if ev["name"] == "peval" else None
        if rnd is None:
            continue
        rows.append({
            "round": int(rnd),
            "name": ev["name"],
            "ts": float(ev["ts"]),
            "wall_us": float(ev.get("dur", 0)),
            "dispatch_us": args.get("dispatched_us"),
            "device_us": args.get("device_wait_us"),
            "active": args.get("active"),
            "verdicts": [],
        })
    return sorted(rows, key=lambda r: r["ts"])


def attach_verdicts(rows, events):
    """Guard instants land on the row whose [ts, ts+dur) contains (or
    last precedes) them — a probe fires after its round's sync."""
    for ev in events:
        if ev.get("ph") != "i" or ev.get("name") not in (
            "guard_breach", "resume"
        ):
            continue
        ts = float(ev.get("ts", 0))
        owner = None
        for r in rows:
            if r["ts"] <= ts:
                owner = r
            else:
                break
        if owner is not None:
            args = ev.get("args") or {}
            tag = args.get("kind", ev["name"])
            owner["verdicts"].append(str(tag))


def query_ledger(events):
    """The pack_ledger args of the last query span (modeled per-round
    cost), or None."""
    led = None
    for ev in events:
        if ev.get("ph") == "X" and ev.get("name") == "query":
            args = ev.get("args") or {}
            if "pack_ledger" in args:
                led = args["pack_ledger"]
    return led


def query_partition(events):
    """The 2-D vertex-cut tile record of the last query span that
    carried one (r10: the worker attaches `partition` when the app
    ran the 2-D mesh), or None."""
    pt = None
    for ev in events:
        if ev.get("ph") == "X" and ev.get("name") == "query":
            args = ev.get("args") or {}
            if "partition" in args:
                pt = args["partition"]
    return pt


def query_pipeline(events):
    """The superstep-pipeline brief of the last query span that
    carried one (r9: the worker attaches `pipeline` when a plan is
    engaged, plus `overlap_hidden_us` once the round count is known),
    or None."""
    pl = None
    for ev in events:
        if ev.get("ph") == "X" and ev.get("name") == "query":
            args = ev.get("args") or {}
            if "pipeline" in args:
                pl = dict(args["pipeline"])
                if "overlap_hidden_us" in args:
                    pl["overlap_hidden_us"] = args["overlap_hidden_us"]
    return pl


def serve_pump_rows(events):
    """(dispatch, harvest) span pairs of the async serve pump, in
    dispatch order: one row per batch with its dispatch/harvest lag
    and the window occupancy at harvest (serve/pipeline.py tags every
    span with window/inflight/overlapped)."""
    disp = sorted(
        (ev for ev in events
         if ev.get("ph") == "X" and ev.get("name") == "serve_dispatch"),
        key=lambda e: float(e.get("ts", 0)),
    )
    harv = sorted(
        (ev for ev in events
         if ev.get("ph") == "X" and ev.get("name") == "serve_harvest"),
        key=lambda e: float(e.get("ts", 0)),
    )
    rows = []
    # FIFO harvest: the i-th harvest drains the i-th dispatch
    for i, h in enumerate(harv):
        d = disp[i] if i < len(disp) else None
        da = (d.get("args") or {}) if d else {}
        ha = h.get("args") or {}
        rows.append({
            "app": ha.get("app", da.get("app", "?")),
            "batch": ha.get("batch", da.get("batch", 0)),
            "mode": ha.get("mode", "?"),
            "dispatch_us": float(d.get("dur", 0)) if d else None,
            "harvest_us": float(h.get("dur", 0)),
            "occupancy": ha.get("inflight", 0),
            "overlapped": bool(ha.get("overlapped", False)),
            "window": ha.get("window", da.get("window", 1)),
        })
    return rows


def serve_query_rows(events):
    """One row per serve_query lane span, in (timestamp, lane) order:
    the per-query view of a serve trace, carrying the queue-wait the
    session stamped at emit time (submit->pop admission wait µs)."""
    rows = []
    for ev in events:
        if ev.get("ph") != "X" or ev.get("name") != "serve_query":
            continue
        a = ev.get("args") or {}
        rows.append({
            "ts": float(ev.get("ts", 0)),
            "wall_us": float(ev.get("dur", 0)),
            "query_id": a.get("query_id", "?"),
            "app": a.get("app", "?"),
            "tenant": a.get("tenant", "") or "-",
            "lane": a.get("lane", 0),
            "rounds": a.get("rounds", 0),
            "ok": a.get("ok", True),
            "queue_wait_us": a.get("queue_wait_us"),
        })
    return sorted(rows, key=lambda r: (r["ts"], r["lane"]))


def fleet_replica_rows(events):
    """fleet_replica spans (fleet/router.py): one per replica pump
    pass that delivered results, on the replica's own trace row."""
    rows = []
    for ev in events:
        if ev.get("ph") != "X" or ev.get("name") != "fleet_replica":
            continue
        a = ev.get("args") or {}
        rows.append({
            "replica": a.get("replica", "?"),
            "results": a.get("results", 0),
            "wall_us": float(ev.get("dur", 0)),
        })
    return rows


_QUERY_ROWS_CAP = 64


def render_serve_queries(rows, replica_rows, out=sys.stdout):
    """Per-query serve table with the queue-wait column, then the
    per-tenant and per-replica rollup rows.  Percentiles follow
    serve/queue.py latency_summary_ms (p50 = v[n//2])."""
    if not rows and not replica_rows:
        return

    def _p50(v):
        return v[len(v) // 2]

    def _p99(v):
        return v[min(len(v) - 1, int(len(v) * 0.99))]

    if rows:
        print("\nserve queries (serve_query lane spans; qwait = "
              "submit->pop admission wait):", file=out)
        print(f"{'qid':>6} {'app':>10} {'tenant':>8} {'lane':>5} "
              f"{'rounds':>6} {'ok':>3} {'qwait_ms':>10} "
              f"{'wall_ms':>10}", file=out)
        for r in rows[:_QUERY_ROWS_CAP]:
            print(
                f"{str(r['query_id']):>6} {r['app']:>10} "
                f"{r['tenant']:>8} {r['lane']:>5} {r['rounds']:>6} "
                f"{'y' if r['ok'] else 'n':>3} "
                f"{_fmt_ms(r['queue_wait_us'])} {_fmt_ms(r['wall_us'])}",
                file=out,
            )
        if len(rows) > _QUERY_ROWS_CAP:
            print(f"  ... {len(rows) - _QUERY_ROWS_CAP} more query "
                  "row(s) elided (rollups below cover all of them)",
                  file=out)
        by_tenant: dict = {}
        for r in rows:
            by_tenant.setdefault(r["tenant"], []).append(r)
        print("  per-tenant rollup:", file=out)
        for t, rs in sorted(by_tenant.items()):
            qw = sorted(float(x["queue_wait_us"] or 0) for x in rs)
            wl = sorted(x["wall_us"] for x in rs)
            print(
                f"    tenant={t:<10} n={len(rs):<4} "
                f"ok={sum(bool(x['ok']) for x in rs):<4} "
                f"qwait p50={_p50(qw) / 1e3:.3f} "
                f"p99={_p99(qw) / 1e3:.3f} "
                f"wall p50={_p50(wl) / 1e3:.3f} "
                f"p99={_p99(wl) / 1e3:.3f} ms", file=out,
            )
    if replica_rows:
        by_rep: dict = {}
        for r in replica_rows:
            by_rep.setdefault(r["replica"], []).append(r)
        print("  per-replica rollup (fleet_replica spans):", file=out)
        for idx, rs in sorted(by_rep.items(), key=lambda kv: str(kv[0])):
            print(
                f"    replica={idx!s:<3} pumps={len(rs):<4} "
                f"results={sum(x['results'] for x in rs):<5} "
                f"pump wall={sum(x['wall_us'] for x in rs) / 1e3:.3f} ms",
                file=out,
            )


def render_serve_pump(rows, out=sys.stdout) -> int:
    """The async-pump section: per-batch dispatch/harvest lag + window
    occupancy, the hidden-harvest fraction, and the PUMP DRIFT flag
    (W>1 armed but <10% of the harvest wall overlapped with in-flight
    work).  Returns 1 when flagged, else 0."""
    if not rows:
        return 0
    print("\nasync serve pump (serve_dispatch/serve_harvest spans, "
          "serve/pipeline.py):", file=out)
    print(f"{'batch':>5} {'app':>10} {'lanes':>6} {'mode':>9} "
          f"{'disp_ms':>10} {'harv_ms':>10} {'occ':>4}  ovl", file=out)
    total = hidden = 0.0
    for i, r in enumerate(rows):
        total += r["harvest_us"]
        if r["overlapped"]:
            hidden += r["harvest_us"]
        print(
            f"{i:>5} {r['app']:>10} {r['batch']:>6} {r['mode']:>9} "
            f"{_fmt_ms(r['dispatch_us'])} {_fmt_ms(r['harvest_us'])} "
            f"{r['occupancy']:>4}  {'y' if r['overlapped'] else '-'}",
            file=out,
        )
    armed = any(r["window"] > 1 for r in rows)
    frac = hidden / total if total > 0 else 0.0
    occ = [r["occupancy"] for r in rows]
    print(
        f"  window={'/'.join(str(w) for w in sorted({r['window'] for r in rows}))} "
        f"occupancy mean={sum(occ) / len(occ):.2f} max={max(occ)} "
        f"hidden harvest wall {frac:.1%}",
        file=out,
    )
    if armed and frac < 0.10:
        print(
            "  PUMP DRIFT: a W>1 window is armed but <10% of the "
            f"harvest wall overlapped in-flight work ({frac:.1%}) — "
            "the stream never kept the window full (batch cadence too "
            "coarse, declines forcing the sync path, or ingest "
            "barriers quiescing every step; see PUMP_STATS and "
            "docs/SERVING.md)",
            file=out,
        )
        return 1
    return 0


def drift_flags(rows, drift_x: float):
    """Flag rounds whose wall-per-modeled-unit ratio is > drift_x off
    the median.  Modeled cost is constant per round (static ledger),
    so the ratio reduces to wall time vs the median round — but the
    division is kept explicit so a future per-round model (active-
    scaled ops) slots in without changing the report."""
    walls = sorted(r["wall_us"] for r in rows if r["wall_us"] > 0)
    if not walls:
        return
    median = walls[len(walls) // 2]
    if median <= 0:
        return
    for r in rows:
        ratio = r["wall_us"] / median
        r["drift"] = ratio
        r["flag"] = ratio > drift_x or ratio < 1.0 / drift_x


def render(events, drift_x: float = DRIFT_X, out=None):
    from libgrape_lite_tpu.obs.export import rollup

    # resolved at call time: a default bound at import would pin
    # whatever stdout happened to be when the module first loaded
    out = out if out is not None else sys.stdout

    rows = superstep_rows(events)
    attach_verdicts(rows, events)
    led = query_ledger(events)
    pipe = query_pipeline(events)
    hidden_us = (pipe or {}).get("hidden_us_per_round")
    print("superstep table (wall/device from synced spans; "
          "docs/OBSERVABILITY.md):", file=out)
    hdr = (f"{'round':>5} {'phase':>9} {'wall_ms':>10} {'disp_ms':>10} "
           f"{'dev_ms':>10} {'ovl_ms':>10} {'active':>9} "
           f"{'x_med':>6}  guard")
    print(hdr, file=out)
    drift_flags(rows, drift_x)
    flagged = 0
    pipe_flagged = 0
    for r in rows:
        flag = "  DRIFT" if r.get("flag") else ""
        flagged += bool(r.get("flag"))
        verd = ",".join(r["verdicts"]) or "-"
        act = r["active"] if r["active"] is not None else "-"
        # overlap column: modeled hidden-exchange µs per superstep
        # when the pipeline is armed (constant per round — the static
        # split; PEval is pre-pipeline, so round 0 shows '-')
        ovl = (hidden_us if hidden_us is not None
               and r["name"] == "superstep" else None)
        print(
            f"{r['round']:>5} {r['name']:>9} {_fmt_ms(r['wall_us'])} "
            f"{_fmt_ms(r['dispatch_us'])} {_fmt_ms(r['device_us'])} "
            f"{_fmt_ms(ovl)} "
            f"{act:>9} {r.get('drift', 0):>6.2f}  {verd}{flag}",
            file=out,
        )
    if not rows:
        print("  (no peval/superstep spans — fused query? the fused "
              "path is one dispatch; use --profile / stepwise for "
              "per-round rows)", file=out)
    if led:
        e = max(1, led.get("edges", 1))
        print(
            "\nmodeled per-round budget (pack ledger on the query "
            f"span): {led.get('vpu_ops', 0) / e:.1f} VPU ops/edge, "
            f"{led.get('mxu_ops', 0) / e:.1f} MXU elems/edge, "
            f"{led.get('hbm_bytes', 0) / e:.1f} B/edge over "
            f"{e} edges",
            file=out,
        )
    if pipe:
        print(
            "\npipeline split (query span, parallel/pipeline.py): "
            f"{pipe.get('boundary_vertices', 0)} boundary / "
            f"{pipe.get('interior_vertices', 0)} interior vertices "
            f"({pipe.get('boundary_edges', 0)} / "
            f"{pipe.get('interior_edges', 0)} edges), "
            f"{pipe.get('mode', '?')} exchange "
            f"{pipe.get('exchange_bytes', 0)} B/round, modeled hidden "
            f"frac {pipe.get('modeled_hidden_frac', 0.0):.2%}"
            + (f", {pipe['overlap_hidden_us']:.1f} µs hidden over the "
               "query" if "overlap_hidden_us" in pipe else ""),
            file=out,
        )
        if pipe.get("modeled_hidden_frac", 0.0) < 0.10:
            pipe_flagged = 1
            print(
                "  PIPELINE DRIFT: pipelining is armed but hides "
                f"<10% of the exchange "
                f"({pipe.get('modeled_hidden_frac', 0.0):.2%}) — the "
                "interior slice is too small to cover the collective "
                "(hub-heavy cut? see docs/PIPELINE.md: the split "
                "costs a dispatch and buys almost nothing here)",
                file=out,
            )
    part = query_partition(events)
    if part:
        # 2-D vertex-cut tile table (r10, docs/PARTITION2D.md): one
        # row per tile with its share of the max-tile skew — the
        # per-tile analogue of the partition-skew warning, read from
        # the SAME record the worker attached to the query span
        k = part.get("k", 0)
        mx = max(1, part.get("max_tile_edges", 1))
        print(
            f"\npartition2d tiles (k={k}, "
            f"max {part.get('max_tile_edges', 0)} / mean "
            f"{part.get('mean_tile_edges', 0)} edges, skew "
            f"{part.get('tile_skew', 0.0):.3f}x):",
            file=out,
        )
        print(f"{'tile':>10} {'edges':>10} {'x_max':>7}", file=out)
        for t in part.get("per_tile", []):
            label = f"({t.get('row', '?')},{t.get('col', '?')})"
            print(
                f"{label:>10} {t.get('edges', 0):>10} "
                f"{t.get('edges', 0) / mx:>7.2f}",
                file=out,
            )
    pump_flagged = render_serve_pump(serve_pump_rows(events), out)
    render_serve_queries(
        serve_query_rows(events), fleet_replica_rows(events), out
    )
    if flagged:
        print(
            f"\n{flagged} superstep(s) drifted >{drift_x}x from the "
            "median wall-per-modeled-unit ratio — same modeled work, "
            "different measured time (contention, recompile, or a "
            "frontier the static model does not see)", file=out,
        )
    print("\nphase rollup:", file=out)
    for name, r in sorted(rollup(events).items(),
                          key=lambda kv: -kv[1]["total_s"]):
        print(
            f"  {name:<20} n={r['count']:<4} total={r['total_s']:.4f}s "
            f"mean={r['mean_s']:.4f}s max={r['max_s']:.4f}s", file=out,
        )
    # superstep x_med drift, the pipeline <10%-hidden flag, and the
    # serve-pump <10%-hidden flag are counted separately (the summary
    # above names only the first); callers get the total so any kind
    # reads as "worth a look"
    return flagged + pipe_flagged + pump_flagged


def render_gang_summary(summary, out=None):
    """The federation header of a --gang report: who contributed,
    how the clocks were aligned, and whether the merge is complete
    (every expected rank present, aligned, and span-bearing)."""
    out = out if out is not None else sys.stdout
    print("gang trace federation (obs/gang.py):", file=out)
    print(
        f"  ranks {summary['ranks']} of nprocs={summary['nprocs']}"
        + (f", MISSING {summary['missing']}" if summary["missing"]
           else ""),
        file=out,
    )
    for r in sorted(summary["spans_by_rank"]):
        print(
            f"  rank {r}: {summary['spans_by_rank'][r]} span(s), "
            f"{summary['supersteps_by_rank'].get(r, 0)} superstep(s)",
            file=out,
        )
    print(
        f"  flows: {summary['flow_ids']} id(s), "
        f"{summary['flow_events']} leg event(s), "
        f"{summary['cross_rank_flows']} crossing rank tracks",
        file=out,
    )
    print(
        f"  aligned={summary['aligned']} monotonic={summary['monotonic']} "
        f"complete={summary['complete']}"
        + (f"\n  merged trace -> {summary['out']}" if summary["out"]
           else ""),
        file=out,
    )


def _gang_dir_of(trace: str) -> str:
    """Resolve the sidecar dir a --gang TRACE argument names: the dir
    itself, or the `<base>.gang` twin of a per-rank trace path."""
    if os.path.isdir(trace):
        return trace
    twin = trace + ".gang"
    if os.path.isdir(twin):
        return twin
    base, _ = os.path.splitext(trace)
    twin = base + ".gang"
    if os.path.isdir(twin):
        return twin
    raise FileNotFoundError(
        f"--gang: no sidecar dir at {trace!r} (or its .gang twin); "
        "expected the dir GRAPE_TRACE's gang federation wrote "
        "rank_<r>.json files into"
    )


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace", help="Chrome trace JSON or JSONL path "
                                  "(with --gang: the sidecar dir or "
                                  "the trace base of one)")
    ap.add_argument("--drift-x", type=float, default=DRIFT_X,
                    help="ratio-vs-median threshold to flag (default 2)")
    ap.add_argument("--gang", action="store_true",
                    help="merge every rank sidecar into one Perfetto "
                         "timeline first, then render it")
    ap.add_argument("--out", default="",
                    help="with --gang: write the merged Chrome trace "
                         "here (default <dir>/merged.json)")
    ns = ap.parse_args(argv)
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), ".."))
    from libgrape_lite_tpu.obs.export import load_trace

    if ns.gang:
        from libgrape_lite_tpu.obs import gang

        dirpath = _gang_dir_of(ns.trace)
        out_path = ns.out or os.path.join(dirpath, "merged.json")
        summary = gang.assemble(dirpath, out_path=out_path)
        render_gang_summary(summary)
        if summary["events"]:
            print(file=sys.stdout)
            render(load_trace(out_path), ns.drift_x)
        # an incomplete merge (missing rank, unaligned clock, or a
        # span-less rank) is the federation's drift flag
        return 0 if summary["complete"] else 1

    events = load_trace(ns.trace)
    render(events, ns.drift_x)
    return 0


if __name__ == "__main__":
    sys.exit(main())
