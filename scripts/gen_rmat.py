#!/usr/bin/env python
"""Generate an RMAT edge file for scale runs (the LDBC datagen stand-in
for this sandbox; reference scope `/root/reference/Performance.md:21-50`).

  python scripts/gen_rmat.py --scale 24 --edge_factor 16 \
      --weighted --out /tmp/rmat24.e

Writes `src dst [w]` lines (integer weights 1..10 so the pandas C
writer stays fast).  The CSV WRITE is chunked (bounded text buffers);
generation itself materialises the full src/dst int64 arrays plus a
per-bit float64 draw, so peak memory is ~5x the edge-array bytes
(scale 24 x ef 16: ~20 GiB).

`--delta N` additionally emits a reproducible update stream of N
`a src dst [w]` lines to `--delta_out` (dyn/ docs/DYNAMIC_GRAPHS.md):
fresh RMAT draws over the SAME vertex universe with a separate seed —
additive-only, so they ride the overlay side-path; the serve CLI
ingests the file via --delta_stream and bench.py's dyn lane measures
updates/sec against exactly this distribution.

`--shuffle_ids` applies a seeded permutation (`--shuffle_seed`) to the
vertex id space before writing: raw RMAT ids are degree-correlated
(low ids are hubs — a=0.57 biases every bit toward 0), which makes
any contiguous-range partitioner put the hubs on one shard and every
shard pay that shard's padded Ep (3.2x waste at scale 24,
docs/SCALE_NOTES.md).  The shuffle breaks the correlation
reproducibly, so a 1-D baseline measured on the shuffled file is the
HONEST best-case edge-cut — the comparison the bench `partition2d`
lane runs its 2-D A/B against (docs/PARTITION2D.md).
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--scale", type=int, default=24)
    p.add_argument("--edge_factor", type=int, default=16)
    p.add_argument("--weighted", action="store_true")
    p.add_argument("--seed", type=int, default=7)
    p.add_argument("--out", required=True)
    p.add_argument("--delta", type=int, default=0,
                   help="also emit N additive delta ops ('a src dst "
                        "[w]' lines) to --delta_out")
    p.add_argument("--delta_out", default="",
                   help="path for the --delta update stream")
    p.add_argument("--delta_seed", type=int, default=101)
    p.add_argument("--shuffle_ids", action="store_true",
                   help="apply a seeded permutation to the vertex id "
                        "space (breaks RMAT's degree-id correlation; "
                        "the honest 1-D baseline for 2-D A/Bs)")
    p.add_argument("--shuffle_seed", type=int, default=53)
    args = p.parse_args(argv)
    if args.delta and not args.delta_out:
        p.error("--delta requires --delta_out")

    from bench import rmat_edges

    t0 = time.perf_counter()
    n, src, dst = rmat_edges(args.scale, args.edge_factor, args.seed)
    if args.shuffle_ids:
        perm = shuffle_perm(n, args.shuffle_seed)
        src, dst = perm[src], perm[dst]
        print(f"[gen_rmat] shuffled ids (seed {args.shuffle_seed})",
              flush=True)
    print(f"[gen_rmat] generated {len(src):,} edges over {n:,} vertices "
          f"in {time.perf_counter() - t0:.1f}s", flush=True)

    import pandas as pd

    rng = np.random.default_rng(args.seed + 1)
    t0 = time.perf_counter()
    chunk = 1 << 24
    with open(args.out, "w") as f:
        for lo in range(0, len(src), chunk):
            hi = min(lo + chunk, len(src))
            cols = {"s": src[lo:hi], "d": dst[lo:hi]}
            if args.weighted:
                cols["w"] = rng.integers(1, 11, hi - lo)
            pd.DataFrame(cols).to_csv(
                f, sep=" ", header=False, index=False
            )
    print(f"[gen_rmat] wrote {args.out} "
          f"({os.path.getsize(args.out) / (1 << 30):.2f} GiB) in "
          f"{time.perf_counter() - t0:.1f}s", flush=True)

    if args.delta:
        t0 = time.perf_counter()
        d_src, d_dst = delta_edges(args.scale, args.delta,
                                   args.delta_seed)
        if args.shuffle_ids:
            # the update stream lives in the same (shuffled) id space
            # as the base graph it mutates
            d_src, d_dst = perm[d_src], perm[d_dst]
        rng_dw = np.random.default_rng(args.delta_seed + 1)
        with open(args.delta_out, "w") as f:
            if args.weighted:
                dw = rng_dw.integers(1, 11, args.delta)
                for s, d, x in zip(d_src.tolist(), d_dst.tolist(),
                                   dw.tolist()):
                    f.write(f"a {s} {d} {x}\n")
            else:
                for s, d in zip(d_src.tolist(), d_dst.tolist()):
                    f.write(f"a {s} {d}\n")
        print(f"[gen_rmat] wrote {args.delta} delta op(s) to "
              f"{args.delta_out} in {time.perf_counter() - t0:.1f}s",
              flush=True)
    return 0


def shuffle_perm(n: int, seed: int = 53) -> np.ndarray:
    """The reproducible id permutation behind --shuffle_ids — shared
    with bench.py's partition2d lane so the benched id space IS the
    scripted one."""
    return np.random.default_rng(seed).permutation(n)


def delta_edges(scale: int, n_ops: int, seed: int):
    """Reproducible additive update stream: RMAT draws over the same
    2^scale vertex universe with an independent seed — shared with
    bench.py's dyn lane so the measured distribution IS the scripted
    one."""
    from bench import rmat_edges

    # rmat_edges draws scale*edge_factor-sized arrays; generate the
    # smallest RMAT batch covering n_ops and slice
    ef = max(1, -(-n_ops // (1 << scale)))
    _, src, dst = rmat_edges(scale, ef, seed)
    return src[:n_ops], dst[:n_ops]


if __name__ == "__main__":
    sys.exit(main())
