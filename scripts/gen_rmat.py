#!/usr/bin/env python
"""Generate an RMAT edge file for scale runs (the LDBC datagen stand-in
for this sandbox; reference scope `/root/reference/Performance.md:21-50`).

  python scripts/gen_rmat.py --scale 24 --edge_factor 16 \
      --weighted --out /tmp/rmat24.e

Writes `src dst [w]` lines (integer weights 1..10 so the pandas C
writer stays fast).  The CSV WRITE is chunked (bounded text buffers);
generation itself materialises the full src/dst int64 arrays plus a
per-bit float64 draw, so peak memory is ~5x the edge-array bytes
(scale 24 x ef 16: ~20 GiB).
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--scale", type=int, default=24)
    p.add_argument("--edge_factor", type=int, default=16)
    p.add_argument("--weighted", action="store_true")
    p.add_argument("--seed", type=int, default=7)
    p.add_argument("--out", required=True)
    args = p.parse_args(argv)

    from bench import rmat_edges

    t0 = time.perf_counter()
    n, src, dst = rmat_edges(args.scale, args.edge_factor, args.seed)
    print(f"[gen_rmat] generated {len(src):,} edges over {n:,} vertices "
          f"in {time.perf_counter() - t0:.1f}s", flush=True)

    import pandas as pd

    rng = np.random.default_rng(args.seed + 1)
    t0 = time.perf_counter()
    chunk = 1 << 24
    with open(args.out, "w") as f:
        for lo in range(0, len(src), chunk):
            hi = min(lo + chunk, len(src))
            cols = {"s": src[lo:hi], "d": dst[lo:hi]}
            if args.weighted:
                cols["w"] = rng.integers(1, 11, hi - lo)
            pd.DataFrame(cols).to_csv(
                f, sep=" ", header=False, index=False
            )
    print(f"[gen_rmat] wrote {args.out} "
          f"({os.path.getsize(args.out) / (1 << 30):.2f} GiB) in "
          f"{time.perf_counter() - t0:.1f}s", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
