"""Microbenchmark the SpMV-path primitives on the default backend.

Isolates where a PageRank round's time goes: the gather (x[nbr]), the
sorted segment_sum (scatter side), the fused gather+segment_sum, and a
dense-matmul calibration point for the chip's ceiling.

    python scripts/prim_bench.py [--scale 20] [--ef 16] [--iters 20]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


from _benchutil import sync, timeit  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=int, default=20)
    ap.add_argument("--ef", type=int, default=16)
    ap.add_argument("--iters", type=int, default=20)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    import bench
    from libgrape_lite_tpu.ops.segment import segment_reduce

    n, src, dst = bench.rmat_edges(args.scale, args.ef)
    # symmetrised CSR order like the fragment stores in-edges
    s2 = np.concatenate([src, dst])
    d2 = np.concatenate([dst, src])
    order = np.argsort(s2, kind="stable")
    row = jnp.asarray(s2[order].astype(np.int32))
    col = jnp.asarray(d2[order].astype(np.int32))
    e = len(s2)
    x = jnp.asarray(np.random.default_rng(0).random(n).astype(np.float32))
    vals = jnp.asarray(np.random.default_rng(1).random(e).astype(np.float32))
    print(f"platform={jax.devices()[0].platform} E={e} N={n}", file=sys.stderr)

    res = {}

    tiny = jnp.zeros((8,), jnp.float32)
    noop = jax.jit(lambda v: v + 1)
    res["noop_roundtrip_ms"] = timeit(noop, tiny, iters=args.iters) * 1e3

    gather = jax.jit(lambda x, c: x[c])
    res["gather_ms"] = timeit(gather, x, col, iters=args.iters) * 1e3

    segsum = jax.jit(lambda v, r: segment_reduce(v, r, n, "sum"))
    res["segment_sum_sorted_ms"] = timeit(segsum, vals, row, iters=args.iters) * 1e3

    seg_unsorted = jax.jit(
        lambda v, r: jax.ops.segment_sum(v, r, num_segments=n)
    )
    res["segment_sum_unsorted_ms"] = (
        timeit(seg_unsorted, vals, row, iters=args.iters) * 1e3
    )

    fused = jax.jit(lambda x, c, r: segment_reduce(x[c], r, n, "sum"))
    res["gather_segsum_fused_ms"] = timeit(fused, x, col, row, iters=args.iters) * 1e3

    # gather with SORTED indices (repeat-like): cost of the expand side
    gather_sorted = jax.jit(lambda x, r: x[r])
    res["gather_sorted_ms"] = timeit(gather_sorted, x, row, iters=args.iters) * 1e3

    # one-hot matmul calibration: [8192, 2048] @ [2048, 128] f32
    a = jnp.ones((8192, 2048), jnp.float32)
    b = jnp.ones((2048, 128), jnp.float32)
    mm = jax.jit(lambda a, b: a @ b)
    t = timeit(mm, a, b, iters=args.iters)
    res["matmul_8192x2048x128_ms"] = t * 1e3
    res["matmul_tflops"] = 2 * 8192 * 2048 * 128 / t / 1e12

    # big matmul ceiling: 4096^3
    c1 = jnp.ones((4096, 4096), jnp.float32)
    mm2 = jax.jit(lambda a: a @ a)
    t = timeit(mm2, c1, iters=args.iters)
    res["matmul4096_tflops_f32"] = 2 * 4096**3 / t / 1e12
    c2 = c1.astype(jnp.bfloat16)
    mm3 = jax.jit(lambda a: (a @ a))
    t = timeit(mm3, c2, iters=args.iters)
    res["matmul4096_tflops_bf16"] = 2 * 4096**3 / t / 1e12

    # HBM bandwidth calibration: big copy
    big = jnp.ones((1 << 27,), jnp.float32)  # 512 MB
    cp = jax.jit(lambda v: v * 2.0)
    t = timeit(cp, big, iters=args.iters)
    res["hbm_gbps_rw"] = 2 * big.nbytes / t / 1e9

    # sort calibration (CDLP-style): 33.5M int32 keys
    keys = col.astype(jnp.int32)
    st = jax.jit(lambda k: jnp.sort(k))
    res["sort_e_int32_ms"] = timeit(st, keys, iters=args.iters) * 1e3

    for k, v in res.items():
        res[k] = round(v, 3)
    print(json.dumps(res))


if __name__ == "__main__":
    main()
