#!/usr/bin/env python
"""Analytic cycle/byte model of the pack-gather SpMV pipeline — the
no-hardware fallback for pricing `ops/spmv_pack.py` (VERDICT r3 next
#1: when the tunnel is dead all round, ship cycle estimates derived
from the real plan, not hand-waved constants).

r6: the model CONSUMES the planner's static op-budget ledger
(`spmv_pack.plan_ledger` — exact per-stage vector-ALU op counts
annotated on every BlockPlan at plan time) instead of re-deriving its
own estimates, and independently RECOUNTS the same quantities from the
shipped device stream arrays (segment runs decoded from the flag
planes, route stage heights from the actual index-block shapes).  A
ledger/recount disagreement > 5% fails the script — and bench.py, which
embeds the ledger totals in the BENCH json, fails the same way.

Counting conventions are documented on `spmv_pack._block_op_ledger`;
the ledger prices, per block: the 2-op hub overlay, route moves at
their true operand heights (a composed lane-aligned fold route is ONE
sublane move, a generic Route3 is three), the `flags != 1` compare,
3 ops per span-aware scan stage (ceil(log2(max_seglen)) stages instead
of the unconditional log2(SUB*128) ladder), and the extraction stages.
Cycle rates are explicit v5e assumptions:

  * vector ALU: 1024 f32 lanes/cycle (one (8,128) vreg op/cycle),
  * sublane dynamic_gather: bounded between 1 row/cycle and ~8
    cycles/row (Mosaic unroll) — THE unknown the probe measures,
  * HBM: 819 GB/s, stream bytes counted from the plan's real dtypes.

    python scripts/pack_cost_model.py [--scale 20] [--ef 16]

Prints one JSON line per level plus a summary with optimistic /
pessimistic wall-clock and MTEPS bounds for the bench PageRank round.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

C = 128                       # lane width
VPU_LANES_PER_CYCLE = 8 * C   # one (8,128) vreg op per cycle
CLOCK_HZ = 940e6              # v5e core clock
HBM_BPS = 819e9               # v5e HBM bandwidth
BASELINE_MTEPS = 3500.0       # reference 8xV100 PageRank, per chip
# sublane dynamic_gather rate bracket (slots/cycle): vreg = a full
# (8,128) vector gathered per cycle, row = one 128-lane row per cycle,
# unroll = Mosaic falls back to ~8-way select unrolling
GATHER_RATES = {"vreg": 1024, "row": 128, "unroll": 16}
MISMATCH_TOLERANCE = 0.05


def build_bench_plan(scale: int, ef: int):
    """The ACTUAL multi-level plan for the bench RMAT shard (undirected
    pull: symmetrised CSR-sorted edge list, like bench.py)."""
    from bench import rmat_edges
    from libgrape_lite_tpu.ops.spmv_pack import PackConfig, plan_pack

    n, src, dst = rmat_edges(scale, ef)
    rows = np.concatenate([src, dst])
    cols = np.concatenate([dst, src])
    order = np.argsort(rows, kind="stable")
    rows, cols = rows[order], cols[order]
    vp = 1 << scale
    # from_env, not PackConfig(): the engaged backend resolves
    # GRAPE_PACK_CFG the same way, so the priced plan IS the plan that
    # would run
    return plan_pack(rows, cols, vp, vp, PackConfig.from_env())


def independent_op_estimate(plan) -> dict:
    """Recount ALU ops and gather rows from the SHIPPED device stream
    arrays, independently of the planner's BlockPlan annotations:
    segment runs are decoded from the flag planes, route/extraction
    stage costs from the actual index-block shapes.  This is the
    cross-check that keeps `plan_ledger` honest."""
    from libgrape_lite_tpu.ops.spmv_pack import _stack_blocks

    levels = list(plan.levels)
    if plan.final is not None and plan.final.blocks:
        levels.append(plan.final)
    tot = {"alu_ops": 0, "gather_rows": 0}
    for lv in levels:
        if not lv.blocks:
            continue
        d = _stack_blocks(lv)
        nb = len(lv.blocks)
        slots = lv.cfg.sub * C
        for b in range(nb):
            fl = d["flags"][b].reshape(-1).astype(np.int64)
            ops = 0
            # merge/restore route: one sublane move when composed
            # lane-aligned, else the three stages at their heights
            if "rr" in d:
                ops += slots
            else:
                ops += (d["l1"].shape[-2] + d["s2"].shape[-2]
                        + d["l3"].shape[-2]) * C
            ops += slots  # the flags != 1 compare
            # span-aware scan stages, re-derived from the flag plane
            e = int(((fl & 1) > 0).sum())
            if e:
                starts = np.flatnonzero((fl & 2) > 0)
                runs = np.diff(np.concatenate([starts, [e]]))
                mx = int(runs.max()) if len(runs) else 1
                stages = max(0, math.ceil(math.log2(max(1, mx))))
            else:
                stages = 0
            ops += 3 * stages * slots
            # extraction: compact eroute or final row-range tiles
            if "el1" in d:
                ops += (d["el1"].shape[-2] + d["es2"].shape[-2]
                        + 2 * d["el3"].shape[-2]) * C
            elif "tel1" in d:
                nt = d["tel1"].shape[1]
                ops += nt * (d["tel1"].shape[-2] + d["tes2"].shape[-2]
                             + 2 * d["teval"].shape[-2]) * C
            if "sub_idx" in d:
                ops += 2 * slots          # hub overlay selects
                tot["gather_rows"] += slots
            tot["alu_ops"] += ops
    return tot


def price(totals: dict, edges: int) -> dict:
    """Wall-clock + MTEPS bracket from ledger totals under the explicit
    v5e rates; the gather rate is bracketed (the probe's unknown)."""
    alu_s = totals["alu_ops"] / VPU_LANES_PER_CYCLE / CLOCK_HZ
    hbm_s = totals["hbm_bytes"] / HBM_BPS
    scenarios = {}
    for name, rate in GATHER_RATES.items():
        g_s = totals["gather_rows"] / rate / CLOCK_HZ
        t = max(alu_s + g_s, hbm_s)
        scenarios[name] = dict(
            gather_ms=round(g_s * 1e3, 2),
            round_ms=round(t * 1e3, 2),
            mteps=round(edges / t / 1e6, 0),
            vs_baseline_3500=round(edges / t / 1e6 / BASELINE_MTEPS, 2),
        )
    return dict(t_alu_ms=round(alu_s * 1e3, 2),
                t_hbm_ms=round(hbm_s * 1e3, 2),
                scenarios=scenarios)


def model(scale: int, ef: int) -> dict:
    """Build the bench plan, read its ledger, recount independently,
    and price the round.  Returns the full report dict."""
    from libgrape_lite_tpu.ops.spmv_pack import plan_ledger

    plan = build_bench_plan(scale, ef)
    ledger = plan_ledger(plan)
    recount = independent_op_estimate(plan)
    totals = ledger["totals"]
    e = ledger["edges"]
    mismatch = abs(totals["alu_ops"] - recount["alu_ops"]) / max(
        1, totals["alu_ops"]
    )
    summary = dict(
        edges=e,
        bytes_per_edge=round(totals["hbm_bytes"] / e, 1),
        alu_ops_per_edge=round(totals["alu_ops"] / e, 1),
        gather_slots_per_edge=round(totals["gather_rows"] / e, 2),
        per_stage_ops_per_edge={
            k: round(v / e, 1)
            for k, v in sorted(totals["per_stage"].items())
        },
        ledger_alu_ops=totals["alu_ops"],
        recount_alu_ops=recount["alu_ops"],
        ledger_recount_mismatch=round(mismatch, 4),
        **price(totals, e),
    )
    return dict(levels=ledger["levels"], summary=summary)


def bench_ledger_summary(scale: int, ef: int,
                         cache_dir: str | None = None) -> dict:
    """The summary dict bench.py embeds in the BENCH json, cached on
    disk keyed by (geometry, PackConfig, schema, compose mode) so
    repeated bench runs skip the O(E log E) planner."""
    import dataclasses

    from libgrape_lite_tpu.ft.fingerprint import stable_config_digest
    from libgrape_lite_tpu.ops.spmv_pack import (
        _PLAN_SCHEMA_VERSION,
        PackConfig,
        _compose_enabled,
    )

    import hashlib

    import libgrape_lite_tpu.ops.route3 as _route3
    import libgrape_lite_tpu.ops.spmv_pack as _spmv_pack

    # the cache must be invalidated by the very drift the 5% gate
    # polices: key it by the planner/kernel/model SOURCE as well as the
    # geometry, so a code change recomputes the recount instead of
    # serving a stale green verdict forever
    code_fp = hashlib.sha256()
    for mod_file in (_spmv_pack.__file__, _route3.__file__, __file__):
        with open(mod_file, "rb") as f:
            code_fp.update(f.read())
    key = stable_config_digest({
        "scale": scale, "ef": ef,
        "cfg": dataclasses.asdict(PackConfig.from_env()),
        "schema": _PLAN_SCHEMA_VERSION,
        "compose": _compose_enabled(),
        "code": code_fp.hexdigest(),
    })[:16]
    path = (os.path.join(cache_dir, f"ledger_{key}.json")
            if cache_dir else None)
    if path and os.path.exists(path):
        try:
            with open(path) as f:
                return json.load(f)
        except Exception:
            pass  # corrupt cache entries are recomputed
    summary = model(scale, ef)["summary"]
    if path:
        os.makedirs(cache_dir, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(summary, f)
        os.replace(tmp, path)
    return summary


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=int, default=20)
    ap.add_argument("--ef", type=int, default=16)
    args = ap.parse_args(argv)

    report = model(args.scale, args.ef)
    for lv in report["levels"]:
        print(json.dumps(lv))
    print(json.dumps({"summary": report["summary"]}))
    mismatch = report["summary"]["ledger_recount_mismatch"]
    if mismatch > MISMATCH_TOLERANCE:
        print(
            f"FATAL: planner ledger and independent recount disagree by "
            f"{mismatch:.1%} (> {MISMATCH_TOLERANCE:.0%}) — the op-budget "
            "annotations have drifted from the shipped kernels",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
