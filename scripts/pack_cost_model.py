#!/usr/bin/env python
"""Analytic cycle/byte model of the pack-gather SpMV pipeline — the
no-hardware fallback for pricing `ops/spmv_pack.py` (VERDICT r3 next
#1: when the tunnel is dead all round, ship cycle estimates derived
from the real plan, not hand-waved constants).

Builds the ACTUAL multi-level plan for an RMAT shard at bench geometry
and walks its static metadata (levels, blocks, passes, stream dtypes),
emitting per-stage op and HBM-byte counts and a cycle estimate under
explicit VPU-rate assumptions:

  * vector ALU ops (masks, selects, shift-combine scan stages, adds):
    1024 f32 lanes/cycle (one (8,128) vreg op/cycle on v5e),
  * sublane dynamic_gather: bounded between 1 row/cycle (hardware
    gather, optimistic) and 8 cycles/row (Mosaic unrolls to per-
    sublane selects, pessimistic) — THE unknown the probe measures,
  * HBM: 819 GB/s (v5e), streams counted from the plan's real dtypes.

    python scripts/pack_cost_model.py [--scale 20] [--ef 16]

Prints one JSON line per level plus a summary with optimistic /
pessimistic wall-clock and MTEPS bounds for the bench PageRank round.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

C = 128                       # lane width
VPU_LANES_PER_CYCLE = 8 * C   # one (8,128) vreg op per cycle
CLOCK_HZ = 940e6              # v5e core clock
HBM_BPS = 819e9               # v5e HBM bandwidth


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=int, default=20)
    ap.add_argument("--ef", type=int, default=16)
    args = ap.parse_args(argv)

    from bench import rmat_edges
    from libgrape_lite_tpu.ops.spmv_pack import PackConfig, plan_pack

    n, src, dst = rmat_edges(args.scale, args.ef)
    # undirected pull: symmetrised CSR-sorted edge list, like the bench
    rows = np.concatenate([src, dst])
    cols = np.concatenate([dst, src])
    order = np.argsort(rows, kind="stable")
    rows, cols = rows[order], cols[order]
    vp = 1 << args.scale
    cfg = PackConfig()
    plan = plan_pack(rows, cols, vp, vp, cfg)

    e = len(rows)
    total = dict(alu_ops=0, gather_rows=0, hbm_bytes=0, blocks=0)
    for li, level in enumerate(plan.levels):
        slots = cfg.sub * C
        nb = len(level.blocks)
        scan_stages = int(math.ceil(math.log2(slots)))
        lv = dict(alu_ops=0, gather_rows=0, hbm_bytes=0)
        for b in level.blocks:
            # gather stage: one sublane dynamic_gather row per slot,
            # plus hub-select overlay (2 vector ops/slot)
            if level.has_gather:
                lv["gather_rows"] += slots
                lv["alu_ops"] += 2 * slots
            # route3 stages: lane gather, sublane gather, lane gather
            lv["alu_ops"] += 3 * slots
            # segmented scan: shift + select + add per stage
            lv["alu_ops"] += 3 * scan_stages * slots
            # extraction route or final per-tile routes + adds
            if b.eroute is not None:
                lv["alu_ops"] += 3 * slots + slots
            elif b.tiles:
                for _t in b.tiles:
                    lv["alu_ops"] += 4 * len(b.out_rows)
            # stream table HBM traffic: every static table read once
            for arr in (b.sub_idx, b.hub_sel, b.flags, b.w):
                if arr is not None:
                    lv["hbm_bytes"] += arr.nbytes
        # x-table reads ride VMEM within a pass; charge one x load per
        # gather level per pass window (streamed once from HBM)
        if level.has_gather:
            lv["hbm_bytes"] += min(vp, slots * nb) * 4
        print(json.dumps(dict(
            level=li, blocks=nb, has_gather=level.has_gather, **lv
        )))
        for k in ("alu_ops", "gather_rows", "hbm_bytes"):
            total[k] += lv[k]
        total["blocks"] += nb

    alu_s = total["alu_ops"] / VPU_LANES_PER_CYCLE / CLOCK_HZ
    hbm_s = total["hbm_bytes"] / HBM_BPS
    # the sublane dynamic_gather rate is THE unknown the hardware probe
    # (scripts/pallas_probe.py case 2) resolves; bracket it:
    #   vreg  — a full (8,128) vector gathered per cycle,
    #   row   — one 128-lane row per cycle,
    #   unroll— Mosaic falls back to ~8-way select unrolling
    rates = {"vreg": 1024, "row": 128, "unroll": 16}
    scenarios = {}
    for name, slots_per_cycle in rates.items():
        g_s = total["gather_rows"] / slots_per_cycle / CLOCK_HZ
        t = max(alu_s + g_s, hbm_s)
        scenarios[name] = dict(
            gather_ms=round(g_s * 1e3, 2),
            round_ms=round(t * 1e3, 2),
            mteps=round(e / t / 1e6, 0),
            vs_baseline_3500=round(e / t / 1e6 / 3500, 2),
        )
    summary = dict(
        edges=e,
        bytes_per_edge=round(total["hbm_bytes"] / e, 1),
        alu_ops_per_edge=round(total["alu_ops"] / e, 1),
        gather_slots_per_edge=round(total["gather_rows"] / e, 2),
        t_alu_ms=round(alu_s * 1e3, 2),
        t_hbm_ms=round(hbm_s * 1e3, 2),
        scenarios=scenarios,
    )
    print(json.dumps({"summary": summary}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
