#!/usr/bin/env python
"""Analytic cycle/byte model of the pack-gather SpMV pipeline — the
no-hardware fallback for pricing `ops/spmv_pack.py` (VERDICT r3 next
#1: when the tunnel is dead all round, ship cycle estimates derived
from the real plan, not hand-waved constants).

r6: the model CONSUMES the planner's static op-budget ledger
(`spmv_pack.plan_ledger` — exact per-stage op counts annotated on
every BlockPlan at plan time) instead of re-deriving its own
estimates, and independently RECOUNTS the same quantities from the
shipped device stream arrays (segment runs decoded from the flag or
ps/bk planes, route stage heights from the actual index-block shapes).
A ledger/recount disagreement > 5% on either engine column fails the
script — and bench.py, which embeds the ledger totals in the BENCH
json, fails the same way.

r7: the ledger carries separate `vpu_ops` / `mxu_ops` / `hbm_bytes`
columns.  MXU-scan levels (GRAPE_PACK_SCAN=mxu, the default) replace
the 3-ops-per-stage shift ladder with triangular-matmul prefix sums:
a flat 10 VPU restoration ops per slot plus 3 matmul output planes
priced at the MXU's measured cumsum rate.

Counting conventions are documented on `spmv_pack._block_op_ledger`;
the ledger prices, per block: the 3-op hub overlay (the per-row hub
-group reduce + two shape-matched gathers from the padded hub table;
the planner row-aligns hub slots so the sublane gather's row index is
lane-uniform), route moves at their true operand
heights (a composed lane-aligned fold route is ONE sublane move, a
generic Route3 is three), the `flags != 1` compare on shift levels,
the span-aware shift ladder or the flat mxu restoration, and the
extraction stages (validity select dropped on non-final levels).
Cycle rates are explicit v5e assumptions:

  * vector ALU: 1024 f32 lanes/cycle (one (8,128) vreg op/cycle),
  * MXU: 0.008 cyc per matmul output element at B >= 512 (the
    verified [B,128] @ tri[128,128] Mosaic lowering),
  * sublane dynamic_gather: bounded between 1 row/cycle and ~8
    cycles/row (Mosaic unroll) — THE unknown the probe measures,
  * HBM: 819 GB/s, stream bytes counted from the plan's real dtypes.

    python scripts/pack_cost_model.py [--scale 20] [--ef 16]

Prints one JSON line per level plus a summary with optimistic /
pessimistic wall-clock and MTEPS bounds for the bench PageRank round.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

C = 128                       # lane width
# Pricing rates come from the shared RateProfile (ops/calibration.py)
# — the pinned default carries exactly the hand-measured v5e numbers
# this script used to inline, and a fitted profile (GRAPE_RATE_PROFILE)
# re-prices every surface here without touching the recount
# CONVENTIONS below (the recounts compare op COUNTS; rates cancel in
# the mismatch, so sharing rates keeps the gate honest).
from libgrape_lite_tpu.ops.calibration import (  # noqa: E402
    active_profile,
    default_profile,
)

VPU_LANES_PER_CYCLE = default_profile().vpu_lanes_per_cycle
CLOCK_HZ = default_profile().clock_hz
HBM_BPS = default_profile().hbm_bps
BASELINE_MTEPS = 3500.0       # reference 8xV100 PageRank, per chip
GATHER_RATES = default_profile().gather_rates
MXU_CYC_PER_ELEM = default_profile().mxu_cyc_per_elem
MISMATCH_TOLERANCE = 0.05


def build_bench_plan(scale: int, ef: int):
    """The ACTUAL multi-level plan for the bench RMAT shard (undirected
    pull: symmetrised CSR-sorted edge list, like bench.py)."""
    from bench import rmat_edges
    from libgrape_lite_tpu.ops.spmv_pack import PackConfig, plan_pack

    n, src, dst = rmat_edges(scale, ef)
    rows = np.concatenate([src, dst])
    cols = np.concatenate([dst, src])
    order = np.argsort(rows, kind="stable")
    rows, cols = rows[order], cols[order]
    vp = 1 << scale
    # from_env, not PackConfig(): the engaged backend resolves
    # GRAPE_PACK_CFG the same way, so the priced plan IS the plan that
    # would run
    return plan_pack(rows, cols, vp, vp, PackConfig.from_env())


def _decode_shift_stages(fl: np.ndarray) -> int:
    """Span-aware scan stage count, re-derived from one block's flag
    plane (the independent decode both recounts share)."""
    e = int(((fl & 1) > 0).sum())
    if not e:
        return 0
    starts = np.flatnonzero((fl & 2) > 0)
    runs = np.diff(np.concatenate([starts, [e]]))
    mx = int(runs.max()) if len(runs) else 1
    return max(0, math.ceil(math.log2(max(1, mx))))


def _recount_level(d: dict, nb: int, sub: int, tot: dict,
                   stage_override=None) -> None:
    """Recount ONE level's blocks from its stacked stream dict
    ([nb, ...] leading block axis) into `tot` — the shared core of the
    single-plan and multi-plan (2-D tile) recounts, so the two gates
    can never codify different conventions.

    `stage_override[b]`, when given, replaces the per-block flag
    decode for shift-scan stages: under shard_map every shard runs ONE
    traced program, so plan_pack_multi unifies each block's stages to
    the cross-shard max before ledgering — the multi recount must
    price the unified count (decoded independently per shard, then
    maxed by the caller), not each shard's own."""
    slots = sub * C
    for b in range(nb):
        ops = 0
        # merge/restore route: one sublane move when composed
        # lane-aligned, else the three stages at their heights
        if "rr" in d:
            ops += slots
        else:
            ops += (d["l1"].shape[-2] + d["s2"].shape[-2]
                    + d["l3"].shape[-2]) * C
        if "ps" in d:
            # mxu level: flat restoration cost — 10 VPU ops and 3
            # matmul output planes per slot, HARDCODED here as the
            # independent codification of the documented
            # convention (importing spmv_pack's constants would
            # make this gate tautological: a planner-side constant
            # drift must trip the 5% mismatch, not follow it).
            # The ps/bk planes are also decoded for consistency:
            # the derived start flag (ps == lane & bk == 0) must
            # mark at least one start per block that ships edges.
            ops += 10 * slots
            tot["mxu_ops"] += 3 * slots
            ps = d["ps"][b].astype(np.int64)
            bk = d["bk"][b].astype(np.int64)
            lane = np.arange(C, dtype=np.int64)[None, :]
            f0 = (ps == lane) & (bk == 0)
            assert f0.any(), (
                "mxu restoration planes decode to zero segment "
                "starts — ps/bk are corrupt"
            )
        else:
            fl = d["flags"][b].reshape(-1).astype(np.int64)
            ops += slots  # the flags != 1 compare
            # span-aware scan stages, re-derived from the flags (or
            # the caller's cross-shard unified count — see docstring)
            if stage_override is not None:
                stages = stage_override[b]
            else:
                stages = _decode_shift_stages(fl)
            ops += 3 * stages * slots
        # extraction: compact eroute (no validity select) or
        # final row-range tiles (select survives: tile outputs
        # sum straight into the dense result)
        if "el1" in d:
            ops += (d["el1"].shape[-2] + d["es2"].shape[-2]
                    + d["el3"].shape[-2]) * C
        elif "tel1" in d:
            nt = d["tel1"].shape[1]
            ops += nt * (d["tel1"].shape[-2] + d["tes2"].shape[-2]
                         + 2 * d["teval"].shape[-2]) * C
        if "gidx" in d:
            # hub-group reduce + the two hub-table gathers
            ops += 3 * slots
            tot["gather_rows"] += slots
        tot["vpu_ops"] += ops


def independent_op_estimate(plan) -> dict:
    """Recount VPU ops, MXU elems and gather rows from the SHIPPED
    device stream arrays, independently of the planner's BlockPlan
    annotations: segment runs are decoded from the flag planes (or,
    on mxu levels, from the ps/bk restoration planes via the derived
    start flag `ps == lane & bk == 0`), route/extraction stage costs
    from the actual index-block shapes.  This is the cross-check that
    keeps `plan_ledger` honest."""
    from libgrape_lite_tpu.ops.spmv_pack import _stack_blocks

    levels = list(plan.levels)
    if plan.final is not None and plan.final.blocks:
        levels.append(plan.final)
    tot = {"vpu_ops": 0, "mxu_ops": 0, "gather_rows": 0}
    for lv in levels:
        if not lv.blocks:
            continue
        _recount_level(_stack_blocks(lv), len(lv.blocks), lv.cfg.sub,
                       tot)
    return tot


def independent_multi_estimate(mplan) -> dict:
    """`independent_op_estimate` for a MultiPackPlan — the form every
    per-tile (2-D vertex-cut) and per-shard plan ships in.  The level
    streams ride stacked as `L{i}_{name}` [fnum, nb, ...] host arrays;
    the recount decodes every shard's slice with the SAME per-level
    core as the single-plan gate (r10)."""
    tot = {"vpu_ops": 0, "mxu_ops": 0, "gather_rows": 0}
    for i, skel in enumerate(mplan.skels):
        prefix = f"L{i}_"
        names = [
            k[len(prefix):] for k in mplan.host_streams
            if k.startswith(prefix)
        ]
        if not names:
            continue
        shards = [
            {n: mplan.host_streams[prefix + n][f] for n in names}
            for f in range(mplan.fnum)
        ]
        # shift-scan levels: every shard runs ONE traced program, so
        # the planner unifies each block's stage count to the
        # cross-shard max (spmv_pack.plan_pack_multi) — decode each
        # shard's stages independently, then price the unified max
        # (extra stages are bit-exact no-ops for the shard that
        # needed fewer, but they execute and the ledger bills them)
        stage_override = None
        if "flags" in shards[0]:
            stage_override = [
                max(
                    _decode_shift_stages(
                        d["flags"][b].reshape(-1).astype(np.int64)
                    )
                    for d in shards
                )
                for b in range(skel.nb)
            ]
        for d in shards:
            _recount_level(d, skel.nb, mplan.cfg.sub, tot,
                           stage_override=stage_override)
    return tot


def tile_plan_recount(mplan) -> dict:
    """The 2-D tile-plan gate (bench `partition2d` lane): the per-tile
    MultiPackPlan's ledger totals vs the independent recount from its
    shipped streams, mismatch gated at MISMATCH_TOLERANCE exactly like
    the 1-D op-budget ledger."""
    rec = independent_multi_estimate(mplan)
    totals = (mplan.ledger or {}).get("totals")
    if not totals:
        return {"tile_recount_mismatch": 1.0,
                "reason": "tile plan ships no ledger"}
    mismatch = max(
        abs(totals[k] - rec[k]) / max(1, totals[k])
        for k in ("vpu_ops", "mxu_ops")
    )
    return {
        "tile_recount_mismatch": round(mismatch, 4),
        "ledger_vpu_ops": totals["vpu_ops"],
        "recount_vpu_ops": rec["vpu_ops"],
        "ledger_mxu_ops": totals["mxu_ops"],
        "recount_mxu_ops": rec["mxu_ops"],
    }


def spgemm_recount(plan) -> dict:
    """The r11 masked-SpGEMM gate (bench `spgemm` lane): the plan's
    op-budget ledger vs an independent recount from the SHIPPED device
    streams.  The real item count is decoded from the `valid` planes
    (never from `plan.items` — that is a planner annotation), the
    per-item plane costs are HARDCODED here as the independent
    codification of the documented conventions (importing
    spgemm_pack's constants would make the gate tautological: 10 VPU
    planes of 128 lanes, one 128-elem MXU count-reduce row and two
    bitmap row fetches per item), and HBM bytes come from the actual
    array sizes.  Mismatch gated at MISMATCH_TOLERANCE by bench.py
    exactly like the SpMV op-budget ledger."""
    st = plan.host_streams
    if st is None:
        return {"spgemm_recount_mismatch": 1.0,
                "reason": "plan_only plan ships no streams"}
    valid = np.asarray(st["valid"]).astype(np.int64)
    items = int(valid.sum())
    # consistency decode: every valid item's rows/tile must be
    # addressable in the shipped sub-bitmap — corrupt streams must
    # fail loudly, not price as zero
    bm = np.asarray(st["bm"])
    kt = np.asarray(st["kt"])
    for f in range(valid.shape[0]):
        sel = valid[f] > 0
        if not sel.any():
            continue
        assert int(np.asarray(st["vrow"])[f, sel].max()) < bm.shape[1], \
            "spgemm item references a row beyond the shipped bitmap"
        assert int(kt[f, sel].max()) * 4 < bm.shape[2], \
            "spgemm item references a K-tile beyond the shipped bitmap"
    rec = {
        "vpu_ops": 10 * 128 * items,
        "mxu_ops": 128 * items,
        "gather_rows": 2 * items,
        "hbm_bytes": sum(int(np.asarray(a).nbytes) for a in st.values()),
    }
    totals = (plan.ledger or {}).get("totals")
    if not totals:
        return {"spgemm_recount_mismatch": 1.0,
                "reason": "plan ships no ledger"}
    mismatch = max(
        abs(totals[k] - rec[k]) / max(1, totals[k])
        for k in ("vpu_ops", "mxu_ops", "hbm_bytes")
    )
    return {
        "spgemm_recount_mismatch": round(mismatch, 4),
        "items_recounted": items,
        "ledger_vpu_ops": totals["vpu_ops"],
        "recount_vpu_ops": rec["vpu_ops"],
        "ledger_mxu_ops": totals["mxu_ops"],
        "recount_mxu_ops": rec["mxu_ops"],
        "ledger_hbm_bytes": totals["hbm_bytes"],
        "recount_hbm_bytes": rec["hbm_bytes"],
    }


def price(totals: dict, edges: int, profile=None) -> dict:
    """Wall-clock + MTEPS bracket from ledger totals under the shared
    profile rates (default: the active RateProfile); the gather rate
    is bracketed (the probe's unknown).  VPU, MXU and gather time are
    summed (no overlap assumed — the conservative bound); HBM streams
    concurrently."""
    p = profile or active_profile()
    vpu_s = totals["vpu_ops"] / p.vpu_lanes_per_cycle / p.clock_hz
    mxu_s = totals["mxu_ops"] * p.mxu_cyc_per_elem / p.clock_hz
    hbm_s = totals["hbm_bytes"] / p.hbm_bps
    scenarios = {}
    for name, rate in p.gather_rates.items():
        g_s = totals["gather_rows"] / rate / p.clock_hz
        t = max(vpu_s + mxu_s + g_s, hbm_s)
        scenarios[name] = dict(
            gather_ms=round(g_s * 1e3, 2),
            round_ms=round(t * 1e3, 2),
            mteps=round(edges / t / 1e6, 0),
            vs_baseline_3500=round(edges / t / 1e6 / BASELINE_MTEPS, 2),
        )
    return dict(t_vpu_ms=round(vpu_s * 1e3, 2),
                t_mxu_ms=round(mxu_s * 1e3, 2),
                t_hbm_ms=round(hbm_s * 1e3, 2),
                scenarios=scenarios)


def model(scale: int, ef: int) -> dict:
    """Build the bench plan, read its ledger, recount independently,
    and price the round.  Returns the full report dict."""
    from libgrape_lite_tpu.ops.spmv_pack import plan_ledger

    plan = build_bench_plan(scale, ef)
    ledger = plan_ledger(plan)
    recount = independent_op_estimate(plan)
    totals = ledger["totals"]
    e = ledger["edges"]
    mismatch = max(
        abs(totals[k] - recount[k]) / max(1, totals[k])
        for k in ("vpu_ops", "mxu_ops")
    )
    summary = dict(
        edges=e,
        bytes_per_edge=round(totals["hbm_bytes"] / e, 1),
        vpu_ops_per_edge=round(totals["vpu_ops"] / e, 1),
        mxu_elems_per_edge=round(totals["mxu_ops"] / e, 1),
        gather_slots_per_edge=round(totals["gather_rows"] / e, 2),
        per_stage_ops_per_edge={
            k: round(v / e, 1)
            for k, v in sorted(totals["per_stage"].items())
        },
        ledger_vpu_ops=totals["vpu_ops"],
        recount_vpu_ops=recount["vpu_ops"],
        ledger_mxu_ops=totals["mxu_ops"],
        recount_mxu_ops=recount["mxu_ops"],
        ledger_recount_mismatch=round(mismatch, 4),
        **price(totals, e),
    )
    return dict(levels=ledger["levels"], summary=summary)


def bench_ledger_summary(scale: int, ef: int,
                         cache_dir: str | None = None) -> dict:
    """The summary dict bench.py embeds in the BENCH json, cached on
    disk keyed by (geometry, PackConfig, schema, compose mode) so
    repeated bench runs skip the O(E log E) planner."""
    import dataclasses

    from libgrape_lite_tpu.ft.fingerprint import stable_config_digest
    from libgrape_lite_tpu.ops.spmv_pack import (
        _PLAN_SCHEMA_VERSION,
        PackConfig,
        _compose_enabled,
        _scan_mode,
    )

    import hashlib

    import libgrape_lite_tpu.ops.route3 as _route3
    import libgrape_lite_tpu.ops.spmv_pack as _spmv_pack

    # the cache must be invalidated by the very drift the 5% gate
    # polices: key it by the planner/kernel/model SOURCE as well as the
    # geometry, so a code change recomputes the recount instead of
    # serving a stale green verdict forever
    code_fp = hashlib.sha256()
    for mod_file in (_spmv_pack.__file__, _route3.__file__, __file__):
        with open(mod_file, "rb") as f:
            code_fp.update(f.read())
    key = stable_config_digest({
        "scale": scale, "ef": ef,
        "cfg": dataclasses.asdict(PackConfig.from_env()),
        "schema": _PLAN_SCHEMA_VERSION,
        "compose": _compose_enabled(),
        "scan": _scan_mode(),
        "code": code_fp.hexdigest(),
    })[:16]
    path = (os.path.join(cache_dir, f"ledger_{key}.json")
            if cache_dir else None)
    if path and os.path.exists(path):
        try:
            with open(path) as f:
                return json.load(f)
        except Exception:
            pass  # corrupt cache entries are recomputed
    summary = model(scale, ef)["summary"]
    if path:
        os.makedirs(cache_dir, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(summary, f)
        os.replace(tmp, path)
    return summary


def overlap_recount(plan) -> dict:
    """The exchange-overlap term (r9, parallel/pipeline.py), recounted
    from the SHIPPED pipeline plan — the same discipline as
    `independent_op_estimate`: the planner's boundary/interior stats
    are annotations, so the boundary/interior edge counts are re-read
    from the arrays that actually dispatch (the `pl_{b,i}_val`
    validity planes on the XLA path, the sub-plan ledgers on the pack
    path) and the exchange bytes from the plan's mode + geometry, NOT
    from `plan.stats`.  Returns the recounted overlap model plus
    `overlap_recount_mismatch`, gated at MISMATCH_TOLERANCE by
    bench.py exactly like the op-budget ledger."""
    from libgrape_lite_tpu.parallel.pipeline import overlap_model

    if plan.pack_b is not None:
        led_b = plan.pack_b.ledger()
        led_i = plan.pack_i.ledger()
        b_edges = int(led_b["edges"]) if led_b else 0
        i_edges = int(led_i["edges"]) if led_i else 0
    else:
        b_edges = int(np.asarray(
            plan.host_entries["pl_b_val"]).sum())
        i_edges = int(np.asarray(
            plan.host_entries["pl_i_val"]).sum())
    # exchange bytes from mode + geometry (f32 payload convention,
    # the same itemsize the shared mirror ledger prices)
    if plan.mode == "mirror":
        xbytes = plan.fnum * plan.m * 4
    else:
        xbytes = plan.fnum * plan.vp * 4
    modeled = overlap_model(b_edges, i_edges, xbytes, plan.ops_per_edge)
    t = plan.stats.get("totals", {})
    planned = overlap_model(
        t.get("boundary_edges", 0), t.get("interior_edges", 0),
        plan.exchange_bytes, plan.ops_per_edge,
    )
    mismatch = max(
        abs(b_edges - t.get("boundary_edges", 0))
        / max(1, t.get("boundary_edges", 0)),
        abs(i_edges - t.get("interior_edges", 0))
        / max(1, t.get("interior_edges", 0)),
        abs(xbytes - plan.exchange_bytes)
        / max(1, plan.exchange_bytes),
        abs(modeled["hidden_frac"] - planned["hidden_frac"])
        / max(1e-9, planned["hidden_frac"] or 1.0),
    )
    return {
        "boundary_edges": b_edges,
        "interior_edges": i_edges,
        "exchange_bytes": xbytes,
        "modeled_hidden_frac": modeled["hidden_frac"],
        "modeled_round_speedup": modeled["round_speedup"],
        "overlap_recount_mismatch": round(mismatch, 4),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=int, default=20)
    ap.add_argument("--ef", type=int, default=16)
    args = ap.parse_args(argv)

    report = model(args.scale, args.ef)
    for lv in report["levels"]:
        print(json.dumps(lv))
    print(json.dumps({"summary": report["summary"]}))
    mismatch = report["summary"]["ledger_recount_mismatch"]
    if mismatch > MISMATCH_TOLERANCE:
        print(
            f"FATAL: planner ledger and independent recount disagree by "
            f"{mismatch:.1%} (> {MISMATCH_TOLERANCE:.0%}) — the op-budget "
            "annotations have drifted from the shipped kernels",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
