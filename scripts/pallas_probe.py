"""Probe Mosaic/Pallas primitive throughput on the real TPU.

Measures the building blocks for a Pallas SpMV (results print one line
per case, cheap cases first):
  1. lane dynamic_gather  out[i,j] = tab[idx[i,j]]   (128-entry table)
  2. sublane dynamic_gather out[i,j] = tab[idx[i,j], j]  (S-row tables)
  3. VPU stream + in-tile cumsum rate
  4. dense matvec rate (the MXU N=1 reference point)

    python scripts/pallas_probe.py [--e_log 22] [--block 512]
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import sys
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


from _benchutil import sync, timeit  # noqa: E402,F401


def emit(name, t_s, e):
    print(
        json.dumps(
            {
                "case": name,
                "ms": round(t_s * 1e3, 3),
                "gelem_s": round(e / t_s / 1e9, 2),
            }
        ),
        flush=True,
    )


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--e_log", type=int, default=22)
    ap.add_argument("--block", type=int, default=512)  # sublane rows / block
    ap.add_argument("--iters", type=int, default=5)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental import pallas as pl

    E = 1 << args.e_log
    B = args.block  # sublane rows per program; lanes always 128
    rows = E // 128
    assert rows % B == 0 and rows >= B, (
        f"E=2^{args.e_log} gives {rows} sublane rows; --block must divide it"
    )
    grid = rows // B
    rng = np.random.default_rng(0)
    print(f"E={E} grid={grid} block=({B},128)", file=sys.stderr)

    # ---- VPU stream baseline ----
    a_np = rng.random((rows, 128)).astype(np.float32)
    a = jnp.asarray(a_np)

    def vpu_kernel(a_ref, out_ref):
        out_ref[...] = a_ref[...] * 2.0 + 1.0

    @jax.jit
    def vpu(a):
        return pl.pallas_call(
            vpu_kernel,
            grid=(grid,),
            in_specs=[pl.BlockSpec((B, 128), lambda i: (i, 0))],
            out_specs=pl.BlockSpec((B, 128), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((rows, 128), jnp.float32),
        )(a)

    emit("vpu_stream", timeit(vpu, a, iters=args.iters), E)

    # ---- 1. lane gather from a 128-entry table ----
    idx_np = rng.integers(0, 128, size=(rows, 128)).astype(np.int32)
    idx = jnp.asarray(idx_np)
    tab128 = jnp.asarray(rng.random((8, 128)).astype(np.float32))

    def lane_kernel(tab_ref, idx_ref, out_ref):
        tab = tab_ref[0:1]  # [1, 128]
        idx = idx_ref[...]  # [B, 128]
        tab_b = jnp.broadcast_to(tab, idx.shape)
        out_ref[...] = jnp.take_along_axis(tab_b, idx, axis=1)

    @jax.jit
    def lane_gather(tab, idx):
        return pl.pallas_call(
            lane_kernel,
            grid=(grid,),
            in_specs=[
                pl.BlockSpec((8, 128), lambda i: (0, 0)),
                pl.BlockSpec((B, 128), lambda i: (i, 0)),
            ],
            out_specs=pl.BlockSpec((B, 128), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((rows, 128), jnp.float32),
        )(tab128, idx)

    try:
        emit("lane_gather_t128", timeit(lane_gather, tab128, idx, iters=args.iters), E)
    except Exception as ex:
        print(f"lane_gather_t128 FAIL {type(ex).__name__}: {str(ex)[:300]}",
              flush=True)

    # ---- 2. sublane gather: tab [S, 128], out[i,j] = tab[idx[i,j], j] ----
    for S in (8, 64, 512, 8192):
        idxs = jnp.asarray(
            rng.integers(0, S, size=(rows, 128)).astype(np.int32)
        )
        tabs = jnp.asarray(rng.random((S, 128)).astype(np.float32))

        def sub_kernel(tab_ref, idx_ref, out_ref):
            tab = tab_ref[...]  # [S, 128]
            idx = idx_ref[...]  # [B, 128]
            # out[i, j] = tab[idx[i, j], j] — gather along sublanes,
            # batched along lanes
            out_ref[...] = jnp.take_along_axis(tab, idx, axis=0)

        @jax.jit
        def sub_gather(tab, idx, S=S):
            return pl.pallas_call(
                sub_kernel,
                grid=(grid,),
                in_specs=[
                    pl.BlockSpec((S, 128), lambda i: (0, 0)),
                    pl.BlockSpec((B, 128), lambda i: (i, 0)),
                ],
                out_specs=pl.BlockSpec((B, 128), lambda i: (i, 0)),
                out_shape=jax.ShapeDtypeStruct((rows, 128), jnp.float32),
            )(tab, idx)

        try:
            emit(f"sublane_gather_S{S}",
                 timeit(sub_gather, tabs, idxs, iters=args.iters), E)
        except Exception as ex:
            print(
                f"sublane_gather_S{S} FAIL {type(ex).__name__}: {str(ex)[:300]}",
                flush=True,
            )

    # ---- 3. in-tile cumsum along lanes ----
    def cs_kernel(a_ref, out_ref):
        out_ref[...] = jnp.cumsum(a_ref[...], axis=1)

    @jax.jit
    def cs(a):
        return pl.pallas_call(
            cs_kernel,
            grid=(grid,),
            in_specs=[pl.BlockSpec((B, 128), lambda i: (i, 0))],
            out_specs=pl.BlockSpec((B, 128), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((rows, 128), jnp.float32),
        )(a)

    try:
        emit("cumsum_lanes", timeit(cs, a, iters=args.iters), E)
    except Exception as ex:
        print(f"cumsum_lanes FAIL {type(ex).__name__}: {str(ex)[:300]}",
              flush=True)

    # ---- 4. dense matvec (XLA) ----
    m = jnp.asarray(rng.random((8192, 8192)).astype(np.float32))
    v = jnp.asarray(rng.random((8192,)).astype(np.float32))
    mv = jax.jit(lambda m, v: m @ v)
    emit("dense_matvec_8192_f32", timeit(mv, m, v, iters=args.iters),
         8192 * 8192)


if __name__ == "__main__":
    main()
