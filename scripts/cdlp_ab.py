"""CDLP wide-path A/B: dynamic label-universe compression vs the
variadic wide sort (VERDICT r4 next #2 'done' criterion).

Builds RMAT at --scale over --fnum shards (a geometry where the STATIC
packed key cannot fit: rank_bits + src_bits > 32), runs a few CDLP
rounds twice — once with the dynamic-compression path (default at this
geometry) and once with the wide sort forced — and prints per-round
wall clock plus the per-round distinct-label counts so the cond's
branch choice is visible.  Reference counterpart: the cdlp vs cdlp_opt
split (`examples/analytical_apps/cdlp/cdlp_opt.h`).

Run on CPU mesh:
  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python scripts/cdlp_ab.py --scale 20 --fnum 8
On TPU (single chip): python scripts/cdlp_ab.py --scale 20 --fnum 1
"""

from __future__ import annotations

import argparse
import json
import sys
import time

sys.path.insert(0, ".")

import numpy as np


def planted_edges(scale: int, edge_factor: int, n_comm: int, seed: int = 11):
    """Planted-partition graph: n=2^scale vertices in n_comm communities,
    ~90% of edges intra-community — the coalescence profile of LDBC
    datagen's person-knows-person graphs (community-structured), unlike
    RMAT whose ~0.34n fragmented tail pins the live label universe at
    O(n)."""
    n = 1 << scale
    e = n * edge_factor
    rng = np.random.default_rng(seed)
    comm = rng.integers(0, n_comm, n)
    order = np.argsort(comm, kind="stable")
    # vertices grouped by community; intra edges pick endpoints within
    # the group via its contiguous index range
    starts = np.searchsorted(comm[order], np.arange(n_comm))
    ends = np.append(starts[1:], n)
    src_c = rng.integers(0, n_comm, e)
    intra = rng.random(e) < 0.9
    lo, hi = starts[src_c], np.maximum(ends[src_c], starts[src_c] + 1)
    u = order[(lo + rng.integers(0, 1 << 62, e) % (hi - lo))]
    v_in = order[(lo + rng.integers(0, 1 << 62, e) % (hi - lo))]
    v_out = rng.integers(0, n, e)
    v = np.where(intra, v_in, v_out)
    return n, u.astype(np.int64), v.astype(np.int64)


def build(scale: int, edge_factor: int, fnum: int, graph: str, n_comm: int):
    import bench
    from libgrape_lite_tpu.fragment.edgecut import ShardedEdgecutFragment
    from libgrape_lite_tpu.parallel.comm_spec import CommSpec
    from libgrape_lite_tpu.utils.types import LoadStrategy
    from libgrape_lite_tpu.vertex_map.partitioner import SegmentedPartitioner
    from libgrape_lite_tpu.vertex_map.vertex_map import VertexMap

    if graph == "planted":
        n, src, dst = planted_edges(scale, edge_factor, n_comm)
    else:
        n, src, dst = bench.rmat_edges(scale, edge_factor, seed=11)
    oids = np.arange(n, dtype=np.int64)
    vm = VertexMap.build(
        oids, SegmentedPartitioner(fnum, oids), idxer_type="sorted_array"
    )
    frag = ShardedEdgecutFragment.build(
        CommSpec(fnum=fnum), vm, src, dst, None,
        directed=False, load_strategy=LoadStrategy.kOnlyOut,
    )
    return n, frag


def run(app_factory, frag, rounds: int):
    """Compile once (untimed), then time each superstep individually
    via the stepwise building blocks (per-round wall clock is the A/B
    quantity; the fused while_loop hides it)."""
    import jax

    from libgrape_lite_tpu.worker.worker import Worker

    app = app_factory()
    w = Worker(app, frag)
    state = w._place_state(app.init_state(frag, max_round=rounds))
    peval_fn = w._compile_single_step("peval", state)
    inc_fn = w._compile_single_step("inceval", state)
    # warm both compiles out of the timed region
    st_w, _ = jax.block_until_ready(peval_fn(frag.dev, state))
    jax.block_until_ready(inc_fn(frag.dev, st_w))

    times = []
    t0 = time.perf_counter()
    st, active = jax.block_until_ready(peval_fn(frag.dev, state))
    times.append(time.perf_counter() - t0)
    r = 1
    while int(active) > 0 and r < rounds:
        t0 = time.perf_counter()
        st, active = jax.block_until_ready(inc_fn(frag.dev, st))
        times.append(time.perf_counter() - t0)
        r += 1
    w._result_state = st
    return w.result_values(), times, sum(times)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=int, default=20)
    ap.add_argument("--edge_factor", type=int, default=16)
    ap.add_argument("--fnum", type=int, default=8)
    ap.add_argument("--rounds", type=int, default=4)
    ap.add_argument("--graph", choices=["rmat", "planted"], default="rmat")
    ap.add_argument("--n_comm", type=int, default=4096)
    args = ap.parse_args()

    from libgrape_lite_tpu.models import CDLP

    n, frag = build(args.scale, args.edge_factor, args.fnum, args.graph,
                    args.n_comm)
    rank_bits = int(np.ceil(np.log2(frag.vp * frag.fnum + 2)))
    src_bits = int(np.ceil(np.log2(frag.vp + 2)))
    assert rank_bits + src_bits > 32, (
        "geometry fits the static pack; A/B is vacuous here"
    )
    print(
        f"[cdlp_ab] n={n:,} vp={frag.vp} fnum={frag.fnum} "
        f"src_bits={src_bits} dyn_budget=2^{32 - src_bits}",
        file=sys.stderr,
    )

    report = {"scale": args.scale, "fnum": args.fnum, "graph": args.graph,
              "rounds": args.rounds, "dyn_budget": 1 << (32 - src_bits),
              "variants": {}}

    for name, force_wide in (("dynamic", False), ("wide", True)):
        def mk(fw=force_wide):
            app = CDLP()
            app._force_dynamic = True
            app._force_wide = fw
            return app

        res, times, total = run(mk, frag, args.rounds)
        report["variants"][name] = {
            "round_s": [round(t, 4) for t in times],
            "total_s": round(total, 3),
        }
        print(f"[cdlp_ab] {name}: rounds={times} total={total:.3f}s",
              file=sys.stderr)
        if name == "dynamic":
            ref = res
        else:
            assert np.array_equal(np.asarray(ref), np.asarray(res)), (
                "dynamic and wide paths diverged"
            )
            report["parity"] = True

    print(json.dumps(report))


if __name__ == "__main__":
    main()
