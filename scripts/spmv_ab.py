"""A/B: strict-tile Pallas SpMV vs XLA segment-sum, per graph shape.

Run ON TPU (the whole point — interpret-mode numbers are meaningless):

    python scripts/spmv_ab.py [--scale 20] [--tile 2048]

Prints one JSON line per (graph, path) and a crossover verdict; commit
the output into docs/PERF_NOTES.md (VERDICT r1 next-round item 2 wants
the measured crossover table in-repo).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def bench_one(name, src_np, vals_np, vp, tile, iters=20):
    import jax
    import jax.numpy as jnp

    from libgrape_lite_tpu.ops.segment import segment_reduce
    from libgrape_lite_tpu.ops.spmv import (
        plan_tiles,
        spmv_strict,
        strict_worthwhile,
    )

    src = jnp.asarray(src_np)
    vals = jnp.asarray(vals_np)
    row_lo, rmax, num_tiles = plan_tiles(src_np, tile, vp)

    xla = jax.jit(lambda v, s: segment_reduce(v, s, vp, "sum"))
    xla(vals, src).block_until_ready()
    t0 = time.perf_counter()
    for _ in range(iters):
        r_xla = xla(vals, src)
    r_xla.block_until_ready()
    t_xla = (time.perf_counter() - t0) / iters

    spmv_strict(vals, src, row_lo, vp, tile, rmax).block_until_ready()
    t0 = time.perf_counter()
    for _ in range(iters):
        r_pl = spmv_strict(vals, src, row_lo, vp, tile, rmax)
    r_pl.block_until_ready()
    t_pl = (time.perf_counter() - t0) / iters

    import numpy as np

    err = float(
        np.abs(np.asarray(r_pl) - np.asarray(r_xla)).max()
        / max(np.abs(np.asarray(r_xla)).max(), 1e-9)
    )
    rec = {
        "graph": name,
        "edges": len(src_np),
        "rmax": rmax,
        "tile": tile,
        "xla_ms": round(t_xla * 1e3, 4),
        "pallas_ms": round(t_pl * 1e3, 4),
        "speedup": round(t_xla / t_pl, 3),
        "planner_says": "pallas" if strict_worthwhile(rmax, tile) else "xla",
        "rel_err": err,
    }
    print(json.dumps(rec), flush=True)
    return rec


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=int, default=20)
    ap.add_argument("--edge_factor", type=int, default=16)
    ap.add_argument("--tile", type=int, default=2048)
    ap.add_argument("--platform", default="default")
    args = ap.parse_args()

    if args.platform != "default":
        import jax

        jax.config.update("jax_platforms", args.platform)

    import numpy as np

    import bench
    from libgrape_lite_tpu.graph.csr import build_csr

    n, src, dst = bench.rmat_edges(args.scale, args.edge_factor)
    rng = np.random.default_rng(0)

    # hub-heavy: RMAT sorted by row (CSR order)
    order = np.argsort(src, kind="stable")
    src_s = src[order].astype(np.int32)
    vals = rng.normal(size=len(src_s)).astype(np.float32)
    bench_one(f"rmat{args.scale}", src_s, vals, n, args.tile)

    # uniform degree-16
    usrc = np.repeat(np.arange(n, dtype=np.int32), args.edge_factor)
    uvals = rng.normal(size=len(usrc)).astype(np.float32)
    bench_one(f"uniform{args.scale}x{args.edge_factor}", usrc, uvals, n,
              args.tile)

    # degree-1 tail (worst case for the indicator matmul)
    tsrc = np.arange(n, dtype=np.int32)
    tvals = rng.normal(size=n).astype(np.float32)
    bench_one(f"degree1_{args.scale}", tsrc, tvals, n, args.tile)


if __name__ == "__main__":
    main()
