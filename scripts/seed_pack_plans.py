#!/usr/bin/env python
"""Pre-seed the persistent pack-plan cache for bench.py's exact
geometry (host-side O(E log E) planning is hardware-independent, so
doing it ahead of a live-TPU window means `GRAPE_SPMV=pack bench.py`
loads the plan instead of spending live minutes building it).

The fragments come from bench.build_bench_fragment /
build_bench_weighted_fragment — the SAME code bench runs — so the
content-addressed digests match by construction.  Exits nonzero when
either plan fails to build (a silent MISS would only be discovered
during the live window)."""
import os
import sys

# host-side planning never needs the TPU: pin CPU before any jax import
# (the axon plugin can hang backend init when the tunnel is in limbo,
# and it registers via sitecustomize regardless of JAX_PLATFORMS)
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["JAX_PLATFORMS"] = "cpu"

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax

jax.config.update("jax_platforms", "cpu")

from bench import PLAN_CACHE_DIR, build_bench_fragment, \
    build_bench_weighted_fragment

os.environ.setdefault("GRAPE_PACK_PLAN_CACHE", PLAN_CACHE_DIR)
from libgrape_lite_tpu.ops.spmv_pack import resolve_pack_dispatch

n, src, dst, comm_spec, vm, frag = build_bench_fragment()
frag_w = build_bench_weighted_fragment(src, dst, comm_spec, vm)

# seed BOTH scan modes: the live-window A/B (GRAPE_PACK_SCAN=mxu vs
# shift, tpu_first_light step 2b) must not burn live minutes on the
# O(E log E) planner; the cache digest fingerprints the mode, so each
# seeds its own entry
ok = True
for mode in ("mxu", "shift"):
    os.environ["GRAPE_PACK_SCAN"] = mode
    d = resolve_pack_dispatch(frag)
    print(f"pagerank plan [{mode}]:",
          "ok" if d is not None else "MISSED", flush=True)
    dw = resolve_pack_dispatch(frag_w, with_weights=True)
    print(f"sssp plan [{mode}]:",
          "ok" if dw is not None else "MISSED", flush=True)
    ok = ok and d is not None and dw is not None

sys.exit(0 if ok else 1)
