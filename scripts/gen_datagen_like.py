"""Generate a datagen-9_0-fb-like surrogate graph (VERDICT r4 next #6).

The baseline's north-star dataset, LDBC datagen-9_0-fb
(`/root/reference/Performance.md:23,36`), is a Facebook-like
person-knows-person graph: |V| = 12,857,672, |E| = 1,049,527,225
undirected (avg degree ~163), community-structured (persons cluster by
university/city), degree distribution lognormal-ish with a hub cutoff
in the low thousands — structurally UNLIKE RMAT/Kronecker (no o(n)
fragmented tail, no degree-correlated id space, high clustering).
The dataset itself cannot be downloaded in this sandbox (zero egress),
and the full size exceeds the box's RAM for a load anyway, so this
generator produces a structure-matched surrogate at a documented
linear scale factor:

  * vertices n = 12,857,672 / s  (s = --scale_div, default 8)
  * target avg degree kept at the FULL graph's ~163 (per-edge
    throughput is what transfers across sizes for O(E)-per-round
    algorithms; shrinking degree with n would change the compute/
    communication ratio)
  * degree sequence: lognormal(sigma=1.15) scaled to the target mean,
    clipped to [1, 2000] (datagen fb's hub cutoff scale)
  * community sizes: Zipf-like power law over ~n/1500 communities,
    clipped to [400, 50k]
  * wiring: configuration model — every vertex gets deg(v) stubs;
    80% of stubs pair WITHIN the community (sorted by (community,
    random), paired consecutively), 20% pair globally; self-loops and
    duplicate pairs dropped (sub-1% degree loss, standard for
    configuration models)
  * weights: uniform(0, 1] float64, the Graphalytics SSSP convention

Output: TSV edge file (+ optional .v), plus a JSON line of structural
properties so the mapping to the real dataset is checkable.  See
docs/DATAGEN_SURROGATE.md for the RMAT<->datagen comparison this
unblocks.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

FULL_V = 12_857_672
FULL_E = 1_049_527_225


def generate(scale_div: int, seed: int = 42):
    rng = np.random.default_rng(seed)
    n = FULL_V // scale_div
    target_avg_deg = 2 * FULL_E / FULL_V  # ~163 (undirected degree)

    # degree sequence
    sigma = 1.15
    mu = np.log(target_avg_deg) - sigma * sigma / 2
    deg = np.clip(
        rng.lognormal(mu, sigma, n), 1, 2000
    ).astype(np.int64)
    # make stub count even so the configuration model closes
    if deg.sum() % 2:
        deg[0] += 1

    # community assignment: power-law sizes.  Mean size ~1500 keeps
    # intra-community edge density ~10% — dense enough for CDLP/LCC
    # community behavior, sparse enough that configuration-model
    # duplicate pairs stay rare (a 150-person mean with 130 intra
    # stubs per member degenerated into near-cliques and lost 25% of
    # edges to dedup)
    n_comm = max(n // 1500, 1)
    raw = rng.zipf(1.35, n_comm).astype(np.float64)
    sizes = np.clip(raw * 300, 400, 50_000)
    sizes = (sizes / sizes.sum() * n).astype(np.int64)
    sizes = np.maximum(sizes, 1)
    # fix rounding drift onto the largest community
    sizes[np.argmax(sizes)] += n - sizes.sum()
    comm = np.repeat(np.arange(len(sizes), dtype=np.int64), sizes)
    rng.shuffle(comm)

    # stubs: vertex v appears deg[v] times
    stubs = np.repeat(np.arange(n, dtype=np.int64), deg)
    intra = rng.random(len(stubs)) < 0.8
    edges = []
    for mask, by_comm in ((intra, True), (~intra, False)):
        s = stubs[mask]
        if len(s) % 2:  # odd stub pool: drop one
            s = s[:-1]
        if by_comm:
            order = np.lexsort((rng.random(len(s)), comm[s]))
        else:
            order = rng.permutation(len(s))
        s = s[order]
        u, v = s[0::2], s[1::2]
        if by_comm:
            # consecutive pairing may straddle a community boundary for
            # one pair per community — those become (valid) inter edges
            pass
        edges.append((u, v))
    src = np.concatenate([e[0] for e in edges])
    dst = np.concatenate([e[1] for e in edges])

    keep = src != dst
    src, dst = src[keep], dst[keep]
    # drop duplicate undirected pairs
    lo = np.minimum(src, dst)
    hi = np.maximum(src, dst)
    key = lo * n + hi
    _, first = np.unique(key, return_index=True)
    src, dst = lo[first], hi[first]
    w = rng.uniform(1e-6, 1.0, len(src))
    return n, src, dst, w, comm, deg


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale_div", type=int, default=8,
                    help="linear downscale factor vs datagen-9_0-fb")
    ap.add_argument("--out", required=True, help="edge TSV path")
    ap.add_argument("--vfile", default="", help="optional vertex file")
    ap.add_argument("--seed", type=int, default=42)
    args = ap.parse_args()

    t0 = time.perf_counter()
    n, src, dst, w, comm, deg = generate(args.scale_div, args.seed)
    t_gen = time.perf_counter() - t0
    props = {
        "surrogate_of": "datagen-9_0-fb",
        "scale_div": args.scale_div,
        "n_vertices": int(n),
        "n_edges_undirected": int(len(src)),
        "full_dataset": {"v": FULL_V, "e": FULL_E},
        "avg_degree": round(2 * len(src) / n, 1),
        "max_degree": int(np.bincount(
            np.concatenate([src, dst])).max()),
        "n_communities": int(len(np.unique(comm))),
        "gen_s": round(t_gen, 1),
    }
    print(json.dumps(props), file=sys.stderr)

    t0 = time.perf_counter()
    import io

    with open(args.out, "w", buffering=1 << 22) as f:
        CHUNK = 4_000_000
        for i in range(0, len(src), CHUNK):
            s, d, ww = src[i:i+CHUNK], dst[i:i+CHUNK], w[i:i+CHUNK]
            buf = io.StringIO()
            np.savetxt(buf, np.column_stack([s, d, ww]),
                       fmt="%d %d %.9f")
            f.write(buf.getvalue())
    if args.vfile:
        with open(args.vfile, "w", buffering=1 << 22) as f:
            f.write("\n".join(map(str, range(n))) + "\n")
    print(f"[gen] wrote {args.out} in {time.perf_counter()-t0:.1f}s",
          file=sys.stderr)
    with open(args.out + ".props.json", "w") as f:
        json.dump(props, f)


if __name__ == "__main__":
    main()
