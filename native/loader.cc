// Native graph-file loader.
//
// Re-design of the reference's native IO stack
// (grape/io/local_io_adaptor.{h,cc} + grape/io/tsv_line_parser.h +
// the partial-read parsing loops of
// grape/fragment/basic_fragment_loader_base.h): mmap the file, split
// it into per-thread byte ranges aligned to line boundaries (the
// SetPartialRead pattern, local_io_adaptor.h:49), and parse
// whitespace-separated integer/float columns with branch-light custom
// scanners.  Exposed through a C ABI consumed via ctypes — no pybind11
// dependency.
//
// Build: `make -C native` produces libgrape_tpu_native.so.

#include <cctype>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

struct Chunk {
  const char* begin;
  const char* end;
  std::vector<int64_t> c0, c1;
  std::vector<double> c2;
  int64_t weight_tokens = 0;  // rows that actually had a weight column
};

inline const char* skip_ws(const char* p, const char* end) {
  while (p < end && (*p == ' ' || *p == '\t' || *p == '\r')) ++p;
  return p;
}

inline const char* parse_i64(const char* p, const char* end, int64_t* out) {
  bool neg = false;
  if (p < end && (*p == '-' || *p == '+')) neg = (*p++ == '-');
  int64_t v = 0;
  while (p < end && *p >= '0' && *p <= '9') v = v * 10 + (*p++ - '0');
  *out = neg ? -v : v;
  return p;
}

inline const char* parse_f64(const char* p, const char* end, double* out) {
  char buf[64];
  int n = 0;
  while (p < end && n < 63 && *p != ' ' && *p != '\t' && *p != '\n' &&
         *p != '\r')
    buf[n++] = *p++;
  buf[n] = 0;
  *out = strtod(buf, nullptr);
  return p;
}

void parse_chunk(Chunk* ch, int ncols, int weighted) {
  const char* p = ch->begin;
  const char* end = ch->end;
  while (p < end) {
    p = skip_ws(p, end);
    if (p >= end) break;
    if (*p == '#' || *p == '\n') {  // comment or blank line
      while (p < end && *p != '\n') ++p;
      if (p < end) ++p;
      continue;
    }
    int64_t a = 0, b = 0;
    double w = 0.0;
    p = parse_i64(p, end, &a);
    if (ncols >= 2) {
      p = skip_ws(p, end);
      p = parse_i64(p, end, &b);
    }
    if (weighted) {
      p = skip_ws(p, end);
      if (p < end && *p != '\n') {
        p = parse_f64(p, end, &w);
        ++ch->weight_tokens;
      }
    }
    ch->c0.push_back(a);
    if (ncols >= 2) ch->c1.push_back(b);
    if (weighted) ch->c2.push_back(w);
    while (p < end && *p != '\n') ++p;
    if (p < end) ++p;
  }
}

struct Parsed {
  std::vector<int64_t> c0, c1;
  std::vector<double> c2;
  int64_t weight_tokens = 0;
};

// Parse `path` into columns. ncols: 1 = vertex file (oid only),
// 2 = unweighted edges. weighted adds a trailing double column.
Parsed* parse_file(const char* path, int ncols, int weighted, int nthreads) {
  int fd = open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size == 0) {
    close(fd);
    auto* out = new Parsed();
    return out;  // empty file -> empty columns
  }
  size_t size = static_cast<size_t>(st.st_size);
  const char* data =
      static_cast<const char*>(mmap(nullptr, size, PROT_READ, MAP_PRIVATE, fd, 0));
  close(fd);
  if (data == MAP_FAILED) return nullptr;

  if (nthreads <= 0) {
    nthreads = static_cast<int>(std::thread::hardware_concurrency());
    if (nthreads < 1) nthreads = 1;
  }
  if (size < (1u << 20)) nthreads = 1;

  // byte ranges aligned to line boundaries (SetPartialRead pattern)
  std::vector<Chunk> chunks(nthreads);
  size_t per = size / nthreads;
  size_t start = 0;
  for (int t = 0; t < nthreads; ++t) {
    size_t end = (t == nthreads - 1) ? size : per * (t + 1);
    if (end < size) {
      while (end < size && data[end] != '\n') ++end;
      if (end < size) ++end;
    }
    if (end < start) end = start;
    chunks[t].begin = data + start;
    chunks[t].end = data + end;
    start = end;
  }

  std::vector<std::thread> threads;
  threads.reserve(nthreads);
  for (int t = 0; t < nthreads; ++t)
    threads.emplace_back(parse_chunk, &chunks[t], ncols, weighted);
  for (auto& th : threads) th.join();

  auto* out = new Parsed();
  size_t total = 0;
  for (auto& ch : chunks) total += ch.c0.size();
  out->c0.reserve(total);
  if (ncols >= 2) out->c1.reserve(total);
  if (weighted) out->c2.reserve(total);
  for (auto& ch : chunks) {
    out->c0.insert(out->c0.end(), ch.c0.begin(), ch.c0.end());
    out->c1.insert(out->c1.end(), ch.c1.begin(), ch.c1.end());
    out->c2.insert(out->c2.end(), ch.c2.begin(), ch.c2.end());
    out->weight_tokens += ch.weight_tokens;
  }
  munmap(const_cast<char*>(data), size);
  return out;
}

}  // namespace

extern "C" {

// Stable two-pass counting sort of an edge list by (src, nbr) — the
// CSR build's lexsort (graph/csr.py), O(E + V) instead of comparison
// sorting.  Outputs are caller-allocated; indptr has num_rows+1 slots.
// The analogue of the reference's two-pass buildCSR
// (csr_edgecut_fragment_base.h:417-736).
void gl_sort_edges(const int64_t* src, const int64_t* nbr, const double* w,
                   int64_t n, int64_t num_rows, int64_t num_cols,
                   int64_t* out_src, int64_t* out_nbr, double* out_w,
                   int64_t* out_indptr) {
  // pass 1: stable counting sort by nbr
  std::vector<int64_t> cnt(static_cast<size_t>(num_cols) + 1, 0);
  for (int64_t i = 0; i < n; ++i) ++cnt[nbr[i]];
  int64_t acc = 0;
  for (size_t c = 0; c < cnt.size(); ++c) {
    int64_t t = cnt[c];
    cnt[c] = acc;
    acc += t;
  }
  std::vector<int64_t> tmp_src(n), tmp_nbr(n);
  std::vector<double> tmp_w(w ? n : 0);
  for (int64_t i = 0; i < n; ++i) {
    int64_t p = cnt[nbr[i]]++;
    tmp_src[p] = src[i];
    tmp_nbr[p] = nbr[i];
    if (w) tmp_w[p] = w[i];
  }
  // pass 2: stable counting sort by src (also yields indptr)
  std::vector<int64_t> rcnt(static_cast<size_t>(num_rows) + 1, 0);
  for (int64_t i = 0; i < n; ++i) ++rcnt[tmp_src[i]];
  acc = 0;
  for (size_t r = 0; r < rcnt.size(); ++r) {
    int64_t t = rcnt[r];
    out_indptr[r] = acc;
    rcnt[r] = acc;
    acc += t;
  }
  for (int64_t i = 0; i < n; ++i) {
    int64_t p = rcnt[tmp_src[i]]++;
    out_src[p] = tmp_src[i];
    out_nbr[p] = tmp_nbr[i];
    if (w) out_w[p] = tmp_w[i];
  }
}

void* gl_parse(const char* path, int ncols, int weighted, int nthreads) {
  return parse_file(path, ncols, weighted, nthreads);
}

int64_t gl_num_rows(void* handle) {
  return static_cast<Parsed*>(handle)->c0.size();
}

const int64_t* gl_col0(void* handle) {
  return static_cast<Parsed*>(handle)->c0.data();
}

const int64_t* gl_col1(void* handle) {
  return static_cast<Parsed*>(handle)->c1.data();
}

const double* gl_colw(void* handle) {
  return static_cast<Parsed*>(handle)->c2.data();
}

// 1 when every parsed row carried a weight token (callers treat a
// weightless file like the python parser's w=None)
int gl_all_weighted(void* handle) {
  auto* p = static_cast<Parsed*>(handle);
  return !p->c0.empty() &&
         p->weight_tokens == static_cast<int64_t>(p->c0.size());
}

void gl_free(void* handle) { delete static_cast<Parsed*>(handle); }

}  // extern "C"
