// Native graph-file loader.
//
// Re-design of the reference's native IO stack
// (grape/io/local_io_adaptor.{h,cc} + grape/io/tsv_line_parser.h +
// the partial-read parsing loops of
// grape/fragment/basic_fragment_loader_base.h): mmap the file, split
// it into per-thread byte ranges aligned to line boundaries (the
// SetPartialRead pattern, local_io_adaptor.h:49), and parse
// whitespace-separated integer/float columns with branch-light custom
// scanners.  Exposed through a C ABI consumed via ctypes — no pybind11
// dependency.
//
// Build: `make -C native` produces libgrape_tpu_native.so.

#include <algorithm>
#include <cctype>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <fcntl.h>
#include <new>
#include <sys/mman.h>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

struct Chunk {
  const char* begin;
  const char* end;
  std::vector<int64_t> c0, c1;
  std::vector<double> c2;
  int64_t weight_tokens = 0;  // rows that actually had a weight column
};

inline const char* skip_ws(const char* p, const char* end) {
  while (p < end && (*p == ' ' || *p == '\t' || *p == '\r')) ++p;
  return p;
}

inline const char* parse_i64(const char* p, const char* end, int64_t* out) {
  bool neg = false;
  if (p < end && (*p == '-' || *p == '+')) neg = (*p++ == '-');
  int64_t v = 0;
  while (p < end && *p >= '0' && *p <= '9') v = v * 10 + (*p++ - '0');
  *out = neg ? -v : v;
  return p;
}

inline const char* parse_f64(const char* p, const char* end, double* out) {
  char buf[64];
  int n = 0;
  while (p < end && n < 63 && *p != ' ' && *p != '\t' && *p != '\n' &&
         *p != '\r')
    buf[n++] = *p++;
  buf[n] = 0;
  *out = strtod(buf, nullptr);
  return p;
}

void parse_chunk(Chunk* ch, int ncols, int weighted) {
  const char* p = ch->begin;
  const char* end = ch->end;
  while (p < end) {
    p = skip_ws(p, end);
    if (p >= end) break;
    if (*p == '#' || *p == '\n') {  // comment or blank line
      while (p < end && *p != '\n') ++p;
      if (p < end) ++p;
      continue;
    }
    int64_t a = 0, b = 0;
    double w = 0.0;
    p = parse_i64(p, end, &a);
    if (ncols >= 2) {
      p = skip_ws(p, end);
      p = parse_i64(p, end, &b);
    }
    if (weighted) {
      p = skip_ws(p, end);
      if (p < end && *p != '\n') {
        p = parse_f64(p, end, &w);
        ++ch->weight_tokens;
      }
    }
    ch->c0.push_back(a);
    if (ncols >= 2) ch->c1.push_back(b);
    if (weighted) ch->c2.push_back(w);
    while (p < end && *p != '\n') ++p;
    if (p < end) ++p;
  }
}

struct Parsed {
  std::vector<int64_t> c0, c1;
  std::vector<double> c2;
  int64_t weight_tokens = 0;
};

// Parse `path` into columns. ncols: 1 = vertex file (oid only),
// 2 = unweighted edges. weighted adds a trailing double column.
Parsed* parse_file(const char* path, int ncols, int weighted, int nthreads) {
  int fd = open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size == 0) {
    close(fd);
    auto* out = new Parsed();
    return out;  // empty file -> empty columns
  }
  size_t size = static_cast<size_t>(st.st_size);
  const char* data =
      static_cast<const char*>(mmap(nullptr, size, PROT_READ, MAP_PRIVATE, fd, 0));
  close(fd);
  if (data == MAP_FAILED) return nullptr;

  if (nthreads <= 0) {
    nthreads = static_cast<int>(std::thread::hardware_concurrency());
    if (nthreads < 1) nthreads = 1;
  }
  if (size < (1u << 20)) nthreads = 1;

  // byte ranges aligned to line boundaries (SetPartialRead pattern)
  std::vector<Chunk> chunks(nthreads);
  size_t per = size / nthreads;
  size_t start = 0;
  for (int t = 0; t < nthreads; ++t) {
    size_t end = (t == nthreads - 1) ? size : per * (t + 1);
    if (end < size) {
      while (end < size && data[end] != '\n') ++end;
      if (end < size) ++end;
    }
    if (end < start) end = start;
    chunks[t].begin = data + start;
    chunks[t].end = data + end;
    start = end;
  }

  std::vector<std::thread> threads;
  threads.reserve(nthreads);
  for (int t = 0; t < nthreads; ++t)
    threads.emplace_back(parse_chunk, &chunks[t], ncols, weighted);
  for (auto& th : threads) th.join();

  auto* out = new Parsed();
  size_t total = 0;
  for (auto& ch : chunks) total += ch.c0.size();
  out->c0.reserve(total);
  if (ncols >= 2) out->c1.reserve(total);
  if (weighted) out->c2.reserve(total);
  for (auto& ch : chunks) {
    out->c0.insert(out->c0.end(), ch.c0.begin(), ch.c0.end());
    out->c1.insert(out->c1.end(), ch.c1.begin(), ch.c1.end());
    out->c2.insert(out->c2.end(), ch.c2.begin(), ch.c2.end());
    out->weight_tokens += ch.weight_tokens;
  }
  munmap(const_cast<char*>(data), size);
  return out;
}

inline uint64_t mix64(uint64_t x) {
  // splitmix64 finalizer — the hash behind both the id table and the MPH
  x += 0x9e3779b97f4a7c15ull;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ull;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebull;
  return x ^ (x >> 31);
}

// ---------------------------------------------------------------------------
// Open-addressing oid->lid table — the reference `IdIndexer`
// (grape/graph/id_indexer.h, ska::flat_hash_map-style) rebuilt as a
// linear-probing int64 table with batch, multithreaded lookup.  Replaces
// the Python dict loops that made the host vertex map the load-path
// bottleneck at LDBC scale.
// ---------------------------------------------------------------------------

struct IdTable {
  std::vector<int64_t> slot_key;
  std::vector<int64_t> slot_val;  // -1 = empty
  std::vector<int64_t> oids;      // lid -> oid (insertion order)
  uint64_t mask = 0;

  void rebuild(size_t need) {
    size_t cap = 16;
    while (cap < need * 2) cap <<= 1;  // load factor <= 0.5
    slot_key.assign(cap, 0);
    slot_val.assign(cap, -1);
    mask = cap - 1;
    for (size_t i = 0; i < oids.size(); ++i) place(oids[i], (int64_t)i);
  }

  void place(int64_t key, int64_t val) {
    uint64_t s = mix64((uint64_t)key) & mask;
    while (slot_val[s] != -1) s = (s + 1) & mask;
    slot_key[s] = key;
    slot_val[s] = val;
  }

  // arrival-order setdefault: returns the existing or new lid
  int64_t insert(int64_t key) {
    uint64_t s = mix64((uint64_t)key) & mask;
    while (slot_val[s] != -1) {
      if (slot_key[s] == key) return slot_val[s];
      s = (s + 1) & mask;
    }
    int64_t lid = (int64_t)oids.size();
    slot_key[s] = key;
    slot_val[s] = lid;
    oids.push_back(key);
    if (oids.size() * 2 > slot_key.size()) rebuild(oids.size());
    return lid;
  }

  int64_t find(int64_t key) const {
    uint64_t s = mix64((uint64_t)key) & mask;
    while (slot_val[s] != -1) {
      if (slot_key[s] == key) return slot_val[s];
      s = (s + 1) & mask;
    }
    return -1;
  }
};

void table_lookup_range(const IdTable* t, const int64_t* q, int64_t lo,
                        int64_t hi, int64_t* out) {
  for (int64_t i = lo; i < hi; ++i) out[i] = t->find(q[i]);
}

// ---------------------------------------------------------------------------
// PTHash-style minimal perfect hash (reference `pthash_idxer.h` +
// vendored thirdparty/pthash): keys -> [0, n) bijectively.  Buckets of
// ~3 keys, per-bucket pilot search with xor displacement into a table
// of size n/alpha, then the standard free-slot remap down to [0, n).
// Build is load-path-only; lookups are branch-light and batch-threaded.
// Unknown keys return an arbitrary in-range position — callers verify
// against the lid->oid array (which they keep for GetOid anyway).
// ---------------------------------------------------------------------------

struct Mph {
  uint64_t seed = 0;
  uint64_t n = 0;    // number of keys == output range
  uint64_t tsz = 0;  // intermediate range (n / alpha)
  uint64_t m = 0;    // bucket count
  std::vector<uint32_t> pilots;
  std::vector<int64_t> remap;  // [tsz - n] -> free slots below n

  inline uint64_t pos_of(int64_t key) const {
    uint64_t h = mix64((uint64_t)key ^ seed);
    uint64_t b = h % m;
    uint64_t pos = mix64(h ^ mix64((uint64_t)pilots[b] + 0x51ab2cd3ull)) % tsz;
    if (pos >= n) pos = (uint64_t)remap[pos - n];
    return pos;
  }
};

constexpr uint32_t kPilotLimit = 1u << 18;

bool mph_try_build(Mph* M, const int64_t* keys, int64_t n, uint64_t seed) {
  M->seed = seed;
  M->n = (uint64_t)n;
  M->tsz = (uint64_t)(n / 0.97) + 1;
  M->m = (uint64_t)(n / 3) + 1;
  M->pilots.assign(M->m, 0);
  M->remap.assign(M->tsz - M->n, 0);

  // counting-sort keys' hashes into buckets
  std::vector<uint64_t> h(n);
  std::vector<uint32_t> bcnt(M->m + 1, 0);
  for (int64_t i = 0; i < n; ++i) {
    h[i] = mix64((uint64_t)keys[i] ^ seed);
    ++bcnt[h[i] % M->m];
  }
  std::vector<uint32_t> bstart(M->m + 1, 0);
  for (uint64_t b = 0; b < M->m; ++b) bstart[b + 1] = bstart[b] + bcnt[b];
  std::vector<uint64_t> bh(n);
  {
    std::vector<uint32_t> cur(bstart.begin(), bstart.end() - 1);
    for (int64_t i = 0; i < n; ++i) bh[cur[h[i] % M->m]++] = h[i];
  }
  // buckets ordered by size descending (PTHash's search order)
  std::vector<uint32_t> order(M->m);
  for (uint64_t b = 0; b < M->m; ++b) order[b] = (uint32_t)b;
  std::vector<uint32_t> sizes(M->m);
  for (uint64_t b = 0; b < M->m; ++b) sizes[b] = bcnt[b];
  std::sort(order.begin(), order.end(),
            [&](uint32_t a, uint32_t b) { return sizes[a] > sizes[b]; });

  std::vector<uint8_t> taken(M->tsz, 0);
  std::vector<uint64_t> tpos(64);
  for (uint32_t b : order) {
    uint32_t sz = sizes[b];
    if (sz == 0) continue;
    if (sz > 64) return false;  // absurd skew: retry with a new seed
    const uint64_t* hk = &bh[bstart[b]];
    // duplicate keys in one bucket can never be separated
    for (uint32_t i = 0; i < sz; ++i)
      for (uint32_t j = i + 1; j < sz; ++j)
        if (hk[i] == hk[j]) return false;
    uint32_t p = 0;
    for (; p < kPilotLimit; ++p) {
      uint64_t ph = mix64((uint64_t)p + 0x51ab2cd3ull);
      bool ok = true;
      for (uint32_t i = 0; i < sz && ok; ++i) {
        uint64_t pos = mix64(hk[i] ^ ph) % M->tsz;
        if (taken[pos]) ok = false;
        for (uint32_t j = 0; j < i && ok; ++j)
          if (tpos[j] == pos) ok = false;
        tpos[i] = pos;
      }
      if (ok) break;
    }
    if (p == kPilotLimit) return false;
    M->pilots[b] = p;
    for (uint32_t i = 0; i < sz; ++i) taken[tpos[i]] = 1;
  }
  // minimal remap: taken slots >= n -> free slots < n, in order
  uint64_t free_slot = 0;
  for (uint64_t pos = M->n; pos < M->tsz; ++pos) {
    if (taken[pos]) {
      while (free_slot < M->n && taken[free_slot]) ++free_slot;
      M->remap[pos - M->n] = (int64_t)free_slot++;
    }
  }
  return true;
}

void mph_pos_range(const Mph* M, const int64_t* q, int64_t lo, int64_t hi,
                   int64_t* out) {
  for (int64_t i = lo; i < hi; ++i) out[i] = (int64_t)M->pos_of(q[i]);
}

int nthreads_for(int64_t n) {
  if (n < (1 << 16)) return 1;
  int t = (int)std::thread::hardware_concurrency();
  return t < 1 ? 1 : t;
}

}  // namespace

extern "C" {

// ---- id table (oid -> lid) ----

void* gl_ht_build(const int64_t* keys, int64_t n) {
  auto* t = new (std::nothrow) IdTable();
  if (!t) return nullptr;
  t->oids.reserve((size_t)n);
  t->rebuild((size_t)n + 1);
  for (int64_t i = 0; i < n; ++i) t->insert(keys[i]);
  return t;
}

void gl_ht_insert(void* handle, const int64_t* keys, int64_t n,
                  int64_t* out_lids) {
  auto* t = static_cast<IdTable*>(handle);
  for (int64_t i = 0; i < n; ++i) {
    int64_t lid = t->insert(keys[i]);
    if (out_lids) out_lids[i] = lid;
  }
}

void gl_ht_lookup(void* handle, const int64_t* q, int64_t n, int64_t* out) {
  auto* t = static_cast<IdTable*>(handle);
  int nt = nthreads_for(n);
  if (nt == 1) {
    table_lookup_range(t, q, 0, n, out);
    return;
  }
  std::vector<std::thread> threads;
  int64_t per = (n + nt - 1) / nt;
  for (int tix = 0; tix < nt; ++tix) {
    int64_t lo = tix * per, hi = std::min(n, lo + per);
    if (lo >= hi) break;
    threads.emplace_back(table_lookup_range, t, q, lo, hi, out);
  }
  for (auto& th : threads) th.join();
}

int64_t gl_ht_size(void* handle) {
  return (int64_t)static_cast<IdTable*>(handle)->oids.size();
}

void gl_ht_oids(void* handle, int64_t* out) {
  auto* t = static_cast<IdTable*>(handle);
  std::memcpy(out, t->oids.data(), t->oids.size() * sizeof(int64_t));
}

void gl_ht_free(void* handle) { delete static_cast<IdTable*>(handle); }

// ---- minimal perfect hash (pthash idxer backend) ----

void* gl_mph_build(const int64_t* keys, int64_t n) {
  if (n <= 0) return nullptr;
  auto* M = new (std::nothrow) Mph();
  if (!M) return nullptr;
  for (uint64_t attempt = 0; attempt < 8; ++attempt) {
    if (mph_try_build(M, keys, n, mix64(0xdecafbadull + attempt)))
      return M;
  }
  delete M;  // duplicate keys or pathological input
  return nullptr;
}

void gl_mph_pos(void* handle, const int64_t* q, int64_t n, int64_t* out) {
  auto* M = static_cast<Mph*>(handle);
  int nt = nthreads_for(n);
  if (nt == 1) {
    mph_pos_range(M, q, 0, n, out);
    return;
  }
  std::vector<std::thread> threads;
  int64_t per = (n + nt - 1) / nt;
  for (int tix = 0; tix < nt; ++tix) {
    int64_t lo = tix * per, hi = std::min(n, lo + per);
    if (lo >= hi) break;
    threads.emplace_back(mph_pos_range, M, q, lo, hi, out);
  }
  for (auto& th : threads) th.join();
}

// bits per key of the MPH structure (diagnostic)
double gl_mph_bits(void* handle) {
  auto* M = static_cast<Mph*>(handle);
  double bits = 8.0 * (M->pilots.size() * sizeof(uint32_t) +
                       M->remap.size() * sizeof(int64_t));
  return bits / (double)M->n;
}

void gl_mph_free(void* handle) { delete static_cast<Mph*>(handle); }

// Stable two-pass counting sort of an edge list by (src, nbr) — the
// CSR build's lexsort (graph/csr.py), O(E + V) instead of comparison
// sorting.  Outputs are caller-allocated; indptr has num_rows+1 slots.
// The analogue of the reference's two-pass buildCSR
// (csr_edgecut_fragment_base.h:417-736).
void gl_sort_edges(const int64_t* src, const int64_t* nbr, const double* w,
                   int64_t n, int64_t num_rows, int64_t num_cols,
                   int64_t* out_src, int64_t* out_nbr, double* out_w,
                   int64_t* out_indptr) {
  // pass 1: stable counting sort by nbr
  std::vector<int64_t> cnt(static_cast<size_t>(num_cols) + 1, 0);
  for (int64_t i = 0; i < n; ++i) ++cnt[nbr[i]];
  int64_t acc = 0;
  for (size_t c = 0; c < cnt.size(); ++c) {
    int64_t t = cnt[c];
    cnt[c] = acc;
    acc += t;
  }
  std::vector<int64_t> tmp_src(n), tmp_nbr(n);
  std::vector<double> tmp_w(w ? n : 0);
  for (int64_t i = 0; i < n; ++i) {
    int64_t p = cnt[nbr[i]]++;
    tmp_src[p] = src[i];
    tmp_nbr[p] = nbr[i];
    if (w) tmp_w[p] = w[i];
  }
  // pass 2: stable counting sort by src (also yields indptr)
  std::vector<int64_t> rcnt(static_cast<size_t>(num_rows) + 1, 0);
  for (int64_t i = 0; i < n; ++i) ++rcnt[tmp_src[i]];
  acc = 0;
  for (size_t r = 0; r < rcnt.size(); ++r) {
    int64_t t = rcnt[r];
    out_indptr[r] = acc;
    rcnt[r] = acc;
    acc += t;
  }
  for (int64_t i = 0; i < n; ++i) {
    int64_t p = rcnt[tmp_src[i]]++;
    out_src[p] = tmp_src[i];
    out_nbr[p] = tmp_nbr[i];
    if (w) out_w[p] = tmp_w[i];
  }
}

void* gl_parse(const char* path, int ncols, int weighted, int nthreads) {
  return parse_file(path, ncols, weighted, nthreads);
}

int64_t gl_num_rows(void* handle) {
  return static_cast<Parsed*>(handle)->c0.size();
}

const int64_t* gl_col0(void* handle) {
  return static_cast<Parsed*>(handle)->c0.data();
}

const int64_t* gl_col1(void* handle) {
  return static_cast<Parsed*>(handle)->c1.data();
}

const double* gl_colw(void* handle) {
  return static_cast<Parsed*>(handle)->c2.data();
}

// 1 when every parsed row carried a weight token (callers treat a
// weightless file like the python parser's w=None)
int gl_all_weighted(void* handle) {
  auto* p = static_cast<Parsed*>(handle);
  return !p->c0.empty() &&
         p->weight_tokens == static_cast<int64_t>(p->c0.size());
}

void gl_free(void* handle) { delete static_cast<Parsed*>(handle); }

// ---- varint / delta-varint decode ----
//
// LEB128 uint64 streams are the fragment-cache wire format
// (utils/archive.py; reference semantics grape/utils/varint.h).  The
// vectorised numpy decoder is the bottleneck of cache loads at scale
// (1.7e9 values ~= 10 min); this single-pass scalar loop runs at
// ~1 GB/s.

// number of encoded values = bytes with the continuation bit clear
int64_t gl_varint_count(const uint8_t* buf, int64_t nbytes) {
  int64_t n = 0;
  for (int64_t i = 0; i < nbytes; ++i) n += !(buf[i] & 0x80);
  return n;
}

// decode into out[max_out]; delta != 0 applies the running-sum
// (delta-varint) transform in the same pass.  Returns the decoded
// count, or -1 on a truncated/overlong stream or out overflow.
int64_t gl_varint_decode(const uint8_t* buf, int64_t nbytes,
                         uint64_t* out, int64_t max_out, int delta) {
  int64_t n = 0, i = 0;
  uint64_t acc = 0;
  while (i < nbytes) {
    uint64_t v = 0;
    int shift = 0;
    for (;;) {
      if (i >= nbytes || shift > 63) return -1;
      uint8_t b = buf[i++];
      v |= (uint64_t)(b & 0x7F) << shift;
      if (!(b & 0x80)) break;
      shift += 7;
    }
    if (n >= max_out) return -1;
    if (delta) {
      acc += v;
      out[n++] = acc;
    } else {
      out[n++] = v;
    }
  }
  return n;
}

// exact encoded size (first pass of the two-pass encode: callers
// allocate tight instead of the 10n worst case)
int64_t gl_varint_size(const uint64_t* vals, int64_t n, int delta) {
  int64_t total = 0;
  uint64_t prev = 0;
  for (int64_t i = 0; i < n; ++i) {
    uint64_t v = delta ? vals[i] - prev : vals[i];
    if (delta) prev = vals[i];
    int bytes = 1;
    while (v >>= 7) ++bytes;
    total += bytes;
  }
  return total;
}

// encode; returns bytes written or -1 on overflow of max_bytes
int64_t gl_varint_encode(const uint64_t* vals, int64_t n, uint8_t* out,
                         int64_t max_bytes, int delta) {
  int64_t p = 0;
  uint64_t prev = 0;
  for (int64_t i = 0; i < n; ++i) {
    uint64_t v = delta ? vals[i] - prev : vals[i];
    if (delta) prev = vals[i];
    do {
      if (p >= max_bytes) return -1;
      uint8_t b = v & 0x7F;
      v >>= 7;
      out[p++] = v ? (b | 0x80) : b;
    } while (v);
  }
  return p;
}

// ---- float-stream byte-plane codec ----
//
// Serialize-side twin of the varint codec (VERDICT r4 next #5;
// reference symmetric codec grape/utils/varint.h:39-402): weight
// streams dominate frag.garc bytes at scale, and raw IEEE floats are
// incompressible as a unit — but byte-plane transposed, the
// sign/exponent plane deflates ~4x while mantissa planes stay raw
// (measured: 20M uniform f32, plane 3: 20 MB -> 5.1 MB).  These two
// passes are the transpose; the per-plane deflate decision lives in
// fragment/loader.py.

// out[plane * n + i] = in[i * itemsize + plane].  Tiled so the input
// is read once and every plane's write run stays within one cache
// line burst (a plane-per-pass loop re-reads the whole input
// `itemsize` times and runs no faster than numpy's strided copy).
void gl_byte_split(const uint8_t* in, int64_t n, int itemsize,
                   uint8_t* out) {
  const int64_t TILE = 1 << 14;
  for (int64_t i0 = 0; i0 < n; i0 += TILE) {
    int64_t i1 = i0 + TILE < n ? i0 + TILE : n;
    for (int p = 0; p < itemsize; ++p) {
      const uint8_t* src = in + p + i0 * itemsize;
      uint8_t* dst = out + (int64_t)p * n + i0;
      for (int64_t i = 0; i < i1 - i0; ++i) dst[i] = src[i * itemsize];
    }
  }
}

// inverse of gl_byte_split, same tiling
void gl_byte_join(const uint8_t* in, int64_t n, int itemsize,
                  uint8_t* out) {
  const int64_t TILE = 1 << 14;
  for (int64_t i0 = 0; i0 < n; i0 += TILE) {
    int64_t i1 = i0 + TILE < n ? i0 + TILE : n;
    for (int p = 0; p < itemsize; ++p) {
      const uint8_t* src = in + (int64_t)p * n + i0;
      uint8_t* dst = out + p + i0 * itemsize;
      for (int64_t i = 0; i < i1 - i0; ++i) dst[i * itemsize] = src[i];
    }
  }
}

}  // extern "C"
