"""The TPU "parallel engine": how ForEach maps to XLA.

Re-design of the reference's two execution engines:

* CPU `ParallelEngine` (`grape/parallel/parallel_engine.h:32-719`):
  thread-pool `ForEach` over ranges / vertex sets with chunked
  work-stealing.
* CUDA `ParallelEngine` (`grape/cuda/parallel/parallel_engine.h:42-1444`):
  the load-balancing kernel catalog `{none, cm, cmold, wm, cta,
  strict}` that assigns edges to threads/warps/CTAs to fight degree
  skew.

On TPU, both collapse into data layout decisions rather than scheduling
code, which is what this module provides:

* `ForEach(vertices)`  -> elementwise ops over `[vp]` state rows (VPU
  lanes are the "threads"; masking replaces range splitting).
* `ForEach(frontier)`  -> the same ops under a boolean mask — XLA fuses
  mask + compute, so an empty frontier costs memory bandwidth, not
  branches (the dense-frontier tradeoff of `DenseVertexSet`).
* `ForEachEdge(lb=*)`  -> edge-major arrays + `segment_reduce`.  Every
  edge is one lane of work keyed by its row id; XLA tiles the sorted
  segment reduction evenly, which is precisely what the reference's
  `strict` policy (exact edge partitioning via binary search,
  `parallel_engine.h:847+`) does in software.  The cm/wm/cta policies
  exist because CUDA kernels must choose a granularity; a TPU segment
  reduction has no such choice to make.

`edge_balanced_tiles` below is the one scheduling primitive the
kernels do need: an exact edge partitioning of a CSR into fixed-size
tiles with per-tile row spans (the `strict` analogue), used by chunked
Pallas kernels to bound VMEM working sets.
"""

from __future__ import annotations

import numpy as np


def edge_balanced_tiles(indptr: np.ndarray, tile_edges: int):
    """Exact edge partitioning (reference LBSTRICT,
    `cuda/parallel/parallel_engine.h:847+`): tile t covers edges
    [t*tile_edges, (t+1)*tile_edges) and rows [row_lo[t], row_hi[t]].

    Returns (row_lo, row_hi) int32 arrays of length num_tiles; rows
    spanning a tile boundary appear in both tiles (callers combine
    partial sums, which segment reductions do for free).
    """
    total = int(indptr[-1])
    num_tiles = max(1, -(-total // tile_edges))
    starts = np.arange(num_tiles, dtype=np.int64) * tile_edges
    ends = np.minimum(starts + tile_edges, total)
    row_lo = np.searchsorted(indptr, starts, side="right") - 1
    row_hi = np.searchsorted(indptr, ends, side="left")
    return row_lo.astype(np.int32), np.maximum(row_hi, row_lo + 1).astype(np.int32)
