"""Mirror-compressed state exchange: sync outer vertices only.

Re-design of the reference's batch-shuffle mirror sync
(`grape/parallel/batch_shuffle_message_manager.h:237-264`, mirror lists
from `grape/fragment/edgecut_fragment_base.h:569-602`): instead of
all_gathering the FULL per-vertex state vector — O(fnum*vp) HBM per
device and O(N) ICI bytes per round regardless of cut quality — each
shard sends every neighbor shard exactly the state rows that shard's
edges reference (its outer-vertex mirrors).

TPU formulation (static shapes, one collective):

  host/prepare time: per (receiver f, sender g) the request list
  req[f][g] = sorted unique pids of shard g referenced by f's edges.
  M = max |req| padded to the lane width; the send table for shard g
  is `send_idx[g]` [fnum, M] (rows ordered by receiver), and every
  edge column is remapped into the COMPACT index space
  [vp local | g0 mirrors | g1 mirrors | ...] of length vp + fnum*M.

  per round (inside shard_map): one gather x_local[send_idx] ->
  [fnum, M], one `all_to_all`, one concat -> x_compact.  ICI bytes
  drop from fnum*vp to fnum*M per device per round; state never
  materialises at O(fnum*vp).

The compact column space composes with the pack-gather SpMV: pack
plans built over `nbr_compact` gather from x_compact, shrinking the
pass table from fnum*vp to vp + fnum*M entries.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field

import numpy as np

_UID = itertools.count(1)


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def exchange_bytes_ledger(fnum: int, vp: int, m: int | None = None,
                          itemsize: int = 4) -> dict:
    """THE per-round exchange-bytes model, in one place.

    Both consumers read this function — `resolve_mirror_plan`'s
    auto-mode engagement gate AND the superstep-pipelining threshold
    (parallel/pipeline.py) — instead of keeping private copies of
    "exchange bytes" that could drift apart (the r9 bugfix: the auto
    gate used to count only the all_gather it replaces, inline).

    Returns {"gather": per-device ICI bytes of the full-state
    all_gather, "mirror": bytes of the mirror all_to_all (None when no
    mirror plan exists/fits)}."""
    return {
        "gather": fnum * vp * itemsize,
        "mirror": None if m is None else fnum * m * itemsize,
    }


def vc2d_exchange_bytes(k: int, vc: int, itemsize: int = 4,
                        pulls: int = 1) -> int:
    """Per-round per-device ICI bytes of the 2-D vertex-cut round
    (fragment/partition.py's side of THE shared exchange model — the
    1-D side is `exchange_bytes_ledger`).  Per pull: one ring psum of
    the [vc] partials along k row peers (2*(k-1)/k * vc payload) plus
    one transpose ppermute ((1 - 1/k) * vc average — diagonal devices
    self-map).  The asymptotic point of SparseP's 2-D argument: this
    is O(N/k) per device where the 1-D gather is O(N)."""
    if k <= 1:
        return 0
    per_pull = (2 * (k - 1) / k + (1 - 1 / k)) * vc * itemsize
    return int(round(pulls * per_pull))


def pipelined_round_s(compute_interior_s: float, exchange_s: float,
                      compute_boundary_s: float) -> float:
    """The software-pipelined round's modeled wall time:

        t = max(compute_interior, exchange) + compute_boundary

    — the exchange for round k+1 overlaps round k's interior slice
    and joins at the fold; only the boundary slice (which produces the
    exchange payload) stays on the critical path.  MAX, not SUM: under
    pipelining, shrinking the exchange below the interior-compute time
    buys nothing, which is why the mirror auto-mode decision and the
    pipeline engagement threshold must share this one model
    (docs/PIPELINE.md)."""
    return max(compute_interior_s, exchange_s) + compute_boundary_s


@dataclass
class MirrorPlan:
    """Static routing for the mirror exchange of one fragment+direction."""

    fnum: int
    vp: int
    m: int                     # mirror slots per (sender, receiver) pair
    n_compact: int             # vp + fnum * m
    send_idx: np.ndarray       # [fnum(sender), fnum(receiver), m] int32 lids
    nbr_compact: np.ndarray    # [fnum, Ep] int32 compact edge columns
    uid: int = field(default_factory=lambda: next(_UID))

    @property
    def bytes_all_gather(self) -> int:
        """Per-device ICI bytes per round of the full-state all_gather
        this plan replaces (f32 payload; shared ledger —
        exchange_bytes_ledger)."""
        return exchange_bytes_ledger(self.fnum, self.vp, self.m)["gather"]

    @property
    def bytes_mirror(self) -> int:
        """Per-device ICI bytes per round of the mirror all_to_all."""
        return exchange_bytes_ledger(self.fnum, self.vp, self.m)["mirror"]

    def state_entries(self, prefix: str) -> dict:
        """Ephemeral state leaves ([fnum, ...], sharded on dim 0)."""
        return {
            prefix + "send": self.send_idx,
            prefix + "nbr": self.nbr_compact,
        }


_FRAG_MIRROR_CACHE = None

# auto-mode engagement gate: mirror must at least halve the per-round
# ICI bytes AND the all_gather it replaces must be big enough for bytes
# (not collective latency) to dominate.  Below ~1 MiB of gathered state
# an all_gather is latency-bound and the extra gather + all_to_all hop
# of the mirror path buys nothing (decision recorded in
# docs/PERF_NOTES.md; revisit with a measured TPU crossover).
_AUTO_RATIO = 0.5
_AUTO_MIN_BYTES = 1 << 20


def resolve_mirror_plan(frag, direction: str = "ie"):
    """Resolve the exchange mode for an app's pull (the single entry
    point models call).  `GRAPE_EXCHANGE`:

      * "mirror" — always exchange mirrors (fnum > 1),
      * "gather" / "off" — always all_gather,
      * unset / "auto" — engage mirrors only when the static bytes
        model shows a clear ICI win (see _AUTO_RATIO/_AUTO_MIN_BYTES).

    Returns a MirrorPlan or None (= use gather_state)."""
    import os

    mode = os.environ.get("GRAPE_EXCHANGE", "auto") or "auto"
    if mode not in ("mirror", "gather", "off", "auto"):
        # an unrecognized value must not silently engage mirrors
        from libgrape_lite_tpu.utils import logging as glog

        glog.log_info(
            f"GRAPE_EXCHANGE={mode!r} is not one of "
            "mirror|gather|off|auto; using gather"
        )
        return None
    if mode in ("gather", "off") or frag.fnum == 1:
        return None
    gather_bytes = exchange_bytes_ledger(frag.fnum, frag.vp)["gather"]
    if mode != "mirror" and gather_bytes <= _AUTO_MIN_BYTES:
        return None  # too small for bytes to matter; skip the planner
    plan = build_mirror_plan(frag, direction)
    if plan is None or mode == "mirror":
        return plan
    if (
        plan.bytes_all_gather > _AUTO_MIN_BYTES
        and plan.bytes_mirror <= _AUTO_RATIO * plan.bytes_all_gather
    ):
        return plan
    return None


def build_mirror_plan(frag, direction: str = "ie") -> MirrorPlan | None:
    """Build (and cache per fragment) the mirror plan for `frag`'s
    pull over `direction` ("ie" | "oe").  Returns None for fnum == 1
    (nothing to exchange — apps use local state directly)."""
    global _FRAG_MIRROR_CACHE
    import weakref

    if frag.fnum == 1:
        return None
    if _FRAG_MIRROR_CACHE is None:
        _FRAG_MIRROR_CACHE = weakref.WeakKeyDictionary()
    per_frag = _FRAG_MIRROR_CACHE.setdefault(frag, {})
    if direction in per_frag:
        return per_frag[direction]

    fnum, vp = frag.fnum, frag.vp
    csrs = frag.host_ie if direction == "ie" else frag.host_oe

    # per (receiver f, sender g) sorted unique request lists
    reqs: list[list[np.ndarray]] = []
    m = 1
    for f in range(fnum):
        h = csrs[f]
        nbr = h.edge_nbr[h.edge_mask].astype(np.int64)
        row = []
        g_of = nbr // vp
        for g in range(fnum):
            if g == f:
                row.append(np.zeros(0, np.int64))
                continue
            r = np.unique(nbr[g_of == g])
            row.append(r)
            m = max(m, len(r))
        reqs.append(row)
    m = _round_up(m, 128)

    send_idx = np.zeros((fnum, fnum, m), dtype=np.int32)
    for g in range(fnum):
        for f in range(fnum):
            if f == g:
                continue
            r = reqs[f][g]
            send_idx[g, f, : len(r)] = (r % vp).astype(np.int32)

    ep = csrs[0].edge_nbr.shape[0]
    nbr_compact = np.zeros((fnum, ep), dtype=np.int32)
    for f in range(fnum):
        h = csrs[f]
        nbr = h.edge_nbr.astype(np.int64)
        g_of = nbr // vp
        out = np.where(g_of == f, nbr % vp, 0).astype(np.int64)
        for g in range(fnum):
            if g == f:
                continue
            sel = g_of == g
            if not sel.any():
                continue
            pos = np.searchsorted(reqs[f][g], nbr[sel])
            out[sel] = vp + g * m + pos
        nbr_compact[f] = np.where(h.edge_mask, out, 0).astype(np.int32)

    plan = MirrorPlan(
        fnum=fnum, vp=vp, m=m, n_compact=vp + fnum * m,
        send_idx=send_idx, nbr_compact=nbr_compact,
    )
    per_frag[direction] = plan
    return plan
