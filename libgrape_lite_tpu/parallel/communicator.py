"""App-facing collective aggregates.

Re-design of `grape/communication/communicator.h:35-127` (MPI gather +
bcast on rank 0) and `grape/cuda/communication/communicator.h:29-216`
(ncclAllReduce): on TPU these are single XLA collectives over the frag
mesh axis, usable *inside* jitted superstep code.
"""

from __future__ import annotations

import jax
from jax import lax

from libgrape_lite_tpu.parallel.comm_spec import FRAG_AXIS


class Communicator:
    """Mixin/namespace of in-step collectives. Methods must be called
    inside `shard_map` tracing over the frag axis."""

    axis = FRAG_AXIS

    @staticmethod
    def sum(x):
        return lax.psum(x, FRAG_AXIS)

    @staticmethod
    def min(x):
        return lax.pmin(x, FRAG_AXIS)

    @staticmethod
    def max(x):
        return lax.pmax(x, FRAG_AXIS)

    @staticmethod
    def all_gather(x, tiled: bool = True):
        """Gather per-shard blocks into the full array (the analogue of
        BatchShuffle's whole-array sync, `batch_shuffle_message_manager.h:237`)."""
        return lax.all_gather(x, FRAG_AXIS, tiled=tiled)

    @staticmethod
    def all_to_all(x, split_axis=0, concat_axis=0):
        return lax.all_to_all(
            x, FRAG_AXIS, split_axis=split_axis, concat_axis=concat_axis, tiled=True
        )

    @staticmethod
    def ppermute(x, perm):
        return lax.ppermute(x, FRAG_AXIS, perm)

    @staticmethod
    def axis_index():
        return lax.axis_index(FRAG_AXIS)

    @staticmethod
    def axis_size():
        return lax.axis_size(FRAG_AXIS)
