"""Fragment <-> device topology.

Re-design of `grape/worker/comm_spec.h:34-239`.  The reference maps one
fragment to one MPI rank and discovers host topology with hostname
allgathers.  On TPU the topology is a `jax.sharding.Mesh`: fragment fid i
lives on mesh device i along the `frag` axis (the identity FragToWorker
mapping of `comm_spec.h:128`), ICI replaces the intra-host communicator,
and multi-slice DCN replaces the inter-host one.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

FRAG_AXIS = "frag"
VC_ROW_AXIS = "vcrow"  # 2-D vertex-cut mesh: fragment (i, j) = device i*k+j
VC_COL_AXIS = "vccol"
kCoordinatorRank = 0  # reference grape/config.h:64


def host_allgather(vec: np.ndarray) -> np.ndarray:
    """Host-side allgather of a small vector, stacked `[nprocs, ...]`
    — the control plane under `ft/distributed.py`'s two-phase commit
    barriers and `guard/vote.py`'s breach votes.  Single-process it
    degenerates to stacking the input alone, touching no backend, so
    the callers' quorum logic is identical at every process count."""
    v = np.asarray(vec)
    if jax.process_count() <= 1:
        return v[None]
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(v))


def put_global(x, sharding: NamedSharding):
    """`jax.device_put` honoring multi-process meshes: when the
    sharding spans non-addressable devices (a jax.distributed run),
    assemble the global array from this process's full host copy via
    `make_array_from_callback` — every process loads identical arrays
    (deterministic loader), the multi-host form of the reference's
    per-rank loading contract.  Single-process: plain device_put."""
    if x is None:
        return None
    if sharding.is_fully_addressable:
        import jax.numpy as jnp

        return jax.device_put(jnp.asarray(x), sharding)
    arr = np.asarray(x)
    return jax.make_array_from_callback(
        arr.shape, sharding, lambda idx: arr[idx]
    )


class CommSpec:
    @classmethod
    def init_distributed(cls, coordinator_address: str | None = None,
                         num_processes: int | None = None,
                         process_id: int | None = None,
                         fnum: int | None = None,
                         retry_policy=None) -> "CommSpec":
        """Multi-host (DCN) initialization — the analogue of the
        reference's `InitMPIComm` (`sync_comm.h:41-45`): bring up the
        jax.distributed runtime so `jax.devices()` spans every host's
        chips, then build the frag mesh over the global device list.
        Collectives ride ICI within a slice and DCN across slices,
        chosen by XLA from the mesh — no NCCL/MPI plumbing.  (Single
        host: falls through to the plain constructor.)

        Transient coordinator failures (handshake timeout, connection
        refused while the coordinator pod is still scheduling) are
        retried with exponential backoff (`ft/retry.py`); contract
        violations (late call, double init) are never retried."""
        if num_processes and num_processes > 1:
            # the CPU backend runs cross-process collectives over gloo,
            # but only if the implementation is selected BEFORE the
            # backend comes up — without this every multi-process
            # computation dies with "Multiprocess computations aren't
            # implemented on the CPU backend".  TPU/GPU ignore it.
            try:
                jax.config.update(
                    "jax_cpu_collectives_implementation", "gloo"
                )
            except (AttributeError, ValueError) as e:
                # AttributeError: the flag was renamed/removed in this
                # jax; ValueError: jaxlib built without gloo.  Either
                # way CPU gangs will fail later — say why now instead
                # of swallowing it silently
                import logging

                logging.getLogger(__name__).warning(
                    "could not select gloo CPU collectives (%s); "
                    "multi-process CPU runs may fail with "
                    "'Multiprocess computations aren't implemented on "
                    "the CPU backend'", e,
                )
            from libgrape_lite_tpu.ft.retry import (
                DISTRIBUTED_INIT_POLICY,
                is_late_init_error,
                is_transient_distributed_error,
                with_retries,
            )

            def _initialize():
                try:
                    jax.distributed.initialize(
                        coordinator_address=coordinator_address,
                        num_processes=num_processes,
                        process_id=process_id,
                    )
                except Exception as e:
                    # a failed handshake can leave the half-constructed
                    # global client/service behind (jax sets them before
                    # connect()); clear it best-effort so the retry hits
                    # the handshake again instead of the double-init
                    # guard ("should only be called once").  ONLY for
                    # errors we will actually retry — a contract
                    # violation (double init / late call) must not tear
                    # down a runtime that is already live and working
                    if is_transient_distributed_error(e):
                        try:
                            jax.distributed.shutdown()
                        except Exception:
                            pass
                    raise

            try:
                with_retries(
                    _initialize,
                    policy=retry_policy or DISTRIBUTED_INIT_POLICY,
                    retryable=is_transient_distributed_error,
                    describe="jax.distributed.initialize",
                )
            except RuntimeError as e:
                # jax.distributed.initialize itself rejects a late call
                # (backends already up); re-raise with the framework-
                # level contract instead of peeking at private jax._src
                # state (VERDICT r4 weak #4).  Classification is by the
                # runtime's specific phrases (ft/retry.py), not a bare
                # "before" substring — a coordinator timeout whose
                # message happens to contain "before" must surface as
                # itself (ADVICE r5)
                if not is_late_init_error(e):
                    raise
                raise RuntimeError(
                    "CommSpec.init_distributed must run before any JAX "
                    "backend use (jax.distributed.initialize cannot "
                    "attach to an initialized runtime)"
                ) from e
        return cls(fnum=fnum)

    def __init__(self, fnum: int | None = None, devices=None):
        if devices is None:
            devices = jax.devices()
        if fnum is None:
            fnum = len(devices)
        if fnum > len(devices):
            raise ValueError(
                f"fnum={fnum} exceeds available devices ({len(devices)}); "
                "the TPU build maps one fragment per device"
            )
        self.fnum = fnum
        self.devices = list(devices[:fnum])
        self.mesh = Mesh(np.array(self.devices), (FRAG_AXIS,))
        self.worker_num = fnum
        self.worker_id = jax.process_index()

    def mesh2d(self) -> Mesh:
        """k x k (row, col) mesh over the same devices in the same
        order (fid = i*k + j) — the SUMMA view for vertex-cut apps
        (reference `VCPartitioner`'s 2-D fragment grid,
        `partitioner.h:269-330`).  psum over one axis reduces a row or
        column of fragments; a transpose is one `ppermute`."""
        k = int(round(np.sqrt(self.fnum)))
        if k * k != self.fnum:
            raise ValueError(f"2-D mesh needs fnum = k^2, got {self.fnum}")
        return Mesh(
            np.array(self.devices).reshape(k, k), (VC_ROW_AXIS, VC_COL_AXIS)
        )

    def frag_to_worker(self, fid: int) -> int:
        return fid  # identity, like the reference

    def worker_to_frag(self, wid: int) -> int:
        return wid

    def sharded(self, *trailing_dims_spec) -> NamedSharding:
        """NamedSharding with the leading dim over the frag axis."""
        return NamedSharding(self.mesh, P(FRAG_AXIS, *trailing_dims_spec))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    @property
    def is_coordinator(self) -> bool:
        return self.worker_id == kCoordinatorRank

    def __repr__(self):
        return f"CommSpec(fnum={self.fnum}, devices={len(self.devices)})"
