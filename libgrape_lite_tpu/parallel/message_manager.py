"""Message managers: collective communication strategies.

Re-design of the reference message-manager family
(`grape/parallel/*message_manager*.h`).  The reference moves explicit
byte archives between MPI ranks; on TPU a "message" is a position in a
dense or fixed-capacity tensor and the transport is an XLA collective.
The managers here are *strategy namespaces* used inside traced superstep
code:

* batch-shuffle / sync-on-outer-vertex  → `StepContext.gather_state`
  (one `all_gather`; see app/base.py) — reference
  `batch_shuffle_message_manager.h`.
* auto messaging (SyncBuffer)           → `AutoParallelMessageManager`:
  per-vertex *proposal* arrays all-reduced with the buffer's aggregate
  op — reference `auto_parallel_message_manager.h:47-365`
  (generateAutoMessages / aggregateAutoMessages become one
  `psum`/`pmin`/`pmax` over pid-indexed proposals).
* point-to-point message tensors        → `AllToAllMessageManager`:
  fixed-capacity per-destination (lid, payload) tensors exchanged with
  `all_to_all` — reference `default_message_manager.h` /
  `parallel_message_manager.h` (the per-destination InArchives + length
  allgather + isend/irecv become one static-shape collective; the
  length sync disappears because capacity is static, and overflow is
  detected with a `psum` vote so the caller can retry with a larger
  capacity — the role of `EstimateMessageSize`, worker.h:157-170).

Termination (`ToTerminate`, `parallel_message_manager.h:123-138`): all
managers express the 2-int MPI_Allreduce as a `psum` of the per-shard
active count; `ForceContinue` is returning a nonzero vote.
"""

from __future__ import annotations

from typing import Dict

import jax.numpy as jnp
import numpy as np
from jax import lax

from libgrape_lite_tpu.parallel.comm_spec import FRAG_AXIS


class MessageManagerBase:
    """Protocol documentation holder (reference
    `message_manager_base.h`): Init/Start/StartARound/FinishARound are
    trace-time no-ops on TPU (XLA owns scheduling); ToTerminate is the
    psum vote computed by the app; Finalize is garbage collection."""


class AutoParallelMessageManager(MessageManagerBase):
    """SyncBuffer aggregation: proposals are [n_pad] arrays (neutral
    element everywhere a shard has nothing to say); the aggregate op
    runs as one all-reduce collective and each shard keeps its slice."""

    _REDUCERS = {
        "min": lambda x: lax.pmin(x, FRAG_AXIS),
        "max": lambda x: lax.pmax(x, FRAG_AXIS),
        "sum": lambda x: lax.psum(x, FRAG_AXIS),
    }

    @classmethod
    def sync(cls, frag, proposals: Dict[str, jnp.ndarray], ops: Dict[str, str]):
        """Aggregate proposals across shards; return own-slice dict."""
        vp = frag.vp
        fid = lax.axis_index(FRAG_AXIS)
        out = {}
        for k, prop in proposals.items():
            combined = cls._REDUCERS[ops[k]](prop)
            out[k] = lax.dynamic_slice(combined, (fid * vp,), (vp,))
        return out


class AllToAllMessageManager(MessageManagerBase):
    """Fixed-capacity point-to-point message tensors.

    `exchange` routes per-message payloads to destination shards:
    messages are sorted by destination, packed into a [fnum, capacity]
    tensor (sliced per destination), exchanged with one `all_to_all`,
    and returned as flat receive buffers plus a global overflow flag.
    """

    @staticmethod
    def exchange(dest_fid, lid, payload, valid, capacity: int, fnum: int):
        """All inputs are per-shard flat arrays of equal length M.

        Returns (recv_lid [fnum*capacity], recv_payload, recv_valid,
        overflowed_scalar).  Messages beyond `capacity` for any single
        destination are dropped and flagged (callers retry with a
        bigger capacity or fall back to the dense path).
        """
        m = dest_fid.shape[0]
        big = jnp.int32(fnum)
        d = jnp.where(valid, dest_fid.astype(jnp.int32), big)
        order = jnp.argsort(d)  # stable: groups by destination
        d_s = d[order]
        lid_s = lid[order]
        pay_s = payload[order]

        # rank within destination group
        idx = jnp.arange(m, dtype=jnp.int32)
        first_of_group = jnp.zeros(m, jnp.int32).at[1:].set(
            (d_s[1:] != d_s[:-1]).astype(jnp.int32)
        )
        # start index of each message's group (running max of group heads)
        starts = jnp.where(first_of_group > 0, idx, 0)
        starts = lax.associative_scan(jnp.maximum, starts)
        rank = idx - starts

        ok = jnp.logical_and(d_s < big, rank < capacity)
        slot_d = jnp.where(ok, d_s, big)
        slot_r = jnp.where(ok, rank, 0)

        send_lid = jnp.zeros((fnum + 1, capacity), lid.dtype)
        send_pay = jnp.zeros((fnum + 1, capacity), payload.dtype)
        send_val = jnp.zeros((fnum + 1, capacity), jnp.bool_)
        send_lid = send_lid.at[slot_d, slot_r].set(
            jnp.where(ok, lid_s, 0)
        )[:fnum]
        send_pay = send_pay.at[slot_d, slot_r].set(
            jnp.where(ok, pay_s, 0)
        )[:fnum]
        send_val = send_val.at[slot_d, slot_r].set(ok)[:fnum]

        overflow_local = jnp.logical_and(
            d_s < big, rank >= capacity
        ).any().astype(jnp.int32)
        overflowed = lax.psum(overflow_local, FRAG_AXIS)

        recv_lid = lax.all_to_all(
            send_lid, FRAG_AXIS, split_axis=0, concat_axis=0, tiled=True
        )
        recv_pay = lax.all_to_all(
            send_pay, FRAG_AXIS, split_axis=0, concat_axis=0, tiled=True
        )
        recv_val = lax.all_to_all(
            send_val, FRAG_AXIS, split_axis=0, concat_axis=0, tiled=True
        )
        return (
            recv_lid.reshape(-1),
            recv_pay.reshape(-1),
            recv_val.reshape(-1),
            overflowed,
        )


def plan_initial_capacity(frag, requested: int | None, learned) -> int:
    """Initial per-destination message capacity for the exchange path —
    the role of the reference's `EstimateMessageSize` priming
    (`parallel_message_manager_opt.h`): `requested` wins; else the
    capacity a previous query on this fragment settled at (`learned` is
    the app's per-fragment WeakKeyDictionary); else a graph-informed
    floor — the densest vertex must be able to push all its edges to a
    single destination shard without overflowing round one.

    An armed fault plan (GRAPE_FT_FAULTS=capacity=N, ft/faults.py)
    clamps the result so the overflow vote + retry ladder actually
    executes in drills instead of being dead code on real graphs."""
    from libgrape_lite_tpu.ft.faults import active_plan

    if requested:
        return active_plan().clamp_capacity(max(1, requested))
    if frag in learned:
        return active_plan().clamp_capacity(learned[frag])
    max_deg = max(
        int(np.diff(c.indptr).max(initial=1)) for c in frag.host_oe
    )
    cap = 1024
    while cap < 2 * max_deg:
        cap *= 2
    return active_plan().clamp_capacity(cap)
