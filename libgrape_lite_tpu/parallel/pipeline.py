"""Superstep software pipelining — overlap the halo exchange with
interior compute (ROADMAP item 3, r9).

The fused superstep runs compute -> exchange strictly serially, so
every round the VPU idles for the full `all_gather`/`all_to_all` (obs/
measures it as the dispatch/device split; SparseP frames the same
compute/transfer balance).  This module restructures the round as a
double-buffered software pipeline over the boundary/interior vertex
split of `fragment/edgecut.boundary_split`:

  round k:   compute BOUNDARY slice   (reads the buffered exchange xbuf)
             kick off the exchange    (round k+1's inputs — only the
                                       boundary rows just computed)
             compute INTERIOR slice   (overlaps the in-flight collective)
             join at the fold         (per-row select on the boundary mask)

Byte-identity argument (the pinned contract, tests/test_pipeline.py):

  * every REMOTE read of fragment g's state touches only g's boundary
    rows (that is the definition of boundary), and the kickoff payload
    carries exactly those rows' NEW values;
  * every LOCAL read goes through `splice`, which overlays the live
    local block over the buffered table — bitwise the serial value;
  * the boundary and interior slices partition the output rows, and
    each row's fold consumes exactly its own edges in their original
    CSR order — so the joined state equals the serial state bit for
    bit, inductively over rounds.

The exchange buffer `xbuf` is an INTERNAL while-loop carry: it is
created after PEval and dropped at loop exit, and it is a pure
function of the query carry (the exchange of the current state).  The
observable cut therefore never moves: guard digests, checkpoint
snapshots and watchdog residuals all observe the post-join carry —
the same consistent cut as the serial loop (docs/PIPELINE.md).

Engagement (`GRAPE_PIPELINE`):

  * unset / "0" / ""  — off: the serial loop body compiles bit-for-bit
    unchanged (lowered-HLO pinned);
  * "1" / "auto"      — engage when the modeled per-round exchange
    bytes (`mirror.exchange_bytes_ledger` — the SAME ledger the
    mirror auto mode reads) clear GRAPE_PIPELINE_MIN_BYTES (default
    1 MiB): latency-bound exchanges lose to the extra dispatch, the
    `_AUTO_MIN_BYTES` discipline;
  * "force"           — engage whenever structurally possible (tests,
    small-graph A/Bs).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from libgrape_lite_tpu.parallel.mirror import (
    exchange_bytes_ledger,
    pipelined_round_s,
)

# auto-mode engagement floor, same discipline (and the same shared
# byte ledger) as mirror._AUTO_MIN_BYTES: below ~1 MiB the exchange is
# collective-latency-bound and the split's extra dispatch loses
_MIN_BYTES_DEFAULT = 1 << 20

# ---- the worker pipeline contract (grape-lint R6) -------------------------
#
# Inside the pipelined window — between the exchange kickoff and the
# join — the ONLY reads of the query carry (or of ephemeral streams
# standing in for it) permitted by grape-lint rule R6 are the names
# below.  Entries ending in "*" are prefixes.  Every name here is an
# AUDITED read: it is safe precisely because the kickoff writes into a
# fresh double buffer and never aliases the live carry — the aliasing
# bug class this contract fossilizes.  Adding a read to the window
# means auditing it and naming it here, in review.
PIPELINE_WINDOW_READS = frozenset({
    # live carry leaves the interior slice folds against
    "dist", "depth", "comp", "rank",
    # CDLP's carry label plane (the join selector of the mode fold)
    # and its replicated rank LUT (read by both part folds)
    "labels", "lut",
    # PageRank's replicated scalars (read by the joined round_update)
    "step", "seed", "dangling_sum", "total_dangling",
    # the boundary mask (the join selector) and the interior streams
    "pl_bmask", "pl_i_src", "pl_i_nbr", "pl_i_val", "pl_i_w",
    # the second-direction streams of the directed double-pull round
    # (WCC oe leg) — both parts fold inside the window, which opens at
    # the FIRST kickoff of the round
    "pl2_*",
    # interior pack sub-plan streams (read inside PackDispatch.reduce)
    "pki_*",
})

# Callees AUDITED to receive the whole carry dict inside the window.
# R6 cannot see into another module's function body, so passing the
# full `state` to an un-named callee after the kickoff is flagged as a
# whole-carry escape; each name here was audited by hand:
#   reduce        PackDispatch.reduce — reads only its own pk*_ stream
#                 leaves (pki_*/pkb_ prefixes) plus the table argument
#   round_update  PageRank — reads the replicated scalar keys named in
#                 PIPELINE_WINDOW_READS above, elementwise per row
#   kickoff       PipelinePlan.kickoff — reads only its send_key leaf
#                 (the mirror send table, a static host stream), never
#                 a live carry value; the directed double-pull round
#                 issues a SECOND kickoff inside the first's window
#   splice        PipelinePlan.splice — reads nothing from the carry
#                 dict at all (mirror mode concatenates its explicit
#                 args; gather mode reads only ctx.fid())
PIPELINE_WINDOW_CALLEES = frozenset({
    "reduce", "round_update", "kickoff", "splice",
})

# resolve-path registry: the last pipeline decision + split stats, so
# plan_stats()/trace_report can surface boundary-set sizes without
# holding fragment references.  Federated as "pipeline"
# (obs/federation.py); mutation sites unchanged.
from libgrape_lite_tpu.obs.federation import FederatedStats as _FedStats

PIPELINE_STATS = _FedStats("pipeline", {
    "resolved": 0,        # plans built (engaged)
    "declined": 0,        # structurally eligible but below threshold/off
    "last_decision": None,
    "last_stats": None,
})


def pipeline_mode() -> str:
    """off | auto | force, from GRAPE_PIPELINE (default off: the
    serial superstep stays the compiled program until an A/B on real
    hardware flips the default — docs/PIPELINE.md)."""
    v = os.environ.get("GRAPE_PIPELINE", "") or "0"
    if v in ("0", "", "off"):
        return "off"
    if v == "force":
        return "force"
    return "auto"  # "1", "auto", anything else truthy


def pipeline_min_bytes() -> int:
    v = os.environ.get("GRAPE_PIPELINE_MIN_BYTES", "")
    return int(v) if v else _MIN_BYTES_DEFAULT


# Modeled rates for the overlap term come from the shared RateProfile
# (ops/calibration.py) — the module-level names stay as the pinned
# default's values for importers (fragment/partition.py, the recount
# in scripts/pack_cost_model.py) but live pricing reads the ACTIVE
# profile, so a fitted profile re-prices the engage decision.
from libgrape_lite_tpu.ops.calibration import (
    active_profile as _active_profile,
    default_profile as _default_profile,
)

VPU_LANES_PER_CYCLE = _default_profile().vpu_lanes_per_cycle
CLOCK_HZ = _default_profile().clock_hz
ICI_BPS = _default_profile().ici_bps
DEFAULT_OPS_PER_EDGE = 30.0     # op COUNT per edge (XLA gather+segment
#                                 fold, no pack ledger) — a counting
#                                 convention, not a rate; stays literal


def pipeline_min_hidden_us() -> float:
    """Priced engage floor (µs): in auto mode the overlap model must
    hide at least this much exchange per round or the pipeline
    declines.  Default 0 — the shipped byte threshold alone decides,
    bit-for-bit the pre-calibration behavior."""
    v = os.environ.get("GRAPE_PIPELINE_MIN_HIDDEN_US", "")
    return float(v) if v else 0.0


def overlap_model(boundary_edges: int, interior_edges: int,
                  exchange_bytes: int,
                  ops_per_edge: float | None = None,
                  profile=None) -> dict:
    """The exchange-overlap term of the op-budget ledger:

        t_serial    = compute_b + compute_i + exchange
        t_pipelined = max(compute_i, exchange) + compute_b

    (`mirror.pipelined_round_s` — max not sum).  Returns modeled round
    times plus `hidden_frac`, the fraction of the exchange hidden
    under interior compute (min(compute_i, exchange) / exchange) —
    the number the bench `pipeline` block and the obs query span
    report, and trace_report flags when it lands under 10%."""
    p = profile or _active_profile()
    ope = DEFAULT_OPS_PER_EDGE if ops_per_edge is None else ops_per_edge
    rate = p.vpu_lanes_per_cycle * p.clock_hz
    t_b = boundary_edges * ope / rate
    t_i = interior_edges * ope / rate
    t_x = exchange_bytes / p.ici_bps
    t_serial = t_b + t_i + t_x
    t_pipe = pipelined_round_s(t_i, t_x, t_b)
    hidden = min(t_i, t_x) / t_x if t_x > 0 else 0.0
    return {
        "t_serial_s": t_serial,
        "t_pipelined_s": t_pipe,
        "hidden_frac": round(hidden, 4),
        "round_speedup": round(t_serial / t_pipe, 4) if t_pipe > 0 else 1.0,
        "exchange_s": t_x,
        "compute_boundary_s": t_b,
        "compute_interior_s": t_i,
    }


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


@dataclass
class PipelinePlan:
    """One resolved boundary/interior pipeline for an app's pull.

    Host side: the split edge streams (or pack sub-dispatches) ride as
    ephemeral state leaves — `host_entries` merges into the app's init
    state exactly like mirror/pack tables (closure capture would trip
    grape-lint R1 and replicate under shard_map).  Traced side:
    `exchange`/`kickoff`/`splice` are the three collective touchpoints
    of the pipelined round (see module docstring)."""

    mode: str                  # "mirror" | "gather"
    key: str                   # the exchanged carry leaf ("dist", ...)
    fnum: int
    vp: int
    m: int                     # mirror slots (0 in gather mode)
    send_key: str              # state key of the mirror send table
    prefix: str = "pl_"
    pack_b: Optional[object] = None   # boundary PackDispatch
    pack_i: Optional[object] = None   # interior PackDispatch
    stats: dict = field(default_factory=dict)
    exchange_bytes: int = 0
    decision: dict = field(default_factory=dict)
    host_entries: dict = field(default_factory=dict)
    ops_per_edge: Optional[float] = None
    # second exchange leg of the directed double-pull round (WCC oe):
    # None when single-direction.  leg=2 on exchange/kickoff/splice
    # routes through these instead — same wiring, second direction.
    mode2: Optional[str] = None
    m2: int = 0
    send_key2: str = ""

    @property
    def uid(self) -> str:
        """STABLE content fingerprint of the compiled-trace-relevant
        plan shape — this rides in the app's `trace_key` (as
        `_pipeline_uid`) to keep serial and pipelined compiles in
        separate runner-cache entries.  It must be identical across
        re-resolves of the same plan: a per-resolve counter here made
        every query recompile (trace_key changed each init_state),
        which turned the bench A/B into a compile-time measurement.
        Stream SHAPES (split sizes, sub-plan skeletons) already key
        the runner cache via the state struct; this only needs the
        routing facts the struct cannot see."""
        return (
            f"{self.mode}:{self.fnum}:{self.vp}:{self.m}:"
            f"{'pack' if self.pack_b is not None else 'xla'}:"
            f"{self.mode2 or '-'}"
        )

    def _leg(self, leg: int):
        if leg == 2:
            if self.mode2 is None:
                raise ValueError("pipeline plan has no second leg")
            return self.mode2, self.send_key2
        return self.mode, self.send_key

    # ---- traced (inside shard_map) ----

    def exchange(self, ctx, x_local, state, leg: int = 1):
        """The halo exchange of `x_local`'s read rows — bitwise the
        payload of the serial round's exchange when the boundary rows
        of `x_local` are current (pad/interior rows are never read
        remotely).  Routed through the SAME StepContext collectives
        the serial round uses (one copy of the exchange wiring); the
        mirror form drops the helper's leading live-local block — the
        buffer must hold only remote rows, `splice` re-attaches the
        LIVE local block at read time.  `leg=2` is the second
        direction of the directed double-pull round."""
        mode, send_key = self._leg(leg)
        if mode == "mirror":
            compact = ctx.exchange_mirrors(
                x_local, state[send_key]
            )
            return compact[self.vp:]
        return ctx.gather_state(x_local)

    def kickoff(self, ctx, x_kick, state, leg: int = 1):
        """Kick off the NEXT pull's exchange from the boundary-merged
        carry (new values at boundary rows, stale elsewhere — the
        stale rows are never read).  Distinct name on purpose: this
        call opens the pipelined window grape-lint R6 audits."""
        return self.exchange(ctx, x_kick, state, leg=leg)

    def splice(self, ctx, x_local, state, xbuf, leg: int = 1):
        """The full pull table for this round: LIVE local rows overlaid
        on the buffered remote rows — local reads are bitwise the
        serial value, remote reads hit only (current) boundary rows."""
        import jax.numpy as jnp
        from jax import lax

        mode, _ = self._leg(leg)
        if mode == "mirror":
            return jnp.concatenate([x_local, xbuf])
        fid = ctx.fid()
        return lax.dynamic_update_slice(xbuf, x_local, (fid * self.vp,))

    # ---- host side ----

    def span_brief(self) -> dict:
        """The obs query-span attachment (and the bench `pipeline`
        block's modeled half)."""
        t = self.stats.get("totals", {})
        model = overlap_model(
            t.get("boundary_edges", 0), t.get("interior_edges", 0),
            self.exchange_bytes, self.ops_per_edge,
        )
        return {
            "engaged": True,
            "mode": self.mode,
            "plan_uid": self.uid,
            "exchange_bytes": self.exchange_bytes,
            "modeled_hidden_frac": model["hidden_frac"],
            "hidden_us_per_round": self.hidden_us_per_round(),
            "boundary_vertices": t.get("boundary_vertices", 0),
            "interior_vertices": t.get("interior_vertices", 0),
            "boundary_edges": t.get("boundary_edges", 0),
            "interior_edges": t.get("interior_edges", 0),
        }

    def hidden_us_per_round(self) -> float:
        """Modeled exchange time hidden under interior compute, per
        superstep, in µs: min(compute_interior, exchange).  The obs
        query span records `overlap_hidden_us` = this x rounds, and
        trace_report's overlap column prints it per superstep with a
        drift flag when the plan is armed but hides <10% of the
        exchange."""
        t = self.stats.get("totals", {})
        model = overlap_model(
            t.get("boundary_edges", 0), t.get("interior_edges", 0),
            self.exchange_bytes, self.ops_per_edge,
        )
        return round(
            min(model["compute_interior_s"], model["exchange_s"]) * 1e6,
            3,
        )


def _split_streams(frag, bmask: np.ndarray, direction: str, mirror,
                   with_weights: bool, prefix: str) -> dict:
    """Stable row-partitioned edge streams for the XLA fold path.

    Per part (b = boundary rows, i = interior rows) and per fragment:
    `src` (pad -> vp overflow row), `nbr` (compact columns under a
    mirror plan, pids otherwise; pad -> 0), `val` (validity), and `w`
    when weighted — each padded to the per-part max across shards
    (one traced program under shard_map).  Within a part the original
    CSR edge order is preserved, so every row's fold consumes its own
    candidates in the serial order (the byte-identity invariant; for
    float sums this additionally relies on XLA's order-deterministic
    sorted segment reduction, pinned by tests/test_pipeline.py)."""
    fnum, vp = frag.fnum, frag.vp
    csrs = frag.host_ie if direction == "ie" else frag.host_oe
    parts = {"b": [], "i": []}
    for f in range(fnum):
        h = csrs[f]
        mask = h.edge_mask
        src = h.edge_src.astype(np.int64)
        cols = (
            mirror.nbr_compact[f] if mirror is not None else h.edge_nbr
        ).astype(np.int64)
        safe_src = np.minimum(src, vp - 1)
        is_b = np.logical_and(mask, bmask[f][safe_src])
        is_i = np.logical_and(mask, ~bmask[f][safe_src])
        for part, sel in (("b", is_b), ("i", is_i)):
            idx = np.flatnonzero(sel)
            parts[part].append((
                src[idx].astype(np.int32),
                cols[idx].astype(np.int32),
                None if not with_weights else h.edge_w[idx],
            ))
    out = {prefix + "bmask": bmask}
    for part, shards in parts.items():
        cap = _round_up(max([len(s[0]) for s in shards] + [1]), 128)
        src_a = np.full((fnum, cap), vp, dtype=np.int32)
        nbr_a = np.zeros((fnum, cap), dtype=np.int32)
        val_a = np.zeros((fnum, cap), dtype=bool)
        w_a = (
            np.zeros((fnum, cap), dtype=csrs[0].edge_w.dtype)
            if with_weights else None
        )
        for f, (src, nbr, w) in enumerate(shards):
            n = len(src)
            src_a[f, :n] = src
            nbr_a[f, :n] = nbr
            val_a[f, :n] = True
            if w_a is not None:
                w_a[f, :n] = w
        p = f"{prefix}{part}_"
        out[p + "src"] = src_a
        out[p + "nbr"] = nbr_a
        out[p + "val"] = val_a
        if w_a is not None:
            out[p + "w"] = w_a
    return out


def resolve_pipeline(frag, *, app_name: str, key: str,
                     direction: str = "ie", mirror=None,
                     mx_prefix: str = "mx_", pack=None,
                     fold: str = "min", with_weights: bool = False,
                     eligible: bool = True, reason: str = "",
                     direction2: str | None = None, mirror2=None,
                     mx2_prefix: str = "mx_oe_"):
    """Resolve the superstep pipeline for one app's pull, or None.

    `mirror`/`pack` are the app's ALREADY-RESOLVED exchange and SpMV
    backends — the pipelined round must use the same exchange mode and
    the same fold machinery as the serial one, or byte-identity is
    off the table.  Decline reasons are recorded in
    PIPELINE_STATS["last_decision"] (and vlogged), never silent.

    `direction2` requests the directed DOUBLE-PULL round (WCC on a
    directed graph: an ie pull then an oe pull per superstep).  The
    boundary mask becomes the JOINT split over both directions — a row
    any remote fragment reads through either edge orientation is
    boundary — so each pull's kickoff payload is current at every
    remotely-read row, and the second leg's streams ride under the
    `pl2_` prefix with their own exchange mode (`mirror2`)."""
    from libgrape_lite_tpu.utils import logging as glog

    mode = pipeline_mode()
    prof = _active_profile()
    decision = {"app": app_name, "mode": mode, "engaged": False,
                "profile": prof.label()}

    def declined(why: str, count: bool = True):
        decision["reason"] = why
        PIPELINE_STATS["last_decision"] = decision
        if count:
            PIPELINE_STATS["declined"] += 1
            glog.vlog(1, "pipeline: declined for %s: %s", app_name, why)
        return None

    if mode == "off":
        return declined("GRAPE_PIPELINE off", count=False)
    if not eligible:
        return declined(reason or "app declared ineligible")
    if frag.fnum <= 1:
        return declined("fnum==1: no exchange to overlap")
    ov = getattr(frag, "dyn_overlay", None)
    if ov is not None:
        return declined("dyn overlay attached (pid-addressed reads)")
    if fold == "sum" and pack is not None:
        # split pack sub-plans regroup float partial sums — exact for
        # min/max folds, only allclose for sums (the documented pack
        # float-parity limit); byte-identity wins
        return declined("sum fold over the pack backend is not "
                        "bit-stable under a split plan")
    if direction2 is not None and pack is not None:
        # the double-pull round would need FOUR pack sub-plans (b/i per
        # direction) whose split fold order is unaudited against the
        # serial two-pull round; the XLA stream path is the pipelined
        # form until that audit lands
        return declined("directed double-pull over the pack backend "
                        "is unaudited; XLA streams only")

    xmode = "mirror" if mirror is not None else "gather"
    bytes_ledger = exchange_bytes_ledger(
        frag.fnum, frag.vp, mirror.m if mirror is not None else None
    )
    xbytes = bytes_ledger[xmode] or 0
    xmode2 = None
    if direction2 is not None:
        xmode2 = "mirror" if mirror2 is not None else "gather"
        ledger2 = exchange_bytes_ledger(
            frag.fnum, frag.vp,
            mirror2.m if mirror2 is not None else None,
        )
        xbytes += ledger2[xmode2] or 0
    decision["exchange_bytes"] = xbytes
    decision["min_bytes"] = pipeline_min_bytes()
    if mode == "auto" and xbytes < pipeline_min_bytes():
        return declined(
            f"modeled exchange bytes {xbytes} below threshold "
            f"{pipeline_min_bytes()} (latency-bound; set "
            "GRAPE_PIPELINE_MIN_BYTES or =force to override)"
        )

    from libgrape_lite_tpu.fragment.edgecut import (
        boundary_split, boundary_stats,
    )

    directions = (direction,) if direction2 is None \
        else (direction, direction2)
    bmask = boundary_split(frag, directions)
    stats = boundary_stats(frag, bmask, direction)
    if direction2 is not None:
        # both pulls fold inside the same round: edge totals sum, the
        # vertex split is shared (one joint mask)
        stats2 = boundary_stats(frag, bmask, direction2)
        for part in ("boundary_edges", "interior_edges"):
            stats["totals"][part] = (
                stats["totals"].get(part, 0)
                + stats2["totals"].get(part, 0)
            )

    min_hidden = pipeline_min_hidden_us()
    if mode == "auto" and min_hidden > 0:
        tot = stats["totals"]
        model = overlap_model(
            tot.get("boundary_edges", 0), tot.get("interior_edges", 0),
            xbytes, profile=prof,
        )
        hidden_us = min(model["compute_interior_s"],
                        model["exchange_s"]) * 1e6
        decision["modeled_hidden_us"] = round(hidden_us, 3)
        # grape-lint R12: a modeled claim must carry its trace
        # correlation key even on the declined path (same recipe as
        # PipelinePlan.uid; re-stamped authoritatively on engage)
        decision["plan_uid"] = (
            f"{xmode}:{frag.fnum}:{frag.vp}:"
            f"{mirror.m if mirror is not None else 0}:"
            f"{'pack' if pack is not None else 'xla'}:{xmode2 or '-'}"
        )
        if hidden_us < min_hidden:
            return declined(
                f"modeled hidden exchange {hidden_us:.2f}us under "
                f"profile {prof.label()} is below the "
                f"GRAPE_PIPELINE_MIN_HIDDEN_US={min_hidden:g} floor"
            )

    pack_b = pack_i = None
    host_entries = {}
    ops_per_edge = None
    if pack is not None:
        from libgrape_lite_tpu.ops.spmv_pack import resolve_pack_dispatch

        inner = frag.host_inner_mask()
        pack_b = resolve_pack_dispatch(
            frag, direction=direction, prefix="pkb_", mirror=mirror,
            with_weights=with_weights, role="boundary", row_mask=bmask,
        )
        pack_i = resolve_pack_dispatch(
            frag, direction=direction, prefix="pki_", mirror=mirror,
            with_weights=with_weights, role="interior",
            row_mask=np.logical_and(inner, ~bmask),
        )
        if pack_b is None or pack_i is None:
            return declined("pack split sub-plans not buildable "
                            "(empty partition?)")
        led = pack.ledger()
        if led and led.get("edges"):
            ops_per_edge = led["totals"]["vpu_ops"] / led["edges"]
        host_entries.update(pack_b.state_entries())
        host_entries.update(pack_i.state_entries())
        host_entries["pl_bmask"] = bmask
    else:
        host_entries.update(_split_streams(
            frag, bmask, direction, mirror, with_weights, "pl_"
        ))
        if direction2 is not None:
            h2 = _split_streams(
                frag, bmask, direction2, mirror2, with_weights, "pl2_"
            )
            h2.pop("pl2_bmask")  # one joint mask, already under pl_
            host_entries.update(h2)

    decision["engaged"] = True
    plan = PipelinePlan(
        mode=xmode, key=key, fnum=frag.fnum, vp=frag.vp,
        m=mirror.m if mirror is not None else 0,
        send_key=mx_prefix + "send",
        pack_b=pack_b, pack_i=pack_i,
        stats=stats, exchange_bytes=xbytes, decision=decision,
        host_entries=host_entries, ops_per_edge=ops_per_edge,
        mode2=xmode2,
        m2=mirror2.m if mirror2 is not None else 0,
        send_key2=mx2_prefix + "send",
    )
    decision["plan_uid"] = plan.uid  # the truth meter's join key
    PIPELINE_STATS["resolved"] += 1
    PIPELINE_STATS["last_decision"] = decision
    PIPELINE_STATS["last_stats"] = stats
    glog.vlog(
        1, "pipeline: engaged for %s (%s exchange, %d B/round, "
        "%d boundary / %d interior vertices)",
        app_name, xmode, xbytes,
        stats["totals"].get("boundary_vertices", 0),
        stats["totals"].get("interior_vertices", 0),
    )
    return plan


# ---- the 2-D vertex-cut (SUMMA) pipeline ----------------------------------


@dataclass
class VC2DPipelinePlan:
    """The pipelined SUMMA round: a two-phase split of each tile's COO
    edge ring so the row-axis `pmin` of the phase-0 partial overlaps
    the phase-1 tile-local fold (docs/PARTITION2D.md "Overlapped
    round").

      serial:     partial = fold(ALL edge slots); pmin(row); transpose
      pipelined:  p0 = fold(slots [:split]); r0 = pmin(p0)  <- kicked
                  p1 = fold(slots [split:])                 <- overlaps
                  r1 = pmin(p1); relax = min(r0, r1); transpose

    Byte-identity argument: min is associative/commutative and
    idempotent over any regrouping of the same candidate multiset, and
    both folds run the identical segment reduction over disjoint
    static slices of the SAME per-shard edge arrays — min(r0, r1)
    is elementwise equal, bit for bit, to the serial pmin of the
    unsplit fold (ints and IEEE floats alike; no float addition
    regroups).  The phase split is static slicing of the device COO —
    no extra host streams, so `host_entries` is empty and the
    exchange buffer is an inert scalar (the SUMMA round has no
    cross-round halo table to double-buffer).

    The split doubles the COLLECTIVE COUNT (two [vc] pmins instead of
    one) but only the first is hidden; `exchange_bytes` prices the
    hideable leg and the auto gate sees exactly that."""

    k: int
    vc: int
    split: int                  # phase-0 edge-slot count (per shard)
    stats: dict = field(default_factory=dict)
    exchange_bytes: int = 0
    decision: dict = field(default_factory=dict)
    host_entries: dict = field(default_factory=dict)
    ops_per_edge: Optional[float] = None
    mode: str = "vc2d"

    @property
    def uid(self) -> str:
        """Stable trace fingerprint (rides `_pipeline_uid` in the
        app's trace_key, same contract as PipelinePlan.uid)."""
        return f"vc2d:{self.k}:{self.vc}:{self.split}"

    def span_brief(self) -> dict:
        t = self.stats.get("totals", {})
        model = overlap_model(
            t.get("boundary_edges", 0), t.get("interior_edges", 0),
            self.exchange_bytes, self.ops_per_edge,
        )
        return {
            "engaged": True,
            "mode": self.mode,
            "plan_uid": self.uid,
            "exchange_bytes": self.exchange_bytes,
            "modeled_hidden_frac": model["hidden_frac"],
            "hidden_us_per_round": self.hidden_us_per_round(),
            "boundary_vertices": t.get("boundary_vertices", 0),
            "interior_vertices": t.get("interior_vertices", 0),
            "boundary_edges": t.get("boundary_edges", 0),
            "interior_edges": t.get("interior_edges", 0),
        }

    def hidden_us_per_round(self) -> float:
        t = self.stats.get("totals", {})
        model = overlap_model(
            t.get("boundary_edges", 0), t.get("interior_edges", 0),
            self.exchange_bytes, self.ops_per_edge,
        )
        return round(
            min(model["compute_interior_s"], model["exchange_s"]) * 1e6,
            3,
        )


def resolve_vc2d_pipeline(frag, *, app_name: str, pack=None,
                          src_pull: bool = False,
                          dtype_bytes: int = 4):
    """Resolve the pipelined SUMMA round for a vc2d app, or None.

    Same engagement ladder as `resolve_pipeline` (GRAPE_PIPELINE
    off/auto/force, the byte and hidden-µs auto floors, declines
    recorded in PIPELINE_STATS — never silent), with the vc2d
    structural gates:

      * `src_pull` (directed WCC's column-axis pull) declines — the
        second pull folds the TRANSPOSED relax of the first, a
        dependent chain with no independent work to overlap;
      * a resolved per-tile pack plan declines — it is one fused
        dispatch whose phase split is unaudited;
      * a tile ring too small to split in two 128-multiple phases
        declines (nothing to overlap).

    The decision record always carries the rate-profile label and the
    modeled `hidden_us_per_round` (the bench `vc2d_pipeline` lane
    gates on both being present)."""
    from libgrape_lite_tpu.utils import logging as glog

    mode = pipeline_mode()
    prof = _active_profile()
    decision = {"app": app_name, "mode": mode, "engaged": False,
                "profile": prof.label(), "plan": "vc2d"}

    def declined(why: str, count: bool = True):
        decision["reason"] = why
        PIPELINE_STATS["last_decision"] = decision
        if count:
            PIPELINE_STATS["declined"] += 1
            glog.vlog(1, "pipeline: declined for %s: %s", app_name, why)
        return None

    if mode == "off":
        return declined("GRAPE_PIPELINE off", count=False)
    k = int(frag.k)
    if k <= 1:
        return declined("k==1: the row-axis pmin is a no-op")
    if src_pull:
        return declined(
            "directed src-pull round: the column-axis pull consumes "
            "the transposed row relax — a dependent chain with no "
            "independent fold to overlap"
        )
    if pack is not None:
        return declined(
            "per-tile pack plan resolved: a single fused dispatch "
            "whose phase split is unaudited; unset GRAPE_SPMV=pack "
            "to pipeline the 2-D round"
        )

    _, _, _, m_arr = frag._host_tiles
    ep = int(m_arr.shape[1])
    split = min(_round_up(max(ep // 2, 1), 128), ep)
    if split >= ep:
        return declined(
            f"tile edge ring too small to split ({ep} slots): "
            "nothing to overlap"
        )

    # the hideable collective: ONE row-axis pmin of the [vc] partial
    # per device — ring all-reduce over the k row peers
    vc = int(frag.vc)
    xbytes = int(vc * dtype_bytes * 2 * (k - 1) / k)
    decision["exchange_bytes"] = xbytes
    decision["min_bytes"] = pipeline_min_bytes()

    # real (unpadded) edges per phase, summed over tiles — the phase-0
    # fold is the "boundary" (pre-kick) term of the overlap model, the
    # phase-1 fold the overlapped "interior" term
    e0 = int(m_arr[:, :split].sum())
    e1 = int(m_arr[:, split:].sum())
    stats = {"totals": {
        "boundary_edges": e0, "interior_edges": e1,
        "boundary_vertices": 0, "interior_vertices": 0,
        "phase_split": split, "edge_slots": ep,
    }}
    model = overlap_model(e0, e1, xbytes, profile=prof)
    hidden_us = min(model["compute_interior_s"],
                    model["exchange_s"]) * 1e6
    decision["modeled_hidden_us"] = round(hidden_us, 3)
    # grape-lint R12: the modeled claim carries its trace key even
    # when a later gate declines (same recipe as VC2DPipelinePlan.uid)
    decision["plan_uid"] = f"vc2d:{k}:{vc}:{split}"

    if mode == "auto" and xbytes < pipeline_min_bytes():
        return declined(
            f"modeled pmin bytes {xbytes} below threshold "
            f"{pipeline_min_bytes()} (latency-bound; set "
            "GRAPE_PIPELINE_MIN_BYTES or =force to override)"
        )
    min_hidden = pipeline_min_hidden_us()
    if mode == "auto" and min_hidden > 0 and hidden_us < min_hidden:
        return declined(
            f"modeled hidden pmin {hidden_us:.2f}us under profile "
            f"{prof.label()} is below the "
            f"GRAPE_PIPELINE_MIN_HIDDEN_US={min_hidden:g} floor"
        )

    decision["engaged"] = True
    plan = VC2DPipelinePlan(
        k=k, vc=vc, split=split, stats=stats,
        exchange_bytes=xbytes, decision=decision,
    )
    PIPELINE_STATS["resolved"] += 1
    PIPELINE_STATS["last_decision"] = decision
    PIPELINE_STATS["last_stats"] = stats
    glog.vlog(
        1, "pipeline: engaged vc2d for %s (k=%d, split %d/%d slots, "
        "%d B pmin/round)", app_name, k, split, ep, xbytes,
    )
    return plan
