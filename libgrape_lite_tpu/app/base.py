"""App-framework API: the TPU PIE model.

Re-design of `grape/app/*`:
  * `ParallelAppBase` (`parallel_app_base.h:38-109`) — PEval/IncEval +
    static traits,
  * `AutoAppBase` (`auto_app_base.h:38-84`) — implicit messaging,
  * `BatchShuffleAppBase` (`batch_shuffle_app_base.h`) — whole-array sync,
  * `GatherScatterAppBase` (`gather_scatter_app_base.h:30-61`) —
    vertex-cut apps,
  * `ContextBase` / `VertexDataContext` (`context_base.h`,
    `vertex_data_context.h:24-80`).

The TPU contract: an app provides

  * `init_state(frag, **query_args)` — host-side: build the initial
    per-fragment state (numpy arrays stacked `[fnum, ...]`; leaves named
    in `replicated_keys` are mesh-replicated scalars/arrays).  This is
    the host half of PEval (e.g. placing the source distance).
  * `peval(ctx, frag, state) -> (state, active)` — traced per shard
    (inside `shard_map`); first superstep.
  * `inceval(ctx, frag, state) -> (state, active)` — traced per shard;
    repeated until the `psum`-reduced `active` vote is zero (the
    reference's termination allreduce,
    `parallel_message_manager.h:123-138`) or `max_rounds` is hit.
  * `finalize(frag, state) -> np.ndarray [fnum, vp]` — host-side
    assemble: per-vertex output values.

`ctx` is the `Communicator` namespace (psum/pmin/pmax/all_gather/
ppermute) plus the gather helper; messaging *is* collectives — there is
no buffer/archive machinery to port because XLA owns the transport.
"""

from __future__ import annotations

from typing import Dict, FrozenSet

import jax.numpy as jnp
import numpy as np
from jax import lax

from libgrape_lite_tpu.fragment.edgecut import DeviceFragment
from libgrape_lite_tpu.parallel.comm_spec import FRAG_AXIS
from libgrape_lite_tpu.parallel.communicator import Communicator
from libgrape_lite_tpu.utils.types import LoadStrategy, MessageStrategy


class StepContext(Communicator):
    """Per-superstep toolkit handed to app code while tracing."""

    @staticmethod
    def gather_state(x_local):
        """Local per-vertex block [vp, ...] -> full pid-indexed array
        [fnum * vp, ...].  The TPU form of BatchShuffle's
        `SyncInnerVertices` + `UpdateOuterVertices`
        (`batch_shuffle_message_manager.h:237,264`): one `all_gather`
        over ICI replaces per-neighbor mirror buffers."""
        return lax.all_gather(x_local, FRAG_AXIS, tiled=True)

    @staticmethod
    def fid():
        return lax.axis_index(FRAG_AXIS)

    @staticmethod
    def exchange_mirrors(x_local, send_idx):
        """Mirror-compressed form of `gather_state` (reference
        `batch_shuffle_message_manager.h:237-264`): exchange only the
        outer-vertex rows each neighbor shard actually references.

        x_local: this shard's [vp] state; send_idx: this shard's
        [fnum, m] send table (rows ordered by receiver, from
        `parallel/mirror.MirrorPlan`).  Returns the compact
        [vp + fnum*m] table addressed by the plan's `nbr_compact`
        columns — O(vp + mirrors) instead of O(fnum*vp)."""
        vals = x_local[send_idx]
        recv = lax.all_to_all(
            vals, FRAG_AXIS, split_axis=0, concat_axis=0, tiled=True
        )
        return jnp.concatenate([x_local, recv.reshape(-1)])


def source_lane_array(frag, source, app_name: str, fill, hit, dtype):
    """(batched, arr): the serve source-vector contract's shared
    scaffolding.  `source` is one query id or a SEQUENCE of k lane ids
    (`batch_query_key`); `arr` is [k, fnum, vp] holding `hit` at each
    resolved source and `fill` everywhere else — SSSP seeds distances
    (inf/0), BFS depths (sentinel/0), personalized PageRank its
    teleport vector (0/1).  An absent or None source leaves its lane
    all-`fill` (the unreachable/zero-mass convention)."""
    batched = isinstance(source, (list, tuple, np.ndarray))
    sources = list(source) if batched else [source]
    arr = np.full((len(sources), frag.fnum, frag.vp), fill, dtype=dtype)
    for b, s in enumerate(sources):
        pid = resolve_source(frag, s, app_name) if s is not None else -1
        if pid >= 0:
            arr[b, pid // frag.vp, pid % frag.vp] = hit
    return batched, arr


def resolve_source(frag, source, app_name: str) -> int:
    """oid -> pid for a query source, logging when absent (shared by
    SSSP/BFS/BC; the reference's GetInnerVertex miss is silent, a
    warning is strictly more debuggable)."""
    pid = int(frag.oid_to_pid(np.array([source]))[0])
    if pid < 0:
        from libgrape_lite_tpu.utils import logging as glog

        glog.log_info(
            f"{app_name}: source {source!r} is not in the vertex map; "
            "all vertices will be unreachable"
        )
    return pid


class ContextBase:
    """Per-query mutable state descriptor (reference `context_base.h`).
    In the TPU build context state *is* the state pytree; this class only
    carries metadata used by the driver."""


class VertexDataContext(ContextBase):
    """Marker for apps whose result is one value per vertex
    (reference `vertex_data_context.h:24-80`)."""


class AppBase:
    # trait parity (parallel_app_base.h:42-46)
    load_strategy: LoadStrategy = LoadStrategy.kBothOutIn
    message_strategy: MessageStrategy = MessageStrategy.kSyncOnOuterVertex
    need_split_edges: bool = False

    # state keys that are mesh-replicated (everything else is sharded
    # with leading fragment dim)
    replicated_keys: FrozenSet[str] = frozenset()

    # state keys that are read-only trace INPUTS, not loop state: they
    # enter the jitted superstep sharded like normal leaves but are
    # excluded from the while_loop carry and from the outputs (the
    # pack pipeline's per-shard stream tables ride in this way —
    # constants can't, because closing over an array under shard_map
    # replicates it to every device)
    ephemeral_keys: FrozenSet[str] = frozenset()

    # which mesh the superstep runs on: "frag" = the 1-D fragment axis
    # (default); "vc2d" = the k x k (vcrow, vccol) SUMMA mesh for
    # vertex-cut apps (CommSpec.mesh2d)
    mesh_kind: str = "frag"

    # serve/: the query arg that varies per lane of a batched
    # multi-source dispatch (e.g. "source" for SSSP/BFS).  When set,
    # `init_state` must also accept a SEQUENCE of k values for that arg
    # and return carry leaves with a leading [k] lane axis while
    # building ephemeral leaves (pack streams, mirror tables) ONCE —
    # shared across lanes.  None = no native vector support; the
    # generic `init_state_batch` stacking fallback applies.
    batch_query_key: str | None = None

    # dyn/: True when the app folds a fragment's staged delta-edge
    # overlay (frag.dyn_overlay) into its pull reduction — sound only
    # for min-fold apps, where extra candidates merge exactly.  Apps
    # without the contract must not run while an overlay holds staged
    # edges (they would silently see the stale graph); Worker.query
    # enforces this, and ServeSession repacks first.
    dyn_overlay_support: bool = False

    # dyn/: the incremental-IncEval contract (dyn/incremental.py).
    #   None            — no contract; query_incremental recomputes cold
    #   "monotone-min"  — additive deltas reuse the previous fixed
    #                     point: seeded = min(fresh_init, migrated prev)
    #                     per key in `inc_seed_keys`, byte-identical to
    #                     a cold run on the mutated graph
    #   "restart"       — declared, but the iteration has no reusable
    #                     fixed point (fixed-round PageRank): cold, counted
    inc_mode: str | None = None
    inc_seed_keys: Dict[str, str] = {}

    def inc_value_map(self, key: str, values: np.ndarray, old_frag,
                      new_frag) -> np.ndarray:
        """Remap carry VALUES across a repack (row migration is the
        framework's job; value remapping is the app's).  Default:
        identity — right for distances/depths; WCC overrides to
        re-address its pid-valued component labels."""
        return values

    def custom_specs(self) -> Dict:
        """Per-key PartitionSpec overrides for state leaves that are
        neither [fnum, ...]-sharded nor replicated (e.g. SUMMA row/col
        chunk state, P("vcrow") / P("vccol")).  These leaves pass into
        the traced step as their per-shard blocks, unsqueezed."""
        return {}

    # ---- superstep pipelining (parallel/pipeline.py, r9) ----
    #
    # Apps whose round is "exchange -> pull-reduce -> fold" can run
    # software-pipelined: compute the boundary slice, kick off the next
    # round's halo exchange, overlap the interior slice with the
    # in-flight collective, join at the fold.  `init_state` resolves
    # the plan (resolve_pipeline — env gate + byte threshold + app
    # eligibility) into `self._pipeline` and merges its host entries
    # into the ephemeral state; the worker routes the fused/chunked
    # loop through `inceval_pipelined` when a plan resolved.  The
    # SERIAL inceval stays untouched either way — stepwise, batched
    # and dyn paths keep it, and byte-identity between the two bodies
    # is the pinned contract (tests/test_pipeline.py).
    pipeline_state_key: str | None = None  # the exchanged carry leaf
    _pipeline = None                       # resolved PipelinePlan | None

    def pipeline_exchange(self, ctx: StepContext, frag, state):
        """The halo exchange producing round k+1's pull inputs from the
        current carry — the worker calls this once at loop entry (and
        at every guarded-chunk re-entry: the buffer is a pure function
        of the carry, so the re-derived value is bitwise the in-flight
        one and the observable cut never moves)."""
        return self._pipeline.exchange(
            ctx, state[self.pipeline_state_key], state
        )

    def inceval_pipelined(self, ctx: StepContext, frag, state, xbuf):
        """One pipelined superstep: (state', active, xbuf') — the
        double-buffered form of `inceval`.  Only called when
        `self._pipeline` resolved; results must be byte-identical to
        `inceval` (the reads inside the post-kickoff window are audited
        against parallel/pipeline.PIPELINE_WINDOW_READS by grape-lint
        R6)."""
        raise NotImplementedError(
            f"{type(self).__name__} resolved a pipeline plan but "
            "implements no inceval_pipelined"
        )

    # 0 means "run until the termination vote fires"
    max_rounds: int = 0

    # output formatting
    result_format: str = "float"  # float | int | sssp_infinity

    def init_state(self, frag, **query_args) -> Dict:
        raise NotImplementedError

    def init_state_batch(self, frag, args_list) -> Dict:
        """Initial state for k query lanes (serve/ batched dispatch):
        carry leaves gain a leading [k] lane axis; ephemeral leaves
        (read-only trace inputs) stay unbatched and shared.

        Apps with a `batch_query_key` and lane-uniform remaining args
        get the cheap path — ONE init_state call with the vector arg,
        so per-query host work (pack-plan resolve, stream builds) is
        paid once.  Everything else falls back to one init_state per
        lane with the carry leaves stacked (lane 0's ephemeral leaves
        are adopted for the batch: plans are deterministic per
        fragment, so every lane builds identical streams)."""
        if not args_list:
            raise ValueError("init_state_batch needs at least one lane")
        key = self.batch_query_key
        if key is not None:
            fixed = {k: v for k, v in args_list[0].items() if k != key}
            if all(
                {k: v for k, v in a.items() if k != key} == fixed
                for a in args_list[1:]
            ):
                return self.init_state(
                    frag, **fixed,
                    **{key: [a.get(key, 0) for a in args_list]},
                )
        states = [self.init_state(frag, **a) for a in args_list]
        eph = frozenset(getattr(self, "ephemeral_keys", ()) or ())
        return {
            k: (states[0][k] if k in eph
                else np.stack([s[k] for s in states]))
            for k in states[0]
        }

    def peval(self, ctx: StepContext, frag: DeviceFragment, state: Dict):
        raise NotImplementedError

    def inceval(self, ctx: StepContext, frag: DeviceFragment, state: Dict):
        raise NotImplementedError

    def finalize(self, frag, state: Dict):
        raise NotImplementedError

    # ---- runtime invariants (guard/) ----
    #
    # Named device-side predicates over consecutive carries, evaluated
    # by the guard monitor when GRAPE_GUARD (or Worker.query(guard=...))
    # arms it: every round in stepwise execution, at every chunk
    # boundary in the guarded-fused path.  The default is the generic
    # floor (NaN-free float carries); apps override to declare their
    # algebraic invariants (monotone distances, conserved mass, label
    # ranges).  `state` is the example carry (placed leaves) — use it
    # to inspect dtypes/keys; predicates themselves are traced.

    def invariants(self, frag, state: Dict) -> list:
        from libgrape_lite_tpu.guard.invariants import default_invariants

        return default_invariants(self, frag, state)

    # ---- MutationContext (reference grape/app/mutation_context.h) ----
    #
    # Apps that mutate the graph mid-query define `collect_mutations`;
    # the stepwise worker calls it between supersteps
    # (reference worker.h:211-222 applies staged mutations through
    # BasicFragmentMutator between rounds) and rebuilds the fragment.
    # State migrates by oid via `migrate_state` (default: aligned copy;
    # new vertices take init_state defaults).

    def migrate_state(self, old_frag, new_frag, old_state, new_state):
        """Copy per-vertex state rows across a rebuild, matching by oid."""
        from libgrape_lite_tpu.fragment.mutation import oid_row_alignment

        of, ol, nf, nl = oid_row_alignment(old_frag, new_frag)
        out = dict(new_state)
        for k, v in new_state.items():
            if k in self.replicated_keys:
                out[k] = old_state.get(k, v)
                continue
            ov = old_state.get(k)
            if (
                ov is not None
                and np.ndim(ov) >= 2
                and ov.shape[:2] == (old_frag.fnum, old_frag.vp)
                and np.ndim(v) >= 2
                and v.shape[:2] == (new_frag.fnum, new_frag.vp)
            ):
                nv = np.array(v)
                nv[nf, nl] = ov[of, ol]
                out[k] = nv
        return out

    def trace_key(self):
        """Hashable fingerprint of every hyperparameter that gets baked
        into the traced superstep (used to key the compiled-runner
        cache).  Default: all primitive instance attributes."""
        items = []
        for k, v in sorted(self.__dict__.items()):
            if isinstance(v, (int, float, str, bool, type(None), np.dtype)):
                items.append((k, v))
        return tuple(items)

    # ---- shared compute helpers ----

    @staticmethod
    def segment_reduce(values, edge_src, vp, kind="sum"):
        """Reduce per-edge values into per-vertex rows; padded edges fall
        into the overflow row `vp` which is sliced off.  This is the TPU
        ForEachEdge: edge-parallel, degree-oblivious (the role of the
        reference CUDA LB kernels, `cuda/parallel/parallel_engine.h`)."""
        from libgrape_lite_tpu.ops.segment import segment_reduce

        return segment_reduce(values, edge_src, vp, kind)

    @staticmethod
    def dyn_min_fold(relaxed, state: Dict, vp: int, prefix: str, cand):
        """Merge the staged delta-edge overlay (dyn/ingest.py) into a
        pull-mode min reduction.  `cand` is the [capacity] per-slot
        candidate vector, already masked to the fold's neutral element
        on inactive slots; rows come from the overlay's lid-sorted
        `src` plane (pad slots route to the vp overflow row).  `min`
        is associative, so the merged result is byte-identical to a
        cold query on the rebuilt mutated graph — the whole point of
        the side-path: the packed CSR, its plans, and the compiled
        runner never change."""
        extra = AppBase.segment_reduce(
            cand, state[prefix + "src"], vp, "min"
        )
        return jnp.minimum(relaxed, extra)


class ParallelAppBase(AppBase):
    """Explicit-messaging superstep app (reference ParallelAppBase)."""


class BatchShuffleAppBase(AppBase):
    """Whole-array mirror-sync app (PageRank-style)."""

    message_strategy = MessageStrategy.kSyncOnOuterVertex


class AutoAppBase(AppBase):
    """Auto-messaging app (reference `auto_app_base.h:38-84` +
    `auto_parallel_message_manager.h:47-365`): the app registers
    SyncBuffers (state-key -> aggregate op) and writes only the local
    compute; messaging is implicit.

    TPU mapping: `propose(ctx, frag, state)` returns, per synced key, a
    full pid-indexed [n_pad] proposal array (neutral element where the
    shard has nothing to say — the push-model scatter of
    generateAutoMessages); the framework all-reduces proposals with the
    buffer op (aggregateAutoMessages) and hands each shard its slice to
    `update` (default: adopt it, vote active while anything changed)."""

    sync_buffers: Dict[str, str] = {}

    def propose(self, ctx: StepContext, frag: DeviceFragment, state: Dict):
        raise NotImplementedError

    def update(self, ctx: StepContext, frag: DeviceFragment, state: Dict,
               combined: Dict):
        changed_any = jnp.int32(0)
        new_state = dict(state)
        for k in self.sync_buffers:
            new = combined[k]
            changed = jnp.logical_and(new != state[k], frag.inner_mask)
            changed_any = changed_any + changed.sum().astype(jnp.int32)
            new_state[k] = new
        return new_state, ctx.sum(changed_any)

    def peval(self, ctx, frag, state):
        return state, jnp.int32(1)

    def inceval(self, ctx, frag, state):
        from libgrape_lite_tpu.parallel.message_manager import (
            AutoParallelMessageManager,
        )

        proposals = self.propose(ctx, frag, state)
        combined = AutoParallelMessageManager.sync(
            frag, proposals, self.sync_buffers
        )
        return self.update(ctx, frag, state, combined)


class GatherScatterAppBase(AppBase):
    """Vertex-cut app (reference `gather_scatter_app_base.h:30-61`)."""

    message_strategy = MessageStrategy.kGatherScatter
