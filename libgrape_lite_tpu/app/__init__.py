from libgrape_lite_tpu.app.base import (
    AppBase,
    ParallelAppBase,
    BatchShuffleAppBase,
    AutoAppBase,
    GatherScatterAppBase,
    ContextBase,
    VertexDataContext,
)
