"""Streaming sources/sinks for the sampler pipeline.

Re-design of `examples/gnn_sampler/kafka_{consumer,producer}.h` +
`run_sampler.cc`: the reference consumes graph-update and query streams
from Kafka and emits sampled neighborhoods back.  Kafka clients are not
part of this image, so the transport is pluggable: `FileSource` /
`FileSink` replay and record the same line protocol
(`e src dst [w]` updates, `q vid` queries), and `KafkaSource/KafkaSink`
bind to confluent_kafka when it is importable.
"""

from __future__ import annotations

from typing import Iterable, Iterator


class FileSource:
    def __init__(self, path: str):
        self.path = path

    def __iter__(self) -> Iterator[str]:
        with open(self.path) as f:
            for line in f:
                line = line.strip()
                if line and line[0] != "#":
                    yield line


class FileSink:
    def __init__(self, path: str):
        self._f = open(path, "w")

    def emit(self, line: str) -> None:
        self._f.write(line + "\n")

    def close(self) -> None:
        self._f.close()


class AsyncSink:
    """Decouple sample emission from the query loop on a writer thread
    — the TPU-build form of run_sampler.cc's pending output job
    (`run_sampler.cc:86-131`: `worker->Output(ostream)` runs on a
    std::thread while the next batch computes).  Lines flow through a
    producer-aware BlockingQueue (`utils/thread_pool.py`); `close()`
    drains and joins."""

    def __init__(self, inner, maxsize: int = 8192):
        import threading

        from libgrape_lite_tpu.utils.thread_pool import BlockingQueue

        self._inner = inner
        # bounded: a slow sink applies backpressure to the query loop
        # (the reference blocks on the previous output job) instead of
        # buffering the whole backlog in RAM
        self._q = BlockingQueue(maxsize=maxsize)
        self._q.set_producer_num(1)
        self._error: Exception | None = None
        self._t = threading.Thread(target=self._drain, daemon=True)
        self._t.start()

    def _drain(self):
        while True:
            item = self._q.get()
            if item is None:
                return
            try:
                self._inner.emit(item)
            except Exception as e:  # surface on the producer side
                self._error = e
                # keep draining so producers don't block on a full
                # queue; lines after the failure are dropped, and the
                # next emit()/close() raises
                while self._q.get() is not None:
                    pass
                return

    def _check(self):
        # error stays sticky: a second emit()/close() after a writer
        # failure must not silently succeed
        if self._error is not None:
            raise RuntimeError("async sink writer failed") from self._error

    def emit(self, line: str) -> None:
        self._check()
        self._q.put(line)

    def close(self) -> None:
        self._q.decrement_producer()
        self._t.join()
        try:
            self._check()
        finally:
            # always close/flush the inner sink, even when the writer
            # thread died mid-stream (no leaked handle / lost buffer)
            self._inner.close()


def kafka_available() -> bool:
    try:
        import confluent_kafka  # noqa: F401

        return True
    except ImportError:
        return False


class KafkaSource:  # pragma: no cover - requires kafka runtime
    def __init__(self, brokers: str, topic: str, group: str = "grape-tpu"):
        from confluent_kafka import Consumer

        self._c = Consumer(
            {"bootstrap.servers": brokers, "group.id": group,
             "auto.offset.reset": "earliest"}
        )
        self._c.subscribe([topic])

    def __iter__(self):
        while True:
            msg = self._c.poll(1.0)
            if msg is None or msg.error():
                continue
            yield msg.value().decode()


class KafkaSink:  # pragma: no cover - requires kafka runtime
    def __init__(self, brokers: str, topic: str):
        from confluent_kafka import Producer

        self._p = Producer({"bootstrap.servers": brokers})
        self._topic = topic

    def emit(self, line: str) -> None:
        self._p.produce(self._topic, line.encode())

    def close(self) -> None:
        self._p.flush()


def run_pipeline(fragment, sampler, source: Iterable[str], sink,
                 fanouts=(10, 5), batch: int = 512,
                 directed: bool = False, seed: int = 0) -> int:
    """The run_sampler.cc loop: drain updates/queries, extend the
    append-only fragment, batch-sample, emit `vid: n1 n2 ...` lines.

    `directed=False` (the reference's graph_spec, run_sampler.cc:78)
    inserts each update in both directions; an `e src dst [w]` line
    therefore means ONE undirected edge — a stream that already
    carries both orientations of each edge should pass directed=True
    (there is no dedup downstream).  Each query batch draws from a
    fresh fold of `seed` so re-queried vertices get independent
    samples."""
    import numpy as np

    queries: list[int] = []
    emitted = 0
    batch_no = 0

    def flush_queries():
        nonlocal emitted, batch_no
        if not queries:
            return
        fragment.flush()
        hops = sampler.sample(
            np.asarray(queries), fanouts, seed=seed + batch_no
        )
        batch_no += 1
        for i, q in enumerate(queries):
            flat = [str(x) for h in hops for x in h[i].tolist() if x >= 0]
            sink.emit(f"{q}: {' '.join(flat)}")
            emitted += 1
        queries.clear()

    for line in source:
        parts = line.split()
        if not parts:
            continue
        if parts[0] == "e":
            # arrival order is the contract: queries already queued must
            # sample the PRE-update graph
            flush_queries()
            s, d = int(parts[1]), int(parts[2])
            w = [float(parts[3])] if len(parts) > 3 else None
            if directed:
                fragment.extend([s], [d], w)
            else:
                fragment.extend([s, d], [d, s], None if w is None
                                else w * 2)
        elif parts[0] == "q":
            queries.append(int(parts[1]))
            if len(queries) >= batch:
                flush_queries()
    flush_queries()
    return emitted
