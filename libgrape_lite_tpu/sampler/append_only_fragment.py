"""Append-only streaming fragment.

Re-design of `examples/gnn_sampler/append_only_edgecut_fragment.h`
(1029 LoC): a fragment that absorbs streaming edge inserts cheaply and
serves adjacency queries.  The reference chains per-vertex extra-edge
blocks; here inserts accumulate in a host spill buffer and the padded
device CSR is rebuilt when the buffer crosses a threshold (amortised
O(E) — the TPU analogue of block chaining, since XLA buffers are
immutable anyway).  `device_csr()` hands out the current snapshot for
jitted samplers.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


class AppendOnlyEdgecutFragment:
    def __init__(self, n: int, src: np.ndarray, dst: np.ndarray,
                 w: np.ndarray | None = None, rebuild_threshold: float = 0.25):
        self._src = np.asarray(src, dtype=np.int64)
        self._dst = np.asarray(dst, dtype=np.int64)
        # the id space grows with the data, exactly like flush()
        self.n = max(
            n,
            int(self._src.max(initial=n - 1)) + 1,
            int(self._dst.max(initial=n - 1)) + 1,
        )
        self._w = None if w is None else np.asarray(w, dtype=np.float32)
        self._pending: list[tuple[int, int, float]] = []
        self.rebuild_threshold = rebuild_threshold
        self._snapshot = None
        self._build()

    # ---- streaming ingest (reference AddEdges path) ----

    def extend(self, src, dst, w=None) -> None:
        src = np.asarray(src).tolist()
        dst = np.asarray(dst).tolist()
        ws = (
            np.asarray(w).tolist()
            if w is not None
            else [1.0] * len(src)
        )
        if w is not None and self._w is None:
            # weights arrive on a previously unweighted stream: backfill
            # existing edges with weight 1 so nothing is dropped
            self._w = np.ones(len(self._src), dtype=np.float32)
        self._pending.extend(zip(src, dst, ws))
        if len(self._pending) > self.rebuild_threshold * max(len(self._src), 1):
            self.flush()

    def flush(self) -> None:
        if not self._pending:
            return
        # ids stay int64 end to end (no float64 round-trip)
        a_src = np.array([s for s, _, _ in self._pending], dtype=np.int64)
        a_dst = np.array([d for _, d, _ in self._pending], dtype=np.int64)
        a_w = np.array([x for _, _, x in self._pending], dtype=np.float32)
        self._src = np.concatenate([self._src, a_src])
        self._dst = np.concatenate([self._dst, a_dst])
        if self._w is not None:
            self._w = np.concatenate([self._w, a_w])
        self.n = max(self.n, int(self._src.max(initial=self.n - 1)) + 1,
                     int(self._dst.max(initial=self.n - 1)) + 1)
        self._pending.clear()
        self._build()

    def _build(self) -> None:
        order = np.lexsort((self._dst, self._src))
        src = self._src[order]
        dst = self._dst[order]
        counts = np.bincount(src, minlength=self.n)
        indptr = np.zeros(self.n + 1, dtype=np.int32)
        np.cumsum(counts, out=indptr[1:])
        self._snapshot = {
            "indptr": jnp.asarray(indptr),
            "nbr": jnp.asarray(dst.astype(np.int32)),
            "w": (
                jnp.asarray(self._w[order])
                if self._w is not None
                else None
            ),
        }

    # ---- queries ----

    @property
    def num_edges(self) -> int:
        return len(self._src) + len(self._pending)

    def device_csr(self):
        """(indptr [n+1], nbr [E], w [E] | None) — includes flushed
        edges only; call flush() for an exact snapshot."""
        return self._snapshot["indptr"], self._snapshot["nbr"], self._snapshot["w"]
