"""Multi-hop neighbor sampling.

Re-design of `examples/gnn_sampler/sampler.h` (238 LoC: random /
edge-weight / top-k strategies) + `fragment_indices.h` (per-vertex
weighted-sample indices): fanout-shaped multi-hop sampling as a jitted
function over the CSR snapshot.

TPU formulation — everything is fixed-fanout dense tensors:

  * random      — per-slot uniform draws scaled by degree, gathered
                  from the CSR row (with replacement, like the
                  reference's random strategy),
  * edge_weight — Gumbel-max over per-edge keys log(w) + G within each
                  row segment, k passes of segment-argmax (sampling
                  WITHOUT replacement, k small),
  * top_k       — the same passes with keys = w (deterministic).

Zero-degree frontier slots produce -1 (the reference emits empty
lists).  Output of `sample(queries, fanouts)` is one [Q, k1, ..., kh]
tensor per hop.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


class GraphSampler:
    """`window` bounds the per-row candidate span the weighted
    strategies (edge_weight / top_k) consider: rows with degree beyond
    it are sampled from their first `window` CSR slots only — the
    VMEM-bounded tradeoff; raise it for hub-heavy graphs.  The `random`
    strategy indexes the whole row and is unaffected."""

    STRATEGIES = ("random", "edge_weight", "top_k")

    def __init__(self, fragment, strategy: str = "random",
                 window: int = 1024):
        if strategy not in self.STRATEGIES:
            raise ValueError(f"unknown strategy {strategy!r}")
        self.fragment = fragment
        self.strategy = strategy
        self.window = window

    def sample(self, queries: np.ndarray, fanouts, seed: int = 0):
        """Multi-hop sample; returns a list of per-hop neighbor arrays:
        hop h has shape [len(queries), prod(fanouts[:h+1])]."""
        indptr, nbr, w = self.fragment.device_csr()
        key = jax.random.PRNGKey(seed)
        frontier = jnp.asarray(np.asarray(queries), dtype=jnp.int32)
        n = int(indptr.shape[0]) - 1
        out = []
        for h, k in enumerate(fanouts):
            key, sub = jax.random.split(key)
            nxt = _sample_hop(
                indptr, nbr, w, frontier.reshape(-1), int(k),
                self.strategy, sub, self.window,
            )
            out.append(np.asarray(nxt).reshape(len(queries), -1))
            # dead (-1) slots become the out-of-range row n, whose degree
            # reads as 0, so they keep yielding -1 in deeper hops
            flat = nxt.reshape(-1)
            frontier = jnp.where(flat >= 0, flat, jnp.int32(n))
        return out


@partial(jax.jit, static_argnames=("k", "strategy", "window"))
def _sample_hop(indptr, nbr, w, frontier, k, strategy, key, window=1024):
    q = frontier.shape[0]
    starts = indptr[frontier]
    degs = indptr[frontier + 1] - starts
    valid = degs > 0

    if strategy == "random":
        u = jax.random.uniform(key, (q, k))
        off = (u * degs[:, None]).astype(jnp.int32)
        idx = starts[:, None] + jnp.minimum(off, jnp.maximum(degs - 1, 0)[:, None])
        res = nbr[idx]
        return jnp.where(valid[:, None], res, -1)

    # per-row k-pass argmax over per-edge keys (Gumbel for edge_weight,
    # raw weight for top_k), without replacement
    e = nbr.shape[0]
    if w is None:
        base_keys = jnp.zeros(e)
    else:
        base_keys = jnp.log(jnp.maximum(w.astype(jnp.float32), 1e-30))
    if strategy == "edge_weight":
        g = -jnp.log(-jnp.log(
            jax.random.uniform(key, (e,), minval=1e-9, maxval=1.0)
        ))
        base_keys = base_keys + g

    def per_query(start, deg):
        win = jnp.arange(window, dtype=jnp.int32)
        in_row = win < jnp.minimum(deg, window)
        idx = start + jnp.minimum(win, jnp.maximum(deg - 1, 0))
        keys = jnp.where(in_row, base_keys[idx], -jnp.inf)

        def pick(carry, _):
            keys_c = carry
            j = jnp.argmax(keys_c)
            chosen = jnp.where(keys_c[j] == -jnp.inf, -1, nbr[start + j])
            keys_c = keys_c.at[j].set(-jnp.inf)
            return keys_c, chosen

        _, picks = jax.lax.scan(pick, keys, None, length=k)
        return picks

    res = jax.vmap(per_query)(starts, degs)
    return jnp.where(valid[:, None], res, -1)
