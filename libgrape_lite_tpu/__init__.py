"""libgrape_lite_tpu — a TPU-native distributed graph-analytics framework.

A from-scratch re-design of the capabilities of `alibaba/libgrape-lite`
(the PIE model: PEval / IncEval / Assemble over partitioned graph
fragments) for TPU hardware:

* compute is expressed as dense / segment-reduce JAX ops (and Pallas
  kernels for the hot paths) that XLA can tile onto the MXU/VPU,
* fragments are padded, statically-shaped CSR shards living in HBM,
* cross-fragment messaging lowers to XLA collectives (`all_gather`,
  `psum`, `all_to_all`, `ppermute`) over the ICI mesh instead of
  MPI/NCCL point-to-point traffic,
* the superstep loop (reference `grape/worker/worker.h:104-146`) is a
  jitted `lax.while_loop` with a `psum` termination vote replacing the
  reference's 2-int `MPI_Allreduce`
  (`grape/parallel/parallel_message_manager.h:123-138`).

Layer map (mirrors SURVEY.md §1):

    models/      the LDBC analytical apps (SSSP, BFS, WCC, PageRank,
                 CDLP, LCC, ...) — reference `examples/analytical_apps`
    app/         app base classes + contexts — reference `grape/app`
    worker/      superstep drivers — reference `grape/worker`
    parallel/    message managers (collective strategies), engine,
                 communicator — reference `grape/parallel`,
                 `grape/communication`
    fragment/    fragment shards, loaders — reference `grape/fragment`
    graph/       CSR storage — reference `grape/graph`
    vertex_map/  oid⇄gid directory, partitioners, idxers — reference
                 `grape/vertex_map`
    ops/         TPU compute primitives + Pallas kernels — reference
                 `grape/cuda` (the accelerator backend)
    io/          TSV/graph IO — reference `grape/io`
    utils/       substrate — reference `grape/utils`
"""

from libgrape_lite_tpu.version import __version__

from libgrape_lite_tpu.utils.types import (
    EmptyType,
    LoadStrategy,
    MessageStrategy,
)
from libgrape_lite_tpu.utils.id_parser import IdParser
from libgrape_lite_tpu.parallel.comm_spec import CommSpec
from libgrape_lite_tpu.fragment.loader import LoadGraph, LoadGraphSpec
from libgrape_lite_tpu.worker.worker import Worker

__all__ = [
    "__version__",
    "EmptyType",
    "LoadStrategy",
    "MessageStrategy",
    "IdParser",
    "CommSpec",
    "LoadGraph",
    "LoadGraphSpec",
    "Worker",
]
