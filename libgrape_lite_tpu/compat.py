"""JAX version compatibility shims.

The codebase targets the current `jax.shard_map` API (public since
jax 0.6, `check_vma=` keyword); older runtimes only ship the
experimental entry point (`jax.experimental.shard_map.shard_map`,
`check_rep=` keyword).  Both trace identically for the SPMD programs
used here — `check_vma`/`check_rep` gate the same replication-rule
checker, which every call site disables anyway (collectives like
`all_to_all` have no rule on the older versions).
"""

from __future__ import annotations

import jax

_HAS_PUBLIC_SHARD_MAP = hasattr(jax, "shard_map")


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """`jax.shard_map` with the modern keyword surface on any
    supported JAX version."""
    if _HAS_PUBLIC_SHARD_MAP:
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma,
    )
