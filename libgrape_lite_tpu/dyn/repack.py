"""Repack policy: when accumulated deltas fold into a rebuilt CSR.

Below the threshold, staged additions ride the dense overlay side-path
(dyn/ingest.py) and queries pay a few hundred extra gather slots per
round — zero pack replanning, zero XLA recompiles.  Past the
threshold the amortized rebuild wins (SparseP's delta-ratio analysis,
arxiv 2201.05072: the overlay's unstructured slots lack the packed
CSR's locality, so their per-edge cost is a large constant multiple of
the planned streams'), and the buffer folds into the base arrays via
the existing mutation machinery: `BasicFragmentMutator.mutate` edits
the retained host edge list and rebuilds the padded shards, the next
`init_state` re-runs the pack planner + rebalancer against the new
content, and the v3 plan cache re-keys itself by content digest — a
counted recompile event, never a silent one.

Non-additive ops (removals, weight updates, vertex changes) force a
repack regardless of ratio: a tropical min-fold cannot "un-min" a
candidate, so the overlay cannot represent them consistently.

Env knobs (read by `RepackPolicy.from_env`):
  GRAPE_DYN_REPACK_RATIO   delta-ratio threshold (default 0.05)
  GRAPE_DYN_CAP            delta buffer / overlay capacity (default 4096)
"""

from __future__ import annotations

import os
from dataclasses import dataclass

from libgrape_lite_tpu.dyn.delta import DeltaBuffer

REPACK_RATIO_ENV = "GRAPE_DYN_REPACK_RATIO"
CAPACITY_ENV = "GRAPE_DYN_CAP"

DEFAULT_REPACK_RATIO = 0.05
DEFAULT_CAPACITY = 4096


@dataclass(frozen=True)
class RepackPolicy:
    """The fold-vs-accumulate trade-off in one place (the dyn/ analogue
    of serve/policy.BatchPolicy)."""

    # staged edge ops / base real edges above which apply() folds the
    # buffer into a rebuilt CSR; 0 repacks on every apply (useful to
    # force the rebuild path in tests), >= 1 effectively never (the
    # bounded buffer still forces a fold at capacity)
    threshold: float = DEFAULT_REPACK_RATIO
    # delta buffer bound == overlay slot capacity per fragment; fixed
    # per DynGraph so ingest never changes compiled state shapes
    capacity: int = DEFAULT_CAPACITY

    def __post_init__(self):
        if self.threshold < 0:
            raise ValueError(
                f"threshold must be >= 0, got {self.threshold}"
            )
        if self.capacity < 1:
            raise ValueError(
                f"capacity must be >= 1, got {self.capacity}"
            )

    @classmethod
    def from_env(cls) -> "RepackPolicy":
        return cls(
            threshold=float(
                os.environ.get(REPACK_RATIO_ENV, DEFAULT_REPACK_RATIO)
            ),
            capacity=int(os.environ.get(CAPACITY_ENV, DEFAULT_CAPACITY)),
        )

    def should_repack(self, buffer: DeltaBuffer, fragment) -> bool:
        """Ratio trigger only — structural triggers (non-additive ops,
        unknown endpoints, overlay slot overflow) are checked by
        DynGraph.apply, which can see the overlay build outcome."""
        return (
            buffer.delta_ratio(fragment.total_edges_num) > self.threshold
        )


def repack_fragment(fragment, buffer: DeltaBuffer):
    """Fold the staged buffer into a rebuilt sharded fragment.

    Reuses the rebuild-on-mutate machinery (`fragment/mutation.py`):
    host edge-list edit -> partition -> padded shard build, validated
    under GRAPE_VALIDATE_LOAD=1 like every other load path.  The
    caller owns cache/worker re-keying (serve/session adopts the new
    fragment into its resident workers; stale compiled runners miss
    naturally because the apps' plan uids change on re-init)."""
    if fragment.edge_list is None:
        raise ValueError(
            "repack needs the retained host edge list; build the base "
            "fragment with retain_edge_list=True (LoadGraphSpec"
            "(retain_edge_list=True) or LoadGraphAndMutate)"
        )
    return buffer.to_mutator(directed=fragment.directed).mutate(fragment)
