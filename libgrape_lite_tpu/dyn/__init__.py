"""dyn/ — dynamic-graph runtime (ROADMAP item 4, docs/DYNAMIC_GRAPHS.md).

Delta-edge buffers staged against the frozen packed CSR, applied at
superstep boundaries as either a dense overlay side-path (zero
replanning, zero recompiles) or an amortized repack; incremental
IncEval seeds queries from the previous fixed point; ServeSession
ingests update streams between batches while queries stay live.
"""

from libgrape_lite_tpu.dyn.delta import (
    DeltaBuffer,
    DeltaOverflowError,
    DeltaSummary,
    parse_ops_file,
    parse_ops_line,
)
from libgrape_lite_tpu.dyn.incremental import (
    incremental_plan,
    reseed_fold,
)
from libgrape_lite_tpu.dyn.ingest import (
    DeltaOverlay,
    DynGraph,
    broadcast_ingest,
    overlay_state_entries,
)
from libgrape_lite_tpu.dyn.repack import RepackPolicy, repack_fragment

__all__ = [
    "DeltaBuffer",
    "DeltaOverflowError",
    "DeltaSummary",
    "DeltaOverlay",
    "DynGraph",
    "RepackPolicy",
    "broadcast_ingest",
    "incremental_plan",
    "overlay_state_entries",
    "parse_ops_file",
    "parse_ops_line",
    "repack_fragment",
    "reseed_fold",
]
