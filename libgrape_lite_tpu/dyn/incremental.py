"""Incremental IncEval: seed a query from the previous fixed point.

PIE's headline capability (the GRAPE paper's IncEval is *specified*
for incremental recomputation after a graph change): instead of
recomputing a query from scratch on the mutated graph, re-activate
only what the delta touched.  In this dense pull-mode formulation
there is no explicit frontier array — re-activation means seeding the
superstep carry so that the very first rounds propagate only the
delta's effect:

    seeded = elementwise_min(fresh_init, migrate(prev_result))

For the monotone-min apps (SSSP/BFS/WCC — `AppBase.inc_mode ==
"monotone-min"`), this is EXACT for additive deltas, not a heuristic:

  * the previous fixed point's values are achievable in the mutated
    graph (additive deltas keep every old edge), so they are valid
    upper bounds — relaxation from them stays sound;
  * the superstep operator F' of the mutated graph is monotone and
    F'(seeded) <= seeded, so iteration decreases;
  * cold* <= seeded <= fresh_init pointwise, and iterating F' from
    fresh_init converges to cold* (that IS the cold query), so by
    monotonicity the seeded iterates are squeezed onto the same fixed
    point — byte-identical values, usually in a fraction of the
    rounds (the seeded run only pays the delta's propagation depth).

  (The min with fresh_init matters for WCC: migrated labels are the
  OLD representatives' ids, which need not be minimal in the new pid
  space — folding the fresh own-pid init back in restores the cold
  fixed point exactly.)

Non-additive deltas break the upper-bound property (a removed edge
can leave stale too-small values), and fixed-round sum iterations
(PageRank runs exactly `max_round` steps from a fixed init — there is
no fixed point to reuse at finite rounds) declare `inc_mode ==
"restart"`: `Worker.query_incremental` then runs the cold query
through the same API, counted in `Worker.inc_stats` — an honest
fallback, never a silent wrong answer.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np


def incremental_plan(app, delta) -> Tuple[str, str]:
    """("seeded" | "cold", reason) for this (app, delta) pair.

    `delta` is a DeltaBuffer / DeltaSummary (anything exposing
    `additive_only`); None means "unknown delta class", which must be
    treated as non-additive."""
    mode = getattr(app, "inc_mode", None)
    if mode is None:
        return "cold", (
            f"{type(app).__name__} declares no incremental contract"
        )
    if mode == "restart":
        return "cold", (
            f"{type(app).__name__} contract is 'restart' (fixed-round "
            "iteration has no reusable fixed point)"
        )
    if mode != "monotone-min":
        raise ValueError(
            f"unknown inc_mode {mode!r} on {type(app).__name__}"
        )
    if delta is None:
        return "cold", "no delta description (treated as non-additive)"
    if getattr(delta, "n_ops", 0) == 0:
        # an empty description is indistinguishable from a missing one
        # — notably DynGraph.summary() AFTER a repack cleared the
        # buffer; seeding on it would silently trust that NOTHING
        # changed, so treat it like no description at all
        return "cold", (
            "empty delta description (describe the ops that separate "
            "prev_result's graph from this one — e.g. the ingest "
            "report's 'delta' snapshot)"
        )
    if not getattr(delta, "additive_only", False):
        return "cold", (
            "non-additive delta (removals/updates/vertex ops) breaks "
            "the monotone upper-bound property"
        )
    if not app.inc_seed_keys:
        return "cold", (
            f"{type(app).__name__} declares monotone-min but no "
            "inc_seed_keys"
        )
    return "seeded", "additive delta under a monotone-min contract"


def migrate_rows(old_frag, new_frag, old_v: np.ndarray,
                 fresh_v: np.ndarray) -> np.ndarray:
    """Old per-vertex rows re-addressed into the new fragment's [fnum,
    vp] layout by oid, with fresh init values where no old row exists
    (new vertices, padding) — the host-side sparse extraction +
    assignment of arxiv 2509.20776, at single-host scale.  The row
    mapping is the same `oid_row_alignment` MutationContext state
    migration uses."""
    from libgrape_lite_tpu.fragment.mutation import oid_row_alignment

    out = np.array(fresh_v, copy=True)
    of, ol, nf, nl = oid_row_alignment(old_frag, new_frag)
    out[nf, nl] = old_v[of, ol]
    return out


def reseed_fold(app, frag, fresh_state: Dict, prev_frag,
                prev_state: Dict) -> Dict[str, np.ndarray]:
    """The seeded carry overrides: per declared key, elementwise min of
    the fresh init and the (migrated, value-remapped) previous result.
    See the module docstring for why this is exact."""
    out = {}
    for key, kind in app.inc_seed_keys.items():
        if kind != "min":
            raise ValueError(
                f"unsupported inc_seed fold {kind!r} for key {key!r}"
            )
        if key not in prev_state:
            raise KeyError(
                f"previous result has no {key!r} carry — "
                "query_incremental needs the state dict returned by "
                "the previous query of the SAME app and args"
            )
        fresh_v = np.asarray(fresh_state[key])
        prev_v = np.asarray(prev_state[key])
        prev_v = app.inc_value_map(key, prev_v, prev_frag, frag)
        if prev_frag is frag and prev_v.shape == fresh_v.shape:
            mig = prev_v
        else:
            mig = migrate_rows(prev_frag, frag, prev_v, fresh_v)
        out[key] = np.minimum(fresh_v, mig.astype(fresh_v.dtype))
    return out
