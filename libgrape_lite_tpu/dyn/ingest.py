"""Live ingest: the delta overlay side-path + the DynGraph runtime.

`DynGraph` pairs a frozen packed fragment with a `DeltaBuffer` and
decides, at every apply boundary, between two representations of the
staged updates:

  * **overlay** — additive-only deltas between known vertices
    materialise as dense [fnum, capacity] side arrays
    (`DeltaOverlay`), attached to the fragment as `frag.dyn_overlay`.
    Overlay-contracted apps (SSSP/BFS/WCC — `AppBase.
    dyn_overlay_support`) ship them as ephemeral state and fold the
    extra edges into their pull reduction with one gather +
    `segment_min` per round, merged at the fold — `min` is
    associative and exact, so the query result is byte-identical to a
    cold run on the rebuilt graph while the pack plans, mirror
    tables, and compiled runners stay untouched (fixed shapes: the
    second query after an ingest is a cache hit, pinned by
    tests/test_dyn.py).
  * **repack** — everything else (ratio past the policy threshold,
    non-additive ops, unknown endpoints, overlay slot overflow) folds
    the buffer into a rebuilt CSR (dyn/repack.py).

Apply points are superstep boundaries by construction: the host pumps
queries and ingests between dispatches, so a delta never lands inside
a running while_loop — ft checkpoint cuts and guard digest semantics
carry over unchanged (a mid-query mutation goes through the
MutationContext path instead, which resets the watchdog history at the
boundary; see guard/monitor.GuardMonitor.on_mutation).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from libgrape_lite_tpu.dyn.delta import (
    DeltaBuffer,
    DeltaOverflowError,
    DeltaSummary,
)
from libgrape_lite_tpu.dyn.repack import RepackPolicy, repack_fragment
from libgrape_lite_tpu.utils import logging as glog


class _OverlaySide:
    """One pull direction's dense side arrays ([fnum, cap] each)."""

    def __init__(self, src, nbr, w, mask):
        self.src = src    # i32 local row (the vertex being relaxed); pad = vp
        self.nbr = nbr    # i32 pid of the contributing neighbor; pad = 0
        self.w = w        # f64 edge weight; pad = 0
        self.mask = mask  # bool


class DeltaOverlay:
    """Dense scatter/gather side-path for staged ADD edges.

    Rows are grouped by owner fragment and sorted by local row id, so
    the fold's `segment_reduce` keeps its sorted-segment lowering; pad
    slots route to the vp overflow row (the library-wide padding
    convention) with mask False."""

    def __init__(self, fnum: int, vp: int, capacity: int,
                 ie: _OverlaySide, oe: _OverlaySide, count: int):
        self.fnum = fnum
        self.vp = vp
        self.capacity = capacity
        self.ie = ie
        self.oe = oe
        self.count = count  # staged edges represented (0 = inert)

    @classmethod
    def empty(cls, frag, capacity: int) -> "DeltaOverlay":
        side = cls._blank(frag.fnum, frag.vp, capacity)
        return cls(frag.fnum, frag.vp, capacity, side, side, 0)

    @staticmethod
    def _blank(fnum: int, vp: int, cap: int) -> _OverlaySide:
        return _OverlaySide(
            src=np.full((fnum, cap), vp, dtype=np.int32),
            nbr=np.zeros((fnum, cap), dtype=np.int32),
            w=np.zeros((fnum, cap), dtype=np.float64),
            mask=np.zeros((fnum, cap), dtype=bool),
        )

    @classmethod
    def build(cls, frag, adds: List[Tuple], capacity: int):
        """(overlay, None) or (None, reason) when the buffer cannot
        ride the side-path and must repack instead."""
        if not adds:
            return cls.empty(frag, capacity), None
        src_oid = np.asarray([a[0] for a in adds])
        dst_oid = np.asarray([a[1] for a in adds])
        w = np.asarray([a[2] for a in adds], dtype=np.float64)
        sp = frag.oid_to_pid(src_oid)
        dp = frag.oid_to_pid(dst_oid)
        if (sp < 0).any() or (dp < 0).any():
            return None, "edge endpoint(s) outside the vertex map"

        # pull-mode orientations: the ie fold relaxes the DST row from
        # the SRC neighbor; undirected graphs symmetrise (both
        # orientations, mirroring the CSR build), and their oe aliases
        # ie — the same multiset either way
        if frag.directed:
            ie_rows, ie_nbr, ie_w = dp, sp, w
            oe_rows, oe_nbr, oe_w = sp, dp, w
        else:
            ie_rows = np.concatenate([dp, sp])
            ie_nbr = np.concatenate([sp, dp])
            ie_w = np.concatenate([w, w])
            oe_rows, oe_nbr, oe_w = ie_rows, ie_nbr, ie_w

        def fill(rows, nbr, ww):
            side = cls._blank(frag.fnum, frag.vp, capacity)
            fid = rows // frag.vp
            lid = rows % frag.vp
            for f in range(frag.fnum):
                m = fid == f
                n = int(m.sum())
                if n > capacity:
                    return None
                order = np.argsort(lid[m], kind="stable")
                side.src[f, :n] = lid[m][order]
                side.nbr[f, :n] = nbr[m][order]
                side.w[f, :n] = ww[m][order]
                side.mask[f, :n] = True
            return side

        ie = fill(ie_rows, ie_nbr, ie_w)
        if ie is None:
            return None, (
                f"overlay capacity ({capacity} slots/fragment) exceeded"
            )
        if frag.directed:
            oe = fill(oe_rows, oe_nbr, oe_w)
            if oe is None:
                return None, (
                    f"overlay capacity ({capacity} slots/fragment) "
                    "exceeded"
                )
        else:
            oe = ie
        return cls(frag.fnum, frag.vp, capacity, ie, oe, len(adds)), None

    def entries(self, direction: str, weight_dtype=None,
                prefix: Optional[str] = None) -> Dict[str, np.ndarray]:
        """Ephemeral state entries for one pull direction.  Keys are
        `dyn_<dir>_{src,nbr,mask[,w]}`; the weight column is included
        only when `weight_dtype` is given (BFS/WCC are unweighted
        folds).  Shapes are [fnum, capacity] — fixed per DynGraph, so
        ingest never perturbs the compiled state structure."""
        side = self.ie if direction == "ie" else self.oe
        prefix = prefix if prefix is not None else f"dyn_{direction}_"
        out = {
            prefix + "src": side.src,
            prefix + "nbr": side.nbr,
            prefix + "mask": side.mask,
        }
        if weight_dtype is not None:
            out[prefix + "w"] = side.w.astype(weight_dtype)
        return out


class DynGraph:
    """A packed fragment + its delta buffer + the apply policy — the
    dynamic-graph runtime a ServeSession (or a bare Worker test) drives.

    Typical use::

        dg = DynGraph(frag)                     # frag built retain_edge_list=True
        dg.ingest([("a", 3, 9, 1.5)])           # stage + apply at the boundary
        Worker(SSSP(), dg.fragment).query(source=0)   # sees the delta

    The overlay is attached to the fragment from construction on (an
    empty, fully-masked one), so overlay-contracted apps compile ONE
    state structure that stays valid across every ingest until a
    repack — the zero-recompile property ServeSession.ingest pins."""

    def __init__(self, fragment, policy: RepackPolicy | None = None):
        self.policy = policy or RepackPolicy.from_env()
        self.fragment = fragment
        self.buffer = DeltaBuffer(capacity=self.policy.capacity)
        self.stats = {
            "ingested": 0, "overlay_applies": 0, "repacks": 0,
            "folded_ops": 0,
        }
        # summary of the ops the last apply() acted on — a repack
        # CLEARS the buffer, so `summary()` alone would afterwards
        # describe an empty (vacuously additive) delta; incremental
        # seeding must use the snapshot that still names the folded
        # ops (rides in every report as "delta", kept here too)
        self.last_applied: Optional[DeltaSummary] = None
        self._attach(DeltaOverlay.empty(fragment, self.policy.capacity))

    def _attach(self, overlay: DeltaOverlay) -> None:
        self.fragment.dyn_overlay = overlay

    @property
    def overlay_count(self) -> int:
        ov = getattr(self.fragment, "dyn_overlay", None)
        return 0 if ov is None else ov.count

    def stage(self, ops) -> int:
        """Stage ops, folding at capacity: when a chunk would overflow
        the bounded buffer, the pending ops repack into the CSR (a
        counted fold) and staging continues — a delta stream longer
        than the buffer must degrade to amortized repacks, not raise
        DeltaOverflowError out of a live serve loop.  Batches larger
        than the capacity itself are split into capacity-sized chunks
        with a fold between each."""
        ops = list(ops)
        total = 0
        cap = self.policy.capacity
        for lo in range(0, len(ops), cap):
            chunk = ops[lo:lo + cap]
            try:
                total += self.buffer.stage(chunk)
            except DeltaOverflowError:
                # buffer.stage is atomic, so nothing half-staged:
                # fold the pending ops, then the chunk (<= capacity)
                # fits the emptied buffer
                self.apply(
                    force_repack=True,
                    reason="delta buffer at capacity",
                )
                total += self.buffer.stage(chunk)
        self.stats["ingested"] += total
        return total

    def ingest(self, ops, *, force_repack: bool = False) -> dict:
        """Stage `ops` and apply at this (between-dispatches) boundary."""
        staged = self.stage(ops)
        report = self.apply(force_repack=force_repack)
        report["staged"] = staged
        return report

    def summary(self) -> DeltaSummary:
        return self.buffer.summary()

    def fold_now(self, reason: str = "forced") -> dict:
        """Unconditional repack of the pending buffer (e.g. before a
        query by an app with no overlay contract)."""
        return self.apply(force_repack=True, reason=reason)

    def apply(self, *, force_repack: bool = False,
              reason: str = "") -> dict:
        """Apply the staged buffer at a superstep/dispatch boundary.

        Decision ladder: forced -> policy ratio -> overlay build
        feasibility (non-additive ops, unknown endpoints, slot
        overflow all fall through to repack).  Returns a report dict
        {mode, pending, delta_ratio, reason, repacked?}."""
        ratio = self.buffer.delta_ratio(self.fragment.total_edges_num)
        delta = self.buffer.summary()
        self.last_applied = delta
        why = reason
        repack = force_repack
        if not repack and self.policy.should_repack(
            self.buffer, self.fragment
        ):
            repack = True
            why = (
                f"delta ratio {ratio:.4f} > threshold "
                f"{self.policy.threshold:g}"
            )
        overlay = None
        if not repack:
            if not self.buffer.additive_only:
                repack = True
                why = "non-additive ops cannot ride the min-fold overlay"
            else:
                overlay, build_reason = DeltaOverlay.build(
                    self.fragment, self.buffer.add_edges,
                    self.policy.capacity,
                )
                if overlay is None:
                    repack = True
                    why = build_reason

        if repack:
            rep = self._repack(why or "forced")
            rep["delta"] = delta
            return rep
        self._attach(overlay)
        self.stats["overlay_applies"] += 1
        glog.vlog(
            1, "dyn: overlay apply — %d staged edge(s), ratio %.4f "
            "(threshold %g)", self.buffer.n_edge_ops, ratio,
            self.policy.threshold,
        )
        return {
            "mode": "overlay",
            "pending": self.buffer.n_ops,
            "delta_ratio": ratio,
            "delta": delta,
            "reason": "below repack threshold",
        }

    def _repack(self, why: str) -> dict:
        n = self.buffer.n_ops
        folded = repack_fragment(self.fragment, self.buffer)
        self.buffer.clear()
        self.fragment = folded
        self._attach(
            DeltaOverlay.empty(folded, self.policy.capacity)
        )
        self.stats["repacks"] += 1
        self.stats["folded_ops"] += n
        glog.log_info(
            f"dyn: repack — folded {n} staged op(s) into a rebuilt "
            f"CSR ({why}); plan cache re-keys on next init"
        )
        return {
            "mode": "repack",
            "pending": 0,
            "folded": n,
            "delta_ratio": 0.0,
            "reason": why,
        }


def broadcast_ingest(targets, ops, *, force_repack: bool = False) -> list:
    """Apply ONE delta chunk to every target (DynGraphs or dyn-enabled
    ServeSessions) in order — the fleet router's replica broadcast
    (fleet/router.py wraps this behind its graph-version fence).  The
    ops list is materialised once so a generator cannot feed replica
    0 a different stream than replica 1; per-target reports return in
    target order."""
    ops = list(ops)
    return [
        t.ingest(ops, force_repack=force_repack) for t in targets
    ]


def overlay_state_entries(frag, direction: str, weight_dtype=None,
                          prefix: Optional[str] = None) -> Dict:
    """Helper for app init_state: the fragment's overlay entries, or {}
    when no overlay is attached (static graphs compile exactly the
    state they always have)."""
    ov = getattr(frag, "dyn_overlay", None)
    if ov is None:
        return {}
    return ov.entries(direction, weight_dtype, prefix)
