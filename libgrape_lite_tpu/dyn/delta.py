"""Delta-edge buffer: a bounded, typed staging area for graph updates.

The frozen packed CSR is the fast path's whole value — pack plans,
mirror tables, and compiled runners are all keyed to its byte layout —
so mutations never touch it directly.  Instead they stage here:

  * `DeltaBuffer` holds typed edge/vertex ops (`add_edge`,
    `remove_edge`, `update_edge`, `add_vertex`, `remove_vertex`) up to
    a fixed capacity, mirroring the reference mutation grammar
    (`ev_fragment_mutator.h:118-127`; `parse_ops` accepts the same
    `a/d/u` line forms as `fragment/mutation.parse_delta_efile`);
  * the buffer is applied only at superstep boundaries (already the
    consistent cuts ft/ checkpoints and guard/ digests are defined on),
    either as a dense overlay side-path (dyn/ingest.py) or by folding
    into a rebuilt CSR (dyn/repack.py);
  * `additive_only` is the soundness switch: edge ADDITIONS between
    known vertices extend a min-fold reduction exactly (extra
    candidates can only improve a tropical min), so they may ride the
    overlay and seed incremental IncEval; removals, weight updates,
    and vertex ops change the candidate set non-monotonically and
    force a repack (SparseP's delta-ratio framing, arxiv 2201.05072:
    past a threshold the amortized rebuild wins anyway).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterable, List, Optional, Sequence, Tuple

import numpy as np


class DeltaOverflowError(RuntimeError):
    """The staged op count exceeded the buffer's declared capacity.

    The buffer is bounded by design: the overlay side-path ships
    fixed-shape [fnum, capacity] arrays so ingest never changes the
    compiled state structure — an unbounded buffer would silently grow
    past what the overlay can represent.  Catch this and repack."""


@dataclass(frozen=True)
class DeltaSummary:
    """Hashable snapshot of a buffer's content class — what the
    incremental-IncEval contract (AppBase.inc_mode) decides on."""

    n_add_edges: int = 0
    n_remove_edges: int = 0
    n_update_edges: int = 0
    n_add_vertices: int = 0
    n_remove_vertices: int = 0
    additive_only: bool = True
    touched_oids: Tuple = ()

    @property
    def n_edge_ops(self) -> int:
        return self.n_add_edges + self.n_remove_edges + self.n_update_edges

    @property
    def n_ops(self) -> int:
        return self.n_edge_ops + self.n_add_vertices + self.n_remove_vertices


class DeltaBuffer:
    """Bounded, typed buffer of staged graph updates (dyn/).

    Ops accumulate until a repack folds them into the base CSR; the
    overlay (dyn/ingest.py) always reflects the FULL buffer, so queries
    between repacks see every staged edge."""

    def __init__(self, capacity: int = 4096):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.add_edges: List[Tuple[int, int, float]] = []
        self.remove_edges: List[Tuple[int, int]] = []
        self.update_edges: List[Tuple[int, int, float]] = []
        self.add_vertices: List[int] = []
        self.remove_vertices: List[int] = []

    # ---- staging ---------------------------------------------------------

    def _room(self, n: int) -> None:
        if self.n_ops + n > self.capacity:
            raise DeltaOverflowError(
                f"staging {n} op(s) would exceed the delta buffer "
                f"capacity ({self.n_ops} staged / {self.capacity}); "
                "repack (DynGraph.fold_now) before staging more"
            )

    def add_edge(self, src, dst, w: float = 0.0) -> None:
        self._room(1)
        self.add_edges.append((src, dst, float(w)))

    def remove_edge(self, src, dst) -> None:
        self._room(1)
        self.remove_edges.append((src, dst))

    def update_edge(self, src, dst, w: float) -> None:
        self._room(1)
        self.update_edges.append((src, dst, float(w)))

    def add_vertex(self, oid) -> None:
        self._room(1)
        self.add_vertices.append(oid)

    def remove_vertex(self, oid) -> None:
        self._room(1)
        self.remove_vertices.append(oid)

    def stage(self, ops: Iterable) -> int:
        """Stage a batch of op tuples; returns how many were staged.

        Atomic: the whole batch is validated (grammar) and checked
        against the capacity bound BEFORE anything is appended, so a
        failure stages NOTHING — the documented recoveries (fix the
        batch, or catch DeltaOverflowError / repack / retry) must
        never fold a half-staged prefix twice as duplicate edges.

        Grammar (one tuple per op, matching the delta-efile forms):
          ("a", src, dst[, w])   add edge
          ("d", src, dst)        remove edge
          ("u", src, dst, w)     update edge weight
          ("av", oid)            add vertex
          ("dv", oid)            remove vertex
        """
        ops = list(ops)
        self._room(len(ops))
        staged = []
        for op in ops:
            kind = op[0]
            if kind == "a" and len(op) >= 3:
                staged.append((self.add_edge, (
                    op[1], op[2], op[3] if len(op) > 3 else 0.0)))
            elif kind == "d" and len(op) >= 3:
                staged.append((self.remove_edge, (op[1], op[2])))
            elif kind == "u" and len(op) >= 4:
                staged.append((self.update_edge, (op[1], op[2], op[3])))
            elif kind == "av" and len(op) >= 2:
                staged.append((self.add_vertex, (op[1],)))
            elif kind == "dv" and len(op) >= 2:
                staged.append((self.remove_vertex, (op[1],)))
            else:
                raise ValueError(
                    f"malformed delta op {op!r}; expected "
                    "('a', s, d[, w]) / ('d', s, d) / ('u', s, d, w) / "
                    "('av', oid) / ('dv', oid)"
                )
        for fn, args in staged:
            fn(*args)
        return len(staged)

    # ---- introspection ---------------------------------------------------

    @property
    def n_ops(self) -> int:
        return (
            len(self.add_edges) + len(self.remove_edges)
            + len(self.update_edges) + len(self.add_vertices)
            + len(self.remove_vertices)
        )

    @property
    def n_edge_ops(self) -> int:
        return (
            len(self.add_edges) + len(self.remove_edges)
            + len(self.update_edges)
        )

    @property
    def additive_only(self) -> bool:
        """True when every staged op is an edge ADDITION — the class
        the overlay side-path and seeded incremental IncEval are exact
        for (see module docstring)."""
        return not (
            self.remove_edges or self.update_edges
            or self.add_vertices or self.remove_vertices
        )

    def delta_ratio(self, base_edges: int) -> float:
        """Staged edge ops as a fraction of the base graph's real edge
        count — the repack-policy trigger (SparseP framing)."""
        return self.n_edge_ops / max(1, int(base_edges))

    def touched_oids(self) -> np.ndarray:
        """Every vertex id named by a staged op (delta-touched set)."""
        ids: List = []
        for s, d, _ in self.add_edges:
            ids += [s, d]
        for s, d in self.remove_edges:
            ids += [s, d]
        for s, d, _ in self.update_edges:
            ids += [s, d]
        ids += list(self.add_vertices) + list(self.remove_vertices)
        if not ids:
            return np.zeros(0, dtype=np.int64)
        arr = np.asarray(ids)
        return np.unique(arr)

    def summary(self) -> DeltaSummary:
        return DeltaSummary(
            n_add_edges=len(self.add_edges),
            n_remove_edges=len(self.remove_edges),
            n_update_edges=len(self.update_edges),
            n_add_vertices=len(self.add_vertices),
            n_remove_vertices=len(self.remove_vertices),
            additive_only=self.additive_only,
            touched_oids=tuple(self.touched_oids().tolist()),
        )

    def clear(self) -> None:
        self.add_edges.clear()
        self.remove_edges.clear()
        self.update_edges.clear()
        self.add_vertices.clear()
        self.remove_vertices.clear()

    # ---- conversion ------------------------------------------------------

    def to_mutator(self, directed: bool = True):
        """The staged ops as a `fragment/mutation.BasicFragmentMutator`
        — the repack path reuses the existing rebuild machinery (pack
        planner + rebalancer run on the rebuilt fragment's next
        init_state, re-keying the v3 plan cache by content digest).

        On undirected graphs, remove/update ops apply to BOTH
        orientations (the reference rule, `ev_fragment_mutator.h:
        118-127`): the retained edge list stores each undirected edge
        in ONE arbitrary orientation, so a one-sided RemoveEdge(3, 9)
        would silently no-op when the list holds (9, 3)."""
        from libgrape_lite_tpu.fragment.mutation import BasicFragmentMutator

        m = BasicFragmentMutator()
        for oid in self.add_vertices:
            m.AddVertex(oid)
        for oid in self.remove_vertices:
            m.RemoveVertex(oid)
        for s, d, w in self.add_edges:
            m.AddEdge(s, d, w)
        for s, d in self.remove_edges:
            m.RemoveEdge(s, d)
            if not directed:
                m.RemoveEdge(d, s)
        for s, d, w in self.update_edges:
            m.UpdateEdge(s, d, w)
            if not directed:
                m.UpdateEdge(d, s, w)
        return m


def parse_ops_line(line: str, weighted: bool = True,
                   string_id: bool = False) -> Optional[tuple]:
    """One delta-stream line -> op tuple (None for blank/comment).

    The line grammar is the reference delta-efile's
    (`ev_fragment_mutator.h`): `a src dst [w]`, `d src dst`,
    `u src dst w`, plus vertex forms `av oid` / `dv oid`."""
    line = line.strip()
    if not line or line[0] == "#":
        return None
    parts = line.split()
    kind = parts[0]

    def vid(tok):
        return tok if string_id else int(tok)

    def need(n, form):
        # every malformed line gets the same descriptive grammar
        # error naming the offending line — never a bare IndexError
        if len(parts) < n:
            raise ValueError(
                f"malformed {kind!r} op {line!r}: expected {form!r}"
            )

    if kind == "a":
        # in a weighted stream the weight is mandatory — defaulting a
        # truncated line to 0.0 would silently add a zero-cost edge
        # (SSSP distances collapse through it with no error)
        need(4 if weighted else 3,
             "a src dst w" if weighted else "a src dst")
        w = float(parts[3]) if weighted else 0.0
        return ("a", vid(parts[1]), vid(parts[2]), w)
    if kind == "d":
        need(3, "d src dst")
        return ("d", vid(parts[1]), vid(parts[2]))
    if kind == "u":
        # the update weight is mandatory regardless of stream mode
        need(4, "u src dst w")
        return ("u", vid(parts[1]), vid(parts[2]), float(parts[3]))
    if kind == "av":
        need(2, "av oid")
        return ("av", vid(parts[1]))
    if kind == "dv":
        need(2, "dv oid")
        return ("dv", vid(parts[1]))
    raise ValueError(f"unknown delta op line {line!r}")


def parse_ops_file(path: str, weighted: bool = True,
                   string_id: bool = False) -> List[tuple]:
    """Read a whole delta stream file (scripts/gen_rmat.py --delta
    emits this format; the serve CLI ingests it via --delta_stream)."""
    out = []
    with open(path) as f:
        for line in f:
            op = parse_ops_line(line, weighted=weighted,
                                string_id=string_id)
            if op is not None:
                out.append(op)
    return out
