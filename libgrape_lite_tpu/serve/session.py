"""ServeSession: a resident graph serving many queries.

The reference libgrape-lite is a library — load, query once, exit; the
ROADMAP north star is a service.  A session inverts the lifetime: the
expensive per-graph artifacts are pinned ONCE and every query reuses
them —

  * the HBM-resident sharded fragment (`frag.dev` device CSRs),
  * pack plans (ops/spmv_pack resolves through its per-fragment cache
    + the v3 on-disk plan cache; `plan_stats()` proves the planner
    never re-runs),
  * compiled fused runners, keyed by (app hyperparameters, state
    shape, max_rounds) in each app's resident Worker
    (`Worker._runner_cache` — the session owns the workers, so the
    cache spans queries and `runner_cache_stats` proves the second
    query of a shape compiles nothing).

Queries arrive through the AdmissionQueue (serve/queue.py), coalesce
into vmapped multi-source batches (Worker.query_batch) under the
BatchPolicy, and keep per-query observability: each lane gets its own
trace track + result record, and with guards armed each lane gets its
own monitor with breach isolation (serve/batch.py).

Typical use::

    sess = ServeSession(frag)
    reqs = [sess.submit("sssp", {"source": s}) for s in sources]
    sess.drain()                      # or pump() under a wait policy
    values = reqs[0].result.values

docs/SERVING.md is the user guide.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from libgrape_lite_tpu import obs
from libgrape_lite_tpu.serve.policy import BatchPolicy, compat_key
from libgrape_lite_tpu.serve.queue import (
    AdmissionQueue,
    QueryRequest,
    ServeResult,
)
from libgrape_lite_tpu.worker.worker import Worker


def _calibration_harvester():
    """The live-harvest hook (ops/calibration.py): when
    GRAPE_CALIBRATE_HARVEST is armed, returns the callable that joins
    a dispatch's telemetry `device_us` stamp to its worker's shipped
    pack-ledger recount; None (the common case) costs one env read."""
    from libgrape_lite_tpu.ops import calibration

    if not calibration.harvest_armed():
        return None
    return calibration.harvest_from_worker


class ServeSession:
    def __init__(self, fragment, apps: Dict | None = None,
                 policy: BatchPolicy | None = None,
                 guard: Optional[str] = None, dyn=None):
        """`apps` maps app_key -> app factory (default: the full
        APP_REGISTRY); `guard` is the session-default guard policy
        (per-request `guard=` wins).

        `dyn` enables live ingest (dyn/, docs/DYNAMIC_GRAPHS.md):
        True (env-configured RepackPolicy), a RepackPolicy, or a
        pre-built DynGraph.  The session then accepts `ingest(ops)`
        between pumps — staged deltas ride the overlay side-path
        (zero replanning, zero recompiles) until the repack policy
        folds them, a counted recompile event.  Requires the fragment
        loaded with retain_edge_list=True for the repack path."""
        if apps is None:
            from libgrape_lite_tpu.models import APP_REGISTRY

            apps = dict(APP_REGISTRY)
        self.dyn = None
        if dyn is not None and dyn is not False:
            # the delta overlay is an edge-cut side-path: the vc2d
            # apps never read `dyn_overlay`, so a dyn vertex-cut
            # session would serve STALE results silently — refuse
            # loudly instead (docs/PARTITION2D.md "Serve + fleet")
            if getattr(fragment, "_host_tiles", None) is not None:
                raise ValueError(
                    "dyn ingest is not supported on a vertex-cut "
                    "fragment: the 2-D tile pulls do not read the "
                    "delta overlay, so staged edges would be "
                    "silently invisible; repack into a new fragment "
                    "instead"
                )
            from libgrape_lite_tpu.dyn import DynGraph, RepackPolicy

            if isinstance(dyn, DynGraph):
                self.dyn = dyn
            else:
                self.dyn = DynGraph(
                    fragment,
                    policy=None if dyn is True else dyn,
                )
            fragment = self.dyn.fragment
        self.fragment = fragment
        self.apps = apps
        self.policy = policy or BatchPolicy()
        self.guard = guard
        self.queue = AdmissionQueue(
            self._dispatch, self.policy, self._compat_key
        )
        self._workers: Dict[str, Worker] = {}
        self._pump = None  # the attached AsyncServePump, if any
        self._closed = False
        # optional result cache (autopilot/cache.py) + its epoch
        # source; a bare session's epoch is its own ingest counter, a
        # fleet replica's is the router fence (attach_result_cache)
        self._cache = None
        self._cache_epoch = None
        self._ingest_epoch = 0
        self.stats = {
            "queries": 0, "batches": 0, "failed": 0,
            "sequential_fallbacks": 0, "cache_hits": 0,
            "ingested_ops": 0, "overlay_applies": 0, "repacks": 0,
            "forced_repacks": 0,
        }

    # ---- resident workers -------------------------------------------------

    def worker(self, app_key: str) -> Worker:
        """The resident Worker for one app: created on first use, then
        reused for every query — its runner cache is the session's
        zero-recompile guarantee."""
        w = self._workers.get(app_key)
        if w is None:
            if app_key not in self.apps:
                raise ValueError(
                    f"unknown application {app_key!r}; session serves: "
                    f"{sorted(self.apps)}"
                )
            w = Worker(self.apps[app_key](), self.fragment)
            self._workers[app_key] = w
        return w

    def cache_stats(self) -> dict:
        """Aggregated cache counters: compiled-runner hits/misses over
        every resident worker plus the pack resolve-path counters —
        the numbers the zero-recompile/zero-replanning acceptance
        asserts on."""
        from libgrape_lite_tpu.ops.spmv_pack import plan_stats

        runner = {"hits": 0, "misses": 0}
        for w in self._workers.values():
            runner["hits"] += w.runner_cache_stats["hits"]
            runner["misses"] += w.runner_cache_stats["misses"]
        return {"runner": runner, "pack": plan_stats()}

    # ---- lifecycle: eviction / re-admission / close (fleet/) --------------

    @property
    def resident(self) -> bool:
        """True while the fragment's device arrays are placed (a
        released/evicted session keeps every host artifact but holds
        no HBM)."""
        return self.fragment.dev is not None

    def release_device(self, *,
                       release_fragment: bool = True) -> dict:
        """Evict this session's device footprint: quiesce any attached
        pump, drop each resident worker's retained result buffers
        (`Worker.release_buffers`), and — unless the fragment is
        shared with a sibling session (`release_fragment=False`, the
        FleetManager's call) — delete the fragment's device arrays.

        Everything HOST-side stays warm: the per-fragment pack-plan
        cache (weak-keyed on this very fragment object), the v3 disk
        plan cache, the compiled-runner caches, the mirror plans.
        `restore_device` therefore re-admits with ZERO pack
        re-planning and ZERO XLA recompiles — counter- and
        compile_events-pinned by tests/test_fleet.py."""
        if self._pump is not None and self._pump.inflight():
            self._pump.quiesce(reason="release_device")
        for w in self._workers.values():
            w.release_buffers()
        released = False
        if release_fragment:
            released = self.fragment.release_device()
        return {"fragment_released": released,
                "workers": len(self._workers)}

    def restore_device(self) -> bool:
        """Re-admit an evicted session: re-place the device arrays
        from the retained host CSRs (byte-identical content — the
        build is deterministic).  Returns True when a placement
        actually happened (False: already resident, e.g. a shared
        fragment restored by a sibling)."""
        if self._closed:
            raise RuntimeError("session is closed")
        return self.fragment.restore_device()

    def close(self) -> None:
        """Terminal release: drain + detach the pump, release the
        device footprint, and drop the resident workers (their
        compiled-runner caches go with them).  Further submits raise;
        close is idempotent."""
        if self._closed:
            return
        if self._pump is not None:
            self._pump.close()
        self.release_device()
        self._workers.clear()
        self._closed = True

    # ---- live ingest (dyn/) ----------------------------------------------

    def ingest(self, ops, *, force_repack: bool = False) -> dict:
        """Apply a batch of delta ops between dispatches (the host-
        pumped loop makes this a superstep boundary by construction —
        no query is ever mid-flight here).  Below the repack threshold
        the staged edges ride the overlay side-path and the next query
        of a warmed shape compiles NOTHING (runner cache hit, zero
        pack planning — pinned by tests/test_dyn.py); at a repack the
        rebuilt fragment is adopted into every resident worker and the
        recompiles that follow are COUNTED in cache_stats, never
        silent.  Returns the DynGraph report ({mode, staged, ...})."""
        if self.dyn is None:
            raise RuntimeError(
                "session was built without dyn=; pass dyn=True (or a "
                "RepackPolicy / DynGraph) to enable live ingest"
            )
        # with an async pump attached, the superstep-boundary
        # invariant is an EXPLICIT drain, not an accident of the sync
        # loop: quiesce the dispatch window before touching the graph
        # (a no-op when nothing is in flight, e.g. when the pump's own
        # ingest barrier already drained it)
        if self._pump is not None and self._pump.inflight():
            self._pump.quiesce(reason="ingest")
        # delta from the DynGraph's own counters: one ingest can fold
        # MORE than once (staging past capacity repacks mid-batch), so
        # the final report's mode alone undercounts
        before_r = self.dyn.stats["repacks"]
        before_o = self.dyn.stats["overlay_applies"]
        report = self.dyn.ingest(ops, force_repack=force_repack)
        self.stats["ingested_ops"] += report.get("staged", 0)
        self.stats["repacks"] += self.dyn.stats["repacks"] - before_r
        self.stats["overlay_applies"] += (
            self.dyn.stats["overlay_applies"] - before_o
        )
        if self.dyn.fragment is not self.fragment:
            self._adopt_fragment()
        if report.get("staged", 0):
            # a content-changing ingest advances the cache epoch (an
            # empty forced repack preserves every answer and must NOT
            # kill the cache); a session owning its own epoch reaps
            # the stale one here — a fleet replica's router does this
            # at the fence bump instead (fleet/router.py)
            self._ingest_epoch += 1
            if self._cache is not None and self._cache_epoch is not None:
                try:
                    self._cache.invalidate_stale(self._cache_epoch())
                except Exception:
                    pass
        return report

    def _adopt_fragment(self) -> None:
        """Point the session and every resident worker at the rebuilt
        fragment.  Stale compiled runners stay in the caches but miss
        naturally: the apps' re-resolved plan/mirror uids enter the
        trace key, so the first post-repack query of each shape is a
        counted compile."""
        self.fragment = self.dyn.fragment
        for w in self._workers.values():
            w.fragment = self.dyn.fragment

    def _ensure_dyn_view(self, app_key: str, w: Worker) -> None:
        """Apps without an overlay contract (PageRank, host-only
        loops) must see a consistent graph: fold the pending overlay
        into the CSR before dispatching them — a counted forced
        repack, not a silent stale read."""
        if self.dyn is None or self.dyn.overlay_count == 0:
            return
        if getattr(w.app, "dyn_overlay_support", False):
            return
        self.dyn.fold_now(
            reason=f"{app_key} has no dyn-overlay contract"
        )
        self.stats["repacks"] += 1
        self.stats["forced_repacks"] += 1
        self._adopt_fragment()

    # ---- admission --------------------------------------------------------

    def _compat_for(self, app_key: str, args: dict, max_rounds,
                    guard, tenant) -> tuple:
        # an unknown app must not raise here: the queue calls this
        # while PICKING the next batch, and a raise would wedge the
        # head of the queue forever — the dispatch path turns the
        # lookup failure into per-request error results instead
        if app_key not in self.apps:
            return (app_key, "?unknown", tenant)
        # batch_query_key is a CLASS attribute: read it off the
        # registered app class directly — instantiating the resident
        # Worker here (as this method once did) built state and pack
        # plans while the queue was merely PICKING a batch, so a bare
        # submit of a never-dispatched app paid a full worker warmup.
        # The tenant tag joins the key so requests of DIFFERENT
        # tenants never share a batched dispatch — one tenant's
        # poisoned lane can never fail a batchmate tenant (fleet/).
        return compat_key(
            app_key, args, max_rounds, guard or self.guard,
            getattr(self.apps[app_key], "batch_query_key", None),
            getattr(self.apps[app_key], "mesh_kind", "frag"),
        ) + (tenant,)

    def _compat_key(self, req: QueryRequest) -> tuple:
        return self._compat_for(req.app_key, req.args, req.max_rounds,
                                req.guard, req.tenant)

    # ---- result cache / admission control (autopilot/) --------------------

    def attach_result_cache(self, cache, epoch=None) -> None:
        """Wire a ResultCache (autopilot/cache.py) into this session:
        `submit` probes it BEFORE the request enters coalescing, and
        the queue's `deliver` stores every cacheable OK result.
        `epoch` supplies the invalidation fence (the FleetRouter
        passes its own `lambda: router.fence`); a bare session uses
        its ingest counter — any content-changing ingest bumps it and
        the stale epoch dies wholesale."""
        self._cache = cache
        self._cache_epoch = epoch or (lambda: self._ingest_epoch)
        self.queue.result_cache = cache
        self.queue.cache_meta = self._cache_meta
        self.queue.cache_epoch = self._cache_epoch

    def attach_admission(self, controller) -> None:
        """Wire an AdmissionController (autopilot/admission.py): the
        queue's pop sweep sheds/defers over-budget tenants before
        coalescing."""
        self.queue.admission = controller.review

    def _cacheable(self, app_key: str, args: dict, guard):
        """The lane source when (app_key, args, guard) is cacheable —
        a point query (batch_query_key contract) with its lane arg
        present and no guard armed (guarded runs carry verdicts a
        cache must not replay) — else None."""
        if self._cache is None or (guard or self.guard) is not None:
            return None
        app = self.apps.get(app_key)
        bq = getattr(app, "batch_query_key", None) if app else None
        if bq is None:
            return None
        return args.get(bq)

    def _cache_meta(self, req: QueryRequest):
        """(compat, source) for a cacheable request, else None — the
        queue's deliver() store hook."""
        source = self._cacheable(req.app_key, req.args, req.guard)
        if source is None:
            return None
        return (self._compat_key(req), source)

    def _deliver_cached(self, app_key: str, args: dict, entry, *,
                        max_rounds, priority, deadline_s,
                        tenant) -> QueryRequest:
        """Serve one cache hit WITHOUT dispatching: mint the request +
        result pair, stamp zeroed stages (no queue wait, no device
        time — honest, not missing), emit a `serve_query` span with
        ``cached=true``, run the SAME `slo.observe` accounting as a
        delivered result, and push it on the queue's out-of-band
        channel so every pump/drain surface returns it."""
        import time as _time

        from libgrape_lite_tpu.obs import slo

        t0_ns = _time.perf_counter_ns()
        req = QueryRequest(
            app_key=app_key, args=dict(args), max_rounds=max_rounds,
            priority=int(priority), deadline_s=deadline_s,
            tenant=tenant,
        )
        req.popped_s = req.submitted_s
        vals, rounds, code = entry
        res = ServeResult(
            request_id=req.id, app_key=app_key, ok=True, values=vals,
            rounds=rounds, terminate_code=code, batch_size=1,
            stages={"queue_wait_us": 0, "window_wait_us": 0,
                    "dispatch_us": 0, "device_us": 0, "harvest_us": 0},
        )
        res.latency_s = _time.perf_counter() - req.submitted_s
        req.result = res
        self.stats["cache_hits"] += 1
        slo.observe(app_key, tenant, res.latency_s, True)
        tr = obs.tracer()
        if tr.enabled:
            tr.emit_span_raw(
                "serve_query", t0_ns=t0_ns,
                dur_ns=_time.perf_counter_ns() - t0_ns,
                tid=tr.lane_tid(0), query_id=req.id, app=app_key,
                lane=0, rounds=rounds, ok=True, cached=True,
                tenant=tenant or "", queue_wait_us=0,
            )
        self.queue.push_oob(res)
        return req

    def submit(self, app_key: str, args: dict | None = None, *,
               max_rounds: int | None = None,
               guard: str | None = None, priority: int = 0,
               deadline_s: float | None = None,
               tenant: str | None = None) -> QueryRequest:
        if self._closed:
            raise RuntimeError("session is closed")
        args = dict(args or {})
        # result-cache probe BEFORE coalescing (autopilot/cache.py): a
        # hit never enters the queue at all — the device, the batch
        # planner, and the admission sweep all skip it
        source = self._cacheable(app_key, args, guard)
        if source is not None:
            compat = self._compat_for(app_key, args, max_rounds,
                                      guard, tenant)
            fence = self._cache_epoch()
            entry = self._cache.lookup(compat, source, fence)
            if entry is not None:
                return self._deliver_cached(
                    app_key, args, entry, max_rounds=max_rounds,
                    priority=priority, deadline_s=deadline_s,
                    tenant=tenant,
                )
        return self.queue.submit(
            app_key, args, max_rounds=max_rounds, guard=guard,
            priority=priority, deadline_s=deadline_s, tenant=tenant,
        )

    def pump(self, **kw) -> List[ServeResult]:
        return self.queue.pump(**kw)

    def drain(self) -> List[ServeResult]:
        return self.queue.drain()

    def async_pump(self, window: int | None = None):
        """An AsyncServePump over this session (serve/pipeline.py):
        up to `window` coalesced batches dispatched-but-unharvested at
        once (default: `policy.inflight`).  W=1 is byte- and
        result-order-identical to the synchronous `pump`/`drain`
        loop; the synchronous loop itself is untouched either way."""
        from libgrape_lite_tpu.serve.pipeline import AsyncServePump

        return AsyncServePump(self, window=window)

    def serve(self, stream) -> List[ServeResult]:
        """Scripted-stream convenience: submit every item, drain, and
        return results in completion order.  Items are (app_key, args)
        pairs or {"app": ..., "args": {...}, "max_rounds": ...,
        "guard": ...} dicts — the CLI `serve` subcommand's format."""
        for item in stream:
            if isinstance(item, dict):
                self.submit(
                    item["app"], item.get("args"),
                    max_rounds=item.get("max_rounds"),
                    guard=item.get("guard"),
                    priority=item.get("priority", 0),
                    deadline_s=item.get("deadline_s"),
                    tenant=item.get("tenant"),
                )
            else:
                app_key, args = item
                self.submit(app_key, args)
        return self.drain()

    # ---- dispatch ---------------------------------------------------------

    def _dispatch(self, batch: List[QueryRequest]) -> List[ServeResult]:
        """Run one coalesced batch: a single query through the plain
        fused path, several through the vmapped batched runner (guarded
        or not), with a sequential fallback for apps that cannot batch
        (host-only loops, mutation apps).  Per-request outcomes never
        raise out of the serve loop — failures become error results."""
        self.stats["batches"] += 1
        self.stats["queries"] += len(batch)
        try:
            w = self.worker(batch[0].app_key)
        except ValueError as e:
            # unknown app: fail these requests, keep the loop serving
            self.stats["failed"] += len(batch)
            return [
                ServeResult(
                    request_id=req.id, app_key=req.app_key, ok=False,
                    error={"error": str(e)}, lane=b,
                    batch_size=len(batch),
                )
                for b, req in enumerate(batch)
            ]
        try:
            self._ensure_dyn_view(batch[0].app_key, w)
        except Exception as e:
            # a failed forced repack (e.g. the fragment was loaded
            # without retain_edge_list) must not raise out of the
            # serve loop — the popped requests get error results
            self.stats["failed"] += len(batch)
            return [
                ServeResult(
                    request_id=req.id, app_key=req.app_key, ok=False,
                    error={"error": f"{type(e).__name__}: {e}"},
                    lane=b, batch_size=len(batch),
                )
                for b, req in enumerate(batch)
            ]
        guard = batch[0].guard or self.guard
        mr = batch[0].max_rounds
        tr = obs.tracer()

        if len(batch) > 1:
            try:
                w._check_batchable()
            except ValueError:
                self.stats["sequential_fallbacks"] += 1
                return [
                    r for req in batch
                    for r in [self._run_single(w, req, guard)]
                ]
            with tr.span("serve_batch", app=batch[0].app_key,
                         batch=len(batch)) as sp:
                results = self._run_batched(w, batch, mr, guard)
            if tr.enabled:
                # one track per query: the lane's interval IS the batch
                # dispatch interval, tagged with its request id so the
                # timeline stays attributable after coalescing
                for b, (req, res) in enumerate(zip(batch, results)):
                    tr.emit_span_raw(
                        "serve_query", t0_ns=sp.t0_ns,
                        dur_ns=sp.dur_ns, tid=tr.lane_tid(b),
                        query_id=req.id, app=req.app_key, lane=b,
                        rounds=res.rounds, ok=res.ok,
                        tenant=req.tenant or "",
                        queue_wait_us=self._queue_wait_us(req),
                    )
            return results

        with tr.span("serve_batch", app=batch[0].app_key, batch=1) as sp:
            res = self._run_single(w, batch[0], guard)
        if tr.enabled:
            tr.emit_span_raw(
                "serve_query", t0_ns=sp.t0_ns, dur_ns=sp.dur_ns,
                tid=tr.lane_tid(0), query_id=batch[0].id,
                app=batch[0].app_key, lane=0, rounds=res.rounds,
                ok=res.ok, tenant=batch[0].tenant or "",
                queue_wait_us=self._queue_wait_us(batch[0]),
            )
        return [res]

    @staticmethod
    def _queue_wait_us(req: QueryRequest) -> int:
        """submit->pop µs for one request (0 before the pop stamp)."""
        if not req.popped_s:
            return 0
        return int((req.popped_s - req.submitted_s) * 1e6)

    @staticmethod
    def _exec_stages(w: Worker, total_ns: int) -> dict:
        """Batch-level stage split of one synchronous dispatch, from
        the worker's host stamps when the path decomposed (fused /
        batched runners) — otherwise the whole execute is attributed
        to dispatch_us (guarded/stepwise/host paths run host work and
        device chunks interleaved; pretending to split them would be
        a made-up number, not a measurement)."""
        st = w.last_stage_ns
        if st is not None:
            return {
                "window_wait_us": 0,
                "dispatch_us": st["dispatch"] // 1000,
                "device_us": st["device"] // 1000,
            }
        return {
            "window_wait_us": 0,
            "dispatch_us": total_ns // 1000,
            "device_us": 0,
        }

    def _run_single(self, w: Worker, req: QueryRequest,
                    guard) -> ServeResult:
        import time as _time

        from libgrape_lite_tpu.guard.monitor import GuardError

        try:
            t0 = _time.perf_counter_ns()
            w.query(req.max_rounds, guard=guard, **req.args)
            t_exec = _time.perf_counter_ns()
            vals = w.result_values()
            stages = self._exec_stages(w, t_exec - t0)
            stages["harvest_us"] = (
                _time.perf_counter_ns() - t_exec
            ) // 1000
            if _calibration_harvester() is not None:
                _calibration_harvester()(w, stages, w.rounds)
            return ServeResult(
                request_id=req.id, app_key=req.app_key, ok=True,
                values=vals, rounds=w.rounds,
                terminate_code=w._terminate_code, batch_size=1,
                stages=stages,
            )
        except GuardError as e:
            self.stats["failed"] += 1
            return ServeResult(
                request_id=req.id, app_key=req.app_key, ok=False,
                error=e.bundle, rounds=w.rounds, batch_size=1,
            )
        except Exception as e:  # one bad query must not kill the loop
            self.stats["failed"] += 1
            return ServeResult(
                request_id=req.id, app_key=req.app_key, ok=False,
                error={"error": f"{type(e).__name__}: {e}"},
                batch_size=1,
            )

    def _run_batched(self, w: Worker, batch: List[QueryRequest],
                     mr, guard) -> List[ServeResult]:
        import time as _time

        try:
            t0 = _time.perf_counter_ns()
            w.query_batch(
                [req.args for req in batch], mr, guard=guard
            )
            t_exec = _time.perf_counter_ns()
        except Exception as e:  # whole-batch failure: every lane errors
            self.stats["failed"] += len(batch)
            return [
                ServeResult(
                    request_id=req.id, app_key=req.app_key, ok=False,
                    error={"error": f"{type(e).__name__}: {e}"},
                    lane=b, batch_size=len(batch),
                )
                for b, req in enumerate(batch)
            ]
        stages = self._exec_stages(w, t_exec - t0)
        if _calibration_harvester() is not None:
            # the vmapped batch runs every lane to the max round in
            # lockstep, so the device stamp covers rounds x lanes of
            # the per-round ledger columns
            br = w.batch_rounds
            rounds = (max(int(r) for r in br)
                      if br is not None and len(br) else w.rounds)
            _calibration_harvester()(w, stages, rounds * len(batch))
        results = []
        breaches = w.batch_breaches or [None] * len(batch)
        for b, req in enumerate(batch):
            if breaches[b] is not None:
                self.stats["failed"] += 1
                results.append(ServeResult(
                    request_id=req.id, app_key=req.app_key, ok=False,
                    error=breaches[b], rounds=int(w.batch_rounds[b]),
                    lane=b, batch_size=len(batch),
                    stages=dict(stages),
                ))
            else:
                results.append(ServeResult(
                    request_id=req.id, app_key=req.app_key, ok=True,
                    values=w.batch_result_values(b),
                    rounds=int(w.batch_rounds[b]),
                    terminate_code=int(w.batch_terminate[b]),
                    lane=b, batch_size=len(batch),
                    stages=dict(stages),
                ))
        # per-lane extraction happened inside the loop above: the
        # batch-level harvest stage is the whole post-sync interval
        harvest_us = (_time.perf_counter_ns() - t_exec) // 1000
        for r in results:
            r.stages["harvest_us"] = harvest_us
        return results
