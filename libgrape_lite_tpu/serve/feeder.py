"""Threaded admission front: real wall-clock arrivals (ROADMAP 2a).

The scripted serving drivers submit every query up front, so
`BatchPolicy.max_wait_s` and the priority/deadline scheduling in
`AdmissionQueue._pop_ready` are never exercised under load — the
queue head has always "waited forever" by the time the pump runs.
`ArrivalFeeder` fixes that with ONE feeder thread that submits the
scripted stream at a fixed arrival rate (deterministic 1/rate
spacing — reproducible arrival ORDER; the wall-clock timestamps are
the point), while the caller's thread keeps pumping:

    feeder = ArrivalFeeder(sess.submit, stream, rate_qps=200.0)
    feeder.start()
    while feeder.is_alive() or sess.queue.pending():
        sess.pump(force=False)   # max_wait_s now genuinely gates
    feeder.join(); sess.drain()

`AdmissionQueue.submit` and `_pop_ready` share a lock, so the feeder
thread and the pump thread never race on the pending list.  The
deterministic scripted mode (no feeder) is byte-for-bit untouched —
this module only ADDS a second producer.

The CLI surface is `serve --arrival_rate QPS` — where QPS is either
a plain float or a **step schedule** like ``"50:2x@100"`` (start at
50 qps, double the rate from query index 100 on).  Steps chain:
``"50:2x@100:0.5x@300"``.  The schedule is what makes load-shift
drills (autopilot/ scale-up under a rate step) reproducible.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Tuple


def parse_rate_spec(spec) -> Tuple[float, List[Tuple[int, float]]]:
    """``"50:2x@100"`` -> ``(50.0, [(100, 2.0)])``: a base rate plus
    ``(index, multiplier)`` steps applied cumulatively from that
    arrival index on.  A bare number (or numeric string) has no
    steps.  Raises ValueError on malformed specs."""
    if isinstance(spec, (int, float)):
        base, steps = float(spec), []
    else:
        parts = str(spec).split(":")
        base = float(parts[0])
        steps = []
        last_idx = 0
        for part in parts[1:]:
            try:
                mult_s, idx_s = part.split("@")
                if not mult_s.endswith("x"):
                    raise ValueError
                mult = float(mult_s[:-1])
                idx = int(idx_s)
            except ValueError:
                raise ValueError(
                    f"bad rate step {part!r} in {spec!r} "
                    "(want MULTx@INDEX, e.g. 2x@100)"
                ) from None
            if mult <= 0:
                raise ValueError(f"rate multiplier must be > 0: {part!r}")
            if idx <= last_idx:
                raise ValueError(
                    f"rate steps must have increasing indices: {spec!r}"
                )
            steps.append((idx, mult))
            last_idx = idx
    if base <= 0:
        raise ValueError(f"rate_qps must be > 0, got {base}")
    return base, steps


def arrival_offsets(n: int, base: float,
                    steps: List[Tuple[int, float]]) -> List[float]:
    """Precomputed arrival offset (seconds from t0) for each of `n`
    arrivals under the step schedule: arrival i+1 follows arrival i
    by 1/rate(i), where rate(i) is the base times every multiplier
    whose step index is <= i."""
    out, t, rate = [], 0.0, float(base)
    pending = list(steps)
    for i in range(n):
        while pending and pending[0][0] <= i:
            rate *= pending.pop(0)[1]
        out.append(t)
        t += 1.0 / rate
    return out


class ArrivalFeeder(threading.Thread):
    """Submit `stream` items through `submit_fn` at `rate_qps`
    arrivals/second — a float, or a step-schedule string like
    ``"50:2x@100"`` (see `parse_rate_spec`).  Items are (app_key,
    args) pairs or dicts in the `ServeSession.serve` format
    (optionally carrying max_rounds / guard / priority / deadline_s /
    tenant).  Submitted requests accumulate in `self.requests` in
    arrival order."""

    def __init__(self, submit_fn: Callable, stream, rate_qps,
                 name: str = "grape-feeder"):
        super().__init__(name=name, daemon=True)
        base, steps = parse_rate_spec(rate_qps)
        self._submit = submit_fn
        self._stream = list(stream)
        self.rate_qps = base  # base rate (back-compat float surface)
        self.rate_steps = steps
        self._offsets = arrival_offsets(len(self._stream), base, steps)
        self.requests: List = []
        self.submitted = 0

    def run(self) -> None:
        t0 = time.perf_counter()
        for i, item in enumerate(self._stream):
            # absolute schedule (t0 + offset[i]), not sleep(period):
            # a slow submit must not stretch every later arrival
            delay = t0 + self._offsets[i] - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            if isinstance(item, dict):
                req = self._submit(
                    item["app"], item.get("args"),
                    max_rounds=item.get("max_rounds"),
                    guard=item.get("guard"),
                    priority=item.get("priority", 0),
                    deadline_s=item.get("deadline_s"),
                    tenant=item.get("tenant"),
                )
            else:
                app_key, args = item
                req = self._submit(app_key, args)
            self.requests.append(req)
            self.submitted += 1
