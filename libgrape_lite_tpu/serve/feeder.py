"""Threaded admission front: real wall-clock arrivals (ROADMAP 2a).

The scripted serving drivers submit every query up front, so
`BatchPolicy.max_wait_s` and the priority/deadline scheduling in
`AdmissionQueue._pop_ready` are never exercised under load — the
queue head has always "waited forever" by the time the pump runs.
`ArrivalFeeder` fixes that with ONE feeder thread that submits the
scripted stream at a fixed arrival rate (deterministic 1/rate
spacing — reproducible arrival ORDER; the wall-clock timestamps are
the point), while the caller's thread keeps pumping:

    feeder = ArrivalFeeder(sess.submit, stream, rate_qps=200.0)
    feeder.start()
    while feeder.is_alive() or sess.queue.pending():
        sess.pump(force=False)   # max_wait_s now genuinely gates
    feeder.join(); sess.drain()

`AdmissionQueue.submit` and `_pop_ready` share a lock, so the feeder
thread and the pump thread never race on the pending list.  The
deterministic scripted mode (no feeder) is byte-for-bit untouched —
this module only ADDS a second producer.

The CLI surface is `serve --arrival_rate QPS`.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List


class ArrivalFeeder(threading.Thread):
    """Submit `stream` items through `submit_fn` at `rate_qps`
    arrivals/second.  Items are (app_key, args) pairs or dicts in the
    `ServeSession.serve` format (optionally carrying max_rounds /
    guard / priority / deadline_s / tenant).  Submitted requests
    accumulate in `self.requests` in arrival order."""

    def __init__(self, submit_fn: Callable, stream, rate_qps: float,
                 name: str = "grape-feeder"):
        super().__init__(name=name, daemon=True)
        if rate_qps <= 0:
            raise ValueError(f"rate_qps must be > 0, got {rate_qps}")
        self._submit = submit_fn
        self._stream = list(stream)
        self.rate_qps = float(rate_qps)
        self.requests: List = []
        self.submitted = 0

    def run(self) -> None:
        period = 1.0 / self.rate_qps
        t0 = time.perf_counter()
        for i, item in enumerate(self._stream):
            # absolute schedule (t0 + i*period), not sleep(period):
            # a slow submit must not stretch every later arrival
            delay = t0 + i * period - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            if isinstance(item, dict):
                req = self._submit(
                    item["app"], item.get("args"),
                    max_rounds=item.get("max_rounds"),
                    guard=item.get("guard"),
                    priority=item.get("priority", 0),
                    deadline_s=item.get("deadline_s"),
                    tenant=item.get("tenant"),
                )
            else:
                app_key, args = item
                req = self._submit(app_key, args)
            self.requests.append(req)
            self.submitted += 1
