"""Admission/coalescing policy for the serving runtime.

The reference libgrape-lite is a library invoked once per query; the
serving runtime (ROADMAP item 1, "millions of users") multiplexes many
point queries over one resident graph, and this module is the ONLY
place the batching trade-off lives: how many compatible queries may
share one vmapped dispatch (`max_batch`), and how long the head of the
queue may wait for batchmates before a partial batch ships
(`max_wait_s`).  The classic serving knobs — same shape as any
batching RPC frontend.

Compatibility is structural, not heuristic: two requests coalesce only
when they would compile to the SAME runner — same app, same
`max_rounds` (the round limit is baked into the while_loop cond; see
Worker._runner_for), same guard policy, and identical non-batched
query args.  The per-lane arg (`batch_query_key`, e.g. the SSSP/BFS
source) is the only thing allowed to vary inside a batch.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class BatchPolicy:
    """Knobs of the admission queue (serve/queue.py)."""

    # lanes per vmapped dispatch; 1 disables batching (every query runs
    # the plain fused path — the bench's baseline lane)
    max_batch: int = 8
    # seconds the queue head may wait for batchmates; 0 = ship whatever
    # has coalesced by the time the pump runs (scripted/offline streams
    # drain as fast as possible)
    max_wait_s: float = 0.0
    # dispatch-window depth for the async pump (serve/pipeline.py):
    # how many coalesced batches may be dispatched-but-unharvested at
    # once.  1 = the synchronous discipline (dispatch, harvest, then
    # pick the next batch — result-order- and byte-identical to the
    # host-pumped loop); >1 overlaps host admission/extraction with
    # device execution via JAX async dispatch.  Only consulted when a
    # pump is constructed — the plain queue.pump path never reads it.
    inflight: int = 1

    def __post_init__(self):
        if self.max_batch < 1:
            raise ValueError(
                f"max_batch must be >= 1, got {self.max_batch}"
            )
        if self.max_wait_s < 0:
            raise ValueError(
                f"max_wait_s must be >= 0, got {self.max_wait_s}"
            )
        if self.inflight < 1:
            raise ValueError(
                f"inflight must be >= 1, got {self.inflight}"
            )


def compat_key(app_key: str, args: dict, max_rounds, guard,
               batch_key: str | None, mesh_kind: str = "frag"):
    """Hashable coalescing key: requests with equal keys may share one
    batched dispatch.  `batch_key` (the app's per-lane query arg) is
    excluded — it is exactly what varies across lanes; everything else
    (app, round limit, guard policy, remaining args) must match or the
    lanes would need different compiled runners.  `mesh_kind` is
    structural too: a vc2d app compiles over the k x k SUMMA mesh and
    must never coalesce (or share a result-cache identity) with a 1-D
    frag-mesh dispatch of the same app key."""
    fixed = tuple(sorted(
        (k, v) for k, v in args.items() if k != batch_key
    ))
    policy = getattr(guard, "policy", guard) or ""
    # whether the lane ARG is present is itself structural: a
    # personalized-PageRank lane (source given) and a global lane
    # (no source) trace different states and must not share a batch
    has_lane_arg = (
        batch_key is not None and args.get(batch_key) is not None
    )
    return (app_key, max_rounds, str(policy), fixed, has_lane_arg,
            mesh_kind)
