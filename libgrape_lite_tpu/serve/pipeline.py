"""Async serving pipeline: overlapped admission/dispatch/harvest.

The synchronous serve loop (`AdmissionQueue.pump` ->
`ServeSession._dispatch`) runs one coalesced batch, blocks pulling
every lane's result to host, and only then lets the queue pick the
next batch — the device idles during admission/coalescing/extraction
and the host idles while the device runs, and a live `--delta_stream`
ingest serialises against both.  JAX async dispatch makes the fix
structural: a dispatched runner returns un-synced device refs, so the
pump can keep a WINDOW of W dispatched batches in flight and harvest
lazily.  Three stages, one host thread, no background workers
(deterministic and testable, like the sync queue):

* **dispatch** (`_fill`/`_dispatch_stage`): pop ready batches with the
  queue's own policy decision (`AdmissionQueue._pop_ready` — same
  batch composition, same FIFO order) and dispatch them un-synced
  through `Worker.query_batch_dispatch` until the window holds W.
  This stage must never force a host sync — grape-lint R7
  (`sync-in-pump`) fossilizes that, judging this module's dispatch
  code against the `PUMP_HARVEST_SYNCS` contract below.
* **harvest** (`_harvest_head`): drain completed batches FIFO — the
  head batch's verdicts sync and its per-lane values extract
  (`ServeResult` deferred-values form) while batches behind it are
  still executing, so host-side extraction of batch N-1 overlaps
  device execution of batch N.  FIFO harvest makes result order
  identical to the synchronous loop by construction.
* **ingest barrier** (`ingest`): a delta apply is a barrier item — the
  pump quiesces the window (the superstep-boundary invariant the dyn
  overlay relies on is an explicit drain here, not an accident of the
  sync loop), applies the delta, and refills.

W=1 is pinned byte-identical and result-order-identical to the
synchronous loop (tests/test_serve_async.py runs the full matrix),
and the synchronous path itself is untouched when no pump is attached.
Batches the window cannot hold un-synced — host-only sequential
fallbacks, guarded single queries, dyn force-repacks (a barrier:
the fold rebuilds the fragment under every resident worker) — run
through the session's own synchronous dispatch, and EVERY such
decline is recorded in `PUMP_STATS`, never silent.

docs/SERVING.md ("The async pump") is the user guide; the CLI surface
is `--inflight W`, and bench.py's `serve_async` block A/Bs W in {1,4}
with concurrent ingest.
"""

from __future__ import annotations

import os
import time
from typing import List, Optional

from libgrape_lite_tpu import obs
from libgrape_lite_tpu.serve.queue import QueryRequest, ServeResult

#: env override for the dispatch-window depth: set GRAPE_SERVE_INFLIGHT=1
#: to force the serial discipline on any pump without touching call
#: sites (the override is recorded in PUMP_STATS, never silent).
INFLIGHT_ENV = "GRAPE_SERVE_INFLIGHT"

#: the audited harvest contract (grape-lint R7 `sync-in-pump`): the
#: ONLY methods of this module that may force a host sync
#: (block_until_ready / device_get / np.asarray / int()/float() on a
#: device value).  R7 walks every self-call chain rooted at a
#: dispatch-stage method (`_fill*` / `_dispatch*`) and flags any sync
#: forcer reached outside these names — the defect class this module
#: exists to remove, fossilized so it cannot creep back in.
PUMP_HARVEST_SYNCS = frozenset({
    "_harvest_head",
    "_results_from_dispatch",
    "_run_declined",
    "drain",
    "harvest",
    "quiesce",
})


class PumpStats:
    """Every engage/decline of the dispatch window — the recorded-
    decision discipline the partition/backend ledgers use, applied to
    serving: a batch that could not ride the window (sequential
    fallback, dyn force-repack, guarded single) or a window forced
    narrower than asked (W=1 env) is COUNTED with its reason, so a
    pump that silently degraded to the serial discipline is visible
    in one dict instead of a wall-clock mystery."""

    #: events kept for inspection — bounded so a long-lived serving
    #: process (the module's use case) never grows it without limit
    MAX_EVENTS = 256

    def __init__(self):
        self.engaged = 0
        self.declines = {}
        self.events: List[dict] = []

    def _record(self, ev: dict) -> None:
        self.events.append(ev)
        if len(self.events) > self.MAX_EVENTS:
            del self.events[: self.MAX_EVENTS // 2]

    def engage(self, **detail) -> None:
        self.engaged += 1
        self._record({"kind": "engage", **detail})

    def decline(self, reason: str, **detail) -> None:
        self.declines[reason] = self.declines.get(reason, 0) + 1
        self._record({"kind": "decline", "reason": reason, **detail})

    def snapshot(self) -> dict:
        return {"engaged": self.engaged,
                "declines": dict(self.declines)}

    def reset(self) -> None:
        self.engaged = 0
        self.declines = {}
        self.events = []


#: module-level record shared by every pump in the process (like the
#: pack plan_stats counters): tests/bench read it, reset() between runs
PUMP_STATS = PumpStats()

# federated as "pump" (obs/federation.py): the class keeps its own
# snapshot()/reset() protocol; the federation just routes to it
from libgrape_lite_tpu.obs import federation as _federation  # noqa: E402

_federation.register("pump", PUMP_STATS.snapshot, PUMP_STATS.reset,
                     module=__name__)


class PendingBatch:
    """One admitted batch inside the dispatch window: the popped
    requests plus either ready results (a recorded decline ran the
    synchronous path) or a prepared/launched dispatch the harvest
    stage turns into results.  `prepared` is the host-side half
    (state built + placed, runner resolved); `dispatch` appears once
    the pump launches it — launches are STAGGERED so executions never
    oversubscribe the backend while preparation and extraction
    overlap whatever is executing."""

    __slots__ = ("batch", "mode", "results", "prepared", "dispatch",
                 "reason", "t0_ns", "t_admit_ns", "t_launch_ns",
                 "disp_ns")

    def __init__(self, batch: List[QueryRequest], mode: str,
                 results: Optional[List[ServeResult]] = None,
                 prepared=None, dispatch=None, reason: str = ""):
        self.batch = batch
        self.mode = mode  # "ready" | "deferred"
        self.results = results
        self.prepared = prepared
        self.dispatch = dispatch
        self.reason = reason
        self.t0_ns = 0
        # stage stamps (host perf_counter_ns): window admission time,
        # execution-launch time, and accumulated host dispatch work
        # (prepare + launch enqueue) — the harvest stage turns these
        # into each lane's window_wait/dispatch/device/harvest µs
        self.t_admit_ns = 0
        self.t_launch_ns = 0
        self.disp_ns = 0

    @property
    def launched(self) -> bool:
        return self.mode == "ready" or self.dispatch is not None

    def ready(self) -> bool:
        if self.mode == "ready":
            return True
        if self.dispatch is None:
            return False  # prepared but not yet executing
        return self.dispatch.is_ready()


class AsyncServePump:
    """Overlapped admission/dispatch/harvest over one ServeSession.

    Construction attaches the pump to the session (`session._pump`),
    which makes `session.ingest` barrier-safe no matter which surface
    calls it.  `window` defaults to `session.policy.inflight`;
    GRAPE_SERVE_INFLIGHT overrides either (recorded).  One host
    thread: `pump()` steps, `drain()` finishes, `ingest()` is the
    barrier item.  Results are delivered in dispatch order (FIFO
    harvest), so W=1 reproduces the synchronous loop exactly."""

    def __init__(self, session, window: int | None = None, *,
                 eager_values: bool = True):
        self.session = session
        w = int(window if window is not None
                else getattr(session.policy, "inflight", 1))
        env = os.environ.get(INFLIGHT_ENV, "")
        if env:
            w_env = max(1, int(env))
            if w_env != w:
                PUMP_STATS.decline(
                    "inflight_env", asked=w, forced=w_env
                )
            w = w_env
        if w < 1:
            raise ValueError(f"window must be >= 1, got {w}")
        self.window = w
        # how many batches may be EXECUTING at once.  The window holds
        # W batches admitted + prepared (host work done); the launch
        # cap staggers their enqueue: on the CPU fallback concurrent
        # XLA executions fight for the same cores (measured ~0.9x), so
        # the default serialises execution and takes the win from
        # overlapping prepare/extract with the one running batch; on a
        # real accelerator the device queue serialises programs anyway,
        # so a deeper cap just keeps the queue fed.
        cap_env = os.environ.get("GRAPE_SERVE_LAUNCH_CAP", "")
        if cap_env:
            self.launch_cap = max(1, int(cap_env))
        else:
            import jax

            self.launch_cap = (
                1 if jax.default_backend() == "cpu" else w
            )
        # True (default): the harvest stage resolves every lane's
        # values as it drains the batch; False keeps them deferred so
        # the caller pays extraction on first read (ServeResult.values)
        self.eager_values = eager_values
        self._inflight: List[PendingBatch] = []
        # queries (not batches) dispatched so far: the budget surface
        # a streaming driver pins its ingest points on (`max_dispatch`
        # below), so the batch <-> graph-version interleave is
        # identical at every window depth
        self.dispatched_queries = 0
        self.stats = {
            "dispatched": 0, "harvested": 0, "max_inflight": 0,
            "overlapped_harvests": 0, "quiesces": 0,
        }
        session._pump = self

    # ---- bookkeeping ------------------------------------------------------

    def inflight(self) -> int:
        return len(self._inflight)

    def pending(self) -> int:
        return self.session.queue.pending()

    def close(self) -> None:
        """Detach from the session (drains first — in-flight work is
        never abandoned)."""
        self.quiesce(reason="close")
        if self.session._pump is self:
            self.session._pump = None

    # ---- dispatch stage (R7: no host syncs on these paths) ----------------

    def _fill(self, now: float | None = None, *, force: bool = False,
              max_dispatch: int | None = None) -> int:
        """Dispatch stage: admit ready batches into the window until
        it is full, the queue has nothing ready, or `max_dispatch`
        total dispatched queries is reached (checked before each
        batch, like the sync streaming loop's ingest_every — batches
        stay atomic)."""
        n = 0
        while len(self._inflight) < self.window:
            if (max_dispatch is not None
                    and self.dispatched_queries >= max_dispatch):
                break
            batch = self.session.queue._pop_ready(now, force=force)
            if not batch:
                break
            self._dispatch(batch)
            n += 1
        return n

    def _dispatch(self, batch: List[QueryRequest]) -> None:
        tr = obs.tracer()
        t_admit = time.perf_counter_ns()
        with tr.span(
            "serve_dispatch", app=batch[0].app_key, batch=len(batch),
            window=self.window, inflight=len(self._inflight),
            queue_depth=self.session.queue.pending(),
        ) as sp:
            pb = self._dispatch_stage(batch)
            sp.set(mode=pb.mode, reason=pb.reason)
        pb.t_admit_ns = t_admit
        pb.disp_ns = time.perf_counter_ns() - t_admit
        if tr.enabled:
            pb.t0_ns = sp.t0_ns
        self._inflight.append(pb)
        self.dispatched_queries += len(batch)
        self.stats["dispatched"] += 1
        self.stats["max_inflight"] = max(
            self.stats["max_inflight"], len(self._inflight)
        )
        self._launch_next()
        if tr.enabled:
            m = obs.metrics()
            m.gauge("grape_serve_window_depth").set(len(self._inflight))
            m.series("grape_serve_queue_depth_series").append(
                self.session.queue.pending()
            )

    def _fail_batch(self, pb: PendingBatch, e: Exception) -> None:
        """Whole-batch failure containment, the sync loop's contract
        carried into the window: one bad batch becomes per-lane error
        results and must not kill the pump or strand its neighbours."""
        self.session.stats["failed"] += len(pb.batch)
        pb.mode = "ready"
        pb.dispatch = None
        pb.results = [
            ServeResult(
                request_id=req.id, app_key=req.app_key, ok=False,
                error={"error": f"{type(e).__name__}: {e}"},
                lane=b, batch_size=len(pb.batch),
            )
            for b, req in enumerate(pb.batch)
        ]

    def _launch_next(self) -> None:
        """Enqueue prepared batches until `launch_cap` executions are
        in flight (FIFO — the head launches first).  No host sync:
        launch() of an unguarded batch only enqueues; a guarded
        batch's chunk loop runs here whole (its probes sync inside
        the worker by design — the audited guarded path, not a
        dispatch-stage stray).  A launch that raises fails ITS batch
        only (per-lane error results), like the sync loop's
        whole-batch containment."""
        launched = sum(
            1 for p in self._inflight
            if p.mode == "deferred" and p.dispatch is not None
        )
        for p in self._inflight:
            if launched >= self.launch_cap:
                break
            if p.mode == "deferred" and p.dispatch is None:
                t_l0 = time.perf_counter_ns()
                try:
                    p.dispatch = p.prepared.launch()
                except Exception as e:
                    self._fail_batch(p, e)
                    continue
                t_l1 = time.perf_counter_ns()
                p.disp_ns += t_l1 - t_l0
                p.t_launch_ns = t_l1
                launched += 1

    def _dispatch_stage(self, batch: List[QueryRequest]) -> PendingBatch:
        """Route one popped batch: un-synced through the window when
        the batched runner can hold it, otherwise the session's own
        synchronous dispatch with the decline recorded."""
        sess = self.session
        app_key = batch[0].app_key
        if app_key not in sess.apps:
            return self._run_declined(batch, "unknown_app")
        w = sess.worker(app_key)
        guard = batch[0].guard or sess.guard
        if (
            sess.dyn is not None
            and sess.dyn.overlay_count > 0
            and not getattr(w.app, "dyn_overlay_support", False)
        ):
            # the forced fold rebuilds the fragment under every
            # resident worker — a window barrier, not a window item
            return self._run_declined(batch, "dyn_force_repack")
        try:
            w._check_batchable()
        except ValueError:
            return self._run_declined(batch, "sequential_fallback")

        from libgrape_lite_tpu.guard.config import GuardConfig

        if len(batch) == 1 and GuardConfig.resolve(guard).enabled:
            # the sync loop runs single guarded queries through the
            # plain Worker.query guard machinery (incl. checkpointed
            # rollback) — keep that path, and its breach bundles,
            # bit-for-bit
            return self._run_declined(batch, "guarded_single")
        sess.stats["batches"] += 1
        sess.stats["queries"] += len(batch)
        try:
            prepared = w.query_batch_prepare(
                [req.args for req in batch], batch[0].max_rounds,
                guard=guard,
            )
        except Exception as e:  # whole-batch failure: per-lane errors
            sess.stats["failed"] += len(batch)
            return PendingBatch(batch, "ready", results=[
                ServeResult(
                    request_id=req.id, app_key=req.app_key, ok=False,
                    error={"error": f"{type(e).__name__}: {e}"},
                    lane=b, batch_size=len(batch),
                )
                for b, req in enumerate(batch)
            ], reason="dispatch_error")
        PUMP_STATS.engage(app=app_key, batch=len(batch),
                          guarded=prepared.guarded)
        return PendingBatch(batch, "deferred", prepared=prepared)

    def _run_declined(self, batch: List[QueryRequest],
                      reason: str) -> PendingBatch:
        """Synchronous fallback: the session's own dispatch loop, with
        the decline recorded in PUMP_STATS.  A dyn force-repack
        additionally quiesces the window FIRST — in-flight batches
        must land on the graph view they were admitted against."""
        if reason == "dyn_force_repack":
            self.quiesce(reason=reason)
        PUMP_STATS.decline(reason, app=batch[0].app_key,
                           batch=len(batch))
        return PendingBatch(
            batch, "ready", results=self.session._dispatch(batch),
            reason=reason,
        )

    # ---- harvest stage ----------------------------------------------------

    def _harvest_head(self, *, block: bool = True) -> List[ServeResult]:
        """Harvest stage: turn the window head into delivered results
        (FIFO — result order is the synchronous loop's).  With
        `block=False` an unsettled head is left in flight and []
        returns."""
        if not self._inflight:
            return []
        pb = self._inflight[0]
        if not block and not pb.ready():
            return []
        self._inflight.pop(0)
        tr = obs.tracer()
        overlapped = bool(self._inflight)
        with tr.span(
            "serve_harvest", app=pb.batch[0].app_key,
            batch=len(pb.batch), window=self.window,
            inflight=len(self._inflight), overlapped=overlapped,
            mode=pb.mode,
        ):
            if pb.mode == "ready":
                results = pb.results
            else:
                results = self._results_from_dispatch(pb)
        delivered = self.session.queue.deliver(pb.batch, results)
        self.stats["harvested"] += 1
        if overlapped:
            self.stats["overlapped_harvests"] += 1
        if tr.enabled:
            obs.metrics().gauge("grape_serve_window_depth").set(
                len(self._inflight)
            )
        return delivered

    def _results_from_dispatch(self, pb: PendingBatch) -> List[ServeResult]:
        """One deferred batch -> ServeResults: launch if the stagger
        hasn't yet (a window behind a slow head), sync the lane
        verdicts, hand the freed execution slot to the next prepared
        batch, THEN extract values — so the extraction (the host work
        the window exists to hide) overlaps the successor's
        execution."""
        sess = self.session
        try:
            if pb.dispatch is None:
                t_l0 = time.perf_counter_ns()
                pb.dispatch = pb.prepared.launch()
                t_l1 = time.perf_counter_ns()
                pb.disp_ns += t_l1 - t_l0
                pb.t_launch_ns = t_l1
            d = pb.dispatch.wait()
            t_sync = time.perf_counter_ns()
        except Exception as e:
            # JAX async dispatch surfaces runtime failures at the
            # sync point — the same whole-batch containment the sync
            # loop's _run_batched applies (one bad batch must not
            # kill the pump or strand the rest of the window)
            self._fail_batch(pb, e)
            self._launch_next()
            return pb.results
        # the head's execution has settled: keep the backend busy
        # while we extract below
        self._launch_next()
        batch = pb.batch
        tr = obs.tracer()
        if tr.enabled and not d.supersteps_counted:
            obs.metrics().counter("grape_supersteps_total").inc(
                int(d.rounds.sum()) + len(batch)
            )
        results: List[ServeResult] = []
        for b, req in enumerate(batch):
            if d.breaches[b] is not None:
                sess.stats["failed"] += 1
                results.append(ServeResult(
                    request_id=req.id, app_key=req.app_key, ok=False,
                    error=d.breaches[b], rounds=int(d.rounds[b]),
                    lane=b, batch_size=len(batch),
                ))
            else:
                results.append(ServeResult(
                    request_id=req.id, app_key=req.app_key, ok=True,
                    values_fn=(lambda dd=d, bb=b: dd.lane_values(bb)),
                    rounds=int(d.rounds[b]),
                    terminate_code=int(d.terminate[b]),
                    lane=b, batch_size=len(batch),
                ))
        if self.eager_values:
            for r in results:
                try:
                    r.resolve()
                except Exception as e:  # one lane's extraction failing
                    sess.stats["failed"] += 1  # must not strand the rest
                    r.ok = False
                    r.values = None
                    r.error = {"error": f"{type(e).__name__}: {e}"}
        t_h1 = time.perf_counter_ns()
        # window_wait overlaps the dispatch stage (admit -> launch
        # includes host prepare time) — an attribution aid, not a
        # partition; queue_wait is stamped at delivery by the queue.
        stages = {
            "window_wait_us": max(0, pb.t_launch_ns - pb.t_admit_ns) // 1000,
            "dispatch_us": pb.disp_ns // 1000,
            "device_us": max(0, t_sync - pb.t_launch_ns) // 1000,
            "harvest_us": max(0, t_h1 - t_sync) // 1000,
        }
        for r in results:
            r.stages = dict(stages)
        if tr.enabled:
            now_ns = time.perf_counter_ns()
            for b, (req, res) in enumerate(zip(batch, results)):
                # per-query lane attribution, dispatch -> harvest
                tr.emit_span_raw(
                    "serve_query", t0_ns=pb.t0_ns,
                    dur_ns=max(0, now_ns - pb.t0_ns),
                    tid=tr.lane_tid(b), query_id=req.id,
                    app=req.app_key, lane=b, rounds=res.rounds,
                    ok=res.ok, tenant=req.tenant or "",
                    queue_wait_us=int(
                        max(0.0, req.popped_s - req.submitted_s) * 1e6
                    ),
                )
        return results

    # ---- driving ----------------------------------------------------------

    def pump(self, now: float | None = None, *, force: bool = False,
             block: bool = False,
             max_dispatch: int | None = None) -> List[ServeResult]:
        """One pump step: fill the window (dispatch stage), drain every
        batch that has already settled, and — when the window is full
        with admitted work still waiting, or the caller passed
        `block=True` — harvest the head to make room so a waiting
        batch is never starved by a full window.  `max_dispatch` caps
        the TOTAL dispatched-query count (streaming drivers pin their
        ingest points with it).  Returns the results delivered THIS
        call ([] = nothing was ready)."""
        out: List[ServeResult] = []
        self._fill(now, force=force, max_dispatch=max_dispatch)
        # deadline-expired requests fail at pop time inside the queue;
        # surface them with this step's results (never silently lost)
        out.extend(self.session.queue.take_expired())
        while True:
            got = self._harvest_head(block=False)
            if not got:
                break
            out.extend(got)
            self._fill(now, force=force, max_dispatch=max_dispatch)
        if self._inflight and (
            block
            or (len(self._inflight) >= self.window
                and self.session.queue.pending() > 0)
        ):
            out.extend(self._harvest_head(block=True))
            self._fill(now, force=force, max_dispatch=max_dispatch)
        return out

    def drain(self) -> List[ServeResult]:
        """Dispatch + harvest until the queue AND the window are empty
        (partial batches forced) — the pump analogue of queue.drain."""
        out: List[ServeResult] = []
        while self.session.queue.pending() or self._inflight:
            self._fill(force=True)
            out.extend(self.session.queue.take_expired())
            out.extend(self._harvest_head(block=True))
        out.extend(self.session.queue.take_expired())
        return out

    def quiesce(self, reason: str = "quiesce") -> List[ServeResult]:
        """Drain the window WITHOUT admitting new batches — the
        explicit superstep-boundary barrier `ingest` relies on.
        Delivered results are bound to their requests as usual."""
        if not self._inflight:
            return []
        self.stats["quiesces"] += 1
        PUMP_STATS._record({
            "kind": "quiesce", "reason": reason,
            "inflight": len(self._inflight),
        })
        out: List[ServeResult] = []
        while self._inflight:
            out.extend(self._harvest_head(block=True))
        return out

    def ingest(self, ops, *, force_repack: bool = False) -> dict:
        """The barrier item: quiesce the window, then apply the delta
        through the session (overlay-only ingests stay zero-recompile
        — pinned by tests).  The window refills on the NEXT
        pump()/drain() step, never here: an eager refill would
        dispatch past the caller's ingest cadence and batches admitted
        after this barrier must see the post-delta graph the caller
        scheduled them against (the `max_dispatch` budget pins that
        interleave across window depths)."""
        self.quiesce(reason="ingest")
        return self.session.ingest(ops, force_repack=force_repack)
