"""Guarded batched execution: per-lane monitors, breach isolation.

The unguarded batched fast path is `Worker.query_batch` (one vmapped
fused dispatch).  With guards armed a batch runs here instead: fused
chunks of `guard_cfg.every` supersteps (Worker._make_batched_chunk_
runner — the same freeze-masked vmapped body) with ONE GuardMonitor
per lane probing its slice of the carry at every chunk boundary.
Lanes are independent under vmap — state never crosses the lane axis —
so a poisoned query cannot contaminate batchmates; what breach
isolation adds is the POLICY surface: a lane whose invariants fail is
frozen (its active vote is forced to zero, pinning its carry) and its
result carries the diagnostic bundle, while every other lane keeps
running to convergence and returns byte-identical results.  This is
the serving-runtime form of the halt policy — one bad query must not
halt the dispatch it shares.

Rollback policy degrades to per-lane halt here: batched queries have
no per-lane checkpoint lineage (the monitor logs the downgrade, as the
unchunked guarded path did before PR 6 grew snapshots).

Under the async pump (serve/pipeline.py) a guarded batch still runs
this chunk loop at dispatch time — breach isolation needs the probe
verdicts, which sync at every chunk boundary by design — but the
verdict arrays are snapshot into a `BatchDispatch` handle and the
per-lane VALUES harvest lazily with everyone else's, so a guarded
batch mid-window never blocks on value extraction and batches behind
it in the window keep executing while the chunk loop probes.  Breach
semantics are pinned unchanged either way (tests/test_serve_async.py
poisons a lane with W>1 batches in flight).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from libgrape_lite_tpu import obs

_INT32_MAX = np.iinfo(np.int32).max


def lane_slices(carry: dict, lane: int) -> dict:
    """Lane `lane`'s [fnum, ...] view of a batched carry (lazy device
    slices — the per-lane probe jits over them directly)."""
    return {k: v[lane] for k, v in carry.items()}


def run_guarded_batch(worker, args_list, mr: int, guard_cfg, *,
                      chunk_hook=None):
    """Execute a k-lane batch under per-lane guard monitors.

    Returns the batched result state (like Worker.query_batch) and
    leaves per-lane verdicts on the worker: `batch_rounds`,
    `batch_terminate`, and `batch_breaches` (one diagnostic bundle or
    None per lane — serve/session.py turns bundles into failed
    ServeResults).

    `chunk_hook(carry, rounds)` is a test seam: called after every
    chunk with the batched device carry, it may return replacement
    numpy leaves (e.g. poisoning one lane) that are re-placed before
    the probes — the breach-isolation drill in tests/test_serve.py
    injects through it."""
    from libgrape_lite_tpu.guard.monitor import GuardMonitor

    app = worker.app
    frag = worker.fragment
    batch = len(args_list)
    if mr <= 0:
        mr = _INT32_MAX
    if guard_cfg.policy == "rollback":
        from libgrape_lite_tpu.utils import logging as glog

        glog.log_info(
            "guard: batched dispatches have no per-lane checkpoint "
            "lineage — rollback degrades to per-lane halt (breach "
            "isolation)"
        )

    state = worker._place_state_batch(
        app.init_state_batch(frag, args_list)
    )
    eph = frozenset(getattr(app, "ephemeral_keys", ()) or ())
    eph_part = {k: v for k, v in state.items() if k in eph}

    def carry_of(st):
        return {k: v for k, v in st.items() if k not in eph}

    monitors = [
        GuardMonitor(app=app, frag=frag, config=guard_cfg,
                     ledger=worker.pack_ledger())
        for _ in range(batch)
    ]
    worker._guard_monitor = monitors[0] if monitors else None
    breaches = [None] * batch
    failed = np.zeros(batch, dtype=bool)

    def probe_lane(b, prev_b, cur, rounds_b, active_b, digest=None,
                   residual=None):
        """One lane's chunk-boundary probe; a non-warn breach freezes
        the lane instead of raising — batchmates keep running."""
        if active_b < 0:  # cooperative abort is the app's own verdict
            return
        breach = monitors[b].check(
            prev_b, lane_slices(cur, b), rounds_b, active_b,
            digest=digest, residual=residual,
        )
        if breach is not None:
            failed[b] = True
            breaches[b] = breach.bundle
            obs.tracer().instant(
                "serve_lane_breach", lane=b, round=rounds_b,
                kind=breach.verdict["kind"], policy=guard_cfg.policy,
            )

    tr = obs.tracer()
    try:
        with tr.span("query", mode="guarded-batched",
                     app=type(app).__name__, batch=batch) as qsp:
            peval_fn = worker._batched_step_for("peval", state, batch)
            prev = [
                lane_slices(carry_of(state), b) for b in range(batch)
            ]
            with tr.span("peval", batch=batch) as sp:
                out = peval_fn(frag.dev, state)
                sp.mark("dispatched")
                carry, active = jax.block_until_ready(out)
            active = np.asarray(active).copy()
            if tr.enabled:
                obs.metrics().counter(
                    "grape_supersteps_total"
                ).inc(batch)
            rounds_v = np.zeros(batch, dtype=np.int32)
            for b in range(batch):
                probe_lane(b, prev[b], carry, 0, int(active[b]))
                prev[b] = lane_slices(carry, b)
            act_eff = np.where(failed, 0, active).astype(np.int32)
            chunk_fn = worker._batched_chunk_runner_for(
                guard_cfg.every, mr, batch, state
            )
            r_global = 0
            while (act_eff > 0).any() and r_global < mr:
                live_in = act_eff > 0
                with tr.span("chunk", start_round=r_global,
                             lanes=int(live_in.sum())) as sp:
                    out = chunk_fn(
                        frag.dev, carry, eph_part,
                        jnp.asarray(act_eff), jnp.asarray(rounds_v),
                        jnp.int32(r_global),
                    )
                    sp.mark("dispatched")
                    carry, rv, act, r2, dig, res = (
                        jax.block_until_ready(out)
                    )
                    sp.set(end_round=int(r2))
                rounds_v = np.asarray(rv).copy()
                active = np.asarray(act).copy()
                dig = np.asarray(dig)
                res = np.asarray(res)
                if tr.enabled:
                    m = obs.metrics()
                    m.counter("grape_supersteps_total").inc(
                        int(r2) - r_global
                    )
                r_global = int(r2)
                if chunk_hook is not None:
                    corrupted = chunk_hook(carry, r_global)
                    if corrupted is not None:
                        carry = {
                            **carry,
                            **worker._place_state_batch(corrupted),
                        }
                        dig = res = None  # stale: re-probe fully
                for b in range(batch):
                    if not live_in[b] or failed[b]:
                        continue
                    digest = (
                        None if dig is None
                        else tuple(int(x) for x in dig[b])
                    )
                    residual = None
                    if res is not None and float(res[b]) >= 0:
                        residual = float(res[b])
                    probe_lane(
                        b, prev[b], carry, int(rounds_v[b]),
                        int(active[b]), digest=digest,
                        residual=residual,
                    )
                    prev[b] = lane_slices(carry, b)
                act_eff = np.where(failed, 0, active).astype(np.int32)
            worker.batch_rounds = rounds_v
            worker.batch_terminate = np.minimum(0, active)
            worker.batch_breaches = list(breaches)
            worker.rounds = int(rounds_v.max()) if batch else 0
            worker._terminate_code = (
                int(worker.batch_terminate.min()) if batch else 0
            )
            if tr.enabled:
                qsp.set(
                    lane_rounds=[int(x) for x in rounds_v],
                    failed_lanes=[
                        b for b in range(batch) if failed[b]
                    ],
                )
            worker._finish_query_obs(qsp)
    finally:
        if tr.enabled:
            obs.flush()
    worker._result_state = {**carry, **eph_part}
    # same provenance record as the unguarded paths: a serve repack
    # rebinds worker.fragment, and query_incremental's prev_fragment
    # default must name the fragment THIS result's rows live in
    worker._result_fragment = frag
    return worker._result_state
