"""Admission queue: accept queries, coalesce compatible ones, dispatch.

Execution model: the queue is a host-side FIFO pumped by the caller
(a scripted stream, the CLI `serve` subcommand, or bench.py's
throughput lane) — no background thread, so results are deterministic
and testable.  `submit` enqueues, `pump` ships at most one batch when
the policy says it is ready (full, or the head has waited
`max_wait_s`), `drain` pumps until empty.  FIFO order is preserved per
compatibility class; a batch is the head request plus the next
compatible requests in arrival order (requests BETWEEN them stay
queued — admission never reorders within a class, and an incompatible
head never blocks forever because `drain`/timeout forces partial
batches).
"""

from __future__ import annotations

import itertools
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import numpy as np

from libgrape_lite_tpu.serve.policy import BatchPolicy

_IDS = itertools.count()


@dataclass
class QueryRequest:
    """One admitted query (serve/): app + args + the limits that gate
    coalescing (policy.compat_key)."""

    app_key: str
    args: dict
    max_rounds: Optional[int] = None
    guard: Optional[str] = None
    id: int = field(default_factory=lambda: next(_IDS))
    submitted_s: float = field(default_factory=time.perf_counter)
    result: Optional["ServeResult"] = None

    @property
    def done(self) -> bool:
        return self.result is not None


@dataclass
class ServeResult:
    """Per-query outcome: either assembled values or a structured
    error (a guard breach bundle for poisoned lanes — batchmates of a
    breached query complete normally, serve/batch.py isolates lanes)."""

    request_id: int
    app_key: str
    ok: bool
    values: Optional[np.ndarray] = None  # [fnum, vp] assembled
    rounds: int = 0
    terminate_code: int = 0
    error: Optional[dict] = None  # breach bundle / failure detail
    lane: int = 0  # position inside the dispatched batch
    batch_size: int = 1
    latency_s: float = 0.0  # submit -> result delivery


class AdmissionQueue:
    """FIFO + coalescing front of a ServeSession.

    `dispatch(batch)` is the session's batched executor: it must
    return one ServeResult per request, in batch order.  The queue
    records a batch-size histogram — the serving bench's saturation
    signal (all-1 bars mean the stream never coalesced)."""

    def __init__(self, dispatch: Callable[[List[QueryRequest]],
                                          List[ServeResult]],
                 policy: BatchPolicy | None = None,
                 compat_key: Callable[[QueryRequest], tuple] | None = None):
        self._dispatch = dispatch
        self.policy = policy or BatchPolicy()
        self._compat = compat_key or (
            lambda r: (r.app_key, r.max_rounds, r.guard or "")
        )
        self._pending: List[QueryRequest] = []
        self.batch_hist: Dict[int, int] = {}
        self.completed = 0

    def submit(self, app_key: str, args: dict | None = None, *,
               max_rounds: int | None = None,
               guard: str | None = None) -> QueryRequest:
        req = QueryRequest(
            app_key=app_key, args=dict(args or {}),
            max_rounds=max_rounds, guard=guard,
        )
        self._pending.append(req)
        return req

    def pending(self) -> int:
        return len(self._pending)

    def _head_batch(self) -> List[QueryRequest]:
        """The head request plus the next compatible requests in FIFO
        order, up to max_batch lanes."""
        head = self._pending[0]
        key = self._compat(head)
        batch = [head]
        for req in self._pending[1:]:
            if len(batch) >= self.policy.max_batch:
                break
            if self._compat(req) == key:
                batch.append(req)
        return batch

    def pump(self, now: float | None = None, *,
             force: bool = False) -> List[ServeResult]:
        """Dispatch at most ONE batch: when it is full, when the head
        request has waited `max_wait_s`, or when `force`d (drain).
        Returns the delivered results ([] = nothing was ready)."""
        if not self._pending:
            return []
        batch = self._head_batch()
        if not force and len(batch) < self.policy.max_batch:
            now = time.perf_counter() if now is None else now
            head_wait = now - self._pending[0].submitted_s
            if head_wait < self.policy.max_wait_s:
                return []
        ids = {r.id for r in batch}
        self._pending = [r for r in self._pending if r.id not in ids]
        results = self._dispatch(batch)
        if len(results) != len(batch):
            raise RuntimeError(
                f"dispatch returned {len(results)} results for a "
                f"{len(batch)}-lane batch"
            )
        t_done = time.perf_counter()
        for req, res in zip(batch, results):
            res.latency_s = t_done - req.submitted_s
            req.result = res
        self.batch_hist[len(batch)] = (
            self.batch_hist.get(len(batch), 0) + 1
        )
        self.completed += len(batch)
        return results

    def drain(self) -> List[ServeResult]:
        """Pump until the queue is empty (partial batches forced) —
        the scripted-stream mode of the CLI `serve` subcommand."""
        out: List[ServeResult] = []
        while self._pending:
            out.extend(self.pump(force=True))
        return out
