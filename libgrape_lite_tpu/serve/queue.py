"""Admission queue: accept queries, coalesce compatible ones, dispatch.

Execution model: the queue is a host-side FIFO pumped by the caller
(a scripted stream, the CLI `serve` subcommand, or bench.py's
throughput lane) — no background thread, so results are deterministic
and testable.  `submit` enqueues, `pump` ships at most one batch when
the policy says it is ready (full, or the head has waited
`max_wait_s`), `drain` pumps until empty.  FIFO order is preserved per
compatibility class; a batch is the head request plus the next
compatible requests in arrival order (requests BETWEEN them stay
queued — admission never reorders within a class, and an incompatible
head never blocks forever because `drain`/timeout forces partial
batches).

Scheduling (r13): requests carry an optional `priority` class — the
queue always serves the highest class present, FIFO within a class,
and classes never coalesce — and an optional `deadline_s`; a request
whose deadline passes before it dispatches FAILS as a ServeResult
with the recorded reason (`take_expired` returns them through every
pump/drain surface), never a silent drop.  `submit` is thread-safe
against `_pop_ready` (one lock) so the threaded admission front
(serve/feeder.py) can produce while the pump consumes.

The pop/dispatch/deliver split (`_pop_ready` / the dispatch callback /
`deliver`) exists for the async pump (serve/pipeline.py): the pump
pops ready batches with the SAME policy decision this module's own
`pump` uses, keeps up to W of them dispatched-but-unharvested, and
delivers through the same bookkeeping — so batch composition, FIFO
order, the batch-size histogram, and the admission-wait record are
one implementation regardless of how many batches are in flight.
"""

from __future__ import annotations

import itertools
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import numpy as np

from libgrape_lite_tpu.serve.policy import BatchPolicy

_IDS = itertools.count()


def latency_summary_ms(latencies) -> dict:
    """{n, p50_ms, p99_ms} of a latency list (seconds in, ms out) —
    THE one percentile convention (sorted ascending, index
    `min(n-1, int(n*p))`) shared by the admission-wait record, the
    CLI global and per-app summaries, and the fleet per-replica /
    per-tenant summaries.  Five hand-rolled copies of this index
    arithmetic would drift; one helper cannot."""
    if not latencies:
        return {"n": 0, "p50_ms": 0.0, "p99_ms": 0.0}
    lat = sorted(latencies)
    return {
        "n": len(lat),
        "p50_ms": round(1e3 * lat[len(lat) // 2], 3),
        "p99_ms": round(
            1e3 * lat[min(len(lat) - 1, int(len(lat) * 0.99))], 3
        ),
    }


@dataclass
class QueryRequest:
    """One admitted query (serve/): app + args + the limits that gate
    coalescing (policy.compat_key).

    `priority` picks the scheduling class: the queue always serves the
    highest class present, FIFO within a class, and requests of
    different classes never coalesce.  `deadline_s` (seconds from
    submission) expires a request that has not DISPATCHED in time —
    it fails as a ServeResult with the recorded reason, never a
    silent drop.  `tenant` (fleet/) tags the owning tenant; requests
    of different tenants never share a batched dispatch, so one
    tenant's poisoned lane can never fail a batchmate tenant."""

    app_key: str
    args: dict
    max_rounds: Optional[int] = None
    guard: Optional[str] = None
    priority: int = 0
    deadline_s: Optional[float] = None
    tenant: Optional[str] = None
    id: int = field(default_factory=lambda: next(_IDS))
    submitted_s: float = field(default_factory=time.perf_counter)
    # stamped by _pop_ready when the request leaves the queue: the
    # submit->pop interval is the per-request queue_wait_us stage
    popped_s: float = 0.0
    result: Optional["ServeResult"] = None

    @property
    def done(self) -> bool:
        return self.result is not None


class ServeResult:
    """Per-query outcome: either assembled values or a structured
    error (a guard breach bundle for poisoned lanes — batchmates of a
    breached query complete normally, serve/batch.py isolates lanes).

    `values` has a DEFERRED form for the async pump
    (serve/pipeline.py): constructed with `values_fn` instead of
    `values`, the [fnum, vp] assembly (device sync + finalize) runs
    the first time `values` is read — or when the harvest stage drains
    the batch, whichever comes first — so host-side extraction of
    batch N-1 overlaps device execution of batch N.  Synchronous
    construction with `values=` is unchanged, and a resolved result is
    indistinguishable from an eager one."""

    __slots__ = ("request_id", "app_key", "ok", "rounds",
                 "terminate_code", "error", "lane", "batch_size",
                 "latency_s", "stages", "_values", "_values_fn")

    def __init__(self, request_id: int, app_key: str, ok: bool,
                 values: Optional[np.ndarray] = None, rounds: int = 0,
                 terminate_code: int = 0, error: Optional[dict] = None,
                 lane: int = 0, batch_size: int = 1,
                 latency_s: float = 0.0,
                 values_fn: Optional[Callable[[], np.ndarray]] = None,
                 stages: Optional[dict] = None):
        self.request_id = request_id
        self.app_key = app_key
        self.ok = ok
        self.rounds = rounds
        self.terminate_code = terminate_code
        self.error = error  # breach bundle / failure detail
        self.lane = lane  # position inside the dispatched batch
        self.batch_size = batch_size
        self.latency_s = latency_s  # submit -> result delivery
        # stage decomposition of the latency (µs ints): queue_wait_us
        # (submit->pop, per request) + window_wait_us / dispatch_us /
        # device_us / harvest_us (batch-level, same for every lane of
        # one dispatch).  deliver() fills queue_wait_us; the dispatch
        # paths fill the rest — a failed request may carry a partial
        # dict, never a missing one after delivery.
        self.stages = stages
        self._values = values  # [fnum, vp] assembled
        self._values_fn = values_fn

    @property
    def values(self) -> Optional[np.ndarray]:
        if self._values is None and self._values_fn is not None:
            fn, self._values_fn = self._values_fn, None
            self._values = fn()
        return self._values

    @values.setter
    def values(self, v) -> None:
        self._values = v
        self._values_fn = None

    @property
    def deferred(self) -> bool:
        """True while the values are still an un-synced thunk."""
        return self._values_fn is not None

    def resolve(self) -> "ServeResult":
        """Force the deferred values now (the harvest stage's drain)."""
        self.values
        return self


class AdmissionQueue:
    """FIFO + coalescing front of a ServeSession.

    `dispatch(batch)` is the session's batched executor: it must
    return one ServeResult per request, in batch order.  The queue
    records a batch-size histogram — the serving bench's saturation
    signal (all-1 bars mean the stream never coalesced)."""

    def __init__(self, dispatch: Callable[[List[QueryRequest]],
                                          List[ServeResult]],
                 policy: BatchPolicy | None = None,
                 compat_key: Callable[[QueryRequest], tuple] | None = None):
        self._dispatch = dispatch
        self.policy = policy or BatchPolicy()
        self._compat = compat_key or (
            lambda r: (r.app_key, r.max_rounds, r.guard or "", r.tenant)
        )
        self._pending: List[QueryRequest] = []
        # guards _pending (and the expired stash) against the threaded
        # admission front (serve/feeder.py): submit may run on a feeder
        # thread while the pump thread pops — everything else stays
        # single-threaded and the scripted mode pays one uncontended
        # acquire per call
        self._lock = threading.Lock()
        self.batch_hist: Dict[int, int] = {}
        self.completed = 0
        # deadline-expired and shed requests failed (never silently
        # dropped): counted here, reason on each result, results
        # returned by the next pump/drain via take_expired()
        self.expired = 0
        self.shed = 0
        self._expired_out: List[ServeResult] = []
        # optional admission-control hook (autopilot/admission.py):
        # callable(req) -> "admit" | "defer" | "shed", consulted by
        # the _pop_ready sweep BEFORE coalescing — shed requests fail
        # loudly (reason=shed_over_budget), deferred tenants queue
        # behind in-budget ones
        self.admission = None
        # optional result cache (autopilot/cache.py): deliver() stores
        # every OK result under its full identity; cache_meta(req)
        # returns (compat, source) for cacheable requests (None
        # otherwise) and cache_epoch() the current fence epoch — both
        # wired by ServeSession.attach_result_cache
        self.result_cache = None
        self.cache_meta = None
        self.cache_epoch = None
        # per-request submit->dispatch wait (seconds), recorded at pop
        # time next to the batch-size histogram: the admission-latency
        # half of the serving story (the histogram says how well the
        # stream coalesced; this says what the coalescing COST each
        # request at the head of the queue)
        self.admission_waits: List[float] = []

    def submit(self, app_key: str, args: dict | None = None, *,
               max_rounds: int | None = None,
               guard: str | None = None, priority: int = 0,
               deadline_s: float | None = None,
               tenant: str | None = None) -> QueryRequest:
        req = QueryRequest(
            app_key=app_key, args=dict(args or {}),
            max_rounds=max_rounds, guard=guard,
            priority=int(priority), deadline_s=deadline_s,
            tenant=tenant,
        )
        with self._lock:
            self._pending.append(req)
        return req

    def pending(self) -> int:
        return len(self._pending)

    def _expire_overdue(self, now: float) -> None:
        """Fail (not drop) every pending request whose deadline passed
        before it dispatched: the request gets an error ServeResult
        with the recorded reason and rides out through take_expired().
        Caller holds the lock."""
        live: List[QueryRequest] = []
        swept: List[int] = []
        for req in self._pending:
            if (req.deadline_s is not None
                    and now - req.submitted_s > req.deadline_s):
                waited = now - req.submitted_s
                res = ServeResult(
                    request_id=req.id, app_key=req.app_key, ok=False,
                    error={
                        "error": "deadline expired before dispatch",
                        "reason": "deadline_expired",
                        "deadline_s": req.deadline_s,
                        "waited_s": round(waited, 6),
                    },
                    latency_s=waited,
                    stages={"queue_wait_us": int(waited * 1e6)},
                )
                req.result = res
                self._expired_out.append(res)
                self.expired += 1
                self.completed += 1
                swept.append(req.id)
                # a query that never dispatched still BURNS its
                # tenant's error budget — without this, the tenant
                # that caused a deadline storm never paid for it
                # (slo.observe never raises and takes no queue locks;
                # safe under the queue lock like the recorder below)
                from libgrape_lite_tpu.obs import slo

                slo.observe(req.app_key, req.tenant, waited, ok=False)
            else:
                live.append(req)
        self._pending = live
        if swept:
            from libgrape_lite_tpu.obs.recorder import (
                DEADLINE_STORM_THRESHOLD,
                RECORDER,
            )

            RECORDER.record("deadline_expired", n=len(swept),
                            ids=swept[:16])
            if len(swept) >= DEADLINE_STORM_THRESHOLD:
                # a deadline STORM — one sweep failing a window's
                # worth of requests — is a postmortem trigger, not
                # just a counter (recorder never raises; safe under
                # the queue lock, it takes no queue locks itself)
                RECORDER.trigger("deadline_storm", extra={
                    "expired_in_sweep": len(swept),
                    "request_ids": swept[:64],
                    "pending": len(self._pending),
                })

    def _review_admission(self) -> set:
        """Run the attached admission hook over the pending list:
        shed requests fail loudly (the deadline-expiry discipline —
        counted, reasoned, SLO-observed, returned via take_expired),
        deferred requests stay queued but their tenants are returned
        so _head_batch serves in-budget tenants first.  Caller holds
        the lock."""
        deferred: set = set()
        if self.admission is None:
            return deferred
        live: List[QueryRequest] = []
        shed_n = 0
        for req in self._pending:
            try:
                verdict = self.admission(req)
            except Exception:
                verdict = "admit"  # a broken hook must not wedge admission
            if verdict == "shed":
                waited = time.perf_counter() - req.submitted_s
                res = ServeResult(
                    request_id=req.id, app_key=req.app_key, ok=False,
                    error={
                        "error": "shed: tenant over error budget",
                        "reason": "shed_over_budget",
                        "tenant": req.tenant or "",
                        "waited_s": round(waited, 6),
                    },
                    latency_s=waited,
                    stages={"queue_wait_us": int(waited * 1e6)},
                )
                req.result = res
                self._expired_out.append(res)
                self.shed += 1
                self.completed += 1
                shed_n += 1
                # shedding burns the shed tenant's budget too — the
                # same accounting rule as deadline expiry above
                from libgrape_lite_tpu.obs import slo

                slo.observe(req.app_key, req.tenant, waited, ok=False)
            else:
                if verdict == "defer":
                    deferred.add(req.tenant)
                live.append(req)
        self._pending = live
        if shed_n:
            from libgrape_lite_tpu.obs.recorder import RECORDER

            RECORDER.record("shed_over_budget", n=shed_n)
        return deferred

    def take_expired(self) -> List[ServeResult]:
        """Drain the out-of-band results — deadline-expired and shed
        failures, plus cache-hit results that never dispatched
        (pump/drain and the async pump call this so such a request is
        always RETURNED to the driver, never silently dropped)."""
        with self._lock:
            out, self._expired_out = self._expired_out, []
        return out

    def push_oob(self, res: ServeResult) -> None:
        """Append one out-of-band result (a cache hit served without
        dispatching — serve/session.py) to the take_expired channel,
        so every pump/drain surface returns it like any other."""
        with self._lock:
            self._expired_out.append(res)
            self.completed += 1

    def _head_batch(self, deferred: set = frozenset()
                    ) -> List[QueryRequest]:
        """The head request plus the next compatible requests in FIFO
        order, up to max_batch lanes.  The head is the FIRST request
        of the HIGHEST priority class present (FIFO within a class);
        only same-class requests may join its batch, so a low-priority
        straggler never rides an urgent dispatch.  Tenants in
        `deferred` (admission control: past error budget) queue
        BEHIND everyone else: they only head a batch when nothing
        in-budget is pending, so deferral never becomes starvation."""
        cands = [r for r in self._pending if r.tenant not in deferred]
        if not cands:
            cands = self._pending
        top = max(r.priority for r in cands)
        head = next(r for r in cands if r.priority == top)
        key = self._compat(head)
        batch = [head]
        seen_head = False
        for req in self._pending:
            if req is head:
                seen_head = True
                continue
            if not seen_head:
                continue
            if len(batch) >= self.policy.max_batch:
                break
            if req.priority == top and self._compat(req) == key:
                batch.append(req)
        return batch

    def _pop_ready(self, now: float | None = None, *,
                   force: bool = False) -> List[QueryRequest]:
        """Pop at most ONE ready batch off the queue — the policy
        decision shared by the synchronous `pump` and the async pump's
        dispatch stage (serve/pipeline.py).  Ready = full, head waited
        `max_wait_s`, or `force`d.  Expires overdue deadlines and runs
        the admission hook first (failed results, via take_expired).
        Records each popped request's submit->dispatch wait.
        [] = nothing ready."""
        now = time.perf_counter() if now is None else now
        with self._lock:
            self._expire_overdue(now)
            deferred = self._review_admission()
            if not self._pending:
                return []
            batch = self._head_batch(deferred)
            if not force and len(batch) < self.policy.max_batch:
                head_wait = now - batch[0].submitted_s
                if head_wait < self.policy.max_wait_s:
                    return []
            ids = {r.id for r in batch}
            self._pending = [
                r for r in self._pending if r.id not in ids
            ]
        t_pop = time.perf_counter()
        from libgrape_lite_tpu import obs

        hist = obs.metrics().histogram(
            "grape_serve_admission_wait_seconds",
            help="per-request submit->dispatch wait in the "
                 "admission queue",
        )
        for req in batch:
            req.popped_s = t_pop
            wait = t_pop - req.submitted_s
            self.admission_waits.append(wait)
            hist.observe(wait)
        return batch

    def deliver(self, batch: List[QueryRequest],
                results: List[ServeResult]) -> List[ServeResult]:
        """Bind one dispatched batch's results to its requests
        (latency stamping, histogram/completion bookkeeping) — shared
        by the synchronous `pump` and the async pump's harvest stage,
        so the two loops account identically."""
        if len(results) != len(batch):
            raise RuntimeError(
                f"dispatch returned {len(results)} results for a "
                f"{len(batch)}-lane batch"
            )
        t_done = time.perf_counter()
        from libgrape_lite_tpu.obs import slo

        for req, res in zip(batch, results):
            res.latency_s = t_done - req.submitted_s
            st = res.stages
            if st is None:
                st = res.stages = {}
            if "queue_wait_us" not in st and req.popped_s:
                st["queue_wait_us"] = int(
                    (req.popped_s - req.submitted_s) * 1e6
                )
            req.result = res
            # the ONE bookkeeping site shared by the sync loop, the
            # async pump, and every fleet replica — so SLO accounting
            # cannot drift between serving modes (no-op when no
            # objectives are configured; never raises)
            slo.observe(req.app_key, req.tenant, res.latency_s,
                        res.ok)
            # result-cache store (autopilot/cache.py), same shared
            # site: sync loop, async pump, and fleet replicas all
            # deliver here, so every cacheable OK result is stored
            # regardless of serving mode.  The key carries the FULL
            # compat identity + source + fence epoch (grape-lint R9).
            if self.result_cache is not None and res.ok:
                meta = self.cache_meta(req) if self.cache_meta else None
                if meta is not None:
                    compat, source = meta
                    fence = self.cache_epoch() if self.cache_epoch else 0
                    self.result_cache.store(compat, source, fence, res)
        self.batch_hist[len(batch)] = (
            self.batch_hist.get(len(batch), 0) + 1
        )
        self.completed += len(batch)
        return results

    def admission_wait_summary(self) -> dict:
        """p50/p99 of the recorded submit->dispatch waits, in ms (the
        CLI `serve` summary and the bench serve_async block surface
        this next to qps)."""
        return latency_summary_ms(self.admission_waits)

    def pump(self, now: float | None = None, *,
             force: bool = False) -> List[ServeResult]:
        """Dispatch at most ONE batch: when it is full, when the head
        request has waited `max_wait_s`, or when `force`d (drain).
        Returns the delivered results, including any deadline-expired
        failures ([] = nothing was ready)."""
        batch = self._pop_ready(now, force=force)
        out = self.take_expired()
        if not batch:
            return out
        out.extend(self.deliver(batch, self._dispatch(batch)))
        return out

    def drain(self) -> List[ServeResult]:
        """Pump until the queue is empty (partial batches forced) —
        the scripted-stream mode of the CLI `serve` subcommand."""
        out: List[ServeResult] = self.take_expired()
        while self._pending:
            out.extend(self.pump(force=True))
        return out
