"""serve/ — the multi-query serving runtime (ROADMAP item 1).

A `ServeSession` pins one loaded graph — HBM-resident CSR shards, pack
plans, compiled fused runners — and serves many queries against it
with zero re-planning and zero recompilation after the first hit of
each (app, state-shape, max_rounds).  An `AdmissionQueue` coalesces
compatible point queries into vmapped multi-source batches
(`Worker.query_batch`: k SSSP/BFS sources per dispatch, per-lane
active masks, byte-identical per-lane results) under a `BatchPolicy`
(max batch / max wait), with per-query obs spans and — when guards are
armed — per-lane invariant monitors whose breaches freeze ONE lane
instead of halting the batch (serve/batch.py).

The async pump (serve/pipeline.py) overlaps the three stages the
host-pumped loop serialises: up to `BatchPolicy.inflight` coalesced
batches dispatched-but-unharvested at once (JAX async dispatch), lazy
FIFO harvest with deferred per-lane values, and `ingest` as an
explicit window barrier — W=1 pinned byte- and result-order-identical
to the synchronous loop.

docs/SERVING.md is the user guide; the CLI surface is
`python -m libgrape_lite_tpu.cli serve ...` (`--inflight W` arms the
pump), and bench.py's `serve` / `serve_async` blocks report
queries/sec at fixed p99 next to MTEPS.
"""

from libgrape_lite_tpu.serve.batch import run_guarded_batch
from libgrape_lite_tpu.serve.feeder import ArrivalFeeder
from libgrape_lite_tpu.serve.pipeline import (
    PUMP_STATS,
    AsyncServePump,
)
from libgrape_lite_tpu.serve.policy import BatchPolicy, compat_key
from libgrape_lite_tpu.serve.queue import (
    AdmissionQueue,
    QueryRequest,
    ServeResult,
)
from libgrape_lite_tpu.serve.session import ServeSession

__all__ = [
    "AdmissionQueue",
    "ArrivalFeeder",
    "AsyncServePump",
    "BatchPolicy",
    "PUMP_STATS",
    "QueryRequest",
    "ServeResult",
    "ServeSession",
    "compat_key",
    "run_guarded_batch",
]
