from libgrape_lite_tpu.fragment.edgecut import (
    DeviceCSR,
    DeviceFragment,
    ShardedEdgecutFragment,
)
from libgrape_lite_tpu.fragment.loader import LoadGraph, LoadGraphSpec
