"""Post-load rebalancer.

Re-design of `grape/fragment/rebalancer.h:27-130`: re-partition the
(vfile-ordered) vertex universe into fnum contiguous blocks of equal
weight, where weight(v) = vertex_factor + degree(v) — so heavy-degree
vertices pull block boundaries tighter.  The reference updates the
vertex map's gid assignment (`VertexMap::UpdateToBalance`); here the
rebalanced partitioner feeds VertexMap.build before shard construction.
"""

from __future__ import annotations

import numpy as np


class Rebalancer:
    def __init__(self, vertex_factor: int = 0):
        self.vertex_factor = vertex_factor

    def partition(self, oids: np.ndarray, src_oid: np.ndarray,
                  dst_oid: np.ndarray, fnum: int):
        """Returns an explicit oid->fid partitioner with degree-balanced
        contiguous blocks over the given oid order (fully vectorised —
        this path exists precisely for huge graphs)."""
        from libgrape_lite_tpu.vertex_map.partitioner import (
            ExplicitPartitioner,
        )

        oids = np.asarray(oids)
        order = np.argsort(oids, kind="stable")
        sorted_oids = oids[order]
        deg = np.zeros(len(oids), dtype=np.int64)
        for arr in (src_oid, dst_oid):
            q = np.asarray(arr)
            pos = np.searchsorted(sorted_oids, q)
            pos_c = np.clip(pos, 0, max(len(sorted_oids) - 1, 0))
            ok = sorted_oids[pos_c] == q
            np.add.at(deg, order[pos_c[ok]], 1)

        weight = deg + self.vertex_factor
        cum = np.cumsum(weight)
        total = int(cum[-1]) if len(cum) else 0
        # block boundaries at equal weight quantiles
        targets = (np.arange(1, fnum) * total) // fnum
        cuts = np.searchsorted(cum, targets, side="left")
        fids = np.zeros(len(oids), dtype=np.int64)
        start = 0
        for f, c in enumerate(np.append(cuts, len(oids))):
            fids[start:c] = f
            start = c
        part = ExplicitPartitioner(oids, fids)
        part.fnum = fnum
        return part
