"""Edge-cut fragments, sharded over the TPU mesh.

Re-design of the reference fragment stack:
  * `grape/fragment/fragment_base.h:50-133` (counts / fid / directed),
  * `grape/fragment/edgecut_fragment_base.h:44-632` (inner/outer vertex
    ranges, id conversions),
  * `grape/fragment/immutable_edgecut_fragment.h:113-917` (CSR storage),
  * `grape/cuda/fragment/host_fragment.h:66-713` + `device_fragment.h`
    (the accelerator mirror).

TPU-first layout decisions (deliberately NOT a translation):

* One Python object (`ShardedEdgecutFragment`) describes *all* fragments
  — single-controller JAX replaces the one-process-per-fragment SPMD of
  the reference.  Device arrays are stacked `[fnum, ...]` and sharded
  over the `frag` mesh axis; inside `shard_map` each device sees its own
  fragment block, which plays the role of the reference's
  `DeviceFragment` POD view (`device_fragment.h:432-449`).

* Per-fragment vertex capacity `Vp` is padded to a power of two, so the
  padded global id `pid = fid * Vp + lid` coincides bit-for-bit with the
  reference's `IdParser` gid (`grape/fragment/id_parser.h:28-41`,
  gid = fid << lid_bits | lid).  All device-side addressing uses pids;
  oids exist only on the host boundary.

* There is no outer-vertex mirror table on the device: state exchange is
  collective (`all_gather`/`ppermute`) over pid-indexed dense arrays, so
  any vertex is addressable by pid.  Host-side outer-vertex lists are
  still derivable for API parity and for the all_to_all message path's
  routing tables.

* Both in- and out-CSRs can be materialised (`LoadStrategy.kBothOutIn`);
  for undirected graphs they alias the same symmetrised arrays, like the
  reference which stores one adjacency for undirected inputs
  (`immutable_edgecut_fragment.h:215-300`).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from libgrape_lite_tpu.graph.csr import CSR, build_csr
from libgrape_lite_tpu.parallel.comm_spec import CommSpec
from libgrape_lite_tpu.utils.id_parser import IdParser
from libgrape_lite_tpu.utils.types import LoadStrategy
from libgrape_lite_tpu.vertex_map.vertex_map import VertexMap


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


# ---- boundary / interior vertex split (superstep pipelining, r9) ----------
#
# A local vertex of fragment f is *boundary* for a pull direction when
# some OTHER fragment's edges over that direction reference it — its
# post-round value must travel in the halo exchange before the next
# round can start anywhere.  Everything else is *interior*: its value
# is only ever read locally, so its compute can overlap the in-flight
# exchange (the communication-avoiding split the reference's message
# manager implies and parallel/pipeline.py exploits).  The read sets
# here are exactly the mirror request lists of parallel/mirror.py —
# the two classifications MUST agree, or the pipelined kickoff would
# ship stale rows (pinned by tests/test_pipeline.py).

_BOUNDARY_CACHE = None


def boundary_split(frag, directions=("ie",)) -> np.ndarray:
    """[fnum, vp] bool — True where the vertex is boundary for a pull
    over `directions` (cached per fragment + direction set).  Padding
    rows are never boundary."""
    global _BOUNDARY_CACHE
    import weakref

    if _BOUNDARY_CACHE is None:
        _BOUNDARY_CACHE = weakref.WeakKeyDictionary()
    per_frag = _BOUNDARY_CACHE.setdefault(frag, {})
    key = tuple(sorted(directions))
    if key in per_frag:
        return per_frag[key]
    fnum, vp = frag.fnum, frag.vp
    read = np.zeros((fnum, vp), dtype=bool)
    for d in key:
        csrs = frag.host_ie if d == "ie" else frag.host_oe
        for g in range(fnum):
            h = csrs[g]
            nbr = h.edge_nbr[h.edge_mask].astype(np.int64)
            owner = nbr // vp
            remote = owner != g
            read[owner[remote], nbr[remote] % vp] = True
    bmask = np.logical_and(read, frag.host_inner_mask())
    per_frag[key] = bmask
    return bmask


def boundary_stats(frag, bmask: np.ndarray, direction: str = "ie") -> dict:
    """Per-fragment boundary/interior vertex + edge counts for one pull
    direction (edges classified by their DESTINATION row: a boundary
    edge feeds a boundary vertex's fold, so it belongs to the slice
    that must finish before the exchange kickoff).  Surfaced through
    spmv_pack.plan_stats(), Worker.pack_ledger() and trace_report."""
    inner = frag.host_inner_mask()
    csrs = frag.host_ie if direction == "ie" else frag.host_oe
    per_frag = []
    for f in range(frag.fnum):
        h = csrs[f]
        src = h.edge_src[h.edge_mask]
        is_b = bmask[f][src]
        bv = int(bmask[f].sum())
        per_frag.append({
            "boundary_vertices": bv,
            "interior_vertices": int(inner[f].sum()) - bv,
            "boundary_edges": int(is_b.sum()),
            "interior_edges": int(len(src) - is_b.sum()),
        })
    tot = {
        k: sum(p[k] for p in per_frag)
        for k in per_frag[0]
    } if per_frag else {}
    return {"per_fragment": per_frag, "totals": tot,
            "direction": direction}


def _next_pow2(x: int) -> int:
    return 1 << max(0, int(np.ceil(np.log2(max(x, 1)))))


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["indptr", "edge_src", "edge_nbr", "edge_w", "edge_mask"],
    meta_fields=[],
)
@dataclass
class DeviceCSR:
    """Stacked [fnum, ...] padded CSR living on device (or its per-shard
    block inside shard_map)."""

    indptr: jax.Array  # [fnum, Vp+1] i32
    edge_src: jax.Array  # [fnum, Ep] i32 (pad rows = Vp)
    edge_nbr: jax.Array  # [fnum, Ep] i32 pid
    edge_w: Optional[jax.Array]  # [fnum, Ep] float or None
    edge_mask: jax.Array  # [fnum, Ep] bool


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["ivnum", "inner_mask", "oids", "oe", "ie", "out_degree", "in_degree"],
    meta_fields=["fnum", "vp", "directed", "total_vnum", "total_enum"],
)
@dataclass
class DeviceFragment:
    """The jittable fragment view. Leaves are stacked [fnum, ...] arrays;
    static metadata rides along as aux data (trace-time constants)."""

    ivnum: jax.Array  # [fnum] i32 real inner vertex count
    inner_mask: jax.Array  # [fnum, Vp] bool
    oids: jax.Array  # [fnum, Vp] i64/i32 original ids (pad = -1)
    oe: DeviceCSR  # outgoing CSR (rows = local lids, nbr = pid)
    ie: DeviceCSR  # incoming CSR (rows = local lids, nbr = pid)
    out_degree: jax.Array  # [fnum, Vp] i32
    in_degree: jax.Array  # [fnum, Vp] i32
    fnum: int
    vp: int
    directed: bool
    total_vnum: int
    total_enum: int

    @property
    def n_pad(self) -> int:
        return self.fnum * self.vp

    def local(self) -> "DeviceFragment":
        """Squeeze the leading frag axis (inside shard_map each block has
        leading extent 1)."""
        sq = lambda a: None if a is None else a[0]
        return DeviceFragment(
            ivnum=self.ivnum[0],
            inner_mask=sq(self.inner_mask),
            oids=sq(self.oids),
            oe=DeviceCSR(
                self.oe.indptr[0],
                self.oe.edge_src[0],
                self.oe.edge_nbr[0],
                sq(self.oe.edge_w),
                self.oe.edge_mask[0],
            ),
            ie=DeviceCSR(
                self.ie.indptr[0],
                self.ie.edge_src[0],
                self.ie.edge_nbr[0],
                sq(self.ie.edge_w),
                self.ie.edge_mask[0],
            ),
            out_degree=sq(self.out_degree),
            in_degree=sq(self.in_degree),
            fnum=self.fnum,
            vp=self.vp,
            directed=self.directed,
            total_vnum=self.total_vnum,
            total_enum=self.total_enum,
        )


class ShardedEdgecutFragment:
    """Host-side descriptor of the full sharded graph (all fragments)."""

    def __init__(
        self,
        comm_spec: CommSpec,
        vertex_map: VertexMap,
        device_fragment: DeviceFragment,
        host_csrs_oe: list[CSR],
        host_csrs_ie: list[CSR],
        directed: bool,
        weighted: bool,
    ):
        self.comm_spec = comm_spec
        self.vertex_map = vertex_map
        self.dev = device_fragment
        self.host_oe = host_csrs_oe
        self.host_ie = host_csrs_ie
        self.directed = directed
        self.weighted = weighted
        self.fnum = device_fragment.fnum
        self.vp = device_fragment.vp
        self.id_parser = IdParser(self.fnum, self.vp)
        # original (pre-symmetrisation) oid edge list, retained when the
        # fragment was built mutable (reference MutableEdgecutFragment
        # keeps slack CSRs instead; we rebuild-on-mutate)
        self.edge_list = None

    # ---- FragmentBase API parity (fragment_base.h:50-133) ----

    @property
    def total_vertices_num(self) -> int:
        return self.dev.total_vnum

    @property
    def total_edges_num(self) -> int:
        return self.dev.total_enum

    def inner_vertices_num(self, fid: int) -> int:
        # host-side source: dev.ivnum is built from exactly this value
        # (_device_put), but the device copy spans non-addressable
        # devices under jax.distributed and cannot be fetched
        return int(self.vertex_map.inner_vertex_num(fid))

    def is_string_keyed(self) -> bool:
        """True when vertex oids are strings (--string_id graphs)."""
        return self.vertex_map.is_string_keyed()

    def host_inner_mask(self) -> np.ndarray:
        """[fnum, vp] bool: True for real (non-padding) vertex rows —
        the single source of truth for padding semantics on the host
        side (device side: DeviceFragment.inner_mask)."""
        ivnum = np.array(
            [self.inner_vertices_num(f) for f in range(self.fnum)]
        )
        return np.arange(self.vp)[None, :] < ivnum[:, None]

    def inner_oids(self, fid: int) -> np.ndarray:
        return self.vertex_map.inner_oids(fid)

    def oid_to_pid(self, oids: np.ndarray) -> np.ndarray:
        """oid -> padded global id (== reference gid bit layout)."""
        gids = self.vertex_map.get_gid(oids)
        if (gids < 0).all() and np.asarray(oids).dtype.kind not in "OUS":
            # string-keyed graph queried with a numeric id (e.g.
            # --sssp_source 6 against --string_id): retry as text
            as_str = np.array([str(o) for o in np.asarray(oids).tolist()],
                              dtype=object)
            gids = self.vertex_map.get_gid(as_str)
        fid = self.vertex_map.id_parser.get_fid(gids)
        lid = self.vertex_map.id_parser.get_lid(gids)
        pid = fid * self.vp + lid
        pid[gids < 0] = -1
        return pid

    def pid_to_oid(self, pids: np.ndarray) -> np.ndarray:
        fid = np.asarray(pids) // self.vp
        lid = np.asarray(pids) % self.vp
        gids = self.vertex_map.id_parser.generate(fid, lid)
        return self.vertex_map.get_oid(gids)

    # ---- device residency (fleet/ eviction, docs/FLEET.md) ----

    def release_device(self) -> bool:
        """Evict: delete the stacked device arrays and drop `dev`.
        Every host artifact survives — host CSRs, vertex map, the
        per-fragment pack-plan cache weak-keyed on THIS object — so
        `restore_device` re-places byte-identical content with zero
        pack re-planning.  Returns False when already released."""
        if self.dev is None:
            return False
        self._dev_meta = (self.dev.total_vnum, self.dev.total_enum)
        seen = set()
        for leaf in jax.tree_util.tree_leaves(self.dev):
            if leaf is None or id(leaf) in seen:
                continue  # undirected ie aliases oe: delete once
            seen.add(id(leaf))
            delete = getattr(leaf, "delete", None)
            if callable(delete):
                try:
                    delete()
                except Exception:
                    pass  # committed/donated buffers: GC frees them
        self.dev = None
        return True

    def restore_device(self) -> bool:
        """Re-admission: rebuild and place the device arrays from the
        host CSRs (the build is deterministic, so the content is
        byte-identical to the evicted arrays).  Returns False when
        already resident."""
        if self.dev is not None:
            return False
        total_vnum, total_enum = self._dev_meta
        self.dev = self._device_put(
            self.comm_spec, self.vertex_map, self.host_oe,
            self.host_ie, self.vp, self.directed, total_vnum,
            total_enum,
        )
        return True

    # ---- construction ----

    @classmethod
    def build(
        cls,
        comm_spec: CommSpec,
        vertex_map: VertexMap,
        src_oid: np.ndarray,
        dst_oid: np.ndarray,
        weights: np.ndarray | None,
        directed: bool,
        load_strategy: LoadStrategy = LoadStrategy.kBothOutIn,
        vid_dtype=np.int32,
        edata_dtype=np.float32,
        retain_edge_list: bool = False,
    ) -> "ShardedEdgecutFragment":
        """Distribute edges to owner fragments and build padded CSRs.

        The reference ships edges to owners over MPI ring threads
        (`basic_fragment_loader_base.h:308-363`); here the host shuffles
        with numpy grouping, then `jax.device_put`s each fragment's block
        onto its mesh device.
        """
        fnum = comm_spec.fnum
        total_vnum = vertex_map.total_vertex_num()
        max_ivnum = max(vertex_map.inner_vertex_num(f) for f in range(fnum))
        vp = _next_pow2(max(max_ivnum, 8))

        # oid -> (fid, lid) -> pid for both endpoints
        def to_pid(oids):
            g = vertex_map.get_gid(oids)
            if (g < 0).any():
                bad = np.asarray(oids)[g < 0][:5]
                raise ValueError(f"edge endpoint(s) not in vertex map, e.g. {bad}")
            f = vertex_map.id_parser.get_fid(g)
            l = vertex_map.id_parser.get_lid(g)
            return (f * vp + l).astype(np.int64), f.astype(np.int64), l.astype(np.int64)

        src_pid, src_fid, src_lid = to_pid(src_oid)
        dst_pid, dst_fid, dst_lid = to_pid(dst_oid)
        real_enum = len(src_pid)

        if not directed:
            # symmetrise with multiplicity, like undirected buildCSR
            # (csr_edgecut_fragment_base.h:417-736)
            src_pid, dst_pid = (
                np.concatenate([src_pid, dst_pid]),
                np.concatenate([dst_pid, src_pid]),
            )
            src_fid, dst_fid = (
                np.concatenate([src_fid, dst_fid]),
                np.concatenate([dst_fid, src_fid]),
            )
            src_lid, dst_lid = (
                np.concatenate([src_lid, dst_lid]),
                np.concatenate([dst_lid, src_lid]),
            )
            if weights is not None:
                weights = np.concatenate([weights, weights])

        # per-fragment edge groups.  For undirected graphs the
        # symmetrised out- and in-CSRs hold the *same* multiset grouped
        # the same way (each (u,v)+(v,u) pair mirrors itself), so one
        # CSR stack is built and aliased — halving edge HBM, like the
        # reference storing a single adjacency for undirected inputs.
        oe_counts = np.bincount(src_fid, minlength=fnum)
        ie_counts = np.bincount(dst_fid, minlength=fnum)
        # undirected kOnlyIn aliases kOnlyOut: the symmetrised CSR is
        # the same multiset either way (see aliasing note above), so
        # build the out stack and alias it rather than crashing on an
        # empty host_oe/host_ie pair
        need_oe = load_strategy in (
            LoadStrategy.kOnlyOut, LoadStrategy.kBothOutIn
        ) or (not directed and load_strategy == LoadStrategy.kOnlyIn)
        need_ie = directed and load_strategy in (
            LoadStrategy.kOnlyIn, LoadStrategy.kBothOutIn
        )
        ep_oe = _round_up(max(int(oe_counts.max()), 1), 128) if need_oe else 128
        ep_ie = _round_up(max(int(ie_counts.max()), 1), 128) if need_ie else 128

        # SPMD blocks must be uniform, so every shard pays the
        # most-loaded shard's padded capacity (Ep = global max) — check
        # the bill fits the chip and surface partition skew BEFORE an
        # opaque device OOM (VERDICT r3 weak #6)
        cls._check_hbm_budget(
            vp, ep_oe, ep_ie,
            aliased=not directed,
            need_oe=need_oe, need_ie=need_ie,
            weighted=weights is not None,
            edata_itemsize=np.dtype(edata_dtype).itemsize,
            oe_counts=oe_counts if need_oe else None,
            ie_counts=ie_counts if need_ie else None,
        )

        w_np = None if weights is None else np.asarray(weights, dtype=edata_dtype)
        host_oe, host_ie = [], []
        for f in range(fnum):
            if need_oe:
                m = src_fid == f
                host_oe.append(
                    build_csr(
                        src_lid[m], dst_pid[m],
                        None if w_np is None else w_np[m],
                        vp, ep_oe, nbr_dtype=vid_dtype,
                    )
                )
            if need_ie:
                m = dst_fid == f
                host_ie.append(
                    build_csr(
                        dst_lid[m], src_pid[m],
                        None if w_np is None else w_np[m],
                        vp, ep_ie, nbr_dtype=vid_dtype,
                    )
                )
        if not need_oe:
            host_oe = host_ie
        if not need_ie:
            host_ie = host_oe

        dev = cls._device_put(
            comm_spec, vertex_map, host_oe, host_ie, vp, directed,
            total_vnum, real_enum,
        )
        out = cls(comm_spec, vertex_map, dev, host_oe, host_ie, directed,
                  weights is not None)
        if retain_edge_list:
            out.edge_list = (
                np.asarray(src_oid).copy(),
                np.asarray(dst_oid).copy(),
                None if weights is None else np.asarray(weights)[: len(src_oid)].copy(),
            )
        return out

    @staticmethod
    def _check_hbm_budget(vp, ep_oe, ep_ie, aliased, need_oe,
                          need_ie, weighted, edata_itemsize,
                          oe_counts=None, ie_counts=None):
        """Estimate per-device fragment bytes and warn before device
        placement when they exceed the HBM budget (GRAPE_HBM_BYTES, by
        default 16 GiB — one v5e chip; set 0 to disable).  Also warns
        on heavy partition skew: since Ep is the max over shards, a
        skewed cut makes EVERY shard pay the hub shard's padding — the
        fix is `--rebalance` (degree-weighted contiguous blocks) or a
        different partitioner, not a bigger chip."""
        import os

        from libgrape_lite_tpu.utils import logging as glog

        budget = int(os.environ.get("GRAPE_HBM_BYTES", 16 << 30))

        def csr_bytes(ep):
            # indptr + edge_src + edge_nbr + mask (+ weights)
            return (vp + 1) * 4 + ep * (4 + 4 + 1) + (
                ep * edata_itemsize if weighted else 0
            )

        per_dev = vp * (4 + 4 + 8 + 1)  # degrees, oids, inner_mask
        if aliased or not (need_oe and need_ie):
            sides = 1
            per_dev += csr_bytes(ep_oe if need_oe else ep_ie)
        else:
            # each side pays ITS OWN padded capacity (in-degree skew
            # can make ep_ie >> ep_oe on directed graphs)
            sides = 2
            per_dev += csr_bytes(ep_oe) + csr_bytes(ep_ie)

        for name, counts, ep in (("oe", oe_counts, ep_oe),
                                 ("ie", ie_counts, ep_ie)):
            if counts is None or len(counts) < 2:
                continue
            mean = max(float(counts.mean()), 1.0)
            skew = float(counts.max()) / mean
            if skew > 1.5:
                glog.log_info(
                    f"partition skew: max/mean {name} edges per shard "
                    f"= {skew:.2f} ({int(counts.max())} vs "
                    f"{mean:.0f}); every shard pads to Ep={ep} — "
                    "consider --rebalance or a hash partitioner"
                )
        if budget and per_dev > budget:
            def fmt(b):
                return (f"{b / (1 << 30):.2f} GiB" if b >= (1 << 30)
                        else f"{b / (1 << 20):.2f} MiB")

            glog.log_info(
                f"fragment needs ~{fmt(per_dev)} per device "
                f"(vp={vp}, ep={max(ep_oe, ep_ie)}, "
                f"{sides} CSR side(s)) — exceeds the {fmt(budget)} HBM "
                "budget (GRAPE_HBM_BYTES); expect an allocator failure "
                "on real chips at this scale/partition"
            )
        return per_dev

    @staticmethod
    def _device_put(
        comm_spec, vertex_map, host_oe, host_ie, vp, directed, total_vnum,
        total_enum,
    ) -> DeviceFragment:
        fnum = comm_spec.fnum
        ivnum = np.array(
            [vertex_map.inner_vertex_num(f) for f in range(fnum)], dtype=np.int32
        )
        inner_mask = np.arange(vp)[None, :] < ivnum[:, None]
        oids = np.full((fnum, vp), -1, dtype=np.int64)
        for f in range(fnum):
            o = vertex_map.inner_oids(f)
            if len(o) and np.asarray(o).dtype.kind in "OUS":
                # string oids can't live on device: use the pid as a
                # stable numeric surrogate (CDLP labels etc.)
                oids[f, : len(o)] = f * vp + np.arange(len(o))
            else:
                oids[f, : len(o)] = o

        def stack_csr(csrs: list[CSR]) -> DeviceCSR:
            return DeviceCSR(
                indptr=np.stack([c.indptr for c in csrs]),
                edge_src=np.stack([c.edge_src for c in csrs]),
                edge_nbr=np.stack([c.edge_nbr for c in csrs]),
                edge_w=(
                    None
                    if csrs[0].edge_w is None
                    else np.stack([c.edge_w for c in csrs])
                ),
                edge_mask=np.stack([c.edge_mask for c in csrs]),
            )

        aliased = host_ie is host_oe
        oe_h = stack_csr(host_oe)
        ie_h = oe_h if aliased else stack_csr(host_ie)
        out_degree = np.stack([c.degree for c in host_oe]).astype(np.int32)
        in_degree = (
            out_degree
            if aliased
            else np.stack([c.degree for c in host_ie]).astype(np.int32)
        )

        shard = comm_spec.sharded()

        from libgrape_lite_tpu.parallel.comm_spec import put_global

        def put(x):
            return put_global(x, shard)

        oe_dev = DeviceCSR(
            put(oe_h.indptr), put(oe_h.edge_src), put(oe_h.edge_nbr),
            put(oe_h.edge_w), put(oe_h.edge_mask),
        )
        ie_dev = (
            oe_dev
            if aliased
            else DeviceCSR(
                put(ie_h.indptr), put(ie_h.edge_src), put(ie_h.edge_nbr),
                put(ie_h.edge_w), put(ie_h.edge_mask),
            )
        )
        out_deg_dev = put(out_degree)
        frag = DeviceFragment(
            ivnum=put_global(ivnum, shard),
            inner_mask=put(inner_mask),
            oids=put(oids),
            oe=oe_dev,
            ie=ie_dev,
            out_degree=out_deg_dev,
            in_degree=out_deg_dev if aliased else put(in_degree),
            fnum=fnum,
            vp=vp,
            directed=directed,
            total_vnum=total_vnum,
            total_enum=total_enum,
        )
        return frag
