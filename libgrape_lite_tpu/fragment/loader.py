"""Graph loading pipeline.

Re-design of `grape/fragment/loader.h:42-80` + `ev_fragment_loader.h:49-229`
+ `basic_fragment_loader_base.h:244-441`: read .v/.e TSV, build the
vertex map (partitioner + idxer), shuffle edges to owner fragments and
construct padded device CSRs.  The reference's MPI ring shuffle becomes
host-side numpy grouping followed by per-device placement.

Also implements the content-hash fragment serialization cache
(`basic_fragment_loader_base.h:127-242`; flags `--serialize/--deserialize`,
`flags.cc:56-59`): prefix/<hex>/part_<fnum>/frag.npz with a `sig` file.
"""

from __future__ import annotations

import hashlib
import json
import os
from dataclasses import dataclass, field

import numpy as np

from libgrape_lite_tpu.fragment.edgecut import ShardedEdgecutFragment
from libgrape_lite_tpu.io.line_parser import read_edge_file, read_vertex_file
from libgrape_lite_tpu.parallel.comm_spec import CommSpec
from libgrape_lite_tpu.utils.types import LoadStrategy
from libgrape_lite_tpu.vertex_map.partitioner import make_partitioner
from libgrape_lite_tpu.vertex_map.vertex_map import VertexMap


@dataclass
class LoadGraphSpec:
    """Loading options (reference `LoadGraphSpec`,
    `basic_fragment_loader_base.h:30-109`)."""

    directed: bool = False
    weighted: bool = True
    load_strategy: LoadStrategy = LoadStrategy.kBothOutIn
    partitioner_type: str = "map"  # hash | map | segment (flags.cc:46-48)
    idxer_type: str = "hashmap"  # sorted_array | hashmap | pthash | local
    rebalance: bool = False
    rebalance_vertex_factor: int = 0
    string_id: bool = False  # reference --string_id (load_tests.cc:45)
    serialize: bool = False
    deserialize: bool = False
    serialization_prefix: str = ""
    vid_dtype: type = np.int32
    edata_dtype: type = np.float32


def _cache_dir(efile: str, vfile: str, spec: LoadGraphSpec, fnum: int) -> str:
    sig = json.dumps(
        {
            "efile": os.path.abspath(efile),
            "vfile": os.path.abspath(vfile) if vfile else "",
            "esize": os.path.getsize(efile),
            "vsize": os.path.getsize(vfile) if vfile else 0,
            "directed": spec.directed,
            "weighted": spec.weighted,
            "strategy": spec.load_strategy.value,
            "partitioner": spec.partitioner_type,
            "idxer": spec.idxer_type,
            "rebalance": spec.rebalance,
            "string_id": spec.string_id,
            "rebalance_vertex_factor": spec.rebalance_vertex_factor,
            "type": "ShardedEdgecutFragment",
        },
        sort_keys=True,
    )
    h = hashlib.sha256(sig.encode()).hexdigest()[:16]
    return os.path.join(spec.serialization_prefix, h, f"part_{fnum}"), sig


def LoadGraph(
    efile: str,
    vfile: str | None,
    comm_spec: CommSpec,
    spec: LoadGraphSpec | None = None,
) -> ShardedEdgecutFragment:
    """Entry point, mirroring `LoadGraph<FRAG_T>` (`loader.h:42-53`)."""
    spec = spec or LoadGraphSpec()

    cache = None
    if (spec.serialize or spec.deserialize) and spec.serialization_prefix:
        cache, sig = _cache_dir(efile, vfile or "", spec, comm_spec.fnum)

    if spec.deserialize and cache and os.path.exists(os.path.join(cache, "sig")):
        return _deserialize_fragment(cache, comm_spec, spec)

    src, dst, w = read_edge_file(
        efile, weighted=spec.weighted, string_id=spec.string_id
    )
    if not spec.weighted:
        w = None
    if vfile:
        oids = read_vertex_file(vfile, string_id=spec.string_id)
    else:
        # efile-only loading (reference basic_efile_fragment_loader.h):
        # vertex universe = the set of edge endpoints.  np.unique yields
        # them in sorted oid order (NOT the reference's first-appearance
        # order); lids therefore differ, but all output is oid-keyed so
        # results are unaffected.
        oids = np.unique(np.concatenate([src, dst]))

    if spec.rebalance:
        from libgrape_lite_tpu.fragment.rebalancer import Rebalancer

        partitioner = Rebalancer(spec.rebalance_vertex_factor).partition(
            oids, src, dst, comm_spec.fnum
        )
    else:
        partitioner = make_partitioner(
            spec.partitioner_type, comm_spec.fnum, oids
        )
    vm = VertexMap.build(oids, partitioner, idxer_type=spec.idxer_type)

    frag = ShardedEdgecutFragment.build(
        comm_spec, vm, src, dst, w,
        directed=spec.directed,
        load_strategy=spec.load_strategy,
        vid_dtype=spec.vid_dtype,
        edata_dtype=spec.edata_dtype,
    )
    frag.load_spec = spec  # preserved across rebuild-on-mutate

    if spec.serialize and cache:
        _serialize_fragment(frag, cache, sig)
    return frag


def _serialize_fragment(frag: ShardedEdgecutFragment, cache: str, sig: str):
    os.makedirs(cache, exist_ok=True)
    vm = frag.vertex_map
    aliased = frag.host_ie is frag.host_oe
    arrays = {
        "fnum": np.int64(frag.fnum),
        "vp": np.int64(frag.vp),
        "directed": np.int64(frag.directed),
        "weighted": np.int64(frag.weighted),
        "aliased": np.int64(aliased),
        "total_vnum": np.int64(frag.dev.total_vnum),
        "total_enum": np.int64(frag.dev.total_enum),
    }
    sides = [("oe", frag.host_oe)] if aliased else [
        ("oe", frag.host_oe), ("ie", frag.host_ie)
    ]
    for f in range(frag.fnum):
        arrays[f"oids_{f}"] = vm.inner_oids(f)
        for side, csrs in sides:
            c = csrs[f]
            arrays[f"{side}_indptr_{f}"] = c.indptr
            arrays[f"{side}_src_{f}"] = c.edge_src
            arrays[f"{side}_nbr_{f}"] = c.edge_nbr
            arrays[f"{side}_mask_{f}"] = c.edge_mask
            arrays[f"{side}_ne_{f}"] = np.int64(c.num_edges)
            if c.edge_w is not None:
                arrays[f"{side}_w_{f}"] = c.edge_w
    np.savez_compressed(os.path.join(cache, "frag.npz"), **arrays)
    with open(os.path.join(cache, "sig"), "w") as f:
        f.write(sig)


def _deserialize_fragment(
    cache: str, comm_spec: CommSpec, spec: LoadGraphSpec
) -> ShardedEdgecutFragment:
    from libgrape_lite_tpu.graph.csr import CSR
    from libgrape_lite_tpu.utils.id_parser import IdParser

    z = np.load(os.path.join(cache, "frag.npz"), allow_pickle=True)
    fnum = int(z["fnum"])
    if fnum != comm_spec.fnum:
        raise ValueError(
            f"serialized fnum={fnum} != requested {comm_spec.fnum}"
        )
    vp = int(z["vp"])
    directed = bool(z["directed"])
    weighted = bool(z["weighted"])

    all_oids = [z[f"oids_{f}"] for f in range(fnum)]
    # rebuild exact fid assignment: oids_f belongs to fragment f
    from libgrape_lite_tpu.vertex_map.idxer import make_idxer
    from libgrape_lite_tpu.vertex_map.partitioner import ExplicitPartitioner

    idxers = [make_idxer(spec.idxer_type, o) for o in all_oids]
    id_parser = IdParser(fnum, vp)
    flat_oids = np.concatenate(all_oids) if all_oids else np.zeros(0, np.int64)
    flat_fids = np.concatenate(
        [np.full(len(o), f, dtype=np.int64) for f, o in enumerate(all_oids)]
    ) if all_oids else np.zeros(0, np.int64)
    part = ExplicitPartitioner(flat_oids, flat_fids)
    part.fnum = fnum
    vm = VertexMap(part, idxers, id_parser)

    def csr_of(side, f):
        return CSR(
            indptr=z[f"{side}_indptr_{f}"],
            edge_src=z[f"{side}_src_{f}"],
            edge_nbr=z[f"{side}_nbr_{f}"],
            edge_w=z[f"{side}_w_{f}"] if f"{side}_w_{f}" in z else None,
            edge_mask=z[f"{side}_mask_{f}"],
            num_rows=vp,
            num_edges=int(z[f"{side}_ne_{f}"]),
        )

    aliased = bool(z["aliased"]) if "aliased" in z else False
    host_oe = [csr_of("oe", f) for f in range(fnum)]
    host_ie = host_oe if aliased else [csr_of("ie", f) for f in range(fnum)]
    dev = ShardedEdgecutFragment._device_put(
        comm_spec, vm, host_oe, host_ie, vp, directed,
        int(z["total_vnum"]), int(z["total_enum"]),
    )
    return ShardedEdgecutFragment(
        comm_spec, vm, dev, host_oe, host_ie, directed, weighted
    )
