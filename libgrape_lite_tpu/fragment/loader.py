"""Graph loading pipeline.

Re-design of `grape/fragment/loader.h:42-80` + `ev_fragment_loader.h:49-229`
+ `basic_fragment_loader_base.h:244-441`: read .v/.e TSV, build the
vertex map (partitioner + idxer), shuffle edges to owner fragments and
construct padded device CSRs.  The reference's MPI ring shuffle becomes
host-side numpy grouping followed by per-device placement.

Also implements the content-hash fragment serialization cache
(`basic_fragment_loader_base.h:127-242`; flags `--serialize/--deserialize`,
`flags.cc:56-59`): prefix/<hex>/part_<fnum>/frag.npz with a `sig` file.
"""

from __future__ import annotations

import hashlib
import json
import os
from dataclasses import dataclass, field

import numpy as np

from libgrape_lite_tpu.fragment.edgecut import ShardedEdgecutFragment
from libgrape_lite_tpu.io.line_parser import read_edge_file, read_vertex_file
from libgrape_lite_tpu.parallel.comm_spec import CommSpec
from libgrape_lite_tpu.utils.types import LoadStrategy
from libgrape_lite_tpu.vertex_map.partitioner import make_partitioner
from libgrape_lite_tpu.vertex_map.vertex_map import VertexMap


@dataclass
class LoadGraphSpec:
    """Loading options (reference `LoadGraphSpec`,
    `basic_fragment_loader_base.h:30-109`)."""

    directed: bool = False
    weighted: bool = True
    load_strategy: LoadStrategy = LoadStrategy.kBothOutIn
    partitioner_type: str = "map"  # hash | map | segment (flags.cc:46-48)
    idxer_type: str = "hashmap"  # sorted_array | hashmap | pthash | local
    rebalance: bool = False
    rebalance_vertex_factor: int = 0
    string_id: bool = False  # reference --string_id (load_tests.cc:45)
    serialize: bool = False
    deserialize: bool = False
    serialization_prefix: str = ""
    vid_dtype: type = np.int32
    edata_dtype: type = np.float32
    # keep the original oid edge list on the fragment — required for
    # rebuild-on-mutate and the dyn/ repack path (deserialize-path
    # loads cannot retain it: the cache stores only the built shards)
    retain_edge_list: bool = False


def _cache_dir(efile: str, vfile: str, spec: LoadGraphSpec, fnum: int) -> str:
    sig = json.dumps(
        {
            "efile": os.path.abspath(efile),
            "vfile": os.path.abspath(vfile) if vfile else "",
            "esize": os.path.getsize(efile),
            "vsize": os.path.getsize(vfile) if vfile else 0,
            "directed": spec.directed,
            "weighted": spec.weighted,
            # undirected fragments alias oe == ie (one symmetrised CSR
            # serves every strategy), so apps with different
            # load_strategy traits share one cache entry — a PageRank
            # --serialize feeds an SSSP --deserialize
            "strategy": (
                "undirected-aliased" if not spec.directed
                else spec.load_strategy.value
            ),
            "partitioner": spec.partitioner_type,
            "idxer": spec.idxer_type,
            "rebalance": spec.rebalance,
            "string_id": spec.string_id,
            "rebalance_vertex_factor": spec.rebalance_vertex_factor,
            "type": "ShardedEdgecutFragment",
        },
        sort_keys=True,
    )
    h = hashlib.sha256(sig.encode()).hexdigest()[:16]
    return os.path.join(spec.serialization_prefix, h, f"part_{fnum}"), sig


VALIDATE_LOAD_ENV = "GRAPE_VALIDATE_LOAD"

#: degree-weighted chunk rebalancing gate (ROADMAP item 4): "1" folds
#: `rebalance=True` into the spec BEFORE the cache signature is
#: computed, so rebalanced and oid-range caches never alias.  At
#: fnum 1 the rebalancer's single block IS the oid range — results are
#: byte-identical (pinned in tests).
REBALANCE_ENV = "GRAPE_PARTITION_REBALANCE"


def _fold_rebalance_env(spec: LoadGraphSpec) -> LoadGraphSpec:
    if spec.rebalance:
        return spec
    if os.environ.get(REBALANCE_ENV, "") in ("", "0", "off"):
        return spec
    import dataclasses

    vf = int(os.environ.get(REBALANCE_ENV + "_VF", "0") or 0)
    return dataclasses.replace(
        spec, rebalance=True, rebalance_vertex_factor=vf
    )


def _shard_skew(partitioner, dst: np.ndarray, fnum: int) -> dict:
    """Per-shard in-edge counts under one partitioner: the padded-max
    bill every SPMD shard pays (the 1d term the partition ledger
    prices).  skew = max/mean — 1.0 is a perfectly balanced cut."""
    pids = partitioner.get_partition_id(dst)
    counts = np.bincount(pids[pids >= 0], minlength=fnum)
    mean = float(counts.mean()) if fnum else 0.0
    return {
        "max_shard_edges": int(counts.max()) if fnum else 0,
        "mean_shard_edges": round(mean, 1),
        "skew": round(float(counts.max()) / mean, 4) if mean else 1.0,
    }


def _validate_load(frag: ShardedEdgecutFragment) -> ShardedEdgecutFragment:
    """GRAPE_VALIDATE_LOAD=1 gate: structural validation of every host
    CSR right after a load/deserialize (graph/csr.py `CSR.validate`).
    A malformed or tampered input — especially a hand-assembled or
    bit-rotted serialization cache — fails loudly HERE instead of
    producing wrong results three queries later."""
    if os.environ.get(VALIDATE_LOAD_ENV, "") in ("", "0"):
        return frag
    n_pad = frag.fnum * frag.vp
    aliased = frag.host_ie is frag.host_oe
    sides = [("oe", frag.host_oe)] if aliased else [
        ("oe", frag.host_oe), ("ie", frag.host_ie)
    ]
    for side, csrs in sides:
        for f, c in enumerate(csrs):
            c.validate(name=f"{side}[{f}]", n_pad=n_pad)
    from libgrape_lite_tpu.utils import logging as glog

    glog.vlog(
        1,
        "load validation: %d CSR(s) structurally sound",
        len(sides) * frag.fnum,
    )
    return frag


def LoadGraph(
    efile: str,
    vfile: str | None,
    comm_spec: CommSpec,
    spec: LoadGraphSpec | None = None,
) -> ShardedEdgecutFragment:
    """Entry point, mirroring `LoadGraph<FRAG_T>` (`loader.h:42-53`).

    With obs/ armed, the load emits a `load_graph` span with
    `read_edges` / `partition` / `build_fragment` / `deserialize` /
    `serialize` children — load skew shows up on the same timeline as
    the query it delays."""
    from libgrape_lite_tpu import obs

    spec = _fold_rebalance_env(spec or LoadGraphSpec())
    tr = obs.tracer()

    with tr.span("load_graph", efile=efile, fnum=comm_spec.fnum) as lsp:
        cache = None
        if (spec.serialize or spec.deserialize) and spec.serialization_prefix:
            cache, sig = _cache_dir(efile, vfile or "", spec, comm_spec.fnum)

        if spec.deserialize and cache and os.path.exists(
            os.path.join(cache, "sig")
        ):
            with tr.span("deserialize", cache=cache):
                frag = _deserialize_fragment(cache, comm_spec, spec)
            lsp.set(path="deserialize")
            return _validate_load(frag)

        with tr.span("read_edges"):
            src, dst, w = read_edge_file(
                efile, weighted=spec.weighted, string_id=spec.string_id
            )
            if not spec.weighted:
                w = None
            if vfile:
                oids = read_vertex_file(vfile, string_id=spec.string_id)
            else:
                # efile-only loading (basic_efile_fragment_loader.h):
                # vertex universe = the set of edge endpoints.
                # np.unique yields them in sorted oid order (NOT the
                # reference's first-appearance order); lids therefore
                # differ, but all output is oid-keyed so results are
                # unaffected.
                oids = np.unique(np.concatenate([src, dst]))
        lsp.set(edges=int(len(src)), vertices=int(len(oids)))

        with tr.span("partition", kind=spec.partitioner_type):
            if spec.rebalance:
                from libgrape_lite_tpu.fragment.rebalancer import Rebalancer

                partitioner = Rebalancer(
                    spec.rebalance_vertex_factor
                ).partition(oids, src, dst, comm_spec.fnum)
                # record the skew the rebalancer fixed (in-edge counts
                # of the pull direction, both orientations when
                # undirected) vs the oid-range cut it replaced — only
                # computed when engaged, the default path pays nothing
                from libgrape_lite_tpu.fragment.partition import (
                    PARTITION_STATS,
                )

                d_all = (dst if spec.directed
                         else np.concatenate([dst, src]))
                before = _shard_skew(
                    make_partitioner(
                        spec.partitioner_type, comm_spec.fnum, oids
                    ), d_all, comm_spec.fnum,
                )
                after = _shard_skew(partitioner, d_all, comm_spec.fnum)
                PARTITION_STATS["rebalance"] = {
                    "fnum": comm_spec.fnum,
                    "vertex_factor": spec.rebalance_vertex_factor,
                    "before": before, "after": after,
                }
            else:
                partitioner = make_partitioner(
                    spec.partitioner_type, comm_spec.fnum, oids
                )
            vm = VertexMap.build(
                oids, partitioner, idxer_type=spec.idxer_type
            )

        with tr.span("build_fragment"):
            frag = ShardedEdgecutFragment.build(
                comm_spec, vm, src, dst, w,
                directed=spec.directed,
                load_strategy=spec.load_strategy,
                vid_dtype=spec.vid_dtype,
                edata_dtype=spec.edata_dtype,
                retain_edge_list=spec.retain_edge_list,
            )
            frag.load_spec = spec  # preserved across rebuild-on-mutate

        if spec.serialize and cache:
            with tr.span("serialize", cache=cache):
                _serialize_fragment(frag, cache, sig)
        if tr.enabled:
            obs.metrics().gauge("grape_graph_edges").set(int(len(src)))
            obs.metrics().gauge("grape_graph_vertices").set(
                int(len(oids))
            )
        return _validate_load(frag)


# ---- archive-backed cache format (utils/archive.py) ---------------------
#
# The reference serializes fragments through InArchive/OutArchive with
# delta-varint gid compression (`basic_fragment_loader_base.h:127-242`,
# `grape/utils/varint.h`); the TPU build does the same at the host
# boundary: CSR indptr / edge_src are non-decreasing -> delta-varint
# (3-5x smaller than raw int64), edge_nbr -> plain varint, masks ->
# packed bits, weights raw.  One `frag.garc` file per partition.

_GARC_MAGIC = 0x47415243  # "GARC"

# stream encodings (flag byte per array)
# _ENC_PICKLE is write-dead since format v3: a crafted cache file must
# not reach pickle.loads at deserialize time (arbitrary code execution);
# string oids use length-prefixed UTF-8 (_ENC_STR) instead.
# _ENC_FPLANE (v3): float streams as byte planes, each plane deflated
# only when it actually compresses — the sign/exponent plane shrinks
# ~4x while mantissa planes are incompressible noise that v2's
# whole-archive deflate burned seconds failing to compress.
# _ENC_VARINT_Z/_ENC_DELTA_Z (v3): the varint payload additionally
# deflated (level 1) when that wins ≥10% — LEB128 output has a skewed
# byte alphabet, so cheap entropy coding recovers most of what v2's
# whole-archive deflate got, per-stream and only where it pays.
(_ENC_RAW, _ENC_VARINT, _ENC_DELTA, _ENC_BITS, _ENC_PICKLE, _ENC_STR,
 _ENC_FPLANE, _ENC_VARINT_Z, _ENC_DELTA_Z) = range(9)

# deflate a float byte-plane only when a cheap level-1 pass wins ≥10%
_PLANE_MIN_GAIN = 0.9
# below this element count the codec machinery costs more than it saves
_FPLANE_MIN = 4096


def _put_array(ar, a: np.ndarray) -> None:
    """Append one array: flag byte, element count, payload, dtype tag."""
    a = np.asarray(a)
    if a.dtype == object:  # string oids: varint lengths + UTF-8 payload
        from libgrape_lite_tpu.utils.archive import varint_encode

        blobs = [str(s).encode("utf-8") for s in a.tolist()]
        lens = varint_encode(
            np.array([len(b) for b in blobs], dtype=np.uint64)
        )
        payload = b"".join(blobs)
        ar.add_scalar(_ENC_STR, "<b")
        ar.add_scalar(len(a))
        ar.add_scalar(len(lens))
        ar.add_bytes(lens)
        ar.add_scalar(len(payload))
        ar.add_bytes(payload)
        return
    from libgrape_lite_tpu.utils.archive import (
        delta_varint_encode, varint_encode,
    )

    if a.dtype == np.bool_:
        ar.add_scalar(_ENC_BITS, "<b")
        ar.add_scalar(len(a))
        ar.add_bytes(np.packbits(a).tobytes())
    elif np.issubdtype(a.dtype, np.integer) and (
        len(a) == 0 or (int(a.min()) >= 0 and int(a.max()) < (1 << 62))
    ):
        monotone = len(a) > 0 and bool((np.diff(a) >= 0).all())
        enc = (delta_varint_encode if monotone else varint_encode)(
            a.astype(np.uint64)
        )
        code = _ENC_DELTA if monotone else _ENC_VARINT
        # GRAPE_GARC_COMPACT=1 trades write time for bytes: deflating
        # the LEB128 payloads recovers v2's whole-archive ratio
        # (measured RMAT-18 weighted: 4.6 s / 45 MB vs the default
        # 2.7 s / 59 MB vs v2's 7.5 s / 46 MB).  "0"/"" disable it,
        # consistent with GRAPE_LCC_TIERS (ADVICE r5)
        compact = os.environ.get("GRAPE_GARC_COMPACT", "") not in ("", "0")
        if compact and len(enc) >= 1 << 12:
            import zlib

            z = zlib.compress(enc, 1)
            if len(z) < _PLANE_MIN_GAIN * len(enc):
                code = _ENC_DELTA_Z if monotone else _ENC_VARINT_Z
                enc = z
        ar.add_scalar(code, "<b")
        ar.add_scalar(len(a))
        ar.add_scalar(len(enc))
        ar.add_bytes(enc)
    elif np.issubdtype(a.dtype, np.floating) and len(a) >= _FPLANE_MIN:
        import zlib

        from libgrape_lite_tpu.io.native import byte_split

        planes = byte_split(a)
        ar.add_scalar(_ENC_FPLANE, "<b")
        ar.add_scalar(len(a))
        ar.add_scalar(planes.shape[0], "<b")
        for p in planes:
            raw = p.tobytes()
            # probe compressibility on a 1 MiB sample first: mantissa
            # planes are noise, and paying a full-plane deflate just to
            # discover that was 60% of the serialize phase (measured:
            # 40.8 s of 67.6 s at 115M f64 weights)
            sample = raw[: 1 << 20]
            z = None
            if len(zlib.compress(sample, 1)) < _PLANE_MIN_GAIN * len(sample):
                z = zlib.compress(raw, 1)
            if z is not None and len(z) < _PLANE_MIN_GAIN * len(raw):
                ar.add_scalar(1, "<b")
                ar.add_scalar(len(z))
                ar.add_bytes(z)
            else:
                ar.add_scalar(0, "<b")
                ar.add_scalar(len(raw))
                ar.add_bytes(raw)
    else:
        ar.add_scalar(_ENC_RAW, "<b")
        ar.add_scalar(len(a))
        ar.add_array(a)
    tag = a.dtype.str.encode()
    ar.add_scalar(len(tag), "<b")
    ar.add_bytes(tag)


def _bounded_decompress(buf: bytes, max_out: int) -> bytes:
    """zlib.decompress with an output-size cap: the stream lengths in a
    frag.garc are attacker-controlled, so an unbounded decompress would
    let a small crafted cache file balloon into a huge allocation
    before any length check runs (decompression bomb, ADVICE r5).  The
    expected output size is always known to the caller; producing more
    than that is by definition a corrupt stream."""
    import zlib

    d = zlib.decompressobj()
    try:
        # never pass 0 as max_length — zlib treats it as "no limit",
        # which would reopen the bomb for streams claiming n=0; a
        # 1-byte cap makes any output at all fail the check below
        out = d.decompress(buf, max(1, max_out))
        # input left over after the output cap was reached means the
        # stream wants to produce more than the caller's bound; probe
        # with a 1-byte cap (never ballooning) to confirm
        extra = d.decompress(d.unconsumed_tail, 1) if d.unconsumed_tail else b""
    except zlib.error as e:
        raise ValueError(f"corrupt deflate stream in frag.garc: {e}") from e
    if extra or len(out) > max_out:
        raise ValueError(
            "corrupt deflate stream in frag.garc: decompressed output "
            f"exceeds the expected {max_out} bytes"
        )
    return out


def _get_array(oa) -> np.ndarray:
    from libgrape_lite_tpu.utils.archive import (
        delta_varint_decode, varint_decode,
    )

    enc = oa.get_scalar("<b")
    if enc == _ENC_PICKLE:
        raise ValueError(
            "pickle-era garc stream refused (deserializing it would run "
            "arbitrary code from the cache file); delete the cache dir "
            "and re-serialize from source"
        )
    if enc == _ENC_STR:
        n = oa.get_scalar()
        nlens = oa.get_scalar()
        lens = varint_decode(bytes(oa.get_bytes(nlens)))
        npay = oa.get_scalar()
        payload = bytes(oa.get_bytes(npay))
        # fail loudly on corrupt/crafted streams (the hardening point
        # of this format): count and payload extent must match exactly
        if len(lens) != n or int(lens.sum()) != len(payload):
            raise ValueError("corrupt string stream in frag.garc")
        out = np.empty(n, dtype=object)
        pos = 0
        for i, ln in enumerate(lens.tolist()):
            out[i] = payload[pos:pos + ln].decode("utf-8")
            pos += ln
        return out
    n = oa.get_scalar()
    if enc == _ENC_FPLANE:
        from libgrape_lite_tpu.io.native import byte_join

        itemsize = oa.get_scalar("<b")
        planes = np.empty((itemsize, n), dtype=np.uint8)
        for p in range(itemsize):
            comp = oa.get_scalar("<b")
            nbytes = oa.get_scalar()
            raw = bytes(oa.get_bytes(nbytes))
            if comp:
                # a plane is exactly n bytes; cap the inflate there
                raw = _bounded_decompress(raw, n)
            if len(raw) != n:
                raise ValueError("corrupt float plane in frag.garc")
            planes[p] = np.frombuffer(raw, dtype=np.uint8)
        tl = oa.get_scalar("<b")
        dt = np.dtype(bytes(oa.get_bytes(tl)).decode())
        if dt.itemsize != itemsize or dt.kind != "f":
            raise ValueError("corrupt float dtype tag in frag.garc")
        return byte_join(planes, dt)
    if enc == _ENC_BITS:
        vals = np.unpackbits(
            np.frombuffer(oa.get_bytes((n + 7) // 8), np.uint8)
        )[:n].astype(bool)
    elif enc in (_ENC_VARINT, _ENC_DELTA, _ENC_VARINT_Z, _ENC_DELTA_Z):
        nbytes = oa.get_scalar()
        buf = bytes(oa.get_bytes(nbytes))
        if enc in (_ENC_VARINT_Z, _ENC_DELTA_Z):
            # LEB128 uses at most 10 bytes per uint64, so n elements
            # bound the inflated payload at 10*n
            buf = _bounded_decompress(buf, 10 * n)
        vals = (
            delta_varint_decode(buf) if enc in (_ENC_DELTA, _ENC_DELTA_Z)
            else varint_decode(buf)
        )
    else:
        vals = oa.get_array(np.uint8)
    tl = oa.get_scalar("<b")
    dt = np.dtype(bytes(oa.get_bytes(tl)).decode())
    if enc == _ENC_RAW:
        return vals.view(dt).copy()
    return vals.astype(dt)


def _serialize_fragment(frag: ShardedEdgecutFragment, cache: str, sig: str):
    from libgrape_lite_tpu.utils.archive import InArchive

    os.makedirs(cache, exist_ok=True)
    vm = frag.vertex_map
    aliased = frag.host_ie is frag.host_oe
    ar = InArchive()
    ar.add_scalar(_GARC_MAGIC)
    ar.add_scalar(3)  # format version (v3: string oids are UTF-8, not pickle)
    for v in (
        frag.fnum, frag.vp, int(frag.directed), int(frag.weighted),
        int(aliased), frag.dev.total_vnum, frag.dev.total_enum,
    ):
        ar.add_scalar(int(v))
    sides = [("oe", frag.host_oe)] if aliased else [
        ("oe", frag.host_oe), ("ie", frag.host_ie)
    ]
    for f in range(frag.fnum):
        _put_array(ar, vm.inner_oids(f))
        for side, csrs in sides:
            c = csrs[f]
            _put_array(ar, c.indptr)
            _put_array(ar, c.edge_src)
            _put_array(ar, c.edge_nbr)
            _put_array(ar, c.edge_mask)
            ar.add_scalar(c.num_edges)
            ar.add_scalar(0 if c.edge_w is None else 1, "<b")
            if c.edge_w is not None:
                _put_array(ar, c.edge_w)
    # v3 container is raw: compression is per-stream now (varint for
    # ints, plane-split deflate for floats) — v2's whole-archive
    # deflate spent most of its time failing to compress float
    # mantissa noise (measured 7.9 s for a 10% saving on 80 MB of
    # weights; the plane codec gets more in < 1/3 the time)
    with open(os.path.join(cache, "frag.garc"), "wb") as fh:
        fh.write(ar.get_buffer())
    with open(os.path.join(cache, "sig"), "w") as f:
        f.write(sig)


def _read_cache_file(path: str) -> bytes:
    """Read one cache shard with the shared transient-IO retry policy
    (ft/retry.py): serialization prefixes live on shared/network
    filesystems where a stale-handle EIO is worth one more try before
    falling back to a full rebuild from source text."""
    from libgrape_lite_tpu.ft.retry import (
        CACHE_READ_POLICY, is_transient_io_error, with_retries,
    )

    def _read():
        with open(path, "rb") as fh:
            return fh.read()

    return with_retries(
        _read,
        policy=CACHE_READ_POLICY,
        retryable=is_transient_io_error,
        describe=f"garc cache read {path}",
    )


def _read_garc(cache: str):
    """Parse frag.garc -> (meta dict, per-fragment streams)."""
    import zlib

    from libgrape_lite_tpu.utils.archive import OutArchive

    blob = _read_cache_file(os.path.join(cache, "frag.garc"))
    # v3 containers start with the raw GARC magic; v2 wrapped the whole
    # archive in one deflate stream (first byte 0x78)
    if not blob.startswith((_GARC_MAGIC).to_bytes(8, "little")):
        blob = zlib.decompress(blob)
    oa = OutArchive(blob)
    if oa.get_scalar() != _GARC_MAGIC:
        raise ValueError("bad garc magic")
    version = oa.get_scalar()
    # v2 accepted for non-string-oid caches; its pickle streams (string
    # oids only) are refused stream-by-stream in _get_array
    if version not in (2, 3):
        raise ValueError(f"unsupported garc version {version}")
    (fnum, vp, directed, weighted, aliased, total_vnum,
     total_enum) = (oa.get_scalar() for _ in range(7))
    meta = dict(
        fnum=fnum, vp=vp, directed=bool(directed),
        weighted=bool(weighted), aliased=bool(aliased),
        total_vnum=total_vnum, total_enum=total_enum,
    )
    sides = ["oe"] if aliased else ["oe", "ie"]
    frags = []
    for _f in range(fnum):
        entry = {"oids": _get_array(oa)}
        for side in sides:
            indptr = _get_array(oa)
            src = _get_array(oa)
            nbr = _get_array(oa)
            mask = _get_array(oa)
            ne = oa.get_scalar()
            has_w = oa.get_scalar("<b")
            w = _get_array(oa) if has_w else None
            entry[side] = (indptr, src, nbr, mask, ne, w)
        frags.append(entry)
    if not oa.empty():  # not an assert: must survive `python -O`
        raise ValueError("trailing bytes in frag.garc")
    return meta, frags


def _rebuild_vertex_map(all_oids, fnum: int, vp: int, spec) -> VertexMap:
    """Rebuild the exact fid assignment from per-fragment oid lists
    (oids_f belongs to fragment f) — shared by both cache formats."""
    from libgrape_lite_tpu.utils.id_parser import IdParser
    from libgrape_lite_tpu.vertex_map.idxer import make_idxer
    from libgrape_lite_tpu.vertex_map.partitioner import ExplicitPartitioner

    idxers = [make_idxer(spec.idxer_type, o) for o in all_oids]
    id_parser = IdParser(fnum, vp)
    flat_oids = np.concatenate(all_oids) if all_oids else np.zeros(0, np.int64)
    flat_fids = np.concatenate(
        [np.full(len(o), f, dtype=np.int64) for f, o in enumerate(all_oids)]
    ) if all_oids else np.zeros(0, np.int64)
    part = ExplicitPartitioner(flat_oids, flat_fids)
    part.fnum = fnum
    return VertexMap(part, idxers, id_parser)


def _deserialize_fragment(
    cache: str, comm_spec: CommSpec, spec: LoadGraphSpec
) -> ShardedEdgecutFragment:
    from libgrape_lite_tpu.graph.csr import CSR

    if os.path.exists(os.path.join(cache, "frag.garc")):
        meta, frags = _read_garc(cache)
        fnum = meta["fnum"]
        if fnum != comm_spec.fnum:
            raise ValueError(
                f"serialized fnum={fnum} != requested {comm_spec.fnum}"
            )
        # the content hash normally guarantees these, but a moved or
        # hand-assembled cache must fail HERE, not as a tracer error
        # deep inside the first query
        if spec.weighted and not meta["weighted"]:
            raise ValueError(
                "serialized fragment has no edge weights but the app "
                "requires them (spec.weighted=True); re-serialize from "
                "a weighted load"
            )
        if bool(meta["directed"]) != bool(spec.directed):
            raise ValueError(
                f"serialized directed={meta['directed']} != requested "
                f"{spec.directed}"
            )
        vp = meta["vp"]
        directed, weighted = meta["directed"], meta["weighted"]
        vm = _rebuild_vertex_map(
            [e["oids"] for e in frags], fnum, vp, spec
        )

        def csr_from(e, side):
            indptr, src, nbr, mask, ne, w = e[side]
            return CSR(
                indptr=indptr, edge_src=src, edge_nbr=nbr, edge_w=w,
                edge_mask=mask, num_rows=vp, num_edges=ne,
            )

        host_oe = [csr_from(e, "oe") for e in frags]
        host_ie = (
            host_oe if meta["aliased"]
            else [csr_from(e, "ie") for e in frags]
        )
        dev = ShardedEdgecutFragment._device_put(
            comm_spec, vm, host_oe, host_ie, vp, directed,
            meta["total_vnum"], meta["total_enum"],
        )
        return ShardedEdgecutFragment(
            comm_spec, vm, dev, host_oe, host_ie, directed, weighted
        )

    # legacy npz caches written before the garc format.  Pickle is only
    # required for object (string-oid) arrays; for the common int-oid
    # case refuse pickled payloads outright so a crafted cache file
    # can't execute code.  string_id=True legacy caches therefore
    # require a trusted serialization_prefix — re-serialize to get the
    # pickle-free garc format.
    # retry only the open (where stale network-FS handles bite); the
    # file object keeps np.load's lazy per-member reads — buffering the
    # whole multi-GB archive would double peak RSS at RMAT-24 scale
    from libgrape_lite_tpu.ft.retry import (
        CACHE_READ_POLICY, is_transient_io_error, with_retries,
    )

    npz_path = os.path.join(cache, "frag.npz")
    fh = with_retries(
        lambda: open(npz_path, "rb"),
        policy=CACHE_READ_POLICY,
        retryable=is_transient_io_error,
        describe=f"npz cache open {npz_path}",
    )
    z = np.load(fh, allow_pickle=bool(spec.string_id))
    fnum = int(z["fnum"])
    if fnum != comm_spec.fnum:
        raise ValueError(
            f"serialized fnum={fnum} != requested {comm_spec.fnum}"
        )
    vp = int(z["vp"])
    directed = bool(z["directed"])
    weighted = bool(z["weighted"])
    # same moved-cache guards as the garc branch
    if spec.weighted and not weighted:
        raise ValueError(
            "serialized fragment has no edge weights but the app "
            "requires them (spec.weighted=True); re-serialize from a "
            "weighted load"
        )
    if directed != bool(spec.directed):
        raise ValueError(
            f"serialized directed={directed} != requested "
            f"{spec.directed}"
        )

    vm = _rebuild_vertex_map(
        [z[f"oids_{f}"] for f in range(fnum)], fnum, vp, spec
    )

    def csr_of(side, f):
        return CSR(
            indptr=z[f"{side}_indptr_{f}"],
            edge_src=z[f"{side}_src_{f}"],
            edge_nbr=z[f"{side}_nbr_{f}"],
            edge_w=z[f"{side}_w_{f}"] if f"{side}_w_{f}" in z else None,
            edge_mask=z[f"{side}_mask_{f}"],
            num_rows=vp,
            num_edges=int(z[f"{side}_ne_{f}"]),
        )

    aliased = bool(z["aliased"]) if "aliased" in z else False
    host_oe = [csr_of("oe", f) for f in range(fnum)]
    host_ie = host_oe if aliased else [csr_of("ie", f) for f in range(fnum)]
    dev = ShardedEdgecutFragment._device_put(
        comm_spec, vm, host_oe, host_ie, vp, directed,
        int(z["total_vnum"]), int(z["total_enum"]),
    )
    return ShardedEdgecutFragment(
        comm_spec, vm, dev, host_oe, host_ie, directed, weighted
    )
