"""1-D edge-cut vs 2-D vertex-cut partition planner (ROADMAP item 2).

The pack planner prices its kernels from a static cost ledger; this
module applies the same discipline one level up: given the host edge
list, price BOTH partition layouts and choose — `GRAPE_PARTITION`:

  * unset / "" / "0" / "1d"  — 1-D edge-cut, the serial path,
    bit-for-bit untouched (lowered-HLO pinned in
    tests/test_partition2d.py);
  * "2d"                      — force the 2-D vertex-cut path when the
    app/geometry is eligible (hard error otherwise would hide the
    reason: ineligibility DECLINES with the reason recorded, and the
    1-D path runs);
  * "auto"                    — engage 2-D only when the modeled round
    cost wins.

Cost model (constants shared with parallel/pipeline.py — one set of
modeled rates, not private copies):

  t_1d = max_shard_edges_padded * ops_per_edge / VPU_rate
         + gather_bytes / ICI          (mirror.exchange_bytes_ledger)
  t_2d = max_tile_edges_padded  * ops_per_edge / VPU_rate
         + vc2d_bytes / ICI            (mirror.vc2d_exchange_bytes)

Both compute terms are PADDED maxima: SPMD blocks are uniform, so
every shard/tile pays the most-loaded one's capacity — exactly the
hub pathology being priced (docs/SCALE_NOTES.md: a degree-correlated
1-D cut pads every shard to the hub shard's Ep; the vertex-cut splits
each hub's edges across its tile column).  Decisions and decline
reasons land in PARTITION_STATS — like resolve_pipeline, never
silent.
"""

from __future__ import annotations

import os

import numpy as np

from libgrape_lite_tpu.parallel.mirror import (
    exchange_bytes_ledger,
    vc2d_exchange_bytes,
)
from libgrape_lite_tpu.ops.calibration import active_profile
from libgrape_lite_tpu.parallel.pipeline import DEFAULT_OPS_PER_EDGE

# 1-D app name -> its registered 2-D vertex-cut twin.  min-fold apps
# are byte-identical to the 1-D pull; PageRankVC's sum fold is
# eps-identical (float partials regroup — the documented pipeline-SUM
# class of decline, accepted here because PageRank is verified by eps
# everywhere already).
VC2D_APPS = {
    "sssp": "sssp_vc",
    "bfs": "bfs_vc",
    "wcc": "wcc_vc",
    "pagerank": "pagerank_vc",
}

# federated as "partition" (obs/federation.py); mutation sites unchanged
from libgrape_lite_tpu.obs.federation import FederatedStats as _FedStats

PARTITION_STATS = _FedStats("partition", {
    "resolved_2d": 0,     # decisions that engaged the 2-D path
    "declined": 0,        # 2d/auto requested but ineligible or priced out
    "last_decision": None,
})


# one set of padding helpers: the modeled vp/capacity terms below
# must round exactly the way the real fragment builders do, or the
# cost comparison drifts from the bill the shards actually pay
from libgrape_lite_tpu.fragment.edgecut import (  # noqa: E402
    _next_pow2,
    _round_up,
)


def partition_mode() -> str:
    """1d | 2d | auto from GRAPE_PARTITION (default 1d: the serial
    edge-cut path stays the compiled program).  Unrecognized values
    fall back to 1d WITH a log line — a typo must not silently
    downgrade a forced 2d to auto (mirror.resolve_mirror_plan
    discipline)."""
    v = (os.environ.get("GRAPE_PARTITION", "") or "1d").strip().lower()
    if v in ("", "0", "off", "1d"):
        return "1d"
    if v == "2d":
        return "2d"
    if v in ("auto", "1"):
        return "auto"
    from libgrape_lite_tpu.utils import logging as glog

    glog.log_info(
        f"GRAPE_PARTITION={v!r} is not one of 1d|2d|auto; using 1d"
    )
    return "1d"


def modeled_costs(src: np.ndarray, dst: np.ndarray, n_vertices: int,
                  fnum: int, *, directed: bool = False,
                  itemsize: int = 4,
                  ops_per_edge: float | None = None,
                  profile=None) -> dict:
    """Price one round of the pull under both layouts.  `src`/`dst`
    are the RAW oid edge list (symmetrised internally when
    undirected, matching both loaders); shard/tile assignment follows
    the contiguous-range conventions of the map partitioner and
    VCPartitioner.  `itemsize` defaults to the f32 payload convention
    BOTH byte ledgers share (mirror.exchange_bytes_ledger) — mixing
    conventions here would bias the 1-D-vs-2-D comparison.  Rates come
    from `profile` (default: the active RateProfile)."""
    p = profile or active_profile()
    ope = DEFAULT_OPS_PER_EDGE if ops_per_edge is None else ops_per_edge
    rate = p.vpu_lanes_per_cycle * p.clock_hz
    s = np.asarray(src)
    d = np.asarray(dst)
    if not directed:
        s, d = np.concatenate([s, d]), np.concatenate([d, s])

    # 1-D: contiguous oid blocks (map/segmented partitioner), in-CSR
    # rows = destination owner; every shard pays the padded max Ep
    shard_w = max(1, -(-n_vertices // fnum))
    shard_counts = np.bincount(
        np.minimum(d // shard_w, fnum - 1), minlength=fnum
    )
    max_shard = int(shard_counts.max())
    vp = _next_pow2(max(shard_w, 8))
    # fnum == 1 has NO exchange on either layout (the ledger's
    # fnum*vp convention would bill a phantom gather and bias auto
    # toward a pointless 2-D swap)
    bytes_1d = (
        exchange_bytes_ledger(fnum, vp)["gather"] if fnum > 1 else 0
    )
    t_1d = _round_up(max_shard, 128) * ope / rate + bytes_1d / p.ici_bps

    # 2-D: k x k oid-range tiles (VCPartitioner); one dst-side pull
    # per round on the symmetrised storage (two orientations when the
    # directed graph must pull both, i.e. WCC — priced by the caller
    # via `pulls` if needed; the default single pull covers
    # SSSP/BFS/undirected)
    k = int(round(np.sqrt(fnum)))
    out = {
        "1d": {
            "max_shard_edges": max_shard,
            "exchange_bytes": bytes_1d,
            "t_round_s": t_1d,
        },
    }
    if k * k == fnum and k >= 1:
        chunk = max(1, -(-n_vertices // k))
        vc = _round_up(chunk, 128)
        tile = np.minimum(s // chunk, k - 1) * k + np.minimum(
            d // chunk, k - 1
        )
        tile_counts = np.bincount(tile, minlength=k * k)
        max_tile = int(tile_counts.max())
        bytes_2d = vc2d_exchange_bytes(k, vc, itemsize=itemsize)
        t_2d = (
            _round_up(max_tile, 128) * ope / rate + bytes_2d / p.ici_bps
        )
        out["2d"] = {
            "k": k,
            "max_tile_edges": max_tile,
            "exchange_bytes": bytes_2d,
            "t_round_s": t_2d,
        }
    return out


def precheck_partition(app_name: str, fnum: int, *,
                       directed: bool = False,
                       string_id: bool = False) -> str | None:
    """The eligibility checks that need NO edge data (decline reason,
    or None = structurally eligible).  Shared by `resolve_partition`
    and the runner's probe gate, so the runner can record a cheap
    decline WITHOUT reading a possibly multi-GB edge file first."""
    if app_name not in VC2D_APPS:
        return (
            f"no 2-D vertex-cut implementation for {app_name!r} "
            f"(known: {sorted(VC2D_APPS)})"
        )
    k = int(round(np.sqrt(fnum)))
    if k * k != fnum:
        return f"fnum={fnum} is not a perfect square"
    if string_id:
        return (
            "string ids: the vertex-cut fragment is specialized to "
            "integer oids (reference immutable_vertexcut_fragment.h)"
        )
    if directed and app_name == "pagerank":
        return (
            "pagerank_vc accumulates both directions (the reference's "
            "undirected gather-scatter semantics); the directed 1-D "
            "formulation has no 2-D twin"
        )
    return None


def resolve_partition(app_name: str, fnum: int, src: np.ndarray,
                      dst: np.ndarray, oids: np.ndarray, *,
                      directed: bool = False, string_id: bool = False,
                      mode: str | None = None, eligible: bool = True,
                      reason: str = "") -> dict:
    """The partition decision for one (app, graph, fnum) — returns the
    recorded decision dict ({"mode": "1d"|"2d", "engaged": bool,
    "costs": ..., "reason": ...}); every 2d/auto request that lands on
    1-D carries its decline reason (resolve_pipeline discipline).
    `eligible=False` + `reason` lets a caller record a decline the
    planner cannot see itself (e.g. a delta-mutation load)."""
    from libgrape_lite_tpu.utils import logging as glog

    mode = partition_mode() if mode is None else mode
    prof = active_profile()
    decision = {
        "app": app_name, "requested": mode, "fnum": fnum,
        "mode": "1d", "engaged": False, "profile": prof.label(),
    }

    def declined(why: str, count: bool = True):
        decision["reason"] = why
        PARTITION_STATS["last_decision"] = decision
        if count:
            PARTITION_STATS["declined"] += 1
            glog.vlog(
                1, "partition: 2d declined for %s: %s", app_name, why
            )
        return decision

    if mode == "1d":
        return declined("GRAPE_PARTITION off (1d)", count=False)
    if not eligible:
        return declined(reason or "caller declared ineligible")
    why = precheck_partition(
        app_name, fnum, directed=directed, string_id=string_id
    )
    if why is not None:
        return declined(why)
    k = int(round(np.sqrt(fnum)))
    n_vertices = int(np.asarray(oids).max()) + 1 if len(oids) else 1
    costs = modeled_costs(src, dst, n_vertices, fnum,
                          directed=directed, profile=prof)
    decision["costs"] = costs
    if "2d" not in costs:
        return declined("cost model found no k^2 tiling")
    if mode == "auto" and costs["2d"]["t_round_s"] >= costs["1d"][
        "t_round_s"
    ]:
        return declined(
            "modeled 2-D round cost "
            f"{costs['2d']['t_round_s']:.3e}s does not beat 1-D "
            f"{costs['1d']['t_round_s']:.3e}s (balanced cut or k too "
            "small for the byte win; GRAPE_PARTITION=2d forces)"
        )
    decision["mode"] = "2d"
    decision["engaged"] = True
    PARTITION_STATS["resolved_2d"] += 1
    PARTITION_STATS["last_decision"] = decision
    glog.vlog(
        1, "partition: 2d engaged for %s (k=%d, max tile %d vs max "
        "shard %d edges, %d vs %d exchange B/round)",
        app_name, k, costs["2d"]["max_tile_edges"],
        costs["1d"]["max_shard_edges"], costs["2d"]["exchange_bytes"],
        costs["1d"]["exchange_bytes"],
    )
    return decision
