"""Vertex-cut (2-D edge partition) fragments.

Re-design of `grape/fragment/immutable_vertexcut_fragment.h:40-349` +
`VCPartitioner` (`grape/vertex_map/partitioner.h:269-330`): fnum must be
k^2; edge (src, dst) lands on fragment (src_chunk * k + dst_chunk);
vertex masters are 1-D oid-range chunks (the reference specialises to
uint64 oids, i.e. the oid value space is the vertex space — same here).

TPU layout: fragment (i, j) holds a padded COO block of edges whose
endpoints are *global padded ids* gpid = chunk * Vc + offset (Vc =
padded chunk width), stacked [fnum, Ep] and sharded over the 1-D frag
mesh axis (fid = i*k + j).  Master state is mesh-replicated — the
gather-scatter manager's GatherToMaster becomes a single `psum` of
scatter-reduced per-fragment partials, ScatterToFragment is free
(replication).  A SUMMA-style 2-axis (row, col) sharding of master
state with `ppermute` transposes is the planned memory-lean successor.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from libgrape_lite_tpu.parallel.comm_spec import CommSpec


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["src", "dst", "w", "mask"],
    meta_fields=["fnum", "k", "vc", "chunk"],
)
@dataclass
class VCDeviceFragment:
    """Stacked [fnum, Ep] COO blocks (or a per-shard view inside
    shard_map — the reference's GetEdgesOfBucket spans)."""

    src: jax.Array  # [fnum, Ep] int32 gpid
    dst: jax.Array  # [fnum, Ep] int32 gpid
    w: jax.Array | None  # [fnum, Ep] or None
    mask: jax.Array  # [fnum, Ep] bool
    fnum: int
    k: int
    vc: int  # padded chunk width
    chunk: int  # real chunk width (oid space / k)

    @property
    def n_pad(self) -> int:
        return self.k * self.vc

    def local(self) -> "VCDeviceFragment":
        return VCDeviceFragment(
            src=self.src[0], dst=self.dst[0],
            w=None if self.w is None else self.w[0],
            mask=self.mask[0],
            fnum=self.fnum, k=self.k, vc=self.vc, chunk=self.chunk,
        )


class ImmutableVertexcutFragment:
    """Host descriptor for the full 2-D partitioned graph."""

    def __init__(self, comm_spec, dev, oids, k, vc, chunk, total_enum):
        self.comm_spec = comm_spec
        self.dev = dev
        self.k = k
        self.vc = vc
        self.chunk = chunk
        self.fnum = k * k
        self.vp = vc  # chunk width, for Worker result shapes
        self.total_enum = total_enum
        self._oids = np.asarray(oids)
        self._chunk_oids = [
            np.sort(self._oids[(self._oids // chunk) == c]) for c in range(k)
        ]
        self.total_vnum = len(self._oids)

    def oid_to_gpid(self, oids: np.ndarray) -> np.ndarray:
        oids = np.asarray(oids)
        return (oids // self.chunk) * self.vc + (oids % self.chunk)

    def vertex_mask(self) -> np.ndarray:
        """[k * vc] bool: which gpid slots are real vertices."""
        m = np.zeros(self.k * self.vc, dtype=bool)
        m[self.oid_to_gpid(self._oids)] = True
        return m

    # masters: the diagonal fragment (c, c) owns chunk c
    # (reference partitioner.h:269-330 master placement)
    def inner_vertices_num(self, fid: int) -> int:
        i, j = divmod(fid, self.k)
        return len(self._chunk_oids[i]) if i == j else 0

    def inner_oids(self, fid: int) -> np.ndarray:
        i, j = divmod(fid, self.k)
        return self._chunk_oids[i] if i == j else np.zeros(0, np.int64)

    @classmethod
    def build(
        cls,
        comm_spec: CommSpec,
        oids: np.ndarray,
        src_oid: np.ndarray,
        dst_oid: np.ndarray,
        weights: np.ndarray | None = None,
        edata_dtype=np.float64,
    ) -> "ImmutableVertexcutFragment":
        fnum = comm_spec.fnum
        k = int(round(np.sqrt(fnum)))
        if k * k != fnum:
            raise ValueError(f"vertex-cut needs fnum = k^2, got {fnum}")
        space = int(np.asarray(oids).max()) + 1 if len(oids) else 1
        chunk = (space + k - 1) // k
        vc = _round_up(chunk, 128)

        src = np.asarray(src_oid)
        dst = np.asarray(dst_oid)
        bad = (src < 0) | (src >= space) | (dst < 0) | (dst >= space)
        if bad.any():
            ex = np.stack([src[bad], dst[bad]], 1)[:3]
            raise ValueError(
                f"edge endpoint(s) outside the vertex oid space "
                f"[0, {space}), e.g. {ex.tolist()} — the vertex-cut "
                "fragment requires dense oid ids covering all endpoints"
            )
        # space <= k*chunk, so // chunk is already < k
        sc = src // chunk
        dc = dst // chunk
        fid = sc * k + dc
        counts = np.bincount(fid, minlength=fnum)
        ep = _round_up(max(int(counts.max()), 1), 128)

        s_arr = np.zeros((fnum, ep), dtype=np.int32)
        d_arr = np.zeros((fnum, ep), dtype=np.int32)
        w_arr = None if weights is None else np.zeros((fnum, ep), edata_dtype)
        m_arr = np.zeros((fnum, ep), dtype=bool)
        sg = (sc * vc + src % chunk).astype(np.int32)
        dg = (dc * vc + dst % chunk).astype(np.int32)
        for f in range(fnum):
            sel = fid == f
            n = int(sel.sum())
            s_arr[f, :n] = sg[sel]
            d_arr[f, :n] = dg[sel]
            if w_arr is not None:
                w_arr[f, :n] = np.asarray(weights)[sel]
            m_arr[f, :n] = True

        shard = comm_spec.sharded()

        def put(x):
            return None if x is None else jax.device_put(jnp.asarray(x), shard)

        dev = VCDeviceFragment(
            src=put(s_arr), dst=put(d_arr), w=put(w_arr), mask=put(m_arr),
            fnum=fnum, k=k, vc=vc, chunk=chunk,
        )
        return cls(comm_spec, dev, oids, k, vc, chunk, len(src))
