"""Vertex-cut (2-D edge partition) fragments.

Re-design of `grape/fragment/immutable_vertexcut_fragment.h:40-349` +
`VCPartitioner` (`grape/vertex_map/partitioner.h:269-330`): fnum must be
k^2; edge (src, dst) lands on fragment (src_chunk * k + dst_chunk);
vertex masters are 1-D oid-range chunks (the reference specialises to
uint64 oids, i.e. the oid value space is the vertex space — same here).

TPU layout: fragment (i, j) holds a padded COO block of edges whose
endpoints are *global padded ids* gpid = chunk * Vc + offset (Vc =
padded chunk width), stacked [fnum, Ep] and sharded over the 1-D frag
mesh axis (fid = i*k + j).  Master state is mesh-replicated — the
gather-scatter manager's GatherToMaster becomes a single `psum` of
scatter-reduced per-fragment partials, ScatterToFragment is free
(replication).  A SUMMA-style 2-axis (row, col) sharding of master
state with `ppermute` transposes is the planned memory-lean successor.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import numpy as np

from libgrape_lite_tpu.obs.federation import FederatedStats
from libgrape_lite_tpu.parallel.comm_spec import CommSpec, put_global


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


# 2-D tile fill / pad-waste observability (OpenMetrics via the obs
# federation, namespace "vc_tiles"): the vertex-cut analogue of the
# rebalancer's before/after edge-skew record — every tile_stats() scan
# publishes the latest fill profile so 2-D skew is scrapeable
VC_TILE_STATS = FederatedStats("vc_tiles", {
    "scans": 0,
    "tiles": 0,
    "edge_slots": 0,        # padded COO slots per tile (Ep)
    "edges": 0,             # real edges across all tiles
    "pad_slots": 0,         # fnum*Ep - edges: allocated-but-dead slots
    "pad_waste_frac": 0.0,  # pad_slots / (fnum*Ep)
    "min_fill_frac": 0.0,
    "mean_fill_frac": 0.0,
    "max_fill_frac": 0.0,
    "tile_skew": 0.0,
})


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["src", "dst", "w", "mask"],
    meta_fields=["fnum", "k", "vc", "chunk", "total_vnum"],
)
@dataclass
class VCDeviceFragment:
    """Stacked [fnum, Ep] COO blocks (or a per-shard view inside
    shard_map — the reference's GetEdgesOfBucket spans)."""

    src: jax.Array  # [fnum, Ep] int32 gpid
    dst: jax.Array  # [fnum, Ep] int32 gpid
    w: jax.Array | None  # [fnum, Ep] or None
    mask: jax.Array  # [fnum, Ep] bool
    fnum: int
    k: int
    vc: int  # padded chunk width
    chunk: int  # real chunk width (oid space / k)
    # real vertex count (guard/ monitor's active-range ceiling)
    total_vnum: int = 0

    @property
    def n_pad(self) -> int:
        return self.k * self.vc

    def local(self) -> "VCDeviceFragment":
        return VCDeviceFragment(
            src=self.src[0], dst=self.dst[0],
            w=None if self.w is None else self.w[0],
            mask=self.mask[0],
            fnum=self.fnum, k=self.k, vc=self.vc, chunk=self.chunk,
            total_vnum=self.total_vnum,
        )


class ImmutableVertexcutFragment:
    """Host descriptor for the full 2-D partitioned graph."""

    def __init__(self, comm_spec, dev, oids, k, vc, chunk, total_enum,
                 directed: bool = True, weighted: bool = False,
                 symmetrized: bool = False):
        self.comm_spec = comm_spec
        self.dev = dev
        self.k = k
        self.vc = vc
        self.chunk = chunk
        self.fnum = k * k
        self.vp = vc  # chunk width, for Worker result shapes
        self.total_enum = total_enum
        self._oids = np.asarray(oids)
        self._chunk_oids = [
            np.sort(self._oids[(self._oids // chunk) == c]) for c in range(k)
        ]
        self.total_vnum = len(self._oids)
        # traversal semantics of the stored tile blocks: `directed`
        # mirrors the loader flag; `symmetrized` says the blocks hold
        # BOTH (u,v) and (v,u) per input edge (min-fold pulls use one
        # dst-side pull per round — the 1-D undirected-CSR convention);
        # PageRankVC-style gather-scatter apps keep raw storage and
        # accumulate both directions in-app instead
        self.directed = directed
        self.weighted = weighted
        self.symmetrized = symmetrized
        self._host_csrs = {}

    def oid_to_gpid(self, oids: np.ndarray) -> np.ndarray:
        oids = np.asarray(oids)
        return (oids // self.chunk) * self.vc + (oids % self.chunk)

    def gpid_to_oid(self, gpids: np.ndarray) -> np.ndarray:
        """Inverse of `oid_to_gpid` — gpid order is oid order (chunks
        are contiguous oid ranges and offset < chunk <= vc), which is
        what makes the 2-D WCC representative the min-OID member."""
        gpids = np.asarray(gpids)
        return (gpids // self.vc) * self.chunk + (gpids % self.vc)

    def vertex_mask(self) -> np.ndarray:
        """[k * vc] bool: which gpid slots are real vertices."""
        m = np.zeros(self.k * self.vc, dtype=bool)
        m[self.oid_to_gpid(self._oids)] = True
        return m

    # ---- per-tile CSR views -------------------------------------------
    #
    # The pack planner (ops/spmv_pack.resolve_pack_dispatch) and the ft
    # fingerprint read fragments through the host_ie/host_oe CSR-list
    # protocol; the vertex-cut tiles expose the same shape so the MXU
    # scan / stream-diet machinery of PRs 2/4 applies per tile:
    #   host_ie[f]: rows = dst offsets in chunk-j space, cols = src
    #               offsets in chunk-i space (the dst-side pull whose
    #               gather table is the [vc] column-broadcast chunk);
    #   host_oe[f]: the transposed orientation (src-side pull — the
    #               directed-WCC second direction).
    # Both index LOCAL [vc] tables, so pack plans are built with
    # n_cols = vc (`pack_n_cols`), not fnum * vp.

    @property
    def pack_n_cols(self) -> int:
        return self.vc

    def _tile_csrs(self, orientation: str):
        if orientation in self._host_csrs:
            return self._host_csrs[orientation]
        from libgrape_lite_tpu.graph.csr import build_csr

        s_arr, d_arr, w_arr, m_arr = self._host_tiles
        rows_all, cols_all = (
            (d_arr, s_arr) if orientation == "ie" else (s_arr, d_arr)
        )
        csrs = []
        ep = s_arr.shape[1]
        for f in range(self.fnum):
            m = m_arr[f]
            csrs.append(build_csr(
                (rows_all[f][m] % self.vc).astype(np.int64),
                (cols_all[f][m] % self.vc).astype(np.int64),
                None if w_arr is None else w_arr[f][m],
                self.vc, ep,
            ))
        self._host_csrs[orientation] = csrs
        return csrs

    @property
    def host_ie(self):
        return self._tile_csrs("ie")

    @property
    def host_oe(self):
        return self._tile_csrs("oe")

    def tile_stats(self) -> dict:
        """Per-tile real edge counts + the skew summary the planner,
        the bench `partition2d` lane and trace_report all read —
        the 2-D analogue of edgecut's partition-skew warning.  HOST
        data only (`_host_tiles`): under jax.distributed the device
        tiles span non-addressable devices and cannot be fetched (the
        PR 18 edgecut.inner_vertices_num bug class).  Also publishes
        the fill / pad-waste profile into the "vc_tiles" federation
        namespace so 2-D skew is scrapeable like the rebalancer's
        edge-skew record."""
        _, _, _, m_arr = self._host_tiles
        ep = int(m_arr.shape[1])
        counts = m_arr.sum(axis=1).astype(int)
        mean = max(float(counts.mean()), 1.0)
        fills = counts / max(ep, 1)
        edges = int(counts.sum())
        pad = self.fnum * ep - edges
        skew = round(float(counts.max()) / mean, 3)
        VC_TILE_STATS["scans"] += 1
        VC_TILE_STATS.update({
            "tiles": self.fnum,
            "edge_slots": ep,
            "edges": edges,
            "pad_slots": pad,
            "pad_waste_frac": round(pad / max(self.fnum * ep, 1), 4),
            "min_fill_frac": round(float(fills.min()), 4),
            "mean_fill_frac": round(float(fills.mean()), 4),
            "max_fill_frac": round(float(fills.max()), 4),
            "tile_skew": skew,
        })
        return {
            "k": self.k,
            "per_tile": [
                {"tile": f, "row": f // self.k, "col": f % self.k,
                 "edges": int(c), "fill_frac": round(float(fr), 4)}
                for f, (c, fr) in enumerate(zip(counts, fills))
            ],
            "max_tile_edges": int(counts.max()),
            "mean_tile_edges": round(mean, 1),
            "tile_skew": skew,
            "edge_slots": ep,
            "pad_slots": pad,
            "pad_waste_frac": round(pad / max(self.fnum * ep, 1), 4),
        }

    # masters: the diagonal fragment (c, c) owns chunk c
    # (reference partitioner.h:269-330 master placement).  Both reads
    # are HOST-side (`_chunk_oids` from the build-time oid array) by
    # audit: the device tiles span non-addressable devices under
    # jax.distributed and must never back these (the bug class PR 18
    # fixed in edgecut.inner_vertices_num).
    def inner_vertices_num(self, fid: int) -> int:
        i, j = divmod(fid, self.k)
        return len(self._chunk_oids[i]) if i == j else 0

    def inner_oids(self, fid: int) -> np.ndarray:
        i, j = divmod(fid, self.k)
        return self._chunk_oids[i] if i == j else np.zeros(0, np.int64)

    # ---- device residency (fleet/ eviction, docs/FLEET.md) ----

    def _place_tiles(self) -> "VCDeviceFragment":
        """Deterministic device placement of the host tile blocks —
        shared by build and restore_device, so a restored fragment's
        content is byte-identical to the evicted one.  put_global (not
        bare device_put): under jax.distributed the frag sharding
        spans non-addressable devices and device_put would throw (the
        same multi-process contract every 1-D placement site honors)."""
        s_arr, d_arr, w_arr, m_arr = self._host_tiles
        shard = self.comm_spec.sharded()

        def put(x):
            return put_global(x, shard)

        return VCDeviceFragment(
            src=put(s_arr), dst=put(d_arr), w=put(w_arr),
            mask=put(m_arr),
            fnum=self.fnum, k=self.k, vc=self.vc, chunk=self.chunk,
            total_vnum=self.total_vnum,
        )

    def release_device(self) -> bool:
        """Evict: delete the stacked COO tile buffers and drop `dev`.
        Every host artifact survives — `_host_tiles`, the cached
        per-tile CSR views, the pack-plan cache weak-keyed on THIS
        object — so `restore_device` re-places byte-identical content
        with zero pack re-planning (the 1-D fleet contract).  Returns
        False when already released."""
        if self.dev is None:
            return False
        seen = set()
        for leaf in jax.tree_util.tree_leaves(self.dev):
            if leaf is None or id(leaf) in seen:
                continue
            seen.add(id(leaf))
            delete = getattr(leaf, "delete", None)
            if callable(delete):
                try:
                    delete()
                except Exception:
                    pass  # committed/donated buffers: GC frees them
        self.dev = None
        return True

    def restore_device(self) -> bool:
        """Re-admission: re-place the device tiles from `_host_tiles`
        (deterministic, byte-identical to the evicted arrays).
        Returns False when already resident."""
        if self.dev is not None:
            return False
        self.dev = self._place_tiles()
        return True

    @classmethod
    def build(
        cls,
        comm_spec: CommSpec,
        oids: np.ndarray,
        src_oid: np.ndarray,
        dst_oid: np.ndarray,
        weights: np.ndarray | None = None,
        edata_dtype=np.float64,
        directed: bool = True,
        symmetrize: bool = False,
    ) -> "ImmutableVertexcutFragment":
        """`symmetrize=True` stores BOTH (u,v) -> tile (cu,cv) and
        (v,u) -> tile (cv,cu) per input edge, so one dst-side pull per
        round covers the undirected traversal (the 1-D loader's
        symmetrised-CSR convention; min folds stay byte-identical).
        The default keeps raw storage — the seed contract PageRankVC's
        both-direction gather-scatter accumulation depends on."""
        fnum = comm_spec.fnum
        k = int(round(np.sqrt(fnum)))
        if k * k != fnum:
            raise ValueError(f"vertex-cut needs fnum = k^2, got {fnum}")
        space = int(np.asarray(oids).max()) + 1 if len(oids) else 1
        chunk = (space + k - 1) // k
        vc = _round_up(chunk, 128)

        src = np.asarray(src_oid)
        dst = np.asarray(dst_oid)
        real_enum = len(src)
        if symmetrize:
            src, dst = (
                np.concatenate([src, dst]), np.concatenate([dst, src])
            )
            if weights is not None:
                weights = np.concatenate([weights, weights])
        bad = (src < 0) | (src >= space) | (dst < 0) | (dst >= space)
        if bad.any():
            ex = np.stack([src[bad], dst[bad]], 1)[:3]
            raise ValueError(
                f"edge endpoint(s) outside the vertex oid space "
                f"[0, {space}), e.g. {ex.tolist()} — the vertex-cut "
                "fragment requires dense oid ids covering all endpoints"
            )
        # space <= k*chunk, so // chunk is already < k
        sc = src // chunk
        dc = dst // chunk
        fid = sc * k + dc
        counts = np.bincount(fid, minlength=fnum)
        ep = _round_up(max(int(counts.max()), 1), 128)

        s_arr = np.zeros((fnum, ep), dtype=np.int32)
        d_arr = np.zeros((fnum, ep), dtype=np.int32)
        w_arr = None if weights is None else np.zeros((fnum, ep), edata_dtype)
        m_arr = np.zeros((fnum, ep), dtype=bool)
        sg = (sc * vc + src % chunk).astype(np.int32)
        dg = (dc * vc + dst % chunk).astype(np.int32)
        for f in range(fnum):
            sel = fid == f
            n = int(sel.sum())
            s_arr[f, :n] = sg[sel]
            d_arr[f, :n] = dg[sel]
            if w_arr is not None:
                w_arr[f, :n] = np.asarray(weights)[sel]
            m_arr[f, :n] = True

        out = cls(comm_spec, None, oids, k, vc, chunk, real_enum,
                  directed=directed, weighted=weights is not None,
                  symmetrized=symmetrize)
        # host tile blocks stay resident: the per-tile CSR views
        # (host_ie/host_oe), tile_stats, the ft content fingerprint and
        # fleet re-admission (restore_device) all read them — the
        # edge-cut fragment keeps its host CSRs the same way
        out._host_tiles = (s_arr, d_arr, w_arr, m_arr)
        out.dev = out._place_tiles()
        return out
