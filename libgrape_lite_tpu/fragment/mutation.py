"""Graph mutation: staged edits + rebuild.

Re-design of the reference mutation stack:
  * `BasicFragmentMutator` (`grape/fragment/basic_fragment_mutator.h`,
    520 LoC) — collects per-fragment add/remove lists, shuffles to
    owners, patches the CSR in place,
  * `EVFragmentMutator` (`ev_fragment_mutator.h`) — parses delta
    files: vfile ops `a oid [data]` / `d oid` / `u oid data`, efile ops
    `a src dst [w]` / `d src dst` / `u src dst w`; for undirected
    graphs `d`/`u` apply to both orientations
    (`ev_fragment_mutator.h:118-127`),
  * `LoadGraphAndMutate` (`grape/fragment/loader.h:59-68`).

TPU policy: **rebuild-on-mutate.**  Device arrays are immutable XLA
buffers with static shapes; in-place slack-capacity CSR surgery (the
reference's `DeMutableCSR`) buys nothing under jit — mutation instead
edits host edge arrays and rebuilds the padded shards, which also
re-amortises capacity planning.  Edits are applied *array-level*
(vectorised pair matching) before any device build, so a
load-and-mutate pays for exactly one build.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Tuple

import numpy as np

from libgrape_lite_tpu.fragment.edgecut import ShardedEdgecutFragment
from libgrape_lite_tpu.parallel.comm_spec import CommSpec
from libgrape_lite_tpu.vertex_map.partitioner import make_partitioner
from libgrape_lite_tpu.vertex_map.vertex_map import VertexMap


def _pair_match(src: np.ndarray, dst: np.ndarray, pairs) -> np.ndarray:
    """Vectorised membership of (src[i], dst[i]) in `pairs` (int64-exact)."""
    if not pairs:
        return np.zeros(len(src), dtype=bool)
    try:
        import pandas as pd

        idx = pd.MultiIndex.from_arrays([src, dst])
        return idx.isin(pairs)
    except Exception:
        pset = set(pairs)
        return np.fromiter(
            ((s, d) in pset for s, d in zip(src.tolist(), dst.tolist())),
            dtype=bool, count=len(src),
        )


def oid_row_alignment(old_frag, new_frag):
    """(of, ol, nf, nl): row coordinates aligning old_frag's
    [fnum, vp] per-vertex layout to new_frag's, matched by oid, for
    every vertex present in BOTH maps — the one migration rule shared
    by `AppBase.migrate_state` (mid-query MutationContext rebuilds)
    and `dyn.incremental.migrate_rows` (incremental-IncEval seeding)."""
    old_oids = (
        np.concatenate(
            [old_frag.inner_oids(f) for f in range(old_frag.fnum)]
        )
        if old_frag.fnum
        else np.zeros(0, np.int64)
    )
    if len(old_oids) == 0:
        z = np.zeros(0, np.int64)
        return z, z, z, z
    old_pids = old_frag.oid_to_pid(old_oids)
    new_pids = new_frag.oid_to_pid(old_oids)
    keep = (old_pids >= 0) & (new_pids >= 0)
    return (
        old_pids[keep] // old_frag.vp, old_pids[keep] % old_frag.vp,
        new_pids[keep] // new_frag.vp, new_pids[keep] % new_frag.vp,
    )


@dataclass
class BasicFragmentMutator:
    """Staged mutation set (reference basic_fragment_mutator.h API)."""

    add_vertices: List[int] = field(default_factory=list)
    remove_vertices: List[int] = field(default_factory=list)
    add_edges: List[Tuple[int, int, float]] = field(default_factory=list)
    remove_edges: List[Tuple[int, int]] = field(default_factory=list)
    update_edges: List[Tuple[int, int, float]] = field(default_factory=list)

    def AddVertex(self, oid: int, data=None) -> None:
        self.add_vertices.append(int(oid))

    def RemoveVertex(self, oid: int) -> None:
        self.remove_vertices.append(int(oid))

    def UpdateVertex(self, oid: int, data=None) -> None:
        pass  # vertex data is EmptyType throughout the LDBC apps

    def AddEdge(self, src: int, dst: int, w: float = 0.0) -> None:
        self.add_edges.append((int(src), int(dst), float(w)))

    def RemoveEdge(self, src: int, dst: int) -> None:
        self.remove_edges.append((int(src), int(dst)))

    def UpdateEdge(self, src: int, dst: int, w: float) -> None:
        self.update_edges.append((int(src), int(dst), float(w)))

    # ---- array-level application ----

    def apply_to_arrays(self, src, dst, w, oid_order):
        """Apply staged ops to host oid edge arrays + the ordered vertex
        universe; returns (src, dst, w, oids)."""
        src = np.asarray(src).copy()
        dst = np.asarray(dst).copy()
        w = None if w is None else np.asarray(w).copy()

        keep = np.ones(len(src), dtype=bool)
        removed_v = set(self.remove_vertices)
        if removed_v:
            rv = np.fromiter(removed_v, dtype=np.int64)
            keep &= ~np.isin(src, rv)
            keep &= ~np.isin(dst, rv)

        if self.remove_edges:
            keep &= ~_pair_match(src, dst, self.remove_edges)

        if self.update_edges and w is not None:
            upd_pairs = [(s, d) for s, d, _ in self.update_edges]
            hit = _pair_match(src, dst, upd_pairs)
            if hit.any():
                upd = {(s, d): x for s, d, x in self.update_edges}
                for i in np.nonzero(hit)[0]:
                    w[i] = upd[(int(src[i]), int(dst[i]))]

        src, dst = src[keep], dst[keep]
        if w is not None:
            w = w[keep]

        if self.add_edges:
            # ids staged as Python ints; build int64 columns directly so
            # oids above 2^53 never round-trip through float64
            a_src = np.array([s for s, _, _ in self.add_edges], dtype=np.int64)
            a_dst = np.array([d for _, d, _ in self.add_edges], dtype=np.int64)
            src = np.concatenate([src, a_src])
            dst = np.concatenate([dst, a_dst])
            if w is not None:
                a_w = np.array([x for _, _, x in self.add_edges], dtype=w.dtype)
                w = np.concatenate([w, a_w])

        # new vertex universe preserving load order (reference
        # VertexMap::ExtendVertices appends)
        oids = [o for o in np.asarray(oid_order).tolist() if o not in removed_v]
        seen = set(oids)
        for o in self.add_vertices:
            if o not in seen:
                oids.append(o)
                seen.add(o)
        return src, dst, w, np.asarray(oids, dtype=np.int64)

    def mutate(self, frag: ShardedEdgecutFragment) -> ShardedEdgecutFragment:
        """Apply staged ops and rebuild (reference MutateFragment)."""
        if frag.edge_list is None:
            raise ValueError(
                "fragment was not built mutable; load with "
                "retain_edge_list=True (LoadGraphAndMutate does this)"
            )
        src, dst, w = frag.edge_list
        old_order = (
            np.concatenate(
                [frag.vertex_map.inner_oids(f) for f in range(frag.fnum)]
            )
            if frag.fnum
            else np.zeros(0, np.int64)
        )
        src, dst, w, oids = self.apply_to_arrays(src, dst, w, old_order)
        spec = getattr(frag, "load_spec", None)
        return _build_edgecut(frag.comm_spec, oids, src, dst, w,
                              frag.directed, spec)


def _build_edgecut(comm_spec, oids, src, dst, w, directed, spec):
    from libgrape_lite_tpu.fragment.loader import LoadGraphSpec, _validate_load
    from libgrape_lite_tpu.utils.types import LoadStrategy

    spec = spec or LoadGraphSpec(directed=directed)
    partitioner = make_partitioner(spec.partitioner_type, comm_spec.fnum, oids)
    vm = VertexMap.build(oids, partitioner, idxer_type=spec.idxer_type)
    frag = ShardedEdgecutFragment.build(
        comm_spec, vm, src, dst, w,
        directed=directed,
        load_strategy=spec.load_strategy,
        vid_dtype=spec.vid_dtype,
        edata_dtype=spec.edata_dtype,
        retain_edge_list=True,
    )
    frag.load_spec = spec
    # the same GRAPE_VALIDATE_LOAD=1 gate every load/deserialize path
    # honors: a rebuild-on-mutate (delta apply, dyn/ repack) must not
    # be the one CSR construction that skips structural validation —
    # a tampered delta corrupts shards exactly like a tampered cache
    return _validate_load(frag)


def replicate_fragment(frag: ShardedEdgecutFragment) -> ShardedEdgecutFragment:
    """A fresh, content-identical sharded fragment built from `frag`'s
    retained host edge list — an EMPTY mutation through the rebuild
    machinery, so the replica gets its own host CSRs and device
    arrays (fleet/ replica routing: each replica must repack/reshard
    independently while siblings keep serving) while the deterministic
    build keeps results byte-identical across replicas."""
    return BasicFragmentMutator().mutate(frag)


def parse_delta_efile(path: str, weighted: bool, mutator: BasicFragmentMutator,
                      directed: bool) -> None:
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line[0] == "#":
                continue
            parts = line.split()
            op = parts[0]
            if op == "a":
                s, d = int(parts[1]), int(parts[2])
                w = float(parts[3]) if (weighted and len(parts) > 3) else 0.0
                mutator.AddEdge(s, d, w)
            elif op == "d":
                s, d = int(parts[1]), int(parts[2])
                mutator.RemoveEdge(s, d)
                if not directed:
                    mutator.RemoveEdge(d, s)
            elif op == "u":
                s, d = int(parts[1]), int(parts[2])
                w = float(parts[3]) if len(parts) > 3 else 0.0
                mutator.UpdateEdge(s, d, w)
                if not directed:
                    mutator.UpdateEdge(d, s, w)


def parse_delta_vfile(path: str, mutator: BasicFragmentMutator) -> None:
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line[0] == "#":
                continue
            parts = line.split()
            if parts[0] == "a":
                mutator.AddVertex(int(parts[1]))
            elif parts[0] == "d":
                mutator.RemoveVertex(int(parts[1]))
            elif parts[0] == "u":
                mutator.UpdateVertex(int(parts[1]))


def LoadGraphAndMutate(
    efile: str,
    vfile: str | None,
    delta_efile: str | None,
    delta_vfile: str | None,
    comm_spec: CommSpec,
    spec=None,
) -> ShardedEdgecutFragment:
    """reference `LoadGraphAndMutate` (`loader.h:59-68`).  The delta is
    applied to the parsed host arrays BEFORE the (single) device build."""
    from libgrape_lite_tpu.fragment.loader import LoadGraphSpec
    from libgrape_lite_tpu.io.line_parser import read_edge_file, read_vertex_file

    spec = spec or LoadGraphSpec()

    src, dst, w = read_edge_file(
        efile, weighted=spec.weighted, string_id=spec.string_id
    )
    if not spec.weighted:
        w = None
    if vfile:
        oids = read_vertex_file(vfile, string_id=spec.string_id)
    else:
        oids = np.unique(np.concatenate([src, dst]))

    mutator = BasicFragmentMutator()
    if delta_vfile:
        parse_delta_vfile(delta_vfile, mutator)
    if delta_efile:
        parse_delta_efile(delta_efile, spec.weighted, mutator, spec.directed)
    src, dst, w, oids = mutator.apply_to_arrays(src, dst, w, oids)
    return _build_edgecut(comm_spec, oids, src, dst, w, spec.directed, spec)
