"""Per-app / per-tenant latency SLOs with error-budget burn.

Objectives are p99-style latency targets in milliseconds, declared via
``GRAPE_SLO`` (or the serve CLI's ``--slo``) as a comma list:

    GRAPE_SLO="sssp=5,bfs=10,tenant:t0=50,*=100"

Keys resolve most-specific-first: ``tenant:<name>`` beats the app
key, the app key beats ``*``.  A query *breaches* when it failed or
its latency exceeded its objective.  A breach is **a traced instant
plus a federated counter, never an exception** — SLOs are a
measurement, not a control path; the serving loop must not change
behaviour because an objective exists.

Error budget: with allowed breach fraction ``f`` (default 1%,
``GRAPE_SLO_BUDGET``), the burn rate for a key is
``breaches / (observed * f)`` — burn 1.0 means the budget is spent
exactly as fast as it accrues; >1.0 means the objective is being
missed faster than the budget allows.  ``SLO_STATS`` federates under
the ``slo`` namespace, so burn is visible on a live ``/metrics``
scrape (``grape_stats_slo_burn_by_key{key="sssp"}``).

``observe()`` is the one hook, called from
``AdmissionQueue.deliver`` — the single bookkeeping site shared by
the synchronous loop, the async pump, and every fleet replica.  With
no objectives configured it is one falsy-dict check.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

from libgrape_lite_tpu.obs.federation import FederatedStats

SLO_ENV = "GRAPE_SLO"
SLO_BUDGET_ENV = "GRAPE_SLO_BUDGET"
DEFAULT_BUDGET_FRAC = 0.01

#: objective key -> latency objective (ms); "" when unconfigured
_OBJECTIVES: Dict[str, float] = {}
_BUDGET_FRAC = DEFAULT_BUDGET_FRAC

SLO_STATS = FederatedStats("slo", {
    "observed": 0,
    "breaches": 0,
    "budget_frac": DEFAULT_BUDGET_FRAC,
    "observed_by_key": {},
    "breaches_by_key": {},
    "burn_by_key": {},
    "objectives_ms": {},
    "max_burn": 0.0,
})


def parse_spec(spec: str) -> Dict[str, float]:
    """``"sssp=5,tenant:t0=50,*=100"`` -> {key: objective_ms}.

    Bad entries raise ValueError — an SLO typo should fail the CLI
    flag loudly at startup, not silently watch nothing.
    """
    out: Dict[str, float] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(f"bad SLO entry (want key=ms): {part!r}")
        key, _, ms = part.partition("=")
        key = key.strip()
        try:
            val = float(ms)
        except ValueError:
            raise ValueError(f"bad SLO objective (want ms): {part!r}")
        if not key or val <= 0:
            raise ValueError(f"bad SLO entry: {part!r}")
        out[key] = val
    return out


def configure(spec: Optional[str] = None,
              budget_frac: Optional[float] = None) -> None:
    """Install objectives (None/"" clears).  Resets SLO_STATS so burn
    counts against the new objectives only."""
    global _BUDGET_FRAC
    _OBJECTIVES.clear()
    if spec:
        _OBJECTIVES.update(parse_spec(spec))
    if budget_frac is not None:
        if not (0 < budget_frac <= 1):
            raise ValueError(
                f"SLO budget fraction out of (0, 1]: {budget_frac}")
        _BUDGET_FRAC = budget_frac
    SLO_STATS.reset()
    SLO_STATS["budget_frac"] = _BUDGET_FRAC
    SLO_STATS["objectives_ms"] = dict(_OBJECTIVES)


def maybe_configure_from_env() -> bool:
    """Arm from GRAPE_SLO / GRAPE_SLO_BUDGET when set."""
    spec = os.environ.get(SLO_ENV)
    if not spec:
        return False
    frac = None
    raw = os.environ.get(SLO_BUDGET_ENV)
    if raw:
        try:
            frac = float(raw)
        except ValueError:
            frac = None
    configure(spec, budget_frac=frac)
    return True


def configured() -> bool:
    return bool(_OBJECTIVES)


def objective_for(app: str,
                  tenant: Optional[str] = None) -> Optional[tuple]:
    """(key, objective_ms) for the most specific matching objective,
    or None: tenant:<t> > app > '*'."""
    if tenant is not None:
        key = f"tenant:{tenant}"
        ms = _OBJECTIVES.get(key)
        if ms is not None:
            return key, ms
    ms = _OBJECTIVES.get(app)
    if ms is not None:
        return app, ms
    ms = _OBJECTIVES.get("*")
    if ms is not None:
        return "*", ms
    return None


def observe(app: str, tenant: Optional[str], latency_s: float,
            ok: bool = True) -> None:
    """Count one delivered query against its objective.  Never raises;
    one falsy-dict check when no objectives are configured."""
    if not _OBJECTIVES:
        return
    hit = objective_for(app, tenant)
    if hit is None:
        return
    key, objective_ms = hit
    latency_ms = latency_s * 1e3
    SLO_STATS["observed"] += 1
    by_obs = SLO_STATS["observed_by_key"]
    by_obs[key] = by_obs.get(key, 0) + 1
    breached = (not ok) or latency_ms > objective_ms
    if breached:
        SLO_STATS["breaches"] += 1
        by_br = SLO_STATS["breaches_by_key"]
        by_br[key] = by_br.get(key, 0) + 1
    # burn = breaches / (observed * budget_frac); observed >= 1 here
    burn = round(
        SLO_STATS["breaches_by_key"].get(key, 0)
        / (by_obs[key] * _BUDGET_FRAC), 4,
    )
    SLO_STATS["burn_by_key"][key] = burn
    if burn > SLO_STATS["max_burn"]:
        SLO_STATS["max_burn"] = burn
    if breached:
        from libgrape_lite_tpu import obs

        obs.tracer().instant(
            "slo_breach", key=key, app=app,
            tenant=tenant if tenant is not None else "",
            latency_ms=round(latency_ms, 3),
            objective_ms=objective_ms, ok=ok, burn=burn,
        )
        obs.metrics().counter(
            "grape_slo_breaches_total",
            "queries past their SLO objective (or failed)",
        ).inc()


maybe_configure_from_env()
