"""Event model shared by the tracer and the exporters.

One process emits a flat stream of event dicts; the schema is a strict
subset of the Chrome `trace_event` format (the JSON Array Format's
per-event objects), so the JSONL sink and the Chrome export are two
serializations of the SAME records — a JSONL line re-wrapped in
`{"traceEvents": [...]}` loads in Perfetto / `chrome://tracing`
unchanged.

Event kinds (the `ph` phase tag):

* ``X`` — complete span: `ts` (start, µs) + `dur` (µs).  Nesting is
  positional, exactly as Chrome renders it: two spans on the same
  `(pid, tid)` row nest iff one's [ts, ts+dur) interval contains the
  other's.  Span args carry the structured payload (round, active,
  dispatch/device split — see tracer.Span for the timing convention).
* ``i`` — instant: a point event (guard breaches, retries, log lines).
* ``C`` — counter: per-round series (active vertices) render as a
  stacked chart under the track.
* ``M`` — metadata: `process_name` / `thread_name` rows.  The tracer
  names each process `grape/r<rank>` and maps host threads and
  per-fragment tracks (`frag/<fid>`) to distinct `tid` rows so a
  multi-fragment mesh renders as parallel tracks.
* ``s``/``t``/``f`` — flow events: start / step / end of a cross-track
  arrow.  All three phases of one flow share `(cat, id)`; Perfetto
  draws the arrow between the enclosing slices.  The gang layer
  (obs/gang.py) uses flows to render a breach vote or a checkpoint
  stage→commit sequence ACROSS rank process-tracks in the merged
  trace — the Dapper-style correlation id is the flow `id`.

Timestamps are integer nanoseconds internally (`time.perf_counter_ns`,
monotonic) and microseconds-with-remainder on export, Chrome's unit.
"""

from __future__ import annotations

from typing import Any, Dict

# tid rows: host threads count up from 0; per-fragment tracks live in
# their own band so a late-spawned writer thread can never collide with
# a fragment row; serve/ per-query lane tracks get a band of their own
# above that, and fleet/ per-replica tracks a band above THAT (all
# three bands restate host intervals, so the span rollup skips
# everything >= FRAG_TID_BASE)
FRAG_TID_BASE = 1000
LANE_TID_BASE = 2000
REPLICA_TID_BASE = 3000

#: keys every exported event must carry (tests/test_obs.py pins these
#: against the files the exporters actually write)
CHROME_REQUIRED = ("ph", "ts", "pid", "name")


def span_event(name: str, *, ts_ns: int, dur_ns: int, pid: int, tid: int,
               args: Dict[str, Any] | None = None,
               cat: str = "grape") -> Dict[str, Any]:
    ev = {
        "ph": "X",
        "name": name,
        "cat": cat,
        "ts": ts_ns / 1000.0,
        "dur": dur_ns / 1000.0,
        "pid": pid,
        "tid": tid,
    }
    if args:
        ev["args"] = args
    return ev


def instant_event(name: str, *, ts_ns: int, pid: int, tid: int,
                  args: Dict[str, Any] | None = None,
                  cat: str = "grape") -> Dict[str, Any]:
    ev = {
        "ph": "i",
        "name": name,
        "cat": cat,
        "ts": ts_ns / 1000.0,
        "pid": pid,
        "tid": tid,
        "s": "t",  # thread-scoped instant (the Chrome default draws nothing)
    }
    if args:
        ev["args"] = args
    return ev


def counter_event(name: str, *, ts_ns: int, pid: int, tid: int,
                  values: Dict[str, float],
                  cat: str = "grape") -> Dict[str, Any]:
    return {
        "ph": "C",
        "name": name,
        "cat": cat,
        "ts": ts_ns / 1000.0,
        "pid": pid,
        "tid": tid,
        "args": dict(values),
    }


def flow_event(name: str, *, ts_ns: int, pid: int, tid: int,
               flow_id: int, phase: str,
               args: Dict[str, Any] | None = None,
               cat: str = "gang") -> Dict[str, Any]:
    """One leg of a cross-track flow arrow.  `phase` is "s" (start),
    "t" (step) or "f" (end); every leg of one arrow must share
    `(cat, flow_id)`.  The end leg carries `bp: "e"` so Perfetto binds
    it to the ENCLOSING slice rather than the next one (the vote flow
    should land on the superstep that halted, not whatever follows)."""
    if phase not in ("s", "t", "f"):
        raise ValueError(f"flow phase must be s/t/f, got {phase!r}")
    ev = {
        "ph": phase,
        "name": name,
        "cat": cat,
        "id": int(flow_id),
        "ts": ts_ns / 1000.0,
        "pid": pid,
        "tid": tid,
    }
    if phase == "f":
        ev["bp"] = "e"
    if args:
        ev["args"] = args
    return ev


def metadata_event(kind: str, *, pid: int, tid: int = 0,
                   name: str) -> Dict[str, Any]:
    """`kind` is `process_name` or `thread_name` (trace_event M args)."""
    return {
        "ph": "M",
        "name": kind,
        "ts": 0,
        "pid": pid,
        "tid": tid,
        "args": {"name": name},
    }
