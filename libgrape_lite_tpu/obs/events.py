"""Event model shared by the tracer and the exporters.

One process emits a flat stream of event dicts; the schema is a strict
subset of the Chrome `trace_event` format (the JSON Array Format's
per-event objects), so the JSONL sink and the Chrome export are two
serializations of the SAME records — a JSONL line re-wrapped in
`{"traceEvents": [...]}` loads in Perfetto / `chrome://tracing`
unchanged.

Event kinds (the `ph` phase tag):

* ``X`` — complete span: `ts` (start, µs) + `dur` (µs).  Nesting is
  positional, exactly as Chrome renders it: two spans on the same
  `(pid, tid)` row nest iff one's [ts, ts+dur) interval contains the
  other's.  Span args carry the structured payload (round, active,
  dispatch/device split — see tracer.Span for the timing convention).
* ``i`` — instant: a point event (guard breaches, retries, log lines).
* ``C`` — counter: per-round series (active vertices) render as a
  stacked chart under the track.
* ``M`` — metadata: `process_name` / `thread_name` rows.  The tracer
  names each process `grape/r<rank>` and maps host threads and
  per-fragment tracks (`frag/<fid>`) to distinct `tid` rows so a
  multi-fragment mesh renders as parallel tracks.

Timestamps are integer nanoseconds internally (`time.perf_counter_ns`,
monotonic) and microseconds-with-remainder on export, Chrome's unit.
"""

from __future__ import annotations

from typing import Any, Dict

# tid rows: host threads count up from 0; per-fragment tracks live in
# their own band so a late-spawned writer thread can never collide with
# a fragment row; serve/ per-query lane tracks get a band of their own
# above that, and fleet/ per-replica tracks a band above THAT (all
# three bands restate host intervals, so the span rollup skips
# everything >= FRAG_TID_BASE)
FRAG_TID_BASE = 1000
LANE_TID_BASE = 2000
REPLICA_TID_BASE = 3000

#: keys every exported event must carry (tests/test_obs.py pins these
#: against the files the exporters actually write)
CHROME_REQUIRED = ("ph", "ts", "pid", "name")


def span_event(name: str, *, ts_ns: int, dur_ns: int, pid: int, tid: int,
               args: Dict[str, Any] | None = None,
               cat: str = "grape") -> Dict[str, Any]:
    ev = {
        "ph": "X",
        "name": name,
        "cat": cat,
        "ts": ts_ns / 1000.0,
        "dur": dur_ns / 1000.0,
        "pid": pid,
        "tid": tid,
    }
    if args:
        ev["args"] = args
    return ev


def instant_event(name: str, *, ts_ns: int, pid: int, tid: int,
                  args: Dict[str, Any] | None = None,
                  cat: str = "grape") -> Dict[str, Any]:
    ev = {
        "ph": "i",
        "name": name,
        "cat": cat,
        "ts": ts_ns / 1000.0,
        "pid": pid,
        "tid": tid,
        "s": "t",  # thread-scoped instant (the Chrome default draws nothing)
    }
    if args:
        ev["args"] = args
    return ev


def counter_event(name: str, *, ts_ns: int, pid: int, tid: int,
                  values: Dict[str, float],
                  cat: str = "grape") -> Dict[str, Any]:
    return {
        "ph": "C",
        "name": name,
        "cat": cat,
        "ts": ts_ns / 1000.0,
        "pid": pid,
        "tid": tid,
        "args": dict(values),
    }


def metadata_event(kind: str, *, pid: int, tid: int = 0,
                   name: str) -> Dict[str, Any]:
    """`kind` is `process_name` or `thread_name` (trace_event M args)."""
    return {
        "ph": "M",
        "name": kind,
        "ts": 0,
        "pid": pid,
        "tid": tid,
        "args": {"name": name},
    }
