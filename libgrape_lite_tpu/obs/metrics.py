"""MetricsRegistry: counters / gauges / histograms / series with a
Prometheus-text and JSON snapshot.

The registry is the query-end complement to the tracer's timeline:
spans say *when*, metrics say *how much in total* — rounds, active
vertices per round, bytes streamed from the pack ledger, guard probe
verdicts, checkpoint save/restore latency, retry attempts, rollback
count.  Instruments are created on first use (`registry.counter(name)`
is get-or-create), so call sites never coordinate registration.

Disarmed discipline mirrors the tracer: `obs.metrics()` returns the
shared `NULL_METRICS` when observability is off, whose instruments are
one no-op object — call sites stay unconditional
(`obs.metrics().counter("grape_retry_attempts_total").inc()`) and pay
two attribute lookups and a no-op call when disarmed.

Naming follows Prometheus conventions: `*_total` for counters,
`*_seconds` for latency histograms, plain gauges otherwise; `series`
is the one non-Prometheus kind (an ordered per-round list, e.g. active
vertices per superstep) and exports to the JSON snapshot only — the
text exposition has no faithful encoding for it, so it is summarised
there as a gauge of its last value.
"""

from __future__ import annotations

import json
import threading
from typing import Dict, List, Optional

#: default latency buckets (seconds): superstep dispatch through
#: checkpoint writes span ~1e-4 .. ~1e2
DEFAULT_BUCKETS = (
    0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0,
    10.0, 60.0,
)


class Counter:
    __slots__ = ("name", "help", "value")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self.value = 0

    def inc(self, n: float = 1) -> None:
        self.value += n


class Gauge:
    __slots__ = ("name", "help", "value")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self.value = 0

    def set(self, v: float) -> None:
        self.value = v

    def inc(self, n: float = 1) -> None:
        self.value += n


class Histogram:
    __slots__ = ("name", "help", "buckets", "counts", "sum", "count")

    def __init__(self, name: str, help: str = "",
                 buckets=DEFAULT_BUCKETS):
        self.name = name
        self.help = help
        self.buckets = tuple(sorted(buckets))
        self.counts = [0] * (len(self.buckets) + 1)  # +inf tail
        self.sum = 0.0
        self.count = 0

    def observe(self, v: float) -> None:
        self.sum += v
        self.count += 1
        for i, b in enumerate(self.buckets):
            if v <= b:
                self.counts[i] += 1
                return
        self.counts[-1] += 1


class Series:
    """Ordered per-round observations (active vertices per superstep).
    JSON-snapshot only; the Prometheus text reports the last value."""

    __slots__ = ("name", "help", "values")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self.values: List[float] = []

    def append(self, v: float) -> None:
        self.values.append(v)


class _NullInstrument:
    """One object serves every disarmed instrument kind."""

    __slots__ = ()

    def inc(self, n: float = 1) -> None:
        pass

    def set(self, v: float) -> None:
        pass

    def observe(self, v: float) -> None:
        pass

    def append(self, v: float) -> None:
        pass


_NULL_INSTRUMENT = _NullInstrument()


def gang_identity() -> tuple:
    """(rank, nprocs) read live from jax.distributed, (0, 1) when the
    process is not part of an initialized gang.  Shared by the metric
    sinks and the gang sidecars so every exported row agrees on who
    wrote it."""
    try:
        from jax._src import distributed

        st = distributed.global_state
        if getattr(st, "client", None) is None:
            return 0, 1  # jax.distributed not initialized
        rank = int(st.process_id or 0)
        n = int(getattr(st, "num_processes", None) or 1)
        return rank, n
    except Exception:
        return 0, 1


class MetricsRegistry:
    def __init__(self):
        self._lock = threading.Lock()  # creation only; updates are GIL-atomic
        self._instruments: Dict[str, object] = {}

    def _get(self, name: str, cls, **kw):
        inst = self._instruments.get(name)
        if inst is None:
            with self._lock:
                inst = self._instruments.get(name)
                if inst is None:
                    inst = cls(name, **kw)
                    self._instruments[name] = inst
        if not isinstance(inst, cls):
            raise TypeError(
                f"metric {name!r} already registered as "
                f"{type(inst).__name__}, requested {cls.__name__}"
            )
        return inst

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(name, Counter, help=help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(name, Gauge, help=help)

    def histogram(self, name: str, help: str = "",
                  buckets=DEFAULT_BUCKETS) -> Histogram:
        return self._get(name, Histogram, help=help, buckets=buckets)

    def series(self, name: str, help: str = "") -> Series:
        return self._get(name, Series, help=help)

    # ---- snapshots -------------------------------------------------------

    def snapshot(self) -> dict:
        """JSON-ready dump of every instrument."""
        out = {}
        for name, inst in sorted(self._instruments.items()):
            if isinstance(inst, Counter):
                out[name] = {"type": "counter", "value": inst.value}
            elif isinstance(inst, Gauge):
                out[name] = {"type": "gauge", "value": inst.value}
            elif isinstance(inst, Histogram):
                out[name] = {
                    "type": "histogram",
                    "sum": inst.sum,
                    "count": inst.count,
                    "buckets": {
                        ("+Inf" if i == len(inst.buckets) else repr(b)): c
                        for i, (b, c) in enumerate(
                            zip(list(inst.buckets) + [None], inst.counts)
                        )
                    },
                }
            elif isinstance(inst, Series):
                out[name] = {"type": "series", "values": list(inst.values)}
        return out

    def to_prometheus_text(self) -> str:
        """Prometheus text exposition format v0.0.4."""
        lines = []
        for name, inst in sorted(self._instruments.items()):
            if getattr(inst, "help", ""):
                lines.append(f"# HELP {name} {inst.help}")
            if isinstance(inst, Counter):
                lines.append(f"# TYPE {name} counter")
                lines.append(f"{name} {_fmt(inst.value)}")
            elif isinstance(inst, Gauge):
                lines.append(f"# TYPE {name} gauge")
                lines.append(f"{name} {_fmt(inst.value)}")
            elif isinstance(inst, Histogram):
                lines.append(f"# TYPE {name} histogram")
                cum = 0
                for b, c in zip(inst.buckets, inst.counts):
                    cum += c
                    lines.append(f'{name}_bucket{{le="{_fmt(b)}"}} {cum}')
                cum += inst.counts[-1]
                lines.append(f'{name}_bucket{{le="+Inf"}} {cum}')
                lines.append(f"{name}_sum {_fmt(inst.sum)}")
                lines.append(f"{name}_count {inst.count}")
            elif isinstance(inst, Series):
                # no faithful text encoding; expose the last value
                last = inst.values[-1] if inst.values else 0
                lines.append(f"# TYPE {name} gauge")
                lines.append(f"{name} {_fmt(last)}")
        return "\n".join(lines) + "\n"

    def write(self, json_path: Optional[str] = None,
              prom_path: Optional[str] = None) -> None:
        import os

        for p in (json_path, prom_path):
            if p:
                os.makedirs(
                    os.path.dirname(os.path.abspath(p)), exist_ok=True
                )
        rank, nprocs = gang_identity()
        if json_path:
            snap = self.snapshot()
            if nprocs > 1:
                # stamp WHO wrote each row; single-process snapshots
                # stay byte-identical to the pre-gang schema
                for row in snap.values():
                    row["rank"] = rank
                    row["nprocs"] = nprocs
            with open(json_path, "w") as fh:
                json.dump(snap, fh, indent=1, sort_keys=True)
                fh.write("\n")
        if prom_path:
            text = self.to_prometheus_text()
            if nprocs > 1:
                text += (
                    "# TYPE grape_gang_rank gauge\n"
                    f"grape_gang_rank {rank}\n"
                    "# TYPE grape_gang_nprocs gauge\n"
                    f"grape_gang_nprocs {nprocs}\n"
                )
            with open(prom_path, "w") as fh:
                fh.write(text)


def _fmt(v) -> str:
    if isinstance(v, float) and v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v) if isinstance(v, float) else str(v)


class _NullMetrics:
    """Disarmed registry: every instrument is the shared no-op."""

    __slots__ = ()

    def counter(self, name: str, help: str = ""):
        return _NULL_INSTRUMENT

    def gauge(self, name: str, help: str = ""):
        return _NULL_INSTRUMENT

    def histogram(self, name: str, help: str = "", buckets=None):
        return _NULL_INSTRUMENT

    def series(self, name: str, help: str = ""):
        return _NULL_INSTRUMENT

    def snapshot(self) -> dict:
        return {}

    def to_prometheus_text(self) -> str:
        return ""

    def write(self, json_path=None, prom_path=None) -> None:
        pass


NULL_METRICS = _NullMetrics()
