"""obs/ — structured superstep tracing and a metrics registry.

One event model unifies the signals PR 2–4 left scattered (op/byte
ledger, guard breach bundles, ad-hoc perf_counter logs): the worker
emits nested host spans (`peval`, `superstep`, `chunk`,
`checkpoint_write`, ...) with a sync-before-close timing convention
(see tracer.py), guard/ft/loader attach their events to the same
timeline, and a `MetricsRegistry` accumulates counters/gauges/
histograms snapshotted at query end.  Export: JSONL + Chrome
`trace_event` JSON (Perfetto-loadable) and Prometheus-text/JSON
metrics dumps.  docs/OBSERVABILITY.md is the user guide;
scripts/trace_report.py renders the per-superstep table.

Off by default: `obs.tracer()` returns a disabled singleton whose
`span()` is a sub-microsecond no-op (pinned by test), and arming is a
host-side decision invisible to jit tracing — the fused hot path's
lowered HLO is byte-identical disarmed vs armed (pinned by test).

Arming: GRAPE_TRACE=/path/trace.json, GRAPE_METRICS=/path/metrics
(env, read once lazily), `--trace`/`--metrics` (CLI), or
`obs.configure(...)` (API).

The telemetry plane (PR 15) layers four always-on surfaces on top:
`federation` (one namespaced snapshot()/reset() over every *_STATS
registry), `exporter` (live OpenMetrics HTTP endpoint, armed via
GRAPE_METRICS_PORT / --metrics_port), `slo` (latency objectives with
error-budget burn; breach = instant + counter, never an exception),
and `recorder` (a flight-recorder ring dumping correlated postmortem
bundles on guard breach / fence violation / deadline storm).

The gang plane (PR 20) extends all of it across ranks: `gang`
(per-rank sidecar files, a clock-offset handshake over the existing
host allgather, a rank-0 assembler producing ONE merged Perfetto
timeline, and the distributed flight recorder dumping every rank's
postmortem under one shared incident id) and `truth` (the overlap
truth meter reconciling modeled `hidden_us_per_round` against the
tracer's measured `device_wait_us`, joined per plan uid).
`scripts/trace_report.py --gang` renders the merged timeline.
"""

from libgrape_lite_tpu.obs import federation
from libgrape_lite_tpu.obs import gang
from libgrape_lite_tpu.obs import truth
from libgrape_lite_tpu.obs.config import (
    METRICS_ENV,
    TRACE_ENV,
    armed,
    configure,
    flush,
    history,
    metrics,
    reset,
    trace_id,
    tracer,
)
from libgrape_lite_tpu.obs.exporter import (
    METRICS_PORT_ENV,
    MetricsExporter,
    maybe_start_from_env,
    start_exporter,
    stop_exporter,
)
from libgrape_lite_tpu.obs.export import (
    load_trace,
    rollup,
    write_chrome_trace,
)
from libgrape_lite_tpu.obs.federation import FederatedStats
from libgrape_lite_tpu.obs import slo
from libgrape_lite_tpu.obs.metrics import (
    NULL_METRICS,
    MetricsRegistry,
)
from libgrape_lite_tpu.obs.recorder import RECORDER, FlightRecorder
from libgrape_lite_tpu.obs.tracer import NULL_SPAN, Span, Tracer

__all__ = [
    "federation",
    "gang",
    "truth",
    "slo",
    "FederatedStats",
    "METRICS_PORT_ENV",
    "MetricsExporter",
    "maybe_start_from_env",
    "start_exporter",
    "stop_exporter",
    "RECORDER",
    "FlightRecorder",
    "METRICS_ENV",
    "TRACE_ENV",
    "armed",
    "configure",
    "flush",
    "history",
    "metrics",
    "reset",
    "trace_id",
    "tracer",
    "load_trace",
    "rollup",
    "write_chrome_trace",
    "MetricsRegistry",
    "NULL_METRICS",
    "NULL_SPAN",
    "Span",
    "Tracer",
]
