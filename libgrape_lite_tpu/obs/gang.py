"""Gang-wide observability: sidecars, clock handshake, assembler,
and the distributed flight recorder.

The single-process telemetry plane (tracer -> Chrome trace, federation
-> scrape) observes exactly one rank; the gang machinery it should be
watching (guard/vote.py breach votes, ft/distributed.py 2PC
checkpoints) is multi-rank.  This module closes the gap with three
pieces, none of which touch the device path:

* **Per-rank sidecars** — each rank periodically rewrites one JSON
  file (``<trace base>.gang/rank_<r>.json``, schema
  ``grape-gang-trace-v1``) holding its full event history, its
  federated ``*_STATS`` snapshot, and the clock handshake.  The write
  is a whole-file atomic replace per superstep boundary, so a rank
  killed mid-run (``os._exit`` skips atexit; SIGKILL skips everything)
  leaves its last completed snapshot behind — the crash-forensics
  property the flight recorder has for breadcrumbs, extended to the
  timeline.

* **Clock handshake** — ``perf_counter`` is per-process (CLOCK_MONOTONIC
  since an arbitrary epoch), so raw cross-rank timestamps are
  incomparable.  ``ensure_handshake`` allgathers every rank's
  monotonic + wall anchors at one collective instant (the int64
  nanosecond values ride the existing int32 ``host_allgather`` as
  30-bit words) and derives ``offset_ns[r] = anchor[0] - anchor[r]``;
  the assembler shifts rank r's events by that offset so spans align
  on rank 0's clock.  Residual skew is bounded by the allgather wall
  time (recorded in the handshake), typically far under a superstep.

* **Gang postmortem** — when a breach vote halts the gang, every rank
  raises from the SAME vote cut (guard/vote.py), so every rank can
  symmetrically dump its flight-recorder bundle under one shared
  incident id (derived deterministically from the voted content — no
  extra message carries it) and join one more allgather carrying a
  28-bit sha prefix of the dumped bytes.  Rank 0 then writes the gang
  manifest (``incident_<id>/gang.json``) verifying each shard's
  digest against its rank's vote — the byte-verification discipline
  recorder.py uses for single bundles, applied gang-wide.

Symmetry contract: everything here that allgathers (the handshake, the
postmortem sha-confirm) is gated on env/flag state that is identical
across ranks (``GRAPE_TRACE`` / ``GRAPE_POSTMORTEM`` set gang-wide,
same CLI flags), the same contract the breach vote itself relies on.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import time
from typing import Any, Dict, List, Optional

import numpy as np

from libgrape_lite_tpu.obs.federation import FederatedStats

GANG_TRACE_SCHEMA = "grape-gang-trace-v1"
GANG_BUNDLE_SCHEMA = "grape-gang-postmortem-v1"

GANG_STATS = FederatedStats("gang", {
    "handshakes": 0,
    "sidecar_writes": 0,
    "assemblies": 0,
    "halts": 0,
    "postmortems": 0,
    "last_incident": None,
})

#: int64 nanoseconds ride the int32 allgather as little-endian 30-bit
#: words (3 words = 90 bits, comfortably above wall-clock ns)
_WORD_BITS = 30
_WORD_MASK = (1 << _WORD_BITS) - 1
_NS_WORDS = 3

_state: Dict[str, Any] = {"handshake": None}


def reset() -> None:
    """Forget the cached handshake (tests re-handshake per case)."""
    _state["handshake"] = None


# ---- clock handshake -----------------------------------------------------


def _split_ns(v: int) -> List[int]:
    v = int(v)
    return [(v >> (_WORD_BITS * i)) & _WORD_MASK
            for i in range(_NS_WORDS)]


def _join_ns(words) -> int:
    return sum((int(w) & _WORD_MASK) << (_WORD_BITS * i)
               for i, w in enumerate(words))


def _default_allgather():
    from libgrape_lite_tpu.parallel.comm_spec import host_allgather

    return host_allgather


def ensure_handshake(*, rank: Optional[int] = None,
                     nprocs: Optional[int] = None,
                     allgather=None,
                     force: bool = False) -> Optional[dict]:
    """Run (or return the cached) clock-offset handshake.

    Every rank reads its monotonic + wall anchors immediately before
    entering one collective allgather; the offsets that align each
    rank onto rank 0's clock are identical on every rank (the
    allgather is symmetric), so the assembler can run anywhere.
    Returns None single-process (nothing to align)."""
    if _state["handshake"] is not None and not force:
        return _state["handshake"]
    if rank is None or nprocs is None:
        from libgrape_lite_tpu.obs.metrics import gang_identity

        rank, nprocs = gang_identity()
    if nprocs <= 1:
        return None
    if allgather is None:
        allgather = _default_allgather()
    t0 = time.perf_counter_ns()
    vec = _split_ns(t0) + _split_ns(time.time_ns())
    stacked = np.asarray(allgather(np.asarray(vec, np.int32)))
    t1 = time.perf_counter_ns()
    anchors = []
    for r in range(stacked.shape[0]):
        row = [int(x) for x in stacked[r]]
        anchors.append({
            "perf_ns": _join_ns(row[:_NS_WORDS]),
            "wall_ns": _join_ns(row[_NS_WORDS:2 * _NS_WORDS]),
        })
    offsets = {
        str(r): anchors[0]["perf_ns"] - a["perf_ns"]
        for r, a in enumerate(anchors)
    }
    hs = {
        "rank": int(rank),
        "nprocs": int(stacked.shape[0]),
        "anchors": anchors,
        "offsets_ns": offsets,
        "allgather_wall_ns": t1 - t0,  # skew upper bound
    }
    _state["handshake"] = hs
    GANG_STATS["handshakes"] += 1
    return hs


# ---- per-rank sidecars ---------------------------------------------------


def gang_dir(trace_path: Optional[str] = None) -> Optional[str]:
    """`<trace base>.gang/` next to the configured Chrome trace, or
    None when tracing has no file sink (in-memory arming)."""
    if trace_path is None:
        from libgrape_lite_tpu.obs import config

        trace_path = config._state["trace_path"]
    if not trace_path:
        return None
    base, ext = os.path.splitext(trace_path)
    return (base if ext else trace_path) + ".gang"


def sidecar_path(rank: int,
                 trace_path: Optional[str] = None) -> Optional[str]:
    d = gang_dir(trace_path)
    return os.path.join(d, f"rank_{int(rank)}.json") if d else None


def write_sidecar(*, tracer=None, path: Optional[str] = None,
                  handshake: Optional[dict] = None,
                  events: Optional[list] = None) -> Optional[str]:
    """Atomically rewrite this rank's sidecar with its full event
    history + federation snapshot.  Whole-file replace, so a rank
    killed between writes leaves the previous complete snapshot — the
    merge never sees a torn file.  Returns the path or None (disarmed
    / no file sink).  Never raises."""
    try:
        from libgrape_lite_tpu import obs
        from libgrape_lite_tpu.obs import federation

        if tracer is None:
            tracer = obs.tracer()
        if not tracer.enabled:
            return None
        rank = tracer.pid
        if path is None:
            path = sidecar_path(rank)
        if path is None:
            return None
        if handshake is None:
            handshake = _state["handshake"]
        if events is None:
            events = (obs.history() if tracer is obs.tracer()
                      else tracer.events())
        try:
            fed = federation.snapshot()
        except Exception:
            fed = {}
        doc = {
            "schema": GANG_TRACE_SCHEMA,
            "rank": int(rank),
            "nprocs": int(tracer.nprocs),
            "trace_id": tracer.trace_id,
            "wall_anchor": tracer.wall_anchor(),
            "handshake": handshake,
            "federation": fed,
            "events": list(events),
        }
        os.makedirs(os.path.dirname(os.path.abspath(path)),
                    exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(doc, fh, default=str)
            fh.write("\n")
        os.replace(tmp, path)
        GANG_STATS["sidecar_writes"] += 1
        return path
    except Exception:
        return None


# ---- rank-0 assembler ----------------------------------------------------

_SIDE_RE = re.compile(r"^rank_(\d+)\.json$")


def load_sidecars(dirpath: str) -> List[dict]:
    """Every `rank_<r>.json` under `dirpath`, sorted by rank."""
    docs = []
    for fn in sorted(os.listdir(dirpath)):
        m = _SIDE_RE.match(fn)
        if not m:
            continue
        with open(os.path.join(dirpath, fn)) as fh:
            doc = json.load(fh)
        doc["rank"] = int(doc.get("rank", int(m.group(1))))
        docs.append(doc)
    docs.sort(key=lambda d: d["rank"])
    return docs


def assemble(dirpath: str,
             out_path: Optional[str] = None) -> dict:
    """Merge every rank sidecar under `dirpath` into one Perfetto
    timeline (one process track per rank) and report completeness.

    Clock alignment: each rank's non-metadata events are shifted by
    the handshake's `offset_ns[rank]` so all timestamps land on rank
    0's monotonic clock; the merged stream is then sorted, so
    post-alignment timestamps are monotonic by construction and the
    summary verifies it.  Flow-event legs (`ph` s/t/f) keep their
    `(cat, id)` so Perfetto draws vote / 2PC arrows across the rank
    tracks."""
    docs = load_sidecars(dirpath)
    if not docs:
        return {"ranks": [], "nprocs": 0, "events": 0,
                "complete": False, "monotonic": False, "aligned": False,
                "missing": [], "flow_ids": 0, "flow_events": 0,
                "spans_by_rank": {}, "supersteps_by_rank": {},
                "out": None}
    nprocs = max(int(d.get("nprocs", 1)) for d in docs)
    offsets: Dict[int, int] = {}
    for d in docs:
        hs = d.get("handshake") or {}
        for k, v in (hs.get("offsets_ns") or {}).items():
            offsets.setdefault(int(k), int(v))
    merged: List[dict] = []
    aligned = True
    for d in docs:
        off = offsets.get(d["rank"])
        if off is None:
            off = 0
            if nprocs > 1:
                aligned = False
        off_us = off / 1000.0
        for ev in d.get("events", ()):
            ev = dict(ev)
            if ev.get("ph") != "M":
                ev["ts"] = float(ev.get("ts", 0)) + off_us
            merged.append(ev)
    merged.sort(key=lambda e: (0 if e.get("ph") == "M" else 1,
                               float(e.get("ts", 0)),
                               int(e.get("pid", 0))))
    ranks = [d["rank"] for d in docs]
    missing = [r for r in range(nprocs) if r not in ranks]
    spans_by_rank = {
        str(d["rank"]): sum(1 for e in d.get("events", ())
                            if e.get("ph") == "X")
        for d in docs
    }
    supersteps_by_rank = {
        str(d["rank"]): sum(1 for e in d.get("events", ())
                            if e.get("ph") == "X"
                            and e.get("name") == "superstep")
        for d in docs
    }
    flows: Dict[tuple, set] = {}
    flow_events = 0
    for ev in merged:
        if ev.get("ph") in ("s", "t", "f"):
            flow_events += 1
            flows.setdefault(
                (ev.get("cat"), ev.get("id")), set()
            ).add(ev.get("pid"))
    ts_seq = [float(e["ts"]) for e in merged if e.get("ph") != "M"]
    monotonic = all(b >= a for a, b in zip(ts_seq, ts_seq[1:]))
    complete = (not missing and aligned
                and all(v > 0 for v in spans_by_rank.values()))
    summary = {
        "ranks": ranks,
        "nprocs": nprocs,
        "events": len(merged),
        "spans_by_rank": spans_by_rank,
        "supersteps_by_rank": supersteps_by_rank,
        "flow_ids": len(flows),
        "flow_events": flow_events,
        "cross_rank_flows": sum(
            1 for pids in flows.values() if len(pids) >= 2),
        "missing": missing,
        "aligned": aligned,
        "monotonic": monotonic,
        "complete": complete,
        "out": None,
    }
    if out_path:
        doc = {
            "traceEvents": merged,
            "displayTimeUnit": "ms",
            "metadata": {
                "producer": "libgrape-lite-tpu obs/gang",
                "gang": {
                    "schema": GANG_TRACE_SCHEMA,
                    "nprocs": nprocs,
                    "ranks": ranks,
                    "offsets_ns": {str(k): v
                                   for k, v in sorted(offsets.items())},
                    "trace_ids": {str(d["rank"]): d.get("trace_id")
                                  for d in docs},
                    "federation": {str(d["rank"]): d.get("federation")
                                   for d in docs},
                },
            },
        }
        os.makedirs(os.path.dirname(os.path.abspath(out_path)),
                    exist_ok=True)
        tmp = out_path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(doc, fh)
            fh.write("\n")
        os.replace(tmp, out_path)
        summary["out"] = out_path
    GANG_STATS["assemblies"] += 1
    return summary


# ---- distributed flight recorder -----------------------------------------


def trace_word() -> int:
    """28-bit prefix of this process's trace id (0 disarmed) —
    int32-safe, so it can ride the vote / 2PC allgather vectors and
    let the merged matrix name every rank's trace file."""
    try:
        from libgrape_lite_tpu import obs

        tid = obs.trace_id()
        return int(tid[:7], 16) if tid else 0
    except Exception:
        return 0


def incident_id(basis) -> str:
    """Deterministic 16-hex incident id over JSON-serializable basis
    content.  guard/vote.py feeds the full allgathered vote matrix —
    identical bytes on every rank — so the gang agrees on the id
    without any extra message."""
    raw = json.dumps(basis, sort_keys=True, default=str)
    return hashlib.sha256(raw.encode()).hexdigest()[:16]


def _sha_words_of_file(path: str) -> tuple:
    """Two 28-bit words of the file's sha256 (int32-safe, the
    ft/distributed.py `_sha_prefix` discipline)."""
    with open(path, "rb") as fh:
        h = hashlib.sha256(fh.read()).hexdigest()
    return int(h[:7], 16), int(h[7:14], 16)


def gang_postmortem(incident: str, reason: str, *,
                    extra: Optional[dict] = None,
                    rank: Optional[int] = None,
                    nprocs: Optional[int] = None,
                    allgather=None) -> Optional[dict]:
    """Dump this rank's postmortem shard under the shared incident id
    and (rank 0) assemble the byte-verified gang manifest.

    Every rank must call this from the same logical cut (the breach
    vote guarantees that) — the sha-confirm allgather is collective.
    No sink configured -> counts only, no allgather (sink presence is
    env-symmetric).  Never raises."""
    try:
        from libgrape_lite_tpu.obs.recorder import RECORDER

        if rank is None or nprocs is None:
            from libgrape_lite_tpu.obs.metrics import gang_identity

            rank, nprocs = gang_identity()
        GANG_STATS["postmortems"] += 1
        GANG_STATS["last_incident"] = incident
        sink = RECORDER.sink()
        if not sink:
            return None
        shard = RECORDER.trigger(
            reason, extra=extra, incident=incident,
            filename=os.path.join(f"incident_{incident}",
                                  f"rank_{int(rank)}.json"),
        )
        ok, lo, hi = 0, 0, 0
        if shard:
            try:
                lo, hi = _sha_words_of_file(shard)
                ok = 1
            except Exception:
                ok, lo, hi = 0, 0, 0
        if nprocs > 1:
            if allgather is None:
                allgather = _default_allgather()
            votes = np.asarray(
                allgather(np.asarray([ok, lo, hi], np.int32)))
        else:
            votes = np.asarray([[ok, lo, hi]], np.int32)
        out = {"incident": incident, "path": shard, "manifest": None,
               "complete": None}
        if int(rank) != 0:
            return out
        incident_dir = os.path.join(sink, f"incident_{incident}")
        shards = {}
        complete = True
        for r in range(int(votes.shape[0])):
            p = os.path.join(incident_dir, f"rank_{r}.json")
            present = os.path.exists(p)
            verified = False
            if present and int(votes[r][0]) == 1:
                try:
                    verified = (_sha_words_of_file(p) ==
                                (int(votes[r][1]), int(votes[r][2])))
                except Exception:
                    verified = False
            complete = complete and present and verified
            shards[str(r)] = {
                "path": f"rank_{r}.json",
                "present": present,
                "verified": verified,
                "sha28x2": [int(votes[r][1]), int(votes[r][2])],
            }
        manifest = {
            "schema": GANG_BUNDLE_SCHEMA,
            "incident": incident,
            "reason": reason,
            "nprocs": int(votes.shape[0]),
            "complete": complete,
            "shards": shards,
        }
        os.makedirs(incident_dir, exist_ok=True)
        mpath = os.path.join(incident_dir, "gang.json")
        tmp = mpath + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(manifest, fh, indent=1)
            fh.write("\n")
        os.replace(tmp, mpath)
        out["manifest"] = mpath
        out["complete"] = complete
        return out
    except Exception:
        return None


def on_breach_halt(err, rounds, *, allgather=None) -> None:
    """Worker hook for a vote-raised halt: stamp the halt into the
    timeline, drain a final sidecar, and run the gang postmortem under
    the incident id the vote attached (`err.gang_incident`).  All
    ranks raise from the same vote cut, so this runs symmetrically.
    Never raises — forensics must not mask the halt."""
    try:
        incident = getattr(err, "gang_incident", None) or incident_id(
            [type(err).__name__, int(rounds)])
        GANG_STATS["halts"] += 1
        GANG_STATS["last_incident"] = incident
        from libgrape_lite_tpu import obs

        tr = obs.tracer()
        if tr.enabled:
            tr.instant("gang_halt", round=int(rounds),
                       error=type(err).__name__, incident=incident)
            write_sidecar()
        extra: Dict[str, Any] = {
            "round": int(rounds), "error": type(err).__name__,
        }
        bundle = getattr(err, "bundle", None)
        if isinstance(bundle, dict):
            extra["vote"] = {
                k: bundle[k] for k in ("rounds", "ranks", "codes")
                if k in bundle
            }
        gang_postmortem(
            incident, f"breach_halt_{type(err).__name__}",
            extra=extra, allgather=allgather,
        )
    except Exception:
        pass
