"""Live OpenMetrics exporter — scrape a running fleet without
stopping it.

A background-thread stdlib ``http.server`` endpoint, armed via
``GRAPE_METRICS_PORT`` or the serve CLI's ``--metrics_port``:

* ``/metrics`` — Prometheus text exposition: the armed
  ``MetricsRegistry`` (obs/metrics.py, empty when disarmed) plus the
  federation snapshot flattened to ``grape_stats_<ns>_<field>`` gauges
  (dict-valued fields become one ``{key="..."}``-labelled sample per
  entry; non-numeric fields are JSON-only).  Every registered
  namespace is guaranteed a ``grape_stats_registry{namespace="…"} 1``
  marker regardless of its field types — the live-scrape smoke in
  app_tests.sh checks exactly that every ``*_STATS`` surface shows up.
* ``/federation`` — the raw federation snapshot as JSON (the full
  truth, including lists and last-decision records).
* ``/healthz`` — liveness, ``{"ok": true, "namespaces": N}``.

The server is a daemon thread off the serving path: a scrape costs
the serving loop nothing but the GIL slices the snapshot copy takes.
Port 0 binds an ephemeral port (tests); the bound port is readable
from ``MetricsExporter.port``.
"""

from __future__ import annotations

import json
import os
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from libgrape_lite_tpu.obs import federation

METRICS_PORT_ENV = "GRAPE_METRICS_PORT"

_NAME_OK = re.compile(r"[^a-zA-Z0-9_]")


def _metric_name(ns: str, field: str) -> str:
    return "grape_stats_%s_%s" % (
        _NAME_OK.sub("_", ns), _NAME_OK.sub("_", field),
    )


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace(
        "\n", "\\n")


def _fmt_num(v) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, float) and v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v) if isinstance(v, float) else str(v)


def federation_text(snap=None) -> str:
    """The federation snapshot in Prometheus/OpenMetrics text form.

    Numeric scalars export directly; dict-valued fields with numeric
    values export one labelled sample per key; every namespace gets
    its ``grape_stats_registry`` marker even when no field is
    exportable (a scrape must name every registered surface).
    """
    if snap is None:
        snap = federation.snapshot()
    lines = []
    from libgrape_lite_tpu.obs.metrics import gang_identity

    rank, nprocs = gang_identity()
    if nprocs > 1:
        # gang identity gauges: which rank this scrape came from
        # (single-process text stays byte-identical to pre-gang)
        lines.append("# TYPE grape_gang_rank gauge")
        lines.append(f"grape_gang_rank {rank}")
        lines.append("# TYPE grape_gang_nprocs gauge")
        lines.append(f"grape_gang_nprocs {nprocs}")
    lines.append("# TYPE grape_stats_registry gauge")
    for ns in sorted(snap):
        lines.append(
            'grape_stats_registry{namespace="%s"} 1' % _escape_label(ns)
        )
    for ns in sorted(snap):
        for field in sorted(snap[ns]):
            v = snap[ns][field]
            name = _metric_name(ns, field)
            if isinstance(v, bool) or isinstance(v, (int, float)):
                lines.append(f"# TYPE {name} gauge")
                lines.append(f"{name} {_fmt_num(v)}")
            elif isinstance(v, dict):
                numeric = {
                    k: x for k, x in v.items()
                    if isinstance(x, (int, float))
                }
                if numeric:
                    lines.append(f"# TYPE {name} gauge")
                    for k in sorted(numeric):
                        lines.append(
                            '%s{key="%s"} %s' % (
                                name, _escape_label(str(k)),
                                _fmt_num(numeric[k]),
                            )
                        )
            # lists / strings / None: JSON endpoint only
    return "\n".join(lines) + "\n"


class _Handler(BaseHTTPRequestHandler):
    server_version = "grape-exporter/1"

    def _send(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 — http.server API
        path = self.path.split("?", 1)[0]
        try:
            if path in ("/metrics", "/"):
                from libgrape_lite_tpu import obs

                text = obs.metrics().to_prometheus_text()
                text += federation_text()
                text += "# EOF\n"
                self._send(200, text.encode("utf-8"),
                           "text/plain; version=0.0.4; charset=utf-8")
            elif path == "/federation":
                body = json.dumps(
                    federation.snapshot(), indent=1, sort_keys=True,
                    default=str,
                ).encode("utf-8")
                self._send(200, body, "application/json")
            elif path == "/healthz":
                from libgrape_lite_tpu.obs.metrics import gang_identity

                health = {
                    "ok": True,
                    "namespaces": len(federation.registered()),
                }
                rank, nprocs = gang_identity()
                if nprocs > 1:
                    health["rank"] = rank
                    health["nprocs"] = nprocs
                body = json.dumps(health).encode("utf-8")
                self._send(200, body, "application/json")
            else:
                self._send(404, b"not found\n", "text/plain")
        except Exception as e:  # a scrape must never kill the server
            self._send(500, f"{type(e).__name__}: {e}\n".encode(),
                       "text/plain")

    def log_message(self, fmt, *args):  # silence per-request stderr
        pass


class MetricsExporter:
    """Background OpenMetrics endpoint over the federation + registry."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1"):
        self._server = ThreadingHTTPServer((host, port), _Handler)
        self._server.daemon_threads = True
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="grape-metrics-exporter", daemon=True,
        )
        self._thread.start()

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    @property
    def url(self) -> str:
        host = self._server.server_address[0]
        return f"http://{host}:{self.port}"

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5)


_exporter: Optional[MetricsExporter] = None
_exporter_lock = threading.Lock()


def start_exporter(port: int = 0) -> MetricsExporter:
    """Start (or return the already-running) module exporter."""
    global _exporter
    with _exporter_lock:
        if _exporter is None:
            _exporter = MetricsExporter(port=port)
        return _exporter


def get_exporter() -> Optional[MetricsExporter]:
    return _exporter


def stop_exporter() -> None:
    global _exporter
    with _exporter_lock:
        if _exporter is not None:
            _exporter.stop()
            _exporter = None


def maybe_start_from_env() -> Optional[MetricsExporter]:
    """Arm from GRAPE_METRICS_PORT when set (the env twin of
    --metrics_port); invalid values are ignored, not fatal — a bad
    env var must not take down a serving process."""
    raw = os.environ.get(METRICS_PORT_ENV)
    if not raw:
        return None
    try:
        port = int(raw)
    except ValueError:
        return None
    if port < 0:
        return None
    try:
        return start_exporter(port)
    except OSError:
        return None
