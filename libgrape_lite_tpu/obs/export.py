"""Exporters: JSONL sink, Chrome trace_event JSON, span rollups.

Both file formats serialize the SAME event dicts (obs/events.py):

* JSONL — one event per line, append-mode, crash-tolerant: a killed
  process leaves every flushed line readable.  The first line of every
  flush batch is a `{"ph": "M"}` metadata block, so a file
  concatenated from several queries still labels its rows.
* Chrome JSON Object Format — `{"traceEvents": [...], ...}`, loadable
  in Perfetto / `chrome://tracing`.  Rewritten whole on each flush
  (the tracer keeps the full event history for it); `metadata`
  carries the trace id and the wall-clock anchor so a timeline can be
  correlated with external logs.

`rollup()` is the in-memory consumer: per-span-name wall-time totals
for bench.py's `obs` block and scripts/trace_report.py.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Iterable, List, Optional


def append_jsonl(events: Iterable[dict], path: str) -> int:
    """Append one JSON line per event; returns the count written."""
    n = 0
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "a") as fh:
        for ev in events:
            fh.write(json.dumps(ev, sort_keys=True))
            fh.write("\n")
            n += 1
    return n


def write_chrome_trace(events: List[dict], path: str, *,
                       trace_id: Optional[str] = None,
                       anchor: Optional[dict] = None) -> None:
    doc = {
        "traceEvents": list(events),
        "displayTimeUnit": "ms",
        "metadata": {
            "producer": "libgrape-lite-tpu obs/",
            **({"trace_id": trace_id} if trace_id else {}),
            **({"clock_anchor": anchor} if anchor else {}),
        },
    }
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(doc, fh)
        fh.write("\n")
    os.replace(tmp, path)  # a reader never sees a half-written trace


def load_trace(path: str) -> List[dict]:
    """Read events back from either format (by content, not extension):
    a JSON object with `traceEvents`, a JSON array, or JSONL."""
    with open(path) as fh:
        text = fh.read()
    stripped = text.lstrip()
    if stripped.startswith("{") :
        try:
            doc = json.loads(text)
        except json.JSONDecodeError:
            doc = None
        if isinstance(doc, dict) and "traceEvents" in doc:
            return list(doc["traceEvents"])
    if stripped.startswith("["):
        return list(json.loads(text))
    events = []
    for line in text.splitlines():
        line = line.strip()
        if line:
            events.append(json.loads(line))
    return events


def rollup(events: Iterable[dict],
           include_frag_rows: bool = False) -> Dict[str, dict]:
    """Per-span-name wall-time aggregation over `ph == "X"` events:
    {name: {count, total_s, mean_s, max_s}} — the bench `obs` block
    and the trace report's phase summary.  Per-fragment mirror rows
    (tid >= FRAG_TID_BASE) restate the same host interval once per
    fragment and are excluded unless asked for, so totals stay wall
    time rather than wall × fnum."""
    from libgrape_lite_tpu.obs.events import FRAG_TID_BASE

    acc: Dict[str, dict] = {}
    for ev in events:
        if ev.get("ph") != "X":
            continue
        if not include_frag_rows and ev.get("tid", 0) >= FRAG_TID_BASE:
            continue
        name = ev.get("name", "?")
        dur_s = float(ev.get("dur", 0)) / 1e6
        r = acc.get(name)
        if r is None:
            acc[name] = {"count": 1, "total_s": dur_s, "max_s": dur_s}
        else:
            r["count"] += 1
            r["total_s"] += dur_s
            r["max_s"] = max(r["max_s"], dur_s)
    for r in acc.values():
        r["total_s"] = round(r["total_s"], 6)
        r["max_s"] = round(r["max_s"], 6)
        r["mean_s"] = round(r["total_s"] / r["count"], 6)
    return acc
