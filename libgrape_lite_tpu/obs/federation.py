"""Stats federation — one registry for every ``*_STATS`` surface.

Before this module, operational truth was scattered over six
module-level registries, each with a private snapshot convention:
``PLAN_STATS`` / ``SPGEMM_STATS`` / ``PARTITION_STATS`` /
``PIPELINE_STATS`` were raw mutable dicts copied ad hoc, while
``PUMP_STATS`` / ``FLEET_STATS`` were classes with ``snapshot()``.
The federation gives them one namespace-keyed ``snapshot()`` /
``reset()`` API, and ``self_check()`` kills declared-but-unwired
namespaces the same way ``check_bench_schema.self_check()`` kills
declared-but-unwired bench blocks: ``EXPECTED`` names every namespace
the tree is supposed to register and the module that owns it, and the
check imports each owner and demands a live, JSON-serializable
registration.  grape-lint R8 (``unfederated-stats``) fossilizes the
retired class: a module-level ``*_STATS`` registry that never
registers here is a finding.

Registration happens at import of the owning module — the federation
itself imports nothing outside the stdlib, so any module (ops/,
fragment/, parallel/, serve/, fleet/) can register without a cycle.

``FederatedStats`` is the drop-in for the raw-dict registries: a
``dict`` subclass, so every existing ``STATS["k"] += 1`` hot-path
call site keeps working unchanged, but snapshots are taken under the
federation lock with per-value list/dict copies — callers can no
longer read a half-updated dict.
"""

from __future__ import annotations

import copy
import json
import threading
from typing import Any, Callable, Dict, List, Optional

# namespace -> {"snapshot": fn, "reset": fn|None, "module": str}
_REGISTRY: Dict[str, Dict[str, Any]] = {}
_LOCK = threading.Lock()

# The wiring contract: every namespace the shipped tree must register,
# and the module whose import performs the registration.  self_check()
# imports each owner — a namespace declared here but never registered
# (or registered with a broken snapshot) is an error, exactly the
# check_bench_schema discipline for bench blocks.
EXPECTED: Dict[str, str] = {
    "plan": "libgrape_lite_tpu.ops.spmv_pack",
    "spgemm": "libgrape_lite_tpu.ops.spgemm_pack",
    "partition": "libgrape_lite_tpu.fragment.partition",
    "pipeline": "libgrape_lite_tpu.parallel.pipeline",
    "pump": "libgrape_lite_tpu.serve.pipeline",
    "fleet": "libgrape_lite_tpu.fleet.budget",
    "slo": "libgrape_lite_tpu.obs.slo",
    "recorder": "libgrape_lite_tpu.obs.recorder",
    "autopilot": "libgrape_lite_tpu.autopilot.signals",
    "vc_tiles": "libgrape_lite_tpu.fragment.vertexcut",
    "gang": "libgrape_lite_tpu.obs.gang",
}


def register(
    namespace: str,
    snapshot: Callable[[], Dict[str, Any]],
    reset: Optional[Callable[[], None]] = None,
    module: str = "",
) -> None:
    """Register one stats surface under `namespace`.

    Re-registration of the same namespace overwrites (module reloads
    in tests re-run the module body); two DIFFERENT modules claiming
    one namespace is a wiring bug and raises.
    """
    if not namespace or not namespace.replace("_", "").isalnum():
        raise ValueError(f"bad federation namespace: {namespace!r}")
    with _LOCK:
        prev = _REGISTRY.get(namespace)
        if prev is not None and module and prev["module"] and \
                prev["module"] != module:
            raise ValueError(
                f"federation namespace {namespace!r} already "
                f"registered by {prev['module']} (now: {module})"
            )
        _REGISTRY[namespace] = {
            "snapshot": snapshot, "reset": reset, "module": module,
        }


def registered() -> List[str]:
    """Sorted namespaces currently registered."""
    with _LOCK:
        return sorted(_REGISTRY)


def snapshot(namespace: Optional[str] = None) -> Dict[str, Any]:
    """One coherent read of every registered surface (or just one).

    Returns ``{namespace: {field: value, ...}, ...}`` — with a
    namespace argument, that namespace's fields directly.
    """
    with _LOCK:
        if namespace is not None:
            ent = _REGISTRY.get(namespace)
            if ent is None:
                raise KeyError(
                    f"unregistered federation namespace: {namespace!r}"
                )
            return dict(ent["snapshot"]())
        return {ns: dict(ent["snapshot"]())
                for ns, ent in sorted(_REGISTRY.items())}


def reset(namespace: Optional[str] = None) -> None:
    """Reset one namespace, or every namespace that supports reset."""
    with _LOCK:
        if namespace is not None:
            ent = _REGISTRY.get(namespace)
            if ent is None:
                raise KeyError(
                    f"unregistered federation namespace: {namespace!r}"
                )
            ents = [ent]
        else:
            ents = list(_REGISTRY.values())
    for ent in ents:
        if ent["reset"] is not None:
            ent["reset"]()


def self_check() -> List[str]:
    """Errors when the wiring contract is broken, [] when clean.

    Imports every EXPECTED owner module (import performs the
    registration), then demands: the namespace is registered, its
    registered module matches the declaration, and its snapshot is a
    JSON-serializable dict.  Mirrors check_bench_schema.self_check():
    a declared-but-unwired namespace can never report clean.
    """
    import importlib

    errors: List[str] = []
    for ns, owner in sorted(EXPECTED.items()):
        try:
            importlib.import_module(owner)
        except Exception as e:  # pragma: no cover — partial checkouts
            errors.append(f"{ns}: owner module {owner} failed to "
                          f"import: {type(e).__name__}: {e}")
            continue
        with _LOCK:
            ent = _REGISTRY.get(ns)
        if ent is None:
            errors.append(
                f"{ns}: declared in federation.EXPECTED but never "
                f"registered by {owner} — declared-but-unwired"
            )
            continue
        if ent["module"] and ent["module"] != owner:
            errors.append(
                f"{ns}: registered by {ent['module']}, declared "
                f"owner is {owner}"
            )
        try:
            snap = ent["snapshot"]()
        except Exception as e:
            errors.append(f"{ns}: snapshot() raised "
                          f"{type(e).__name__}: {e}")
            continue
        if not isinstance(snap, dict):
            errors.append(f"{ns}: snapshot() returned "
                          f"{type(snap).__name__}, want dict")
            continue
        try:
            json.dumps(snap)
        except (TypeError, ValueError) as e:
            errors.append(f"{ns}: snapshot() not JSON-serializable: "
                          f"{e}")
    return errors


class FederatedStats(dict):
    """A module-level stats dict that self-registers at construction.

    Drop-in for the raw-dict registries: mutation sites keep the plain
    ``STATS["planned"] += 1`` / ``STATS["declines"].append(...)``
    idiom, but ``snapshot()`` copies under the federation lock (lists
    and dicts value-copied) and ``reset()`` restores the construction-
    time initial state — the snapshot protocol PumpStats/FleetStats
    already had, now shared by every registry.
    """

    def __init__(self, namespace: str, initial: Dict[str, Any],
                 register_: bool = True):
        super().__init__(copy.deepcopy(initial))
        self.namespace = namespace
        self._initial = copy.deepcopy(initial)
        if register_:
            register(namespace, self.snapshot, self.reset,
                     module=self.__class__.__module__
                     if type(self) is not FederatedStats
                     else _caller_module())

    def snapshot(self) -> Dict[str, Any]:
        out = {}
        for k, v in self.items():
            if isinstance(v, list):
                out[k] = list(v)
            elif isinstance(v, dict):
                out[k] = dict(v)
            else:
                out[k] = v
        return out

    def reset(self) -> None:
        self.clear()
        self.update(copy.deepcopy(self._initial))


def _caller_module() -> str:
    """Module name of the frame constructing a FederatedStats — the
    registry's owner for self_check's module-match."""
    import inspect

    frame = inspect.currentframe()
    try:
        # _caller_module <- __init__ <- owning module body
        f = frame.f_back.f_back
        while f is not None:
            mod = f.f_globals.get("__name__", "")
            if mod != __name__:
                return mod
            f = f.f_back
        return ""
    finally:
        del frame
