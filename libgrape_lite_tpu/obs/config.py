"""Global observability state: arming, env resolution, flushing.

Off by default.  Three ways to arm:

* env — `GRAPE_TRACE=/path/out.json` (Chrome trace; a JSONL twin is
  written next to it as `out.jsonl`) and/or `GRAPE_METRICS=/path/m`
  (writes `m.json` + `m.prom` at flush).  Resolved lazily on the first
  `obs.tracer()` / `obs.metrics()` call, so a plain
  `GRAPE_TRACE=t.json python -m libgrape_lite_tpu.cli ...` traces with
  no code involvement; an `atexit` hook guarantees the files land even
  when the driver never flushes explicitly.
* CLI — `--trace out.json` / `--metrics out` set the same config
  programmatically (runner.py).
* API — `obs.configure(trace_path=..., metrics_path=...,
  in_memory=True)`; `in_memory` arms the tracer+registry with no file
  sink (bench.py rolls spans up from the buffer itself).

The armed/disarmed decision is a host-side read; nothing here is
visible to jit tracing, so the fused path's lowered HLO is identical
either way (pinned by tests/test_obs.py).
"""

from __future__ import annotations

import atexit
import os
import threading
from typing import Optional

from libgrape_lite_tpu.obs import export as _export
from libgrape_lite_tpu.obs.metrics import (
    NULL_METRICS,
    MetricsRegistry,
)
from libgrape_lite_tpu.obs.tracer import DISABLED, Tracer

TRACE_ENV = "GRAPE_TRACE"
METRICS_ENV = "GRAPE_METRICS"

_lock = threading.Lock()
_state = {
    "resolved": False,     # env looked at yet?
    "tracer": DISABLED,
    "metrics": NULL_METRICS,
    "trace_path": None,    # Chrome JSON (raw, un-suffixed)
    "jsonl_path": None,
    "metrics_path": None,  # basename; .json/.prom appended
    "in_memory": False,    # keep history with no file sinks (bench)
    "chrome_history": [],  # full event history for whole-file rewrites
    "atexit": False,
}


def _jsonl_twin(trace_path: str) -> str:
    base, ext = os.path.splitext(trace_path)
    return (base if ext else trace_path) + ".jsonl"


def _rank_suffixed(path: Optional[str], rank: int,
                   default_ext: str) -> Optional[str]:
    if not path or not rank:
        return path
    base, ext = os.path.splitext(path)
    return f"{base}.r{rank}{ext or default_ext}"


def _sink_paths():
    """(trace, jsonl, metrics) paths with the per-rank suffix, resolved
    at FLUSH time: the tracer can be armed before
    jax.distributed.initialize (the runner arms obs before CommSpec),
    so the rank is only trustworthy once work has actually run — and
    multi-host processes must not clobber one file."""
    tr = _state["tracer"]
    rank = tr.pid if tr.enabled else 0
    return (
        _rank_suffixed(_state["trace_path"], rank, ".json"),
        _rank_suffixed(_state["jsonl_path"], rank, ".jsonl"),
        (f"{_state['metrics_path']}.r{rank}"
         if rank and _state["metrics_path"] else _state["metrics_path"]),
    )


def _resolve_env_locked() -> None:
    if _state["resolved"]:
        return
    _state["resolved"] = True
    trace = os.environ.get(TRACE_ENV, "")
    metrics = os.environ.get(METRICS_ENV, "")
    if trace or metrics:
        _configure_locked(
            trace_path=trace or None,
            metrics_path=metrics or None,
        )


def _configure_locked(*, trace_path: Optional[str] = None,
                      jsonl_path: Optional[str] = None,
                      metrics_path: Optional[str] = None,
                      in_memory: bool = False) -> None:
    if trace_path and not jsonl_path:
        jsonl_path = _jsonl_twin(trace_path)
    _state["trace_path"] = trace_path
    _state["jsonl_path"] = jsonl_path
    _state["metrics_path"] = metrics_path
    _state["in_memory"] = in_memory
    _state["tracer"] = Tracer(enabled=True)
    _state["metrics"] = MetricsRegistry()
    _state["chrome_history"] = []
    _state["resolved"] = True
    if not in_memory and not _state["atexit"]:
        _state["atexit"] = True
        atexit.register(flush)


def configure(*, trace_path: Optional[str] = None,
              jsonl_path: Optional[str] = None,
              metrics_path: Optional[str] = None,
              in_memory: bool = False) -> Tracer:
    """Arm observability programmatically; returns the new tracer."""
    with _lock:
        _configure_locked(
            trace_path=trace_path, jsonl_path=jsonl_path,
            metrics_path=metrics_path, in_memory=in_memory,
        )
        return _state["tracer"]


def reset() -> None:
    """Disarm and forget any env resolution (tests re-arm per case)."""
    with _lock:
        _state["resolved"] = False
        _state["tracer"] = DISABLED
        _state["metrics"] = NULL_METRICS
        _state["trace_path"] = None
        _state["jsonl_path"] = None
        _state["metrics_path"] = None
        _state["in_memory"] = False
        _state["chrome_history"] = []
    try:
        from libgrape_lite_tpu.obs import gang

        gang.reset()  # forget the cached clock handshake with the rest
    except Exception:
        pass


def tracer() -> Tracer:
    if not _state["resolved"]:
        with _lock:
            _resolve_env_locked()
    return _state["tracer"]


def metrics():
    if not _state["resolved"]:
        with _lock:
            _resolve_env_locked()
    return _state["metrics"]


def armed() -> bool:
    return tracer().enabled


def trace_id() -> Optional[str]:
    return tracer().trace_id


def flush() -> dict:
    """Drain buffered events to the configured sinks; returns
    {"events": n, "trace": path|None, "jsonl": path|None,
    "metrics": basename|None}.  Safe (and cheap) to call disarmed or
    with no file sinks configured — bench-style in-memory users read
    `tracer().events()` instead."""
    tr = _state["tracer"]
    out = {"events": 0, "trace": None, "jsonl": None, "metrics": None}
    if not tr.enabled:
        return out
    drained = tr.drain()
    out["events"] = len(drained)
    trace_path, jsonl_path, mp = _sink_paths()
    if jsonl_path and (drained or tr.metadata()):
        _export.append_jsonl(tr.metadata() + drained, jsonl_path)
        out["jsonl"] = jsonl_path
    if trace_path or _state["in_memory"]:
        # the chrome rewrite (and the in-memory rollup surface) needs
        # the full history; metrics-only arming has no consumer for
        # past events, so they are dropped after the drain instead of
        # growing host memory without bound
        _state["chrome_history"].extend(drained)
    if trace_path:
        _export.write_chrome_trace(
            tr.metadata() + _state["chrome_history"], trace_path,
            trace_id=tr.trace_id, anchor=tr.wall_anchor(),
        )
        out["trace"] = trace_path
    if mp:
        _state["metrics"].write(
            json_path=mp + ".json", prom_path=mp + ".prom"
        )
        out["metrics"] = mp
    if _state["trace_path"] and tr.nprocs > 1:
        # gang runs also rewrite this rank's sidecar so the rank-0
        # assembler (trace_report --gang) sees everything flushed so
        # far; single-process flushes never touch the gang dir
        try:
            from libgrape_lite_tpu.obs import gang

            gang.write_sidecar()
        except Exception:
            pass
    return out


def history() -> list:
    """Every event this armed session has recorded (flushed + pending)
    — the rollup surface for in-memory users."""
    tr = _state["tracer"]
    if not tr.enabled:
        return []
    return tr.metadata() + _state["chrome_history"] + list(tr._buf)
