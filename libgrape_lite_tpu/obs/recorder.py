"""Flight recorder — an always-cheap ring of recent events that dumps
a correlated postmortem bundle when something goes wrong.

The ring (``deque(maxlen=…)``, default 512) costs one append per
``record()`` whether or not anything ever breaks; there is no arming
step, so the events leading INTO a failure are already captured when
the failure fires.  Three triggers dump:

* a guard breach (guard/monitor.py ``_policy`` — carries the guard's
  forensic bundle),
* a fleet fence violation (fleet/router.py ``_check_fence``),
* a deadline storm (serve/queue.py — more than
  ``DEADLINE_STORM_THRESHOLD`` queries expired in one sweep).

A dump is written only when a sink is configured
(``GRAPE_POSTMORTEM=<dir>`` or ``set_sink()``); triggers without a
sink still count in the federated ``recorder`` namespace, so a scrape
shows that postmortem-worthy moments happened even when nobody kept
the bundles.  Triggers never raise: the recorder is a measurement
plane, not a control path.

Bundle schema (``grape-postmortem-v1``, rendered by the CLI
``postmortem`` subcommand):

* ``reason`` / ``detail`` — what tripped the dump,
* ``trace_id`` / ``wall_anchor`` — correlation to the Chrome trace,
* ``events`` — the recorder's own ring (admission/dispatch/…
  breadcrumbs),
* ``spans`` / ``instants`` — the last-N buffered tracer events,
  VERBATIM: tracer buffers hold final export-form dicts (µs
  timestamps), so each bundle span row is byte-identical to the same
  row in the flushed Chrome trace's ``traceEvents`` — the postmortem
  and the timeline can be joined row-for-row,
* ``federation`` — the full stats-federation snapshot (plan/spgemm/
  partition/pipeline/pump/fleet/slo/recorder ledgers),
* ``guard`` — the guard bundle when the trigger was a breach,
* ``extra`` — trigger-specific context (fence versions, expired ids).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from libgrape_lite_tpu.obs.federation import FederatedStats

POSTMORTEM_ENV = "GRAPE_POSTMORTEM"
RING_CAPACITY = 512
BUNDLE_SPANS = 256
DEADLINE_STORM_THRESHOLD = 8
BUNDLE_SCHEMA = "grape-postmortem-v1"

REC_STATS = FederatedStats("recorder", {
    "recorded": 0,
    "dropped": 0,
    "triggers": 0,
    "dumps": 0,
    "last_reason": None,
})


class FlightRecorder:
    """Bounded ring of breadcrumbs + the postmortem dump path."""

    def __init__(self, capacity: int = RING_CAPACITY):
        self._ring: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._sink: Optional[str] = None
        self._seq = 0

    # ---- always-cheap side ----------------------------------------------

    def record(self, kind: str, **detail) -> None:
        """One breadcrumb: a dict append into a bounded deque.  The
        deque drops the oldest entry itself; the drop counter keeps
        the loss visible on a scrape."""
        if len(self._ring) == self._ring.maxlen:
            REC_STATS["dropped"] += 1
        self._ring.append({
            "kind": kind, "t_ns": time.perf_counter_ns(), **detail,
        })
        REC_STATS["recorded"] += 1

    def events(self) -> List[dict]:
        return list(self._ring)

    # ---- dump side -------------------------------------------------------

    def set_sink(self, path: Optional[str]) -> None:
        """Directory bundles are written to (None → env only)."""
        self._sink = path

    def sink(self) -> Optional[str]:
        return self._sink or os.environ.get(POSTMORTEM_ENV) or None

    def build_bundle(self, reason: str,
                     extra: Optional[Dict[str, Any]] = None,
                     guard: Optional[Dict[str, Any]] = None) -> dict:
        from libgrape_lite_tpu import obs
        from libgrape_lite_tpu.obs import federation

        spans: List[dict] = []
        instants: List[dict] = []
        trace_id = None
        wall_anchor = None
        try:
            if obs.armed():
                trace_id = obs.trace_id()
                tr = obs.tracer()
                wall_anchor = tr.wall_anchor()
                # history events are the final export-form dicts —
                # copied by reference so a bundle row serializes
                # byte-identically to the same traceEvents row
                for ev in obs.history():
                    ph = ev.get("ph")
                    if ph == "X":
                        spans.append(ev)
                    elif ph == "i":
                        instants.append(ev)
                spans = spans[-BUNDLE_SPANS:]
                instants = instants[-BUNDLE_SPANS:]
        except Exception:  # never let forensics kill the patient
            pass
        try:
            fed = federation.snapshot()
        except Exception:
            fed = {}
        bundle = {
            "schema": BUNDLE_SCHEMA,
            "reason": reason,
            "trace_id": trace_id,
            "wall_anchor": wall_anchor,
            "events": self.events(),
            "spans": spans,
            "instants": instants,
            "federation": fed,
            "guard": guard,
            "extra": extra or {},
        }
        try:
            from libgrape_lite_tpu.obs.metrics import gang_identity

            rank, nprocs = gang_identity()
            if nprocs > 1:
                # who dumped this shard; single-process manifests stay
                # byte-identical to the pre-gang schema
                bundle["rank"] = rank
                bundle["nprocs"] = nprocs
        except Exception:
            pass
        return bundle

    def trigger(self, reason: str,
                extra: Optional[Dict[str, Any]] = None,
                guard: Optional[Dict[str, Any]] = None,
                incident: Optional[str] = None,
                filename: Optional[str] = None,
                ) -> Optional[str]:
        """Count the postmortem-worthy moment; dump a bundle when a
        sink is configured.  Returns the bundle path or None.  Never
        raises.

        `incident` stamps a gang-shared incident id into the bundle;
        `filename` overrides the default `postmortem_<reason>_<seq>`
        name (relative to the sink — obs/gang.py uses
        `incident_<id>/rank_<r>.json` so every rank's shard of one
        incident lands in one directory)."""
        try:
            REC_STATS["triggers"] += 1
            REC_STATS["last_reason"] = reason
            sink = self.sink()
            if not sink:
                return None
            bundle = self.build_bundle(reason, extra=extra,
                                       guard=guard)
            if incident:
                bundle["incident"] = incident
            with self._lock:
                self._seq += 1
                seq = self._seq
            os.makedirs(sink, exist_ok=True)
            if filename:
                path = os.path.join(sink, filename)
                os.makedirs(os.path.dirname(path), exist_ok=True)
            else:
                safe = "".join(
                    c if c.isalnum() or c in "-_" else "_"
                    for c in reason
                )
                path = os.path.join(
                    sink, f"postmortem_{safe}_{seq:03d}.json")
            tmp = path + ".tmp"
            with open(tmp, "w") as fh:
                json.dump(bundle, fh, indent=1, sort_keys=False,
                          default=str)
                fh.write("\n")
            os.replace(tmp, path)
            REC_STATS["dumps"] += 1
            try:
                from libgrape_lite_tpu import obs

                obs.tracer().instant(
                    "postmortem", reason=reason, path=path,
                )
            except Exception:
                pass
            return path
        except Exception:
            return None


RECORDER = FlightRecorder()
