"""Tracer: nested host spans with a disabled fast path.

Two design constraints rule this file:

1. **Disarmed cost is a branch, not a feature.**  Every call site in
   the worker's superstep loop runs `tracer.span(...)` unconditionally;
   with tracing off that call must cost well under a microsecond
   (pinned by tests/test_obs.py::test_disabled_span_overhead_budget),
   and the *compiled* fused path must be byte-identical to an
   obs-less build (pinned by the lowered-HLO test) — the same
   discipline guard/ established for guards-off.  A disabled tracer
   therefore returns one shared no-op span object from a two-branch
   method; no allocation, no clock read, no buffering.

2. **Armed cost stays off the device path.**  Spans buffer into a
   `collections.deque` — append is a single GIL-atomic bytecode, so
   concurrent emitters (the superstep loop, the checkpoint writer
   thread, a retry loop) never contend on a lock — and nothing is
   serialized until `flush()`.

Timing convention (the satellite fix for `Worker.query_stepwise`):
JAX dispatch is asynchronous, so a naive `t1 - t0` around a jitted
call measures only host-side enqueue for every round except the one
that forces a host read.  A span's clock therefore stops only after
the caller has synced on the device results (`jax.block_until_ready`
on the full carry) — `dur` is honest wall time including device
execution.  Callers that want the split call `span.mark("dispatched")`
between the dispatch returning and the sync: the span then reports
`dispatched_us` (host enqueue) and `device_wait_us` (sync wait, the
device-execution estimate) in its args.  The first round after a
compile still includes trace+compile time in `dispatch_us`; spans
never try to hide that — instead the worker calls
`span.mark("compiled")` on any round whose runner came out of a jit
cache MISS, so the span carries `compiled_us` and downstream readers
(the overlap truth meter, trace_report) can EXCLUDE compile rounds
from overlap accounting rather than silently folding compile time
into the measurement.
"""

from __future__ import annotations

import itertools
import threading
import time
import uuid
from collections import deque
from typing import Any, Dict, Optional

from libgrape_lite_tpu.obs.events import (
    FRAG_TID_BASE,
    counter_event,
    flow_event,
    instant_event,
    metadata_event,
    span_event,
)


class _NullSpan:
    """Shared no-op span: the entire disabled-tracer surface."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def mark(self, label: str) -> None:
        pass

    def set(self, **args) -> None:
        pass

    def close(self) -> None:
        pass


NULL_SPAN = _NullSpan()


class Span:
    """One armed span; created by `Tracer.span` and closed by the
    context manager (or an explicit `close()`)."""

    __slots__ = ("_tracer", "name", "args", "tid", "t0_ns", "dur_ns",
                 "_marks")

    def __init__(self, tracer: "Tracer", name: str, tid: int,
                 args: Dict[str, Any]):
        self._tracer = tracer
        self.name = name
        self.args = args
        self.tid = tid
        self.t0_ns = time.perf_counter_ns()
        self.dur_ns = 0
        self._marks = None

    def mark(self, label: str) -> None:
        """Record a named intermediate timestamp (µs offsets land in
        args as `<label>_us`); `dispatched` additionally yields
        `device_wait_us` = close - mark, the device-execution estimate
        under the sync-before-close convention."""
        if self._marks is None:
            self._marks = []
        self._marks.append((label, time.perf_counter_ns()))

    def set(self, **args) -> None:
        """Attach/overwrite args (visible in the exported event)."""
        self.args.update(args)

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            self.args["error"] = exc_type.__name__
        self.close()
        return False

    def close(self) -> None:
        end = time.perf_counter_ns()
        self.dur_ns = end - self.t0_ns
        if self._marks:
            for label, t in self._marks:
                self.args[f"{label}_us"] = round((t - self.t0_ns) / 1000.0, 3)
            last_label, last_t = self._marks[-1]
            if last_label == "dispatched":
                self.args["device_wait_us"] = round((end - last_t) / 1000.0, 3)
        self._tracer._emit_span(self)


class Tracer:
    """Buffered per-process span/instant/counter recorder.

    `enabled` is fixed at construction: the global disarmed tracer is a
    singleton whose `span()`/`instant()`/`counter()` are two-branch
    no-ops, and arming (obs.configure) swaps in a fresh enabled
    instance — call sites hold no state, they re-read the global
    through `obs.tracer()` per query."""

    def __init__(self, enabled: bool = True, *, rank: int | None = None,
                 nprocs: int | None = None):
        self.enabled = enabled
        self._rank_fallback = int(rank or 0)
        self._nprocs_fallback = int(nprocs or 1)
        self.trace_id = uuid.uuid4().hex if enabled else None
        self._buf = deque()  # lock-free: deque.append is GIL-atomic
        self._meta_rows: list = []  # (tid, name) thread rows
        self._tids: Dict[int, int] = {}
        self._tid_counter = itertools.count()
        self._lock = threading.Lock()  # tid registry only, never the hot path
        self._t_anchor_ns = time.perf_counter_ns()
        self._wall_anchor = time.time()

    @property
    def pid(self) -> int:
        """The process rank, read LIVE on every use: the tracer can be
        armed before `jax.distributed.initialize` lands (the runner
        arms obs before CommSpec), and this jax build's pre-init
        `process_id` default is 0 — indistinguishable from a final
        single-host rank — so caching would freeze every multi-host
        process at rank 0.  Events emitted before init carry pid 0;
        everything from the first collective onward (all query spans)
        carries the real rank."""
        try:
            from jax._src import distributed

            st = distributed.global_state
            if getattr(st, "client", None) is None:
                # jax.distributed not initialized: the pre-init
                # process_id default (0) is indistinguishable from a
                # real rank, so the constructor fallback wins — tests
                # build fake rank-r tracers this way
                return self._rank_fallback
            pid = st.process_id
            return int(pid) if pid is not None else self._rank_fallback
        except Exception:
            return self._rank_fallback

    @property
    def nprocs(self) -> int:
        """Gang size, read live like `pid` (same pre-init caveat); the
        constructor fallback lets tests build a fake rank-r-of-n tracer
        without touching jax.distributed."""
        try:
            from jax._src import distributed

            st = distributed.global_state
            if getattr(st, "client", None) is None:
                return self._nprocs_fallback
            n = getattr(st, "num_processes", None)
            return int(n) if n else self._nprocs_fallback
        except Exception:
            return self._nprocs_fallback

    # ---- track bookkeeping ----------------------------------------------

    def _tid(self) -> int:
        ident = threading.get_ident()
        tid = self._tids.get(ident)
        if tid is None:
            with self._lock:
                tid = self._tids.setdefault(ident, next(self._tid_counter))
            name = threading.current_thread().name
            self._meta_rows.append(
                (tid, "host" if tid == 0 else name)
            )
        return tid

    def frag_tid(self, fid: int) -> int:
        """The per-fragment track row (named lazily on first use)."""
        tid = FRAG_TID_BASE + int(fid)
        if tid not in self._tids:
            with self._lock:
                if tid not in self._tids:
                    self._tids[tid] = tid
                    self._meta_rows.append((tid, f"frag/{fid}"))
        return tid

    def lane_tid(self, lane: int) -> int:
        """The per-lane track row for serve/ batched dispatches: each
        query of a batch renders as its own Perfetto row (the lane's
        interval IS the batch dispatch interval — attribution, not
        measurement).  Like frag rows, lane rows restate host
        intervals, so the span rollup excludes them."""
        from libgrape_lite_tpu.obs.events import LANE_TID_BASE

        tid = LANE_TID_BASE + int(lane)
        if tid not in self._tids:
            with self._lock:
                if tid not in self._tids:
                    self._tids[tid] = tid
                    self._meta_rows.append((tid, f"lane/{lane}"))
        return tid

    def replica_tid(self, replica: int) -> int:
        """The per-replica track row for fleet/ routing: each
        replica's dispatch intervals render as their own Perfetto row
        (attribution across the replica set, like lane rows across a
        batch; excluded from the span rollup the same way)."""
        from libgrape_lite_tpu.obs.events import REPLICA_TID_BASE

        tid = REPLICA_TID_BASE + int(replica)
        if tid not in self._tids:
            with self._lock:
                if tid not in self._tids:
                    self._tids[tid] = tid
                    self._meta_rows.append((tid, f"replica/{replica}"))
        return tid

    # ---- emitters --------------------------------------------------------

    def _push(self, ev: Dict[str, Any]) -> None:
        """Buffer one event, stamping `rank`/`nprocs` when the process
        is part of a real gang.  Single-process exports (nprocs == 1)
        are untouched so rank-0 solo output stays byte-identical to
        the pre-gang schema."""
        n = self.nprocs
        if n > 1:
            ev["rank"] = ev["pid"]
            ev["nprocs"] = n
        self._buf.append(ev)

    def span(self, name: str, **args):
        if not self.enabled:
            return NULL_SPAN
        return Span(self, name, self._tid(), args)

    def _emit_span(self, span: Span) -> None:
        self._push(span_event(
            span.name, ts_ns=span.t0_ns, dur_ns=span.dur_ns,
            pid=self.pid, tid=span.tid,
            args=span.args or None,
        ))

    def emit_span_raw(self, name: str, *, t0_ns: int, dur_ns: int,
                      tid: int, **args) -> None:
        """Re-emit a span interval on another track (the worker mirrors
        superstep spans onto per-fragment rows: SPMD execution is
        lockstep across the mesh, so the host wall interval IS each
        fragment's interval)."""
        if not self.enabled:
            return
        self._push(span_event(
            name, ts_ns=t0_ns, dur_ns=dur_ns, pid=self.pid, tid=tid,
            args=args or None,
        ))

    def instant(self, name: str, **args) -> None:
        if not self.enabled:
            return
        self._push(instant_event(
            name, ts_ns=time.perf_counter_ns(), pid=self.pid,
            tid=self._tid(), args=args or None,
        ))

    def counter(self, name: str, **values) -> None:
        if not self.enabled:
            return
        self._push(counter_event(
            name, ts_ns=time.perf_counter_ns(), pid=self.pid,
            tid=self._tid(), values=values,
        ))

    def flow(self, name: str, *, flow_id: int, phase: str,
             cat: str = "gang", **args) -> None:
        """Emit one leg of a cross-rank flow arrow (ph s/t/f).  Every
        rank participating in one logical edge (a breach vote, a 2PC
        stage→commit) emits its own leg with the SAME `(cat, flow_id)`;
        the gang assembler merges them and Perfetto draws the arrow
        across process tracks."""
        if not self.enabled:
            return
        self._push(flow_event(
            name, ts_ns=time.perf_counter_ns(), pid=self.pid,
            tid=self._tid(), flow_id=flow_id, phase=phase, cat=cat,
            args=args or None,
        ))

    # ---- draining --------------------------------------------------------

    def drain(self) -> list:
        """Pop every buffered event (metadata rows stay; they re-export
        with every flush so partial files stay loadable)."""
        out = []
        while True:
            try:
                out.append(self._buf.popleft())
            except IndexError:
                return out

    def events(self) -> list:
        """Non-destructive snapshot: metadata + buffered events (test
        and rollup surface; flush() is the draining exporter)."""
        return self.metadata() + list(self._buf)

    def metadata(self) -> list:
        """Process/thread-name rows, built at export time so they
        carry the CURRENT rank (see the `pid` property)."""
        if not self.enabled:
            return []
        pid = self.pid
        rows = [metadata_event(
            "process_name", pid=pid, name=f"grape/r{pid}"
        )]
        rows += [
            metadata_event("thread_name", pid=pid, tid=tid, name=name)
            for tid, name in list(self._meta_rows)
        ]
        n = self.nprocs
        if n > 1:
            for ev in rows:
                ev["rank"] = pid
                ev["nprocs"] = n
        return rows

    def wall_anchor(self) -> Dict[str, float]:
        """Monotonic→wall-clock correlation for the export metadata."""
        return {
            "perf_counter_ns": self._t_anchor_ns,
            "unix_time": self._wall_anchor,
        }


#: the module-level disarmed singleton (obs/config.py swaps the global
#: reference; this instance is what every call site sees by default)
DISABLED = Tracer(enabled=False)
