"""Overlap truth meter: modeled `hidden_us_per_round` vs measured
`device_wait_us`, joined per plan uid.

Every pipeline/2-D engagement headline in this tree is *modeled*: the
overlap model prices boundary/interior edges and exchange bytes under
a rate profile and claims `hidden_us_per_round` of exchange time
hidden under interior compute.  The tracer, meanwhile, *measures*: a
span that `mark("dispatched")`s before syncing reports
`device_wait_us`, the honest device-execution estimate.  This module
reconciles the two — per plan uid, the correlation key grape-lint R12
requires every modeled claim to carry — and reports how large the
modeled claim is relative to the measured round wall
(``claim_frac = modeled_hidden_us_per_round / measured_round_us``).

A claim_frac above the limit (default 1.25) means the model claims to
hide more exchange per round than the whole measured round took —
physically impossible, so either the rate profile or the edge totals
are wrong.  The bench ``calibration`` lane gates exit-2 on exactly
that, but ONLY under an explicit ``GRAPE_RATE_PROFILE`` (the same
condition as its rate-drift gate): on the CPU-fallback bench host,
measured walls dwarf modeled TPU numbers, so the gate would never
fire and the report is informational.

Honesty rule: rounds whose span carries `compiled_us` (the worker
marks the first dispatch of a fresh-compiled runner) are EXCLUDED —
compile time in the denominator would launder the claim.

Joined rows feed the calibration harvest
(``ops.calibration.harvest_overlap``, armed by
``GRAPE_CALIBRATE_HARVEST``) so fitted rate profiles can see measured
overlap walls next to the spmv/spgemm surfaces.
"""

from __future__ import annotations

from typing import Dict, List, Optional

#: modeled hidden µs may not exceed the measured round wall by more
#: than this factor (a little slack for clock/model noise)
DEFAULT_CLAIM_LIMIT = 1.25


def _median(xs: List[float]) -> float:
    s = sorted(xs)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else (s[mid - 1] + s[mid]) / 2.0


def truth_report(events,
                 claim_limit: float = DEFAULT_CLAIM_LIMIT) -> dict:
    """Join every engaged pipelined query span in `events` against its
    measured device waits.

    Fused queries measure from the query span's own
    `device_wait_us` / `rounds`; stepwise queries join the superstep
    spans inside the query window (same pid) and take the median
    `device_wait_us`.  Spans carrying `compiled_us` are excluded (and
    counted) — see the module docstring."""
    evs = [e for e in events if isinstance(e, dict)]
    queries = [e for e in evs
               if e.get("ph") == "X" and e.get("name") == "query"]
    supersteps = [e for e in evs
                  if e.get("ph") == "X" and e.get("name") == "superstep"]
    rows: List[dict] = []
    excluded_compile = 0
    for q in queries:
        a = q.get("args") or {}
        pipe = a.get("pipeline") or {}
        if not pipe.get("engaged"):
            continue
        modeled = float(pipe.get("hidden_us_per_round") or 0.0)
        rounds = int(a.get("rounds") or 0)
        measured: Optional[float] = None
        n_meas = 0
        if "compiled_us" in a:
            # the whole fused dispatch included trace+compile: no
            # honest device split exists for this query
            excluded_compile += 1
        elif "device_wait_us" in a:
            # fused: one dispatch covers PEval + `rounds` IncEvals
            measured = float(a["device_wait_us"]) / max(rounds + 1, 1)
            n_meas = rounds + 1
        else:
            # stepwise: the per-round superstep spans inside the
            # query window carry the splits
            t0 = float(q.get("ts", 0))
            t1 = t0 + float(q.get("dur", 0))
            waits = []
            for s in supersteps:
                if s.get("pid") != q.get("pid"):
                    continue
                sa = s.get("args") or {}
                if "device_wait_us" not in sa:
                    continue
                ts = float(s.get("ts", 0))
                if not (t0 <= ts <= t1):
                    continue
                if "compiled_us" in sa:
                    excluded_compile += 1
                    continue
                waits.append(float(sa["device_wait_us"]))
            if waits:
                measured = _median(waits)
                n_meas = len(waits)
        row: Dict[str, object] = {
            "plan_uid": pipe.get("plan_uid") or "-",
            "mode": pipe.get("mode"),
            "modeled_hidden_us_per_round": modeled,
            "measured_round_us": measured,
            "rounds_measured": n_meas,
            "joined": measured is not None,
        }
        if measured is not None and measured > 0:
            frac = round(modeled / measured, 4)
            row["claim_frac"] = frac
            row["ok"] = frac <= claim_limit
        else:
            row["claim_frac"] = None
            row["ok"] = None
        rows.append(row)
    joined = [r for r in rows if r["joined"]]
    fracs = [r["claim_frac"] for r in joined
             if r["claim_frac"] is not None]
    return {
        "queries": len(rows),
        "joined": len(joined),
        "compile_rounds_excluded": excluded_compile,
        "claim_limit": claim_limit,
        "max_claim_frac": max(fracs) if fracs else None,
        "median_claim_frac": _median(fracs) if fracs else None,
        "ok": (all(bool(r["ok"]) for r in joined
                   if r["ok"] is not None)
               if joined else True),
        "rows": rows,
    }


def block_brief(report: dict) -> dict:
    """The bench-block form of a truth report: schema-stable scalars
    for the first joined row (check_bench_schema pins the keys)."""
    first = next((r for r in report["rows"] if r["joined"]), None) or {}
    return {
        "queries": int(report["queries"]),
        "joined": int(report["joined"]),
        "plan_uid": str(first.get("plan_uid") or "-"),
        "modeled_hidden_us_per_round": float(
            first.get("modeled_hidden_us_per_round") or 0.0),
        "measured_round_us": float(
            first.get("measured_round_us") or 0.0),
        "claim_frac": float(first.get("claim_frac") or 0.0),
        "compile_rounds_excluded": int(
            report["compile_rounds_excluded"]),
        "ok": bool(report["ok"]),
    }


def harvest_report(events_or_report, pipe_brief: Optional[dict] = None,
                   ) -> int:
    """Feed every joined reconciliation row into the calibration
    harvest buffer (no-op unless ``GRAPE_CALIBRATE_HARVEST`` is
    armed).  Accepts either a raw event list or an already-built
    truth report; `pipe_brief` supplies the edge/byte columns when
    the caller has the live plan brief (bench lanes do) — without it
    the row still lands with the span's modeled/measured pair but
    zero op columns, so it is skipped.  Returns rows harvested."""
    from libgrape_lite_tpu.ops import calibration as calib

    if not calib.harvest_armed():
        return 0
    report = (events_or_report
              if isinstance(events_or_report, dict)
              else truth_report(events_or_report))
    n = 0
    for row in report["rows"]:
        if not row["joined"]:
            continue
        brief = dict(pipe_brief or {})
        brief.setdefault("plan_uid", row["plan_uid"])
        brief.setdefault("hidden_us_per_round",
                         row["modeled_hidden_us_per_round"])
        sample = calib.harvest_overlap(
            brief, float(row["measured_round_us"]),
            max(int(row["rounds_measured"]), 1),
        )
        if sample is not None:
            n += 1
    return n
