"""CDLP — community detection by synchronous label propagation.

Re-design of `examples/analytical_apps/cdlp/cdlp.h` +
`cdlp_utils.h::update_label_fast`: labels start as vertex ids; each of
`max_round` rounds every vertex adopts the most frequent label among its
out-neighbors (previous-round values), ties broken toward the smallest
label (the reference sorts labels ascending and keeps the first strict
maximum).

TPU formulation of the mode computation — sort-free-loop, all segment
ops (no per-vertex hash map):

  1. gather labels, read one per edge,
  2. sort edge (src, label) pairs (`jnp.lexsort`),
  3. run-length encode equal (src,label) runs via boundary cumsum,
  4. per-edge run length -> per-src max run length (`segment_max`),
  5. among runs achieving the max, take the smallest label
     (`segment_min` over masked labels).

Everything is O(E log E) on device with static shapes; multi-edges
contribute multiplicity exactly like the reference's neighbor scan.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from libgrape_lite_tpu.app.base import ParallelAppBase, StepContext
from libgrape_lite_tpu.utils.types import LoadStrategy, MessageStrategy

class CDLP(ParallelAppBase):
    load_strategy = LoadStrategy.kOnlyOut
    message_strategy = MessageStrategy.kAlongOutgoingEdgeToOuterVertex
    result_format = "int"
    replicated_keys = frozenset({"step", "lut"})
    # r9: the mode fold is per-row multiset arithmetic — splitting the
    # edge set by destination row (boundary/interior) and folding each
    # part separately reproduces every row's (src,label) run structure
    # exactly, so the double-buffered round is byte-identical
    pipeline_state_key = "labels"

    def __init__(self, max_round: int = 10, label_dtype=np.int64):
        self.max_round = max_round
        self.label_dtype = label_dtype
        # test hooks: force the wide (variadic-sort) path even when the
        # packed-uint32 key would fit / force the dynamic-compression
        # path even when the static LUT pack would fit / shrink the
        # dynamic universe budget to exercise the in-jit wide fallback
        self._force_wide = False
        self._force_dynamic = False
        self._u_budget_override: int | None = None

    def init_state(self, frag, max_round: int | None = None):
        if max_round is not None:
            self.max_round = max_round
        import jax

        eff_dt = np.dtype(self.label_dtype)
        if eff_dt == np.int64 and not jax.config.jax_enable_x64:
            # device arrays will be int32 anyway; build host arrays in
            # the effective dtype so the BIG sentinel doesn't wrap
            eff_dt = np.dtype(np.int32)
        raw = np.asarray(frag.dev.oids)
        if raw.max(initial=0) >= np.iinfo(eff_dt).max:
            raise ValueError(
                f"vertex ids exceed the {eff_dt} label range; enable "
                "jax_enable_x64 (or pass label_dtype=np.int64 under x64) "
                "for 64-bit ids"
            )
        oids = raw.astype(eff_dt)
        big = np.iinfo(eff_dt).max
        labels = np.where(oids >= 0, oids, big)
        # static sorted label universe (labels only ever move between
        # existing ids); +1 slot so searchsorted results stay in range
        lut = np.sort(np.append(labels.reshape(-1), big))
        state = {"labels": labels, "step": np.int32(0), "lut": lut}
        # superstep pipelining (r9): gather exchange, oe pull; CDLPOpt
        # inherits (its shortcut only replaces peval — round 1 runs
        # serial on either path)
        from libgrape_lite_tpu.parallel.pipeline import resolve_pipeline

        self._pipeline = resolve_pipeline(
            frag, app_name=type(self).__name__, key="labels",
            direction="oe", mirror=None, pack=None, fold="min",
            with_weights=False,
        )
        if self._pipeline is not None:
            state.update(self._pipeline.host_entries)
            self.ephemeral_keys = frozenset(self._pipeline.host_entries)
        self._pipeline_uid = (
            self._pipeline.uid if self._pipeline is not None else -1
        )
        return state

    def _mode_fold(self, src, lab, full, lut, vp):
        """Per-row mode label from one (src, label) edge multiset:
        sort, run-length encode, max-run per row, ties to smallest
        label — the TPU counting kernel shared by the serial round and
        both pipelined parts (the fold only ever groups edges of equal
        src, so any edge subset CLOSED over destination rows — the
        full set, the boundary part, the interior part — yields the
        per-row result of the full fold for the rows it covers)."""
        dt = lab.dtype
        big = jnp.asarray(np.iinfo(np.dtype(dt).name).max, dt)
        n_pad = full.shape[0]
        rank_bits = max(1, int(np.ceil(np.log2(n_pad + 2))))
        src_bits = max(1, int(np.ceil(np.log2(vp + 2))))
        from jax import lax as jlax

        def _wide(src, lab):
            # ONE variadic lexicographic sort over the (src, label)
            # pair — `lax.sort` with num_keys=2 compares tuples
            # directly, so no rank LUT, no permutation gather, and no
            # second stable sort (the old lexsort fallback paid both).
            # Works at any label width the dtype admits.
            return jlax.sort((src, lab), num_keys=2)

        if rank_bits + src_bits <= 32 and not (
            self._force_wide or self._force_dynamic
        ):
            # labels always belong to the initial id universe, so they
            # rank into a static sorted LUT; packing (src, rank) into
            # one uint32 key lets ONE sort replace the two-key lexsort,
            # and (ss, ll) decode straight from the sorted keys — no
            # permutation gather
            rank = jnp.searchsorted(lut, lab).astype(jnp.uint32)
            key = (src.astype(jnp.uint32) << rank_bits) | rank
            key = jnp.sort(key)
            ss = (key >> rank_bits).astype(jnp.int32)
            ll = lut[
                jnp.minimum(key & jnp.uint32((1 << rank_bits) - 1),
                            jnp.uint32(n_pad)).astype(jnp.int32)
            ]
        elif 32 - src_bits >= 10 and not self._force_wide:
            # Dynamic label-universe compression (VERDICT r4 next #2;
            # reference XL-graph counterpart: cdlp_opt.h): when the
            # STATIC universe (n_pad ids) outgrows the 32-bit pack, the
            # LIVE universe usually hasn't — label propagation
            # coalesces labels geometrically, so after the first couple
            # of rounds the distinct-label count is far below n_pad.
            # Build the live universe each round from the gathered
            # state (one u32 sort of n_pad values — ~E/d of the edge
            # sort), rank edges into it, and let an in-jit lax.cond
            # pick the packed single-key sort when the universe fits
            # 2^(32 - src_bits), else the variadic wide sort.  Early
            # all-distinct rounds take the wide branch; coalesced
            # rounds (the bulk of max_round) take the packed one.
            dyn_bits = 32 - src_bits
            u_budget = 1 << dyn_bits
            u_budget = min(u_budget, int(2 ** np.ceil(np.log2(n_pad + 2))))
            if self._u_budget_override is not None:
                u_budget = self._u_budget_override
            # the cond predicate must be CHEAP in the non-engaging case
            # (RMAT's ~0.34n live universe never fits any 32-src_bits
            # budget, and a measured RMAT-20 A/B put an unconditional
            # universe sort at +23% per round): count distinct labels
            # by scatter into the static lut positions — O(n_pad)
            # searchsorted + scatter, no sort.  The universe SORT runs
            # inside the packed branch only.
            pos = jnp.searchsorted(lut, full)
            mark = jnp.zeros((n_pad + 1,), jnp.int32).at[pos].set(1)
            n_distinct = mark.sum()

            def _packed(args):
                src, lab, full = args
                su = jnp.sort(full)
                first_u = jnp.ones_like(su, dtype=bool).at[1:].set(
                    su[1:] != su[:-1]
                )
                uidx = jnp.cumsum(first_u.astype(jnp.int32)) - 1
                uniq = jnp.full((u_budget,), big, dt).at[
                    jnp.where(first_u, uidx, u_budget)
                ].set(su, mode="drop")
                rank = jnp.searchsorted(uniq, lab).astype(jnp.uint32)
                key = (src.astype(jnp.uint32) << dyn_bits) | rank
                key = jnp.sort(key)
                ss = (key >> dyn_bits).astype(jnp.int32)
                ll = uniq[
                    jnp.minimum(key & jnp.uint32((1 << dyn_bits) - 1),
                                jnp.uint32(u_budget - 1)).astype(jnp.int32)
                ]
                return ss, ll

            ss, ll = jlax.cond(
                n_distinct <= jnp.int32(u_budget), _packed,
                lambda args: _wide(args[0], args[1]), (src, lab, full),
            )
        else:
            # wide path (vertices/shard beyond even the dynamic pack,
            # or forced): see _wide
            ss, ll = _wide(src, lab)
        valid = ss != jnp.int32(vp)

        first = jnp.ones_like(ss, dtype=bool).at[1:].set(
            jnp.logical_or(ss[1:] != ss[:-1], ll[1:] != ll[:-1])
        )
        run_id = jnp.cumsum(first.astype(jnp.int32)) - 1
        e = ss.shape[0]
        run_len = self.segment_reduce(
            valid.astype(jnp.int32), run_id, e, "sum"
        )  # runs <= E, so size the table with e rows — when every
        # (src,label) pair is distinct, run_id reaches e-1 and must not
        # land in the sliced-off overflow segment
        c_e = run_len[run_id]

        cmax = self.segment_reduce(c_e, ss, vp, "max")
        is_best = jnp.logical_and(valid, c_e == cmax[jnp.minimum(ss, vp - 1)])
        cand = jnp.where(is_best, ll, big)
        return self.segment_reduce(cand, ss, vp, "min")

    def _propagate(self, ctx, frag, labels, lut):
        oe = frag.oe
        vp = frag.vp
        dt = labels.dtype
        big = jnp.asarray(np.iinfo(np.dtype(dt).name).max, dt)

        full = ctx.gather_state(labels)
        lab = jnp.where(oe.edge_mask, full[oe.edge_nbr], big)
        src = jnp.where(oe.edge_mask, oe.edge_src, jnp.int32(vp))
        new_lab = self._mode_fold(src, lab, full, lut, vp)

        has_out = frag.out_degree > 0
        keep = jnp.logical_or(~frag.inner_mask, ~has_out)
        return jnp.where(jnp.logical_or(keep, new_lab == big), labels, new_lab)

    def inceval_pipelined(self, ctx: StepContext, frag, state, xbuf):
        """Double-buffered round (parallel/pipeline.py, r9): fold the
        mode over the BOUNDARY rows' edges, kick off the next round's
        label exchange from them, fold the interior rows' edges under
        the in-flight collective, join.  Byte-identical to inceval:
        the edge split is closed over destination rows, so each part's
        (src,label) run structure matches the full fold row-for-row
        (see _mode_fold)."""
        pl = self._pipeline
        labels = state["labels"]
        lut = state["lut"]
        vp = frag.vp
        dt = labels.dtype
        big = jnp.asarray(np.iinfo(np.dtype(dt).name).max, dt)
        step = state["step"] + 1
        bmask = state["pl_bmask"]
        has_out = frag.out_degree > 0
        keep = jnp.logical_or(~frag.inner_mask, ~has_out)
        full = pl.splice(ctx, labels, state, xbuf)
        lab_b = jnp.where(
            state["pl_b_val"], full[state["pl_b_nbr"]], big
        )
        fold_b = self._mode_fold(
            state["pl_b_src"], lab_b, full, lut, vp
        )
        new_b = jnp.where(
            jnp.logical_or(keep, fold_b == big), labels, fold_b
        )
        xbuf2 = pl.kickoff(ctx, jnp.where(bmask, new_b, labels), state)
        # ---- pipelined window: carry reads below are named in
        # parallel/pipeline.PIPELINE_WINDOW_READS (grape-lint R6) ----
        lab_i = jnp.where(
            state["pl_i_val"], full[state["pl_i_nbr"]], big
        )
        fold_i = self._mode_fold(
            state["pl_i_src"], lab_i, full, lut, vp
        )
        new_i = jnp.where(
            jnp.logical_or(keep, fold_i == big), labels, fold_i
        )
        new = jnp.where(bmask, new_b, new_i)
        active = jnp.where(
            step >= jnp.int32(self.max_round), jnp.int32(0),
            jnp.int32(1),
        )
        return {"labels": new, "step": step, "lut": lut}, active, xbuf2

    def peval(self, ctx: StepContext, frag, state):
        # reference PEval: step=1, one propagation (cdlp.h PEval)
        labels = self._propagate(ctx, frag, state["labels"], state["lut"])
        state = dict(state, labels=labels, step=jnp.int32(1))
        active = jnp.int32(1 if self.max_round > 1 else 0)
        return state, active

    def inceval(self, ctx: StepContext, frag, state):
        step = state["step"] + 1
        labels = self._propagate(ctx, frag, state["labels"], state["lut"])
        active = jnp.where(step >= jnp.int32(self.max_round), jnp.int32(0), jnp.int32(1))
        return dict(state, labels=labels, step=step), active

    def invariants(self, frag, state):
        # Labels are NOT monotone under mode adoption (the most
        # frequent neighbor label can exceed the current one — that is
        # why CDLP runs a fixed round budget), so the sound invariant
        # is universe membership: every label is an id that existed at
        # init (<= the max initial label) or the pad sentinel.
        from libgrape_lite_tpu.guard.invariants import Invariant

        def in_universe(dev, prev, cur):
            lab = cur["labels"]
            dt = lab.dtype
            big = jnp.asarray(np.iinfo(np.dtype(dt).name).max, dt)
            # the largest real id in the sorted universe (the lut also
            # holds one sentinel per padded row, so filter rather than
            # index from the end)
            lut = cur["lut"]
            max_id = jnp.max(jnp.where(lut < big, lut, jnp.asarray(-1, dt)))
            ok = jnp.logical_and(
                lab >= 0,
                jnp.logical_or(lab <= max_id, lab == big),
            )
            nbad = (~ok).sum().astype(jnp.int32)
            return nbad == 0, nbad.astype(jnp.float32)

        return [Invariant(
            "cdlp_label_universe", in_universe, ("labels", "lut"),
            "labels stay within the initial id universe (or the pad "
            "sentinel)",
        )]

    def finalize(self, frag, state):
        labels = np.asarray(state["labels"])
        if frag.is_string_keyed():
            # device labels are pid surrogates (edgecut oids array);
            # map back to the original string ids for output
            flat = labels.reshape(-1)
            uniq = np.unique(flat[flat >= 0])
            lut = {
                int(p): o
                for p, o in zip(uniq, np.asarray(frag.pid_to_oid(uniq)).tolist())
            }
            return np.vectorize(
                lambda x: lut.get(int(x), -1), otypes=[object]
            )(labels)
        return labels


class CDLPOpt(CDLP):
    """CDLP with the reference's first-round shortcut
    (`cdlp_opt.h:139-162`, `cdlp_opt_ud.h:148-162`): initial labels are
    all-distinct vertex ids, so "most frequent, ties to smallest"
    degenerates to a plain neighbor minimum — one O(E) segment_min pull
    replaces the O(E log E) sort-mode pipeline for round 1.  (Like the
    reference shortcut, this assumes a simple graph: a parallel edge
    would give its endpoint's label multiplicity ≥ 2 in round 1 and the
    true mode could differ from the min.  LDBC inputs are simple.)

    The reference's remaining opt machinery maps as follows (argued in
    PARITY.md):
      * sparse change-frontier rounds (`cdlp_opt_ud.h:89-120`,
        threshold in `cdlp_opt_context.h`) — N/A on TPU: the dense
        masked formulation recomputes every row at full VPU width
        regardless, so a sparse frontier saves nothing and costs a
        gather;
      * `update_label_fast_{jump,sparse,dense}` per-vertex counting
        kernels (`cdlp_utils.h`) — scalar-CPU/SIMD concerns; the
        packed-key sort + run-length encode here IS the vectorized
        counting kernel;
      * `ud` (undirected-only load) — the oe==ie aliased CSR already
        halves storage for undirected graphs (fragment/edgecut.py).
    Output is bit-identical to CDLP for every round count.
    """

    def peval(self, ctx: StepContext, frag, state):
        labels = state["labels"]
        oe = frag.oe
        dt = labels.dtype
        big = jnp.asarray(np.iinfo(np.dtype(dt).name).max, dt)
        full = ctx.gather_state(labels)
        cand = jnp.where(oe.edge_mask, full[oe.edge_nbr], big)
        mn = self.segment_reduce(cand, oe.edge_src, frag.vp, "min")
        has_out = frag.out_degree > 0
        keep = jnp.logical_or(~frag.inner_mask, ~has_out)
        new = jnp.where(jnp.logical_or(keep, mn == big), labels, mn)
        state = dict(state, labels=new, step=jnp.int32(1))
        return state, jnp.int32(1 if self.max_round > 1 else 0)
