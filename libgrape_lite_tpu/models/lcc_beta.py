"""LCCBeta — scalable LCC via sorted-adjacency merge intersection.

Re-design of `examples/analytical_apps/lcc/lcc_beta.h` (the reference's
alternative LCC) with the round-2 scaling goal (ROADMAP item 3): the
packed-bitmap LCC (models/lcc.py) costs O(N/32) words per row — ideal
for LDBC-scale graphs, wrong beyond ~2^21 vertices.  This variant
intersects *sorted oriented neighbor lists* instead:

  * the degree-oriented DAG's out-adjacency is materialised as a padded
    ELL block `[vp, D] int32` (D = max oriented out-degree, bounded by
    graph degeneracy — O(sqrt(2E)) worst case), rows sorted ascending;
  * for every oriented edge (v, u): a batched `searchsorted` of N+(v)
    into N+(u) finds the common members w — one pass yields all three
    triangle credits (v and u by count, each w by scatter on the
    matched values), so no reverse (N−) structure and no second pass;
  * remote rows ride the same ring `ppermute` as the bitmap kernel;
    credits accumulate in a pid-indexed vector folded by one `psum`.

Working set is O(chunk · D) — independent of vertex count.  Exactness
matches the golden within eps like models/lcc.py: triangle enumeration
is orientation-agnostic (each triangle is found exactly once at its
DAG-minimal edge and all three credits scatter), so the kernels agree
even though this one defaults to the "lo" orientation while the bitmap
kernel keeps the reference's "hi" convention (simple-graph multiplicity
assumption documented there).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from libgrape_lite_tpu.app.base import ParallelAppBase, StepContext
from libgrape_lite_tpu.parallel.comm_spec import FRAG_AXIS
from libgrape_lite_tpu.utils.types import LoadStrategy, MessageStrategy


class LCCBeta(ParallelAppBase):
    load_strategy = LoadStrategy.kOnlyOut
    message_strategy = MessageStrategy.kAlongOutgoingEdgeToOuterVertex
    result_format = "float"

    # "lcc": full triple crediting + clustering-coefficient ratio.
    # "apex": apex-only triangle counts (each triangle counted once at
    # its DAG apex) — the k=3 clique-counting mode used by KClique.
    credit_mode = "lcc"
    # DAG orientation for the ELL build: "lo" = edges point to the
    # higher-(degree,id) endpoint, bounding max out-degree D by graph
    # DEGENERACY instead of hub degree.  Triangle enumeration is
    # orientation-agnostic (each triangle is found exactly once, at its
    # DAG-minimal edge, and all three credits are scattered), so this
    # is purely the scaling choice: under "hi" a RMAT-24 hub row would
    # be D = 6202+ (a ~52 GB ELL); under "lo" D stays at degeneracy
    # scale (VERDICT r4 weak #6).  Exception: degree_threshold > 0
    # switches back to "hi", because the reference's filter semantics
    # (`lcc.h:234-243`: apex and middle unfiltered, far end exempt) are
    # DEFINED on lower-degree neighbor lists — and under "lo" hub rows
    # are already degeneracy-short, so the cost cap is moot anyway.
    orientation = "lo"

    def _eff_orientation(self) -> str:
        # the threshold flip applies ONLY to lcc crediting: apex-mode
        # subclasses (ApexTriangleCount, the clique kernels) pin "lo"
        # because their per-apex attribution and hub_cap gating are
        # defined on the degeneracy-bounded orientation
        if self.credit_mode == "lcc" and getattr(
            self, "degree_threshold", 0
        ) > 0:
            return "hi"
        return self.orientation

    def init_state(self, frag, degree_threshold: int = 0, **_):
        """Host prep: dedup degree-oriented out-adjacency as sorted,
        padded ELL blocks (the analogue of lcc.h stage-1 neighbor
        filtering, done once against the host CSRs).

        degree_threshold > 0 drops filtered (hub) vertices' lists — the
        reference's LCC cost cap (`lcc.h:234-243`, 0 = disabled)."""
        from libgrape_lite_tpu.ops.spgemm_pack import resolve_lcc_backend

        # GRAPE_LCC_BACKEND = spgemm/auto: the merge-intersection
        # kernel has no spgemm lowering — RECORDED decline (never
        # silent), results stay intersect-parity
        resolve_lcc_backend(
            type(self).__name__, frag, supported=False,
            unsupported_reason="merge-intersection ELL kernel has no "
            "spgemm lowering (use lcc_bitmap/lcc_opt)",
        )
        self.degree_threshold = int(degree_threshold)
        fnum, vp = frag.fnum, frag.vp
        n_pad = fnum * vp
        sent = n_pad  # sorts last, never matches a valid query

        # global degree (incl multiplicity) per pid
        deg = np.zeros(n_pad, dtype=np.int64)
        for f in range(fnum):
            deg[f * vp : (f + 1) * vp] = np.diff(frag.host_oe[f].indptr)

        rows_per_frag = []
        cnts = np.zeros((fnum, vp), dtype=np.int32)
        d_max = 1
        for f in range(fnum):
            c = frag.host_oe[f]
            e = c.num_edges
            v = f * vp + c.edge_src[:e].astype(np.int64)
            u = c.edge_nbr[:e].astype(np.int64)
            pairs = np.unique(np.stack([v, u], 1), axis=0)
            v, u = pairs[:, 0], pairs[:, 1]
            if self._eff_orientation() == "lo":
                # low->high: out-degree bounded by degeneracy (hubs
                # keep only higher-degree neighbors — few); the k=4
                # kernel uses this to stay under hub_cap on power-law
                # graphs
                keep = (deg[u] > deg[v]) | ((deg[u] == deg[v]) & (u > v))
            else:
                keep = (deg[u] < deg[v]) | ((deg[u] == deg[v]) & (u < v))
            keep &= u != v
            if self.degree_threshold > 0:
                keep &= deg[v] <= self.degree_threshold
            v, u = v[keep], u[keep]
            lid = (v - f * vp).astype(np.int64)
            cnt = np.bincount(lid, minlength=vp).astype(np.int32)
            cnts[f] = cnt
            d_max = max(d_max, int(cnt.max(initial=1)))
            rows_per_frag.append((lid, u, cnt))

        est_bytes = fnum * vp * d_max * 4
        if est_bytes > 8 << 30:
            from libgrape_lite_tpu.utils import logging as glog

            # --degree_threshold switches to "hi" rows whose width is
            # bounded by the threshold itself, so only a value that
            # keeps n_pad*t*4 under budget actually helps — print it
            t_fit = (8 << 30) // max(fnum * vp * 4, 1)
            glog.log_info(
                f"LCC ELL estimate {est_bytes / (1 << 30):.1f} GiB "
                f"(n_pad={fnum * vp:,} x D={d_max}); "
                f"--degree_threshold below ~{t_fit} caps hub rows "
                "(reference FLAGS_degree_threshold, lcc.h:234-243)"
            )
        # build int32 in place: an int64 staging copy + stack + astype
        # would peak ~5x the printed estimate on the host
        stacked = np.full((fnum, vp, d_max), sent, dtype=np.int32)
        for f in range(fnum):
            lid, u, cnt = rows_per_frag[f]
            order = np.lexsort((u, lid))
            lid_s, u_s = lid[order], u[order]
            starts = np.zeros(vp, dtype=np.int64)
            np.cumsum(cnt[:-1], out=starts[1:])
            col = np.arange(len(lid_s)) - starts[lid_s]
            stacked[f, lid_s, col] = u_s  # ascending per row (lexsort)

        eperm = self._build_tier_perm(frag, cnts, d_max)
        state = {
            "ell": stacked,
            "cnt": cnts,
            "lcc": np.zeros((fnum, vp), dtype=np.float64),
        }
        if eperm is not None:
            state["eperm"] = eperm
            # read-only schedule table: keep it out of the fused-loop
            # carry and the result state (the spmv_pack stream-table
            # convention, worker.py eph_part)
            self.ephemeral_keys = frozenset({"eperm"})
        return state

    # width ladder for the tiered merge passes; "0" disables tiering.
    # Subclasses that override peval with their own edge walk (the
    # clique kernels) set uses_tiered_pass = False so they don't pay
    # the host bucketing pass or carry a dead schedule table.
    _TIER_WIDTHS = (64, 256)
    uses_tiered_pass = True

    def _build_tier_perm(self, frag, cnts, d_max):
        """Tiered edge schedule (r5): the query side of the merge pass
        costs W_query x log(D) per edge, but the average oriented
        out-degree is far below D (RMAT-22: mean 16 vs D 1030 — 98% of
        searchsorted lanes probe ELL padding, on the CPU substrate and
        the TPU VPU alike).  Bucket every oe edge by its SOURCE row's
        ELL width and process each bucket at its own static width:
        tier t covers rows with cnt <= W_t, so its queries slice
        `ell[:, :W_t]` with zero semantic change (the sliced-off lanes
        were invalid by qvalid anyway).

        Produces state["eperm"] [fnum, L] int32 — per-tier segments of
        oe-edge indices, sentinel Ep in the padding slots — plus
        self._tier_info [(offset, n_chunks, chunk, W)] with segment
        geometry uniform across shards (max over shards, padded to the
        tier's chunk size), as shard_map needs one static program."""
        import os

        if not self.uses_tiered_pass:
            self._tier_info = None
            return None
        spec = os.environ.get("GRAPE_LCC_TIERS")
        if spec == "0":
            self._tier_info = None
            return None
        req = self._TIER_WIDTHS
        if spec:
            try:
                req = tuple(int(x) for x in spec.split(","))
            except ValueError:
                from libgrape_lite_tpu.utils import logging as glog

                glog.log_info(
                    f"GRAPE_LCC_TIERS={spec!r} is not a comma-separated "
                    "int list; using the default width ladder"
                )
        widths = [w for w in req if 0 < w < d_max]
        widths = sorted(set(widths)) + [d_max]
        if len(widths) == 1:
            self._tier_info = None  # nothing to tier
            return None

        fnum, vp = frag.fnum, frag.vp
        ep = len(frag.host_oe[0].edge_src)
        bounds = np.asarray(widths, dtype=np.int64)
        per_shard = []  # [fnum][tier] -> edge index arrays
        for f in range(fnum):
            src = np.asarray(frag.host_oe[f].edge_src, dtype=np.int64)
            c = np.append(cnts[f], 0)  # pad rows (src == vp) -> cnt 0
            tier = np.searchsorted(bounds, c[np.minimum(src, vp)],
                                   side="left")
            per_shard.append(
                [np.flatnonzero(tier == t).astype(np.int32)
                 for t in range(len(widths))]
            )

        info = []
        segs = [[] for _ in range(fnum)]
        offset = 0
        for t, w in enumerate(widths):
            c_t = max(128, min(4096, (1 << 22) // max(w, 1)))
            n_t = max(len(per_shard[f][t]) for f in range(fnum))
            n_t = -(-max(n_t, 1) // c_t) * c_t  # pad to chunk multiple
            for f in range(fnum):
                seg = np.full(n_t, ep, dtype=np.int32)  # Ep = sentinel
                idx = per_shard[f][t]
                seg[: len(idx)] = idx
                segs[f].append(seg)
            info.append((offset, n_t // c_t, c_t, w))
            offset += n_t
        self._tier_info = info
        return np.stack([np.concatenate(s) for s in segs])

    def _oriented_edge_mask(self, ctx, frag):
        """Traced oriented-dedup edge mask over frag.oe — the SAME rule
        as the host ELL build, honoring `self._eff_orientation()` (shared by
        the LCC pass and the k=4 kernel so the two can never drift)."""
        from libgrape_lite_tpu.models.lcc import LCC

        vp = frag.vp
        my_fid = lax.axis_index(FRAG_AXIS).astype(jnp.int32)
        oe = frag.oe
        deg_local = frag.out_degree
        deg_full = ctx.gather_state(deg_local)
        row_pid = my_fid * vp + jnp.minimum(oe.edge_src, vp - 1)
        d_row = deg_local[jnp.minimum(oe.edge_src, vp - 1)]
        d_nbr = deg_full[oe.edge_nbr]
        if self._eff_orientation() == "lo":
            keep = jnp.logical_or(
                d_nbr > d_row,
                jnp.logical_and(d_nbr == d_row, oe.edge_nbr > row_pid),
            )
        else:
            keep = jnp.logical_or(
                d_nbr < d_row,
                jnp.logical_and(d_nbr == d_row, oe.edge_nbr < row_pid),
            )
        keep = jnp.logical_and(LCC._dedup_mask(oe), keep)
        keep = jnp.logical_and(keep, oe.edge_nbr != row_pid)
        if self.degree_threshold > 0:
            # filtered v enumerates no oriented edges; a filtered middle
            # u's ELL row is already empty (host build dropped it)
            keep = jnp.logical_and(keep, d_row <= self.degree_threshold)
        return keep

    def peval(self, ctx: StepContext, frag, state):
        vp, fnum = frag.vp, frag.fnum
        n_pad = vp * fnum
        my_fid = lax.axis_index(FRAG_AXIS).astype(jnp.int32)

        ell, cnt = state["ell"], state["cnt"]
        d = ell.shape[-1]
        oe = frag.oe

        keep = self._oriented_edge_mask(ctx, frag)

        ep = oe.edge_src.shape[0]
        # chunk size bounded so chunk*d stays ~4M int32 entries
        c_e = max(128, min(4096, (1 << 22) // max(d, 1)))
        c_e = min(c_e, ep)
        n_chunks = max(1, -(-ep // c_e))
        nbr_fid = (oe.edge_nbr // vp).astype(jnp.int32)
        nbr_lid = (oe.edge_nbr % vp).astype(jnp.int32)

        cred = jnp.zeros((n_pad + 1,), dtype=jnp.int32)
        tier_info = getattr(self, "_tier_info", None)
        tiered = tier_info is not None and "eperm" in state
        if tiered:
            eperm = state["eperm"]
            # per-tier query tables: static slices of the local ELL
            # (queries always come from LOCAL rows; only the target
            # side rides the ring at full width)
            tier_ells = [ell[:, :w] for (_, _, _, w) in tier_info]

        def chunk_credit(cr, srcs, nlid_c, sel, q, qv, rot_ell, rot_cnt,
                         cur_fid):
            """Shared credit math for one chunk: q [C, W] queries from
            local rows `srcs`, targets = rot_ell rows of nlid_c."""
            sl = jnp.minimum(srcs, vp - 1)
            tgt = rot_ell[nlid_c]               # [C, D] sorted (N+(u))
            tcnt = rot_cnt[nlid_c]
            pos = jax.vmap(jnp.searchsorted)(tgt, q)  # [C, W]
            pos_c = jnp.minimum(pos, d - 1)
            hit = jnp.take_along_axis(tgt, pos_c, axis=1) == q
            hit = jnp.logical_and(hit, pos < tcnt[:, None])
            hit = jnp.logical_and(hit, qv)
            hit = jnp.logical_and(hit, sel[:, None])

            c1 = hit.sum(axis=1, dtype=jnp.int32)
            v_pid = my_fid * vp + sl  # local row pid
            cr = cr.at[jnp.where(sel, v_pid, n_pad)].add(
                jnp.where(sel, c1, 0)
            )
            if self.credit_mode == "lcc":
                u_pid = cur_fid * vp + nlid_c
                cr = cr.at[jnp.where(sel, u_pid, n_pad)].add(
                    jnp.where(sel, c1, 0)
                )
                # far-end credits: +1 per matched member value
                w_idx = jnp.where(hit, q, jnp.int32(n_pad))
                cr = cr.at[w_idx.reshape(-1)].add(
                    hit.reshape(-1).astype(jnp.int32)
                )
            return cr

        def pass_for(carry_cred, rot_ell, rot_cnt, cur_fid):
            if tiered:
                cr = carry_cred
                for (off, n_chunks_t, c_t, w_t), ell_t in zip(
                    tier_info, tier_ells
                ):
                    def body(i, cr, off=off, c_t=c_t, w_t=w_t,
                             ell_t=ell_t):
                        idx = lax.dynamic_slice(
                            eperm, (off + i * c_t,), (c_t,)
                        )
                        vld = idx < ep          # Ep = padding sentinel
                        ic = jnp.minimum(idx, ep - 1)
                        srcs = oe.edge_src[ic]
                        nfid_c = nbr_fid[ic]
                        nlid_c = nbr_lid[ic]
                        sel = jnp.logical_and(
                            jnp.logical_and(vld, keep[ic]),
                            nfid_c == cur_fid,
                        )
                        sl = jnp.minimum(srcs, vp - 1)
                        q = ell_t[sl]           # [C, W_t]
                        # tier rows have cnt <= W_t by construction
                        qv = jnp.arange(w_t)[None, :] < cnt[sl][:, None]
                        return chunk_credit(
                            cr, srcs, nlid_c, sel, q, qv, rot_ell,
                            rot_cnt, cur_fid,
                        )

                    cr = lax.fori_loop(0, n_chunks_t, body, cr)
                return cr

            def body(i, cr):
                start = jnp.minimum(i * c_e, ep - c_e)
                pos0 = start + jnp.arange(c_e, dtype=jnp.int32)
                fresh = pos0 >= i * c_e
                srcs = lax.dynamic_slice(oe.edge_src, (start,), (c_e,))
                nfid = lax.dynamic_slice(nbr_fid, (start,), (c_e,))
                nlid = lax.dynamic_slice(nbr_lid, (start,), (c_e,))
                kept = lax.dynamic_slice(keep, (start,), (c_e,))
                sel = jnp.logical_and(jnp.logical_and(kept, fresh),
                                      nfid == cur_fid)

                sl = jnp.minimum(srcs, vp - 1)
                q = ell[sl]                     # [C, D] queries (N+(v))
                qv = jnp.arange(d)[None, :] < cnt[sl][:, None]
                return chunk_credit(
                    cr, srcs, nlid, sel, q, qv, rot_ell, rot_cnt,
                    cur_fid,
                )

            return lax.fori_loop(0, n_chunks, body, carry_cred)

        if fnum == 1:
            cred = pass_for(cred, ell, cnt, jnp.int32(0))
        else:
            perm = [(i, (i - 1) % fnum) for i in range(fnum)]

            def ring_body(s, carry):
                cr, r_ell, r_cnt = carry
                cur_fid = (my_fid + s) % fnum
                cr = pass_for(cr, r_ell, r_cnt, cur_fid)
                r_ell = lax.ppermute(r_ell, FRAG_AXIS, perm)
                r_cnt = lax.ppermute(r_cnt, FRAG_AXIS, perm)
                return cr, r_ell, r_cnt

            cred, _, _ = lax.fori_loop(
                0, fnum, ring_body, (cred, ell, cnt)
            )

        total = ctx.sum(cred[:n_pad])
        tri = lax.dynamic_slice(total, (my_fid * vp,), (vp,))

        if self.credit_mode == "apex":
            # raw per-apex triangle counts (k=3 clique counting) stay
            # integer end to end — float32 would round above 2^24
            out = jnp.where(frag.inner_mask, tri, 0).astype(jnp.int32)
            return dict(state, tri=out), jnp.int32(0)
        dt = state["lcc"].dtype
        deg_local = frag.out_degree
        degf = deg_local.astype(dt)
        denom = degf * (degf - 1)
        lcc = jnp.where(
            jnp.logical_and(frag.inner_mask, deg_local >= 2),
            2.0 * tri.astype(dt) / jnp.maximum(denom, 1),
            jnp.asarray(0, dt),
        )
        return dict(state, lcc=lcc), jnp.int32(0)

    def inceval(self, ctx, frag, state):
        return state, jnp.int32(0)

    def finalize(self, frag, state):
        return np.asarray(state["lcc"])


class ApexTriangleCount(LCCBeta):
    """k=3 clique counting: the merge kernel in apex-only credit mode
    with integer counts (used by models/kclique.py).  Uses the same
    low->high orientation as the k=4 kernel and the host recursion, so
    per-apex attribution is consistent across every k (each clique
    credits its (degree, id)-minimal member)."""

    credit_mode = "apex"
    orientation = "lo"
    result_format = "int"

    def init_state(self, frag, **kw):
        state = super().init_state(frag, **kw)
        state["tri"] = np.zeros((frag.fnum, frag.vp), dtype=np.int32)
        return state

    def finalize(self, frag, state):
        return np.asarray(state["tri"]).astype(np.int64)
