"""BC — betweenness centrality from a single source (Brandes).

Re-design of `examples/analytical_apps/bc/bc.h` (two-stage: forward BFS
accumulating shortest-path counts, then a level-by-level backward
dependency sweep pushed along out-edges to depth-1 predecessors;
`bc.h:162-178, 199-220`).

TPU formulation: both stages are `lax.while_loop`s over depth levels
inside one traced PEval:

  forward  d -> d+1:  pn_new[v] = Σ_{(u,v) in-edges, depth[u]==d} pn[u]
                      (gather + segment_sum), newly-reached vertices get
                      depth d+1 — path counting and BFS fused,
  backward d+1 -> d:  delta[u] = pn[u] · Σ_{(v,u) in-edges,
                      depth[v]==d+1} (1+delta[v])/pn[v]
                      — identical update order to the reference's
                      accum/multiply form (`bc.h:205-211`).

Output value = the dependency (the reference's `centrality_value`).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
from jax import lax

from libgrape_lite_tpu.app.base import ParallelAppBase, StepContext
from libgrape_lite_tpu.utils.types import LoadStrategy, MessageStrategy

_SENT = np.iinfo(np.int32).max


class BC(ParallelAppBase):
    load_strategy = LoadStrategy.kBothOutIn
    message_strategy = MessageStrategy.kSyncOnOuterVertex
    result_format = "float"

    def init_state(self, frag, source=0):
        fnum, vp = frag.fnum, frag.vp
        depth = np.full((fnum, vp), _SENT, dtype=np.int32)
        pn = np.zeros((fnum, vp), dtype=np.float64)
        from libgrape_lite_tpu.app.base import resolve_source

        pid = resolve_source(frag, source, "BC")
        if pid >= 0:
            depth[pid // vp, pid % vp] = 0
            pn[pid // vp, pid % vp] = 1.0
        delta = np.zeros((fnum, vp), dtype=np.float64)
        return {"depth": depth, "pn": pn, "delta": delta}

    def peval(self, ctx: StepContext, frag, state):
        ie = frag.ie
        vp = frag.vp
        sent = jnp.int32(_SENT)
        dt = state["pn"].dtype

        def forward_round(carry):
            depth, pn, d, _ = carry
            full_depth = ctx.gather_state(depth)
            full_pn = ctx.gather_state(pn)
            at_d = jnp.logical_and(ie.edge_mask, full_depth[ie.edge_nbr] == d)
            contrib = jnp.where(at_d, full_pn[ie.edge_nbr], jnp.asarray(0, dt))
            acc = self.segment_reduce(contrib, ie.edge_src, vp, "sum")
            newly = jnp.logical_and(depth == sent, acc > 0)
            # vertices discovered exactly now get depth d+1 and pathcount;
            # vertices already at depth d+1 (same level, found from
            # another shard's frontier) accumulate — the dense pull sums
            # all depth-d predecessors at once, so acc is already total
            depth2 = jnp.where(newly, d + 1, depth)
            pn2 = jnp.where(
                jnp.logical_and(depth2 == d + 1, frag.inner_mask), acc, pn
            )
            n_new = ctx.sum(jnp.logical_and(newly, frag.inner_mask).sum().astype(jnp.int32))
            return depth2, pn2, d + 1, n_new

        def forward_cond(carry):
            _, _, d, n_new = carry
            return n_new > 0

        depth, pn, max_d, _ = lax.while_loop(
            forward_cond,
            forward_round,
            (state["depth"], state["pn"], jnp.int32(0), jnp.int32(1)),
        )

        delta = jnp.zeros_like(state["delta"])
        # depth/pn are fixed after the forward phase — gather once and
        # close over them (XLA won't hoist collectives out of while_loop)
        full_depth = ctx.gather_state(depth)
        full_pn = ctx.gather_state(pn)

        def backward_round(carry):
            delta, d = carry
            full_delta = ctx.gather_state(delta)
            from_succ = jnp.logical_and(
                ie.edge_mask, full_depth[ie.edge_nbr] == d
            )
            contrib = jnp.where(
                from_succ,
                (1.0 + full_delta[ie.edge_nbr])
                / jnp.maximum(full_pn[ie.edge_nbr], jnp.asarray(1e-300, dt)),
                jnp.asarray(0, dt),
            )
            acc = self.segment_reduce(contrib, ie.edge_src, vp, "sum")
            mine = jnp.logical_and(depth == d - 1, frag.inner_mask)
            delta2 = jnp.where(mine, pn * acc, delta)
            return delta2, d - 1

        def backward_cond(carry):
            _, d = carry
            return d > 0

        delta, _ = lax.while_loop(backward_cond, backward_round, (delta, max_d))

        return {"depth": depth, "pn": pn, "delta": delta}, jnp.int32(0)

    def invariants(self, frag, state):
        # Brandes partials: shortest-path counts and dependencies are
        # finite and nonnegative (in_range(lo=0) rejects NaN — NaN >= 0
        # is False); depth is the BFS level or the untouched sentinel
        from libgrape_lite_tpu.guard.invariants import finite, in_range

        return [
            finite("pn"),
            in_range("pn", lo=0),
            finite("delta"),
            in_range("delta", lo=0),
            in_range("depth", lo=0, hi=_SENT),
        ]

    def inceval(self, ctx, frag, state):
        return state, jnp.int32(0)

    def finalize(self, frag, state):
        return np.asarray(state["delta"])
