"""K-hop neighborhood — the serve-routable sampling workload.

One notch of ROADMAP 5c (the reference ships `examples/gnn_sampler`):
the fleet bench needs a workload whose traffic shape looks like real
user traffic — many tiny point queries, each touching a small
neighborhood — and k-hop neighborhood extraction is exactly the
frontier expansion a GNN sampler runs before fanout subsampling
(sampler/sampler.py keeps the fixed-fanout strategies; the full GNN
driver stays a follow-on).

Formulation: the BFS unit-weight tropical relaxation with the round
budget AS the hop bound — after k `inceval` rounds the depth plane
holds exactly the <= k-hop ball around the source.  Everything BFS
earned rides along for free: the `batch_query_key="source"` contract
(serve/ coalesces k sources into one vmapped dispatch), the dyn
overlay fold (staged delta edges join the neighborhood exactly), the
pack-gather SpMV, and the guard invariants.  `k` is a constructor
hyperparameter (it is baked into the while_loop bound, so it rides
`trace_key` and two k's never share a compile).

Result: hop distance for members of the ball, -1 outside (the
reference sampler emits empty lists for unreached frontiers).
"""

from __future__ import annotations

import numpy as np

from libgrape_lite_tpu.models.bfs import _SENTINEL, BFS


class KHopNeighborhood(BFS):
    result_format = "int"
    # bounded-round iteration: the previous fixed point is not
    # reusable under the hop cap, so incremental IncEval stays an
    # honest counted cold run (dyn overlay support is inherited — the
    # min fold is exact at any round budget)
    inc_mode = None
    inc_seed_keys: dict = {}

    def __init__(self, k: int = 2):
        k = int(k)
        if k < 1:
            raise ValueError(f"khop needs k >= 1, got {k}")
        self.k = k
        # the hop bound IS the round budget: round r relaxes depths
        # to r, so k rounds yield exactly the <= k-hop ball
        self.max_rounds = k

    def finalize(self, frag, state):
        d = np.asarray(state["depth"]).astype(np.int64)
        return np.where((d == _SENTINEL) | (d > self.k), -1, d)
