"""CoreDecomposition — per-vertex core numbers by level peeling.

Re-design of `examples/analytical_apps/core_decomposition/
core_decomposition.h`: peel level by level; at level L, repeatedly pin
every alive vertex whose residual degree <= L to core number L until
the level drains, then advance (the reference's nested
curr/next_inner_updated worklists).

TPU formulation: one `lax.while_loop` whose body does a single
synchronous sub-round of the current level (gather alive bitmap +
`segment_sum` residual degrees + pin), advancing the level only on
sub-rounds that removed nothing.  Same fixpoint as the reference's
nested loops, expressed as a flat loop so XLA keeps everything on
device.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
from jax import lax

from libgrape_lite_tpu.app.base import ParallelAppBase, StepContext
from libgrape_lite_tpu.utils.types import LoadStrategy, MessageStrategy


class CoreDecomposition(ParallelAppBase):
    load_strategy = LoadStrategy.kOnlyOut
    message_strategy = MessageStrategy.kSyncOnOuterVertex
    result_format = "int"
    replicated_keys = frozenset({"level"})

    def init_state(self, frag, **_):
        return {
            "core": np.zeros((frag.fnum, frag.vp), dtype=np.int32),
            "alive": frag.host_inner_mask(),
            "level": np.int32(1),
        }

    def peval(self, ctx: StepContext, frag, state):
        alive = jnp.logical_and(state["alive"], frag.out_degree > 0)
        return dict(state, alive=alive), jnp.int32(1)

    def invariants(self, frag, state):
        # coreness algebra: core numbers are written exactly once
        # (0 -> level) and never negative; the peeling level only
        # advances; dead vertices never resurrect
        from libgrape_lite_tpu.guard.invariants import (
            in_range,
            monotone_non_decreasing,
            monotone_non_increasing,
            set_once,
        )

        return [
            in_range("core", lo=0),
            set_once("core", unset=0),
            monotone_non_decreasing("level"),
            monotone_non_increasing("alive"),
        ]

    def inceval(self, ctx: StepContext, frag, state):
        core, alive, level = state["core"], state["alive"], state["level"]
        ie = frag.ie
        full = ctx.gather_state(alive.astype(jnp.int32))
        resid = self.segment_reduce(
            jnp.where(ie.edge_mask, full[ie.edge_nbr], 0), ie.edge_src,
            frag.vp, "sum",
        )
        pin = jnp.logical_and(alive, resid <= level)
        core2 = jnp.where(pin, level, core)
        alive2 = jnp.logical_and(alive, ~pin)

        n_pinned = ctx.sum(pin.sum().astype(jnp.int32))
        n_alive = ctx.sum(alive2.sum().astype(jnp.int32))
        # drained this level -> jump straight to the smallest remaining
        # residual degree (skipping empty levels costs one pmin instead
        # of one full superstep each)
        big = jnp.int32(np.iinfo(np.int32).max)
        min_resid = ctx.min(
            jnp.where(alive2, resid, big).min().astype(jnp.int32)
        )
        level2 = jnp.where(
            n_pinned == 0, jnp.maximum(level + 1, min_resid), level
        )
        active = jnp.where(n_alive > 0, jnp.int32(1), jnp.int32(0))
        return {"core": core2, "alive": alive2, "level": level2}, active

    def finalize(self, frag, state):
        return np.asarray(state["core"]).astype(np.int64)
