"""BFSOpt — direction-optimizing BFS (Beamer push/pull switching).

Re-design of `examples/analytical_apps/bfs/bfs_opt.h` (the reference's
direction-optimizing variant): level-synchronous BFS that runs *push*
rounds while the frontier is sparse and switches to *pull* rounds when
the frontier's out-edge volume approaches the unexplored edge volume,
switching back once the frontier thins out.  The classic heuristic
(Beamer et al., also the reference's `alpha`/`beta` thresholds):

    push -> pull  when  m_f > m_u / alpha
    pull -> push  when  n_f < n / beta

with m_f = frontier out-edge count, m_u = out-edges of unvisited
vertices, n_f = frontier vertex count.

TPU formulation: the two phases are two compiled supersteps sharing the
depth/frontier state.

* push — the message-tensor path (`AllToAllMessageManager.exchange`):
  frontier vertices send depth+1 to their out-neighbors; volume is
  O(frontier edges), with the overflow-vote capacity retry of
  `sssp_msg.py` (static shapes grow by re-execution).
* pull — the dense gather + `segment_min` relaxation of `bfs.py`:
  O(E) per round but throughput-optimal when most of the graph is
  active.  Capacity-independent, so it compiles once per fragment.

Both phases perform the identical monotone min-relaxation, so the level
assignment is exact regardless of the switch points; the heuristic only
affects wall-clock.  The host drives rounds (mode decisions are
data-dependent) exactly like the reference's per-round frontier logic.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from libgrape_lite_tpu import compat
from libgrape_lite_tpu.app.base import resolve_source
from libgrape_lite_tpu.models.exchange_base import (
    ExchangeAppBase,
    exchange_relax,
)
from libgrape_lite_tpu.ops.segment import segment_reduce
from libgrape_lite_tpu.parallel.comm_spec import FRAG_AXIS
from libgrape_lite_tpu.utils.types import LoadStrategy, MessageStrategy

_SENTINEL = np.iinfo(np.int32).max
_OUT_SENTINEL = np.iinfo(np.int64).max


class BFSOpt(ExchangeAppBase):
    load_strategy = LoadStrategy.kBothOutIn
    message_strategy = MessageStrategy.kAlongOutgoingEdgeToOuterVertex
    result_format = "int"

    def __init__(self, alpha: int = 14, beta: int = 24,
                 initial_capacity: int | None = None):
        super().__init__(initial_capacity)
        self.alpha = alpha
        self.beta = beta
        self.pull_rounds = 0
        self.push_rounds = 0

    # ---- compiled supersteps ----------------------------------------

    def _shard_spec(self, comm_spec):
        return dict(
            mesh=comm_spec.mesh,
            in_specs=(P(FRAG_AXIS), P(FRAG_AXIS), P(FRAG_AXIS)),
            out_specs=(P(FRAG_AXIS), P(FRAG_AXIS), P(), P(), P(), P()),
            check_vma=False,
        )

    @staticmethod
    def _stats(lf, depth, frontier):
        """(n_f, m_f, m_u) over the whole mesh."""
        sent = jnp.int32(_SENTINEL)
        deg = lf.out_degree.astype(jnp.int64)
        n_f = lax.psum(frontier.sum().astype(jnp.int64), FRAG_AXIS)
        m_f = lax.psum(jnp.where(frontier, deg, 0).sum(), FRAG_AXIS)
        unvisited = jnp.logical_and(lf.inner_mask, depth == sent)
        m_u = lax.psum(jnp.where(unvisited, deg, 0).sum(), FRAG_AXIS)
        return n_f, m_f, m_u

    def _push_for(self, frag, cap: int):
        per_frag = self._cache.setdefault(frag, {})
        key = ("push", cap)
        if key in per_frag:
            return per_frag[key]

        fnum, vp = frag.fnum, frag.vp
        sent = jnp.int32(_SENTINEL)

        def push(frag_stacked, depth, frontier):
            lf = frag_stacked.local()
            d, fr = depth[0], frontier[0]
            oe = lf.oe
            src = jnp.minimum(oe.edge_src, vp - 1)
            valid = jnp.logical_and(oe.edge_mask, fr[src])
            # int32 payloads straight through the exchange (it is
            # payload-dtype-generic); invalid slots carry the sentinel
            cand = jnp.where(valid, d[src] + 1, sent)
            relaxed, ovf = exchange_relax(oe, cand, valid, cap, fnum, vp, sent)
            new = jnp.minimum(d, relaxed)
            fr2 = jnp.logical_and(new < d, lf.inner_mask)
            n_f, m_f, m_u = self._stats(lf, new, fr2)
            return new[None], fr2[None], n_f, m_f, m_u, ovf

        fn = jax.jit(compat.shard_map(push, **self._shard_spec(frag.comm_spec)))
        per_frag[key] = fn
        return fn

    def _pull_for(self, frag):
        """Capacity-independent: one compile per fragment, ever."""
        per_frag = self._cache.setdefault(frag, {})
        if "pull" in per_frag:
            return per_frag["pull"]

        vp = frag.vp
        sent = jnp.int32(_SENTINEL)

        def pull(frag_stacked, depth, frontier):
            lf = frag_stacked.local()
            d = depth[0]
            ie = lf.ie
            full = lax.all_gather(d, FRAG_AXIS, tiled=True)
            nbr_d = full[ie.edge_nbr]
            cand = jnp.where(
                jnp.logical_and(ie.edge_mask, nbr_d != sent), nbr_d + 1, sent
            )
            relaxed = segment_reduce(cand, ie.edge_src, vp, "min")
            new = jnp.minimum(d, relaxed)
            fr2 = jnp.logical_and(new < d, lf.inner_mask)
            n_f, m_f, m_u = self._stats(lf, new, fr2)
            return new[None], fr2[None], n_f, m_f, m_u, jnp.int32(0)

        fn = jax.jit(compat.shard_map(pull, **self._shard_spec(frag.comm_spec)))
        per_frag["pull"] = fn
        return fn

    # ---- host-driven query ------------------------------------------

    def host_compute(self, frag, source=0, max_rounds: int | None = None):
        fnum, vp = frag.fnum, frag.vp
        depth0 = np.full((fnum, vp), _SENTINEL, dtype=np.int32)
        frontier0 = np.zeros((fnum, vp), dtype=bool)
        pid = resolve_source(frag, source, "BFSOpt")
        if pid >= 0:
            depth0[pid // vp, pid % vp] = 0
            frontier0[pid // vp, pid % vp] = True

        depth = jnp.asarray(depth0)
        frontier = jnp.asarray(frontier0)
        total_v = frag.total_vertices_num
        limit = max_rounds if (max_rounds and max_rounds > 0) else None

        cap = self._initial_cap(frag)
        self.rounds = self.retries = self.push_rounds = self.pull_rounds = 0
        # pre-round stats for the first decision
        n_f, m_f = (1, 0) if pid >= 0 else (0, 0)
        m_u = frag.total_edges_num * (1 if frag.directed else 2)
        pulling = False
        while n_f > 0 and (limit is None or self.rounds < limit):
            # Beamer switch on the CURRENT frontier
            if not pulling and m_f > m_u // self.alpha:
                pulling = True
            elif pulling and n_f < total_v // self.beta:
                pulling = False
            step = self._pull_for(frag) if pulling else self._push_for(frag, cap)
            out = step(frag.dev, depth, frontier)
            new_depth, new_frontier, n_f_d, m_f_d, m_u_d, ovf = out
            if int(ovf) > 0:
                cap *= 2
                self.retries += 1
                continue
            depth, frontier = new_depth, new_frontier
            n_f, m_f, m_u = int(n_f_d), int(m_f_d), int(m_u_d)
            self.rounds += 1
            if pulling:
                self.pull_rounds += 1
            else:
                self.push_rounds += 1
        self._save_cap(frag, cap)
        return {"depth": depth}

    def finalize(self, frag, state):
        d = np.asarray(state["depth"]).astype(np.int64)
        return np.where(d == _SENTINEL, _OUT_SENTINEL, d)
