"""SSSP — single-source shortest paths.

Re-design of `examples/analytical_apps/sssp/sssp.h:36-170` (frontier
DenseVertexSet + atomic_min relax + SyncStateOnOuterVertex).

TPU formulation: pull-mode Bellman-Ford.  Each superstep gathers the
global distance vector (`all_gather` over ICI — the collective form of
the reference's outer-vertex sync) and relaxes *all* in-edges with one
gather + `segment_min`; the frontier bitset becomes implicit (vertices
whose distance did not change contribute no improvement).  `min` is
associative, so the result is bit-exact regardless of reduction order —
matching the reference's atomic_min semantics and golden outputs.
Termination: `psum` of the per-shard changed-count (the reference's 2-int
MPI_Allreduce, `parallel_message_manager.h:123-138`).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from libgrape_lite_tpu.app.base import ParallelAppBase, StepContext
from libgrape_lite_tpu.utils.types import LoadStrategy, MessageStrategy


class SSSP(ParallelAppBase):
    load_strategy = LoadStrategy.kBothOutIn
    message_strategy = MessageStrategy.kSyncOnOuterVertex
    result_format = "sssp_infinity"
    needs_edata = True  # double edata (run_app.cc:48-52)
    batch_query_key = "source"  # serve/: [k]-source batched dispatch
    # dyn/: staged additive deltas fold exactly into the tropical min
    # relax, and the previous fixed point seeds incremental IncEval
    dyn_overlay_support = True
    inc_mode = "monotone-min"
    inc_seed_keys = {"dist": "min"}
    # r9: the tropical relax pipelines — min folds are any-order exact,
    # so the boundary/interior split is bit-stable on both backends
    pipeline_state_key = "dist"

    def init_state(self, frag, source=0):
        import os

        import jax

        if not frag.weighted or frag.host_ie[0].edge_w is None:
            # the reference SSSP requires double edata (run_app.cc:48-52);
            # fail at init instead of a tracer TypeError mid-superstep
            raise ValueError(
                "SSSP requires edge weights; load the graph with "
                "weighted=True (use BFS for unit-weight traversal)"
            )
        dtype = frag.host_ie[0].edge_w.dtype
        if not jax.config.jax_enable_x64:
            # honest TPU dtype: x64-off would downcast silently anyway
            dtype = np.float32
        from libgrape_lite_tpu.app.base import source_lane_array

        # a SEQUENCE of sources builds the batched [k, fnum, vp] carry
        # for the serve/ vmapped multi-source dispatch — the ephemeral
        # streams below are built once and shared across lanes
        batched, dist = source_lane_array(
            frag, source, "SSSP", np.inf, 0.0, dtype
        )
        dist = dist if batched else dist[0]
        # tropical pack pipeline (ops/spmv_pack.py, GRAPE_SPMV=pack):
        # min-relaxation with the f32 weight stream baked into the plan
        self._pack = None
        state = {"dist": dist}
        eph_entries = {}
        # fused dense pull (r6): pre-mask the weight stream ONCE at init
        # (inf at masked edges), so the per-round relax is one gather +
        # one add — the separate edge_mask select pass is gone and the
        # result is bit-identical (x + inf == inf == the old masked
        # lane; distances never reach -inf, so no NaN).  The host CSRs
        # are already padded to the device Ep, so the stream stacks
        # uniformly.  GRAPE_SSSP_FUSE=0 reverts for A/B.
        self._fuse = os.environ.get("GRAPE_SSSP_FUSE", "1") not in (
            "0", "")
        from libgrape_lite_tpu.parallel.mirror import resolve_mirror_plan

        # dyn/ overlay: staged delta edges ride as ephemeral side
        # arrays and fold into the relax below.  Their neighbor reads
        # index the pid-addressed full gather, so mirror compaction is
        # disabled while an overlay is attached (the entries are
        # present — possibly all-masked — whenever the fragment is
        # dyn-managed, keeping the compiled state structure stable
        # across ingests: zero recompiles below the repack threshold)
        self._dyn = getattr(frag, "dyn_overlay", None) is not None
        if self._dyn:
            from libgrape_lite_tpu.dyn.ingest import overlay_state_entries

            eph_entries.update(
                overlay_state_entries(frag, "ie", dtype, "dyn_ie_")
            )
            self._mx = None
        else:
            self._mx = resolve_mirror_plan(frag, "ie")
        if self._mx is not None:
            eph_entries.update(self._mx.state_entries("mx_"))
        self._mx_uid = self._mx.uid if self._mx is not None else -1
        if os.environ.get("GRAPE_SPMV") == "pack":
            from libgrape_lite_tpu.ops.spmv_pack import (
                resolve_pack_dispatch,
                warn_pack_ineligible,
            )

            if np.dtype(dtype) != np.float32:
                warn_pack_ineligible(
                    "SSSP", f"state dtype {np.dtype(dtype)} is not float32"
                )
            elif not frag.weighted:
                warn_pack_ineligible(
                    "SSSP", "fragment has no edge weights"
                )
            else:
                self._pack = resolve_pack_dispatch(
                    frag, with_weights=True, mirror=self._mx
                )
                if self._pack is None:
                    warn_pack_ineligible("SSSP", "no pack plan buildable")
                else:
                    eph_entries.update(self._pack.state_entries())
        if self._pack is not None:
            self._fuse = False  # pack bakes the weight stream already
        if self._fuse:
            eph_entries["wf_eff"] = np.stack([
                np.where(frag.host_ie[f].edge_mask,
                         frag.host_ie[f].edge_w,
                         np.asarray(np.inf, frag.host_ie[f].edge_w.dtype))
                for f in range(frag.fnum)
            ])
        # superstep pipelining (r9): resolved AFTER the exchange mode
        # and SpMV backend, because the pipelined round must reuse both
        # decisions verbatim for byte-identity; batched lanes keep the
        # serial body (the vmapped runner is not pipelined)
        self._pipeline = None
        if not batched and not self._dyn:
            from libgrape_lite_tpu.parallel.pipeline import resolve_pipeline

            self._pipeline = resolve_pipeline(
                frag, app_name="SSSP", key="dist", direction="ie",
                mirror=self._mx, mx_prefix="mx_", pack=self._pack,
                fold="min", with_weights=True,
            )
            if self._pipeline is not None:
                eph_entries.update(self._pipeline.host_entries)
        self._pipeline_uid = (
            self._pipeline.uid if self._pipeline is not None else -1
        )
        if eph_entries:
            state.update(eph_entries)
            self.ephemeral_keys = frozenset(eph_entries)
        self._pack_plan_uid = (
            self._pack.uid if self._pack is not None else -1
        )
        return state

    def peval(self, ctx: StepContext, frag, state):
        # The reference PEval relaxes only the source's out-edges
        # (sssp.h:68-83); the first pull round subsumes that.
        return state, jnp.int32(1)  # ForceContinue (sssp.h:90)

    def inceval(self, ctx: StepContext, frag, state):
        dist = state["dist"]
        ie = frag.ie
        if self._mx is not None:
            full = ctx.exchange_mirrors(dist, state["mx_send"])
            nbr = state["mx_nbr"]
        else:
            full = ctx.gather_state(dist)
            nbr = ie.edge_nbr
        if self._pack is not None:
            relaxed = self._pack.reduce(full, state, "min")
        elif self._fuse:
            # one gather pass: the pre-masked weight stream (wf_eff,
            # inf at masked edges) folds the relax-mask select into the
            # add — bit-identical to the where() form
            cand = full[nbr] + state["wf_eff"]
            relaxed = self.segment_reduce(cand, ie.edge_src, frag.vp, "min")
        else:
            inf = jnp.asarray(jnp.inf, dist.dtype)
            cand = jnp.where(
                ie.edge_mask, full[nbr] + ie.edge_w, inf
            )
            relaxed = self.segment_reduce(cand, ie.edge_src, frag.vp, "min")
        if "dyn_ie_nbr" in state:
            # staged delta edges (dyn/): one extra gather + segment_min
            # over the dense overlay slots, merged at the fold — `full`
            # is pid-addressed here (mirror compaction is off in
            # overlay mode, see init_state)
            inf = jnp.asarray(jnp.inf, dist.dtype)
            dcand = jnp.where(
                state["dyn_ie_mask"],
                full[state["dyn_ie_nbr"]] + state["dyn_ie_w"], inf,
            )
            relaxed = self.dyn_min_fold(
                relaxed, state, frag.vp, "dyn_ie_", dcand
            )
        new = jnp.minimum(dist, relaxed)
        changed = jnp.logical_and(new < dist, frag.inner_mask)
        active = ctx.sum(changed.sum().astype(jnp.int32))
        return {"dist": new}, active

    def inceval_pipelined(self, ctx: StepContext, frag, state, xbuf):
        """Double-buffered round (parallel/pipeline.py): boundary relax
        first, exchange kickoff, interior relax overlapping the
        collective, join at the boundary mask.  min is associative and
        commutative, so each row's fold over its own (order-preserved)
        edge subset is bit-identical to the serial relax."""
        pl = self._pipeline
        dist = state["dist"]
        full = pl.splice(ctx, dist, state, xbuf)
        inf = jnp.asarray(jnp.inf, dist.dtype)
        bmask = state["pl_bmask"]
        if pl.pack_b is not None:
            rel_b = pl.pack_b.reduce(full, state, "min")
        else:
            cand_b = jnp.where(
                state["pl_b_val"],
                full[state["pl_b_nbr"]] + state["pl_b_w"], inf,
            )
            rel_b = self.segment_reduce(
                cand_b, state["pl_b_src"], frag.vp, "min"
            )
        new_b = jnp.minimum(dist, rel_b)
        xbuf2 = pl.kickoff(ctx, jnp.where(bmask, new_b, dist), state)
        # ---- pipelined window: every carry read below is named in
        # parallel/pipeline.PIPELINE_WINDOW_READS (grape-lint R6) ----
        if pl.pack_i is not None:
            rel_i = pl.pack_i.reduce(full, state, "min")
        else:
            cand_i = jnp.where(
                state["pl_i_val"],
                full[state["pl_i_nbr"]] + state["pl_i_w"], inf,
            )
            rel_i = self.segment_reduce(
                cand_i, state["pl_i_src"], frag.vp, "min"
            )
        new_i = jnp.minimum(dist, rel_i)
        new = jnp.where(bmask, new_b, new_i)
        changed = jnp.logical_and(new < dist, frag.inner_mask)
        active = ctx.sum(changed.sum().astype(jnp.int32))
        return {"dist": new}, active, xbuf2

    def invariants(self, frag, state):
        # distances are tropical-min state: never negative, never NaN
        # (in_range(lo=0) rejects NaN — NaN >= 0 is False), and only
        # ever improving; +inf is the legitimate unreached sentinel
        from libgrape_lite_tpu.guard.invariants import (
            in_range, monotone_non_increasing,
        )

        return [
            in_range("dist", lo=0.0),
            monotone_non_increasing("dist"),
        ]

    def finalize(self, frag, state):
        return np.asarray(state["dist"])
