"""PageRankLocal — the competitor-convergence PageRank variant.

Re-design of `examples/analytical_apps/pagerank/pagerank_local.h`
(+ `pagerank_local_parallel.h`): the unnormalised formulation
`r' = (1-d) + d * Σ r[nbr]/deg[nbr]` with NO dangling redistribution,
run for a fixed round count — the variant used for the
competitor-compatible numbers in `Performance.md:61-67`.

Per-round state holds r/deg (like the LDBC variant); the final round
multiplies back by the degree.  The reference's per-source-fragment
partial mirror updates (`UpdatePartialOuterVertices`) are an MPI
overlap optimisation; on TPU the single fused all_gather + SpMV is the
same traffic without the bookkeeping.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from libgrape_lite_tpu.app.base import BatchShuffleAppBase, StepContext
from libgrape_lite_tpu.utils.types import LoadStrategy, MessageStrategy


class PageRankLocal(BatchShuffleAppBase):
    load_strategy = LoadStrategy.kBothOutIn
    message_strategy = MessageStrategy.kAlongOutgoingEdgeToOuterVertex
    result_format = "float"
    replicated_keys = frozenset({"step"})

    def __init__(self, delta: float = 0.85, max_round: int = 10):
        self.delta = delta
        self.max_round = max_round

    def init_state(self, frag, delta: float | None = None,
                   max_round: int | None = None):
        if delta is not None:
            self.delta = delta
        if max_round is not None:
            self.max_round = max_round
        return {
            "rank": np.zeros((frag.fnum, frag.vp), dtype=np.float64),
            "step": np.int32(0),
        }

    def peval(self, ctx: StepContext, frag, state):
        deg = frag.out_degree
        dt = state["rank"].dtype
        one = jnp.asarray(1.0, dt)
        rank = jnp.where(
            frag.inner_mask,
            jnp.where(deg > 0, one / jnp.maximum(deg, 1).astype(dt), one),
            jnp.asarray(0, dt),
        )
        return dict(rank=rank, step=jnp.int32(0)), jnp.int32(
            1 if self.max_round > 0 else 0
        )

    def inceval(self, ctx: StepContext, frag, state):
        d = self.delta
        rank = state["rank"]
        dt = rank.dtype
        step = state["step"] + 1
        ie = frag.ie
        full = ctx.gather_state(rank)
        contrib = jnp.where(ie.edge_mask, full[ie.edge_nbr], jnp.asarray(0, dt))
        cur = self.segment_reduce(contrib, ie.edge_src, frag.vp, "sum")
        deg = frag.out_degree
        val = jnp.asarray(1.0 - d, dt) + jnp.asarray(d, dt) * cur
        nxt = jnp.where(
            deg > 0, val / jnp.maximum(deg, 1).astype(dt), jnp.asarray(1.0, dt)
        )
        nxt = jnp.where(frag.inner_mask, nxt, jnp.asarray(0, dt))
        is_last = step >= jnp.int32(self.max_round)
        finald = jnp.where(deg > 0, nxt * deg.astype(dt), nxt)
        rank_out = jnp.where(is_last, finald, nxt)
        return dict(rank=rank_out, step=step), jnp.where(
            is_last, jnp.int32(0), jnp.int32(1)
        )

    def finalize(self, frag, state):
        return np.asarray(state["rank"])
