"""KCore — iterative k-core peeling.

Re-design of `examples/analytical_apps/kcore/kcore.h`: vertices with
residual degree < k are removed; removals decrement neighbor degrees;
iterate to fixpoint (the reference pushes per-removal decrement
messages, `kcore.h` IncEval).

TPU formulation: dense synchronous peeling — each round recomputes the
alive-neighbor count with one gather + `segment_sum` and drops every
under-k vertex at once (the message traffic of the reference becomes
the all_gather of the alive bitmap).  Fixpoint via psum vote.

Result: per-vertex membership (1 if in the k-core else 0) — the
reference's per-vertex artifact is the residual-degree array consumed
as `result >= k` (`kcore_context.h` Output counts exactly that).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from libgrape_lite_tpu.app.base import ParallelAppBase, StepContext
from libgrape_lite_tpu.utils.types import LoadStrategy, MessageStrategy


class KCore(ParallelAppBase):
    load_strategy = LoadStrategy.kOnlyOut
    message_strategy = MessageStrategy.kSyncOnOuterVertex
    result_format = "int"

    def __init__(self, k: int = 0):
        self.k = k

    def init_state(self, frag, k: int | None = None):
        if k is not None:
            self.k = k
        return {"alive": frag.host_inner_mask()}

    def peval(self, ctx: StepContext, frag, state):
        # initial cut: degree < k (kcore.h PEval)
        alive = jnp.logical_and(state["alive"], frag.out_degree >= self.k)
        return {"alive": alive}, jnp.int32(1)

    def invariants(self, frag, state):
        # peeling only removes: a dead vertex must never resurrect
        # (monotone across any probe cadence — removal is transitive)
        from libgrape_lite_tpu.guard.invariants import (
            monotone_non_increasing,
        )

        return [monotone_non_increasing("alive")]

    def inceval(self, ctx: StepContext, frag, state):
        alive = state["alive"]
        ie = frag.ie
        full = ctx.gather_state(alive.astype(jnp.int32))
        cnt = self.segment_reduce(
            jnp.where(ie.edge_mask, full[ie.edge_nbr], 0), ie.edge_src,
            frag.vp, "sum",
        )
        removed = jnp.logical_and(alive, cnt < self.k)
        new_alive = jnp.logical_and(alive, ~removed)
        active = ctx.sum(removed.sum().astype(jnp.int32))
        return {"alive": new_alive}, active

    def finalize(self, frag, state):
        return np.asarray(state["alive"]).astype(np.int64)
