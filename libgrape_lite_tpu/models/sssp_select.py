"""Evidence-based SSSP variant selection (VERDICT r4 next #4).

The reference's CUDA SSSP picks its work discipline from the graph: the
near-far priority bucketing (`examples/analytical_apps/cuda/sssp/sssp.h:50-100`)
exists because on high-diameter graphs a plain Bellman-Ford sweep pays
O(E) per round for thousands of rounds, while on low-diameter power-law
graphs the sweep converges in tens of rounds and any frontier machinery
is pure overhead (measured in docs/FRONTIER_NOTES.md).

TPU formulation of the same decision: the round count of the dense
pull is bounded by the hop-diameter from the source (times the weight
stretch), so probe exactly that quantity — one host BFS over the
already-resident host CSRs, capped at `cap` levels.  O(E) total work
(each edge scanned once via frontier-sliced CSR ranges), a negligible
one-off against the device compile itself.

  * converges within `cap` levels  -> "sssp"       (dense fused pull;
    the measured winner on every low-diameter graph, FRONTIER_NOTES)
  * frontier still alive at `cap`  -> "sssp_delta" (bucketed near/far:
    round count decouples from diameter, relaxation volume per round
    stays at the frontier scale)

`GRAPE_SSSP_PROBE_CAP` overrides the crossover (default 64: RMAT/social
graphs finish in < 15 levels, road networks run to thousands).
"""

from __future__ import annotations

import os

import numpy as np


def host_bfs_levels(frag, src_pid: int, cap: int = 64):
    """Hop levels from `src_pid` over the out-CSRs, capped.

    Returns (levels, converged): `levels` = last level at which the
    frontier was non-empty; `converged` False means the cap was hit
    with a live frontier (high-diameter evidence).  Total work is O(E):
    every vertex enters the frontier at most once and only frontier
    adjacency is scanned (the repeat/cumsum range-slice below is the
    vectorised form of the reference's per-vertex neighbor loop).
    """
    fnum, vp = frag.fnum, frag.vp
    degs, adjs = [], []
    for f in range(fnum):
        c = frag.host_oe[f]
        n_real = int(c.indptr[c.num_rows])
        degs.append(np.diff(c.indptr[: c.num_rows + 1]).astype(np.int64))
        # keep the storage dtype (int32): pids index fine as-is, and an
        # int64 upcast would transiently double the probe's footprint
        # on bench-scale graphs
        adjs.append(c.edge_nbr[:n_real])
    deg = np.concatenate(degs)
    indptr = np.zeros(len(deg) + 1, dtype=np.int64)
    np.cumsum(deg, out=indptr[1:])
    adj = np.concatenate(adjs) if adjs else np.zeros(0, np.int32)

    visited = np.zeros(fnum * vp, dtype=bool)
    frontier = np.asarray([src_pid], dtype=np.int64)
    visited[src_pid] = True
    levels = 0
    for level in range(1, cap + 1):
        d = deg[frontier]
        total = int(d.sum())
        if total == 0:
            return levels, True
        starts = indptr[frontier]
        # frontier-sliced CSR gather: absolute edge indices of every
        # frontier vertex's adjacency range, in one shot
        base = np.repeat(starts - np.concatenate(([0], np.cumsum(d[:-1]))), d)
        nxt = adj[np.arange(total, dtype=np.int64) + base]
        nxt = nxt[~visited[nxt]]
        if nxt.size == 0:
            return levels, True
        nxt = np.unique(nxt)
        visited[nxt] = True
        frontier = nxt
        levels = level
    return levels, False


def select_sssp_variant(frag, source) -> tuple[str, str]:
    """Pick the SSSP app for this (graph, source): returns
    (registry_name, reason).  See module docstring for the decision
    rule and its measured basis."""
    from libgrape_lite_tpu.app.base import resolve_source

    cap = int(os.environ.get("GRAPE_SSSP_PROBE_CAP", "64"))
    pid = resolve_source(frag, source, "SSSP")
    if pid < 0:
        return "sssp", "source not in graph; trivial query"
    levels, converged = host_bfs_levels(frag, int(pid), cap)
    if converged:
        return "sssp", (
            f"BFS probe: {levels} hop levels (< cap {cap}) -> dense "
            "fused pull (low-diameter regime, FRONTIER_NOTES)"
        )
    return "sssp_delta", (
        f"BFS probe: frontier alive after {cap} levels -> delta-stepping "
        "(high-diameter regime; near-far analogue, cuda/sssp.h:50-100)"
    )
