"""BFS — breadth-first search levels.

Re-design of `examples/analytical_apps/bfs/bfs.h:30-150` (level-sync
frontier bitmaps).  TPU formulation: pull-mode unit-weight Bellman-Ford
over int32 depths — identical level assignment, no frontier compaction
needed (masked dense relaxation; XLA keeps it on the VPU).  Unreached
vertices keep the int sentinel and print as the reference's
`std::numeric_limits<int64_t>::max()` (`bfs_context.h:44`, golden
`p2p-31-BFS`).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from libgrape_lite_tpu.app.base import ParallelAppBase, StepContext
from libgrape_lite_tpu.utils.types import LoadStrategy, MessageStrategy

_SENTINEL = np.iinfo(np.int32).max
_OUT_SENTINEL = np.iinfo(np.int64).max  # printed for unreachable


class BFS(ParallelAppBase):
    load_strategy = LoadStrategy.kBothOutIn
    message_strategy = MessageStrategy.kSyncOnOuterVertex
    result_format = "int"
    batch_query_key = "source"  # serve/: [k]-source batched dispatch
    # dyn/: unit-weight tropical relax — additive deltas fold exactly,
    # and the previous depth vector seeds incremental IncEval
    dyn_overlay_support = True
    inc_mode = "monotone-min"
    inc_seed_keys = {"depth": "min"}
    # r9: unit-weight tropical relax — min folds split bit-stably
    pipeline_state_key = "depth"

    def init_state(self, frag, source=0):
        import os

        from libgrape_lite_tpu.app.base import source_lane_array

        # a SEQUENCE of sources builds the batched [k, fnum, vp] carry
        # for the serve/ vmapped multi-source dispatch (ephemeral
        # streams below are built once and shared across lanes)
        batched, depth = source_lane_array(
            frag, source, "BFS", _SENTINEL, 0, np.int32
        )
        depth = depth if batched else depth[0]
        state = {"depth": depth}
        eph_entries = {}
        from libgrape_lite_tpu.parallel.mirror import resolve_mirror_plan

        # dyn/ overlay (see SSSP.init_state): pid-addressed side
        # arrays, mirror compaction off while attached
        self._dyn = getattr(frag, "dyn_overlay", None) is not None
        if self._dyn:
            from libgrape_lite_tpu.dyn.ingest import overlay_state_entries

            eph_entries.update(
                overlay_state_entries(frag, "ie", None, "dyn_ie_")
            )
            self._mx = None
        else:
            self._mx = resolve_mirror_plan(frag, "ie")
        if self._mx is not None:
            eph_entries.update(self._mx.state_entries("mx_"))
        self._mx_uid = self._mx.uid if self._mx is not None else -1
        # pack-gather min pull (GRAPE_SPMV=pack): unit-weight tropical
        # relaxation — min(nbr)+1 == min(nbr+1), so the plan needs no
        # weight stream; unreached vertices travel as +inf
        self._pack = None
        if os.environ.get("GRAPE_SPMV") == "pack":
            from libgrape_lite_tpu.ops.spmv_pack import (
                resolve_pack_dispatch,
                warn_pack_ineligible,
            )

            if frag.fnum * frag.vp > (1 << 24):
                warn_pack_ineligible(
                    "BFS", "depth range exceeds exact f32 range (2^24)"
                )
            else:
                self._pack = resolve_pack_dispatch(
                    frag, direction="ie", mirror=self._mx
                )
                if self._pack is None:
                    warn_pack_ineligible("BFS", "no pack plan buildable")
                else:
                    eph_entries.update(self._pack.state_entries())
        # superstep pipelining (r9): after the exchange/SpMV decisions,
        # which the pipelined round reuses verbatim (see SSSP)
        self._pipeline = None
        if not batched and not self._dyn:
            from libgrape_lite_tpu.parallel.pipeline import resolve_pipeline

            self._pipeline = resolve_pipeline(
                frag, app_name="BFS", key="depth", direction="ie",
                mirror=self._mx, mx_prefix="mx_", pack=self._pack,
                fold="min", with_weights=False,
            )
            if self._pipeline is not None:
                eph_entries.update(self._pipeline.host_entries)
        self._pipeline_uid = (
            self._pipeline.uid if self._pipeline is not None else -1
        )
        if eph_entries:
            state.update(eph_entries)
            self.ephemeral_keys = frozenset(eph_entries)
        self._pack_uid = self._pack.uid if self._pack is not None else -1
        return state

    def peval(self, ctx: StepContext, frag, state):
        return state, jnp.int32(1)

    def inceval(self, ctx: StepContext, frag, state):
        depth = state["depth"]
        ie = frag.ie
        sent = jnp.int32(_SENTINEL)
        if self._mx is not None:
            full = ctx.exchange_mirrors(depth, state["mx_send"])
            nbr = state["mx_nbr"]
        else:
            full = ctx.gather_state(depth)
            nbr = ie.edge_nbr
        if self._pack is not None:
            full_f = jnp.where(
                full == sent, jnp.float32(jnp.inf),
                full.astype(jnp.float32),
            )
            red = self._pack.reduce(full_f, state, "min") + 1.0
            relaxed = jnp.where(
                jnp.isfinite(red), red.astype(jnp.int32), sent
            )
        else:
            nbr_d = full[nbr]
            cand = jnp.where(
                jnp.logical_and(ie.edge_mask, nbr_d != sent),
                nbr_d + 1, sent,
            )
            relaxed = self.segment_reduce(cand, ie.edge_src, frag.vp,
                                          "min")
        if "dyn_ie_nbr" in state:
            # staged delta edges (dyn/): extra unit-weight candidates
            # merged at the fold; `full` is pid-addressed in overlay
            # mode (init_state disables mirror compaction)
            dv = full[state["dyn_ie_nbr"]]
            dcand = jnp.where(
                jnp.logical_and(state["dyn_ie_mask"], dv != sent),
                dv + 1, sent,
            )
            relaxed = self.dyn_min_fold(
                relaxed, state, frag.vp, "dyn_ie_", dcand
            )
        new = jnp.minimum(depth, relaxed)
        changed = jnp.logical_and(new < depth, frag.inner_mask)
        active = ctx.sum(changed.sum().astype(jnp.int32))
        return {"depth": new}, active

    def inceval_pipelined(self, ctx: StepContext, frag, state, xbuf):
        """Double-buffered round (parallel/pipeline.py; see SSSP):
        boundary relax, exchange kickoff, interior relax overlapping
        the collective, join at the boundary mask — bit-identical to
        the serial min relax."""
        pl = self._pipeline
        depth = state["depth"]
        sent = jnp.int32(_SENTINEL)
        full = pl.splice(ctx, depth, state, xbuf)
        bmask = state["pl_bmask"]

        def pack_relax(dispatch):
            full_f = jnp.where(
                full == sent, jnp.float32(jnp.inf),
                full.astype(jnp.float32),
            )
            red = dispatch.reduce(full_f, state, "min") + 1.0
            return jnp.where(
                jnp.isfinite(red), red.astype(jnp.int32), sent
            )

        if pl.pack_b is not None:
            rel_b = pack_relax(pl.pack_b)
        else:
            nb = full[state["pl_b_nbr"]]
            cand_b = jnp.where(
                jnp.logical_and(state["pl_b_val"], nb != sent),
                nb + 1, sent,
            )
            rel_b = self.segment_reduce(
                cand_b, state["pl_b_src"], frag.vp, "min"
            )
        new_b = jnp.minimum(depth, rel_b)
        xbuf2 = pl.kickoff(ctx, jnp.where(bmask, new_b, depth), state)
        # ---- pipelined window: carry reads below are named in
        # parallel/pipeline.PIPELINE_WINDOW_READS (grape-lint R6) ----
        if pl.pack_i is not None:
            rel_i = pack_relax(pl.pack_i)
        else:
            ni = full[state["pl_i_nbr"]]
            cand_i = jnp.where(
                jnp.logical_and(state["pl_i_val"], ni != sent),
                ni + 1, sent,
            )
            rel_i = self.segment_reduce(
                cand_i, state["pl_i_src"], frag.vp, "min"
            )
        new_i = jnp.minimum(depth, rel_i)
        new = jnp.where(bmask, new_b, new_i)
        changed = jnp.logical_and(new < depth, frag.inner_mask)
        active = ctx.sum(changed.sum().astype(jnp.int32))
        return {"depth": new}, active, xbuf2

    def invariants(self, frag, state):
        # levels live in [0, SENTINEL] and only ever improve (pull-mode
        # unit-weight relaxation is tropical-min, like SSSP)
        from libgrape_lite_tpu.guard.invariants import (
            in_range, monotone_non_increasing,
        )

        return [
            in_range("depth", lo=0, hi=_SENTINEL),
            monotone_non_increasing("depth"),
        ]

    def finalize(self, frag, state):
        d = np.asarray(state["depth"]).astype(np.int64)
        return np.where(d == _SENTINEL, _OUT_SENTINEL, d)
