"""BFS — breadth-first search levels.

Re-design of `examples/analytical_apps/bfs/bfs.h:30-150` (level-sync
frontier bitmaps).  TPU formulation: pull-mode unit-weight Bellman-Ford
over int32 depths — identical level assignment, no frontier compaction
needed (masked dense relaxation; XLA keeps it on the VPU).  Unreached
vertices keep the int sentinel and print as the reference's
`std::numeric_limits<int64_t>::max()` (`bfs_context.h:44`, golden
`p2p-31-BFS`).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from libgrape_lite_tpu.app.base import ParallelAppBase, StepContext
from libgrape_lite_tpu.utils.types import LoadStrategy, MessageStrategy

_SENTINEL = np.iinfo(np.int32).max
_OUT_SENTINEL = np.iinfo(np.int64).max  # printed for unreachable


class BFS(ParallelAppBase):
    load_strategy = LoadStrategy.kBothOutIn
    message_strategy = MessageStrategy.kSyncOnOuterVertex
    result_format = "int"

    def init_state(self, frag, source=0):
        depth = np.full((frag.fnum, frag.vp), _SENTINEL, dtype=np.int32)
        from libgrape_lite_tpu.app.base import resolve_source

        pid = resolve_source(frag, source, "BFS")
        if pid >= 0:
            depth[pid // frag.vp, pid % frag.vp] = 0
        return {"depth": depth}

    def peval(self, ctx: StepContext, frag, state):
        return state, jnp.int32(1)

    def inceval(self, ctx: StepContext, frag, state):
        depth = state["depth"]
        ie = frag.ie
        full = ctx.gather_state(depth)
        nbr_d = full[ie.edge_nbr]
        sent = jnp.int32(_SENTINEL)
        cand = jnp.where(
            jnp.logical_and(ie.edge_mask, nbr_d != sent), nbr_d + 1, sent
        )
        relaxed = self.segment_reduce(cand, ie.edge_src, frag.vp, "min")
        new = jnp.minimum(depth, relaxed)
        changed = jnp.logical_and(new < depth, frag.inner_mask)
        active = ctx.sum(changed.sum().astype(jnp.int32))
        return {"depth": new}, active

    def finalize(self, frag, state):
        d = np.asarray(state["depth"]).astype(np.int64)
        return np.where(d == _SENTINEL, _OUT_SENTINEL, d)
