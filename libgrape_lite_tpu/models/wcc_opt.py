"""WCCOpt — label propagation accelerated with pointer jumping.

Re-design of `examples/analytical_apps/wcc/wcc_opt.h`: the reference's
opt variant compresses label chains while propagating.  TPU
formulation: each superstep does the standard neighbor `min` pull
(models/wcc.py) plus a pointer-jump `comp[v] <- comp[comp[v]]` — labels
are pids, so the jump is one gather on the freshly gathered global
label vector.  Rounds drop from O(diameter) to O(log diameter) on
chain-heavy graphs; the fixpoint (and the output) is identical.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from libgrape_lite_tpu.app.base import StepContext
from libgrape_lite_tpu.models.wcc import WCC


class WCCOpt(WCC):
    def _post_pull(self, ctx: StepContext, frag, new):
        # pointer jumping: follow the representative's representative.
        # comp values are pids; padded rows hold the int32 sentinel, so
        # clamp the index and keep the sentinel out of real rows via the
        # jumped < new guard
        full = ctx.gather_state(new)
        n_pad = frag.fnum * frag.vp
        jumped = full[jnp.minimum(new, jnp.int32(n_pad - 1))]
        return jnp.where(
            jnp.logical_and(frag.inner_mask, jumped < new), jumped, new
        )
