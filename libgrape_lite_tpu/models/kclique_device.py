"""Device k=4 clique counting over the sorted-ELL oriented DAG.

Re-design of the reference's recursive clique kernel
(`examples/analytical_apps/kclique/kclique.h` UniFragCliqueNumRecursive)
for the k=4 level: every 4-clique has a unique DAG-rank order
v < u < w < x under the (degree, id) orientation, so

    count(v) = Σ_{u ∈ N+(v)} Σ_{w ∈ C2} |C2 ∩ N+(w)|,
    C2 = N+(v) ∩ N+(u)

— one more intersection level than the triangle kernel
(models/lcc_beta.py).  Remote adjacency rows ride a DOUBLE ring: the
outer ring rotates u's ELL block, the inner ring rotates w's
(fnum² systolic steps, each a batched searchsorted).

Shapes are static: per edge chunk the third level materialises
[chunk, D, D] candidate hits, D = the graph's max oriented out-degree.
The low->high (degree, id) orientation bounds D by degeneracy scale —
RMAT hubs keep only their few higher-degree neighbors (rmat16 D = 151
vs 6202 under high->low), which is what admits power-law graphs to
this kernel at all.  `hub_cap` (`models/kclique.py`) gates per-edge
work: beyond it the host recursion takes over (RMAT-20's D = 679).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from libgrape_lite_tpu.models.lcc_beta import LCCBeta
from libgrape_lite_tpu.parallel.comm_spec import FRAG_AXIS


class KClique4Device(LCCBeta):
    """Per-apex 4-clique counts (k=3's ApexTriangleCount sibling)."""

    result_format = "int"
    credit_mode = "apex"
    # low->high orientation: RMAT hubs keep only their few higher-degree
    # neighbors, so D stays under hub_cap (rmat16: 151 vs 6202 hi->lo)
    orientation = "lo"
    uses_tiered_pass = False  # own edge walk; LCCBeta's schedule unused

    def init_state(self, frag, **kw):
        state = super().init_state(frag, **kw)
        state["quad"] = np.zeros((frag.fnum, frag.vp), dtype=np.int32)
        state.pop("lcc", None)
        return state

    def peval(self, ctx, frag, state):
        vp, fnum = frag.vp, frag.fnum
        n_pad = vp * fnum
        my_fid = lax.axis_index(FRAG_AXIS).astype(jnp.int32)

        ell, cnt = state["ell"], state["cnt"]
        d = ell.shape[-1]
        oe = frag.oe

        # oriented dedup edge mask — same rule (and orientation) as the
        # ELL build, via the shared helper
        keep = self._oriented_edge_mask(ctx, frag)

        ep = oe.edge_src.shape[0]
        # [chunk, d, d] third-level tensors bound the chunk size
        c_e = max(8, min(512, (1 << 21) // max(d * d, 1)))
        c_e = min(c_e, ep)
        n_chunks = max(1, -(-ep // c_e))
        nbr_fid = (oe.edge_nbr // vp).astype(jnp.int32)
        nbr_lid = (oe.edge_nbr % vp).astype(jnp.int32)

        def pass_for(quad, ru_ell, ru_cnt, cur_u, rw_ell, rw_cnt, cur_w):
            def body(i, q):
                start = jnp.minimum(i * c_e, ep - c_e)
                pos0 = start + jnp.arange(c_e, dtype=jnp.int32)
                fresh = pos0 >= i * c_e
                srcs = lax.dynamic_slice(oe.edge_src, (start,), (c_e,))
                nfid = lax.dynamic_slice(nbr_fid, (start,), (c_e,))
                nlid = lax.dynamic_slice(nbr_lid, (start,), (c_e,))
                kept = lax.dynamic_slice(keep, (start,), (c_e,))
                sel = jnp.logical_and(
                    jnp.logical_and(kept, fresh), nfid == cur_u
                )

                sl = jnp.minimum(srcs, vp - 1)
                qv = ell[sl]  # [C, d] = N+(v), sorted, sentinel-padded
                qvalid = jnp.arange(d)[None, :] < cnt[sl][:, None]
                tgt_u = ru_ell[nlid]  # [C, d] = N+(u)
                tcnt_u = ru_cnt[nlid]

                # level 2: C2 = N+(v) ∩ N+(u), marked on qv positions
                p2 = jax.vmap(jnp.searchsorted)(tgt_u, qv)
                h2 = jnp.take_along_axis(
                    tgt_u, jnp.minimum(p2, d - 1), axis=1
                ) == qv
                c2 = jnp.logical_and(h2, p2 < tcnt_u[:, None])
                c2 = jnp.logical_and(c2, qvalid)
                c2 = jnp.logical_and(c2, sel[:, None])

                # level 3: for members w of C2 on shard cur_w,
                # count |C2 ∩ N+(w)|
                wfid = (qv // vp).astype(jnp.int32)
                wlid = (qv % vp).astype(jnp.int32)
                wsel = jnp.logical_and(c2, wfid == cur_w)
                rows_w = rw_ell[jnp.minimum(wlid, vp - 1)]  # [C, d, d]
                rcnt_w = rw_cnt[jnp.minimum(wlid, vp - 1)]  # [C, d]

                t = rows_w.reshape(c_e * d, d)
                qq = jnp.broadcast_to(
                    qv[:, None, :], (c_e, d, d)
                ).reshape(c_e * d, d)
                p3 = jax.vmap(jnp.searchsorted)(t, qq)
                h3 = jnp.take_along_axis(
                    t, jnp.minimum(p3, d - 1), axis=1
                ) == qq
                h3 = jnp.logical_and(h3, p3 < rcnt_w.reshape(c_e * d, 1))
                h3 = h3.reshape(c_e, d, d)
                # x must itself be a C2 member; w must be a selected
                # member resident on the current inner-ring shard
                h3 = jnp.logical_and(h3, c2[:, None, :])
                h3 = jnp.logical_and(h3, wsel[:, :, None])
                cnt4 = h3.sum(axis=(1, 2)).astype(jnp.int32)
                return q.at[jnp.where(sel, sl, vp - 1)].add(
                    jnp.where(sel, cnt4, 0)
                )

            return lax.fori_loop(0, n_chunks, body, quad)

        quad = jnp.zeros((vp,), dtype=jnp.int32)
        if fnum == 1:
            quad = pass_for(
                quad, ell, cnt, jnp.int32(0), ell, cnt, jnp.int32(0)
            )
        else:
            perm = [(i, (i - 1) % fnum) for i in range(fnum)]

            def outer(su, carry):
                q, ru_ell, ru_cnt = carry
                cur_u = (my_fid + su) % fnum

                def inner(sw, icarry):
                    qi, rw_ell, rw_cnt = icarry
                    cur_w = (my_fid + sw) % fnum
                    qi = pass_for(
                        qi, ru_ell, ru_cnt, cur_u, rw_ell, rw_cnt, cur_w
                    )
                    rw_ell = lax.ppermute(rw_ell, FRAG_AXIS, perm)
                    rw_cnt = lax.ppermute(rw_cnt, FRAG_AXIS, perm)
                    return qi, rw_ell, rw_cnt

                # the inner ring completes a full cycle, returning the
                # blocks to their home shard for the next outer step
                q, _, _ = lax.fori_loop(0, fnum, inner, (q, ell, cnt))
                ru_ell = lax.ppermute(ru_ell, FRAG_AXIS, perm)
                ru_cnt = lax.ppermute(ru_cnt, FRAG_AXIS, perm)
                return q, ru_ell, ru_cnt

            quad, _, _ = lax.fori_loop(0, fnum, outer, (quad, ell, cnt))

        out = jnp.where(frag.inner_mask, quad, 0).astype(jnp.int32)
        return dict(state, quad=out), jnp.int32(0)

    def inceval(self, ctx, frag, state):
        return state, jnp.int32(0)

    def finalize(self, frag, state):
        return np.asarray(state["quad"]).astype(np.int64)


class KCliqueDevice(LCCBeta):
    """General-k (k >= 4) on-device clique counting (the r4 coverage
    hole: the reference's `UniFragCliqueNumRecursive` is general-k,
    `examples/analytical_apps/kclique/kclique.h`).

    Formulation: after C2 = N+(v) ∩ N+(u) over an oriented edge chunk,
    a k-clique needs k-2 mutually-adjacent members of C2.  The
    (degree, id) DAG orientation makes rank ordering automatic —
    N+(w) only contains higher-ranked vertices — so the count is a
    depth-(k-2) candidate-set intersection:

        count(mask, 1) = popcount(mask)
        count(mask, 2) = Σ_{w ∈ mask} |mask ∩ N+(w)|   (batched, the
                          k=4 kernel's [chunk, D, D] inner level)
        count(mask, m) = Σ_{w ∈ mask} count(mask ∩ N+(w), m-1)
                          (lax.fori_loop over the D candidate slots)

    built as traced Python recursion over the STATIC m = k-2, i.e.
    d^(k-4) fori iterations around one batched [chunk, D, D] level.

    Remote rows: unlike the k=4 double ring, every recursion level may
    touch any shard's adjacency, and a (k-2)-fold nested ring would
    cost fnum^(k-2) systolic steps — so this kernel all_gathers the
    hub-capped ELL once ([n_pad, D] int32; the work-budget cap in
    KClique.host_compute bounds D before this path is chosen)."""

    result_format = "int"
    credit_mode = "apex"
    orientation = "lo"
    uses_tiered_pass = False  # own edge walk; LCCBeta's schedule unused

    def __init__(self, k: int):
        if k < 4:
            raise ValueError("KCliqueDevice handles k >= 4")
        self.k = int(k)

    def init_state(self, frag, **kw):
        state = super().init_state(frag, **kw)
        state["quad"] = np.zeros((frag.fnum, frag.vp), dtype=np.int32)
        state.pop("lcc", None)
        return state

    def peval(self, ctx, frag, state):
        vp, fnum = frag.vp, frag.fnum
        n_pad = vp * fnum
        ell, cnt = state["ell"], state["cnt"]
        d = ell.shape[-1]
        oe = frag.oe
        keep = self._oriented_edge_mask(ctx, frag)

        if fnum == 1:
            full_ell, full_cnt = ell, cnt
        else:
            full_ell = lax.all_gather(ell, FRAG_AXIS).reshape(n_pad, d)
            full_cnt = lax.all_gather(cnt, FRAG_AXIS).reshape(n_pad)
        # sentinel row: padded qv entries (pid == n_pad) must gather an
        # empty adjacency, not the last real row
        full_ell = jnp.concatenate(
            [full_ell, jnp.full((1, d), n_pad, full_ell.dtype)]
        )
        full_cnt = jnp.concatenate([full_cnt, jnp.zeros((1,), cnt.dtype)])

        ep = oe.edge_src.shape[0]
        c_e = max(8, min(512, (1 << 21) // max(d * d, 1)))
        c_e = min(c_e, ep)
        n_chunks = max(1, -(-ep // c_e))

        def memb(rows, rcnt, qv):
            """[C, d] bool: is qv[c, j] in sorted rows[c, :rcnt[c]]?"""
            p = jax.vmap(jnp.searchsorted)(rows, qv)
            hit = jnp.take_along_axis(
                rows, jnp.minimum(p, d - 1), axis=1
            ) == qv
            return jnp.logical_and(hit, p < rcnt[:, None])

        def count_chains(mask, m, qv):
            """[C] counts of m-length mutually-adjacent ascending
            chains within `mask` (positions index qv)."""
            if m == 1:
                return mask.sum(axis=1).astype(jnp.int32)
            if m == 2:
                # batched last level: membership of every x against
                # every candidate w at once — memb() on the flattened
                # [C*d, d] view (same primitive as level 2)
                cc = mask.shape[0]
                qcl = jnp.minimum(qv, n_pad)
                rows_w = full_ell[qcl]                   # [C, d, d]
                rcnt_w = full_cnt[qcl]                   # [C, d]
                qq = jnp.broadcast_to(
                    qv[:, None, :], (cc, d, d)
                ).reshape(cc * d, d)
                h3 = memb(
                    rows_w.reshape(cc * d, d), rcnt_w.reshape(cc * d), qq
                ).reshape(cc, d, d)
                h3 = jnp.logical_and(h3, mask[:, :, None])  # w chosen
                h3 = jnp.logical_and(h3, mask[:, None, :])  # x still valid
                return h3.sum(axis=(1, 2)).astype(jnp.int32)

            def body(p, acc):
                chosen = mask[:, p]
                w_pid = jnp.minimum(qv[:, p], n_pad)
                nm = jnp.logical_and(
                    mask, memb(full_ell[w_pid], full_cnt[w_pid], qv)
                )
                nm = jnp.logical_and(nm, chosen[:, None])
                return acc + count_chains(nm, m - 1, qv)

            return lax.fori_loop(
                0, d, body,
                jnp.zeros((mask.shape[0],), jnp.int32),
            )

        def chunk_body(i, quad):
            start = jnp.minimum(i * c_e, ep - c_e)
            pos0 = start + jnp.arange(c_e, dtype=jnp.int32)
            fresh = pos0 >= i * c_e
            srcs = lax.dynamic_slice(oe.edge_src, (start,), (c_e,))
            nbrs = lax.dynamic_slice(oe.edge_nbr, (start,), (c_e,))
            kept = lax.dynamic_slice(keep, (start,), (c_e,))
            sel = jnp.logical_and(kept, fresh)

            sl = jnp.minimum(srcs, vp - 1)
            qv = ell[sl]                       # [C, d] = N+(v)
            qvalid = jnp.arange(d)[None, :] < cnt[sl][:, None]
            u_pid = jnp.minimum(nbrs, n_pad)
            c2 = memb(full_ell[u_pid], full_cnt[u_pid], qv)
            c2 = jnp.logical_and(c2, qvalid)
            c2 = jnp.logical_and(c2, sel[:, None])

            cnt_e = count_chains(c2, self.k - 2, qv)
            return quad.at[jnp.where(sel, sl, vp - 1)].add(
                jnp.where(sel, cnt_e, 0)
            )

        quad = lax.fori_loop(
            0, n_chunks, chunk_body, jnp.zeros((vp,), jnp.int32)
        )
        out = jnp.where(frag.inner_mask, quad, 0).astype(jnp.int32)
        return dict(state, quad=out), jnp.int32(0)

    def inceval(self, ctx, frag, state):
        return state, jnp.int32(0)

    def finalize(self, frag, state):
        return np.asarray(state["quad"]).astype(np.int64)
