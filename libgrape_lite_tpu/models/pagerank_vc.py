"""PageRankVC — PageRank on vertex-cut storage via gather-scatter.

Re-design of `examples/analytical_apps/pagerank/pagerank_vc.h` +
`GatherScatterMessageManager`
(`grape/parallel/gather_scatter_message_manager.h:28-399`):

  * degree = # of appearances as src or dst (the stored edge list is
    the raw directed file; accumulation flows both directions,
    `pagerank_vc.h` IncEval),
  * per-round: every fragment scatter-adds `curr[src] -> next[dst]` and
    `curr[dst] -> next[src]` over its edge block, partial sums are
    gathered to masters (`GatherMasterVertices` with NumericSum) — on
    TPU one `psum` over the frag axis,
  * master update `(base + d·sum)/deg` (final round: `d·sum + base`),
    then ScatterMasterVertices — free here because master state is
    mesh-replicated.

State lives in the padded 1-D gpid space of the vertex-cut chunks.
"""

from __future__ import annotations

import jax.numpy as jnp
import jax.ops as jops
import numpy as np

from libgrape_lite_tpu.app.base import GatherScatterAppBase, StepContext
from libgrape_lite_tpu.utils.types import LoadStrategy, MessageStrategy


class PageRankVC(GatherScatterAppBase):
    load_strategy = LoadStrategy.kNullLoadStrategy
    message_strategy = MessageStrategy.kGatherScatter
    result_format = "float"

    def __init__(self, delta: float = 0.85, max_round: int = 10):
        self.delta = delta
        self.max_round = max_round

    @property
    def replicated_keys(self):
        return frozenset(
            {"rank", "deg", "vmask", "step", "dangling_sum", "total_dangling"}
        )

    def init_state(self, frag, delta: float | None = None,
                   max_round: int | None = None):
        if delta is not None:
            self.delta = delta
        if max_round is not None:
            self.max_round = max_round
        n_pad = frag.dev.n_pad
        return {
            "rank": np.zeros(n_pad, dtype=np.float64),
            "deg": np.zeros(n_pad, dtype=np.int64),
            "vmask": frag.vertex_mask(),
            "step": np.int32(0),
            "dangling_sum": np.float64(0),
            "total_dangling": np.float64(0),
        }

    def peval(self, ctx: StepContext, frag, state):
        n_pad = frag.n_pad
        dt = state["rank"].dtype
        ones = jnp.where(frag.mask, 1, 0)
        local_deg = jops.segment_sum(
            ones, frag.dst, num_segments=n_pad
        ) + jops.segment_sum(ones, frag.src, num_segments=n_pad)
        # int32 is plenty for degree counts and avoids x64-dependent dtypes
        deg = ctx.sum(local_deg).astype(jnp.int32)

        vmask = state["vmask"]
        n = vmask.sum().astype(dt)
        p = jnp.asarray(1.0, dt) / n
        dangling = jnp.logical_and(vmask, deg == 0)
        rank = jnp.where(
            vmask,
            jnp.where(deg > 0, p / jnp.maximum(deg, 1).astype(dt), p),
            jnp.asarray(0, dt),
        )
        # the dangling count is over masters globally; vmask is
        # replicated so no psum is needed (communicator.h Sum is the
        # MPI form of the same aggregate)
        total_dangling = dangling.sum().astype(dt)
        state = dict(
            state,
            rank=rank,
            deg=deg,
            dangling_sum=p * total_dangling,
            total_dangling=total_dangling,
            step=jnp.int32(0),
        )
        return state, jnp.int32(1 if self.max_round > 0 else 0)

    def inceval(self, ctx: StepContext, frag, state):
        n_pad = frag.n_pad
        rank = state["rank"]
        dt = rank.dtype
        vmask = state["vmask"]
        deg = state["deg"]
        n = vmask.sum().astype(dt)
        d = self.delta

        step = state["step"] + 1
        base = jnp.asarray(1.0 - d, dt) / n + jnp.asarray(d, dt) * state["dangling_sum"] / n
        dangling_sum = base * state["total_dangling"]

        zero = jnp.asarray(0, dt)
        c_src = jnp.where(frag.mask, rank[frag.src], zero)
        c_dst = jnp.where(frag.mask, rank[frag.dst], zero)
        partial = jops.segment_sum(
            c_src, frag.dst, num_segments=n_pad
        ) + jops.segment_sum(c_dst, frag.src, num_segments=n_pad)
        gathered = ctx.sum(partial)  # GatherMasterVertices<NumericSum>

        is_last = step >= jnp.int32(self.max_round)
        iter_val = jnp.where(
            deg > 0,
            (base + jnp.asarray(d, dt) * gathered)
            / jnp.maximum(deg, 1).astype(dt),
            base,
        )
        final_val = gathered * jnp.asarray(d, dt) + base
        new_rank = jnp.where(
            vmask, jnp.where(is_last, final_val, iter_val), zero
        )
        state = dict(
            state, rank=new_rank, step=step, dangling_sum=dangling_sum
        )
        return state, jnp.where(is_last, jnp.int32(0), jnp.int32(1))

    def finalize(self, frag, state):
        # compact the replicated gpid-space rank into [fnum, vc] rows
        # aligned with inner_oids order (masters = diagonal fragments)
        rank = np.asarray(state["rank"]).reshape(frag.k, frag.vc)
        out = np.zeros((frag.fnum, frag.vc), dtype=rank.dtype)
        for c in range(frag.k):
            oids = frag.inner_oids(c * frag.k + c)
            offs = oids % frag.chunk
            out[c * frag.k + c, : len(oids)] = rank[c, offs]
        return out
