"""PageRankVC — PageRank on vertex-cut storage via gather-scatter.

Re-design of `examples/analytical_apps/pagerank/pagerank_vc.h` +
`GatherScatterMessageManager`
(`grape/parallel/gather_scatter_message_manager.h:28-399`):

  * degree = # of appearances as src or dst (the stored edge list is
    the raw directed file; accumulation flows both directions,
    `pagerank_vc.h` IncEval),
  * per-round: every fragment scatter-adds `curr[src] -> next[dst]` and
    `curr[dst] -> next[src]` over its edge block, partial sums are
    gathered to masters (`GatherMasterVertices` with NumericSum),
  * master update `(base + d·sum)/deg` (final round: `d·sum + base`),
    then ScatterMasterVertices.

TPU formulation (SUMMA): the k x k fragment grid IS a 2-D device mesh
(`CommSpec.mesh2d`, axes vcrow/vccol; fragment (i, j) holds the edge
block src∈chunk_i x dst∈chunk_j).  Master state is SHARDED, not
replicated: device (i, j) keeps rank/deg for chunk i (row copy) and
chunk j (column copy) — O(N/k) per device, realizing the 2-D
partition's memory advantage
(`immutable_vertexcut_fragment.h:82-148`).  Per round:

  * scatter into dst: partials psum over `vcrow` → complete chunk-j
    sums, column-sharded (the GatherToMaster segment-reduce);
  * scatter into src: partials psum over `vccol` → row-sharded, then
    ONE transpose `ppermute` ((i,j)→(j,i)) aligns them column-sharded;
  * the master update runs on the column copy; a second transpose
    refreshes the row copy (ScatterToFragment).

PageRankVCReplicated keeps the round-1 mesh-replicated formulation for
A/B (`pagerank_vc_rep`).
"""

from __future__ import annotations

import jax.numpy as jnp
import jax.ops as jops
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from libgrape_lite_tpu.app.base import GatherScatterAppBase, StepContext
from libgrape_lite_tpu.models.vc2d import vc_transpose as _transpose
from libgrape_lite_tpu.parallel.comm_spec import VC_COL_AXIS, VC_ROW_AXIS
from libgrape_lite_tpu.utils.types import LoadStrategy, MessageStrategy


class PageRankVC(GatherScatterAppBase):
    load_strategy = LoadStrategy.kNullLoadStrategy
    message_strategy = MessageStrategy.kGatherScatter
    result_format = "float"
    mesh_kind = "vc2d"
    replicated_keys = frozenset({"step", "dangling_sum", "total_dangling"})

    def __init__(self, delta: float = 0.85, max_round: int = 10):
        self.delta = delta
        self.max_round = max_round

    def custom_specs(self):
        return {
            "rank_col": P(VC_COL_AXIS), "rank_row": P(VC_ROW_AXIS),
            "deg_col": P(VC_COL_AXIS), "deg_row": P(VC_ROW_AXIS),
            "vmask_col": P(VC_COL_AXIS), "vmask_row": P(VC_ROW_AXIS),
        }

    def init_state(self, frag, delta: float | None = None,
                   max_round: int | None = None):
        if delta is not None:
            self.delta = delta
        if max_round is not None:
            self.max_round = max_round
        # partition fingerprint (r10): keys the runner cache apart
        # from any 1-D compile and feeds the obs query span's tile
        # record (trace_report's tile table)
        self._partition = "2d"
        self._mesh_k = frag.k
        self._partition_stats = frag.tile_stats()
        n_pad = frag.dev.n_pad
        vmask = frag.vertex_mask()
        return {
            # global [k*vc] leaves; placement shards them into [vc]
            # row/col chunk copies per device
            "rank_col": np.zeros(n_pad, dtype=np.float64),
            "rank_row": np.zeros(n_pad, dtype=np.float64),
            "deg_col": np.zeros(n_pad, dtype=np.int32),
            "deg_row": np.zeros(n_pad, dtype=np.int32),
            "vmask_col": vmask,
            "vmask_row": vmask,
            "step": np.int32(0),
            "dangling_sum": np.float64(0),
            "total_dangling": np.float64(0),
        }

    def peval(self, ctx: StepContext, frag, state):
        k, vc = frag.k, frag.vc
        dt = state["rank_col"].dtype
        vmask_col = state["vmask_col"]

        ones = jnp.where(frag.mask, 1, 0)
        # degree: appearances as dst (column copy) + as src (row copy)
        dd = lax.psum(
            jops.segment_sum(ones, frag.dst % vc, num_segments=vc),
            VC_ROW_AXIS,
        )
        ds = lax.psum(
            jops.segment_sum(ones, frag.src % vc, num_segments=vc),
            VC_COL_AXIS,
        )
        deg_col = (dd + _transpose(ds, k)).astype(jnp.int32)
        deg_row = _transpose(deg_col, k)

        # global vertex count: each column chunk counted once per row
        n = lax.psum(vmask_col.sum(), VC_COL_AXIS).astype(dt)
        p = jnp.asarray(1.0, dt) / n
        dangling = jnp.logical_and(vmask_col, deg_col == 0)
        total_dangling = lax.psum(dangling.sum(), VC_COL_AXIS).astype(dt)

        rank_col = jnp.where(
            vmask_col,
            jnp.where(deg_col > 0, p / jnp.maximum(deg_col, 1).astype(dt), p),
            jnp.asarray(0, dt),
        )
        state = dict(
            state,
            rank_col=rank_col,
            rank_row=_transpose(rank_col, k),
            deg_col=deg_col,
            deg_row=deg_row,
            dangling_sum=p * total_dangling,
            total_dangling=total_dangling,
            step=jnp.int32(0),
        )
        return state, jnp.int32(1 if self.max_round > 0 else 0)

    def inceval(self, ctx: StepContext, frag, state):
        k, vc = frag.k, frag.vc
        dt = state["rank_col"].dtype
        vmask_col = state["vmask_col"]
        deg_col = state["deg_col"]
        n = lax.psum(vmask_col.sum(), VC_COL_AXIS).astype(dt)
        d = self.delta

        step = state["step"] + 1
        base = jnp.asarray(1.0 - d, dt) / n + jnp.asarray(d, dt) * state["dangling_sum"] / n
        dangling_sum = base * state["total_dangling"]

        zero = jnp.asarray(0, dt)
        # src-side ranks flow to dst (column direction) and vice versa
        c_src = jnp.where(frag.mask, state["rank_row"][frag.src % vc], zero)
        c_dst = jnp.where(frag.mask, state["rank_col"][frag.dst % vc], zero)
        into_dst = lax.psum(
            jops.segment_sum(c_src, frag.dst % vc, num_segments=vc),
            VC_ROW_AXIS,
        )
        into_src = lax.psum(
            jops.segment_sum(c_dst, frag.src % vc, num_segments=vc),
            VC_COL_AXIS,
        )
        gathered = into_dst + _transpose(into_src, k)

        is_last = step >= jnp.int32(self.max_round)
        iter_val = jnp.where(
            deg_col > 0,
            (base + jnp.asarray(d, dt) * gathered)
            / jnp.maximum(deg_col, 1).astype(dt),
            base,
        )
        final_val = gathered * jnp.asarray(d, dt) + base
        rank_col = jnp.where(
            vmask_col, jnp.where(is_last, final_val, iter_val), zero
        )
        state = dict(
            state,
            rank_col=rank_col,
            rank_row=_transpose(rank_col, k),
            step=step,
            dangling_sum=dangling_sum,
        )
        return state, jnp.where(is_last, jnp.int32(0), jnp.int32(1))

    def finalize(self, frag, state):
        # compact the gpid-space rank into [fnum, vc] rows aligned with
        # inner_oids order (masters = diagonal fragments)
        rank = np.asarray(state["rank_col"]).reshape(frag.k, frag.vc)
        out = np.zeros((frag.fnum, frag.vc), dtype=rank.dtype)
        for c in range(frag.k):
            oids = frag.inner_oids(c * frag.k + c)
            offs = oids % frag.chunk
            out[c * frag.k + c, : len(oids)] = rank[c, offs]
        return out


class PageRankVCReplicated(GatherScatterAppBase):
    """Round-1 formulation: master state mesh-replicated ([n_pad] per
    device), gather = one psum over the frag axis.  O(N) memory per
    device — kept for A/B against the SUMMA-sharded default."""

    load_strategy = LoadStrategy.kNullLoadStrategy
    message_strategy = MessageStrategy.kGatherScatter
    result_format = "float"

    def __init__(self, delta: float = 0.85, max_round: int = 10):
        self.delta = delta
        self.max_round = max_round

    @property
    def replicated_keys(self):
        return frozenset(
            {"rank", "deg", "vmask", "step", "dangling_sum", "total_dangling"}
        )

    def init_state(self, frag, delta: float | None = None,
                   max_round: int | None = None):
        if delta is not None:
            self.delta = delta
        if max_round is not None:
            self.max_round = max_round
        n_pad = frag.dev.n_pad
        return {
            "rank": np.zeros(n_pad, dtype=np.float64),
            "deg": np.zeros(n_pad, dtype=np.int64),
            "vmask": frag.vertex_mask(),
            "step": np.int32(0),
            "dangling_sum": np.float64(0),
            "total_dangling": np.float64(0),
        }

    def peval(self, ctx: StepContext, frag, state):
        n_pad = frag.n_pad
        dt = state["rank"].dtype
        ones = jnp.where(frag.mask, 1, 0)
        local_deg = jops.segment_sum(
            ones, frag.dst, num_segments=n_pad
        ) + jops.segment_sum(ones, frag.src, num_segments=n_pad)
        # int32 is plenty for degree counts and avoids x64-dependent dtypes
        deg = ctx.sum(local_deg).astype(jnp.int32)

        vmask = state["vmask"]
        n = vmask.sum().astype(dt)
        p = jnp.asarray(1.0, dt) / n
        dangling = jnp.logical_and(vmask, deg == 0)
        rank = jnp.where(
            vmask,
            jnp.where(deg > 0, p / jnp.maximum(deg, 1).astype(dt), p),
            jnp.asarray(0, dt),
        )
        # the dangling count is over masters globally; vmask is
        # replicated so no psum is needed (communicator.h Sum is the
        # MPI form of the same aggregate)
        total_dangling = dangling.sum().astype(dt)
        state = dict(
            state,
            rank=rank,
            deg=deg,
            dangling_sum=p * total_dangling,
            total_dangling=total_dangling,
            step=jnp.int32(0),
        )
        return state, jnp.int32(1 if self.max_round > 0 else 0)

    def inceval(self, ctx: StepContext, frag, state):
        n_pad = frag.n_pad
        rank = state["rank"]
        dt = rank.dtype
        vmask = state["vmask"]
        deg = state["deg"]
        n = vmask.sum().astype(dt)
        d = self.delta

        step = state["step"] + 1
        base = jnp.asarray(1.0 - d, dt) / n + jnp.asarray(d, dt) * state["dangling_sum"] / n
        dangling_sum = base * state["total_dangling"]

        zero = jnp.asarray(0, dt)
        c_src = jnp.where(frag.mask, rank[frag.src], zero)
        c_dst = jnp.where(frag.mask, rank[frag.dst], zero)
        partial = jops.segment_sum(
            c_src, frag.dst, num_segments=n_pad
        ) + jops.segment_sum(c_dst, frag.src, num_segments=n_pad)
        gathered = ctx.sum(partial)  # GatherMasterVertices<NumericSum>

        is_last = step >= jnp.int32(self.max_round)
        iter_val = jnp.where(
            deg > 0,
            (base + jnp.asarray(d, dt) * gathered)
            / jnp.maximum(deg, 1).astype(dt),
            base,
        )
        final_val = gathered * jnp.asarray(d, dt) + base
        new_rank = jnp.where(
            vmask, jnp.where(is_last, final_val, iter_val), zero
        )
        state = dict(
            state, rank=new_rank, step=step, dangling_sum=dangling_sum
        )
        return state, jnp.where(is_last, jnp.int32(0), jnp.int32(1))

    def finalize(self, frag, state):
        # compact the replicated gpid-space rank into [fnum, vc] rows
        # aligned with inner_oids order (masters = diagonal fragments)
        rank = np.asarray(state["rank"]).reshape(frag.k, frag.vc)
        out = np.zeros((frag.fnum, frag.vc), dtype=rank.dtype)
        for c in range(frag.k):
            oids = frag.inner_oids(c * frag.k + c)
            offs = oids % frag.chunk
            out[c * frag.k + c, : len(oids)] = rank[c, offs]
        return out
