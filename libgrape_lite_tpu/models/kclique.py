"""KClique — k-clique counting.

Re-design of `examples/analytical_apps/kclique/kclique.h` +
`kclique_utils.h`: count k-cliques by recursive candidate-set
intersection over a degree-ordered orientation DAG (each clique counted
once at its DAG-minimal apex).

The reference runs this as a recursive CPU kernel under its thread-pool
engine (`UniFragCliqueNumRecursive`); the irregular recursion has no
profitable static-shape form, so this app runs on the *host engine*
(numpy packed bitmaps, vectorised innermost levels) rather than the
traced superstep path — mirroring where the reference actually executes
it — except k=3 (merge-intersection kernel, models/lcc_beta.py in
apex-counting mode) and k=4 under `hub_cap` (double-ring ELL kernel,
models/kclique_device.py), which run ON-DEVICE.  k>=5 and over-cap k=4
recurse per apex on the host with vectorised leaf levels.

Output: per-apex clique counts (sum == global k-clique count, exposed
as `worker.app.total_cliques` after a query; the reference prints only
the global count, `kclique_context.h` Output).
"""

from __future__ import annotations

import weakref

import numpy as np

from libgrape_lite_tpu.app.base import AppBase
from libgrape_lite_tpu.utils.types import LoadStrategy, MessageStrategy

# fragment -> Worker over the device triangle kernel, so repeated k=3
# queries reuse the compiled step (entries self-purge with the fragment)
_TRIANGLE_WORKERS = weakref.WeakKeyDictionary()


def _popcount(a: np.ndarray) -> np.ndarray:
    """Row-wise popcount of a 2-D packed bitmap -> [rows] int64."""
    if hasattr(np, "bitwise_count"):
        return np.bitwise_count(a).sum(axis=1, dtype=np.int64)
    # fallback: byte-table popcount
    b = a.view(np.uint8)
    table = np.array([bin(i).count("1") for i in range(256)], dtype=np.uint8)
    return table[b].reshape(a.shape[0], -1).sum(axis=1, dtype=np.int64)


class KClique(AppBase):
    load_strategy = LoadStrategy.kOnlyOut
    message_strategy = MessageStrategy.kSyncOnOuterVertex
    result_format = "int"
    host_only = True

    # k=4 runs on-device (models/kclique_device.py) while the max
    # oriented out-degree stays under this cap; the kernel's chunking
    # keeps the [chunk, D, D] third-level tensor under ~2M entries, so
    # the cap bounds per-edge WORK (D² candidate tests/edge), not
    # memory.  Low->high orientation keeps D at degeneracy scale
    # (rmat13/16/18/20 → 66/151/259/679 vs 1508/6202/… hi->lo); 320
    # admits RMAT-18 on-device, RMAT-20 hubs recurse on host
    hub_cap = 320

    def __init__(self, k: int = 3):
        self.k = k
        self.total_cliques = 0
        self.used_device_kernel = False

    def host_compute(self, frag, k: int | None = None):
        if k is not None:
            self.k = k
        k = self.k
        fnum, vp = frag.fnum, frag.vp

        if k == 3:
            # triangles run on-device through the merge-intersection
            # kernel in apex-counting mode (ROADMAP item 3) — before
            # any host edge materialization, which is the bottleneck
            # this path removes
            from libgrape_lite_tpu.models.lcc_beta import ApexTriangleCount
            from libgrape_lite_tpu.worker.worker import Worker

            if frag not in _TRIANGLE_WORKERS:
                _TRIANGLE_WORKERS[frag] = Worker(ApexTriangleCount(), frag)
            w = _TRIANGLE_WORKERS[frag]
            w.query()
            per_apex = w.result_values()
            self.used_device_kernel = True
            self.total_cliques = int(per_apex.sum())
            return {"count": per_apex}

        def run_device(app):
            from libgrape_lite_tpu.worker.worker import Worker

            w = Worker(app, frag)
            w.query()
            per_apex = w.result_values()
            self.used_device_kernel = True
            self.total_cliques = int(per_apex.sum())
            return {"count": per_apex}

        if k == 4 and self._oriented_dmax(frag) <= self.hub_cap:
            # low-degeneracy graphs: the double-ring ELL kernel
            from libgrape_lite_tpu.models.kclique_device import (
                KClique4Device,
            )

            return run_device(KClique4Device())

        if k >= 5 and self._oriented_dmax(frag) <= self.general_cap(k):
            # general-k device kernel (all-gathered ELL, depth-(k-2)
            # traced intersection); the work budget caps D so the
            # d^(k-2) candidate tests per edge stay device-sized.
            # Unlike the k=4 ring kernel, this one REPLICATES the
            # hub-capped ELL per device — bill that gather against a
            # budget so a huge low-degeneracy graph (road network)
            # stays on the sharded host path instead of OOMing HBM
            dmax = self._oriented_dmax(frag)
            gather_bytes = (fnum * vp + 1) * (dmax + 1) * 4
            if gather_bytes <= self._GATHER_BYTES_BUDGET:
                from libgrape_lite_tpu.models.kclique_device import (
                    KCliqueDevice,
                )

                return run_device(KCliqueDevice(k))
        self.used_device_kernel = False

        # global (dense-compacted) oriented adjacency from the host CSRs
        v, u = _oriented_pairs(frag)

        counts = np.zeros(fnum * vp, dtype=np.int64)
        if k == 1:
            counts[: fnum * vp] = 0
            for f in range(fnum):
                counts[f * vp : f * vp + frag.inner_vertices_num(f)] = 1
        elif k == 2:
            np.add.at(counts, v, 1)
        elif len(v) > 0:
            # compact pids to dense ranks for the bitmap universe
            used = np.unique(np.concatenate([v, u]))
            rank = {p: i for i, p in enumerate(used.tolist())}
            n = len(used)
            words = (n + 63) // 64
            vr = np.array([rank[p] for p in v.tolist()])
            ur = np.array([rank[p] for p in u.tolist()])
            B = np.zeros((n, words), dtype=np.uint64)
            np.bitwise_or.at(
                B, (vr, ur // 64), np.uint64(1) << (ur % 64).astype(np.uint64)
            )

            # k >= 4: host recursion (k == 3 returned above)
            # adjacency (oriented out-neighbor ranks) per vertex
            order = np.argsort(vr, kind="stable")
            vs, us = vr[order], ur[order]
            starts = np.searchsorted(vs, np.arange(n))
            ends = np.searchsorted(vs, np.arange(n) + 1)

            def _bits(bm: np.ndarray) -> np.ndarray:
                out = []
                for wi in np.nonzero(bm)[0]:
                    word = int(bm[wi])
                    while word:
                        b = word & -word
                        out.append(wi * 64 + b.bit_length() - 1)
                        word ^= b
                return np.asarray(out, dtype=np.int64)

            def rec(cand: np.ndarray, depth: int) -> int:
                """Count cliques extending the current chain whose
                remaining candidates are `cand` (packed bitmap)."""
                if depth == 0:
                    return int(_popcount(cand[None, :]).sum())
                members = _bits(cand)
                if len(members) == 0:
                    return 0
                if depth == 1:
                    inter = B[members] & cand[None, :]
                    return int(_popcount(inter).sum())
                total = 0
                for w in members:
                    total += rec(cand & B[w], depth - 1)
                return total

            for apex_rank in range(n):
                s, e = starts[apex_rank], ends[apex_rank]
                if e - s < k - 1:
                    continue
                cand = np.zeros(words, np.uint64)
                np.bitwise_or.at(
                    cand, us[s:e] // 64,
                    np.uint64(1) << (us[s:e] % 64).astype(np.uint64),
                )
                counts[int(used[apex_rank])] += rec(cand, k - 2)

        self.total_cliques = int(counts.sum())
        return {"count": counts.reshape(fnum, vp)}

    # per-edge candidate-test budget for the general-k device kernel:
    # D^(k-2) <= _GENERAL_WORK_BUDGET picks the max admissible oriented
    # out-degree per k (k=5: D<=80, k=6: D<=26, k=7: D<=13); beyond it
    # the host recursion takes over, same as the over-cap k=4 case
    _GENERAL_WORK_BUDGET = 1 << 19
    # replicated-ELL ceiling for the general-k kernel's all_gather
    # ((n_pad+1) x (D+1) int32 per device); ~2 GiB default
    _GATHER_BYTES_BUDGET = 2 << 30

    def general_cap(self, k: int) -> int:
        return int(self._GENERAL_WORK_BUDGET ** (1.0 / (k - 2)))

    @staticmethod
    def _oriented_dmax(frag) -> int:
        """Max (degree, id)-oriented out-degree — the degeneracy bound
        that sizes the device kernel's [D, D] third-level tensors."""
        v, _ = _oriented_pairs(frag)
        if len(v) == 0:
            return 0
        return int(np.bincount(v).max())

    def finalize(self, frag, state):
        return np.asarray(state["count"])


def _oriented_pairs(frag):
    """Dedup (degree, pid)-oriented edge pairs (v, u) in global pid
    space — the host-side form of the orientation every clique/LCC
    kernel shares (`lcc.h` stage-1 neighbor filter).  Cached per
    fragment: k=4 queries consult it for the hub-cap gate and the host
    recursion reuses the same pairs."""
    cached = _ORIENTED_PAIRS.get(frag)
    if cached is not None:
        return cached
    fnum, vp = frag.fnum, frag.vp
    v_list, u_list = [], []
    deg = np.zeros(fnum * vp, dtype=np.int64)
    for f in range(fnum):
        c = frag.host_oe[f]
        e = c.num_edges
        deg[f * vp : (f + 1) * vp] = np.diff(c.indptr)
        v_list.append(f * vp + c.edge_src[:e].astype(np.int64))
        u_list.append(c.edge_nbr[:e].astype(np.int64))
    v = np.concatenate(v_list) if v_list else np.zeros(0, np.int64)
    u = np.concatenate(u_list) if u_list else np.zeros(0, np.int64)

    pairs = np.unique(np.stack([v, u], 1), axis=0)
    v, u = pairs[:, 0], pairs[:, 1]
    # low->high orientation (matches KClique4Device's ELL): every clique
    # is counted at its (degree,id)-minimal member, and max oriented
    # out-degree is bounded by degeneracy instead of raw hub degree
    keep = (deg[u] > deg[v]) | ((deg[u] == deg[v]) & (u > v))
    keep &= v != u
    cached = (v[keep], u[keep])
    _ORIENTED_PAIRS[frag] = cached
    return cached


_ORIENTED_PAIRS = weakref.WeakKeyDictionary()
