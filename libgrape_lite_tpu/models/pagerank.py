"""PageRank — LDBC variant with dangling-mass approximation.

Re-design of `examples/analytical_apps/pagerank/pagerank.h:34-160` (the
BatchShuffle app): during iteration the state holds rank/degree; each
round pulls the neighbor sum (SpMV), applies

    base = (1-d)/n + d * dangling_sum / n
    next[v] = deg > 0 ? (d * sum + base) / deg : base
    dangling_sum' = base * total_dangling

and after `max_round` pulls multiplies by the degree
(`pagerank.h:146-156`).  The dangling allreduce (`pagerank.h:85`,
`communicator.h:110-113`) is a `psum`.

TPU formulation: the per-round whole-array mirror exchange
(`batch_shuffle_message_manager.h:237,264`) is ONE `all_gather` of the
rank vector over ICI; the pull loop is a gather + `segment_sum` — a
sparse-dense SpMV the XLA scheduler pipelines with the collective.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from libgrape_lite_tpu.app.base import BatchShuffleAppBase, StepContext
from libgrape_lite_tpu.utils.types import LoadStrategy, MessageStrategy


class PageRank(BatchShuffleAppBase):
    # kBothOutIn like pagerank_parallel.h:46 — the pull reads incoming
    # edges while the normalisation uses the out-degree; on undirected
    # graphs the two CSRs alias so this costs nothing extra
    load_strategy = LoadStrategy.kBothOutIn
    message_strategy = MessageStrategy.kAlongOutgoingEdgeToOuterVertex
    need_split_edges = True
    result_format = "float"
    replicated_keys = frozenset({"step", "dangling_sum", "total_dangling"})
    # serve/: personalized PageRank batches over the per-lane seed via
    # the same source-vector contract SSSP/BFS use (app/base.py);
    # global queries (no source) fall back to generic lane stacking
    batch_query_key = "source"
    # dyn/: PageRank runs exactly max_round steps from a fixed init —
    # there is no fixed point to reuse at finite rounds, so the
    # incremental contract is an honest counted restart
    inc_mode = "restart"
    # r9: the rank pull pipelines on the plain XLA segment-sum path
    # (per-row addend order is preserved by the stable row partition);
    # the pack/strict backends regroup float partials and stay serial
    pipeline_state_key = "rank"

    def __init__(self, delta: float = 0.85, max_round: int = 10):
        self.delta = delta
        self.max_round = max_round
        self._personalized = False

    def init_state_batch(self, frag, args_list):
        """Vector-seed batching only when EVERY lane carries a source
        (personalized); all-global lanes take the generic stacking
        fallback — the cheap path's default-fill would otherwise
        silently personalize a global query at vertex 0.  A MIX of
        the two cannot share one batch (personalized carries trace a
        seed leaf, global ones don't): fail with the reason instead
        of a bare KeyError out of the stacker.  The serve compat key
        keeps mixed lanes apart upstream; this guards the direct
        Worker.query_batch surface."""
        seeded = ["source" in a and a["source"] is not None
                  for a in args_list]
        if not any(seeded):
            key, self.batch_query_key = self.batch_query_key, None
            try:
                return super().init_state_batch(frag, args_list)
            finally:
                self.batch_query_key = key
        if not all(seeded):
            raise ValueError(
                "personalized (source given) and global PageRank "
                "lanes cannot share one batch — their carries have "
                "different structure; batch them separately"
            )
        return super().init_state_batch(frag, args_list)

    def init_state(self, frag, delta: float | None = None,
                   max_round: int | None = None, source=None):
        if delta is not None:
            self.delta = delta
        if max_round is not None:
            self.max_round = max_round
        import jax

        # honest TPU dtype (VERDICT r1 weak #6): with x64 disabled, JAX
        # silently downcasts float64 state anyway — declare f32 up
        # front so eps behavior is explicit and the f32-only Pallas
        # paths are eligible; under x64 (the CPU golden lanes) keep f64
        default_f = np.float64 if jax.config.jax_enable_x64 else np.float32
        dtype = (
            frag.host_oe[0].edge_w.dtype
            if (frag.weighted and frag.host_oe[0].edge_w is not None)
            else default_f
        )
        self.dtype = np.dtype(dtype) if np.dtype(dtype).kind == "f" else np.dtype(default_f)
        # personalized PageRank (PPR): `source` turns the uniform
        # teleport vector into a one-hot seed; a SEQUENCE of sources
        # builds the [k, ...] batched carry for the serve/ vmapped
        # dispatch (seed mass 1 per lane; an absent source leaves a
        # zero seed — rank identically zero, like SSSP's unreachable
        # convention).  source=None keeps the LDBC global variant
        # BIT-IDENTICAL: no seed leaf enters the state and the legacy
        # scalar base formula below is untouched.
        batched = isinstance(source, (list, tuple, np.ndarray))
        sources = list(source) if batched else [source]
        self._personalized = any(s is not None for s in sources)
        rank = np.zeros((frag.fnum, frag.vp), dtype=self.dtype)
        state = {
            "rank": rank,
            "step": np.int32(0),
            "dangling_sum": self.dtype.type(0),
            "total_dangling": self.dtype.type(0),
        }
        if self._personalized:
            from libgrape_lite_tpu.app.base import source_lane_array

            _, seed = source_lane_array(
                frag, sources, "PageRank", 0.0, 1.0, self.dtype
            )
            k = len(sources)
            if batched:
                state = {
                    "rank": np.zeros((k, frag.fnum, frag.vp),
                                     dtype=self.dtype),
                    "step": np.zeros((k,), np.int32),
                    "dangling_sum": np.zeros((k,), self.dtype),
                    "total_dangling": np.zeros((k,), self.dtype),
                    "seed": seed,
                }
            else:
                state["seed"] = seed[0]
        # SpMV path selection (GRAPE_SPMV env: auto|xla|strict|pack):
        #   pack   — the pack-gather Pallas pipeline (ops/spmv_pack.py),
        #            f32 + single-shard; the round-2 perf design
        #   strict — the strict-tile kernel (ops/spmv.py)
        #   auto   — XLA segment_sum until a hardware A/B flips the
        #            default (docs/PERF_NOTES.md tracks measurements)
        import os

        self._spmv_mode = os.environ.get("GRAPE_SPMV", "auto")
        self._pack = None
        eph_entries = {}
        # mirror-compressed exchange (GRAPE_EXCHANGE): sync only
        # outer-vertex rows instead of all_gathering the full state
        from libgrape_lite_tpu.parallel.mirror import resolve_mirror_plan

        self._mx = resolve_mirror_plan(frag, "ie")
        if self._mx is not None:
            eph_entries.update(self._mx.state_entries("mx_"))
        self._mx_uid = self._mx.uid if self._mx is not None else -1
        if self._spmv_mode == "pack":
            from libgrape_lite_tpu.ops.spmv_pack import (
                resolve_pack_dispatch,
                warn_pack_ineligible,
            )

            if self.dtype != np.float32:
                warn_pack_ineligible(
                    "PageRank", f"state dtype {self.dtype} is not float32"
                )
            else:
                # single-shard: stream tables close over the trace;
                # multi-shard: they enter as sharded ephemeral state
                self._pack = resolve_pack_dispatch(frag, mirror=self._mx)
                if self._pack is None:
                    warn_pack_ineligible(
                        "PageRank", "no pack plan buildable"
                    )
                else:
                    eph_entries.update(self._pack.state_entries())
        if eph_entries:
            state.update(eph_entries)
            self.ephemeral_keys = frozenset(eph_entries)
        # bake the plan identity into the trace key: a cached runner
        # must never pair with a different fragment's closed-over plan
        self._pack_plan_uid = (
            self._pack.uid if self._pack is not None else -1
        )
        if self._pack is None:
            from libgrape_lite_tpu.ops.spmv import plan_for_app

            plan = plan_for_app(frag, frag.vp, self.dtype)
            self._spmv_tile = plan[1] if plan else 0
            self._spmv_rmax = plan[2] if plan else 0
            if plan:
                row_lo = plan[0]
                if batched:
                    # pass-through carry leaves need the lane axis too
                    row_lo = np.broadcast_to(
                        row_lo, (len(sources),) + row_lo.shape
                    ).copy()
                state["spmv_row_lo"] = row_lo
        else:
            self._spmv_tile = self._spmv_rmax = 0
        # superstep pipelining (r9): only the plain gather+segment_sum
        # path splits bit-stably (a sorted segment sum consumes each
        # row's addends in stream order; the strict-tile and pack
        # backends regroup partials across a split — pinned in
        # tests/test_pipeline.py)
        self._pipeline = None
        if not batched:
            from libgrape_lite_tpu.parallel.pipeline import resolve_pipeline

            self._pipeline = resolve_pipeline(
                frag, app_name="PageRank", key="rank", direction="ie",
                mirror=self._mx, mx_prefix="mx_", pack=self._pack,
                fold="sum", with_weights=False,
                eligible="spmv_row_lo" not in state,
                reason="strict-tile spmv plan engaged (tile partial "
                       "sums regroup under a split)",
            )
            if self._pipeline is not None:
                state.update(self._pipeline.host_entries)
                self.ephemeral_keys = frozenset(
                    set(self.ephemeral_keys)
                    | set(self._pipeline.host_entries)
                )
        self._pipeline_uid = (
            self._pipeline.uid if self._pipeline is not None else -1
        )
        return state

    def peval(self, ctx: StepContext, frag, state):
        n = frag.total_vnum
        dt = state["rank"].dtype
        deg = frag.out_degree
        dangling = jnp.logical_and(frag.inner_mask, deg == 0)
        if self._personalized:
            # PPR: the teleport vector is the one-hot seed s instead of
            # the uniform 1/n — same rank/deg stored form, and the two
            # conserved scalars become seed MASSES (total_dangling =
            # seed mass sitting on dangling vertices; dangling_sum =
            # that same mass at init)
            s = state["seed"]
            rank = jnp.where(
                frag.inner_mask,
                jnp.where(deg > 0, s / jnp.maximum(deg, 1).astype(dt), s),
                jnp.asarray(0, dt),
            )
            total_dangling = ctx.sum(
                jnp.where(dangling, s, jnp.asarray(0, dt)).sum()
            )
            state = dict(
                state,
                rank=rank,
                step=jnp.int32(0),
                dangling_sum=total_dangling,
                total_dangling=total_dangling,
            )
            return state, jnp.int32(1 if self.max_round > 0 else 0)
        p = jnp.asarray(1.0 / n, dt)
        rank = jnp.where(
            frag.inner_mask,
            jnp.where(deg > 0, p / jnp.maximum(deg, 1).astype(dt), p),
            jnp.asarray(0, dt),
        )
        total_dangling = ctx.sum(dangling.sum().astype(dt))
        state = dict(
            state,  # preserve pass-through keys (e.g. spmv_row_lo)
            rank=rank,
            step=jnp.int32(0),
            dangling_sum=p * total_dangling,
            total_dangling=total_dangling,
        )
        return state, jnp.int32(1 if self.max_round > 0 else 0)

    def round_update(self, frag, state, cur):
        """One PageRank round given the in-neighbor rank sum `cur` —
        shared by the pull path (inceval) and the push/SyncBuffer path
        (PageRankAuto): base/dangling bookkeeping, degree division, and
        the final-round rank*deg re-multiplication (pagerank.h:102-156)."""
        n = frag.total_vnum
        d = self.delta
        dt = state["rank"].dtype
        step = state["step"] + 1
        if self._personalized:
            # PPR: teleport + dangling mass both land on the seed, so
            # the scalar base becomes a per-vertex vector scal * s_v;
            # the mass that re-lands on dangling vertices is scal *
            # (seed mass on dangling) — same conservation algebra as
            # the global variant with e_seed in place of 1/n
            scal = (
                jnp.asarray(1.0 - d, dt)
                + jnp.asarray(d, dt) * state["dangling_sum"]
            )
            base = scal * state["seed"]
            dangling_sum = scal * state["total_dangling"]
        else:
            base = jnp.asarray((1.0 - d) / n, dt) + jnp.asarray(d / n, dt) * state["dangling_sum"]
            dangling_sum = base * state["total_dangling"]
        deg = frag.out_degree
        nxt = jnp.where(
            deg > 0,
            (jnp.asarray(d, dt) * cur + base) / jnp.maximum(deg, 1).astype(dt),
            base,
        )
        nxt = jnp.where(frag.inner_mask, nxt, jnp.asarray(0, dt))

        is_last = step >= jnp.int32(self.max_round)
        # final assemble (pagerank.h:146-156): ranks stored as rank/deg
        # during iteration; multiply back on the last round
        finald = jnp.where(deg > 0, nxt * deg.astype(dt), nxt)
        rank_out = jnp.where(is_last, finald, nxt)
        new_state = dict(
            state,  # preserve pass-through keys (e.g. spmv_row_lo)
            rank=rank_out,
            step=step,
            dangling_sum=dangling_sum,
        )
        return new_state, jnp.where(is_last, jnp.int32(0), jnp.int32(1))

    def inceval(self, ctx: StepContext, frag, state):
        # pull over incoming edges (pagerank_parallel.h:128-136: for
        # undirected graphs this equals the out-adjacency pull of
        # pagerank.h:122-128, and it is the correct direction when
        # --directed)
        rank = state["rank"]
        dt = rank.dtype
        ie = frag.ie
        if self._mx is not None:
            full = ctx.exchange_mirrors(rank, state["mx_send"])
            nbr = state["mx_nbr"]
        else:
            full = ctx.gather_state(rank)
            nbr = ie.edge_nbr
        if self._pack is not None:
            # pack-gather pipeline: the plan owns BOTH the x[nbr]
            # gather and the row reduction (pad edges were excluded at
            # plan time, so no mask multiply is needed)
            cur = self._pack.reduce(full, state, "sum").astype(dt)
            return self.round_update(frag, state, cur)
        contrib = jnp.where(ie.edge_mask, full[nbr], jnp.asarray(0, dt))
        from libgrape_lite_tpu.ops.spmv import segment_sum_auto

        plan = (
            (state["spmv_row_lo"], self._spmv_tile, self._spmv_rmax)
            if "spmv_row_lo" in state
            else None
        )
        cur = segment_sum_auto(contrib, ie.edge_src, frag.vp, plan).astype(dt)
        return self.round_update(frag, state, cur)

    def inceval_pipelined(self, ctx: StepContext, frag, state, xbuf):
        """Double-buffered round (parallel/pipeline.py): the boundary
        slice's rank sum runs first, `round_update` lifts it to the
        boundary rows' NEW ranks (the update is elementwise per row
        given the round's replicated scalars, so the boundary rows of
        the partial update equal the joined update bitwise), the
        exchange kicks off, and the interior sum overlaps it.  The
        final `round_update` runs ONCE on the joined sums — scalars
        (step, dangling_sum) and the vote come from that single call,
        exactly like the serial round."""
        pl = self._pipeline
        rank = state["rank"]
        dt = rank.dtype
        zero = jnp.asarray(0, dt)
        full = pl.splice(ctx, rank, state, xbuf)
        bmask = state["pl_bmask"]
        cur_b = self.segment_reduce(
            jnp.where(state["pl_b_val"], full[state["pl_b_nbr"]], zero),
            state["pl_b_src"], frag.vp, "sum",
        ).astype(dt)
        st_b, _ = self.round_update(frag, state, cur_b)
        xbuf2 = pl.kickoff(
            ctx, jnp.where(bmask, st_b["rank"], rank), state
        )
        # ---- pipelined window: carry reads below are named in
        # parallel/pipeline.PIPELINE_WINDOW_READS (grape-lint R6) ----
        cur_i = self.segment_reduce(
            jnp.where(state["pl_i_val"], full[state["pl_i_nbr"]], zero),
            state["pl_i_src"], frag.vp, "sum",
        ).astype(dt)
        cur = jnp.where(bmask, cur_b, cur_i)
        st2, active = self.round_update(frag, state, cur)
        return st2, active, xbuf2

    # PageRank is a probability distribution: within each round the
    # stored form is rank/deg (dangling vertices hold the raw base), so
    # the conserved quantity is sum(deg>0 ? rank*deg : rank) == 1; the
    # final round multiplies the degree back in, making it sum(rank).
    # The tolerance absorbs f32 segment-sum error at RMAT-20 scale.
    mass_rtol = 1e-3

    def invariants(self, frag, state):
        from libgrape_lite_tpu.guard.invariants import (
            Invariant, finite, in_range,
        )

        mr = self.max_round
        rtol = self.mass_rtol
        personalized = self._personalized

        def mass_fn(dev, prev, cur):
            rank = cur["rank"]
            dt = rank.dtype
            deg = dev.out_degree.astype(dt)
            iter_mass = jnp.where(deg > 0, rank * deg, rank).sum()
            is_final = cur["step"] >= jnp.int32(mr)
            mass = jnp.where(is_final, rank.sum(), iter_mass)
            # PPR conserves the SEED mass (1 when the source resolves,
            # 0 for an absent seed) instead of the global unit mass
            target = (
                cur["seed"].sum() if personalized
                else jnp.asarray(1.0, dt)
            )
            err = jnp.abs(mass - target)
            return err <= jnp.asarray(rtol, dt), err

        out = [finite("rank"), in_range("rank", lo=0.0)]
        if mr > 0:  # a 0-round query never leaves the rank/deg form
            requires = (
                ("rank", "step", "seed") if personalized
                else ("rank", "step")
            )
            out.append(Invariant(
                "pagerank_mass", mass_fn, requires,
                f"total probability mass conserved within {rtol:g}",
            ))
        return out

    def finalize(self, frag, state):
        return np.asarray(state["rank"])
