"""LCC — local clustering coefficient (triangle counting).

Re-design of `examples/analytical_apps/lcc/lcc.h` (+ the SIMD set
intersection of `lcc_opt.h:26-41`): orient the (deduplicated) undirected
graph into a DAG by (degree, id) — u ∈ N+(v) iff deg(u) < deg(v) or
(deg equal and id(u) < id(v)) (`lcc.h` stage-1 neighbor filter) — then
every triangle has a unique apex v with v→u, v→w, u→w and each corner
earns +1 (`lcc.h:170-180`).  lcc(v) = 2·T(v) / (deg(v)·(deg(v)−1)) with
deg the raw adjacency degree (`lcc_context.h:52-68`).

TPU formulation (validated bit-exact vs `dataset/p2p-31-LCC`):

  * N+ / N− adjacency become *packed bitmaps* `[vp, N_pad/32] uint32`;
    set intersection = `bitwise_and` + `lax.population_count` — the VPU
    replaces the reference's STTNI/AVX-512 intersection kernels.
  * Remote bitmap rows travel by ring `ppermute` (the classic systolic
    distributed-join): at step s each shard holds shard (fid+s)'s N+
    block and processes exactly the edges whose head lives there.  This
    replaces the reference's per-vertex neighbor-list messages
    (`lcc.h` stage 1→2) with dense ICI traffic.
  * Per-corner credits: apex and middle credit locally per edge
    (v, u ∈ edge), the far-end credit accumulates into a pid-indexed
    vector folded by `psum` at the end.

Three popcount passes per edge total — O(E · N/32) word-ops, chunked to
bound HBM working set (GRAPE_LCC_CHUNK, default 4096).

r11 (ops/spgemm_pack.py): the promised successor landed as the tiled
masked-SpGEMM backend — GRAPE_LCC_BACKEND = intersect | spgemm | auto
routes the triangle-credit pass through pruned [128, 128] bitmap-tile
products reduced on the MXU instead of the O(N/32)-per-row popcount
sweep; `auto` prices both static ledgers at the pack cost model's
rates and records the decision (declines too — never silent) in
spgemm_pack.SPGEMM_STATS.  Per-vertex triangle counts are
integer-identical across backends (same 3-credit algebra over the same
oriented dedup edge set), so the lcc output is BIT-exact either way:
both backends feed the same `_emit` tail.
"""

from __future__ import annotations

import os

import jax.numpy as jnp
import numpy as np
from jax import lax

from libgrape_lite_tpu.app.base import ParallelAppBase, StepContext
from libgrape_lite_tpu.ops.pallas_kernels import row_and_popcount
from libgrape_lite_tpu.parallel.comm_spec import FRAG_AXIS
from libgrape_lite_tpu.utils.types import LoadStrategy, MessageStrategy

_CHUNK_DEFAULT = 4096


def _lcc_chunk() -> int:
    """Edge-chunk size of the intersect kernel's HBM working set —
    env-tunable (GRAPE_LCC_CHUNK) instead of the r1 baked constant
    (grape-lint R1's baked-constant class: a module literal consumed
    by a traced body is invisible to every cache key; as an app
    attribute it rides `trace_key` and the intersect op model)."""
    spec = os.environ.get("GRAPE_LCC_CHUNK", "")
    if not spec:
        return _CHUNK_DEFAULT
    try:
        v = int(spec)
    except ValueError:
        raise ValueError(
            f"GRAPE_LCC_CHUNK={spec!r}: expected a positive int"
        ) from None
    if v <= 0:
        raise ValueError(f"GRAPE_LCC_CHUNK={v} must be positive")
    return v


class LCC(ParallelAppBase):
    load_strategy = LoadStrategy.kOnlyOut
    message_strategy = MessageStrategy.kAlongOutgoingEdgeToOuterVertex
    result_format = "float"
    replicated_keys = frozenset()

    def init_state(self, frag, degree_threshold: int = 0, **_):
        from libgrape_lite_tpu.ops.spgemm_pack import (
            resolve_lcc_backend,
            resolve_spgemm_dispatch,
        )

        # degree_threshold > 0 skips hub vertices' neighbor lists — the
        # reference's cost cap (`lcc.h:234-243` filterByDegree, flag
        # default INT_MAX i.e. disabled; 0 here means disabled too)
        self.degree_threshold = int(degree_threshold)
        self.lcc_chunk = _lcc_chunk()
        state = {
            "lcc": np.zeros((frag.fnum, frag.vp), dtype=np.float64),
        }
        # backend resolution (GRAPE_LCC_BACKEND; decisions + declines
        # recorded in SPGEMM_STATS).  `lcc_backend` and the plan uid
        # are primitive attrs, so they ride trace_key: the two
        # backends never share a compiled runner
        self.lcc_backend = resolve_lcc_backend(
            type(self).__name__, frag,
            degree_threshold=self.degree_threshold,
            chunk=self.lcc_chunk,
        )
        self._spgemm = None
        self._spgemm_uid = -1
        self.ephemeral_keys = frozenset()
        if self.lcc_backend == "spgemm":
            self._spgemm = resolve_spgemm_dispatch(
                frag, degree_threshold=self.degree_threshold
            )
            self._spgemm_uid = self._spgemm.uid
            entries = self._spgemm.state_entries()
            state.update(entries)
            self.ephemeral_keys = frozenset(entries)
        return state

    # ---- helpers -------------------------------------------------------

    @staticmethod
    def _dedup_mask(csr):
        """Adjacent-duplicate mask; build_csr sorts by (src, nbr) so
        multi-edges are adjacent."""
        s, n = csr.edge_src, csr.edge_nbr
        dup = jnp.zeros_like(csr.edge_mask).at[1:].set(
            jnp.logical_and(s[1:] == s[:-1], n[1:] == n[:-1])
        )
        return jnp.logical_and(csr.edge_mask, ~dup)

    @staticmethod
    def _build_bitmap(rows, cols, keep, vp, words):
        """Packed adjacency bitmap — delegates to the shared
        utils/bitset.pack_bits (kept (row, col) pairs must be unique so
        bit-add == bit-or)."""
        from libgrape_lite_tpu.utils.bitset import pack_bits

        return pack_bits(cols, keep, vp, rows, words * 32)

    # ---- the staged computation ---------------------------------------

    def peval(self, ctx: StepContext, frag, state):
        """Backend-dispatched triangle credits, one shared emit tail:
        both backends produce the SAME int32 per-vertex triangle
        counts (pinned by tests/test_spgemm.py), so every downstream
        bit is backend-independent by construction."""
        if getattr(self, "lcc_backend", "intersect") == "spgemm":
            tri = self._tri_spgemm(ctx, frag, state)
        else:
            tri = self._tri_intersect(ctx, frag, state)
        return self._emit(ctx, frag, state, tri)

    def _tri_spgemm(self, ctx: StepContext, frag, state):
        """Per-vertex triangle counts via the tiled masked SpGEMM
        (ops/spgemm_pack.py): per-shard pruned tile products credit
        apex/middle/far into a pid-indexed vector, folded by one psum
        — the same credit exchange as the intersect ring."""
        vp, fnum = frag.vp, frag.fnum
        my_fid = lax.axis_index(FRAG_AXIS).astype(jnp.int32)
        cred = self._spgemm.credits(state)
        cred_all = ctx.sum(cred)
        return lax.dynamic_slice(cred_all, (my_fid * vp,), (vp,))

    def _emit(self, ctx: StepContext, frag, state, tri):
        deg_local = frag.out_degree
        deg64 = deg_local.astype(
            jnp.float64 if state["lcc"].dtype == jnp.float64
            else jnp.float32
        )
        denom = deg64 * (deg64 - 1)
        lcc = jnp.where(
            jnp.logical_and(frag.inner_mask, deg_local >= 2),
            2.0 * tri.astype(denom.dtype) / jnp.maximum(denom, 1),
            0.0,
        )
        return dict(state, lcc=lcc.astype(state["lcc"].dtype)), jnp.int32(0)

    def _tri_intersect(self, ctx: StepContext, frag, state):
        vp, fnum = frag.vp, frag.fnum
        n_pad = vp * fnum
        words = (n_pad + 31) // 32
        my_fid = lax.axis_index(FRAG_AXIS).astype(jnp.int32)
        base_pid = my_fid * vp

        deg_local = frag.out_degree  # includes multiplicity (lcc_context degree)
        deg_full = ctx.gather_state(deg_local)

        oe, ie = frag.oe, frag.ie

        def oriented(csr, toward_nbr: bool):
            """toward_nbr=True keeps edges oriented row→nbr
            (deg[nbr] < deg[row] or tie with nbr_pid < row_pid);
            False keeps nbr→row."""
            row_pid = base_pid + jnp.minimum(csr.edge_src, vp - 1)
            d_row = deg_local[jnp.minimum(csr.edge_src, vp - 1)]
            d_nbr = deg_full[csr.edge_nbr]
            if toward_nbr:
                k = jnp.logical_or(
                    d_nbr < d_row,
                    jnp.logical_and(d_nbr == d_row, csr.edge_nbr < row_pid),
                )
            else:
                k = jnp.logical_or(
                    d_row < d_nbr,
                    jnp.logical_and(d_nbr == d_row, row_pid < csr.edge_nbr),
                )
            thr = getattr(self, "degree_threshold", 0)
            if thr > 0:
                # a filtered vertex contributes no N+ list (lcc.h:98,164):
                # drop rows of filtered list owners — the list owner is
                # the row vertex when orienting row→nbr, the nbr otherwise
                owner_deg = d_row if toward_nbr else d_nbr
                k = jnp.logical_and(k, owner_deg <= thr)
            return jnp.logical_and(self._dedup_mask(csr), k)

        keep_oe = oriented(oe, True)   # v(row) → u(nbr):  u ∈ N+(v)
        keep_ie = oriented(ie, False)  # u(nbr) → w(row):  u ∈ N−(w)

        bplus = self._build_bitmap(oe.edge_src, oe.edge_nbr, keep_oe, vp, words)
        bminus = self._build_bitmap(ie.edge_src, ie.edge_nbr, keep_ie, vp, words)

        ep_oe = oe.edge_src.shape[0]
        ep_ie = ie.edge_src.shape[0]
        chunk = getattr(self, "lcc_chunk", _CHUNK_DEFAULT)
        c_oe = min(chunk, ep_oe)
        c_ie = min(chunk, ep_ie)
        tri = jnp.zeros((vp,), dtype=jnp.int32)
        cred = jnp.zeros((n_pad,), dtype=jnp.int32)

        nbr_fid_oe = (oe.edge_nbr // vp).astype(jnp.int32)
        nbr_lid_oe = (oe.edge_nbr % vp).astype(jnp.int32)
        nbr_fid_ie = (ie.edge_nbr // vp).astype(jnp.int32)
        nbr_lid_ie = (ie.edge_nbr % vp).astype(jnp.int32)

        def edge_chunks(ep, c):
            return max(1, -(-ep // c))

        def intersect_pass(carry_tri, carry_cred, brot, cur_fid):
            """One ring step: process oe edges (apex+middle credits) and
            ie edges (far-end credit) whose nbr lives on `cur_fid`."""

            def oe_body(i, acc):
                t, c = acc
                start = jnp.minimum(i * c_oe, ep_oe - c_oe)
                pos = start + jnp.arange(c_oe, dtype=jnp.int32)
                fresh = pos >= i * c_oe  # exclude clamped overlap
                srcs = lax.dynamic_slice(oe.edge_src, (start,), (c_oe,))
                nfid = lax.dynamic_slice(nbr_fid_oe, (start,), (c_oe,))
                nlid = lax.dynamic_slice(nbr_lid_oe, (start,), (c_oe,))
                kept = lax.dynamic_slice(keep_oe, (start,), (c_oe,))
                sel = jnp.logical_and(jnp.logical_and(kept, fresh), nfid == cur_fid)
                rows_v = bplus[jnp.minimum(srcs, vp - 1)]
                rows_u = brot[nlid]
                cnt = row_and_popcount(rows_v, rows_u)
                cnt = jnp.where(sel, cnt, 0)
                t = t.at[jnp.where(sel, srcs, vp - 1)].add(
                    jnp.where(sel, cnt, 0)
                )
                u_pid = cur_fid * vp + nlid
                c = c.at[jnp.where(sel, u_pid, 0)].add(jnp.where(sel, cnt, 0))
                return t, c

            def ie_body(i, t):
                start = jnp.minimum(i * c_ie, ep_ie - c_ie)
                pos = start + jnp.arange(c_ie, dtype=jnp.int32)
                fresh = pos >= i * c_ie
                srcs = lax.dynamic_slice(ie.edge_src, (start,), (c_ie,))
                nfid = lax.dynamic_slice(nbr_fid_ie, (start,), (c_ie,))
                nlid = lax.dynamic_slice(nbr_lid_ie, (start,), (c_ie,))
                kept = lax.dynamic_slice(keep_ie, (start,), (c_ie,))
                sel = jnp.logical_and(jnp.logical_and(kept, fresh), nfid == cur_fid)
                rows_w = bminus[jnp.minimum(srcs, vp - 1)]
                rows_v = brot[nlid]
                cnt = row_and_popcount(rows_w, rows_v)
                t = t.at[jnp.where(sel, srcs, vp - 1)].add(
                    jnp.where(sel, cnt, 0)
                )
                return t

            t = lax.fori_loop(
                0, edge_chunks(ep_oe, c_oe), oe_body, (carry_tri, carry_cred)
            )
            carry_tri, carry_cred = t
            carry_tri = lax.fori_loop(
                0, edge_chunks(ep_ie, c_ie), ie_body, carry_tri
            )
            return carry_tri, carry_cred

        if fnum == 1:
            tri, cred = intersect_pass(tri, cred, bplus, jnp.int32(0))
        else:
            perm = [(i, (i - 1) % fnum) for i in range(fnum)]  # shift left

            def ring_body(s, carry):
                t, c, brot = carry
                cur_fid = (my_fid + s) % fnum
                t, c = intersect_pass(t, c, brot, cur_fid)
                brot = lax.ppermute(brot, FRAG_AXIS, perm)
                return t, c, brot

            tri, cred, _ = lax.fori_loop(0, fnum, ring_body, (tri, cred, bplus))

        cred_all = ctx.sum(cred)
        return tri + lax.dynamic_slice(cred_all, (base_pid,), (vp,))

    def inceval(self, ctx: StepContext, frag, state):
        return state, jnp.int32(0)

    def invariants(self, frag, state):
        # a clustering coefficient is a triangle fraction: [0, 1] on a
        # deduplicated simple graph (in_range also rejects NaN)
        from libgrape_lite_tpu.guard.invariants import in_range

        return [in_range("lcc", lo=0.0, hi=1.0)]

    def finalize(self, frag, state):
        return np.asarray(state["lcc"])
