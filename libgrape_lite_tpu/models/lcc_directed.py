"""LCCDirected — clustering coefficient for directed graphs.

Re-design of `examples/analytical_apps/lcc/lcc_directed.h` (+ context
`lcc_directed_context.h:52-63`): the neighborhood N(v) is the
*deduplicated* union of in- and out-neighbors (self-loops excluded);
tricnt counts every directed edge (u, w) with u, w ∈ N(v) — reciprocal
pairs count twice (the reference tracks per-pair direction multiplicity
as a uint8 weight); lcc = tricnt / (d·(d−1)) with d = |N(v)|.

TPU formulation: two packed bitmap families per shard — NB (undirected
dedup union) and OUT (dedup directed out-adjacency) — then for every
dedup pair (v, u ∈ N(v)):   T[v] += popcount(OUT[u] & NB[v]),
with OUT blocks ring-`ppermute`d through the mesh for remote rows,
exactly like the undirected LCC kernel (models/lcc.py).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
from jax import lax

from libgrape_lite_tpu.app.base import ParallelAppBase, StepContext
from libgrape_lite_tpu.ops.pallas_kernels import row_and_popcount
from libgrape_lite_tpu.parallel.comm_spec import FRAG_AXIS
from libgrape_lite_tpu.utils.types import LoadStrategy, MessageStrategy

_CHUNK = 4096


class LCCDirected(ParallelAppBase):
    load_strategy = LoadStrategy.kBothOutIn
    message_strategy = MessageStrategy.kAlongOutgoingEdgeToOuterVertex
    result_format = "float"

    def init_state(self, frag, degree_threshold: int = 0, **_):
        from libgrape_lite_tpu.ops.spgemm_pack import resolve_lcc_backend

        # GRAPE_LCC_BACKEND = spgemm/auto: directed tricnt weighs
        # reciprocal pairs twice — not the masked-SpGEMM credit
        # algebra; RECORDED decline, results stay intersect-parity
        resolve_lcc_backend(
            type(self).__name__, frag, supported=False,
            unsupported_reason="directed tricnt (direction-weighted "
            "pairs) has no spgemm lowering",
        )
        # hub cap like the undirected app; directed degree = out + in
        # with multiplicity (reference lcc.h:234-238)
        self.degree_threshold = int(degree_threshold)
        return {"lcc": np.zeros((frag.fnum, frag.vp), dtype=np.float64)}

    def peval(self, ctx: StepContext, frag, state):
        vp, fnum = frag.vp, frag.fnum
        n_pad = vp * fnum
        words = (n_pad + 31) // 32
        my_fid = lax.axis_index(FRAG_AXIS).astype(jnp.int32)
        base_pid = my_fid * vp

        oe, ie = frag.oe, frag.ie

        # union edge stream (v, u): rows + nbr pids from both CSRs,
        # lexsorted and adjacent-deduped; self-loops dropped
        src = jnp.concatenate([oe.edge_src, ie.edge_src])
        nbr = jnp.concatenate([oe.edge_nbr, ie.edge_nbr])
        msk = jnp.concatenate([oe.edge_mask, ie.edge_mask])
        row_pid = base_pid + jnp.minimum(src, vp - 1)
        msk = jnp.logical_and(msk, nbr != row_pid)
        order = jnp.lexsort((nbr, src, ~msk))  # valid entries first
        src, nbr, msk = src[order], nbr[order], msk[order]
        dup = jnp.zeros_like(msk).at[1:].set(
            jnp.logical_and(src[1:] == src[:-1], nbr[1:] == nbr[:-1])
        )
        keep_nb = jnp.logical_and(msk, ~dup)

        thr = getattr(self, "degree_threshold", 0)
        deg_dir = frag.out_degree + frag.in_degree  # raw, with multiplicity

        # OUT: dedup directed out-adjacency (self-loops dropped)
        o_row_pid = base_pid + jnp.minimum(oe.edge_src, vp - 1)
        o_msk = jnp.logical_and(oe.edge_mask, oe.edge_nbr != o_row_pid)
        o_dup = jnp.zeros_like(o_msk).at[1:].set(
            jnp.logical_and(
                oe.edge_src[1:] == oe.edge_src[:-1],
                oe.edge_nbr[1:] == oe.edge_nbr[:-1],
            )
        )
        keep_out = jnp.logical_and(o_msk, ~o_dup)
        if thr > 0:
            # a filtered vertex contributes no neighbor list: drop its
            # NB row (apex) and its OUT row (middle), lcc.h:98,164
            keep_nb = jnp.logical_and(
                keep_nb, deg_dir[jnp.minimum(src, vp - 1)] <= thr
            )
            keep_out = jnp.logical_and(
                keep_out, deg_dir[jnp.minimum(oe.edge_src, vp - 1)] <= thr
            )

        from libgrape_lite_tpu.models.lcc import LCC

        nb_bm = LCC._build_bitmap(src, nbr, keep_nb, vp, words)
        out_bm = LCC._build_bitmap(oe.edge_src, oe.edge_nbr, keep_out, vp, words)

        e_u = src.shape[0]
        c_u = min(_CHUNK, e_u)
        n_chunks = max(1, -(-e_u // c_u))
        nbr_fid = (nbr // vp).astype(jnp.int32)
        nbr_lid = (nbr % vp).astype(jnp.int32)

        tri = jnp.zeros((vp,), dtype=jnp.int32)

        def pass_for(out_rot, cur_fid, tri):
            def body(i, t):
                start = jnp.minimum(i * c_u, e_u - c_u)
                pos = start + jnp.arange(c_u, dtype=jnp.int32)
                fresh = pos >= i * c_u
                s = lax.dynamic_slice(src, (start,), (c_u,))
                nf = lax.dynamic_slice(nbr_fid, (start,), (c_u,))
                nl = lax.dynamic_slice(nbr_lid, (start,), (c_u,))
                kp = lax.dynamic_slice(keep_nb, (start,), (c_u,))
                sel = jnp.logical_and(jnp.logical_and(kp, fresh), nf == cur_fid)
                rows_nb = nb_bm[jnp.minimum(s, vp - 1)]
                rows_out = out_rot[nl]
                cnt = row_and_popcount(rows_nb, rows_out)
                return t.at[jnp.where(sel, s, vp - 1)].add(
                    jnp.where(sel, cnt, jnp.int32(0))
                )

            return lax.fori_loop(0, n_chunks, body, tri)

        if fnum == 1:
            tri = pass_for(out_bm, jnp.int32(0), tri)
        else:
            perm = [(i, (i - 1) % fnum) for i in range(fnum)]

            def ring_body(s, carry):
                t, rot = carry
                cur_fid = (my_fid + s) % fnum
                t = pass_for(rot, cur_fid, t)
                rot = lax.ppermute(rot, FRAG_AXIS, perm)
                return t, rot

            tri, _ = lax.fori_loop(0, fnum, ring_body, (tri, out_bm))

        from libgrape_lite_tpu.utils.bitset import popcount_rows

        deg = popcount_rows(nb_bm).astype(jnp.int32)
        dt = state["lcc"].dtype
        denom = (deg * (deg - 1)).astype(dt)
        lcc = jnp.where(
            jnp.logical_and(frag.inner_mask, deg >= 2),
            tri.astype(dt) / jnp.maximum(denom, 1),
            jnp.asarray(0, dt),
        )
        return {"lcc": lcc.astype(state["lcc"].dtype)}, jnp.int32(0)

    def inceval(self, ctx, frag, state):
        return state, jnp.int32(0)

    def finalize(self, frag, state):
        return np.asarray(state["lcc"])
