"""WCC — weakly connected components.

Re-design of `examples/analytical_apps/wcc/wcc.h` (min-gid label
propagation over both edge directions, atomic_min + outer-vertex sync).

TPU formulation: component ids are pids (bit-identical to the
reference's gids given the power-of-two padding); each superstep pulls
`min` over in- and out-neighborhoods via gather + `segment_min`.  For
undirected graphs the two CSRs hold the same symmetrised multiset, so a
single pull suffices.  Output labels are canonicalised to the component
representative's *oid* on the host (the LDBC WCC check is
partition-isomorphism, `misc/wcc_check.cc`).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from libgrape_lite_tpu.app.base import ParallelAppBase, StepContext
from libgrape_lite_tpu.utils.types import LoadStrategy, MessageStrategy


class WCC(ParallelAppBase):
    load_strategy = LoadStrategy.kBothOutIn
    message_strategy = MessageStrategy.kSyncOnOuterVertex
    result_format = "int"

    def init_state(self, frag, **_):
        vp = frag.vp
        pids = np.arange(frag.fnum * vp, dtype=np.int32).reshape(frag.fnum, vp)
        # padded rows get a big sentinel so they never win a min
        comp = np.where(frag.host_inner_mask(), pids, np.iinfo(np.int32).max)
        return {"comp": comp.astype(np.int32)}

    def peval(self, ctx: StepContext, frag, state):
        return state, jnp.int32(1)

    def _pull(self, ctx, frag, comp, csr):
        full = ctx.gather_state(comp)
        big = jnp.int32(np.iinfo(np.int32).max)
        cand = jnp.where(csr.edge_mask, full[csr.edge_nbr], big)
        return self.segment_reduce(cand, csr.edge_src, frag.vp, "min")

    def _post_pull(self, ctx, frag, new):
        """Hook between the neighbor pull and the change count —
        WCCOpt inserts pointer jumping here."""
        return new

    def inceval(self, ctx: StepContext, frag, state):
        comp = state["comp"]
        new = jnp.minimum(comp, self._pull(ctx, frag, comp, frag.ie))
        if frag.directed:
            new = jnp.minimum(new, self._pull(ctx, frag, new, frag.oe))
        new = self._post_pull(ctx, frag, new)
        changed = jnp.logical_and(new < comp, frag.inner_mask)
        active = ctx.sum(changed.sum().astype(jnp.int32))
        return {"comp": new}, active

    def finalize(self, frag, state):
        comp = np.asarray(state["comp"]).astype(np.int64)
        # canonicalise: component id -> oid of representative pid
        # (oids may be str objects for --string_id graphs)
        flat = comp.reshape(-1)
        reps = np.unique(flat[flat != np.iinfo(np.int32).max])
        rep_oids = frag.pid_to_oid(reps)
        lut = {int(r): o for r, o in zip(reps, np.asarray(rep_oids).tolist())}
        otype = object if frag.is_string_keyed() else np.int64
        return np.vectorize(lambda c: lut.get(int(c), -1), otypes=[otype])(comp)
