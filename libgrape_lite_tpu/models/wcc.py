"""WCC — weakly connected components.

Re-design of `examples/analytical_apps/wcc/wcc.h` (min-gid label
propagation over both edge directions, atomic_min + outer-vertex sync).

TPU formulation: component ids are pids (bit-identical to the
reference's gids given the power-of-two padding); each superstep pulls
`min` over in- and out-neighborhoods via gather + `segment_min`.  For
undirected graphs the two CSRs hold the same symmetrised multiset, so a
single pull suffices.  Output labels are canonicalised to the component
representative's *oid* on the host (the LDBC WCC check is
partition-isomorphism, `misc/wcc_check.cc`).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from libgrape_lite_tpu.app.base import ParallelAppBase, StepContext
from libgrape_lite_tpu.utils.types import LoadStrategy, MessageStrategy


class WCC(ParallelAppBase):
    load_strategy = LoadStrategy.kBothOutIn
    message_strategy = MessageStrategy.kSyncOnOuterVertex
    result_format = "int"
    # dyn/: min-gid propagation is a tropical fold — additive deltas
    # merge exactly, and the previous labeling seeds incremental
    # IncEval (labels remapped across repacks via inc_value_map)
    dyn_overlay_support = True
    inc_mode = "monotone-min"
    inc_seed_keys = {"comp": "min"}
    # r9: min-gid propagation pipelines in BOTH graph forms.  The
    # undirected round is the canonical single-pull split; the
    # directed round runs the two-kickoff double-pull form — the oe
    # exchange is kicked from the ie BOUNDARY fold (complete at every
    # remotely-read row under the joint ie+oe boundary mask) and
    # rides under the ie INTERIOR fold, then the next round's ie
    # exchange kicks from the oe boundary fold symmetrically
    pipeline_state_key = "comp"

    def init_state(self, frag, **_):
        import os

        vp = frag.vp
        pids = np.arange(frag.fnum * vp, dtype=np.int32).reshape(frag.fnum, vp)
        # padded rows get a big sentinel so they never win a min
        comp = np.where(frag.host_inner_mask(), pids, np.iinfo(np.int32).max)
        state = {"comp": comp.astype(np.int32)}
        eph_entries = {}
        # mirror-compressed exchange (GRAPE_EXCHANGE=mirror), per pull
        # direction
        from libgrape_lite_tpu.parallel.mirror import resolve_mirror_plan

        self._mx_ie = self._mx_oe = None
        # dyn/ overlay (see SSSP.init_state): pid-addressed side
        # arrays for each pull direction, mirror compaction off
        self._dyn = getattr(frag, "dyn_overlay", None) is not None
        if self._dyn:
            from libgrape_lite_tpu.dyn.ingest import overlay_state_entries

            eph_entries.update(
                overlay_state_entries(frag, "ie", None, "dyn_ie_")
            )
            if frag.directed:
                eph_entries.update(
                    overlay_state_entries(frag, "oe", None, "dyn_oe_")
                )
        else:
            self._mx_ie = resolve_mirror_plan(frag, "ie")
        if self._mx_ie is not None:
            eph_entries.update(self._mx_ie.state_entries("mx_ie_"))
            if frag.directed:
                self._mx_oe = resolve_mirror_plan(frag, "oe")
                if self._mx_oe is not None:
                    eph_entries.update(self._mx_oe.state_entries("mx_oe_"))
        self._mx_uid = self._mx_ie.uid if self._mx_ie is not None else -1
        # pack-gather min pull (GRAPE_SPMV=pack): the label space must
        # stay exactly representable in f32 (labels are pids < 2^24)
        self._pack_ie = self._pack_oe = None
        if os.environ.get("GRAPE_SPMV") == "pack":
            from libgrape_lite_tpu.ops.spmv_pack import (
                resolve_pack_dispatch,
                warn_pack_ineligible,
            )

            if frag.fnum * vp > (1 << 24):
                warn_pack_ineligible(
                    "WCC", "pid label space exceeds exact f32 range (2^24)"
                )
            else:
                ie = resolve_pack_dispatch(frag, direction="ie",
                                           prefix="pk_ie_",
                                           mirror=self._mx_ie)
                oe = (
                    resolve_pack_dispatch(frag, direction="oe",
                                          prefix="pk_oe_",
                                          mirror=self._mx_oe)
                    if frag.directed else None
                )
                if ie is None or (frag.directed and oe is None):
                    warn_pack_ineligible("WCC", "no pack plan buildable")
                else:
                    self._pack_ie, self._pack_oe = ie, oe
                    eph_entries.update(ie.state_entries())
                    if oe is not None:
                        eph_entries.update(oe.state_entries())
        # superstep pipelining (r9): undirected single-pull split, or
        # the directed two-kickoff double-pull form (leg 2 = oe)
        self._pipeline = None
        if not self._dyn:
            from libgrape_lite_tpu.parallel.pipeline import resolve_pipeline

            self._pipeline = resolve_pipeline(
                frag, app_name="WCC", key="comp", direction="ie",
                mirror=self._mx_ie, mx_prefix="mx_ie_",
                pack=self._pack_ie, fold="min", with_weights=False,
                direction2="oe" if frag.directed else None,
                mirror2=self._mx_oe if frag.directed else None,
                eligible=(type(self)._post_pull is WCC._post_pull),
                reason="_post_pull overrides (WCCOpt pointer jumping) "
                       "gather the folded labels again — a dependent "
                       "third exchange the split cannot hide",
            )
            if self._pipeline is not None:
                eph_entries.update(self._pipeline.host_entries)
        self._pipeline_uid = (
            self._pipeline.uid if self._pipeline is not None else -1
        )
        if eph_entries:
            state.update(eph_entries)
            self.ephemeral_keys = frozenset(eph_entries)
        self._pack_uid = (
            self._pack_ie.uid if self._pack_ie is not None else -1
        )
        return state

    def peval(self, ctx: StepContext, frag, state):
        return state, jnp.int32(1)

    def _pull(self, ctx, frag, comp, csr, pack=None, state=None,
              mx=None, mx_prefix="mx_ie_", dyn_prefix=None):
        big = jnp.int32(np.iinfo(np.int32).max)
        if mx is not None:
            full = ctx.exchange_mirrors(comp, state[mx_prefix + "send"])
            nbr = state[mx_prefix + "nbr"]
        else:
            full = ctx.gather_state(comp)
            nbr = csr.edge_nbr
        if pack is not None:
            # tropical min over the static pack routes: labels travel
            # as exact f32 ints; rows with no edges come back +inf
            red = pack.reduce(full.astype(jnp.float32), state, "min")
            red = jnp.where(
                jnp.isfinite(red), red.astype(jnp.int32), big
            )
        else:
            cand = jnp.where(csr.edge_mask, full[nbr], big)
            red = self.segment_reduce(cand, csr.edge_src, frag.vp, "min")
        if dyn_prefix is not None and dyn_prefix + "nbr" in state:
            # staged delta edges (dyn/): extra label candidates merged
            # at the fold; `full` is pid-addressed in overlay mode
            # (init_state disables mirror compaction)
            dcand = jnp.where(
                state[dyn_prefix + "mask"],
                full[state[dyn_prefix + "nbr"]], big,
            )
            red = self.dyn_min_fold(red, state, frag.vp, dyn_prefix,
                                    dcand)
        return red

    def _post_pull(self, ctx, frag, new):
        """Hook between the neighbor pull and the change count —
        WCCOpt inserts pointer jumping here."""
        return new

    def inceval(self, ctx: StepContext, frag, state):
        comp = state["comp"]
        new = jnp.minimum(
            comp,
            self._pull(ctx, frag, comp, frag.ie, self._pack_ie, state,
                       self._mx_ie, "mx_ie_", dyn_prefix="dyn_ie_"),
        )
        if frag.directed:
            new = jnp.minimum(
                new,
                self._pull(ctx, frag, new, frag.oe, self._pack_oe, state,
                           self._mx_oe, "mx_oe_", dyn_prefix="dyn_oe_"),
            )
        new = self._post_pull(ctx, frag, new)
        changed = jnp.logical_and(new < comp, frag.inner_mask)
        active = ctx.sum(changed.sum().astype(jnp.int32))
        return {"comp": new}, active

    def inceval_pipelined(self, ctx: StepContext, frag, state, xbuf):
        """Double-buffered round (parallel/pipeline.py; see SSSP) for
        the undirected single-pull form: boundary label fold, exchange
        kickoff, interior fold under the in-flight collective, join —
        bit-identical (min-gid is any-order exact).  Directed graphs
        run the two-kickoff double-pull form instead."""
        pl = self._pipeline
        if pl.mode2 is not None:
            return self._inceval_pipelined_directed(ctx, frag, state,
                                                    xbuf)
        comp = state["comp"]
        big = jnp.int32(np.iinfo(np.int32).max)
        full = pl.splice(ctx, comp, state, xbuf)
        bmask = state["pl_bmask"]

        def pack_fold(dispatch):
            red = dispatch.reduce(full.astype(jnp.float32), state, "min")
            return jnp.where(
                jnp.isfinite(red), red.astype(jnp.int32), big
            )

        if pl.pack_b is not None:
            rel_b = pack_fold(pl.pack_b)
        else:
            cand_b = jnp.where(
                state["pl_b_val"], full[state["pl_b_nbr"]], big
            )
            rel_b = self.segment_reduce(
                cand_b, state["pl_b_src"], frag.vp, "min"
            )
        new_b = jnp.minimum(comp, rel_b)
        xbuf2 = pl.kickoff(ctx, jnp.where(bmask, new_b, comp), state)
        # ---- pipelined window: carry reads below are named in
        # parallel/pipeline.PIPELINE_WINDOW_READS (grape-lint R6) ----
        if pl.pack_i is not None:
            rel_i = pack_fold(pl.pack_i)
        else:
            cand_i = jnp.where(
                state["pl_i_val"], full[state["pl_i_nbr"]], big
            )
            rel_i = self.segment_reduce(
                cand_i, state["pl_i_src"], frag.vp, "min"
            )
        new_i = jnp.minimum(comp, rel_i)
        new = jnp.where(bmask, new_b, new_i)
        changed = jnp.logical_and(new < comp, frag.inner_mask)
        active = ctx.sum(changed.sum().astype(jnp.int32))
        return {"comp": new}, active, xbuf2

    def _inceval_pipelined_directed(self, ctx: StepContext, frag,
                                    state, xbuf):
        """Two-kickoff double-pull round for directed graphs.  The
        serial round's oe pull reads the ie-folded labels — a
        dependent second exchange.  It pipelines anyway because the
        joint ie+oe boundary mask makes the ie BOUNDARY fold complete
        at every remotely-read row: the oe exchange kicks right after
        it and hides under the ie INTERIOR fold; symmetrically, the
        NEXT round's ie exchange kicks from the oe boundary fold and
        hides under the oe interior fold.  Joins are min over disjoint
        row sets — bit-identical to the serial two-pull round."""
        pl = self._pipeline
        comp = state["comp"]
        big = jnp.int32(np.iinfo(np.int32).max)
        bmask = state["pl_bmask"]
        # leg 1 (ie): last round kicked this exchange; splice + fold
        # the boundary rows' edges first
        full1 = pl.splice(ctx, comp, state, xbuf)
        cand = jnp.where(
            state["pl_b_val"], full1[state["pl_b_nbr"]], big
        )
        rel1_b = self.segment_reduce(
            cand, state["pl_b_src"], frag.vp, "min"
        )
        new1_b = jnp.minimum(comp, rel1_b)
        x_oe = pl.kickoff(
            ctx, jnp.where(bmask, new1_b, comp), state, leg=2
        )
        # ---- pipelined window: carry reads below are named in
        # parallel/pipeline.PIPELINE_WINDOW_READS (grape-lint R6) ----
        cand = jnp.where(
            state["pl_i_val"], full1[state["pl_i_nbr"]], big
        )
        rel1_i = self.segment_reduce(
            cand, state["pl_i_src"], frag.vp, "min"
        )
        new1 = jnp.where(bmask, new1_b, jnp.minimum(comp, rel1_i))
        # leg 2 (oe): remote rows of full2 come from x_oe, current at
        # every remotely-read row (all boundary); local rows are live
        full2 = pl.splice(ctx, new1, state, x_oe, leg=2)
        cand = jnp.where(
            state["pl2_b_val"], full2[state["pl2_b_nbr"]], big
        )
        rel2_b = self.segment_reduce(
            cand, state["pl2_b_src"], frag.vp, "min"
        )
        new2_b = jnp.minimum(new1, rel2_b)
        xbuf2 = pl.kickoff(ctx, jnp.where(bmask, new2_b, new1), state)
        cand = jnp.where(
            state["pl2_i_val"], full2[state["pl2_i_nbr"]], big
        )
        rel2_i = self.segment_reduce(
            cand, state["pl2_i_src"], frag.vp, "min"
        )
        new = jnp.where(bmask, new2_b, jnp.minimum(new1, rel2_i))
        changed = jnp.logical_and(new < comp, frag.inner_mask)
        active = ctx.sum(changed.sum().astype(jnp.int32))
        return {"comp": new}, active, xbuf2

    def inc_value_map(self, key, values, old_frag, new_frag):
        """Component labels are PIDS, so a repack (which renumbers the
        pid space) must re-address the label VALUES, not just migrate
        rows: old representative pid -> its oid -> its new pid.  A
        representative absent from the new map (only possible for
        non-additive deltas, which never reach the seeded path) falls
        back to the sentinel — no information, the fresh init wins."""
        if old_frag is new_frag or key != "comp":
            return values
        sent = np.iinfo(np.int32).max
        flat = np.asarray(values).reshape(-1)
        valid = flat != sent
        if not valid.any():
            return values
        reps = np.unique(flat[valid])
        rep_oids = old_frag.pid_to_oid(reps)
        new_reps = new_frag.oid_to_pid(np.asarray(rep_oids))
        new_reps = np.where(new_reps < 0, sent, new_reps).astype(
            values.dtype
        )
        idx = np.searchsorted(reps, flat[valid])
        out = flat.copy()
        out[valid] = new_reps[idx]
        return out.reshape(np.asarray(values).shape)

    def invariants(self, frag, state):
        # min-gid propagation: labels are pids (or the pad sentinel)
        # and only ever shrink toward the component representative
        from libgrape_lite_tpu.guard.invariants import (
            in_range, monotone_non_increasing,
        )

        return [
            in_range("comp", lo=0, hi=np.iinfo(np.int32).max),
            monotone_non_increasing("comp"),
        ]

    def finalize(self, frag, state):
        comp = np.asarray(state["comp"]).astype(np.int64)
        # canonicalise: component id -> oid of representative pid
        # (oids may be str objects for --string_id graphs)
        flat = comp.reshape(-1)
        reps = np.unique(flat[flat != np.iinfo(np.int32).max])
        rep_oids = frag.pid_to_oid(reps)
        lut = {int(r): o for r, o in zip(reps, np.asarray(rep_oids).tolist())}
        otype = object if frag.is_string_keyed() else np.int64
        return np.vectorize(lambda c: lut.get(int(c), -1), otypes=[otype])(comp)
