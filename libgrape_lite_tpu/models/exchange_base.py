"""Shared machinery for message-exchange apps (sssp_msg / bfs_opt /
sssp_delta): per-fragment compiled-step caches keyed by capacity, and
the overflow-retry capacity protocol — grow on overflow, remember the
settled capacity per fragment so repeat queries skip the retry ladder
(the reference `EstimateMessageSize` priming,
`parallel_message_manager_opt.h`).

The host loops themselves stay in each app (plain Bellman-Ford vs
push/pull mode switching vs bucket advancement are genuinely different
round structures); what must never diverge — capacity planning and the
learned-capacity lifecycle — lives here.
"""

from __future__ import annotations

import weakref

import jax.numpy as jnp
import numpy as np

from libgrape_lite_tpu.app.base import AppBase
from libgrape_lite_tpu.ops.segment import segment_reduce
from libgrape_lite_tpu.parallel.message_manager import AllToAllMessageManager


def exchange_relax(oe, cand, valid, cap: int, fnum: int, vp: int, neutral):
    """Route per-edge candidates to their owners and min-reduce into
    [vp] rows — the shared push-relax step of sssp_msg / bfs_opt /
    sssp_delta.  `neutral` fills invalid receive slots (inf for
    distances, the int sentinel for levels).  Returns (relaxed [vp],
    overflow_vote)."""
    dest = (oe.edge_nbr // vp).astype(jnp.int32)
    lid = (oe.edge_nbr % vp).astype(jnp.int32)
    rl, rp, rv, ovf = AllToAllMessageManager.exchange(
        dest, lid, cand, valid, cap, fnum
    )
    relaxed = segment_reduce(
        jnp.where(rv, rp, neutral),
        jnp.where(rv, rl, jnp.int32(vp)),
        vp, "min", sorted_ids=False,
    )
    return relaxed, ovf


class ExchangeAppBase(AppBase):
    host_only = True  # data-dependent host loops (capacity retry, modes)
    host_guard = True  # the host loops run guard probes (see _round_hooks)

    @staticmethod
    def _dist_dtype(frag):
        """Distance dtype: the edge-weight dtype when it is a float,
        f32 otherwise (shared by every distance-carrying exchange app;
        BFSMsg overrides — levels never depend on edge data)."""
        dt = frag.host_oe[0].edge_w.dtype if frag.weighted else np.float32
        return dt if np.dtype(dt).kind == "f" else np.float32

    def __init__(self, initial_capacity: int | None = None):
        # None = derive from the graph at query time via
        # plan_initial_capacity (message_manager.py)
        self.initial_capacity = initial_capacity
        self.rounds = 0
        self.retries = 0  # overflow-driven capacity regrows
        self.final_capacity = initial_capacity or 1024
        # fragment -> {capacity: compiled step(s)}
        self._cache = weakref.WeakKeyDictionary()
        self._learned_cap = weakref.WeakKeyDictionary()

    def _initial_cap(self, frag) -> int:
        from libgrape_lite_tpu.parallel.message_manager import (
            plan_initial_capacity,
        )

        return plan_initial_capacity(
            frag, self.initial_capacity, self._learned_cap
        )

    def _save_cap(self, frag, cap: int) -> None:
        self.final_capacity = cap
        self._learned_cap[frag] = cap

    # ---- runtime invariants + host-loop guard probes (guard/) -----------

    def invariants(self, frag, state):
        """The exchange apps' distance state is tropical-min exactly
        like models/sssp.py: never negative (in_range(lo=0) rejects
        NaN too) and only ever improving; +inf is the legitimate
        unreached sentinel.  BFS variants inherit soundly — integer
        levels carried as floats obey the same algebra.  The monitor's
        `requires` filtering drops these for any subclass whose carry
        has no "dist" leaf."""
        from libgrape_lite_tpu.guard.invariants import (
            in_range, monotone_non_increasing,
        )

        return [
            in_range("dist", lo=0.0),
            monotone_non_increasing("dist"),
        ]

    def _round_hooks(self, frag, carry0: dict) -> "_HostRoundHooks":
        """Guard + fault-injection hooks for the data-dependent host
        loop: the Worker cannot chunk a host-driven loop, so the app
        itself probes at round boundaries (its consistent cuts).
        Armed by Worker.query(guard=...) via `_host_guard_cfg`, or by
        GRAPE_GUARD directly when host_compute is called standalone."""
        return _HostRoundHooks(self, frag, carry0)


class _HostRoundHooks:
    """Per-query guard monitor + fault plan for a host-driven loop.

    `observe(carry, rounds, active)` mirrors the stepwise worker's
    per-round order exactly: injected corruption lands FIRST (so
    detection is same-round), then the invariant probe (warn logs,
    halt/rollback raise — rollback downgrades to halt, host loops have
    no checkpoint lineage), then the remaining fault hooks (kill@K).
    Returns the possibly-corrupted carry for the loop to adopt."""

    def __init__(self, app, frag, carry0: dict):
        from libgrape_lite_tpu.ft.faults import active_plan
        from libgrape_lite_tpu.guard.config import GuardConfig

        # the worker hands over THIS query's resolved config (a
        # disabled one is authoritative too: guard="off" must disarm
        # an env-armed GRAPE_GUARD); the env fallback only covers
        # standalone host_compute calls that bypass the Worker
        cfg = getattr(app, "_host_guard_cfg", None) or GuardConfig.resolve(
            None
        )
        self.frag = frag
        self.monitor = None
        if cfg.enabled:
            from libgrape_lite_tpu.guard.monitor import GuardMonitor

            self.monitor = GuardMonitor(app=app, frag=frag, config=cfg)
        app._host_guard_monitor = self.monitor
        plan = active_plan()
        self.plan = None if plan.is_noop() else plan
        self._prev = dict(carry0)

    @property
    def armed(self) -> bool:
        return self.monitor is not None or self.plan is not None

    def observe(self, carry: dict, rounds: int, active: int) -> dict:
        import jax.numpy as jnp

        if self.plan is not None:
            corrupted = self.plan.maybe_corrupt_carry(carry, rounds)
            if corrupted is not None:
                carry = {
                    **carry,
                    **{k: jnp.asarray(v) for k, v in corrupted.items()},
                }
        if (
            self.monitor is not None
            and active >= 0
            and self.monitor.due(rounds)
        ):
            breach = self.monitor.check(
                self._prev, carry, rounds, active
            )
            if breach is not None:
                # no snapshot lineage in a host loop: anything
                # surviving the warn policy halts (the monitor logs
                # the rollback downgrade itself)
                self.monitor.raise_breach(breach)
            self._prev = dict(carry)
        if self.plan is not None:
            self.plan.on_superstep(rounds, None)
        return carry
