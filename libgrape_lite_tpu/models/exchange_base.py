"""Shared machinery for message-exchange apps (sssp_msg / bfs_opt /
sssp_delta): per-fragment compiled-step caches keyed by capacity, and
the overflow-retry capacity protocol — grow on overflow, remember the
settled capacity per fragment so repeat queries skip the retry ladder
(the reference `EstimateMessageSize` priming,
`parallel_message_manager_opt.h`).

The host loops themselves stay in each app (plain Bellman-Ford vs
push/pull mode switching vs bucket advancement are genuinely different
round structures); what must never diverge — capacity planning and the
learned-capacity lifecycle — lives here.
"""

from __future__ import annotations

import weakref

import jax.numpy as jnp
import numpy as np

from libgrape_lite_tpu.app.base import AppBase
from libgrape_lite_tpu.ops.segment import segment_reduce
from libgrape_lite_tpu.parallel.message_manager import AllToAllMessageManager


def exchange_relax(oe, cand, valid, cap: int, fnum: int, vp: int, neutral):
    """Route per-edge candidates to their owners and min-reduce into
    [vp] rows — the shared push-relax step of sssp_msg / bfs_opt /
    sssp_delta.  `neutral` fills invalid receive slots (inf for
    distances, the int sentinel for levels).  Returns (relaxed [vp],
    overflow_vote)."""
    dest = (oe.edge_nbr // vp).astype(jnp.int32)
    lid = (oe.edge_nbr % vp).astype(jnp.int32)
    rl, rp, rv, ovf = AllToAllMessageManager.exchange(
        dest, lid, cand, valid, cap, fnum
    )
    relaxed = segment_reduce(
        jnp.where(rv, rp, neutral),
        jnp.where(rv, rl, jnp.int32(vp)),
        vp, "min", sorted_ids=False,
    )
    return relaxed, ovf


class ExchangeAppBase(AppBase):
    host_only = True  # data-dependent host loops (capacity retry, modes)

    @staticmethod
    def _dist_dtype(frag):
        """Distance dtype: the edge-weight dtype when it is a float,
        f32 otherwise (shared by every distance-carrying exchange app;
        BFSMsg overrides — levels never depend on edge data)."""
        dt = frag.host_oe[0].edge_w.dtype if frag.weighted else np.float32
        return dt if np.dtype(dt).kind == "f" else np.float32

    def __init__(self, initial_capacity: int | None = None):
        # None = derive from the graph at query time via
        # plan_initial_capacity (message_manager.py)
        self.initial_capacity = initial_capacity
        self.rounds = 0
        self.retries = 0  # overflow-driven capacity regrows
        self.final_capacity = initial_capacity or 1024
        # fragment -> {capacity: compiled step(s)}
        self._cache = weakref.WeakKeyDictionary()
        self._learned_cap = weakref.WeakKeyDictionary()

    def _initial_cap(self, frag) -> int:
        from libgrape_lite_tpu.parallel.message_manager import (
            plan_initial_capacity,
        )

        return plan_initial_capacity(
            frag, self.initial_capacity, self._learned_cap
        )

    def _save_cap(self, frag, cap: int) -> None:
        self.final_capacity = cap
        self._learned_cap[frag] = cap
