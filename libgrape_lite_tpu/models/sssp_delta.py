"""SSSPDelta — bucketed (delta-stepping style) SSSP.

Re-design of the reference's near/far worklist SSSP
(`examples/analytical_apps/cuda/sssp/sssp.h:70-124`, also `sssp_opt.h`):
instead of relaxing from EVERY improved vertex each round (plain
Bellman-Ford, `sssp_msg.py`), vertices with pending improvements are
bucketed by distance.  Only the *near* set — pending vertices with
dist < threshold — pushes; far improvements wait.  When the near set
drains, the threshold advances to the next non-empty bucket.  This
bounds wasted relaxations from provisional (still-shrinking) distances:
a vertex usually pushes once, with its (near-)final distance, instead
of once per improvement.

TPU formulation: the same message-tensor exchange as `sssp_msg.py`
(fixed-capacity all_to_all + overflow-vote retry); the threshold is a
traced scalar argument so bucket advances don't retrace.  The host
drives the loop — bucket advancement is data-dependent (it reads the
psum'd near/pending counts and the pmin of pending distances), exactly
the role of the reference's host-side worklist swap.

Unlike classic delta-stepping there is no light/heavy edge split: TPU
relaxes all out-edges of a near vertex in one edge-parallel sweep (the
split only pays when heavy edges can be deferred per-edge, which a
dense edge tensor cannot).  Convergence and exactness are unaffected —
the result equals Bellman-Ford's fixed point.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from libgrape_lite_tpu import compat
from libgrape_lite_tpu.app.base import resolve_source
from libgrape_lite_tpu.models.exchange_base import (
    ExchangeAppBase,
    exchange_relax,
)
from libgrape_lite_tpu.parallel.comm_spec import FRAG_AXIS
from libgrape_lite_tpu.utils.types import LoadStrategy, MessageStrategy


class SSSPDelta(ExchangeAppBase):
    load_strategy = LoadStrategy.kBothOutIn
    message_strategy = MessageStrategy.kAlongEdgeToOuterVertex
    result_format = "sssp_infinity"
    needs_edata = True

    def __init__(self, delta: float | None = None,
                 initial_capacity: int | None = None):
        super().__init__(initial_capacity)
        self.delta = delta  # None = mean edge weight at query time
        self.buckets = 0
        import weakref

        self._delta_cache = weakref.WeakKeyDictionary()

    def _resolve_delta(self, frag) -> float:
        if self.delta is not None and self.delta > 0:
            return float(self.delta)
        if frag in self._delta_cache:
            return self._delta_cache[frag]
        # heuristic: mean positive edge weight — buckets then hold
        # roughly one extra hop each (the reference tunes its near/far
        # boundary the same order of magnitude).  O(E) host scan, so the
        # result is cached per (immutable) fragment.
        w = frag.host_oe[0].edge_w
        if w is None:
            return 1.0
        total, count = 0.0, 0
        for c in frag.host_oe:
            if c.edge_w is not None and c.num_edges:
                total += float(c.edge_w[c.edge_mask].sum())
                count += int(c.num_edges)
        delta = max(total / count, 1e-6) if count else 1.0
        self._delta_cache[frag] = delta
        return delta

    def _step_for(self, frag, cap: int):
        per_frag = self._cache.setdefault(frag, {})
        if cap in per_frag:
            return per_frag[cap]

        comm_spec = frag.comm_spec
        fnum, vp = frag.fnum, frag.vp

        def step(frag_stacked, dist, pending, thr):
            lf = frag_stacked.local()
            d, pend = dist[0], pending[0]
            inf = jnp.asarray(jnp.inf, d.dtype)
            near = jnp.logical_and(pend, d < thr)
            oe = lf.oe
            src = jnp.minimum(oe.edge_src, vp - 1)
            valid = jnp.logical_and(oe.edge_mask, near[src])
            cand = d[src] + oe.edge_w
            relaxed, ovf = exchange_relax(oe, cand, valid, cap, fnum, vp, inf)
            new = jnp.minimum(d, relaxed)
            improved = jnp.logical_and(new < d, lf.inner_mask)
            pend2 = jnp.logical_or(jnp.logical_and(pend, ~near), improved)
            n_near = lax.psum(near.sum().astype(jnp.int32), FRAG_AXIS)
            n_pend = lax.psum(pend2.sum().astype(jnp.int32), FRAG_AXIS)
            min_pend = lax.pmin(
                jnp.where(pend2, new, inf).min(), FRAG_AXIS
            )
            return new[None], pend2[None], n_near, n_pend, min_pend, ovf

        fn = jax.jit(
            compat.shard_map(
                step, mesh=comm_spec.mesh,
                in_specs=(P(FRAG_AXIS), P(FRAG_AXIS), P(FRAG_AXIS), P()),
                out_specs=(P(FRAG_AXIS), P(FRAG_AXIS), P(), P(), P(), P()),
                check_vma=False,
            )
        )
        per_frag[cap] = fn
        return fn

    def host_compute(self, frag, source=0, max_rounds: int | None = None):
        fnum, vp = frag.fnum, frag.vp
        dt = np.dtype(self._dist_dtype(frag))
        dist0 = np.full((fnum, vp), np.inf, dtype=dt)
        pend0 = np.zeros((fnum, vp), dtype=bool)
        pid = resolve_source(frag, source, "SSSPDelta")
        if pid >= 0:
            dist0[pid // vp, pid % vp] = 0.0
            pend0[pid // vp, pid % vp] = True

        delta = self._resolve_delta(frag)
        dist = jnp.asarray(dist0)
        pending = jnp.asarray(pend0)
        thr = delta
        cap = self._initial_cap(frag)
        self.rounds = self.retries = self.buckets = 0
        limit = max_rounds if (max_rounds and max_rounds > 0) else None
        n_pend = 1 if pid >= 0 else 0
        # guard/ft hooks at round boundaries (bucket advances and
        # overflow retries don't complete a round — no probe there).
        # `pending` is part of the probed carry: a bucketed round can
        # legitimately leave dist unchanged while the near set drains,
        # and a dist-only digest would repeat — the watchdog would
        # mis-prove a cycle on healthy progress
        hooks = self._round_hooks(frag, {"dist": dist, "pending": pending})
        while n_pend > 0 and (limit is None or self.rounds < limit):
            out = self._step_for(frag, cap)(
                frag.dev, dist, pending, jnp.asarray(thr, dt)
            )
            new_dist, new_pend, n_near, n_pend_d, min_pend, ovf = out
            if int(ovf) > 0:
                cap *= 2
                self.retries += 1
                continue
            if int(n_near) == 0:
                # near set empty but work remains: advance to the bucket
                # holding the smallest pending distance (skipping empty
                # buckets — the reference's worklist swap).  The new
                # threshold must exceed min_pend IN THE DIST DTYPE:
                # with a tiny delta and large distances the bucket
                # arithmetic can round back to <= min_pend in float32,
                # which would spin forever — clamp to the next
                # representable value above min_pend.
                mp = float(min_pend)
                if not np.isfinite(mp):
                    break
                thr = (np.floor(mp / delta) + 1.0) * delta
                if float(np.asarray(thr, dt)) <= mp:
                    thr = float(np.nextafter(dt.type(mp), dt.type(np.inf)))
                self.buckets += 1
                continue
            dist, pending = new_dist, new_pend
            n_pend = int(n_pend_d)
            self.rounds += 1
            if hooks.armed:
                probed = hooks.observe(
                    {"dist": dist, "pending": pending},
                    self.rounds, n_pend,
                )
                dist, pending = probed["dist"], probed["pending"]
        self._save_cap(frag, cap)
        return {"dist": dist}

    def finalize(self, frag, state):
        return np.asarray(state["dist"])
