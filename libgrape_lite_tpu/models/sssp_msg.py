"""SSSPMsg — SSSP over the point-to-point message-tensor path.

The reference's SSSP (`sssp.h`) IS a message-path app: frontier
vertices push relaxations to owners through per-destination buffers.
The six LDBC apps here normally use the gather/push collectives (denser
but faster for their round structure); this variant runs the same
Bellman-Ford through `AllToAllMessageManager.exchange` — fixed-capacity
per-destination (lid, dist) tensors, one `all_to_all` per round, and
the overflow vote driving the reference's `EstimateMessageSize` role:
on overflow the round is discarded and re-run with doubled capacity
(static shapes can't grow mid-compile; re-execution is the TPU form of
buffer reallocation).

Results are identical to models/sssp.py; rounds are the push
Bellman-Ford rounds.  Message volume per round is O(frontier edges)
instead of O(E) — the win on high-diameter, low-frontier graphs.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from libgrape_lite_tpu import compat
from libgrape_lite_tpu.app.base import resolve_source
from libgrape_lite_tpu.models.exchange_base import (
    ExchangeAppBase,
    exchange_relax,
)
from libgrape_lite_tpu.parallel.comm_spec import FRAG_AXIS
from libgrape_lite_tpu.utils.types import LoadStrategy, MessageStrategy


class SSSPMsg(ExchangeAppBase):
    load_strategy = LoadStrategy.kBothOutIn
    message_strategy = MessageStrategy.kAlongEdgeToOuterVertex
    result_format = "sssp_infinity"
    needs_edata = True

    @staticmethod
    def _payload(dist_at_src, oe):
        """Per-edge message value: relaxation candidate."""
        return dist_at_src + oe.edge_w

    def host_compute(self, frag, source=0, max_rounds: int | None = None):
        comm_spec = frag.comm_spec
        fnum, vp = frag.fnum, frag.vp
        dist0 = np.full((fnum, vp), np.inf, dtype=self._dist_dtype(frag))
        changed0 = np.zeros((fnum, vp), dtype=bool)
        pid = resolve_source(frag, source, "SSSPMsg")
        if pid >= 0:
            dist0[pid // vp, pid % vp] = 0.0
            changed0[pid // vp, pid % vp] = True

        def round_for(cap: int):
            # persistent across queries (the Worker._runner_cache
            # pattern): WeakKeyDictionary keyed on the fragment, so a
            # recycled id can never alias and dead entries self-purge
            per_frag = self._cache.setdefault(frag, {})
            if cap in per_frag:
                return per_frag[cap]

            def step(frag_stacked, dist, changed):
                lf = frag_stacked.local()
                d, ch = dist[0], changed[0]
                oe = lf.oe
                src_d = d[jnp.minimum(oe.edge_src, vp - 1)]
                valid = jnp.logical_and(
                    oe.edge_mask, ch[jnp.minimum(oe.edge_src, vp - 1)]
                )
                cand = self._payload(src_d, oe)
                inf = jnp.asarray(jnp.inf, d.dtype)
                relaxed, ovf = exchange_relax(
                    oe, cand, valid, cap, fnum, vp, inf
                )
                new = jnp.minimum(d, relaxed)
                ch2 = jnp.logical_and(new < d, lf.inner_mask)
                active = lax.psum(ch2.sum().astype(jnp.int32), FRAG_AXIS)
                return new[None], ch2[None], active, ovf

            fn = jax.jit(
                compat.shard_map(
                    step, mesh=comm_spec.mesh,
                    in_specs=(P(FRAG_AXIS), P(FRAG_AXIS), P(FRAG_AXIS)),
                    out_specs=(P(FRAG_AXIS), P(FRAG_AXIS), P(), P()),
                    check_vma=False,
                )
            )
            per_frag[cap] = fn
            return fn

        dist = jnp.asarray(dist0)
        changed = jnp.asarray(changed0)
        cap = self._initial_cap(frag)
        self.rounds = 0
        self.retries = 0
        limit = max_rounds if (max_rounds and max_rounds > 0) else None
        active = 1
        # guard/ft hooks at round boundaries (the host loop's
        # consistent cuts): invariant probes + corrupt_carry drills
        hooks = self._round_hooks(frag, {"dist": dist})
        while active > 0 and (limit is None or self.rounds < limit):
            new_dist, new_changed, active_d, ovf = round_for(cap)(
                frag.dev, dist, changed
            )
            if int(ovf) > 0:
                # EstimateMessageSize's role: grow capacity, redo the
                # round with the SAME state (overflowed sends were lost)
                cap *= 2
                self.retries += 1
                continue
            dist, changed = new_dist, new_changed
            active = int(active_d)
            self.rounds += 1
            if hooks.armed:
                dist = hooks.observe(
                    {"dist": dist}, self.rounds, active
                )["dist"]
        self._save_cap(frag, cap)
        return {"dist": dist}

    def finalize(self, frag, state):
        return np.asarray(state["dist"])


class BFSMsg(SSSPMsg):
    """BFS levels over the message-tensor path (unit-weight Bellman-Ford
    = level-synchronous BFS; the reference `bfs.h` pushes exactly these
    frontier messages).  Distances are float levels internally; output
    formats as the reference's integer depths with the int64-max
    sentinel for unreachable vertices (`bfs_context.h:44`)."""

    result_format = "int"
    needs_edata = False

    @staticmethod
    def _dist_dtype(frag):
        # levels never depend on edge data (and must not inherit a
        # non-float edata dtype); f32 holds exact ints to 2^24 levels
        return np.float32

    @staticmethod
    def _payload(dist_at_src, oe):
        return dist_at_src + 1.0

    def finalize(self, frag, state):
        d = np.asarray(state["dist"])
        out = np.full(d.shape, np.iinfo(np.int64).max, dtype=np.int64)
        finite = np.isfinite(d)
        out[finite] = d[finite].astype(np.int64)
        return out
