"""The analytical-app library (reference `examples/analytical_apps`).

Each app is a PIE program: host-side `init_state` (PEval's setup),
traced `peval`/`inceval` supersteps, host-side `finalize` (Assemble).
"""

from libgrape_lite_tpu.models.pagerank import PageRank
from libgrape_lite_tpu.models.sssp import SSSP
from libgrape_lite_tpu.models.bfs import BFS
from libgrape_lite_tpu.models.wcc import WCC
from libgrape_lite_tpu.models.cdlp import CDLP
from libgrape_lite_tpu.models.lcc import LCC

APP_REGISTRY = {
    "pagerank": PageRank,
    "sssp": SSSP,
    "bfs": BFS,
    "wcc": WCC,
    "cdlp": CDLP,
    "lcc": LCC,
}
