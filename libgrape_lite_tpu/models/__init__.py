"""The analytical-app library (reference `examples/analytical_apps`).

Each app is a PIE program: host-side `init_state` (PEval's setup),
traced `peval`/`inceval` supersteps, host-side `finalize` (Assemble).

The registry mirrors the reference's app-variant names
(`run_app.h:214-296` dispatch).  Variants that differ only by CPU-side
execution strategy (e.g. SIMD/pooled-buffer builds of the same
algorithm) map to the same TPU implementation — XLA owns those
concerns; variants with genuinely different round/communication
structure have distinct classes: `*_auto` (SyncBuffer push),
`pagerank_push`, `bfs_opt` (direction-optimizing push/pull),
`sssp_opt`/`sssp_delta` (bucketed near/far worklists).
Exceptions: cdlp_auto / lcc_auto alias the base apps — their SyncBuffer
is a plain mirror-overwrite (no aggregate op), which the gather model
performs inherently, so push and pull coincide.
"""

from libgrape_lite_tpu.models.pagerank import PageRank
from libgrape_lite_tpu.models.sssp import SSSP
from libgrape_lite_tpu.models.bfs import BFS
from libgrape_lite_tpu.models.wcc import WCC
from libgrape_lite_tpu.models.cdlp import CDLP, CDLPOpt
from libgrape_lite_tpu.models.lcc import LCC
from libgrape_lite_tpu.models.bc import BC
from libgrape_lite_tpu.models.kcore import KCore
from libgrape_lite_tpu.models.core_decomposition import CoreDecomposition
from libgrape_lite_tpu.models.pagerank_local import PageRankLocal
from libgrape_lite_tpu.models.kclique import KClique
from libgrape_lite_tpu.models.pagerank_vc import (
    PageRankVC,
    PageRankVCReplicated,
)
from libgrape_lite_tpu.models.vc2d import BFSVC2D, SSSPVC2D, WCCVC2D
from libgrape_lite_tpu.models.lcc_directed import LCCDirected
from libgrape_lite_tpu.models.wcc_opt import WCCOpt
from libgrape_lite_tpu.models.sssp_msg import BFSMsg, SSSPMsg
from libgrape_lite_tpu.models.bfs_opt import BFSOpt
from libgrape_lite_tpu.models.sssp_delta import SSSPDelta
from libgrape_lite_tpu.models.lcc_beta import LCCBeta
from libgrape_lite_tpu.models.triangle_count import (
    CommonNeighbors,
    TriangleCount,
)
from libgrape_lite_tpu.models.khop import KHopNeighborhood
from libgrape_lite_tpu.models.auto_apps import (
    BFSAuto,
    PageRankAuto,
    SSSPAuto,
    WCCAuto,
)

APP_REGISTRY = {
    "sssp": SSSP,
    # probe-and-pick: host BFS hop probe chooses dense vs delta at
    # query time (models/sssp_select.py; near-far heuristic analogue)
    "sssp_select": SSSP,
    "sssp_auto": SSSPAuto,
    # sssp_opt = the reference's worklist-optimized variant
    # (cuda/sssp/sssp.h near/far): here the bucketed delta-stepping app
    "sssp_opt": SSSPDelta,
    "sssp_delta": SSSPDelta,
    "sssp_msg": SSSPMsg,
    "bfs": BFS,
    "bfs_auto": BFSAuto,
    # bfs_opt = direction-optimizing push/pull (bfs/bfs_opt.h)
    "bfs_opt": BFSOpt,
    "bfs_msg": BFSMsg,
    "wcc": WCC,
    "wcc_auto": WCCAuto,
    "wcc_opt": WCCOpt,
    "pagerank": PageRank,
    "pagerank_auto": PageRankAuto,
    "pagerank_parallel": PageRank,
    "pagerank_opt": PageRank,
    "pagerank_push": PageRankAuto,
    # the reference's push_opt differs from push only by the Opt
    # message manager (pooled buffers — compiler-managed here)
    "pagerank_push_opt": PageRankAuto,
    "cdlp": CDLP,
    "cdlp_auto": CDLP,
    "cdlp_opt": CDLPOpt,
    "cdlp_opt_ud": CDLPOpt,
    "cdlp_opt_ud_dense": CDLPOpt,
    # `lcc` = the merge-intersection variant (LCCBeta): measured 6.1s
    # warm vs 10.8s for the bitmap kernel on the p2p-31 CI config
    # (4-dev CPU mesh, scripts/run_ldbc.py, round 2); O(chunk·Dmax)
    # working set scales past the bitmap's O(N/32)-per-row.  The bitmap
    # variant stays as lcc_opt/lcc_bitmap (its VPU popcount path is the
    # analogue of the reference's SIMD lcc_opt.h) pending a TPU A/B.
    "lcc": LCCBeta,
    "lcc_auto": LCCBeta,
    "lcc_opt": LCC,
    "lcc_bitmap": LCC,
    "lcc_beta": LCCBeta,
    "lcc_directed": LCCDirected,
    # pagerank already pulls over in-edges (pagerank_parallel.h
    # semantics), which is the directed-correct formulation
    "pagerank_directed": PageRank,
    # the reference's opt-mode bc runs the staged pair
    # StagedBCBFS -> StagedBC (run_app_opt.h:471-472); here both
    # stages are fused into one PIE program (two while_loops in
    # BC.peval), so all three names resolve to it
    "bc": BC,
    "staged_bc": BC,
    "staged_bc_bfs": BC,
    "kcore": KCore,
    "kclique": KClique,
    "core_decomposition": CoreDecomposition,
    "pagerank_local": PageRankLocal,
    "pagerank_local_parallel": PageRankLocal,
    # pagerank_vc = SUMMA-sharded master state (O(N/k) per device);
    # _rep keeps the mesh-replicated round-1 formulation for A/B
    "pagerank_vc": PageRankVC,
    "pagerank_vc_rep": PageRankVCReplicated,
    # 2-D vertex-cut min-fold apps (models/vc2d.py, ROADMAP item 2):
    # byte-identical to the 1-D pulls; selected by GRAPE_PARTITION
    # via fragment/partition.resolve_partition
    "sssp_vc": SSSPVC2D,
    "bfs_vc": BFSVC2D,
    "wcc_vc": WCCVC2D,
    # r11 spgemm-backed workloads (ops/spgemm_pack.py, docs/SPGEMM.md):
    # triangle counts share the LCC credit pass (both backends);
    # common_neighbors is the serve-able 2-hop point query
    "triangle_count": TriangleCount,
    "common_neighbors": CommonNeighbors,
    # k-hop neighborhood extraction (models/khop.py): the
    # serve-routable sampling workload — ROADMAP 5c one notch
    "khop": KHopNeighborhood,
}
