"""Auto-parallel app variants (reference `sssp_auto.h`, `bfs_auto.h`,
`wcc_auto.h`, `pagerank_auto.h` under `examples/analytical_apps/`).

These exercise the SyncBuffer/auto-messaging path: instead of the
explicit pull (gather + per-row reduce) of the base apps, state updates
are *pushed* — scattered by destination pid with a segment reduce and
combined across shards by the SyncBuffer's aggregate op (`pmin`/`psum`).
Results are identical; the execution strategy differs, which is exactly
the relationship the reference variants have to their base apps.
"""

from __future__ import annotations

import jax.numpy as jnp
import jax.ops as jops
import numpy as np
from jax import lax

from libgrape_lite_tpu.app.base import AutoAppBase, StepContext
from libgrape_lite_tpu.models.bfs import BFS, _SENTINEL
from libgrape_lite_tpu.models.pagerank import PageRank
from libgrape_lite_tpu.models.sssp import SSSP
from libgrape_lite_tpu.models.wcc import WCC
from libgrape_lite_tpu.parallel.comm_spec import FRAG_AXIS


def _own_slice_min(prop, local, frag):
    """Fold the shard's own current values into its slice of the
    proposal array (a vertex is always a proposal source for itself)."""
    fid = lax.axis_index(FRAG_AXIS)
    start = fid * frag.vp
    own = lax.dynamic_slice(prop, (start,), (frag.vp,))
    return lax.dynamic_update_slice(prop, jnp.minimum(own, local), (start,))


class SSSPAuto(AutoAppBase, SSSP):
    """SSSP via SyncBuffer<dist, min> (reference sssp_auto.h)."""

    sync_buffers = {"dist": "min"}

    def propose(self, ctx: StepContext, frag, state):
        dist = state["dist"]
        oe = frag.oe
        n_pad = frag.fnum * frag.vp
        inf = jnp.asarray(jnp.inf, dist.dtype)
        src_dist = dist[jnp.minimum(oe.edge_src, frag.vp - 1)]
        cand = jnp.where(oe.edge_mask, src_dist + oe.edge_w, inf)
        prop = jops.segment_min(cand, oe.edge_nbr, num_segments=n_pad)
        return {"dist": _own_slice_min(prop, dist, frag)}


class BFSAuto(AutoAppBase, BFS):
    """BFS via SyncBuffer<depth, min> (reference bfs_auto.h)."""

    sync_buffers = {"depth": "min"}

    def propose(self, ctx: StepContext, frag, state):
        depth = state["depth"]
        oe = frag.oe
        n_pad = frag.fnum * frag.vp
        sent = jnp.int32(_SENTINEL)
        src_d = depth[jnp.minimum(oe.edge_src, frag.vp - 1)]
        cand = jnp.where(
            jnp.logical_and(oe.edge_mask, src_d != sent), src_d + 1, sent
        )
        prop = jops.segment_min(cand, oe.edge_nbr, num_segments=n_pad)
        return {"depth": _own_slice_min(prop, depth, frag)}


class WCCAuto(AutoAppBase, WCC):
    """WCC via SyncBuffer<comp, min> (reference wcc_auto.h): labels are
    pushed along both edge directions."""

    sync_buffers = {"comp": "min"}

    def propose(self, ctx: StepContext, frag, state):
        comp = state["comp"]
        n_pad = frag.fnum * frag.vp
        big = jnp.int32(np.iinfo(np.int32).max)

        def push(csr, prop):
            src_c = comp[jnp.minimum(csr.edge_src, frag.vp - 1)]
            cand = jnp.where(csr.edge_mask, src_c, big)
            return jnp.minimum(
                prop, jops.segment_min(cand, csr.edge_nbr, num_segments=n_pad)
            )

        prop = push(frag.oe, jnp.full((n_pad,), big, comp.dtype))
        if frag.directed:
            prop = push(frag.ie, prop)
        return {"comp": _own_slice_min(prop, comp, frag)}


class PageRankAuto(AutoAppBase, PageRank):
    """PageRank via SyncBuffer<rank, sum> (reference pagerank_auto.h):
    contributions are scattered along out-edges and psum-combined."""

    sync_buffers = {"rank": "sum"}
    replicated_keys = PageRank.replicated_keys

    # PageRank's PEval (degree/dangling setup) applies unchanged
    peval = PageRank.peval

    def propose(self, ctx: StepContext, frag, state):
        rank = state["rank"]
        oe = frag.oe
        n_pad = frag.fnum * frag.vp
        dt = rank.dtype
        src_r = rank[jnp.minimum(oe.edge_src, frag.vp - 1)]
        cand = jnp.where(oe.edge_mask, src_r, jnp.asarray(0, dt))
        prop = jops.segment_sum(cand, oe.edge_nbr, num_segments=n_pad)
        return {"rank": prop}

    def update(self, ctx: StepContext, frag, state, combined):
        # psum of pushed contributions = the in-neighbor rank sum
        return self.round_update(frag, state, combined["rank"])
