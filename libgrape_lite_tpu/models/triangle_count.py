"""Triangle counting + 2-hop common-neighbor queries (r11).

Workloads the tiled masked SpGEMM primitive (ops/spgemm_pack.py)
opens beyond the six LDBC pulls — ROADMAP item 5a:

  * `TriangleCount` — per-vertex T(v) and the global triangle count,
    the GraphBLAS ``B = (A · Aᵀ) ∘ A`` formulation over the oriented
    DAG.  It IS the LCC credit pass without the clustering-coefficient
    ratio: the class subclasses LCC and swaps only the emit tail, so
    both backends (GRAPE_LCC_BACKEND = intersect | spgemm | auto) and
    the degree-threshold semantics come for free and per-vertex counts
    are integer-identical to the LCC credits by construction.
  * `CommonNeighbors` — the 2-hop point query cn(v) = |N(u) ∩ N(v)|
    for a source u: two unit SpMV pulls of the one-hot source vector
    (cn = A · (A · e_u), the masked-SpGEMM row the serve path asks for
    one output row of).  Wired as a serve-able batched app via the
    source-vector contract (`batch_query_key = "source"`), so the
    admission queue coalesces k sources into one vmapped dispatch.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
from jax import lax

from libgrape_lite_tpu.app.base import (
    ParallelAppBase,
    StepContext,
    source_lane_array,
)
from libgrape_lite_tpu.models.lcc import LCC
from libgrape_lite_tpu.utils.types import LoadStrategy, MessageStrategy


class TriangleCount(LCC):
    """Per-vertex triangle counts T(v); the global count T = Σ T(v)/3
    lands in `self.global_triangles` at finalize (each triangle
    credits its three corners exactly once — the invariant the
    spgemm-vs-intersect tests pin)."""

    result_format = "int"

    def init_state(self, frag, degree_threshold: int = 0, **_):
        state = super().init_state(
            frag, degree_threshold=degree_threshold
        )
        state.pop("lcc")
        state["tri"] = np.zeros((frag.fnum, frag.vp), dtype=np.int32)
        return state

    def _emit(self, ctx: StepContext, frag, state, tri):
        out = jnp.where(frag.inner_mask, tri, 0).astype(jnp.int32)
        return dict(state, tri=out), jnp.int32(0)

    def invariants(self, frag, state):
        from libgrape_lite_tpu.guard.invariants import in_range

        # a triangle count is a non-negative cardinality
        return [in_range("tri", lo=0)]

    def finalize(self, frag, state):
        vals = np.asarray(state["tri"]).astype(np.int64)
        inner = np.zeros_like(vals)
        for f in range(frag.fnum):
            n = frag.inner_vertices_num(f)
            inner[f, :n] = vals[f, :n]
        self.global_triangles = int(inner.sum() // 3)
        return vals


_NO_SOURCE = -1


class CommonNeighbors(ParallelAppBase):
    """cn(v) = |N(u) ∩ N(v)| for a query source u — two pull rounds of
    the one-hot source vector over the (deduplicated) out-adjacency;
    the source's own row is zeroed (cn(u, u) is a degree, not a
    common-neighbor count).  Multiplicities are deduplicated like the
    LCC family: cn counts NEIGHBORS, not parallel edges."""

    load_strategy = LoadStrategy.kOnlyOut
    message_strategy = MessageStrategy.kSyncOnOuterVertex
    result_format = "int"
    batch_query_key = "source"   # serve/: [k]-source batched dispatch
    replicated_keys = frozenset({"hop"})
    max_rounds = 8  # 2 pull rounds; the vote terminates after hop 2

    def init_state(self, frag, source=_NO_SOURCE, **_):
        batched, seed = source_lane_array(
            frag, source, "CommonNeighbors", 0, 1, np.int32
        )
        k = seed.shape[0]
        state = {
            "cn": seed.copy() if batched else seed[0].copy(),
            "seed": seed if batched else seed[0],
            "hop": (np.zeros((k,), np.int32) if batched
                    else np.int32(0)),
        }
        return state

    def peval(self, ctx: StepContext, frag, state):
        return state, jnp.int32(1)

    def inceval(self, ctx: StepContext, frag, state):
        oe = frag.oe
        vp = frag.vp
        full = ctx.gather_state(state["cn"])
        # the LCC family's adjacent-duplicate rule, shared — cn counts
        # NEIGHBORS, not parallel edges
        vals = jnp.where(
            LCC._dedup_mask(oe), full[oe.edge_nbr], 0
        ).astype(jnp.int32)
        pulled = self.segment_reduce(vals, oe.edge_src, vp, "sum")
        hop = state["hop"] + 1
        done = hop >= 2
        # the final hop zeroes the source row and masks padding
        cn = jnp.where(
            jnp.logical_and(frag.inner_mask, state["seed"] == 0),
            pulled, 0,
        )
        cn = jnp.where(done, cn, pulled).astype(jnp.int32)
        active = jnp.where(done, jnp.int32(0), jnp.int32(1))
        return dict(state, cn=cn, hop=hop), active

    def invariants(self, frag, state):
        from libgrape_lite_tpu.guard.invariants import in_range

        return [in_range("cn", lo=0)]

    def finalize(self, frag, state):
        return np.asarray(state["cn"]).astype(np.int64)
