"""2-D vertex-cut min-fold apps — SSSP/BFS/WCC on the SUMMA mesh.

The tentpole of ROADMAP item 2 (PR 10): promote the vertex-cut seed
side-path (fragment/vertexcut.py, until now PageRankVC-only) to a
first-class execution path for the tropical-min LDBC apps, so
hub-heavy graphs stop paying the edge-cut pathology (docs/
SCALE_NOTES.md: at RMAT scale 12 a degree-correlated 1-D cut makes
99% of edges boundary edges and every shard pays the hub shard's Ep).
SparseP (arxiv 2201.05072) is the blueprint: equally-wide 2-D tiles
bound both per-tile compute and per-tile collective volume.

Layout (fragment (i, j) = mesh device (i, j), fid = i*k + j):

  * tile (i, j) holds the COO block of edges src ∈ chunk_i x
    dst ∈ chunk_j (undirected graphs are symmetrised at build, like
    the 1-D loader, so ONE dst-side pull per round covers both
    directions);
  * the master carry (dist/depth/comp) is sharded 1-D by row chunk:
    the [k*vc] leaf rides P(vcrow) — device (i, j) holds chunk i,
    replicated along the column axis.  That replication IS the
    "broadcast source values along the column axis" of the SUMMA
    round: every tile reads its source chunk locally.

Per round (inceval):

  1. local scatter-reduce: candidates over the tile's edges fold into
     [vc] row partials for chunk j via ops/segment.py (or the per-tile
     pack plan — resolve_pack_dispatch runs on the tile's COO->CSR
     block, so the MXU scan + stream-diet wins of PRs 2/4 carry over);
  2. pmin along the row axis completes chunk j (column-sharded);
  3. ONE transpose ppermute ((i,j) -> (j,i)) re-aligns the completed
     fold row-sharded, and the master fold + termination vote run on
     the row copy.

Identity argument (pinned in tests/test_partition2d.py): min is
associative and commutative, and every candidate `value[src] (+ w)`
is computed from exactly the operands the 1-D pull uses — regrouping
the fold across tiles is bit-exact, so SSSP/BFS/WCC results are
byte-identical to the 1-D path.  (Sum folds — PageRankVC — regroup
float partials and are eps-identical instead, the same documented
decline as the pipeline SUM split.)
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from libgrape_lite_tpu.app.base import GatherScatterAppBase, StepContext
from libgrape_lite_tpu.parallel.comm_spec import VC_COL_AXIS, VC_ROW_AXIS
from libgrape_lite_tpu.utils.types import LoadStrategy, MessageStrategy

_INT_SENT = np.iinfo(np.int32).max
_OUT_SENTINEL = np.iinfo(np.int64).max  # BFS prints the reference's max


def vc_transpose(x, k):
    """Swap row/col sharding of a chunk-sharded per-device block:
    device (i, j) exchanges with (j, i) — one ppermute over the joint
    axis (diagonal devices self-map, so the average per-device ICI
    volume is (1 - 1/k) * |x|; the planner's byte model prices it
    that way)."""
    if k == 1:
        return x
    perm = [(i * k + j, j * k + i) for i in range(k) for j in range(k)]
    return lax.ppermute(x, (VC_ROW_AXIS, VC_COL_AXIS), perm)


def _phase_view(frag, lo, hi):
    """A STATIC slice of the traced tile's COO edge ring — the
    phase-0/phase-1 halves of the pipelined SUMMA round.  Pure python
    slicing of the per-shard [Ep] leaves (lo/hi are host ints from the
    resolved plan), so both phases fold the identical segment machinery
    over disjoint slot ranges of the same arrays; pad slots carry
    mask=False and fold to the identity either side of the cut."""
    import dataclasses

    return dataclasses.replace(
        frag,
        src=frag.src[lo:hi],
        dst=frag.dst[lo:hi],
        w=None if frag.w is None else frag.w[lo:hi],
        mask=frag.mask[lo:hi],
    )


def vc_source_carry(frag, source, app_name: str, fill, hit, dtype):
    """`[k*vc]` gpid-space carry seeded at `source` — or `[B, k*vc]`
    when `source` is a sequence (the batched init contract of
    `batch_query_key`, the vc2d analogue of app.base's
    source_lane_array).  Out-of-range sources leave their lane all
    `fill` (every vertex unreachable), logged like the 1-D apps."""
    batched = isinstance(source, (list, tuple, np.ndarray))
    srcs = np.asarray(
        source if batched else [source], dtype=np.int64
    ).reshape(-1)
    arr = np.full((len(srcs), frag.k * frag.vc), fill, dtype=dtype)
    for b, s in enumerate(srcs):
        if 0 <= s < frag.k * frag.chunk:
            arr[b, int(frag.oid_to_gpid(np.array([s]))[0])] = hit
        else:
            from libgrape_lite_tpu.utils import logging as glog

            glog.log_info(
                f"{app_name}: source {int(s)!r} is outside the oid "
                "space; all vertices will be unreachable"
            )
    return arr if batched else arr[0]


def vc_finalize_rows(frag, flat: np.ndarray) -> np.ndarray:
    """Compact a gpid-space [k*vc] result into [fnum, vc] rows aligned
    with inner_oids order (masters = diagonal fragments) — the Worker
    output contract shared by every vertex-cut app.  A carry leaf that
    spans non-addressable devices (jax.distributed) is gathered via
    process_allgather first — np.asarray on it would throw (the PR 18
    edgecut bug class; same idiom as worker.result_values)."""
    if not getattr(flat, "is_fully_addressable", True):
        from jax.experimental import multihost_utils

        flat = np.asarray(multihost_utils.process_allgather(flat))
    vals = np.asarray(flat).reshape(frag.k, frag.vc)
    out = np.zeros((frag.fnum, frag.vc), dtype=vals.dtype)
    for c in range(frag.k):
        oids = frag.inner_oids(c * frag.k + c)
        offs = oids % frag.chunk
        out[c * frag.k + c, : len(oids)] = vals[c, offs]
    return out


class VC2DMinAppBase(GatherScatterAppBase):
    """Shared scaffolding of the tropical-min vertex-cut apps: the
    row-sharded carry, the per-tile pack resolve, the SUMMA round and
    the diagonal-master finalize.  Subclasses declare `state_key` and
    the candidate builder."""

    load_strategy = LoadStrategy.kNullLoadStrategy
    message_strategy = MessageStrategy.kGatherScatter
    mesh_kind = "vc2d"
    state_key = ""          # the carry leaf ("dist"/"depth"/"comp")
    needs_weights = False

    def custom_specs(self):
        return {
            self.state_key: P(VC_ROW_AXIS),
            "vmask_row": P(VC_ROW_AXIS),
        }

    # ---- shared init scaffolding ----

    def _init_common(self, frag, carry: np.ndarray):
        """Carry + ephemeral leaves, per-tile pack resolve, and the
        partition fingerprint facts that key the compiled-runner cache
        (a 1-D and a 2-D compile must never share an entry — `k` and
        the mode ride in trace_key as primitive attributes)."""
        import os

        state = {self.state_key: carry}
        eph_entries = {"vmask_row": frag.vertex_mask()}
        self._partition = "2d"
        self._mesh_k = frag.k
        self._partition_stats = frag.tile_stats()
        # decided on the HOST fragment (the traced VCDeviceFragment
        # carries only geometry); a primitive, so it rides trace_key
        self._src_pull = self._wants_src_pull(frag)
        self._pack_ie = self._pack_oe = None
        if os.environ.get("GRAPE_SPMV") == "pack":
            self._resolve_tile_packs(frag, eph_entries)
        self._pack_uid = (
            self._pack_ie.uid if self._pack_ie is not None else -1
        )
        from libgrape_lite_tpu.parallel.pipeline import (
            resolve_vc2d_pipeline,
        )

        self._pipeline = resolve_vc2d_pipeline(
            frag, app_name=type(self).__name__, pack=self._pack_ie,
            src_pull=self._src_pull,
            dtype_bytes=int(np.dtype(carry.dtype).itemsize),
        )
        self._pipeline_uid = (
            self._pipeline.uid if self._pipeline is not None else "-"
        )
        # the truth meter joins measured device waits against modeled
        # overlap by plan uid; the partition record is how the 2-D
        # path's key reaches the obs partition surface
        self._partition_stats["plan_uid"] = self._pipeline_uid
        state.update(eph_entries)
        self.ephemeral_keys = frozenset(eph_entries)
        return state

    def _pack_eligible(self, frag) -> str | None:
        """None = eligible; otherwise the warn_pack_ineligible reason."""
        if frag.k * frag.vc > (1 << 24):
            return "gpid value space exceeds exact f32 range (2^24)"
        return None

    def _resolve_tile_packs(self, frag, eph_entries: dict):
        from libgrape_lite_tpu.ops.spmv_pack import (
            resolve_pack_dispatch,
            warn_pack_ineligible,
        )

        name = type(self).__name__
        why = self._pack_eligible(frag)
        if why is not None:
            warn_pack_ineligible(name, why)
            return
        role = f"vc2d-k{frag.k}"
        ie = resolve_pack_dispatch(
            frag, direction="ie", prefix="pk_ie_", role=role,
            with_weights=self.needs_weights,
        )
        oe = (
            resolve_pack_dispatch(
                frag, direction="oe", prefix="pk_oe_", role=role,
                with_weights=self.needs_weights,
            )
            if self._src_pull else None
        )
        if ie is None or (self._src_pull and oe is None):
            warn_pack_ineligible(name, "no tile pack plan buildable")
            return
        self._pack_ie, self._pack_oe = ie, oe
        eph_entries.update(ie.state_entries())
        if oe is not None:
            eph_entries.update(oe.state_entries())

    def _wants_src_pull(self, frag) -> bool:
        """Directed WCC pulls the src side too (weak connectivity needs
        both directions; undirected tiles are symmetrised instead)."""
        return False

    # ---- the SUMMA round ----

    def peval(self, ctx: StepContext, frag, state):
        # like the 1-D pull apps: the first pull round subsumes the
        # reference's source-only PEval
        return state, jnp.int32(1)

    def _dst_partial(self, ctx, frag, val_row, state):
        """Tile-local candidates folded into [vc] chunk-j partials
        (pull into dst) — the pack plan or the XLA segment machinery."""
        raise NotImplementedError

    def _src_partial(self, ctx, frag, val_col, state):
        """Optional src-side partials (directed WCC)."""
        raise NotImplementedError

    def inceval(self, ctx: StepContext, frag, state):
        k, vc = frag.k, frag.vc
        val = state[self.state_key]  # [vc] chunk i (row copy)
        partial = self._dst_partial(ctx, frag, val, state)
        relax_col = lax.pmin(partial, VC_ROW_AXIS)  # complete chunk j
        relax_row = vc_transpose(relax_col, k)      # re-align to chunk i
        if self._src_pull:
            val_col = vc_transpose(val, k)          # chunk j copy
            partial2 = self._src_partial(ctx, frag, val_col, state)
            relax_row = jnp.minimum(
                relax_row, lax.pmin(partial2, VC_COL_AXIS)
            )
        new = jnp.minimum(val, relax_row)
        changed = jnp.logical_and(new < val, state["vmask_row"])
        # each column of devices holds all k chunks once: the psum
        # over vcrow IS the global changed count, identical everywhere
        active = lax.psum(changed.sum().astype(jnp.int32), VC_ROW_AXIS)
        return {self.state_key: new}, active

    # ---- the pipelined SUMMA round (VC2DPipelinePlan) ----

    def pipeline_exchange(self, ctx: StepContext, frag, state):
        """The SUMMA round has no cross-round halo table: the carry's
        row replication along the column axis IS the broadcast, and the
        row-axis pmin completes inside the round.  The worker's
        pipelined loop still carries an exchange buffer, so hand it an
        inert scalar — re-derived at every chunk entry to the same
        constant, keeping the observable cut contract vacuously."""
        return jnp.int32(0)

    def inceval_pipelined(self, ctx: StepContext, frag, state, xbuf):
        """The two-phase round: fold phase 0, kick its row-axis pmin,
        fold phase 1 UNDER the in-flight collective, complete with the
        second pmin and merge.  min(pmin(fold0), pmin(fold1)) is
        bitwise pmin(fold(all slots)) — min regrouping over disjoint
        static slices of the same edge arrays is exact (ints and IEEE
        floats; no float addition crosses the cut), so the result is
        byte-identical to `inceval` (the directed src-pull form never
        resolves a plan, see resolve_vc2d_pipeline)."""
        k = frag.k
        pl = self._pipeline
        val = state[self.state_key]  # [vc] chunk i (row copy)
        f0 = _phase_view(frag, 0, pl.split)
        f1 = _phase_view(frag, pl.split, None)
        p0 = self._dst_partial(ctx, f0, val, state)
        r0 = lax.pmin(p0, VC_ROW_AXIS)  # kicked; phase 1 overlaps it
        p1 = self._dst_partial(ctx, f1, val, state)
        r1 = lax.pmin(p1, VC_ROW_AXIS)
        relax_row = vc_transpose(jnp.minimum(r0, r1), k)
        new = jnp.minimum(val, relax_row)
        changed = jnp.logical_and(new < val, state["vmask_row"])
        active = lax.psum(changed.sum().astype(jnp.int32), VC_ROW_AXIS)
        return {self.state_key: new}, active, xbuf

    def finalize(self, frag, state):
        return vc_finalize_rows(frag, np.asarray(state[self.state_key]))


class SSSPVC2D(VC2DMinAppBase):
    """SSSP on the 2-D mesh: tropical relax `min(dist[src] + w)` per
    tile, completed by the row-axis pmin — byte-identical to the 1-D
    pull (same adds, min regrouping is exact)."""

    state_key = "dist"
    result_format = "sssp_infinity"
    needs_edata = True
    needs_weights = True
    batch_query_key = "source"

    def _pack_eligible(self, frag):
        import jax

        if jax.config.jax_enable_x64:
            return "state dtype float64 is not float32"
        if not frag.weighted:
            return "fragment has no edge weights"
        return None

    def init_state(self, frag, source=0):
        import jax

        if not frag.weighted:
            raise ValueError(
                "SSSP requires edge weights; build the vertex-cut "
                "fragment with weights (use bfs_vc for unit-weight "
                "traversal)"
            )
        _, _, w_arr, _ = frag._host_tiles
        dtype = w_arr.dtype
        if not jax.config.jax_enable_x64:
            dtype = np.float32
        dist = vc_source_carry(
            frag, source, "SSSPVC2D", np.inf, 0.0, dtype
        )
        return self._init_common(frag, dist)

    def _dst_partial(self, ctx, frag, val_row, state):
        vc = frag.vc
        if self._pack_ie is not None:
            return self._pack_ie.reduce(val_row, state, "min")
        inf = jnp.asarray(jnp.inf, val_row.dtype)
        cand = jnp.where(frag.mask, val_row[frag.src % vc] + frag.w, inf)
        return self.segment_reduce(cand, frag.dst % vc, vc, "min")

    def invariants(self, frag, state):
        from libgrape_lite_tpu.guard.invariants import (
            in_range, monotone_non_increasing,
        )

        return [
            in_range("dist", lo=0.0),
            monotone_non_increasing("dist"),
        ]


class BFSVC2D(VC2DMinAppBase):
    """BFS levels on the 2-D mesh: unit-weight tropical relax
    `min(depth[src] + 1)` — byte-identical to the 1-D pull."""

    state_key = "depth"
    result_format = "int"
    batch_query_key = "source"

    def init_state(self, frag, source=0):
        depth = vc_source_carry(
            frag, source, "BFSVC2D", _INT_SENT, 0, np.int32
        )
        return self._init_common(frag, depth)

    def _dst_partial(self, ctx, frag, val_row, state):
        vc = frag.vc
        sent = jnp.int32(_INT_SENT)
        if self._pack_ie is not None:
            # unit-weight tropical relax over the pack routes:
            # min(nbr) + 1 == min(nbr + 1); unreached rides as +inf
            val_f = jnp.where(
                val_row == sent, jnp.float32(jnp.inf),
                val_row.astype(jnp.float32),
            )
            red = self._pack_ie.reduce(val_f, state, "min") + 1.0
            return jnp.where(
                jnp.isfinite(red), red.astype(jnp.int32), sent
            )
        nb = val_row[frag.src % vc]
        cand = jnp.where(
            jnp.logical_and(frag.mask, nb != sent), nb + 1, sent
        )
        return self.segment_reduce(cand, frag.dst % vc, vc, "min")

    def invariants(self, frag, state):
        from libgrape_lite_tpu.guard.invariants import (
            in_range, monotone_non_increasing,
        )

        return [
            in_range("depth", lo=0, hi=_INT_SENT),
            monotone_non_increasing("depth"),
        ]

    def finalize(self, frag, state):
        out = vc_finalize_rows(
            frag, np.asarray(state["depth"]).astype(np.int64)
        )
        return np.where(out == _INT_SENT, _OUT_SENTINEL, out)


class WCCVC2D(VC2DMinAppBase):
    """WCC on the 2-D mesh: min-gpid label propagation.  gpid order is
    oid order (contiguous chunks), so the converged representative is
    the min-OID member — the same vertex the 1-D map-partitioned path
    canonicalises to, making the finalized labels byte-identical.

    Directed graphs pull BOTH tile orientations per round (weak
    connectivity) from the same carry snapshot; the fixed point is the
    unique per-component min either way, but round counts can differ
    from the 1-D path's dependent second pull, so the byte-identity
    pin covers the undirected form."""

    state_key = "comp"
    result_format = "int"

    def _wants_src_pull(self, frag) -> bool:
        return bool(frag.directed) and not frag.symmetrized

    def init_state(self, frag, **_):
        gpids = np.arange(frag.k * frag.vc, dtype=np.int32)
        comp = np.where(frag.vertex_mask(), gpids, _INT_SENT).astype(
            np.int32
        )
        return self._init_common(frag, comp)

    def _label_partial(self, ctx, frag, table, rows, cols, state, pack):
        vc = frag.vc
        big = jnp.int32(_INT_SENT)
        if pack is not None:
            # labels travel as exact f32 ints (gpid space < 2^24);
            # rows with no edges come back +inf
            red = pack.reduce(table.astype(jnp.float32), state, "min")
            return jnp.where(
                jnp.isfinite(red), red.astype(jnp.int32), big
            )
        cand = jnp.where(frag.mask, table[cols % vc], big)
        return self.segment_reduce(cand, rows % vc, vc, "min")

    def _dst_partial(self, ctx, frag, val_row, state):
        return self._label_partial(
            ctx, frag, val_row, frag.dst, frag.src, state, self._pack_ie
        )

    def _src_partial(self, ctx, frag, val_col, state):
        return self._label_partial(
            ctx, frag, val_col, frag.src, frag.dst, state, self._pack_oe
        )

    def invariants(self, frag, state):
        from libgrape_lite_tpu.guard.invariants import (
            in_range, monotone_non_increasing,
        )

        return [
            in_range("comp", lo=0, hi=_INT_SENT),
            monotone_non_increasing("comp"),
        ]

    def finalize(self, frag, state):
        comp = np.asarray(state["comp"]).astype(np.int64)
        out = vc_finalize_rows(frag, comp)
        # canonicalise label -> representative oid (pure arithmetic:
        # gpid encodes the oid) — matching the 1-D WCC finalize
        return np.where(
            out == _INT_SENT, -1, frag.gpid_to_oid(out)
        )
