"""guard/ — runtime invariant monitors, divergence watchdog, and
self-healing rollback-replay.

The PIE model gives every query a per-superstep consistent cut; the
`ft/` subsystem already exploits it for checkpoint/restore.  `guard/`
closes the loop by *detecting* that a run has gone wrong and driving
recovery without an operator:

* **Invariants** (`invariants.py`) — app-declared, named device-side
  predicates over consecutive carries (`AppBase.invariants`):
  SSSP/BFS distances monotonically non-increasing, PageRank mass
  conserved within eps, WCC labels non-increasing, all float carries
  NaN-free, active votes within `[0, vnum]`.
* **Divergence watchdog** (`watchdog.py`) — a carry-digest history
  proves oscillation cycles (a digest repeat under a deterministic
  superstep IS an infinite cycle) and flags K-round residual
  stagnation, halting with a structured diagnostic bundle instead of
  spinning to `max_rounds`.
* **Monitor + breach policies** (`monitor.py`) — `warn | halt |
  rollback`; rollback restores the last good snapshot via
  `ft.checkpoint.restore_latest`, replays in stepwise "paranoid" mode
  (probe every round) to localize the faulty round, and continues.
* **Cross-rank breach votes** (`vote.py`) — under `jax.distributed`,
  every rank exchanges a verdict at each hazard boundary so one
  rank's halt becomes an all-ranks halt (`RemoteBreachError`) at the
  same superstep cut instead of stranding siblings in a collective.

Execution contract: guards are OFF by default and the fused
`shard_map(while_loop)` fast path is byte-identical with guards off
(`Worker.query` consults only the env/kwarg to pick a path; the fused
runner trace never changes).  Guards on: `query_stepwise` probes every
round (`GRAPE_GUARD_EVERY` thins the cadence); the fused path runs in
chunks of `GRAPE_GUARD_EVERY` supersteps with a probe at every chunk
boundary, so a breach is detected within one cadence.
"""

from libgrape_lite_tpu.guard.config import (
    GUARD_ENV,
    GUARD_EVERY_ENV,
    GUARD_STAGNATION_ENV,
    GuardConfig,
)
from libgrape_lite_tpu.guard.invariants import (
    Invariant,
    default_invariants,
    finite,
    in_range,
    monotone_non_increasing,
    no_nan,
)
from libgrape_lite_tpu.guard.monitor import (
    DivergenceError,
    GuardError,
    GuardMonitor,
    InvariantBreachError,
)
from libgrape_lite_tpu.guard.vote import BreachVote, RemoteBreachError
from libgrape_lite_tpu.guard.watchdog import DivergenceWatchdog, carry_digest

__all__ = [
    "GUARD_ENV",
    "GUARD_EVERY_ENV",
    "GUARD_STAGNATION_ENV",
    "GuardConfig",
    "Invariant",
    "default_invariants",
    "finite",
    "in_range",
    "monotone_non_increasing",
    "no_nan",
    "GuardError",
    "InvariantBreachError",
    "DivergenceError",
    "GuardMonitor",
    "BreachVote",
    "RemoteBreachError",
    "DivergenceWatchdog",
    "carry_digest",
]
