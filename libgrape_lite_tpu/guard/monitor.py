"""GuardMonitor: one probe per cadence, breach policy, rollback state.

The monitor owns detection and policy; the Worker owns execution (it
re-places restored state and rewinds its loop counters).  Flow per
probe:

  1. host check: the psum'd active vote must lie in [0, total_vnum]
     (negative active is the app's own cooperative abort, not a
     breach — the loop exits before the monitor ever sees it);
  2. ONE jitted device dispatch evaluates every applicable invariant,
     the carry digest, and the float residual;
  3. invariant failures -> breach verdict; otherwise, while the run is
     still voting active, the watchdog checks the digest history;
  4. policy: warn logs and continues; halt raises with the diagnostic
     bundle; rollback asks the Worker to restore the last good
     snapshot (requires a CheckpointManager) and flips the monitor
     into paranoid mode (probe every round) so a deterministic fault
     is localized to its exact superstep on replay.

Watchdog verdicts never roll back: the cycle/stagnation proof is a
property of the healthy deterministic loop, so a replay would diverge
identically — they halt (or warn) instead.
"""

from __future__ import annotations

import weakref
from dataclasses import asdict, dataclass
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from libgrape_lite_tpu import obs
from libgrape_lite_tpu.guard.config import GuardConfig
from libgrape_lite_tpu.guard.watchdog import (
    DivergenceWatchdog,
    carry_digest,
    digest_hex,
)
from libgrape_lite_tpu.utils import logging as glog
from libgrape_lite_tpu.utils.types import state_struct

_HISTORY = 64  # rounds of digest/active context kept for the bundle

# compiled probes shared across monitors: a GuardMonitor is created
# per query — and per LANE per batch in serve/batch.py — so holding
# the jitted probe on the instance re-traced and re-compiled it for
# every guarded dispatch (jit caches by wrapper identity; the wrapper
# was new each time).  The cache is keyed weakly on the fragment
# (probes bind invariants resolved against it) and strongly on (app
# class, app.trace_key(), carry structure) — the same identity the
# worker's runner cache uses; a repack/mutation swaps the fragment
# and naturally starts a fresh entry.  Found by grape-lint R2, the
# PR 6 guarded-serve re-jit class (analysis/rules.py).
_PROBE_CACHE: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


class GuardError(RuntimeError):
    """A guard breach under the halt policy (or an exhausted rollback
    budget).  `.bundle` carries the structured diagnostic."""

    def __init__(self, msg: str, bundle: dict):
        super().__init__(msg)
        self.bundle = bundle


class InvariantBreachError(GuardError):
    """An app-declared invariant failed on the live carry."""


class DivergenceError(GuardError):
    """The watchdog proved an oscillation cycle or flagged residual
    stagnation."""


@dataclass
class Breach:
    action: str  # "halt" | "rollback"
    verdict: dict
    bundle: dict
    message: str


class GuardMonitor:
    def __init__(self, app, frag, config: GuardConfig, *,
                 ckpt=None, ledger=None):
        self.app = app
        self.frag = frag
        self.config = config
        self.ckpt = ckpt
        self.watchdog = DivergenceWatchdog(config.stagnation_window)
        self.paranoid = False
        self.rollbacks = 0
        self.probes = 0
        self.mutations = 0  # mutation boundaries crossed (dyn/)
        self.breaches: List[dict] = []
        self._invariants = None
        self._probe = None
        self._ledger = ledger
        self._digest_hist: List = []
        self._active_hist: List = []
        self._last_breach = None

    # ---- probe construction ---------------------------------------------

    def due(self, rounds: int) -> bool:
        return (
            self.paranoid
            or self.config.every <= 1
            or rounds % self.config.every == 0
        )

    def can_rollback(self) -> bool:
        return self.ckpt is not None

    def on_mutation(self, new_frag, ledger=None) -> None:
        """Mutation-boundary reset (dyn/): after a delta apply the
        graph — and with it the deterministic superstep operator —
        changed.  A digest match against a pre-mutation round no
        longer proves a cycle (the same carry under a DIFFERENT
        operator evolves differently), so the watchdog history must
        clear or a legitimately re-visited state raises a
        false-positive DivergenceError.  The compiled probe is also
        dropped: state shapes and the fragment arrays it binds may
        have been rebuilt.  `ledger` is the re-resolved pack ledger
        for post-mutation breach bundles — the pre-mutation snapshot
        would misattribute modeled cost, so absent a fresh one it is
        nulled rather than left stale."""
        self.frag = new_frag
        self._ledger = ledger
        self.mutations += 1
        self.watchdog.reset()
        self._probe = None
        self._probe_inv = None
        self._invariants = None
        # a pre-mutation snapshot is NOT a valid rollback target for
        # the rebuilt graph (shapes/pids may differ, and replaying
        # would re-run already-applied mutations) — drop it so a later
        # rollback verdict degrades to halt.  Unreachable today
        # (checkpointing MutationContext apps is rejected up front),
        # but cheap insurance against that restriction loosening.
        self.ckpt = None
        obs.tracer().instant("guard_mutation_reset")
        glog.vlog(
            1, "guard: mutation boundary — watchdog history reset, "
            "probe re-resolves against the mutated fragment",
        )

    def _resolve(self, carry: Dict) -> None:
        cache = _PROBE_CACHE.setdefault(self.frag, {})
        key = (
            type(self.app).__qualname__,
            self.app.trace_key(),
            state_struct(carry),
        )
        hit = cache.get(key)
        if hit is None:
            hit = self._build_probe(carry)
            cache[key] = hit
        self._invariants, self._probe, self._probe_inv = hit

    def _build_probe(self, carry: Dict):
        """(kept invariants, jitted probe, jitted invariants-only
        probe or None) — built once per (fragment, app class +
        hyperparameters, carry structure) and shared through
        _PROBE_CACHE across every monitor of that identity."""
        declared = self.app.invariants(self.frag, carry)
        kept, dropped = [], []
        for inv in declared:
            (kept if set(inv.requires) <= set(carry) else dropped).append(inv)
        if dropped:
            glog.log_info(
                "guard: dropped invariants whose carry keys are absent: "
                + ", ".join(i.name for i in dropped)
            )
        float_keys = sorted(
            k for k in carry if np.dtype(carry[k].dtype).kind == "f"
        )

        # `dev` rides as a jit ARGUMENT (DeviceFragment is a pytree):
        # closing over it would bake multi-MB fragment arrays into the
        # probe executable as XLA constants
        def inv_part(dev, prev, cur):
            oks, vals = [], []
            for inv in kept:
                ok, val = inv.check(dev, prev, cur)
                oks.append(ok)
                vals.append(val)
            oks = (
                jnp.stack(oks) if oks else jnp.zeros((0,), jnp.bool_)
            )
            vals = (
                jnp.stack(vals) if vals else jnp.zeros((0,), jnp.float32)
            )
            return oks, vals

        def probe(dev, prev, cur):
            oks, vals = inv_part(dev, prev, cur)
            digest = carry_digest(cur)
            residual = None
            if float_keys:
                # non-finite deltas (inf sentinels present in BOTH
                # carries give inf - inf = NaN; a newly-reached inf ->
                # finite transition gives inf) carry no usable
                # magnitude — mask them so one padded +inf row cannot
                # poison the stagnation metric with NaN forever
                diffs = []
                for k in float_keys:
                    d = jnp.abs(
                        cur[k].astype(jnp.float32)
                        - prev[k].astype(jnp.float32)
                    )
                    diffs.append(jnp.max(
                        jnp.where(jnp.isfinite(d), d, jnp.float32(0))
                    ))
                residual = jnp.max(jnp.stack(diffs))
            return oks, vals, digest, residual

        # invariants-only probe for callers that already hold the
        # digest/residual (the guarded-fused chunk runner emits them
        # as extra loop outputs); apps with no invariants then skip
        # the probe dispatch entirely
        return kept, jax.jit(probe), (jax.jit(inv_part) if kept else None)

    # ---- per-probe entry point ------------------------------------------

    def check(self, prev: Dict, cur: Dict, rounds: int,
              active: int, *, digest=None,
              residual=None) -> Optional[Breach]:
        """One probe.  `digest`/`residual` may be supplied by a caller
        that computed them inside its own dispatch (the guarded-fused
        chunk runner emits the carry digest and masked residual as
        extra loop outputs — value-identical to the probe's, same
        functions on the same global carry); the monitor then runs
        only the invariants-only probe, or nothing at all when the app
        declares no invariants."""
        self.probes += 1
        obs.metrics().counter("grape_guard_probes_total").inc()
        if self._probe is None:
            self._resolve(cur)
        vnum = self.frag.dev.total_vnum
        if active > vnum:
            verdict = {
                "kind": "active_range",
                "round": rounds,
                "active": int(active),
                "detail": (
                    f"active vote {int(active)} exceeds the vertex count "
                    f"{vnum} — the termination allreduce is corrupt"
                ),
            }
            return self._policy(verdict, rounds, active, failed=None)

        if digest is None:
            oks, vals, digest_words, residual = self._probe(
                self.frag.dev, prev, cur
            )
            digest = tuple(int(x) for x in np.asarray(digest_words))
            if residual is not None:
                residual = float(residual)
        elif self._probe_inv is not None:
            oks, vals = self._probe_inv(self.frag.dev, prev, cur)
        else:
            oks = vals = np.zeros((0,))
        oks = np.asarray(oks)
        vals = np.asarray(vals)
        self._digest_hist.append((rounds, digest_hex(digest)[:16]))
        self._active_hist.append((rounds, int(active)))
        del self._digest_hist[:-_HISTORY], self._active_hist[:-_HISTORY]

        failed = [
            (inv, float(v))
            for inv, ok, v in zip(self._invariants, oks, vals)
            if not bool(ok)
        ]
        if failed:
            verdict = {
                "kind": "invariant",
                "round": rounds,
                "failed": {inv.name: v for inv, v in failed},
                "detail": "; ".join(
                    f"{inv.name}: {inv.description} (measure={v:g})"
                    for inv, v in failed
                ),
            }
            return self._policy(
                verdict, rounds, active,
                failed=tuple(inv.name for inv, _ in failed),
            )
        if active > 0:
            # a converged final round legitimately repeats the previous
            # digest (nothing changed, active==0) — only a still-active
            # loop can be diagnosed as cycling/stagnating
            verdict = self.watchdog.observe(
                rounds, digest,
                None if residual is None else float(residual),
            )
            if verdict is not None:
                return self._policy(verdict, rounds, active, failed=None)
        return None

    # ---- policy ----------------------------------------------------------

    def _policy(self, verdict: dict, rounds: int, active: int,
                failed) -> Optional[Breach]:
        bundle = self._bundle(verdict, rounds, active)
        self.breaches.append(bundle)
        # the breach lands on the trace timeline as an instant event,
        # so a Perfetto view shows WHICH superstep span it interrupted;
        # the bundle carries the trace id for the reverse lookup
        obs.metrics().counter("grape_guard_breaches_total").inc()
        obs.tracer().instant(
            "guard_breach", kind=verdict["kind"], round=rounds,
            policy=self.config.policy,
            detail=verdict.get("detail", ""),
        )
        from libgrape_lite_tpu.obs.recorder import RECORDER

        RECORDER.trigger(
            "guard_breach",
            extra={"kind": verdict["kind"], "round": rounds,
                   "policy": self.config.policy},
            guard=bundle,
        )
        msg = (
            f"guard: {verdict['kind']} breach at superstep {rounds} "
            f"(policy={self.config.policy}): {verdict['detail']}"
        )
        if self.config.policy == "warn":
            glog.log_info(msg + " — continuing (warn policy)")
            return None
        action = "halt"
        if self.config.policy == "rollback" and verdict["kind"] == "invariant":
            if not self.can_rollback():
                glog.log_info(
                    "guard: rollback policy without a checkpoint manager "
                    "(no checkpoint_every/checkpoint_dir) — halting instead"
                )
            elif (
                self.rollbacks > 0
                and self._last_breach == (rounds, failed)
            ):
                # the paranoid replay reproduced the exact breach: the
                # fault is a deterministic property of this superstep,
                # not transient state damage — localized, stop retrying
                glog.log_info(
                    f"guard: breach recurred at superstep {rounds} after a "
                    "rollback — the fault is deterministic; localized, "
                    "halting"
                )
                bundle["localized_round"] = rounds
            elif self.rollbacks >= self.config.max_rollbacks:
                glog.log_info(
                    f"guard: rollback budget ({self.config.max_rollbacks}) "
                    "exhausted — halting"
                )
            else:
                action = "rollback"
        elif self.config.policy == "rollback":
            # oscillation/stagnation replay identically — never roll back
            glog.log_info(
                f"guard: {verdict['kind']} verdicts are deterministic "
                "under replay — halting instead of rolling back"
            )
        self._last_breach = (rounds, failed)
        glog.log_info(msg)
        return Breach(action=action, verdict=verdict, bundle=bundle, message=msg)

    def raise_breach(self, breach: Breach):
        cls = (
            InvariantBreachError
            if breach.verdict["kind"] in ("invariant", "active_range")
            else DivergenceError
        )
        raise cls(breach.message, breach.bundle)

    # ---- rollback --------------------------------------------------------

    def rollback(self, breach: Breach):
        """(restored_state, meta) of the last good snapshot; flips the
        monitor paranoid and resets the watchdog history (replayed
        rounds must not re-match their own old digests)."""
        from libgrape_lite_tpu.ft.checkpoint import restore_latest

        self.ckpt.wait()  # an in-flight write must land before listing
        with obs.tracer().span(
            "rollback", breach_round=breach.verdict["round"]
        ):
            state, meta = restore_latest(
                self.ckpt.directory, self.ckpt.fingerprint
            )
        self.rollbacks += 1
        obs.metrics().counter("grape_guard_rollbacks_total").inc()
        self.paranoid = True
        self.watchdog.reset()
        glog.log_info(
            f"guard: rolled back to superstep {int(meta['rounds'])} "
            f"(breach at superstep {breach.verdict['round']}, "
            f"rollback {self.rollbacks}/{self.config.max_rollbacks}); "
            "replaying in paranoid mode"
        )
        return state, meta

    # ---- diagnostics -----------------------------------------------------

    def _bundle(self, verdict: dict, rounds: int, active: int) -> dict:
        try:
            from libgrape_lite_tpu.ft.fingerprint import (
                app_registry_name,
                fragment_content_hash,
            )

            fingerprint = (
                dict(self.ckpt.fingerprint) if self.ckpt is not None else {
                    "app": app_registry_name(self.app),
                    "fragment_hash": fragment_content_hash(self.frag),
                    "fnum": self.frag.fnum,
                    "vp": self.frag.vp,
                }
            )
        except Exception as e:  # diagnostics must never mask the breach
            fingerprint = {"error": f"{type(e).__name__}: {e}"}
        ledger = None
        if self._ledger:
            ledger = {
                "edges": self._ledger.get("edges"),
                "totals": {
                    k: v
                    for k, v in self._ledger.get("totals", {}).items()
                    if not isinstance(v, dict)
                },
            }
        return {
            "verdict": dict(verdict),
            "round": rounds,
            "active": int(active),
            # None when obs/ is disarmed; with tracing on, the id ties
            # this bundle to the trace file's metadata block
            "trace_id": obs.trace_id(),
            "policy": self.config.policy,
            "paranoid": self.paranoid,
            "rollbacks": self.rollbacks,
            "recent_digests": list(self._digest_hist),
            "active_history": list(self._active_hist),
            "invariants": [i.name for i in (self._invariants or [])],
            "op_ledger": ledger,
            "config_fingerprint": fingerprint,
            "guard_config": asdict(self.config),
        }

    def report(self) -> dict:
        return {
            "policy": self.config.policy,
            "every": self.config.every,
            "probes": self.probes,
            "paranoid": self.paranoid,
            "rollbacks": self.rollbacks,
            "mutations": self.mutations,
            "breaches": list(self.breaches),
            "invariants": [i.name for i in (self._invariants or [])],
        }
